"""Benchmark: flagship causal-LM training throughput on the local device.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.

The reference publishes no numbers (SURVEY.md §6) — its machinery reports
wandb ``perf/*`` samples/sec (``finetuner-workflow/finetuner/finetuner.py:516-533``).
We report trained tokens/sec for a pythia-410m-class model, the metric its
flagship finetuner path optimizes; ``vs_baseline`` is vs. the best value
recorded in prior rounds (1.0 until a baseline exists).
"""

from __future__ import annotations

import json
import time

import jax
import jax.numpy as jnp

from kubernetes_cloud_tpu.models.causal_lm import PRESETS
from kubernetes_cloud_tpu.parallel.sharding import shard_batch
from kubernetes_cloud_tpu.core.mesh import MeshSpec, build_mesh
from kubernetes_cloud_tpu.train.train_step import (
    TrainConfig,
    init_train_state,
    make_train_step,
)

BATCH = 16
SEQ = 1024
WARMUP_STEPS = 2
BENCH_STEPS = 10


def main() -> None:
    import dataclasses

    # attn_out remat policy: saving each block's attention output beats
    # full recompute by ~4% at this shape (backward never re-runs attn).
    model_cfg = dataclasses.replace(PRESETS["pythia-410m"], remat=True,
                                    remat_policy="attn_out")
    train_cfg = TrainConfig(warmup_steps=10, total_steps=1000)
    mesh = build_mesh(MeshSpec())
    state = init_train_state(model_cfg, train_cfg, jax.random.key(0), mesh)
    step = jax.jit(make_train_step(model_cfg, train_cfg), donate_argnums=0)

    rng = jax.random.key(1)
    batch = shard_batch(
        {
            "input_ids": jax.random.randint(
                rng, (BATCH, SEQ), 0, model_cfg.vocab_size, dtype=jnp.int32),
            "attention_mask": jnp.ones((BATCH, SEQ), jnp.int32),
        },
        mesh,
    )

    for _ in range(WARMUP_STEPS):
        state, metrics = step(state, batch)
    jax.block_until_ready(metrics["loss"])

    t0 = time.perf_counter()
    for _ in range(BENCH_STEPS):
        state, metrics = step(state, batch)
    jax.block_until_ready(metrics["loss"])
    dt = time.perf_counter() - t0

    tokens_per_sec = BATCH * SEQ * BENCH_STEPS / dt
    print(json.dumps({
        "metric": "pythia410m_train_tokens_per_sec_bs16_seq1024",
        "value": round(tokens_per_sec, 2),
        "unit": "tokens/s",
        "vs_baseline": 1.0,
    }))


if __name__ == "__main__":
    import sys

    if "--kernels" in sys.argv:
        # Real-chip flash-kernel parity gate (Mosaic vs XLA, fwd+grads).
        from scripts.kernel_parity import main as kernel_parity_main

        sys.exit(kernel_parity_main())
    main()
