"""Benchmark: flagship causal-LM training throughput on the local device.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.

The reference publishes no numbers (SURVEY.md §6) — its machinery reports
wandb ``perf/*`` samples/sec (``finetuner-workflow/finetuner/finetuner.py:516-533``).
We report trained tokens/sec for a pythia-410m-class model, the metric its
flagship finetuner path optimizes; ``vs_baseline`` is vs. the best value
recorded in prior rounds (1.0 until a baseline exists).
"""

from __future__ import annotations

import glob
import json
import os
import time

import jax
import jax.numpy as jnp

from kubernetes_cloud_tpu.models.causal_lm import PRESETS
from kubernetes_cloud_tpu.parallel.sharding import shard_batch
from kubernetes_cloud_tpu.core.mesh import MeshSpec, build_mesh
from kubernetes_cloud_tpu.train.train_step import (
    TrainConfig,
    init_train_state,
    make_train_step,
)

BATCH = 16
SEQ = 1024
WARMUP_STEPS = 2
BENCH_STEPS = 10

#: v5e peak bf16 throughput (197 TFLOP/s) — the chip the driver benches on.
PEAK_BF16_FLOPS = 197e12


def _best_prior_value(metric: str) -> float | None:
    """Best value for ``metric`` across prior rounds' BENCH_r*.json files."""
    best = None
    here = os.path.dirname(os.path.abspath(__file__))
    for path in glob.glob(os.path.join(here, "BENCH_r*.json")):
        try:
            with open(path) as f:
                rec = json.load(f)
        except (OSError, ValueError):
            continue
        parsed = rec.get("parsed") or {}
        if parsed.get("metric") == metric and isinstance(
                parsed.get("value"), (int, float)):
            v = float(parsed["value"])
            best = v if best is None else max(best, v)
    return best


def _train_flops_per_token(cfg) -> float:
    """fwd+bwd FLOPs/token: 6*N_params(non-embed) + 12*L*S*D attention."""
    d, l, f, v = (cfg.hidden_size, cfg.num_layers, cfg.ffn_size,
                  cfg.vocab_size)
    n_block = l * (4 * d * d + 2 * d * f)  # qkvo + mlp matmul params
    n_unembed = d * v
    attn_scores = 12 * l * SEQ * d  # 2*(QK^T + PV) fwd, x3 with bwd
    return 6 * (n_block + n_unembed) + attn_scores


def main() -> None:
    import dataclasses

    # attn_island_mlp + the batch-folded resident flash kernel (round 5):
    # attention runs outside the rematerialized block halves, its
    # q/k/v/out/lse residuals are saved flat ([B,S,H*D] — tile-exact, no
    # 64->128 lane padding), and the backward never re-runs the attention
    # forward; the MLP hidden is also saved.  perf_sweep round 5:
    # 33.0k tok/s vs 26.3k for round 4's attn_mlp+XLA-attention.
    model_cfg = dataclasses.replace(PRESETS["pythia-410m"], remat=True,
                                    remat_policy="attn_island_mlp",
                                    attn_impl="pallas", cast_once=True)
    train_cfg = TrainConfig(warmup_steps=10, total_steps=1000)
    mesh = build_mesh(MeshSpec())
    state = init_train_state(model_cfg, train_cfg, jax.random.key(0), mesh)
    step = jax.jit(make_train_step(model_cfg, train_cfg), donate_argnums=0)

    rng = jax.random.key(1)
    # Packed-dataset semantics: the tokenized corpus is chunked to exact
    # block_size (data/tokenized.py), so there is no padding and the
    # trainer passes no attention mask (loss treats None as all-ones —
    # identical labels, and the maskless fused-attention path stays
    # eligible).
    batch = shard_batch(
        {
            "input_ids": jax.random.randint(
                rng, (BATCH, SEQ), 0, model_cfg.vocab_size, dtype=jnp.int32),
        },
        mesh,
    )

    def _sync(state, metrics):
        # Wait for the full step (backward + optimizer update included),
        # then force a host transfer of the step counter — the tunneled
        # device backend has been observed returning from
        # block_until_ready before enqueued executions actually ran.
        jax.block_until_ready((state, metrics))
        int(state["step"])

    for _ in range(WARMUP_STEPS):
        state, metrics = step(state, batch)
    _sync(state, metrics)

    t0 = time.perf_counter()
    for _ in range(BENCH_STEPS):
        state, metrics = step(state, batch)
    _sync(state, metrics)
    dt = time.perf_counter() - t0

    tokens_per_sec = BATCH * SEQ * BENCH_STEPS / dt
    metric = "pythia410m_train_tokens_per_sec_bs16_seq1024"
    prior = _best_prior_value(metric)
    mfu = (tokens_per_sec * _train_flops_per_token(model_cfg)
           / (PEAK_BF16_FLOPS * jax.device_count()))
    print(json.dumps({
        "metric": metric,
        "value": round(tokens_per_sec, 2),
        "unit": "tokens/s",
        "vs_baseline": round(tokens_per_sec / prior, 4) if prior else 1.0,
        "mfu": round(mfu, 4),
    }))


if __name__ == "__main__":
    import sys

    if "--kernels" in sys.argv:
        # Real-chip flash-kernel parity gate (Mosaic vs XLA, fwd+grads).
        from scripts.kernel_parity import main as kernel_parity_main

        sys.exit(kernel_parity_main())
    main()
