"""Native HTTP serving front-end (``csrc/http_server``) for ModelServer.

The reference's serving data planes are C++ cores (TF-Serving for the
SavedModel services, ``gpt-s3-inferenceservice.yaml:14-16``; Triton for
FasterTransformer, ``ft-inference-service-gptj.yml:15-17``) with the
model logic layered on top.  :class:`NativeModelServer` gives
:class:`~kubernetes_cloud_tpu.serve.server.ModelServer` the same split:
sockets, connection concurrency, HTTP parsing and keep-alive live in
C++ threads that never touch the GIL; each parsed request enters Python
once through a ctypes callback into the exact same ``handle()`` routing
the stdlib server uses — so both front-ends serve identical APIs and
the pure-Python ``ModelServer`` remains the no-toolchain fallback.

That shared-``handle()`` split is why the observability plane needs no
native code: ``GET /metrics`` (the :class:`~kubernetes_cloud_tpu.serve.
server.TextResponse` path carrying the Prometheus content type through
``hs_respond``) and the ``GET /debug/*`` introspection endpoints
(flight-recorder timeline, slot/page occupancy, profiler windows —
plain JSON) ride the same callback, and the ``debug.render`` /
``metrics.render`` containment contract holds identically on native
threads (tests/test_debug_endpoints.py drives both front-ends).
"""

from __future__ import annotations

import ctypes
import json
import logging
import os
import subprocess
from typing import Iterable, Optional

from kubernetes_cloud_tpu.serve.model import Model
from kubernetes_cloud_tpu.serve.server import ModelServer, TextResponse

log = logging.getLogger(__name__)

_CSRC = os.path.join(os.path.dirname(os.path.dirname(
    os.path.dirname(os.path.abspath(__file__)))), "csrc", "http_server")

# body is POINTER(c_char), NOT c_char_p: c_char_p would convert to a
# NUL-terminated bytes copy, so string_at on a body with embedded NULs
# would read past the truncated copy (out-of-bounds) instead of the real
# C buffer.  (headers is a C string by construction: CRLF-terminated
# header block, no NULs.)
_HANDLER = ctypes.CFUNCTYPE(
    None, ctypes.c_char_p, ctypes.c_char_p, ctypes.c_char_p,
    ctypes.POINTER(ctypes.c_char), ctypes.c_long, ctypes.c_void_p)


def _parse_headers(raw: bytes) -> dict:
    """Raw header block → {Title-Cased-Name: value}.  Title-casing makes
    lookups like ``headers.get("X-Request-Deadline-Ms")`` behave the
    same as the stdlib front-end's case-insensitive email.Message."""
    out: dict[str, str] = {}
    lines = raw.decode("latin-1", errors="replace").split("\r\n")
    # lines[0] is the request line ("POST /v1/models/m:predict HTTP/1.1")
    # — a path with a colon would otherwise parse as a junk header
    for line in lines[1:]:
        name, sep, value = line.partition(":")
        if sep:
            out[name.strip().title()] = value.strip()
    return out

_lib: Optional[ctypes.CDLL] = None
_lib_failed = False


def build_library(out_dir: Optional[str] = None, *,
                  force: bool = False) -> str:
    src = os.path.join(_CSRC, "http_server.cpp")
    if out_dir is None:
        out_dir = os.path.join(_CSRC, "build")
    os.makedirs(out_dir, exist_ok=True)
    lib = os.path.join(out_dir, "libhttp_server.so")
    if not force and os.path.exists(lib) and (
            os.path.getmtime(lib) >= os.path.getmtime(src)):
        return lib
    tmp = f"{lib}.tmp.{os.getpid()}"  # atomic vs concurrent builders
    subprocess.run(
        ["g++", "-O2", "-std=c++17", "-shared", "-fPIC", "-pthread",
         src, "-o", tmp],
        check=True, capture_output=True, text=True)
    os.replace(tmp, lib)
    return lib


def _load() -> Optional[ctypes.CDLL]:
    global _lib, _lib_failed
    if _lib is not None or _lib_failed:
        return _lib
    try:
        lib = ctypes.CDLL(build_library())
    except Exception:  # noqa: BLE001 - no toolchain => python fallback
        _lib_failed = True
        return None
    lib.hs_start.restype = ctypes.c_void_p
    lib.hs_start.argtypes = [ctypes.c_int, ctypes.c_int, _HANDLER]
    lib.hs_port.restype = ctypes.c_int
    lib.hs_port.argtypes = [ctypes.c_void_p]
    lib.hs_stop.restype = None
    lib.hs_stop.argtypes = [ctypes.c_void_p]
    lib.hs_respond.restype = None
    lib.hs_respond.argtypes = [
        ctypes.c_void_p, ctypes.c_int, ctypes.c_char_p, ctypes.c_char_p,
        ctypes.c_long]
    _lib = lib
    return _lib


def available() -> bool:
    return _load() is not None


class NativeModelServer(ModelServer):
    """ModelServer with the C++ front-end instead of http.server."""

    def __init__(self, models: Iterable[Model], *, host: str = "0.0.0.0",
                 port: int = 8080):
        super().__init__(models, host=host, port=port)
        self._native = None
        self._cb = None  # keep the callback object alive (ctypes rule)

    def _make_callback(self):
        lib = _load()

        @_HANDLER
        def on_request(method, path, headers, body, body_len, resp):
            ctype = b"application/json"
            try:
                status, obj = self.handle(
                    method.decode(), path.decode(),
                    ctypes.string_at(body, body_len) if body_len else b"",
                    _parse_headers(headers or b""))
                if isinstance(obj, TextResponse):
                    # /metrics: Prometheus text exposition, not JSON
                    data = obj.body.encode()
                    ctype = obj.content_type.encode()
                else:
                    data = json.dumps(obj).encode()
            except Exception as e:  # noqa: BLE001 - never unwind into C
                log.exception("native handler failure")
                status, data = 500, json.dumps({"error": str(e)}).encode()
            lib.hs_respond(resp, status, ctype, data, len(data))

        return on_request

    def start(self) -> None:
        lib = _load()
        if lib is None:
            raise RuntimeError(
                "native http front-end unavailable (no C++ toolchain); "
                "use ModelServer")
        if self._native is not None:
            raise RuntimeError("server already started")
        self._cb = self._make_callback()
        self._native = lib.hs_start(self.port, 128, self._cb)
        if not self._native:
            raise OSError(f"failed to bind port {self.port}")
        self.port = int(lib.hs_port(self._native))
        log.info("native front-end serving on :%d", self.port)

    def serve_forever(self) -> None:
        import time

        self.load_all()
        self.start()
        try:
            # Poll-wait on the native handle: a SIGTERM drain's stop()
            # clears it, and serve_forever must then RETURN (so the
            # process exits inside terminationGracePeriodSeconds rather
            # than idling into the SIGKILL).
            while self._native is not None:
                time.sleep(0.5)
        except KeyboardInterrupt:
            pass
        finally:
            self.stop()

    def stop(self) -> None:
        if self._native is not None:
            _load().hs_stop(self._native)
            self._native = None
