"""Client programs for the serving data plane.

The reference treats clients as integration tests (SURVEY.md §4): the
FasterTransformer gRPC/HTTP client with its own BPE tokenizer
(``online-inference/fastertransformer/client/example.py``) and the BASNet
image→mask compositing client
(``online-inference/custom-basnet/client/main.py:13-37``).  Equivalents
here speak the V1 data plane of :mod:`kubernetes_cloud_tpu.serve.server`.
"""

from __future__ import annotations

import argparse
import base64
import io
import json
import urllib.request
from typing import Any, Optional


def predict(url: str, payload: dict, *, timeout: float = 300.0) -> dict:
    req = urllib.request.Request(
        url, data=json.dumps(payload).encode(),
        headers={"Content-Type": "application/json"})
    with urllib.request.urlopen(req, timeout=timeout) as resp:
        return json.loads(resp.read())


# -------------------------------------------------------------------------
# LM client (fastertransformer/client/example.py equivalent)


def generate_text(
    url: str,
    prompt: str,
    *,
    codec=None,
    max_tokens: int = 64,
    temperature: float = 1.0,
    top_k: int = 0,
    top_p: float = 1.0,
    timeout: float = 300.0,
) -> str:
    """Client-side tokenization like the FT client: if ``codec`` is given
    the prompt is BPE-encoded locally and token ids are decoded on return;
    otherwise the server tokenizes."""
    params = {"max_tokens": max_tokens, "temperature": temperature,
              "top_k": top_k, "top_p": top_p}
    if codec is not None:
        payload = {"instances": [codec.encode(prompt)],
                   "parameters": params}
        out = predict(url, payload, timeout=timeout)
        ids = out["predictions"][0]
        if isinstance(ids, dict):
            ids = ids.get("token_ids", ids.get("text"))
        return codec.decode(ids) if isinstance(ids, list) else str(ids)
    payload = {"instances": [prompt], "parameters": params}
    out = predict(url, payload, timeout=timeout)
    pred = out["predictions"][0]
    return pred["text"] if isinstance(pred, dict) else pred


# -------------------------------------------------------------------------
# Segmentation-mask compositing client (custom-basnet/client/main.py)


def cutout(url: str, image_path: str, out_path: str, *,
           timeout: float = 300.0) -> str:
    """POST an image to a mask predictor; composite mask as alpha to cut
    the foreground out, write RGBA PNG.  Mask responses accepted as
    ``{"predictions": [{"mask": {"b64": <png>}}]}`` or a nested float
    list."""
    import numpy as np
    from PIL import Image

    with open(image_path, "rb") as f:
        raw = f.read()
    payload = {"instances": [{"image_bytes": {
        "b64": base64.b64encode(raw).decode()}}]}
    resp = predict(url, payload, timeout=timeout)
    pred = resp["predictions"][0]

    img = Image.open(io.BytesIO(raw)).convert("RGBA")
    if isinstance(pred, dict) and "mask" in pred:
        mask_img = Image.open(io.BytesIO(
            base64.b64decode(pred["mask"]["b64"]))).convert("L")
    else:
        arr = np.asarray(pred, np.float32)
        if arr.max() <= 1.0:
            arr = arr * 255.0
        mask_img = Image.fromarray(arr.astype("uint8"), "L")
    mask_img = mask_img.resize(img.size, Image.BILINEAR)
    img.putalpha(mask_img)
    img.save(out_path, "PNG")
    return out_path


def main(argv: Optional[list[str]] = None) -> Any:
    ap = argparse.ArgumentParser(description=__doc__)
    sub = ap.add_subparsers(dest="cmd", required=True)

    g = sub.add_parser("generate", help="text generation client")
    g.add_argument("--url", required=True)
    g.add_argument("--prompt", required=True)
    g.add_argument("--codec-dir", default=None,
                   help="vocab.json+merges.txt dir for client-side BPE")
    g.add_argument("--max-tokens", type=int, default=64)
    g.add_argument("--temperature", type=float, default=1.0)

    c = sub.add_parser("cutout", help="mask + composite client")
    c.add_argument("--url", required=True)
    c.add_argument("--image", required=True)
    c.add_argument("--out", required=True)

    args = ap.parse_args(argv)
    if args.cmd == "generate":
        codec = None
        if args.codec_dir:
            from kubernetes_cloud_tpu.serve.bpe import BPECodec

            codec = BPECodec.from_dir(args.codec_dir)
        text = generate_text(args.url, args.prompt, codec=codec,
                             max_tokens=args.max_tokens,
                             temperature=args.temperature)
        print(text)
        return text
    return cutout(args.url, args.image, args.out)


if __name__ == "__main__":
    main()
