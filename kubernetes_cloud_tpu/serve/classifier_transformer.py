"""Image pre/post-processing sidecar container
(``deploy/online-inference/image-classifier/classifier-inferenceservice
.yaml`` transformer; logic in
:class:`kubernetes_cloud_tpu.serve.transformer.ImageTransformer`)."""

from __future__ import annotations

import argparse
import logging
import os
from typing import Optional

from kubernetes_cloud_tpu.serve import boot
from kubernetes_cloud_tpu.serve.transformer import (
    ImageTransformer,
    load_class_map,
)


def main(argv: Optional[list] = None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--predictor-host",
                    default=os.environ.get("PREDICTOR_HOST",
                                           "127.0.0.1:8081"),
                    help="host:port of the predictor container")
    ap.add_argument("--image-size", type=int, default=224)
    ap.add_argument("--class-map", default=None,
                    help="JSON list/dict mapping class ids to labels")
    boot.add_common_args(ap)
    args = ap.parse_args(argv)
    logging.basicConfig(level=logging.INFO)
    boot.wait_for_artifact(args)  # class-map file may come from the PVC
    class_map = load_class_map(args.class_map) if args.class_map else None
    svc = ImageTransformer(args.model_name or "classifier",
                           args.predictor_host,
                           image_size=args.image_size,
                           class_map=class_map)
    boot.serve([svc], args)
    return 0


if __name__ == "__main__":  # pragma: no cover - container entry
    import sys

    sys.exit(main())
