"""Multi-tenant traffic plane: admission quotas, weighted fair queueing,
and QoS lanes for the continuous-batching engine.

"Millions of users" is a scheduling problem before it is a throughput
problem: the reference stack's only traffic knob is Knative
``containerConcurrency``, so fairness across callers lands inside the
engine — and an engine admitting from one global FIFO lets a single
aggressive tenant monopolize every slot and every KV page while
interactive callers starve behind batch jobs.  This module is the
traffic plane in front of (and inside) the scheduler:

* **Identity** — every request carries a tenant, resolved at the door
  from the ``X-API-Key`` header or an explicit payload ``tenant`` field
  (``TenancyConfig.resolve``); unknown callers share the ``default``
  tenant, so metric label cardinality is bounded by configuration, not
  by client-chosen strings.
* **Admission** — per-tenant token buckets in requests/s and
  prompt-tokens/s (:class:`TokenBucket`).  A drained bucket raises the
  typed, retryable :class:`~kubernetes_cloud_tpu.serve.errors.
  TenantQuotaError` (HTTP 503 with a ``retry_after_s`` hint) *before*
  the request touches the bounded queue — quota exhaustion is the
  tenant's problem, never its neighbours'.
* **Weighted fair queueing** — per-tenant queues drained in virtual-time
  order (:class:`TenantScheduler`), the VTC rendering (PAPERS.md:
  "Fairness in Serving Large Language Models", OSDI '24): each tenant's
  virtual clock advances by *service actually received* — prefilled +
  decoded tokens, not request count — divided by its weight, so long
  generations pay their way and a greedy tenant's clock races ahead
  until everyone else catches up.  Idle tenants re-enter at the
  busy minimum (no credit banking).  Per-pass slot and page quotas cap
  a tenant at its weight share of the pool *under contention* while
  idle capacity stays work-conserving.
* **QoS lanes** — two lanes, ``interactive`` and ``batch``.  An
  interactive arrival may preempt a batch slot mid-decode (the engine's
  half lives in ``serve/continuous.py``): the preempted request
  re-queues at its lane head — paged mode keeps its KV pages pinned so
  resume is prefill-free; slot mode re-prefills its context — and
  resumes bitwise-identically (the RNG and emitted tokens live on the
  request, never re-sampled).

Everything here is host-side bookkeeping: the scheduler state is
guarded by the engine's queue lock (``TenantScheduler`` documents which
methods expect it); only :class:`TokenBucket` carries its own lock,
because admission checks run on HTTP threads before the queue lock is
taken.
"""

from __future__ import annotations

import collections
import dataclasses
import math
import threading
import time
from typing import TYPE_CHECKING, Any, Mapping, Optional

from kubernetes_cloud_tpu import obs
from kubernetes_cloud_tpu.serve.errors import TenantQuotaError

if TYPE_CHECKING:  # pragma: no cover - annotation-only import cycle
    from kubernetes_cloud_tpu.serve.continuous import GenRequest

#: QoS lanes, in preemption-priority order: an ``interactive`` arrival
#: may preempt a ``batch`` slot; never the reverse.
LANES = ("interactive", "batch")

#: the catch-all tenant every unconfigured caller shares
DEFAULT_TENANT = "default"

# Per-tenant metric families.  Label cardinality is bounded: ``tenant``
# only ever takes configured tenant names plus DEFAULT_TENANT (unknown
# callers collapse into it), ``lane`` is the fixed LANES vocabulary.
_M_ADMITTED = obs.counter(
    "kct_tenant_admitted_total",
    "Requests admitted into slots, per tenant and QoS lane.",
    ("model", "tenant", "lane"))
_M_SHED = obs.counter(
    "kct_tenant_shed_total",
    "Requests shed before decoding, per tenant by reason "
    "(quota_requests | quota_tokens | queue_full | deadline).",
    ("model", "tenant", "reason"))
_M_PREEMPTED = obs.counter(
    "kct_tenant_preempted_total",
    "Mid-decode batch-lane preemptions suffered, per tenant.",
    ("model", "tenant"))
_M_TOKENS = obs.counter(
    "kct_tenant_tokens_total",
    "Tokens actually served per tenant, by kind (prefill = prompt "
    "tokens computed, cache hits excluded; decode = completion tokens "
    "emitted) — the service measure the fair-queueing virtual clock "
    "advances on.",
    ("model", "tenant", "kind"))
_M_QUEUE = obs.gauge(
    "kct_tenant_queue_depth",
    "Queued (not yet admitted) requests per tenant; summing over "
    "tenants gives the engine's aggregate admission queue depth.",
    ("model", "tenant"))
_M_TTFT = obs.histogram(
    "kct_tenant_ttft_seconds",
    "Submit to first emitted token, per tenant and lane (the per-"
    "tenant SLO the fairness plane exists to protect).",
    ("model", "tenant", "lane"))


@dataclasses.dataclass(frozen=True)
class TenantSpec:
    """One tenant's traffic contract (deploy/README.md "Multi-tenancy &
    QoS" documents the tuning math)."""

    name: str
    #: fair-queueing weight: under contention the tenant is entitled to
    #: ``weight / sum(weights of busy tenants)`` of slots, pages, and
    #: tokens/s
    weight: float = 1.0
    #: default QoS lane for this tenant's requests ("interactive" may
    #: preempt "batch" slots mid-decode)
    lane: str = "interactive"
    #: admission token bucket in requests/s (0 = unlimited)
    req_rate: float = 0.0
    #: request-bucket burst capacity (0 = ceil(req_rate), min 1)
    req_burst: float = 0.0
    #: admission token bucket in prompt tokens/s (0 = unlimited)
    token_rate: float = 0.0
    #: prompt-token bucket burst capacity (0 = ceil(token_rate))
    token_burst: float = 0.0
    #: API keys mapping to this tenant (the ``X-API-Key`` values)
    api_keys: tuple = ()

    def __post_init__(self):
        if not self.name:
            raise ValueError("tenant name must be non-empty")
        if not self.weight > 0:
            raise ValueError(f"tenant {self.name}: weight must be > 0")
        if self.lane not in LANES:
            raise ValueError(
                f"tenant {self.name}: lane must be one of {LANES}")
        for f in ("req_rate", "req_burst", "token_rate", "token_burst"):
            if getattr(self, f) < 0:
                raise ValueError(f"tenant {self.name}: {f} must be >= 0")


@dataclasses.dataclass(frozen=True)
class TenancyConfig:
    """The engine's tenant table.  The zero-argument default — no
    configured tenants, an unlimited default tenant — degenerates to
    the pre-tenancy engine exactly: one FIFO queue, no buckets, no
    preemption (every request shares one lane)."""

    tenants: tuple = ()
    default: TenantSpec = TenantSpec(DEFAULT_TENANT)
    #: interactive-over-batch preemption (lane semantics) on/off
    preemption: bool = True
    #: preemptions allowed per scheduler pass (bounds re-prefill churn)
    max_preempt_per_step: int = 2
    #: a batch slot is preemptable only after decoding this many
    #: tokens since its last (re)admission — the progress guarantee
    #: that turns preemption thrash (evict → re-prefill → evict ...)
    #: into bounded overhead: a request of N completion tokens suffers
    #: at most N / min_batch_progress preemptions
    min_batch_progress: int = 16
    #: price the WFQ service clock by each token's analytical FLOPs
    #: (prefill-at-context vs decode-at-context, obs/flops.py) instead
    #: of counting every token as one tick — VTC's deferred per-kind
    #: weighted-cost item, closed.  Off = the legacy equal-count clock.
    flop_weighted_cost: bool = True

    def __post_init__(self):
        names = [t.name for t in self.tenants]
        if len(set(names)) != len(names):
            raise ValueError(f"duplicate tenant names: {names}")
        if DEFAULT_TENANT in names:
            raise ValueError(
                f"configure the catch-all via 'default', not a tenant "
                f"named {DEFAULT_TENANT!r}")
        if self.max_preempt_per_step < 0:
            raise ValueError("max_preempt_per_step must be >= 0")
        if self.min_batch_progress < 1:
            raise ValueError("min_batch_progress must be >= 1")
        keys: dict[str, str] = {}
        for t in (*self.tenants, self.default):
            for k in t.api_keys:
                if k in keys:
                    raise ValueError(
                        f"api key maps to both {keys[k]!r} and "
                        f"{t.name!r}")
                keys[k] = t.name

    def spec(self, name: Optional[str]) -> TenantSpec:
        for t in self.tenants:
            if t.name == name:
                return t
        return self.default

    def resolve(self, tenant: Optional[str] = None,
                api_key: Optional[str] = None) -> TenantSpec:
        """Identity ladder: the API key is the credential, so it wins —
        a recognized key (in a tenant's ``api_keys``, or equal to a
        configured tenant name) resolves to that tenant regardless of
        what the payload claims; an UNRECOGNIZED key resolves to the
        default tenant (presenting a bad credential must not let the
        payload ``tenant`` label impersonate a configured tenant and
        drain its buckets).  Only a keyless request may classify
        itself via the payload ``tenant`` field (mesh-internal
        callers); everyone else shares the default tenant — so
        labels/queues stay bounded by config."""
        if api_key:
            for t in (*self.tenants, self.default):
                if api_key in t.api_keys:
                    return t
            for t in self.tenants:
                # name-as-key convenience ONLY for tenants that
                # configured no keys: names are public (metrics,
                # /debug, error bodies), so a tenant WITH secret keys
                # must not be reachable by its name
                if api_key == t.name and not t.api_keys:
                    return t
            return self.default
        for t in self.tenants:
            if tenant == t.name:
                return t
        return self.default


def parse_tenancy(raw: Optional[Mapping[str, Any]]
                  ) -> Optional[TenancyConfig]:
    """``model_config.json`` ``"tenancy"`` key → :class:`TenancyConfig`
    (None stays None: tenancy off means the legacy single-queue path).

    Schema (deploy/README.md "Multi-tenancy & QoS")::

        {"tenancy": {
           "preemption": true, "max_preempt_per_step": 2,
           "default": {"weight": 1, "lane": "interactive", ...},
           "tenants": [{"name": "acme", "weight": 4, "lane": "batch",
                        "req_rate": 10, "token_rate": 4096,
                        "api_keys": ["k-acme-1"]}, ...]}}
    """
    if not raw:
        return None

    def spec(name: str, d: Mapping[str, Any]) -> TenantSpec:
        known = ("weight", "lane", "req_rate", "req_burst", "token_rate",
                 "token_burst", "api_keys")
        unknown = set(d) - set(known) - {"name"}
        if unknown:
            raise ValueError(
                f"tenant {name!r}: unknown keys {sorted(unknown)}")
        kw: dict[str, Any] = {k: d[k] for k in known if k in d}
        if "api_keys" in kw:
            kw["api_keys"] = tuple(str(k) for k in kw["api_keys"])
        for k in ("weight", "req_rate", "req_burst", "token_rate",
                  "token_burst"):
            if k in kw:
                kw[k] = float(kw[k])
        return TenantSpec(name=name, **kw)

    tenants = tuple(spec(str(d.get("name", "")), d)
                    for d in raw.get("tenants", ()))
    default = spec(DEFAULT_TENANT, raw.get("default") or {})
    return TenancyConfig(
        tenants=tenants, default=default,
        preemption=bool(raw.get("preemption", True)),
        max_preempt_per_step=int(raw.get("max_preempt_per_step", 2)),
        min_batch_progress=int(raw.get("min_batch_progress", 16)),
        flop_weighted_cost=bool(raw.get("flop_weighted_cost", True)))


class FleetClock:
    """Shared per-tenant virtual clocks across the replicas of a fleet
    (:mod:`kubernetes_cloud_tpu.serve.fleet`).

    PR-9's WFQ fairness is per-engine: each replica's
    :class:`TenantScheduler` tracks service locally, so a tenant served
    heavily on replica A still looks freshly arrived to replica B — the
    router's load balancing would let it collect a fair share *per
    replica* instead of fleet-wide.  Attaching one ``FleetClock`` to
    every replica's scheduler (``TenantScheduler.attach_fleet_clock``)
    makes the virtual clocks — and the no-banked-credit floor — one
    shared ledger: every charge lands here, every drain-order
    comparison reads from here, so a tenant's weighted service is
    equalized across the whole fleet.

    Thread-safety: one small lock; callers are the replicas' scheduler
    threads (one per engine) plus HTTP submit threads doing the idle
    lift.  Critical sections are a dict read/write — no blocking calls.
    """

    def __init__(self):
        self._lock = threading.Lock()
        self._vt: dict[str, float] = {}
        self._floor = 0.0

    def vt(self, tenant: str) -> float:
        # deliberately LOCK-FREE: a dict read of a float is GIL-atomic
        # (the same idiom as the engine's cross-thread vt reads), and
        # this is every replica's WFQ sort key — taking the fleet lock
        # O(tenants log tenants) per scheduler pass would convoy all
        # replicas' hot decode loops on one lock.  Writers still
        # serialize below.
        return self._vt.get(tenant, 0.0)

    def advance(self, tenant: str, delta: float) -> float:
        """Charge ``delta`` weighted service; returns the new clock and
        raises the fleet floor to it."""
        with self._lock:
            v = self._vt.get(tenant, 0.0) + delta
            self._vt[tenant] = v
            if v > self._floor:
                self._floor = v
            return v

    def lift(self, tenant: str, to: float) -> float:
        """Monotonic lift (idle re-entry): never moves a clock back."""
        with self._lock:
            v = max(self._vt.get(tenant, 0.0), to)
            self._vt[tenant] = v
            return v

    def floor(self) -> float:
        return self._floor  # lock-free float read, like vt()

    def raise_floor(self, v: float) -> None:
        with self._lock:
            if v > self._floor:
                self._floor = v

    def snapshot(self) -> dict:
        with self._lock:
            return {"floor": round(self._floor, 3),
                    "vt": {t: round(v, 3)
                           for t, v in sorted(self._vt.items())}}


class TokenBucket:
    """Monotonic-clock token bucket; thread-safe (admission checks run
    on HTTP threads).  ``rate <= 0`` disables the bucket entirely."""

    def __init__(self, rate: float, burst: float = 0.0,
                 now: Optional[float] = None):
        self.rate = float(rate)
        self.burst = float(burst) if burst > 0 else max(1.0,
                                                        math.ceil(rate))
        self._level = self.burst
        self._at = time.monotonic() if now is None else now
        self._lock = threading.Lock()

    def try_take(self, n: float = 1.0,
                 now: Optional[float] = None) -> float:
        """Take ``n`` tokens if available; returns 0.0 on success, else
        the seconds until ``n`` tokens will have refilled (the
        ``retry_after_s`` hint — nothing is taken on refusal)."""
        if self.rate <= 0:
            return 0.0
        if now is None:
            now = time.monotonic()
        with self._lock:
            self._level = min(self.burst,
                              self._level + (now - self._at) * self.rate)
            self._at = now
            if self._level >= n:
                self._level -= n
                return 0.0
            need = min(n, self.burst) - self._level
            return max(need / self.rate, 1e-3)

    def give_back(self, n: float = 1.0) -> None:
        """Refund a charge that bought nothing (the request was shed
        later in admission — queue full, deadline) so backpressure
        does not double-penalize a tenant below its contracted rate."""
        if self.rate <= 0:
            return
        with self._lock:
            self._level = min(self.burst, self._level + n)


class _TenantState:
    """One tenant's live scheduling state inside an engine (queues,
    virtual clock, occupancy counts, bound metric children)."""

    __slots__ = ("spec", "vt", "queues", "active_slots", "pages",
                 "req_bucket", "tok_bucket", "m_admitted", "m_shed",
                 "m_preempted", "m_prefill", "m_decode", "m_queue",
                 "m_ttft", "stats")

    def __init__(self, spec: TenantSpec, model: str):
        self.spec = spec
        self.vt = 0.0
        self.queues: dict[str, collections.deque] = {
            lane: collections.deque() for lane in LANES}
        self.active_slots = 0
        self.pages = 0
        self.req_bucket = TokenBucket(spec.req_rate, spec.req_burst)
        self.tok_bucket = TokenBucket(spec.token_rate, spec.token_burst)
        t = {"model": model, "tenant": spec.name}
        self.m_admitted = {lane: _M_ADMITTED.labels(lane=lane, **t)
                           for lane in LANES}
        self.m_shed = {r: _M_SHED.labels(reason=r, **t)
                       for r in ("quota_requests", "quota_tokens",
                                 "queue_full", "deadline")}
        self.m_preempted = _M_PREEMPTED.labels(**t)
        self.m_prefill = _M_TOKENS.labels(kind="prefill", **t)
        self.m_decode = _M_TOKENS.labels(kind="decode", **t)
        self.m_queue = _M_QUEUE.labels(**t)
        self.m_ttft = {lane: _M_TTFT.labels(lane=lane, **t)
                       for lane in LANES}
        #: bench-facing in-process counters, engine-lifetime
        self.stats = {"admitted": 0, "shed": 0, "preempted": 0,
                      "prefill_tokens": 0, "decode_tokens": 0}

    def queued(self) -> int:
        return sum(len(q) for q in self.queues.values())

    def in_system(self) -> bool:
        return self.active_slots > 0 or self.pages > 0 or self.queued() > 0


class TenantScheduler:
    """Per-tenant queues + the virtual-time drain order.

    Thread-safety contract: every method is designed to run under the
    ENGINE'S queue lock (the same ``_qlock`` that guarded the old
    global deque) — the scheduler adds no lock of its own, so the
    submit-path invariant ("queued" trace inside the lock can never be
    outrun by "admitted") carries over unchanged.  The only exception
    is :meth:`admit_check`, which touches only the tenant's own
    (internally locked) buckets and MUST be called *without* the queue
    lock, from the submitting HTTP thread.
    """

    def __init__(self, cfg: Optional[TenancyConfig], *, slots: int,
                 page_capacity: int = 0, model: str = "engine"):
        self.cfg = cfg or TenancyConfig()
        self.slots = slots
        self.page_capacity = page_capacity
        self.model = model
        self._states: dict[str, _TenantState] = {}
        for spec in (*self.cfg.tenants, self.cfg.default):
            self._states[spec.name] = _TenantState(spec, model)
        #: the highest virtual clock ever served — the re-entry floor
        #: for a tenant returning to an otherwise-idle engine, so
        #: sitting out a quiet period never banks credit against
        #: tenants who worked through it
        self._vt_floor = 0.0
        #: fleet-wide shared clock (serve/fleet.py); None = standalone
        #: engine, clocks stay local.  Set via attach_fleet_clock.
        self.fleet: Optional[FleetClock] = None
        #: per-kind FLOP pricing coefficients (obs/flops.py affine
        #: decode cost, set by the engine via set_cost_model) — None
        #: until wired, which leaves the legacy count-tokens-equally
        #: charge, as does cfg.flop_weighted_cost=False
        self._cost_base: Optional[float] = None
        self._cost_per_ctx: float = 0.0

    def set_cost_model(self, base: float, per_ctx: float) -> None:
        """Arm exact per-kind FLOP pricing of the WFQ service clock
        (VTC, OSDI '24, closed its deferred weighted-cost item here):
        a token at context ``c`` costs ``(base + per_ctx·c) / base``
        decode-token-equivalents, so a long-context prefill burst pays
        its true attention cost instead of one clock tick per token.
        Normalizing by ``base`` keeps the virtual-time units ≈ tokens
        — weights, floors, and the fleet ledger need no rescaling, and
        ``flop_weighted_cost=False`` degrades to the legacy charge
        continuously rather than to a different clock regime."""
        if base > 0:
            self._cost_base = float(base)
            self._cost_per_ctx = float(per_ctx)

    def _token_cost(self, start: int, tokens: int) -> float:
        """Decode-token-equivalents for ``tokens`` consecutive tokens
        whose contexts grow from ``start+1``: span_flops / base."""
        if tokens <= 0:
            return 0.0
        if self._cost_base is None or not self.cfg.flop_weighted_cost:
            return float(tokens)
        r = self._cost_per_ctx / self._cost_base
        return tokens + r * (tokens * start + tokens * (tokens + 1) / 2.0)

    # -- fleet-wide virtual time (serve/fleet.py) --------------------------

    def attach_fleet_clock(self, clock: FleetClock) -> None:
        """Share virtual clocks (and the no-banked-credit floor) with
        every other scheduler attached to ``clock``, making WFQ
        fairness hold fleet-wide instead of per replica.  Idempotent;
        safe to re-apply after an engine rebuild (the fresh scheduler's
        zero clocks are lifted to the fleet ledger, never the other
        way around)."""
        if self.fleet is clock:
            return
        for name, st in self._states.items():
            clock.lift(name, st.vt)
        clock.raise_floor(self._vt_floor)
        self.fleet = clock

    def _vt(self, st: _TenantState) -> float:
        if self.fleet is not None:
            return self.fleet.vt(st.spec.name)
        return st.vt

    def _vt_advance(self, st: _TenantState, delta: float) -> None:
        if self.fleet is not None:
            # the mirror keeps snapshot()/debug cheap and lock-local
            st.vt = self.fleet.advance(st.spec.name, delta)
        else:
            st.vt += delta
            if st.vt > self._vt_floor:
                self._vt_floor = st.vt

    def _vt_lift(self, st: _TenantState, to: float) -> None:
        if self.fleet is not None:
            st.vt = self.fleet.lift(st.spec.name, to)
        else:
            st.vt = max(st.vt, to)

    def _floor(self) -> float:
        if self.fleet is not None:
            return self.fleet.floor()
        return self._vt_floor

    def _raise_floor(self, v: float) -> None:
        if self.fleet is not None:
            self.fleet.raise_floor(v)
        elif v > self._vt_floor:
            self._vt_floor = v

    # -- identity / admission (HTTP threads) -------------------------------

    def resolve(self, tenant: Optional[str] = None,
                api_key: Optional[str] = None) -> TenantSpec:
        return self.cfg.resolve(tenant, api_key)

    def state(self, name: Optional[str]) -> _TenantState:
        st = self._states.get(name or DEFAULT_TENANT)
        return st if st is not None else self._states[DEFAULT_TENANT]

    def admit_check(self, spec: TenantSpec, prompt_tokens: int) -> None:
        """Charge the tenant's buckets for one request; raises the
        retryable :class:`TenantQuotaError` (→ 503 + ``retry_after_s``)
        when a bucket is dry.  Called WITHOUT the queue lock (buckets
        are internally locked); a shed here never touches the queue, so
        a hot-looping tenant burns only its own HTTP threads."""
        st = self.state(spec.name)
        if (spec.token_rate > 0
                and prompt_tokens > st.tok_bucket.burst):
            # can NEVER be admitted, even by a full bucket: a config
            # mismatch, not transient backpressure — a retryable 503
            # with a tiny retry_after_s would hot-loop the client
            # forever (same contract as submit()'s impossible
            # page-claim rejection)
            raise ValueError(
                f"prompt ({prompt_tokens} tokens) exceeds tenant "
                f"{spec.name!r} token-bucket burst "
                f"({st.tok_bucket.burst:g}); raise token_burst")
        wait = st.req_bucket.try_take(1.0)
        if wait > 0.0:
            st.m_shed["quota_requests"].inc()
            st.stats["shed"] += 1
            raise TenantQuotaError(
                f"tenant {spec.name!r} request quota exhausted "
                f"({spec.req_rate:g} req/s)", retry_after_s=wait)
        wait = st.tok_bucket.try_take(float(prompt_tokens))
        if wait > 0.0:
            st.req_bucket.give_back(1.0)  # the pair is all-or-nothing
            st.m_shed["quota_tokens"].inc()
            st.stats["shed"] += 1
            raise TenantQuotaError(
                f"tenant {spec.name!r} prompt-token quota exhausted "
                f"({spec.token_rate:g} tok/s)", retry_after_s=wait)

    def refund(self, spec: TenantSpec, prompt_tokens: int) -> None:
        """Give back an :meth:`admit_check` charge whose request was
        shed later in admission (queue full, dead deadline): the
        tenant got no service, so under sustained backpressure its
        buckets must not lock it out below the contracted rate.
        Called WITHOUT the queue lock, like admit_check."""
        st = self.state(spec.name)
        st.req_bucket.give_back(1.0)
        st.tok_bucket.give_back(float(prompt_tokens))

    def count_shed(self, tenant: Optional[str], reason: str) -> None:
        st = self.state(tenant)
        st.m_shed[reason].inc()
        st.stats["shed"] += 1

    # -- queue surface (engine's _qlock held) ------------------------------

    def append(self, req: "GenRequest") -> None:
        st = self.state(req.tenant)
        if not st.in_system():
            # VTC lift: an idle tenant re-enters at the busy minimum —
            # fairness is about rates while competing, not banked
            # credit for time spent away.  With nobody busy, re-enter
            # at the highest clock ever served (the floor): a tenant
            # returning to an idle engine must not drag the fair-share
            # baseline back to its own ancient clock.  With a fleet
            # clock attached both reads are fleet-wide, so hopping
            # replicas banks no credit either.
            busy = [self._vt(s) for s in self._states.values()
                    if s.in_system()]
            self._vt_lift(st, min(busy) if busy else self._floor())
        st.queues[req.lane].append(req)

    def append_head(self, req: "GenRequest") -> None:
        """Lane-head re-queue: a preempted (or transiently page-starved)
        request goes back in FRONT of its lane so later arrivals of its
        own tenant cannot leapfrog it."""
        self.state(req.tenant).queues[req.lane].appendleft(req)

    def depth(self) -> int:
        return sum(st.queued() for st in self._states.values())

    def busy_count(self) -> int:
        """Tenants currently in the system (queued or holding slots/
        pages) — the worst-case divisor of the admission bandwidth a
        newly queued request competes under."""
        return sum(1 for st in self._states.values() if st.in_system())

    def queue_share(self, spec: TenantSpec, max_queue_size: int) -> int:
        """The tenant's slice of the bounded admission queue:
        ``weight / Σ(all configured weights)`` of the bound (min 1).
        Enforcing the bound per tenant — not on the aggregate — is
        what keeps one unlimited tenant's backlog from 503ing its
        neighbours out of admission entirely; the single-default-
        tenant config degenerates to the whole bound (legacy
        behavior)."""
        total = sum(t.weight
                    for t in (*self.cfg.tenants, self.cfg.default))
        return max(1, math.ceil(spec.weight / total * max_queue_size))

    def depths(self) -> dict[str, int]:
        return {name: st.queued() for name, st in self._states.items()}

    def drain(self) -> list:
        out: list = []
        for st in self._states.values():
            for q in st.queues.values():
                out.extend(q)
                q.clear()
        return out

    def iter_queued(self) -> list:
        """Flat snapshot of every queued request, no removal (request-
        phase lookup / cancel-by-id; engine's ``_qlock`` held)."""
        out: list = []
        for st in self._states.values():
            for q in st.queues.values():
                out.extend(q)
        return out

    def purge(self, pred) -> list:
        """Remove (and return) every queued request matching ``pred`` —
        the cancelled-request reaper, now reaching into every tenant
        queue (a dead request must not hold bounded capacity)."""
        out: list = []
        for st in self._states.values():
            for q in st.queues.values():
                dead = [r for r in q if pred(r)]
                if dead:
                    alive = [r for r in q if not pred(r)]
                    q.clear()
                    q.extend(alive)
                    out.extend(dead)
        return out

    # -- fair-queueing drain (scheduler thread, _qlock held) ---------------

    def _quota_slots(self, st: _TenantState, total_w: float) -> int:
        return max(1, math.ceil(st.spec.weight / total_w * self.slots))

    def _quota_pages(self, st: _TenantState, total_w: float) -> int:
        return max(1, math.ceil(st.spec.weight / total_w
                                * self.page_capacity))

    def _under_quota(self, st: _TenantState, total_w: float) -> bool:
        if st.active_slots >= self._quota_slots(st, total_w):
            return False
        if (self.page_capacity
                and st.pages >= self._quota_pages(st, total_w)):
            return False
        return True

    def _busy_weight(self) -> float:
        return sum(st.spec.weight for st in self._states.values()
                   if st.in_system()) or 1.0

    def pop_next(self) -> Optional["GenRequest"]:
        """The WFQ drain: among tenants with queued work, serve the
        smallest virtual clock, preferring tenants still under their
        per-pass slot/page quota; when ONLY over-quota tenants are
        queued the minimum-clock one is served anyway (work
        conservation — an idle slot helps nobody).  Within a tenant the
        interactive lane drains before batch; each lane is FIFO.

        The popped tenant's ``active_slots`` is charged immediately
        (the pass admits several requests before any lands in a slot;
        deferring the charge would let one tenant blow through its
        quota inside a single pass) — give it back via :meth:`unpop`
        if admission cannot complete."""
        cands = [st for st in self._states.values() if st.queued()]
        if not cands:
            return None
        total_w = self._busy_weight()
        cands.sort(key=lambda st: (self._vt(st), st.spec.name))
        pick = next((st for st in cands
                     if self._under_quota(st, total_w)), cands[0])
        self._raise_floor(self._vt(pick))
        for lane in LANES:
            if pick.queues[lane]:
                req = pick.queues[lane].popleft()
                pick.active_slots += 1
                return req
        raise AssertionError("queued() lied")  # pragma: no cover

    def unpop(self, req: "GenRequest") -> None:
        """Give back a popped request (transient page exhaustion):
        lane-head re-queue + the provisional slot charge reversed."""
        st = self.state(req.tenant)
        st.active_slots -= 1
        st.queues[req.lane].appendleft(req)

    def note_dequeued(self, req: "GenRequest") -> None:
        """A popped request was closed out (cancelled / deadline shed)
        instead of admitted: reverse the provisional slot charge."""
        self.state(req.tenant).active_slots -= 1

    def note_pages(self, tenant: Optional[str], delta: int) -> None:
        self.state(tenant).pages += delta

    def find_pinned(self) -> Optional["GenRequest"]:
        """A queued preempted request still holding pinned KV pages
        (the prefill-free-resume claim), or None.  The engine's arena
        pressure valve: pinned pages must not starve the admission a
        preemption was FOR, so under exhaustion one claim is released
        and that request re-prefills at resume instead."""
        for st in self._states.values():
            for q in st.queues.values():
                for r in q:
                    if r.pinned_pages:
                        return r
        return None

    def note_finished(self, req: "GenRequest",
                      pages_released: int = 0) -> None:
        st = self.state(req.tenant)
        st.active_slots -= 1
        st.pages -= pages_released

    # -- service accounting (virtual time) ---------------------------------

    def charge_prefill(self, req: "GenRequest", tokens: int,
                       start: int = 0) -> None:
        """Charge ``tokens`` computed prefill tokens whose contexts
        begin past ``start`` cached ones (cache hits charge only the
        computed tail — AND, under FLOP pricing, at the tail's true
        deep-context cost)."""
        st = self.state(req.tenant)
        self._vt_advance(st,
                         self._token_cost(start, tokens) / st.spec.weight)
        st.m_prefill.inc(tokens)
        st.stats["prefill_tokens"] += tokens
        st.m_admitted[req.lane].inc()
        st.stats["admitted"] += 1

    def charge_decode(self, req: "GenRequest",
                      ctx: Optional[int] = None) -> None:
        """Charge one decoded token at context ``ctx`` (None = legacy
        flat charge — also what flop_weighted_cost=False yields)."""
        st = self.state(req.tenant)
        cost = (self._token_cost(ctx - 1, 1) if ctx is not None else 1.0)
        self._vt_advance(st, cost / st.spec.weight)
        st.m_decode.inc()
        st.stats["decode_tokens"] += 1

    def observe_ttft(self, req: "GenRequest", seconds: float) -> None:
        self.state(req.tenant).m_ttft[req.lane].observe(seconds)

    # -- preemption (lane semantics) ---------------------------------------

    def pop_interactive_preemptor(self) -> Optional["GenRequest"]:
        """Pop the interactive request that justifies evicting a batch
        slot mid-decode: smallest-virtual-clock tenant with queued
        interactive work that is still UNDER its slot quota.  Quota-
        capping the preemptor bounds preemption churn — sustained
        interactive overload stops taking batch slots at its weight
        share instead of starving the batch lane outright.  None when
        preemption is off or nobody qualifies.  Charges the tenant's
        provisional slot exactly like :meth:`pop_next` (``unpop`` to
        give it back)."""
        if not self.cfg.preemption:
            return None
        total_w = self._busy_weight()
        cands = [st for st in self._states.values()
                 if st.queues["interactive"]
                 and self._under_quota(st, total_w)]
        if not cands:
            return None
        st = min(cands, key=lambda s: (self._vt(s), s.spec.name))
        self._raise_floor(self._vt(st))
        req = st.queues["interactive"].popleft()
        st.active_slots += 1
        return req

    def pick_victim(self, slotted, *,
                    tokenless_eligible: bool = True) -> Optional[int]:
        """Choose the batch-lane slot to preempt: the request whose
        tenant has consumed the most weighted service (max virtual
        clock — the mirror image of the drain order), newest admission
        first on ties (least wasted work to redo).  Slots that have
        not yet decoded ``min_batch_progress`` tokens since their last
        (re)admission are ineligible — the progress guarantee that
        bounds thrash."""
        best, best_key = None, None
        for slot, req in slotted:
            if req.lane != "batch":
                continue
            # a slot still mid-chunked-prefill (no tokens emitted yet)
            # is eligible when the engine says eviction is free
            # (``tokenless_eligible``: paged mode — pinned pages resume
            # the remaining chunks exactly where they stopped, so no
            # work is lost).  In dense mode a preempted slot re-chunks
            # from position 0, so mid-prefill slots fall under the
            # progress guard like everyone else — without it a
            # sustained interactive stream could re-prefill the same
            # long prompt forever and the request never emits a token.
            if not req.tokens:
                if not tokenless_eligible:
                    continue
            elif (len(req.tokens) - req.resume_len
                    < self.cfg.min_batch_progress):
                continue
            key = (self._vt(self.state(req.tenant)),
                   req.admitted_at or 0.0)
            if best_key is None or key > best_key:
                best, best_key = slot, key
        return best

    def note_preempted(self, req: "GenRequest") -> None:
        st = self.state(req.tenant)
        st.active_slots -= 1  # pages stay charged while pinned
        st.m_preempted.inc()
        st.stats["preempted"] += 1

    # -- observability -----------------------------------------------------

    def refresh_gauges(self) -> None:
        for st in self._states.values():
            st.m_queue.set(st.queued())

    def snapshot(self) -> dict:
        """Per-tenant scheduling state for ``GET /debug/slots`` (and
        the bench): queue depths by lane, occupancy, virtual clocks."""
        total_w = self._busy_weight()
        out = {}
        for name, st in self._states.items():
            entry = {
                "lane": st.spec.lane,
                "weight": st.spec.weight,
                "queued": {lane: len(st.queues[lane]) for lane in LANES},
                "active_slots": st.active_slots,
                "slot_quota": self._quota_slots(st, total_w),
                "virtual_time": round(self._vt(st), 3),
                **st.stats,
            }
            if self.page_capacity:
                entry["pages"] = st.pages
                entry["page_quota"] = self._quota_pages(st, total_w)
            out[name] = entry
        return out

    def stats(self) -> dict:
        return {name: dict(st.stats)
                for name, st in self._states.items()}
