"""Shared serving-container bootstrap.

Every InferenceService container in ``deploy/`` boots through this:
parse the common flags, honor the ``.ready.txt`` download gate
(reference ``bloom.py:79-90``), pick the native C++ front-end when the
toolchain is present (stdlib fallback otherwise), and serve forever on
``--port`` / ``$PORT`` (KServe's injected port).
"""

from __future__ import annotations

import argparse
import logging
import os
from typing import Iterable, Optional

from kubernetes_cloud_tpu.serve.model import Model

log = logging.getLogger(__name__)


def add_common_args(ap: argparse.ArgumentParser) -> None:
    ap.add_argument("--model-name", default=None,
                    help="name on the V1 data plane")
    ap.add_argument("--port", type=int,
                    default=int(os.environ.get("PORT", "8080")))
    ap.add_argument("--ready-file", default=None,
                    help="wait for this sentinel before loading")
    ap.add_argument("--ready-timeout", type=float, default=3600.0)
    ap.add_argument("--frontend", choices=("auto", "native", "python"),
                    default="auto")
    ap.add_argument("--compile-cache",
                    default=os.environ.get("KCT_COMPILE_CACHE",
                                           "/tmp/jax-compile-cache"),
                    help="persistent XLA compile cache dir (PVC-mount it "
                         "so replica cold starts skip the 20-40s first "
                         "compile; empty string disables)")


def enable_compile_cache(args) -> None:
    """Persistent compilation cache: the TPU analogue of the cold-start
    problem the reference attacks with Tensorizer — weights stream fast,
    then XLA compiles for 20-40s.  A cache dir on the PVC makes every
    replica after the first boot with warm programs."""
    cache_dir = getattr(args, "compile_cache", None)
    if not cache_dir:
        return
    import jax

    try:
        jax.config.update("jax_compilation_cache_dir", cache_dir)
        jax.config.update("jax_persistent_cache_min_compile_time_secs",
                          1.0)
        log.info("persistent compile cache: %s", cache_dir)
    except Exception as e:  # noqa: BLE001 - cache is best-effort
        log.warning("compile cache unavailable: %s", e)


def wait_for_artifact(args) -> None:
    if not args.ready_file:
        return
    from kubernetes_cloud_tpu.weights.checkpoint import wait_ready

    directory = os.path.dirname(args.ready_file) or "."
    log.info("waiting for %s", args.ready_file)
    if not wait_ready(directory, args.ready_timeout):
        raise TimeoutError(f"artifact never became ready: {args.ready_file}")


def make_server(models: Iterable[Model], args):
    from kubernetes_cloud_tpu.serve import native_server
    from kubernetes_cloud_tpu.serve.server import ModelServer

    use_native = args.frontend == "native" or (
        args.frontend == "auto" and native_server.available())
    cls = native_server.NativeModelServer if use_native else ModelServer
    log.info("front-end: %s", cls.__name__)
    return cls(models, port=args.port)


def serve(models: Iterable[Model], args) -> None:  # pragma: no cover - loop
    enable_compile_cache(args)
    server = make_server(models, args)
    server.serve_forever()
