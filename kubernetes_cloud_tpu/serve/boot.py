"""Shared serving-container bootstrap.

Every InferenceService container in ``deploy/`` boots through this:
parse the common flags, honor the ``.ready.txt`` download gate
(reference ``bloom.py:79-90``), pick the native C++ front-end when the
toolchain is present (stdlib fallback otherwise), and serve forever on
``--port`` / ``$PORT`` (KServe's injected port).
"""

from __future__ import annotations

import argparse
import logging
import os
from typing import Iterable, Optional

from kubernetes_cloud_tpu.serve.model import Model

log = logging.getLogger(__name__)


def add_common_args(ap: argparse.ArgumentParser) -> None:
    ap.add_argument("--model-name", default=None,
                    help="name on the V1 data plane")
    ap.add_argument("--port", type=int,
                    default=int(os.environ.get("PORT", "8080")))
    ap.add_argument("--ready-file", default=None,
                    help="wait for this sentinel before loading")
    ap.add_argument("--ready-timeout", type=float, default=3600.0)
    ap.add_argument("--frontend", choices=("auto", "native", "python"),
                    default="auto")
    ap.add_argument("--compile-cache",
                    default=os.environ.get("KCT_COMPILE_CACHE",
                                           "/tmp/jax-compile-cache"),
                    help="persistent XLA compile cache dir (PVC-mount it "
                         "so replica cold starts skip the 20-40s first "
                         "compile; empty string disables)")
    ap.add_argument("--drain-timeout", type=float, default=30.0,
                    help="SIGTERM: max seconds to wait for in-flight "
                         "requests before closing (size the manifest's "
                         "terminationGracePeriodSeconds above this)")
    ap.add_argument("--hang-timeout", type=float, default=10.0,
                    help="supervisor: engine heartbeat staleness that "
                         "counts as a hang (must exceed the slowest "
                         "legitimate scheduler iteration)")
    ap.add_argument("--trace-log",
                    default=os.environ.get("KCT_TRACE_LOG"),
                    help="request-lifecycle trace JSONL path (spans "
                         "queued→admitted→prefill→decode→first_token→"
                         "complete per request id); unset disables "
                         "tracing — /metrics stays on regardless")
    ap.add_argument("--profile-dir",
                    default=os.environ.get("KCT_PROFILE_DIR",
                                           "/tmp/kct-profile"),
                    help="jax.profiler trace output dir for "
                         "GET /debug/profile?seconds=N windows "
                         "(TensorBoard-readable; PVC-mount it to pull "
                         "traces off a pod)")


def install_tracer(args) -> None:
    """Arm request-lifecycle tracing when ``--trace-log`` /
    ``KCT_TRACE_LOG`` names a JSONL sink (off by default: span writes
    are file I/O on the scheduler thread; the metrics registry, which
    is pure memory, is always on)."""
    path = getattr(args, "trace_log", None)
    if not path:
        return
    from kubernetes_cloud_tpu.obs import tracing

    tracing.install(tracing.RequestTracer(path))
    log.info("request tracing to %s", path)


def enable_compile_cache(args) -> None:
    """Persistent compilation cache: the TPU analogue of the cold-start
    problem the reference attacks with Tensorizer — weights stream fast,
    then XLA compiles for 20-40s.  A cache dir on the PVC makes every
    replica after the first boot with warm programs."""
    cache_dir = getattr(args, "compile_cache", None)
    if not cache_dir:
        return
    import jax

    try:
        jax.config.update("jax_compilation_cache_dir", cache_dir)
        jax.config.update("jax_persistent_cache_min_compile_time_secs",
                          1.0)
        log.info("persistent compile cache: %s", cache_dir)
    except Exception as e:  # noqa: BLE001 - cache is best-effort
        log.warning("compile cache unavailable: %s", e)


def wait_for_artifact(args) -> None:
    if not args.ready_file:
        return
    from kubernetes_cloud_tpu.weights.checkpoint import wait_ready

    directory = os.path.dirname(args.ready_file) or "."
    log.info("waiting for %s", args.ready_file)
    if not wait_ready(directory, args.ready_timeout):
        raise TimeoutError(f"artifact never became ready: {args.ready_file}")


def make_server(models: Iterable[Model], args):
    from kubernetes_cloud_tpu.serve import native_server
    from kubernetes_cloud_tpu.serve.server import ModelServer

    use_native = args.frontend == "native" or (
        args.frontend == "auto" and native_server.available())
    cls = native_server.NativeModelServer if use_native else ModelServer
    log.info("front-end: %s", cls.__name__)
    server = cls(models, port=args.port)
    profile_dir = getattr(args, "profile_dir", None)
    if profile_dir:
        server.profiler.trace_dir = profile_dir
    return server


def install_sigterm_drain(server, drain_timeout: float = 30.0) -> bool:
    """Knative pod termination: SIGTERM → graceful drain (readiness 503,
    stop admitting, finish in-flight, drain worker slots, close) instead
    of dropping every open stream.  The drain runs on its own thread —
    ``ThreadingHTTPServer.shutdown`` deadlocks if called from the thread
    running ``serve_forever`` (which is where the handler fires)."""
    import signal
    import threading

    def _terminate(signum, frame):
        log.info("SIGTERM: draining (timeout %.0fs)", drain_timeout)
        threading.Thread(target=server.drain, args=(drain_timeout,),
                         daemon=True, name="sigterm-drain").start()

    try:
        signal.signal(signal.SIGTERM, _terminate)
        return True
    except ValueError:  # not on the main thread (embedded/test use)
        log.warning("not on the main thread; SIGTERM drain not installed")
        return False


def serve(models: Iterable[Model], args) -> None:  # pragma: no cover - loop
    from kubernetes_cloud_tpu import faults
    from kubernetes_cloud_tpu.serve.supervisor import (
        SupervisorConfig,
        supervise,
    )

    enable_compile_cache(args)
    faults.install_from_env()  # chaos drills: KCT_FAULTS json specs
    install_tracer(args)  # request spans: --trace-log / KCT_TRACE_LOG
    models = list(models)  # iterated twice (server + supervisor); a
    # generator would leave the supervisor silently watching nothing
    server = make_server(models, args)
    sup = supervise(models, SupervisorConfig(
        hang_timeout_s=getattr(args, "hang_timeout", 10.0)))
    if sup is not None:
        log.info("serving supervisor watching %d worker model(s)",
                 len(sup._watched))
    install_sigterm_drain(server, getattr(args, "drain_timeout", 30.0))
    server.serve_forever()  # returns after a SIGTERM drain completes
