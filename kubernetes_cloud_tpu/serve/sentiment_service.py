"""Container entrypoint for the sentiment predictor
(``deploy/online-inference/custom-predictors/custom-sentiment-isvc.yaml``;
see :mod:`kubernetes_cloud_tpu.serve.sentiment`)."""

from __future__ import annotations

import argparse
import logging
from typing import Optional

from kubernetes_cloud_tpu.serve import boot
from kubernetes_cloud_tpu.serve.sentiment import SentimentModel


def main(argv: Optional[list] = None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--model", required=True,
                    help="dir containing sentiment.tensors")
    boot.add_common_args(ap)
    args = ap.parse_args(argv)
    logging.basicConfig(level=logging.INFO)
    boot.wait_for_artifact(args)
    svc = SentimentModel(args.model_name or "sentiment",
                         artifact_dir=args.model)
    boot.serve([svc], args)
    return 0


if __name__ == "__main__":  # pragma: no cover - container entry
    import sys

    sys.exit(main())
