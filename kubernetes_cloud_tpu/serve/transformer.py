"""Transformer sidecars — KServe's pre/post-processing containers.

The reference splits encode/decode out of the predictor into a separate
"transformer" pod on the KServe data plane: the GPT-2 service BPE-encodes
text before TF-Serving and decodes logits after
(``online-inference/gpt-2/transformer/transformer.py:16-20``), and the
image classifier turns b64/URL images into tensors and argmax outputs into
ImageNet labels (``online-inference/image-classifier/transformer/
transformer.py:25-48``).  Same split here: a transformer is itself a
:class:`~kubernetes_cloud_tpu.serve.model.Model` served by
:class:`~kubernetes_cloud_tpu.serve.server.ModelServer`, forwarding the
transformed payload to ``predictor_host`` over the V1 protocol.
"""

from __future__ import annotations

import base64
import io
import json
import urllib.request
from typing import Any, Mapping, Optional

import numpy as np

from kubernetes_cloud_tpu.serve.model import Model


class TransformerModel(Model):
    """preprocess → POST predictor_host/v1/models/<name>:predict →
    postprocess."""

    def __init__(self, name: str, predictor_host: str, *,
                 timeout: float = 300.0):
        super().__init__(name)
        self.predictor_host = predictor_host.rstrip("/")
        self.timeout = timeout

    def load(self) -> None:
        self.ready = True

    def preprocess(self, payload: Mapping[str, Any]) -> dict:
        return dict(payload)

    def postprocess(self, response: Mapping[str, Any]) -> dict:
        return dict(response)

    def _forward(self, payload: dict) -> dict:
        from kubernetes_cloud_tpu.serve.clients import predict

        host = (self.predictor_host if "://" in self.predictor_host
                else f"http://{self.predictor_host}")
        return predict(f"{host}/v1/models/{self.name}:predict", payload,
                       timeout=self.timeout)

    def predict(self, payload: Mapping[str, Any]) -> dict:
        return self.postprocess(self._forward(self.preprocess(payload)))


class TextBPETransformer(TransformerModel):
    """GPT-2-style text sidecar: BPE-encode ``instances`` strings to token
    ids, decode predicted token ids back to text (reference
    ``gpt-2/transformer/transformer.py``)."""

    def __init__(self, name: str, predictor_host: str, *,
                 codec=None, codec_dir: Optional[str] = None, **kw):
        super().__init__(name, predictor_host, **kw)
        if codec is None:
            from kubernetes_cloud_tpu.serve.bpe import BPECodec

            if codec_dir is None:
                raise ValueError("need codec or codec_dir")
            codec = BPECodec.from_dir(codec_dir)
        self.codec = codec

    def preprocess(self, payload: Mapping[str, Any]) -> dict:
        return {"instances": [self.codec.encode(t)
                              for t in payload.get("instances", [])]}

    def postprocess(self, response: Mapping[str, Any]) -> dict:
        return {"predictions": [self.codec.decode(ids)
                                for ids in response.get("predictions", [])]}


#: ImageNet class-id → human label; loaded lazily from a JSON mapping file
#: (the reference ships ``imagenet_classes.json`` in its transformer image).
def load_class_map(path: str) -> dict[int, str]:
    with open(path) as f:
        raw = json.load(f)
    if isinstance(raw, list):
        return dict(enumerate(raw))
    return {int(k): v for k, v in raw.items()}


class ImageTransformer(TransformerModel):
    """Image sidecar: accepts ``{"instances": [{"image_bytes": {"b64": ..}}
    | {"image_url": ...}]}``, emits normalized NHWC tensors; postprocess
    maps argmax (or the predictor's label ids) to class names (reference
    ``image-classifier/transformer/transformer.py:25-48``)."""

    def __init__(self, name: str, predictor_host: str, *,
                 image_size: int = 224,
                 class_map: Optional[dict[int, str]] = None, **kw):
        super().__init__(name, predictor_host, **kw)
        self.image_size = image_size
        self.class_map = class_map or {}

    def _decode_image(self, inst: Mapping[str, Any]) -> np.ndarray:
        from PIL import Image

        if "image_bytes" in inst:
            data = base64.b64decode(inst["image_bytes"]["b64"])
        elif "image_url" in inst:
            with urllib.request.urlopen(inst["image_url"],
                                        timeout=self.timeout) as r:
                data = r.read()
        else:
            raise ValueError("instance needs image_bytes.b64 or image_url")
        img = Image.open(io.BytesIO(data)).convert("RGB")
        # Same transform the eval data path uses — serving preprocessing
        # must not drift from training-side eval preprocessing.
        from kubernetes_cloud_tpu.data.images import eval_transform

        return eval_transform(img, self.image_size)

    def preprocess(self, payload: Mapping[str, Any]) -> dict:
        return {"instances": [self._decode_image(i).tolist()
                              for i in payload.get("instances", [])]}

    def postprocess(self, response: Mapping[str, Any]) -> dict:
        out = []
        for pred in response.get("predictions", []):
            if isinstance(pred, list):  # raw logits → argmax
                pred = int(np.argmax(np.asarray(pred)))
            out.append(self.class_map.get(int(pred), str(pred)))
        return {"predictions": out}
