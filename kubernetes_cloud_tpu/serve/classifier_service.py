"""Image-classifier predictor service
(``deploy/online-inference/image-classifier/classifier-inferenceservice
.yaml``).  The reference serves a TF SavedModel through TF-Serving with a
transformer sidecar doing image decode and label mapping
(``online-inference/image-classifier/``); here the predictor is the
ResNet family on TPU and the sidecar is
:mod:`kubernetes_cloud_tpu.serve.classifier_transformer`.

Request: ``{"instances": [[H][W][3] float array, ...]}`` (what the
sidecar emits) → ``{"predictions": [[logits...], ...]}``.
"""

from __future__ import annotations

import argparse
import logging
import os
import time
from typing import Any, Mapping, Optional

import jax
import jax.numpy as jnp
import numpy as np

from kubernetes_cloud_tpu.serve import boot
from kubernetes_cloud_tpu.serve.model import Model

log = logging.getLogger(__name__)


class VisionClassifierService(Model):
    def __init__(self, name: str, model_dir: str):
        super().__init__(name)
        self.model_dir = model_dir

    def load(self) -> None:
        import dataclasses

        from kubernetes_cloud_tpu.models.vision.resnet import ResNetConfig
        from kubernetes_cloud_tpu.weights.tensorstream import (
            load_pytree,
            read_index,
        )

        from kubernetes_cloud_tpu.weights.tensorstream import (
            resolve_artifact,
        )

        path = resolve_artifact(self.model_dir)
        t0 = time.perf_counter()
        meta = read_index(path)["meta"]
        raw = dict(meta.get("resnet_config", {}))
        fields = {f.name for f in dataclasses.fields(ResNetConfig)}
        raw = {k: v for k, v in raw.items()
               if k in fields and k not in ("dtype", "param_dtype")}
        self.cfg = ResNetConfig(**raw)
        tree = load_pytree(path)
        self.params = tree["params"]
        self.batch_stats = tree["batch_stats"]
        self._forward = jax.jit(self._logits)
        log.info("loaded %s in %.2fs", path, time.perf_counter() - t0)
        self.ready = True

    def _logits(self, images):
        from kubernetes_cloud_tpu.models.vision.resnet import forward

        logits, _ = forward(self.cfg, self.params, images,
                            self.batch_stats, train=False)
        return logits

    def predict(self, payload: Mapping[str, Any]) -> dict:
        instances = payload.get("instances")
        if not isinstance(instances, list) or not instances:
            raise ValueError('payload needs {"instances": [image, ...]}')
        batch = jnp.asarray(np.asarray(instances, np.float32))
        if batch.ndim != 4 or batch.shape[-1] != 3:
            raise ValueError(
                f"instances must be [N, H, W, 3] images, got {batch.shape}")
        logits = np.asarray(self._forward(batch))
        return {"predictions": logits.tolist()}


def main(argv: Optional[list] = None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--model", required=True,
                    help=".tensors file or dir containing model.tensors")
    boot.add_common_args(ap)
    args = ap.parse_args(argv)
    logging.basicConfig(level=logging.INFO)
    boot.wait_for_artifact(args)
    svc = VisionClassifierService(args.model_name or "classifier",
                                  args.model)
    boot.serve([svc], args)
    return 0


if __name__ == "__main__":  # pragma: no cover - container entry
    import sys

    sys.exit(main())
