from kubernetes_cloud_tpu.serve.model import Model  # noqa: F401
from kubernetes_cloud_tpu.serve.errors import (  # noqa: F401
    DeadlineExceededError,
    EngineRestartedError,
    QueueFullError,
    RetryableError,
    StreamTimeoutError,
)
from kubernetes_cloud_tpu.serve.server import ModelServer  # noqa: F401
from kubernetes_cloud_tpu.serve.supervisor import (  # noqa: F401
    ServingSupervisor,
    SupervisorConfig,
    supervise,
)
from kubernetes_cloud_tpu.serve.lm_service import (  # noqa: F401
    ByteTokenizer,
    CausalLMService,
)
from kubernetes_cloud_tpu.serve.continuous import (  # noqa: F401
    ContinuousBatchingEngine,
    ContinuousBatchingModel,
    EngineConfig,
)
