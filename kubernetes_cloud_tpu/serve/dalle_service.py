"""Container entrypoint for the replicated multi-candidate txt2img
service (``deploy/online-inference/dalle-mini/02-inference-service.yaml``;
capability parity with the reference's DALL-E Mini JAX service —
see :mod:`kubernetes_cloud_tpu.serve.replicated`)."""

from __future__ import annotations

from typing import Optional

from kubernetes_cloud_tpu.serve.replicated import ReplicatedTxt2ImgService
from kubernetes_cloud_tpu.serve.sd_service import main as _sd_main


def main(argv: Optional[list] = None) -> int:
    return _sd_main(argv, service_cls=ReplicatedTxt2ImgService)


if __name__ == "__main__":  # pragma: no cover - container entry
    import sys

    sys.exit(main())
