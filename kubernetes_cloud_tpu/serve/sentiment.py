"""Sentiment predictor — the ``custom-sentiment`` service, JAX-native.

The reference serves a fastai text learner behind a 25-line ``KFModel``
(``online-inference/custom-sentiment/custom-predictor/model.py:6-30``):
``load()`` reads an exported artifact off the PVC, ``predict()`` maps
``instances`` strings to labeled scores.  Here the artifact is a hashed
bag-of-words linear classifier — a pure-JAX pytree saved with
:mod:`kubernetes_cloud_tpu.weights.tensorstream` — because the service
contract (artifact on PVC → label + confidence per instance) is the
capability, not fastai.  Training is included so the artifact is
reproducible end-to-end on CPU in seconds.
"""

from __future__ import annotations

import os
import re
import zlib
from typing import Any, Iterable, Mapping

import jax
import jax.numpy as jnp
import numpy as np

from kubernetes_cloud_tpu.serve.model import Model

_TOKEN = re.compile(r"[a-z0-9']+")
N_BUCKETS = 1 << 16
LABELS = ("negative", "positive")


def featurize(text: str) -> np.ndarray:
    """Hashed unigram+bigram counts, L2-normalized."""
    toks = _TOKEN.findall(text.lower())
    grams = toks + [f"{a}_{b}" for a, b in zip(toks, toks[1:])]
    vec = np.zeros((N_BUCKETS,), np.float32)
    for g in grams:
        # crc32, NOT hash(): Python's hash is salted per process, which
        # would scramble buckets between the training job and the serving
        # pod loading the artifact.
        vec[zlib.crc32(g.encode()) % N_BUCKETS] += 1.0
    n = np.linalg.norm(vec)
    return vec / n if n else vec


def init_params(rng: jax.Array) -> dict:
    return {"w": jnp.zeros((N_BUCKETS, len(LABELS)), jnp.float32),
            "b": jnp.zeros((len(LABELS),), jnp.float32)}


def train(texts: Iterable[str], labels: Iterable[int], *,
          epochs: int = 20, lr: float = 1.0) -> dict:
    """Full-batch logistic regression (the corpus is small by design)."""
    x = jnp.asarray(np.stack([featurize(t) for t in texts]))
    y = jnp.asarray(np.asarray(list(labels), np.int32))
    params = init_params(jax.random.key(0))

    @jax.jit
    def step(params):
        def loss(p):
            logits = x @ p["w"] + p["b"]
            logp = jax.nn.log_softmax(logits)
            return -jnp.mean(jnp.take_along_axis(logp, y[:, None], 1))

        g = jax.grad(loss)(params)
        return jax.tree.map(lambda p, gi: p - lr * gi, params, g)

    for _ in range(epochs):
        params = step(params)
    return params


class SentimentModel(Model):
    """``{"instances": ["text", ...]}`` → label + probability each."""

    def __init__(self, name: str = "sentiment",
                 artifact_dir: str = "/mnt/model"):
        super().__init__(name)
        self.artifact_dir = artifact_dir
        self.params: dict | None = None

    def load(self) -> None:
        from kubernetes_cloud_tpu.weights.tensorstream import load_pytree

        path = os.path.join(self.artifact_dir, "sentiment.tensors")
        self.params = load_pytree(path)
        self.ready = True

    def save(self, params: dict) -> str:
        from kubernetes_cloud_tpu.weights.tensorstream import write_pytree

        os.makedirs(self.artifact_dir, exist_ok=True)
        path = os.path.join(self.artifact_dir, "sentiment.tensors")
        write_pytree(path, params)
        return path

    def predict(self, payload: Mapping[str, Any]) -> dict:
        texts = payload.get("instances")
        if not isinstance(texts, list):
            raise ValueError('payload needs {"instances": [text, ...]}')
        if not texts:
            return {"predictions": []}
        x = jnp.asarray(np.stack([featurize(t) for t in texts]))
        probs = jax.nn.softmax(x @ self.params["w"] + self.params["b"])
        probs = np.asarray(probs)
        out = []
        for row in probs:
            idx = int(np.argmax(row))
            out.append({"label": LABELS[idx],
                        "score": float(row[idx])})
        return {"predictions": out}
