"""Text BPE encode/decode sidecar container
(``deploy/online-inference/gpt-2/gpt-s3-inferenceservice.yaml``
transformer; logic in
:class:`kubernetes_cloud_tpu.serve.transformer.TextBPETransformer`)."""

from __future__ import annotations

import argparse
import logging
import os
from typing import Optional

from kubernetes_cloud_tpu.serve import boot
from kubernetes_cloud_tpu.serve.transformer import TextBPETransformer


def main(argv: Optional[list] = None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--predictor-host",
                    default=os.environ.get("PREDICTOR_HOST",
                                           "127.0.0.1:8081"))
    ap.add_argument("--codec-dir",
                    default=os.environ.get("CODEC_DIR", "/mnt/models"),
                    help="dir with vocab.json + merges.txt")
    boot.add_common_args(ap)
    args = ap.parse_args(argv)
    logging.basicConfig(level=logging.INFO)
    boot.wait_for_artifact(args)  # vocab/merges may still be downloading
    svc = TextBPETransformer(args.model_name or "gpt2",
                             args.predictor_host,
                             codec_dir=args.codec_dir)
    boot.serve([svc], args)
    return 0


if __name__ == "__main__":  # pragma: no cover - container entry
    import sys

    sys.exit(main())
