"""Replicated multi-candidate txt2img service — the DALL-E Mini pattern.

The reference's one JAX service (``online-inference/dalle-mini/model/
service.py``) replicates Flax params over all local devices and pmaps
generate/decode with a sharded PRNG key, returning ``num_images``
candidate images per prompt in one device-parallel call (``:121-158``).
pmap + ``replicate()`` is legacy JAX; the same program here is a mesh
whose ``data`` axis spans the local devices with the candidate batch
sharded over it — XLA partitions the denoising loop per candidate and the
code is identical single- and multi-chip.

Request protocol parity: ``{"instances": [{"prompt": ...}], "parameters":
{"num_predictions": N, ...}}`` → N b64 PNGs.
"""

from __future__ import annotations

import time
from typing import Any, Mapping

import jax

from kubernetes_cloud_tpu.core.mesh import MeshSpec, build_mesh
from kubernetes_cloud_tpu.serve.sd_service import StableDiffusionService


class ReplicatedTxt2ImgService(StableDiffusionService):
    OPTIONS = {
        **StableDiffusionService.OPTIONS,
        "NUM_PREDICTIONS": 0,  # 0 => one per local device
    }

    def __init__(self, name: str, model_dir: str, tokenize=None,
                 devices=None):
        super().__init__(name, model_dir, tokenize)
        self._devices = devices

    def load(self) -> None:
        super().load()
        devices = self._devices or jax.local_devices()
        self.mesh = build_mesh(MeshSpec(data=len(devices)), devices=devices)
        self.n_devices = len(devices)

    def predict(self, payload: Mapping[str, Any]) -> dict:
        from kubernetes_cloud_tpu.serve.sd_service import (
            extract_prompt,
            png_predictions,
        )

        opts = self.configure_request(payload)
        prompt = extract_prompt(payload)
        n = int(opts["NUM_PREDICTIONS"]) or self.n_devices
        # candidate batch must tile the data axis; round up like the
        # reference rounds to whole devices, then trim
        n_padded = -(-n // self.n_devices) * self.n_devices
        t0 = time.time()
        imgs = self.generate_batch(
            prompt, n_images=n_padded, height=int(opts["HEIGHT"]),
            width=int(opts["WIDTH"]),
            steps=int(opts["NUM_INFERENCE_STEPS"]),
            guidance_scale=float(opts["GUIDANCE_SCALE"]),
            seed=int(opts["SEED"]), mesh=self.mesh)[:n]
        return {"predictions": png_predictions(imgs, time.time() - t0)}
