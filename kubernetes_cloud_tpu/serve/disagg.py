"""Prefill/decode disaggregation — DistServe-style role coupling
(OSDI '24; see PAPERS.md).

A long prefill occupies a whole engine iteration, so every admission
burst inflates the inter-token latency of every ACTIVE request — the
micro-partition interference PAPERS.md's Tail-at-Scale entry deferred
to this layer.  This module splits the Orca loop across roles:

* a **prefill-role** :class:`~kubernetes_cloud_tpu.serve.continuous.
  ContinuousBatchingEngine` owns admission (tenancy buckets, WFQ,
  prefix cache) and runs prefill only — after a request's first token
  it extracts the prompt's KV pages and hands the request over;
* one or more **decode-role** engines adopt the request: the pages
  install into their own arena's free list and the request resumes
  through the existing pinned-pages path — page-granular transfer,
  ZERO re-prefill tokens on the happy path (``stats["reprefill_
  tokens"]`` is the acceptance counter);
* :class:`DisaggregatedEngine` is the coupler: it presents the same
  duck-typed surface as a single engine (``ContinuousBatchingModel``
  and the debug plane cannot tell), routes handoffs to the least-
  loaded live decode slice, and runs a small monitor that transplants
  a dead decode slice's queued requests onto a survivor — which
  re-prefills them (token-identically, via the virtual-prompt resume)
  rather than losing them.

In-process the "transfer" is host-staged (device→host→device); on
hardware the same page indices address per-slice arenas and the
payload rides DCN/ICI — the deploy story (prefill and decode slice
groups with distinct ``gke-tpu-topology`` selectors) lives in
deploy/README.md "Sharded & disaggregated serving".
"""

from __future__ import annotations

import dataclasses
import logging
import threading
import time
from typing import Any, Optional, Sequence

from kubernetes_cloud_tpu.serve.continuous import (
    ContinuousBatchingEngine,
    EngineConfig,
    GenRequest,
    KVHandoff,
    _STREAM_END,
)
from kubernetes_cloud_tpu.serve.errors import (
    EngineRestartedError,
    RetryableError,
)
from kubernetes_cloud_tpu.obs.tracing import trace

log = logging.getLogger(__name__)


class _CombinedHeartbeat:
    """Worst-of view over the member engines' heartbeats — what the
    supervisor's staleness watchdog should see: the pair is only as
    live as its sickest scheduler."""

    def __init__(self, engines: Sequence[ContinuousBatchingEngine]):
        self._engines = list(engines)

    def beat(self) -> None:  # the members beat themselves
        pass

    @property
    def age(self) -> float:
        return max(e.heartbeat.age for e in self._engines)


class DisaggregatedEngine:
    """One prefill engine + N decode engines behind the single-engine
    surface ``ContinuousBatchingModel`` (and the debug plane, the
    supervisor's duck-typed probes, the fleet's clock attach) already
    speaks."""

    def __init__(self, prefill: ContinuousBatchingEngine,
                 decodes: Sequence[ContinuousBatchingEngine], *,
                 name: str = "engine",
                 monitor_interval_s: float = 0.1):
        if not decodes:
            raise ValueError("a disaggregated engine needs at least "
                             "one decode slice")
        self.name = name
        self.prefill = prefill
        self.decodes = list(decodes)
        self.monitor_interval_s = monitor_interval_s
        #: config surface: the prefill side is the admission door, so
        #: its config answers capacity/identity questions
        self.ecfg = prefill.ecfg
        self.cfg = prefill.cfg
        self.paged = True
        self.mesh_shards = prefill.mesh_shards
        self.heartbeat = _CombinedHeartbeat([prefill, *self.decodes])
        #: supervisor duck-typing (`_EngineTarget.deliberately_stopped`
        #: reads engine._stop): the pair's stop() runs through the
        #: prefill engine first, so its event IS the pair's intent
        self._stop = prefill._stop
        self.stats_extra = {"transplants": 0, "handoff_failed": 0}
        self._rr = 0
        self._lock = threading.Lock()
        self._monitor_stop = threading.Event()
        self._monitor_thread: Optional[threading.Thread] = None
        #: decode engines whose death was already transplanted
        self._dead_handled: set[int] = set()
        prefill.set_handoff(self._handoff)

    # -- lifecycle ---------------------------------------------------------

    @property
    def engines(self) -> list[ContinuousBatchingEngine]:
        return [self.prefill, *self.decodes]

    @property
    def alive(self) -> bool:
        """Serving requires the admission door AND at least one decode
        slice; dead minority slices are the monitor's problem."""
        return self.prefill.alive and any(d.alive for d in self.decodes)

    @property
    def draining(self) -> bool:
        return any(e.draining for e in self.engines)

    @property
    def grace_until(self) -> float:
        return max(e.grace_until for e in self.engines)

    @property
    def last_error(self) -> Optional[Exception]:
        for e in self.engines:
            if e.last_error is not None:
                return e.last_error
        return None

    @property
    def iter_s(self) -> Optional[float]:
        return self.prefill.iter_s

    @property
    def tenants(self):
        """Admission-side scheduler (fleet-clock attach point)."""
        return self.prefill.tenants

    @property
    def allocator(self):
        return self.prefill.allocator

    @property
    def flight(self):
        """The prefill ring backs ``/debug/timeline`` for the pair;
        per-slice rings stay reachable through ``debug_meta``'s
        engine listing."""
        return self.prefill.flight

    def start(self) -> None:
        # decode slices first: a handoff fired during prefill warmup
        # must have a live target
        for eng in self.decodes:
            eng.start()
        self.prefill.start()
        if self._monitor_thread is None or \
                not self._monitor_thread.is_alive():
            self._monitor_stop.clear()
            self._monitor_thread = threading.Thread(
                target=self._monitor, daemon=True,
                name="disagg-monitor")
            self._monitor_thread.start()

    def stop(self) -> None:
        self._monitor_stop.set()
        if self._monitor_thread is not None:
            self._monitor_thread.join(timeout=5.0)
            self._monitor_thread = None
        # prefill first: its drain flushes in-flight handoffs into the
        # decode slices, which then drain their slots to completion
        self.prefill.stop()
        for eng in self.decodes:
            eng.stop()

    # -- request side (the ContinuousBatchingModel surface) ----------------

    def submit(self, *args, **kwargs) -> GenRequest:
        return self.prefill.submit(*args, **kwargs)

    def requeue(self, req: GenRequest) -> None:
        """Supervisor/fleet transplant intake: re-admit through the
        prefill door (it re-prefills the virtual prompt and hands the
        KV to a decode slice, token-identity intact)."""
        self.prefill.requeue(req)

    def extract_queued(self) -> list[GenRequest]:
        out = []
        for eng in self.engines:
            out.extend(eng.extract_queued())
        return out

    def abandon(self, err: Exception) -> list[GenRequest]:
        out = []
        for eng in self.engines:
            out.extend(eng.abandon(err))
        return out

    def queue_depth(self) -> int:
        return sum(e.queue_depth() for e in self.engines)

    def estimated_queue_delay(self, tenant: Optional[str] = None
                              ) -> float:
        return self.prefill.estimated_queue_delay(tenant)

    def reset_peak_active(self) -> None:
        for eng in self.engines:
            eng.reset_peak_active()

    def note_quant_probe(self, probe) -> None:
        for eng in self.engines:
            eng.note_quant_probe(probe)

    def request_phase(self, request_id: Optional[str]) -> Optional[str]:
        phase = None
        for eng in self.engines:
            got = eng.request_phase(request_id)
            if got == "active":
                return "active"
            phase = phase or got
        return phase

    def cancel_request(self, request_id: Optional[str]) -> bool:
        hit = False
        for eng in self.engines:
            hit = eng.cancel_request(request_id) or hit
        return hit

    @property
    def stats(self) -> dict:
        """Summed member stats plus coupler counters; per-engine dicts
        ride along under ``engines`` for the bench's A/B breakdowns.
        ``kv_transfer_pages`` counts each page ONCE (the decode-side
        install) — a blind sum would add the prefill side's export of
        the very same pages and double the figure."""
        agg: dict[str, Any] = dict(self.stats_extra)
        for eng in self.engines:
            for k, v in eng.stats.items():
                agg[k] = agg.get(k, 0) + v
        agg["kv_transfer_pages"] = sum(
            e.stats["kv_transfer_pages"] for e in self.decodes)
        agg["engines"] = {e.name: dict(e.stats) for e in self.engines}
        return agg

    # -- debug plane -------------------------------------------------------

    def debug_meta(self) -> dict:
        meta = self.prefill.debug_meta()
        meta["role"] = "disaggregated"
        meta["decode_slices"] = len(self.decodes)
        meta["slices"] = {e.name: {"role": e.role, "alive": e.alive}
                          for e in self.engines}
        return meta

    def debug_slots(self) -> list[dict]:
        out = []
        for eng in self.engines:
            for entry in eng.debug_slots():
                out.append({"engine": eng.name, "role": eng.role,
                            **entry})
        return out

    def debug_tenants(self) -> dict:
        return self.prefill.debug_tenants()

    def debug_pages(self) -> Optional[dict]:
        snap = self.prefill.debug_pages() or {}
        snap["slices"] = {e.name: e.debug_pages() for e in self.decodes}
        return snap

    # -- coupling ----------------------------------------------------------

    def _pick_decode(self, exclude: Optional[set] = None
                     ) -> Optional[ContinuousBatchingEngine]:
        """Least-loaded live decode slice (active slots + queued),
        round-robin on ties so a cold pair interleaves."""
        live = [e for e in self.decodes if e.alive
                and not e._stop.is_set()
                and (not exclude or id(e) not in exclude)]
        if not live:
            return None
        with self._lock:
            self._rr += 1
            rr = self._rr
        return min(
            (e for e in live),
            key=lambda e: (sum(1 for s in e._slots if s is not None)
                           + e.queue_depth(),
                           (self.decodes.index(e) + rr)
                           % max(len(self.decodes), 1)))

    def _handoff(self, req: GenRequest, payload: KVHandoff) -> None:
        """Runs on the prefill engine's scheduler thread.  A slice
        that dies between pick and adopt is failed over: every live
        slice gets a try before the request is bounced back to the
        client."""
        tried: set[int] = set()
        while True:
            eng = self._pick_decode(exclude=tried)
            if eng is None:
                break
            try:
                t0 = time.monotonic()
                eng.adopt(req, payload)
                trace(req.request_id, "kv_transfer", model=self.name,
                      dur_s=time.monotonic() - t0, target=eng.name)
                return
            except Exception as e:  # noqa: BLE001 - a dead slice is an
                # outcome to fail over, never an unwound scheduler
                tried.add(id(eng))
                log.warning("%s: handoff to %s failed: %s", self.name,
                            eng.name, e)
        with self._lock:
            self.stats_extra["handoff_failed"] += 1
        if not req.event.is_set():
            req.error = RetryableError(
                "no live decode slice to adopt the request; retry")
            trace(req.request_id, "failed", model=self.name,
                  error="RetryableError")
            req.stream.put(_STREAM_END)
            req.event.set()

    def _monitor(self) -> None:
        """Transplant a dead decode slice's queued work onto a
        survivor: the survivor re-prefills each request's virtual
        prompt (prompt + emitted tokens) and continues token-
        identically — mid-decode actives already failed with the
        typed retryable 503 when the slice died (the client retry
        path), exactly like a supervisor crash."""
        while not self._monitor_stop.wait(self.monitor_interval_s):
            for i, eng in enumerate(self.decodes):
                if eng.alive or i in self._dead_handled:
                    continue
                self._dead_handled.add(i)
                orphans = eng.abandon(EngineRestartedError(
                    f"decode slice {eng.name} died; retry"))
                survivors = [d for d in self.decodes if d.alive]
                moved = 0
                for req in orphans:
                    if req.cancelled:
                        continue
                    if survivors:
                        survivors[0].requeue(req)
                        moved += 1
                    elif not req.event.is_set():
                        req.error = RetryableError(
                            "every decode slice is down; retry")
                        req.stream.put(_STREAM_END)
                        req.event.set()
                with self._lock:
                    self.stats_extra["transplants"] += moved
                log.warning(
                    "%s: decode slice %s died; transplanted %d queued "
                    "request(s) to %s", self.name, eng.name, moved,
                    survivors[0].name if survivors else "nobody")


def build_disaggregated_engine(cfg, params, engine_cfg: EngineConfig, *,
                               eos_token_id=None, pad_token_id: int = 0,
                               mesh=None, name: str = "engine",
                               draft=None,
                               weights_version=None) -> DisaggregatedEngine:
    """One prefill engine + ``engine_cfg.decode_slices`` decode
    engines over shared weights (in-process; on hardware each engine
    maps to its own slice group), coupled by page-granular KV
    handoff.  A speculative-decoding ``draft`` goes to the decode
    slices only (a prefill-role engine never decodes, so it never
    speculates)."""
    from kubernetes_cloud_tpu.serve.spec_decode import DraftSource

    if (engine_cfg.decode_slices > 1 and isinstance(draft, DraftSource)
            and not draft.shareable):
        # a stateful DraftSource (ModelDraft: its own slot pool keyed
        # by engine-local slot index, mutated lock-free on the owning
        # scheduler thread) handed to N decode engines would race its
        # pool and collide slot namespaces.  Pass (cfg, params) so
        # every slice builds a private draft, or run one slice.
        raise ValueError(
            f"draft source {draft.kind!r} holds per-slot state and "
            f"cannot be shared across {engine_cfg.decode_slices} "
            "decode slices; pass (cfg, params) instead so each slice "
            "builds its own, or set decode_slices=1")
    pcfg = dataclasses.replace(engine_cfg, role="prefill")
    dcfg = dataclasses.replace(engine_cfg, role="decode")
    prefill = ContinuousBatchingEngine(
        cfg, params, pcfg, eos_token_id=eos_token_id,
        pad_token_id=pad_token_id, mesh=mesh, name=f"{name}-prefill",
        weights_version=weights_version)
    decodes = [
        ContinuousBatchingEngine(
            cfg, params, dcfg, eos_token_id=eos_token_id,
            pad_token_id=pad_token_id, mesh=mesh,
            name=f"{name}-decode{i}", draft=draft,
            weights_version=weights_version)
        for i in range(engine_cfg.decode_slices)]
    pod = DisaggregatedEngine(prefill, decodes, name=name)
    # the facade answers serving_metadata/probes for the whole pod
    pod.weights_version = weights_version
    return pod
