"""Lifecycle-managed model registry — the multiplexing half of the
streaming-weights plane (ROADMAP item 2: "that refactor is the point").

``ModelServer`` historically held ``{name: Model}``, built once and
never mutated: one static model per process, a failed ``load()``
leaving the registry half-populated.  :class:`ModelCache` replaces it
**as a dict subclass** — every ``items()`` / ``get()`` / ``sorted()``
call site in the server, fleet router, and debug plane keeps working —
and adds the lifecycle the multi-model story needs:

* **states**: ``loading → active → draining → retired``, plus terminal
  ``failed`` (a load that raised: the model STAYS registered so
  ``/readyz`` reports the failure per-model instead of pretending the
  name never existed);
* **LRU paging** for a small model zoo / LoRA-style adapters:
  ``capacity`` bounds resident loaded models; admitting one more evicts
  the least-recently-used idle model through its drain path first
  (``model.stop()`` — the engine's slot drain, so eviction never drops
  in-flight work);
* **tenancy**: an adapter admitted for a tenant counts against that
  tenant's ``tenant_model_quota`` — one tenant cannot page the whole
  zoo in and evict everyone else's models.

The cache is the server-side anchor for live weight hot-swaps too:
``swap(name, path)`` delegates to the model's ``swap_weights`` (the
engine-level drain/transplant rollout in ``serve/continuous.py``) and
keeps the registry's lifecycle/metrics honest around it.
"""

from __future__ import annotations

import dataclasses
import logging
import threading
import time
from typing import Iterable, Optional

from kubernetes_cloud_tpu import obs
from kubernetes_cloud_tpu.serve.errors import (
    ModelCacheFullError,
    TenantQuotaError,
)
from kubernetes_cloud_tpu.serve.model import Model

log = logging.getLogger(__name__)

#: lifecycle vocabulary (also the ``kct_weights_cache_models`` label set)
STATES = ("loading", "active", "draining", "retired", "failed")

_M_CACHE = obs.gauge(
    "kct_weights_cache_models",
    "Models in the lifecycle cache per state (loading | active | "
    "draining | retired | failed).", ("state",))


@dataclasses.dataclass
class ModelEntry:
    """Lifecycle metadata riding alongside the registry's Model."""

    model: Model
    state: str = "loading"
    tenant: Optional[str] = None
    error: Optional[str] = None
    loaded_at: float = 0.0
    last_used: float = 0.0
    inflight: int = 0

    def snapshot(self) -> dict:
        out = {"state": self.state}
        if self.tenant is not None:
            out["tenant"] = self.tenant
        if self.error is not None:
            out["error"] = self.error
        version = getattr(self.model, "weights_version", None)
        if version is not None:
            out["weights_version"] = version
        return out


class ModelCache(dict):
    """``{name: Model}`` with lifecycle states, LRU paging, and tenant
    quotas.  The dict holds every non-retired model (including
    ``failed`` ones, so readiness stays honest); ``entries`` carries
    the metadata, including retired history."""

    def __init__(self, models: Iterable[Model] = (), *,
                 capacity: int = 0, tenant_model_quota: int = 0):
        super().__init__()
        #: max resident (loading|active) models; 0 = unbounded
        self.capacity = capacity
        #: max non-retired models one tenant may hold; 0 = unbounded
        self.tenant_model_quota = tenant_model_quota
        self.entries: dict[str, ModelEntry] = {}
        self._lock = threading.RLock()
        for m in models:
            self.admit(m)

    # -- admission / paging ------------------------------------------------

    def admit(self, model: Model, *, tenant: Optional[str] = None) -> Model:
        """Register a model in ``loading`` state.  Enforces the tenant
        quota, then makes room: over ``capacity`` the least-recently-
        used idle model is evicted (drain path) first.  Raises
        :class:`TenantQuotaError` / :class:`ModelCacheFullError` —
        both retryable-503s, the request was fine."""
        with self._lock:
            if model.name in self and self.entries[
                    model.name].state != "retired":
                raise ValueError(f"model {model.name!r} already "
                                 f"registered")
            if tenant is not None and self.tenant_model_quota:
                held = sum(1 for e in self.entries.values()
                           if e.tenant == tenant
                           and e.state not in ("retired",))
                if held >= self.tenant_model_quota:
                    raise TenantQuotaError(
                        f"tenant {tenant!r} already holds {held} "
                        f"model(s) (quota {self.tenant_model_quota})")
            self._make_room()
            entry = ModelEntry(model=model, tenant=tenant,
                               state="loading" if not model.ready
                               else "active")
            if model.ready:
                entry.loaded_at = entry.last_used = time.monotonic()
            self.entries[model.name] = entry
            self[model.name] = model
            self._export()
            return model

    def _resident(self) -> int:
        return sum(1 for e in self.entries.values()
                   if e.state in ("loading", "active"))

    def _make_room(self) -> None:
        """Evict LRU idle models until under capacity (lock held)."""
        if not self.capacity:
            return
        while self._resident() >= self.capacity:
            victims = sorted(
                (e for e in self.entries.values()
                 if e.state == "active" and e.inflight == 0),
                key=lambda e: e.last_used)
            if not victims:
                raise ModelCacheFullError(
                    f"model cache at capacity ({self.capacity}) and "
                    f"every resident model is busy — retry after a "
                    f"request completes")
            self.evict(victims[0].model.name)

    def evict(self, name: str, *, drain_timeout_s: float = 10.0) -> None:
        """Page a model out: ``active → draining`` (the model's own
        ``stop()`` drains engine slots — in-flight work completes) →
        ``retired``, removed from the registry.  Terminal ``failed``
        entries retire without a drain."""
        with self._lock:
            entry = self.entries.get(name)
            if entry is None or entry.state == "retired":
                return
            prior, entry.state = entry.state, "draining"
            self._export()
        try:
            if prior == "active":
                deadline = time.monotonic() + drain_timeout_s
                while entry.inflight > 0 and time.monotonic() < deadline:
                    time.sleep(0.01)
                stop = getattr(entry.model, "stop", None)
                if callable(stop):
                    stop()
        except Exception:  # noqa: BLE001 - eviction is best-effort drain
            log.exception("draining %s during eviction failed", name)
        finally:
            with self._lock:
                entry.state = "retired"
                entry.model.ready = False
                self.pop(name, None)
                self._export()
        log.info("model %s retired from cache (%s)", name, prior)

    # -- loading -----------------------------------------------------------

    def load(self, name: str) -> None:
        """Run the model's ``load()``: ``loading → active``; an
        exception lands the entry in terminal ``failed`` (the model
        stays registered and unready — ``/readyz`` reports it) and
        re-raises for callers loading a single model."""
        entry = self.entries[name]
        try:
            entry.model.load()
        except Exception as e:  # noqa: BLE001 - recorded as the entry's
            # terminal failed state (and re-raised below)
            with self._lock:
                entry.state = "failed"
                entry.error = f"{type(e).__name__}: {e}"
                entry.model.ready = False
                self._export()
            raise
        with self._lock:
            entry.state = "active"
            entry.error = None
            entry.loaded_at = entry.last_used = time.monotonic()
            self._export()

    def load_all(self) -> dict[str, str]:
        """Load every unready model, continuing past failures.  Returns
        ``{name: error}`` for the models that landed in ``failed``."""
        failed: dict[str, str] = {}
        for name in list(self):
            entry = self.entries[name]
            if entry.model.ready or entry.state == "failed":
                continue
            try:
                self.load(name)
            except Exception as e:  # noqa: BLE001 - recorded per model
                log.exception("loading model %s failed", name)
                failed[name] = f"{type(e).__name__}: {e}"
        return failed

    # -- dispatch bookkeeping ----------------------------------------------

    def using(self, name: str) -> "_Using":
        """Context manager the server wraps dispatch in: counts the
        model's in-flight work (eviction waits on it) and touches the
        LRU clock."""
        return _Using(self, name)

    def touch(self, name: str) -> None:
        entry = self.entries.get(name)
        if entry is not None:
            entry.last_used = time.monotonic()

    # -- introspection -----------------------------------------------------

    def entry(self, name: str) -> Optional[ModelEntry]:
        return self.entries.get(name)

    def states(self) -> dict[str, str]:
        return {name: e.state for name, e in self.entries.items()}

    def _export(self) -> None:
        counts = dict.fromkeys(STATES, 0)
        for e in self.entries.values():
            counts[e.state] += 1
        for state, n in counts.items():
            _M_CACHE.labels(state=state).set(n)


class _Using:
    def __init__(self, cache: ModelCache, name: str):
        self._cache, self._name = cache, name
        self._entry = cache.entries.get(name)

    def __enter__(self) -> "_Using":
        if self._entry is not None:
            with self._cache._lock:
                self._entry.inflight += 1
                self._entry.last_used = time.monotonic()
        return self

    def __exit__(self, *exc) -> None:
        if self._entry is not None:
            with self._cache._lock:
                self._entry.inflight -= 1
                self._entry.last_used = time.monotonic()
