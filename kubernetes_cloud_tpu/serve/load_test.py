"""Serving load-test harness: throughput / goodput / latency.

Port of the reference's benchmark
(``online-inference/tensorizer-isvc/benchmark/load_test.py:38-100`` async
aiohttp driver, ``:131-176`` stats: requests/sec, goodput = successful
fraction, mean±stddev latency) with the same two modes:

* ``async`` — ``concurrency`` requests in flight at once via a thread
  pool (same concurrency semantics and stats as the aiohttp original,
  no third-party dependency);
* ``sync``  — one request at a time (the reference's ``requests`` loop).

CLI::

    python -m kubernetes_cloud_tpu.serve.load_test \
        --url http://host/v1/models/m:predict --requests 100 \
        --concurrency 8 --payload '{"instances": [..]}' \
        [--inputs prompts.txt]

``--inputs`` cycles prompt lines into ``{"instances": [line]}`` payloads
(the reference's ``benchmark/inputs.txt`` corpus).
"""

from __future__ import annotations

import argparse
import itertools
import json
import statistics
import time
import urllib.request
from concurrent.futures import ThreadPoolExecutor
from dataclasses import dataclass, field


@dataclass
class Result:
    latency: float
    status: int
    error: str = ""
    #: generated tokens reported by the response (LM endpoints attach
    #: ``tokens_out`` per prediction); 0 for non-LM payloads
    tokens_out: int = 0

    @property
    def ok(self) -> bool:
        return self.status == 200 and not self.error


@dataclass
class Summary:
    total_time: float
    results: list[Result] = field(repr=False, default_factory=list)

    @property
    def n(self) -> int:
        return len(self.results)

    @property
    def n_ok(self) -> int:
        return sum(r.ok for r in self.results)

    def stats(self) -> dict:
        lat = sorted(r.latency for r in self.results if r.ok)
        toks = sum(r.tokens_out for r in self.results if r.ok)

        def pct(p: float):
            if not lat:
                return None
            return round(lat[min(len(lat) - 1, int(p * len(lat)))], 4)

        return {
            "requests": self.n,
            "successful": self.n_ok,
            "total_time_s": round(self.total_time, 4),
            # reference names: throughput = all completed / time,
            # goodput = successful / time (load_test.py:158-176)
            "throughput_rps": round(self.n / self.total_time, 4),
            "goodput_rps": round(self.n_ok / self.total_time, 4),
            "latency_mean_s": round(statistics.mean(lat), 4) if lat else None,
            "latency_stddev_s": round(statistics.stdev(lat), 4)
            if len(lat) > 1 else None,
            "latency_min_s": round(min(lat), 4) if lat else None,
            "latency_max_s": round(max(lat), 4) if lat else None,
            "latency_p50_s": pct(0.50),
            "latency_p90_s": pct(0.90),
            "latency_p95_s": pct(0.95),
            "latency_p99_s": pct(0.99),
            # end-to-end generation throughput (not just request rate):
            # only meaningful for LM endpoints that report tokens_out
            "tokens_out_total": toks,
            "tokens_out_per_sec": round(toks / self.total_time, 4),
        }


def _count_tokens_out(body: bytes) -> int:
    """Sum ``tokens_out`` fields from a V1 response body (LM endpoints
    attach one per prediction); 0 for any other response shape."""
    try:
        obj = json.loads(body)
        return sum(int(p.get("tokens_out", 0))
                   for p in obj.get("predictions", [])
                   if isinstance(p, dict))
    except (ValueError, TypeError, AttributeError):
        return 0


def _one_request(url: str, payload: bytes, timeout: float) -> Result:
    t0 = time.monotonic()
    try:
        req = urllib.request.Request(
            url, data=payload, headers={"Content-Type": "application/json"})
        with urllib.request.urlopen(req, timeout=timeout) as resp:
            body = resp.read()
            return Result(time.monotonic() - t0, resp.status,
                          tokens_out=_count_tokens_out(body))
    except Exception as e:  # noqa: BLE001 - goodput counts all failures
        return Result(time.monotonic() - t0, 0, str(e))


def run_sync(url: str, payloads: list[bytes], *,
             timeout: float = 300.0) -> Summary:
    t0 = time.monotonic()
    results = [_one_request(url, p, timeout) for p in payloads]
    return Summary(time.monotonic() - t0, results)


def run_concurrent(url: str, payloads: list[bytes], *, concurrency: int = 8,
                   timeout: float = 300.0) -> Summary:
    """The async mode: ``concurrency`` in-flight requests until the payload
    list drains (thread pool; stats match the aiohttp original)."""
    t0 = time.monotonic()
    with ThreadPoolExecutor(max_workers=concurrency) as pool:
        results = list(pool.map(
            lambda p: _one_request(url, p, timeout), payloads))
    return Summary(time.monotonic() - t0, results)


def run_ramp(url: str, payload_pool: list[bytes], *,
             stages: list[int], stage_duration: float,
             timeout: float = 300.0) -> dict:
    """Locust-style ramping profile (reference
    ``tensorizer-isvc/benchmark/locustfile.py``): each stage holds a
    concurrency level for ``stage_duration`` seconds — workers loop
    firing requests until the stage deadline — and reports per-stage
    throughput/goodput + latency percentiles, so saturation shows up as
    the knee where p90 climbs while goodput flattens."""
    cycle = itertools.cycle(payload_pool)
    out = []
    for conc in stages:
        deadline = time.monotonic() + stage_duration
        results: list[Result] = []

        def worker():
            got = []
            while time.monotonic() < deadline:
                got.append(_one_request(url, next(cycle), timeout))
            return got

        t0 = time.monotonic()
        with ThreadPoolExecutor(max_workers=conc) as pool:
            for batch in pool.map(lambda _: worker(), range(conc)):
                results.extend(batch)
        summary = Summary(time.monotonic() - t0, results)
        out.append({"concurrency": conc, **summary.stats()})
    return {"stages": out}


def build_payloads(args) -> list[bytes]:
    if args.inputs:
        with open(args.inputs) as f:
            lines = [ln.strip() for ln in f if ln.strip()]
        cycle = itertools.cycle(lines)
        return [json.dumps({"instances": [next(cycle)]}).encode()
                for _ in range(args.requests)]
    return [args.payload.encode()] * args.requests


def main(argv=None) -> dict:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--url", required=True)
    ap.add_argument("--requests", type=int, default=100)
    ap.add_argument("--concurrency", type=int, default=8)
    ap.add_argument("--mode", choices=("async", "sync", "ramp"),
                    default="async")
    ap.add_argument("--payload", default='{"instances": ["hello"]}')
    ap.add_argument("--inputs", default=None,
                    help="file of prompt lines cycled into payloads")
    ap.add_argument("--timeout", type=float, default=300.0)
    ap.add_argument("--ramp-stages", default="1,2,4,8",
                    help="comma-separated concurrency levels (ramp mode)")
    ap.add_argument("--stage-duration", type=float, default=15.0,
                    help="seconds per ramp stage")
    args = ap.parse_args(argv)

    payloads = build_payloads(args)
    if args.mode == "ramp":
        stats = run_ramp(
            args.url, payloads,
            stages=[int(s) for s in args.ramp_stages.split(",") if s],
            stage_duration=args.stage_duration, timeout=args.timeout)
    elif args.mode == "sync":
        stats = run_sync(args.url, payloads, timeout=args.timeout).stats()
    else:
        stats = run_concurrent(args.url, payloads,
                               concurrency=args.concurrency,
                               timeout=args.timeout).stats()
    print(json.dumps(stats))
    return stats


if __name__ == "__main__":
    main()
