"""Serving load-test harness: throughput / goodput / latency.

Port of the reference's benchmark
(``online-inference/tensorizer-isvc/benchmark/load_test.py:38-100`` async
aiohttp driver, ``:131-176`` stats: requests/sec, goodput = successful
fraction, mean±stddev latency) with the same two modes:

* ``async`` — ``concurrency`` requests in flight at once via a thread
  pool (same concurrency semantics and stats as the aiohttp original,
  no third-party dependency);
* ``sync``  — one request at a time (the reference's ``requests`` loop).

CLI::

    python -m kubernetes_cloud_tpu.serve.load_test \
        --url http://host/v1/models/m:predict --requests 100 \
        --concurrency 8 --payload '{"instances": [..]}' \
        [--inputs prompts.txt] [--deadline-ms 2000]

``--inputs`` cycles prompt lines into ``{"instances": [line]}`` payloads
(the reference's ``benchmark/inputs.txt`` corpus).  ``--deadline-ms``
attaches an ``X-Request-Deadline-Ms`` budget to every request, so the
server's shedding behaviour (503 backpressure vs 504 deadline misses)
becomes measurable: every run reports an ``outcomes`` breakdown
(``2xx`` / ``503_shed`` / ``504_deadline`` / ``client_timeout`` /
``4xx`` / ``5xx`` / ``error``).

``--shared-prefix N`` prepends one deterministic N-token prefix to
every prompt (the system-prompt traffic shape), and the summary's
``prompt_tokens_total`` / ``cached_prompt_tokens_total`` /
``prefill_tokens_computed_total`` fields account what the paged
engine's prefix cache absorbed vs what prefill actually computed.

``--trace trace.jsonl`` switches to OPEN-LOOP trace replay
(:mod:`kubernetes_cloud_tpu.serve.trace`): requests fire at their
recorded arrival times regardless of outstanding work — the tenant-mix
workload shape per-tenant SLO claims must be measured under — and the
report becomes per-tenant p50/p95 TTFT + tokens/s plus a Jain fairness
index.  ``--gen-trace poisson|bursty|diurnal`` synthesizes such a trace
(Zipf-skewed tenants, mixed lengths, deterministic ``--trace-seed``);
``--trace-out`` saves it as JSONL instead of replaying.

LM endpoints that attach per-prediction ``ttft_s`` (the continuous-
batching engine) additionally get a client-observed TTFT distribution
(``ttft_mean_s`` / ``ttft_p50_s`` / ``ttft_p95_s``).  ``--check-metrics``
scrapes the server's ``GET /metrics`` before and after the run and
asserts the ``kct_server_request_seconds`` histogram's count delta for
the driven route equals the number of requests this client sent — the
client-vs-server bookkeeping cross-check (exit code 2 on disagreement).

``--check-trace`` mints a distinct ``Traceparent`` per request (the
client roots every distributed trace — :mod:`kubernetes_cloud_tpu.obs.
dtrace`) and asserts every 2xx response echoes exactly the trace_id it
was sent (exit code 2 otherwise) — the propagation cross-check.  Any
run whose responses carry trace ids also reports the trace_ids of the
5 worst-TTFT requests (``worst_ttft``), so the p99 straggler's full
waterfall is one ``GET /debug/trace/<id>`` away.
"""

from __future__ import annotations

import argparse
import itertools
import json
import statistics
import time
import urllib.error
import urllib.request
from concurrent.futures import ThreadPoolExecutor
from dataclasses import dataclass, field
from typing import Mapping, Optional


@dataclass
class Result:
    latency: float
    status: int
    error: str = ""
    #: generated tokens reported by the response (LM endpoints attach
    #: ``tokens_out`` per prediction); 0 for non-LM payloads
    tokens_out: int = 0
    #: time to first streamed token reported by the response (the
    #: continuous-batching engine attaches ``ttft_s`` per prediction);
    #: None when the endpoint doesn't report it
    ttft: Optional[float] = None
    #: TTFT decomposition (engine-attached ``ttft_queue_s`` /
    #: ``ttft_prefill_s``): time queued before the scheduler claimed
    #: the request vs prefill compute until the first token
    ttft_queue: Optional[float] = None
    ttft_prefill: Optional[float] = None
    #: prompt tokens submitted / served from the server's prefix cache
    #: (paged engine attaches both per prediction); 0 otherwise
    prompt_tokens: int = 0
    cached_tokens: int = 0
    #: fleet-router accounting (the response's ``fleet`` annotation,
    #: serve/fleet.py): how many replica dispatches this request cost
    #: (1 = clean), whether it succeeded only via retry, whether a
    #: hedge answered first, and whether an unhealthy replica was
    #: routed around — what makes retry amplification reportable
    #: honestly instead of hiding inside a green 2xx count
    fleet_dispatches: int = 0
    retried_ok: bool = False
    hedge_win: bool = False
    rerouted: bool = False
    #: distributed-trace correlation: the trace_id the response body
    #: carries (servers echo it on every 2xx), and — when this client
    #: minted the request's Traceparent — the trace_id it sent, so
    #: --check-trace can assert the propagation round-trips
    trace_id: str = ""
    sent_trace_id: str = ""

    @property
    def ok(self) -> bool:
        return self.status == 200 and not self.error

    @property
    def outcome(self) -> str:
        """Per-run shedding breakdown bucket: distinguishes retryable
        backpressure (503), shed deadline misses (504), and the client
        giving up on a stalled stream (socket timeout)."""
        if self.ok:
            return "2xx"
        if self.status == 503:
            return "503_shed"
        if self.status == 504:
            return "504_deadline"
        if self.status == 0 and "timed out" in self.error:
            return "client_timeout"
        if 400 <= self.status < 500:
            return "4xx"
        if self.status >= 500:
            return "5xx"
        return "error"


@dataclass
class Summary:
    total_time: float
    results: list[Result] = field(repr=False, default_factory=list)

    @property
    def n(self) -> int:
        return len(self.results)

    @property
    def n_ok(self) -> int:
        return sum(r.ok for r in self.results)

    def stats(self) -> dict:
        lat = sorted(r.latency for r in self.results if r.ok)
        toks = sum(r.tokens_out for r in self.results if r.ok)
        prompt = sum(r.prompt_tokens for r in self.results if r.ok)
        cached = sum(r.cached_tokens for r in self.results if r.ok)
        ttfts = sorted(r.ttft for r in self.results
                       if r.ok and r.ttft is not None)
        queues = sorted(r.ttft_queue for r in self.results
                        if r.ok and r.ttft_queue is not None)
        prefills = sorted(r.ttft_prefill for r in self.results
                          if r.ok and r.ttft_prefill is not None)
        outcomes: dict[str, int] = {}
        for r in self.results:
            outcomes[r.outcome] = outcomes.get(r.outcome, 0) + 1

        def pct(p: float, values=lat):
            if not values:
                return None
            return round(values[min(len(values) - 1,
                                    int(p * len(values)))], 4)

        return {
            "requests": self.n,
            "successful": self.n_ok,
            "total_time_s": round(self.total_time, 4),
            # reference names: throughput = all completed / time,
            # goodput = successful / time (load_test.py:158-176)
            "throughput_rps": round(self.n / self.total_time, 4),
            "goodput_rps": round(self.n_ok / self.total_time, 4),
            "latency_mean_s": round(statistics.mean(lat), 4) if lat else None,
            "latency_stddev_s": round(statistics.stdev(lat), 4)
            if len(lat) > 1 else None,
            "latency_min_s": round(min(lat), 4) if lat else None,
            "latency_max_s": round(max(lat), 4) if lat else None,
            "latency_p50_s": pct(0.50),
            "latency_p90_s": pct(0.90),
            "latency_p95_s": pct(0.95),
            "latency_p99_s": pct(0.99),
            # end-to-end generation throughput (not just request rate):
            # only meaningful for LM endpoints that report tokens_out
            "tokens_out_total": toks,
            "tokens_out_per_sec": round(toks / self.total_time, 4),
            # time-to-first-token as the CLIENT sees it (the serving
            # metric autoscaling and interactivity SLOs are set on);
            # None for endpoints that don't report ttft_s
            "ttft_mean_s": round(statistics.mean(ttfts), 4)
            if ttfts else None,
            "ttft_p50_s": pct(0.50, ttfts),
            "ttft_p95_s": pct(0.95, ttfts),
            # TTFT decomposition (engine-attached): time queued before
            # the scheduler claimed the request vs prefill compute —
            # the split that says whether slow first tokens need more
            # replicas (queue-bound) or chunked prefill (compute-bound)
            "ttft_queue_mean_s": round(statistics.mean(queues), 4)
            if queues else None,
            "ttft_queue_p95_s": pct(0.95, queues),
            "ttft_prefill_mean_s": round(statistics.mean(prefills), 4)
            if prefills else None,
            "ttft_prefill_p95_s": pct(0.95, prefills),
            # prefill accounting (paged engine attaches prompt_tokens /
            # cached_tokens per prediction): what prefill actually cost
            # vs what the prefix cache absorbed
            "prompt_tokens_total": prompt,
            "cached_prompt_tokens_total": cached,
            "prefill_tokens_computed_total": prompt - cached,
            # shedding visibility: how every request ended
            "outcomes": outcomes,
            **self._fleet_stats(),
            **self._worst_ttft(),
        }

    def _worst_ttft(self, keep: int = 5) -> dict:
        """Exemplar trace_ids of the worst-TTFT requests: the p99
        straggler's distributed-trace waterfall is then one ``GET
        /debug/trace/<id>`` (or ``perf_report --trace <id>``) away
        instead of a needle in the aggregate histogram."""
        tagged = sorted(
            ((r.ttft, r.trace_id) for r in self.results
             if r.ok and r.ttft is not None and r.trace_id),
            reverse=True)[:keep]
        if not tagged:
            return {}
        return {"worst_ttft": [
            {"ttft_s": round(t, 4), "trace_id": tid}
            for t, tid in tagged]}

    def _fleet_stats(self) -> dict:
        """Fleet-router accounting when the target annotates responses
        (serve/fleet.py): per-request outcome counts plus the retry
        amplification — replica dispatches per client request, the
        honest cost of the green 2xx column."""
        dispatches = sum(r.fleet_dispatches for r in self.results)
        if not dispatches:
            return {}
        return {"fleet": {
            "retried_ok": sum(r.retried_ok for r in self.results),
            "hedge_win": sum(r.hedge_win for r in self.results),
            "rerouted": sum(r.rerouted for r in self.results),
            "dispatches_total": dispatches,
            "retry_amplification": round(dispatches / max(self.n, 1), 4),
        }}


def _parse_fleet(obj) -> dict:
    """Extract the fleet-router annotation (serve/fleet.py) a routed
    response — success or failure body — carries."""
    fleet = obj.get("fleet") if isinstance(obj, dict) else None
    if not isinstance(fleet, dict):
        return {}
    return {
        "fleet_dispatches": int(fleet.get("dispatches") or 0),
        "retried_ok": bool(fleet.get("retried_ok")),
        "hedge_win": bool(fleet.get("hedge_win")),
        "rerouted": bool(fleet.get("rerouted")),
    }


def _parse_response(body: bytes) -> dict:
    """Extract the LM accounting fields a V1 response attaches per
    prediction (token counts summed, first TTFT + its queue/prefill
    decomposition) plus the fleet annotation; zeros/None otherwise."""
    try:
        obj = json.loads(body)
        preds = [p for p in obj.get("predictions", [])
                 if isinstance(p, dict)]

        def first(key):
            return next((float(p[key]) for p in preds
                         if p.get(key) is not None), None)

        return {
            "tokens_out": sum(int(p.get("tokens_out", 0)) for p in preds),
            "ttft": first("ttft_s"),
            "ttft_queue": first("ttft_queue_s"),
            "ttft_prefill": first("ttft_prefill_s"),
            "prompt_tokens": sum(int(p.get("prompt_tokens", 0))
                                 for p in preds),
            "cached_tokens": sum(int(p.get("cached_tokens", 0))
                                 for p in preds),
            "trace_id": str(obj.get("trace_id") or ""),
            **_parse_fleet(obj),
        }
    except (ValueError, TypeError, AttributeError):
        return {}


def _one_request(url: str, payload: bytes, timeout: float,
                 headers: Optional[Mapping[str, str]] = None,
                 mint_trace: bool = False) -> Result:
    t0 = time.monotonic()
    hdrs = {"Content-Type": "application/json", **(headers or {})}
    sent_trace = ""
    if mint_trace:
        # the client roots the distributed trace: a DISTINCT id per
        # request, carried on the wire header both front-ends honor
        from kubernetes_cloud_tpu.obs import dtrace

        ctx = dtrace.mint()
        hdrs[dtrace.TRACEPARENT_HEADER] = ctx.wire()
        sent_trace = ctx.trace_id
    try:
        req = urllib.request.Request(url, data=payload, headers=hdrs)
        with urllib.request.urlopen(req, timeout=timeout) as resp:
            body = resp.read()
            return Result(time.monotonic() - t0, resp.status,
                          sent_trace_id=sent_trace,
                          **_parse_response(body))
    except urllib.error.HTTPError as e:
        # keep the real status — the outcome breakdown needs to tell a
        # 503 shed from a 504 deadline miss from a genuine 500 — and
        # read the body: a fleet router annotates FAILURES with their
        # dispatch cost too (a 503 that burned 4 replica attempts must
        # count toward retry amplification)
        fleet = {}
        try:
            fleet = _parse_fleet(json.loads(e.read() or b"{}"))
        except (ValueError, TypeError, AttributeError):
            pass
        return Result(time.monotonic() - t0, e.code,
                      e.reason or f"HTTP {e.code}",
                      sent_trace_id=sent_trace, **fleet)
    except Exception as e:  # noqa: BLE001 - goodput counts all failures
        return Result(time.monotonic() - t0, 0, str(e),
                      sent_trace_id=sent_trace)


def _norm_urls(url) -> list[str]:
    """Single-target str, or a list of targets round-robined — the
    client-side load balancing a naive multi-pod deployment gets (and
    the baseline arm the fleet router is benchmarked against)."""
    urls = [url] if isinstance(url, str) else list(url)
    if not urls:
        raise ValueError("need at least one target url")
    return urls


def run_sync(url, payloads: list[bytes], *, timeout: float = 300.0,
             headers: Optional[Mapping[str, str]] = None,
             mint_trace: bool = False) -> Summary:
    urls = _norm_urls(url)
    t0 = time.monotonic()
    results = [_one_request(urls[i % len(urls)], p, timeout, headers,
                            mint_trace)
               for i, p in enumerate(payloads)]
    return Summary(time.monotonic() - t0, results)


def run_concurrent(url, payloads: list[bytes], *, concurrency: int = 8,
                   timeout: float = 300.0,
                   headers: Optional[Mapping[str, str]] = None,
                   mint_trace: bool = False) -> Summary:
    """The async mode: ``concurrency`` in-flight requests until the payload
    list drains (thread pool; stats match the aiohttp original)."""
    urls = _norm_urls(url)
    t0 = time.monotonic()
    with ThreadPoolExecutor(max_workers=concurrency) as pool:
        results = list(pool.map(
            lambda up: _one_request(up[0], up[1], timeout, headers,
                                    mint_trace),
            [(urls[i % len(urls)], p) for i, p in enumerate(payloads)]))
    return Summary(time.monotonic() - t0, results)


def run_ramp(url, payload_pool: list[bytes], *,
             stages: list[int], stage_duration: float,
             timeout: float = 300.0,
             headers: Optional[Mapping[str, str]] = None,
             mint_trace: bool = False) -> dict:
    """Locust-style ramping profile (reference
    ``tensorizer-isvc/benchmark/locustfile.py``): each stage holds a
    concurrency level for ``stage_duration`` seconds — workers loop
    firing requests until the stage deadline — and reports per-stage
    throughput/goodput + latency percentiles, so saturation shows up as
    the knee where p90 climbs while goodput flattens."""
    cycle = itertools.cycle(payload_pool)
    targets = itertools.cycle(_norm_urls(url))
    out = []
    for conc in stages:
        deadline = time.monotonic() + stage_duration
        results: list[Result] = []

        def worker():
            got = []
            while time.monotonic() < deadline:
                got.append(_one_request(next(targets), next(cycle),
                                        timeout, headers, mint_trace))
            return got

        t0 = time.monotonic()
        with ThreadPoolExecutor(max_workers=conc) as pool:
            for batch in pool.map(lambda _: worker(), range(conc)):
                results.extend(batch)
        summary = Summary(time.monotonic() - t0, results)
        out.append({"concurrency": conc, **summary.stats()})
    return {"stages": out}


def scrape_metrics(metrics_url: str, timeout: float = 10.0) -> list:
    """GET /metrics and strictly parse the exposition (raises on a
    malformed or unreachable scrape)."""
    from kubernetes_cloud_tpu import obs

    with urllib.request.urlopen(metrics_url, timeout=timeout) as resp:
        return obs.parse_text(resp.read().decode())


def metrics_endpoint(target_url: str) -> str:
    """Derive ``scheme://host:port/metrics`` from the driven URL."""
    import urllib.parse

    parts = urllib.parse.urlsplit(target_url)
    return urllib.parse.urlunsplit(
        (parts.scheme, parts.netloc, "/metrics", "", ""))


def timeline_endpoint(target_url: str, last: int = 4096) -> str:
    """Derive the ``/debug/timeline`` URL from the driven URL."""
    import urllib.parse

    parts = urllib.parse.urlsplit(target_url)
    return urllib.parse.urlunsplit(
        (parts.scheme, parts.netloc, "/debug/timeline",
         f"last={last}", ""))


def snapshot_timeline(target_url: str, last: int = 4096,
                      timeout: float = 10.0) -> dict:
    """Fetch the server's flight-recorder dump and reduce each model's
    timeline to the phase-share + MFU summary
    (:func:`kubernetes_cloud_tpu.obs.report.summarize`) — the
    ``--timeline`` embedding for benchmark JSON records."""
    from kubernetes_cloud_tpu.obs import report

    with urllib.request.urlopen(timeline_endpoint(target_url, last),
                                timeout=timeout) as resp:
        dump = json.loads(resp.read())
    return {name: report.summarize(entry)
            for name, entry in dump.get("models", {}).items()}


def check_metrics(before: list, after: list, target_url,
                  client_count: int,
                  client_responded: Optional[int] = None) -> dict:
    """Client-vs-server bookkeeping cross-check: every request that got
    an HTTP response was definitely counted by the server's per-route
    histogram, so its count delta must cover at least those; requests
    the client gave up on (timeout / transport error) may still be
    mid-``handle()`` at the after-scrape — or may never have reached
    the server at all — so the delta may exceed ``client_responded``
    but never the total attempted.  ``client_responded=None`` demands
    exact equality (every request answered — the common case).

    Multi-target runs pass lists of scrapes (one pair per ``--url``);
    the server counts are summed — the fleet invariant is that the
    TARGETS together saw exactly what the client sent."""
    from kubernetes_cloud_tpu import obs
    from kubernetes_cloud_tpu.serve.server import route_label

    import urllib.parse

    urls = _norm_urls(target_url)
    befores = before if isinstance(before[0], list) else [before]
    afters = after if isinstance(after[0], list) else [after]
    # the server's own vocabulary — one source of truth for the label
    route = route_label(urllib.parse.urlsplit(urls[0]).path)
    name = "kct_server_request_seconds_count"
    server_n = 0
    for b, a in zip(befores, afters):
        server_n += int(obs.sample_value(a, name, {"route": route})
                        - obs.sample_value(b, name, {"route": route}))
    lo = client_count if client_responded is None else client_responded
    return {"route": route, "client_requests": client_count,
            "client_responded": lo,
            "server_requests": server_n,
            "ok": lo <= server_n <= client_count}


def check_trace(results: list[Result]) -> dict:
    """Propagation cross-check for ``--check-trace`` runs: every 2xx
    response must echo exactly the trace_id this client minted into its
    request's ``Traceparent`` — a missing id means the door dropped the
    header; a different id means some hop re-rooted the trace instead
    of joining it."""
    ok_results = [r for r in results if r.ok]
    missing = sum(1 for r in ok_results if not r.trace_id)
    mismatched = sum(1 for r in ok_results
                     if r.trace_id and r.sent_trace_id
                     and r.trace_id != r.sent_trace_id)
    return {"requests_2xx": len(ok_results),
            "missing_trace_id": missing,
            "mismatched_trace_id": mismatched,
            "ok": missing == 0 and mismatched == 0}


def _with_shared_prefix(payload: bytes, prefix: str) -> bytes:
    """Prepend the shared prefix to every string instance of a V1
    payload (non-instance payloads pass through untouched)."""
    try:
        obj = json.loads(payload)
        inst = obj.get("instances")
        if not isinstance(inst, list):
            return payload
        obj["instances"] = [prefix + i if isinstance(i, str) else i
                            for i in inst]
        return json.dumps(obj).encode()
    except ValueError:
        return payload


def shared_prefix_text(n_tokens: int, seed: int = 0) -> str:
    """Deterministic ``n_tokens``-char prefix (byte tokenizer: one char
    = one token), identical across client processes so every worker
    hits the SAME server-side prefix-cache entry."""
    import random as _random

    rng = _random.Random(seed)
    return "".join(rng.choice("abcdefghij klmnop qrstuv wxyz")
                   for _ in range(n_tokens))


def build_payloads(args) -> list[bytes]:
    if args.inputs:
        with open(args.inputs) as f:
            lines = [ln.strip() for ln in f if ln.strip()]
        cycle = itertools.cycle(lines)
        payloads = [json.dumps({"instances": [next(cycle)]}).encode()
                    for _ in range(args.requests)]
    else:
        payloads = [args.payload.encode()] * args.requests
    if args.shared_prefix:
        prefix = shared_prefix_text(args.shared_prefix)
        payloads = [_with_shared_prefix(p, prefix) for p in payloads]
    return payloads


def main(argv=None) -> dict:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--url", action="append", default=None,
                    help="target endpoint (required unless only "
                         "generating a trace with --trace-out); "
                         "repeatable — multiple targets are round-"
                         "robined client-side (the naive multi-pod "
                         "baseline the fleet router is measured "
                         "against)")
    ap.add_argument("--requests", type=int, default=100)
    ap.add_argument("--concurrency", type=int, default=8)
    ap.add_argument("--mode", choices=("async", "sync", "ramp"),
                    default="async")
    ap.add_argument("--payload", default='{"instances": ["hello"]}')
    ap.add_argument("--inputs", default=None,
                    help="file of prompt lines cycled into payloads")
    ap.add_argument("--timeout", type=float, default=300.0)
    ap.add_argument("--deadline-ms", type=float, default=None,
                    help="attach an X-Request-Deadline-Ms budget to "
                         "every request (server sheds misses with 504)")
    ap.add_argument("--shared-prefix", type=int, default=0,
                    help="prepend ONE deterministic N-token prefix to "
                         "every prompt — the system-prompt traffic "
                         "shape the paged engine's prefix cache "
                         "serves; the summary's prefill-token "
                         "accounting shows what the cache absorbed")
    ap.add_argument("--ramp-stages", default="1,2,4,8",
                    help="comma-separated concurrency levels (ramp mode)")
    ap.add_argument("--stage-duration", type=float, default=15.0,
                    help="seconds per ramp stage")
    ap.add_argument("--trace", default=None, metavar="FILE",
                    help="trace-replay mode: fire a JSONL arrival "
                         "trace (serve/trace.py schema) OPEN-LOOP — "
                         "requests launch at their recorded t, not "
                         "when a worker frees up — and report per-"
                         "tenant p50/p95 TTFT, tokens/s, and a Jain "
                         "fairness index instead of the closed-loop "
                         "summary")
    ap.add_argument("--gen-trace", default=None,
                    choices=("poisson", "bursty", "diurnal"),
                    help="generate a synthetic trace (Zipf-skewed "
                         "tenants, mixed lengths, deterministic seed) "
                         "and either save it (--trace-out) or replay "
                         "it immediately")
    ap.add_argument("--trace-out", default=None, metavar="FILE",
                    help="write the generated trace as JSONL and exit "
                         "(no --url needed)")
    ap.add_argument("--trace-duration", type=float, default=30.0,
                    help="generated trace length in seconds")
    ap.add_argument("--trace-rate", type=float, default=8.0,
                    help="generated trace mean arrival rate (req/s)")
    ap.add_argument("--trace-tenants", type=int, default=4,
                    help="generated trace tenant count (Zipf mix)")
    ap.add_argument("--trace-zipf", type=float, default=1.1,
                    help="Zipf skew exponent for the tenant mix")
    ap.add_argument("--trace-seed", type=int, default=0,
                    help="trace generator seed (same seed = identical "
                         "trace, byte for byte)")
    ap.add_argument("--trace-speed", type=float, default=1.0,
                    help="replay time compression (2.0 = fire the "
                         "trace twice as fast)")
    ap.add_argument("--trace-workers", type=int, default=128,
                    help="replay worker-pool bound (true open-loop "
                         "needs more workers than peak in-flight)")
    ap.add_argument("--check-metrics", action="store_true",
                    help="scrape GET /metrics before/after and assert "
                         "the server's request histogram count delta "
                         "matches this client's request count (exit 2 "
                         "on disagreement)")
    ap.add_argument("--check-trace", action="store_true",
                    help="mint a distinct Traceparent per request and "
                         "assert every 2xx response echoes exactly the "
                         "trace_id it was sent (exit 2 otherwise) — "
                         "the distributed-trace propagation check")
    ap.add_argument("--timeline", action="store_true",
                    help="snapshot GET /debug/timeline after the run "
                         "and embed each model's phase-share + MFU "
                         "summary (flight-recorder breakdown) in the "
                         "output JSON")
    args = ap.parse_args(argv)
    urls = args.url or []

    headers = None
    if args.deadline_ms is not None:
        headers = {"X-Request-Deadline-Ms": str(args.deadline_ms)}

    if args.trace or args.gen_trace:
        from kubernetes_cloud_tpu.serve import trace as trace_mod

        if args.trace:
            entries = trace_mod.load_trace(args.trace)
        else:
            entries = trace_mod.generate_trace(
                kind=args.gen_trace, duration_s=args.trace_duration,
                rate_rps=args.trace_rate, n_tenants=args.trace_tenants,
                zipf_s=args.trace_zipf, seed=args.trace_seed)
        if args.trace_out:
            trace_mod.save_trace(args.trace_out, entries)
            out = {"trace": args.trace_out, "requests": len(entries)}
            print(json.dumps(out))
            return out
        if len(urls) != 1:
            ap.error("trace replay takes exactly one --url "
                     "(use --trace-out to only generate a trace)")
        stats = trace_mod.replay(
            urls[0], entries, timeout=args.timeout,
            speed=args.trace_speed, headers=headers,
            max_workers=args.trace_workers)
        print(json.dumps(stats))
        return stats

    if not urls:
        ap.error("--url is required")
    if args.check_trace and args.mode == "ramp":
        ap.error("--check-trace needs per-result bookkeeping; "
                 "use --mode async or sync")
    payloads = build_payloads(args)
    before = ([scrape_metrics(metrics_endpoint(u)) for u in urls]
              if args.check_metrics else None)
    if args.mode == "ramp":
        stats = run_ramp(
            urls, payloads,
            stages=[int(s) for s in args.ramp_stages.split(",") if s],
            stage_duration=args.stage_duration, timeout=args.timeout,
            headers=headers)
        client_n = sum(s["requests"] for s in stats["stages"])
        # requests with a real HTTP status (status != 0) definitely
        # reached — and were counted by — the server
        responded = client_n - sum(
            s["outcomes"].get("client_timeout", 0)
            + s["outcomes"].get("error", 0) for s in stats["stages"])
        summary = None
    elif args.mode == "sync":
        summary = run_sync(urls, payloads, timeout=args.timeout,
                           headers=headers,
                           mint_trace=args.check_trace)
        stats, client_n = summary.stats(), summary.n
        responded = sum(1 for r in summary.results if r.status != 0)
    else:
        summary = run_concurrent(urls, payloads,
                                 concurrency=args.concurrency,
                                 timeout=args.timeout,
                                 headers=headers,
                                 mint_trace=args.check_trace)
        stats, client_n = summary.stats(), summary.n
        responded = sum(1 for r in summary.results if r.status != 0)
    if args.check_trace and summary is not None:
        stats["trace_check"] = check_trace(summary.results)
    if args.check_metrics:
        after = [scrape_metrics(metrics_endpoint(u)) for u in urls]
        stats["metrics_check"] = check_metrics(
            before, after, urls, client_n,
            client_responded=responded)
    if args.timeline:
        try:
            if len(urls) == 1:
                stats["timeline"] = snapshot_timeline(urls[0])
            else:
                stats["timeline"] = {u: snapshot_timeline(u)
                                     for u in urls}
        except Exception as e:  # noqa: BLE001 - introspection is
            # best-effort: a pod without the debug plane (old build,
            # recorder disabled) must not fail the load test itself
            stats["timeline"] = {"error": str(e)}
    print(json.dumps(stats))
    if args.check_metrics and not stats["metrics_check"]["ok"]:
        raise SystemExit(2)  # server lost (or double-counted) requests
    if args.check_trace and not stats["trace_check"]["ok"]:
        raise SystemExit(2)  # a 2xx lost or re-rooted its trace_id
    return stats


if __name__ == "__main__":
    main()
