"""Self-healing serving supervisor: watchdog, restarts, circuit breaker.

The serving stack's workers — the continuous-batching engine's scheduler
(:mod:`kubernetes_cloud_tpu.serve.continuous`) and the dynamic batcher's
dispatcher (:mod:`kubernetes_cloud_tpu.serve.batcher`) — are single
threads that own the device.  A wedged device call (driver hang,
deadlocked collective) or a crashed loop strands every in-flight request
and, before this module, required a human (or a Kubernetes liveness
kill) to restart the whole pod, losing the warmed compile cache and the
loaded weights.

The supervisor keeps the pod alive through worker failure instead:

1.  **Heartbeat watchdog.**  Every worker beats a :class:`Heartbeat`
    once per scheduler iteration (including idle polls, so a fresh
    heartbeat always means "the loop is turning").  The watchdog thread
    polls each watched model: a dead worker thread is a *crash*, a
    stale heartbeat on a live thread is a *hang*.
2.  **Restart.**  On failure the old worker is abandoned (in-flight
    requests fail with the retryable
    :class:`~kubernetes_cloud_tpu.serve.errors.EngineRestartedError` →
    HTTP 503), a fresh engine is built over the already-loaded weights
    (fresh slot pool; the jit cache is module-level, so no recompile),
    and requests that were still queued — admitted by nobody — are
    re-admitted to the new engine untouched.
3.  **Crash-loop circuit breaker.**  More than ``max_restarts`` inside
    ``restart_window_s`` opens the circuit: the model is marked
    permanently unready (``/readyz`` 503, Knative routes elsewhere /
    the liveness probe's restart policy takes over), because restarting
    a worker that immediately dies again just burns requests.
4.  **Honest readiness.**  :meth:`ServingSupervisor.health` is what a
    watched model's ``health()`` reports to ``/readyz``: worker alive ∧
    heartbeat fresh ∧ circuit closed ∧ queue below the shed threshold.

``/healthz`` (process liveness) stays unconditionally 200 — the whole
point is that a wedged engine is the *supervisor's* problem, not a
reason to kill a pod holding hundreds of GiB of streamed weights.
"""

from __future__ import annotations

import collections
import contextlib
import dataclasses
import logging
import threading
import time
from typing import Callable, Iterable, Optional

from kubernetes_cloud_tpu import obs
from kubernetes_cloud_tpu.serve.errors import EngineRestartedError

log = logging.getLogger(__name__)

# Supervisor metric families — restart behaviour was previously only
# log lines; these are what an operator alerts on
_M_RESTARTS = obs.counter(
    "kct_supervisor_restarts_total",
    "Worker restarts by cause (hang = stale heartbeat on a live "
    "thread, crash = dead worker thread).", ("model", "cause"))
_M_HEARTBEAT = obs.gauge(
    "kct_supervisor_heartbeat_age_seconds",
    "Watched worker heartbeat age at the last watchdog pass.",
    ("model",))
_M_CIRCUIT = obs.gauge(
    "kct_supervisor_circuit_open",
    "1 while the crash-loop circuit is open (model permanently "
    "unready).", ("model",))
_M_REQUEUED = obs.counter(
    "kct_supervisor_requeued_total",
    "Queued requests transplanted into a replacement engine.",
    ("model",))


class Heartbeat:
    """Monotonic liveness pulse, beaten by worker loops, read by the
    watchdog.  Lock-free: a float store is atomic under the GIL."""

    __slots__ = ("_t",)

    def __init__(self):
        self._t = time.monotonic()

    def beat(self) -> None:
        self._t = time.monotonic()

    @property
    def age(self) -> float:
        return time.monotonic() - self._t


@dataclasses.dataclass(frozen=True)
class SupervisorConfig:
    poll_interval_s: float = 0.5   # watchdog wake cadence
    hang_timeout_s: float = 10.0   # engine stale-heartbeat threshold
    # (must exceed the slowest legitimate scheduler iteration)
    max_restarts: int = 3          # inside restart_window_s, then …
    restart_window_s: float = 60.0  # … the circuit opens
    shed_queue_depth: Optional[int] = None  # readiness threshold;
    # None = 90% of the worker's own queue bound
    #: hang threshold for BatchingModel dispatchers.  None (default)
    #: disables hang detection there — crash detection stays on — since
    #: the batcher's heartbeat unit is a whole run-to-completion batch,
    #: and one legitimate long batch (or a first-request XLA compile)
    #: would read as a hang.  Opt in with a value sized above the
    #: worst-case batch.
    batcher_hang_timeout_s: Optional[float] = None

    def __post_init__(self):
        if self.poll_interval_s <= 0 or self.hang_timeout_s <= 0:
            raise ValueError("intervals must be > 0")
        if self.max_restarts < 1:
            raise ValueError("max_restarts must be >= 1")


class _EngineTarget:
    """Adapter over ``ContinuousBatchingModel`` (duck-typed: anything
    with ``.engine`` carrying heartbeat/abandon/requeue works)."""

    def __init__(self, model):
        self.model = model

    @property
    def name(self) -> str:
        return self.model.name

    def worker_alive(self) -> bool:
        eng = self.model.engine
        return eng is not None and eng.alive

    def deliberately_stopped(self) -> bool:
        # engine=None is NOT deliberate: with the model still ready it
        # means a restart attempt failed (load() raised) — that must
        # read as a crash so the watchdog retries and, failing
        # repeatedly, opens the circuit instead of silently giving up.
        eng = self.model.engine
        return eng is not None and eng._stop.is_set()

    def heartbeat_age(self) -> float:
        eng = self.model.engine
        return eng.heartbeat.age if eng is not None else 0.0

    def queue_depth(self) -> int:
        eng = self.model.engine
        return eng.queue_depth() if eng is not None else 0

    def queue_bound(self) -> int:
        return self.model.cfg.max_queue_size

    def hang_timeout(self, cfg: SupervisorConfig) -> Optional[float]:
        # Floor at a few idle polls: an IDLE engine's heartbeat ages up
        # to idle_wait_s (+ GIL jitter) between beats, so any timeout
        # below that guarantees false hangs on a healthy idle pod.
        eng = self.model.engine
        floor = eng.ecfg.idle_wait_s * 4 if eng is not None else 0.0
        return max(cfg.hang_timeout_s, floor)

    def in_compile_grace(self) -> bool:
        """A first-time prefill shape is compiling (engine raised
        grace_until around the cold dispatch): the silence is XLA, not
        a wedge.  A wedge DURING such a compile is still caught — at
        grace expiry instead of hang_timeout."""
        eng = self.model.engine
        return (eng is not None
                and time.monotonic() < getattr(eng, "grace_until", 0.0))

    def restart(self, err: Exception) -> int:
        # serialize against a live weight hot-swap's pointer cutover
        # (continuous.py swap_weights): whichever side wins the lock,
        # the process converges to exactly ONE live engine — a restart
        # landing mid-swap rebuilds over whatever version the swap
        # left as current, never a torn half of each
        lock = getattr(self.model, "_swap_lock", None)
        with (lock if lock is not None else contextlib.nullcontext()):
            old, self.model.engine = self.model.engine, None
            queued = old.abandon(err) if old is not None else []
            self.model.load()  # weights stay; fresh engine + slot pool
            for req in queued:
                self.model.engine.requeue(req)
        return len(queued)

    def shut_down(self, err: Exception) -> None:
        old, self.model.engine = self.model.engine, None
        if old is not None:
            old.abandon(err)
        self.model.ready = False


class _BatcherTarget:
    """Adapter over ``BatchingModel``: same contract, dispatcher
    restarts happen in place (no device state to rebuild)."""

    def __init__(self, model):
        self.model = model

    @property
    def name(self) -> str:
        return self.model.name

    def worker_alive(self) -> bool:
        t = self.model._thread
        return t is not None and t.is_alive()

    def deliberately_stopped(self) -> bool:
        return self.model._thread is None or self.model._stop.is_set()

    def heartbeat_age(self) -> float:
        return self.model.heartbeat.age

    def queue_depth(self) -> int:
        return self.model._queue.qsize()

    def queue_bound(self) -> int:
        return self.model.cfg.max_queue_size

    def hang_timeout(self, cfg: SupervisorConfig) -> Optional[float]:
        return cfg.batcher_hang_timeout_s

    def in_compile_grace(self) -> bool:
        return False  # batcher hang detection is opt-in/pre-sized

    def restart(self, err: Exception) -> int:
        return self.model.restart_dispatcher(err)

    def shut_down(self, err: Exception) -> None:
        self.model.abandon_dispatcher(err)
        self.model.ready = False


class _Watched:
    __slots__ = ("target", "restarts", "circuit_open", "restarting",
                 "last_failure", "total_restarts")

    def __init__(self, target):
        self.target = target
        self.restarts: "collections.deque[float]" = collections.deque()
        self.circuit_open = False
        #: a restart (engine rebuild — a blocking device call) is in
        #: flight on its own thread; health reports unready meanwhile
        self.restarting = False
        self.last_failure: Optional[str] = None
        #: lifetime restart count (the windowed deque above is the
        #: circuit budget; /readyz reports this one)
        self.total_restarts = 0


class ServingSupervisor:
    """One watchdog thread over any number of serving workers."""

    def __init__(self, cfg: SupervisorConfig = SupervisorConfig()):
        self.cfg = cfg
        self._watched: list[_Watched] = []
        self._by_model: dict[int, _Watched] = {}
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._lock = threading.Lock()  # serializes restart vs health
        self.stats = {"restarts": 0, "hangs": 0, "crashes": 0,
                      "circuit_opens": 0, "requeued": 0}
        #: optional capacity-change hook (serve/autoscaler.py wires
        #: ``Autoscaler.kick`` here): a restart beginning/finishing or
        #: a circuit opening changes this pod's ready capacity, and an
        #: elastic control loop should re-evaluate NOW rather than at
        #: its next tick
        self.on_capacity_change: Optional[Callable[[], None]] = None

    # -- registration ------------------------------------------------------

    def watch(self, model) -> None:
        """Supervise ``model``; picks the adapter by shape and installs
        itself as ``model.supervisor`` (consulted by ``health()``)."""
        if hasattr(model, "engine"):
            target = _EngineTarget(model)
        elif hasattr(model, "heartbeat") and hasattr(model, "_thread"):
            target = _BatcherTarget(model)
        else:
            raise TypeError(
                f"{type(model).__name__} has no supervisable worker "
                "(need .engine or .heartbeat/._thread)")
        w = _Watched(target)
        self._watched.append(w)
        self._by_model[id(model)] = w
        model.supervisor = self

    # -- lifecycle ---------------------------------------------------------

    def start(self) -> None:
        if self._thread is not None and self._thread.is_alive():
            return
        self._stop.clear()
        self._thread = threading.Thread(target=self._loop, daemon=True,
                                        name="serving-supervisor")
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5.0)

    def _loop(self) -> None:
        while not self._stop.wait(self.cfg.poll_interval_s):
            try:
                self.check_now()
            except Exception:  # noqa: BLE001 - the watchdog never dies
                log.exception("supervisor check failed")

    # -- watchdog ----------------------------------------------------------

    def check_now(self) -> None:
        """One watchdog pass (the thread calls this every poll; tests
        may call it synchronously)."""
        for w in self._watched:
            self._check(w)

    def _check(self, w: _Watched) -> None:
        # Diagnosis + budget bookkeeping happen under the lock; the
        # restart itself does NOT — rebuilding an engine is a blocking
        # device call, and on a genuinely wedged device it may never
        # return.  It runs on its own thread so /readyz and the other
        # watched models keep being served/supervised regardless.
        with self._lock:
            t = w.target
            try:  # scrape-facing levels, refreshed every watchdog pass
                _M_HEARTBEAT.labels(model=t.name).set(t.heartbeat_age())
                _M_CIRCUIT.labels(model=t.name).set(
                    1.0 if w.circuit_open else 0.0)
            except Exception:  # noqa: BLE001 - telemetry never blocks
                log.exception("supervisor gauge update failed")
            if (w.circuit_open or w.restarting
                    or not getattr(t.model, "ready", False)):
                return
            if t.deliberately_stopped():
                # stop()/drain in progress (or already finished): a dead
                # thread is completion, a stale heartbeat is the final
                # queue drain — neither is a failure, and "restarting"
                # here would resurrect a worker mid-shutdown.
                return
            reason = cause = None
            if not t.worker_alive():
                self.stats["crashes"] += 1
                reason, cause = "worker thread died", "crash"
            else:
                hang_timeout = t.hang_timeout(self.cfg)
                if hang_timeout is not None and not t.in_compile_grace():
                    age = t.heartbeat_age()
                    if age > hang_timeout:
                        self.stats["hangs"] += 1
                        reason = (f"heartbeat stale for {age:.2f}s "
                                  f"(> {hang_timeout}s)")
                        cause = "hang"
            if reason is None:
                return
            w.last_failure = reason
            now = time.monotonic()
            while (w.restarts
                   and now - w.restarts[0] > self.cfg.restart_window_s):
                w.restarts.popleft()
            err = EngineRestartedError(
                f"{t.name}: engine restarted ({reason}); retry")
            if len(w.restarts) >= self.cfg.max_restarts:
                w.circuit_open = True
                self.stats["circuit_opens"] += 1
                _M_CIRCUIT.labels(model=t.name).set(1.0)
                log.error("%s: circuit OPEN after %d restarts in %.0fs "
                          "(%s); marking permanently unready", t.name,
                          len(w.restarts), self.cfg.restart_window_s,
                          reason)
                t.shut_down(err)  # fails work only; never touches device
                self._notify_capacity_change()
                return
            w.restarts.append(now)
            self.stats["restarts"] += 1
            w.total_restarts += 1
            _M_RESTARTS.labels(model=t.name, cause=cause).inc()
            w.restarting = True
        log.warning("%s: %s; restarting worker (restart %d/%d in window)",
                    t.name, reason, len(w.restarts), self.cfg.max_restarts)
        self._notify_capacity_change()  # pod unready for the rebuild
        threading.Thread(target=self._do_restart, args=(w, err),
                         daemon=True, name=f"restart-{t.name}").start()

    def _notify_capacity_change(self) -> None:
        hook = self.on_capacity_change
        if hook is None:
            return
        try:
            hook()
        except Exception:  # noqa: BLE001 - an elastic control loop's
            # poke must never take the watchdog down with it
            log.exception("on_capacity_change hook failed")

    def _do_restart(self, w: _Watched, err: Exception) -> None:
        try:
            requeued = w.target.restart(err)
            with self._lock:
                self.stats["requeued"] += requeued
            if requeued:
                _M_REQUEUED.labels(model=w.target.name).inc(requeued)
        except Exception:  # noqa: BLE001 - a failed restart = next check
            log.exception("%s: restart failed", w.target.name)
        finally:
            with self._lock:
                w.restarting = False
            self._notify_capacity_change()  # pod routable again

    # -- readiness ---------------------------------------------------------

    def _shed_threshold(self, t) -> int:
        if self.cfg.shed_queue_depth is not None:
            return self.cfg.shed_queue_depth
        return max(1, int(t.queue_bound() * 0.9))

    def health(self, model) -> dict:
        """The model's ``/readyz`` contribution: ok ⇔ worker alive ∧
        heartbeat fresh ∧ circuit closed ∧ queue below shed depth.

        Every verdict — healthy or not — carries the diagnostic state
        (heartbeat age, circuit, restart count, queue depth), so a human
        with curl can tell a wedged engine from a crash-looped one from
        a saturated queue without reading pod logs."""
        w = self._by_model.get(id(model))
        if w is None:
            return {"ok": bool(getattr(model, "ready", False)),
                    "reason": "unwatched"}
        with self._lock:
            t = w.target
            age = t.heartbeat_age()
            depth = t.queue_depth()
            detail = {
                "heartbeat_age_s": round(age, 3),
                "circuit": "open" if w.circuit_open else "closed",
                "restarts": w.total_restarts,
                "queue_depth": depth,
                # model-declared rollout metadata (kv_dtype/attn_impl
                # on the continuous engine) rides along so supervising
                # a model never hides what its unsupervised /readyz
                # would have said about its serving configuration
                **getattr(model, "serving_metadata", dict)(),
            }

            def verdict(ok: bool, reason: str) -> dict:
                return {"ok": ok, "reason": reason, **detail}

            if w.circuit_open:
                return verdict(False, f"circuit open ({w.last_failure})")
            if w.restarting:
                return verdict(False, f"restarting ({w.last_failure})")
            if not model.ready:
                return verdict(False, "not loaded")
            if not t.worker_alive():
                return verdict(False, "worker dead")
            hang_timeout = t.hang_timeout(self.cfg)
            if (hang_timeout is not None and age > hang_timeout
                    and not t.in_compile_grace()):
                return verdict(False, f"heartbeat stale ({age:.2f}s)")
            shed = self._shed_threshold(t)
            if depth >= shed:
                return verdict(False, f"queue depth {depth} >= shed "
                                      f"threshold {shed}")
            return verdict(True, "ok")


def supervise(models: Iterable, cfg: SupervisorConfig = SupervisorConfig()
              ) -> Optional[ServingSupervisor]:
    """Watch every supervisable model in ``models``; returns the started
    supervisor, or None if nothing needed watching (one-shot services
    have no worker thread to wedge)."""
    sup = ServingSupervisor(cfg)
    for m in models:
        try:
            sup.watch(m)
        except TypeError:
            continue
    if not sup._watched:
        return None
    sup.start()
    return sup
