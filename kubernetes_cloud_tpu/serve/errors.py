"""Typed serving errors — the HTTP status vocabulary in one place.

The data-plane contract the supervisor/probe layer relies on
(`deploy/README.md` "Failure modes & recovery"): every failure a client
can act on gets a *typed* exception, and :class:`~kubernetes_cloud_tpu.
serve.server.ModelServer` maps types to statuses, not messages:

* :class:`RetryableError` subtypes → **503**: the request itself was
  fine, the pod transiently was not (queue full, engine restarting,
  stream stalled, pod draining).  Knative/KServe retry these and the
  autoscaler treats them as backpressure.
* :class:`DeadlineExceededError` → **504**: the answer would arrive
  after the caller stopped waiting.  Sheddable work, never retried
  as-is (the retry would carry the same dead deadline).
* ``ValueError`` → 400, anything else → 500 (a real fault).

This module is dependency-free so every serving layer (batcher, engine,
supervisor, server) can import it without cycles.
``QueueFullError`` historically lived in :mod:`kubernetes_cloud_tpu.
serve.batcher`; its canonical definition moved here and the batcher
re-exports it, so existing imports stay valid.
"""

from __future__ import annotations


class RetryableError(RuntimeError):
    """Transient server-side condition; safe for the client to retry."""


class QueueFullError(RetryableError):
    """Backpressure: the request queue is at max_queue_size.  Mapped to
    HTTP 503 by the server so clients/autoscalers can retry, unlike a
    real fault's 500."""


class KVPagesExhaustedError(QueueFullError):
    """Backpressure one level below the queue: the paged KV arena has no
    free (or evictable) pages left for a new request's reservation.
    Same 503 contract as ``QueueFullError`` — the request was fine, the
    pod's KV memory transiently was not; retries land once decoding
    frees pages."""


class TenantQuotaError(RetryableError):
    """Per-tenant admission quota exhausted (request-rate or prompt-
    token bucket drained).  The 429 of this stack's vocabulary, mapped
    to the same retryable 503 as queue backpressure so Knative/KServe
    retry ladders need no new case — but scoped to ONE tenant: the
    request never touched the shared queue, so a hot-looping tenant
    sheds only itself.  ``retry_after_s`` carries the bucket's refill
    estimate; the server surfaces it in the error body as the
    Retry-After hint."""

    def __init__(self, message: str,
                 retry_after_s: "float | None" = None):
        super().__init__(message)
        self.retry_after_s = retry_after_s


class ModelCacheFullError(RetryableError):
    """The lifecycle model cache is at capacity and every resident
    model is busy — nothing is idle enough to page out.  Same
    retryable-503 contract as queue backpressure: the admission was
    fine, the zoo transiently was not; retries land once a request
    completes and an LRU victim frees up."""


class SwapInProgressError(RetryableError):
    """A live weight hot-swap is already running on this model; swaps
    serialize (the old version is never released until the new one
    passes verification, so two at once cannot both hold that
    guarantee).  Retry after the running swap lands or rolls back."""


class SwapVerificationError(RuntimeError):
    """A hot-swap candidate passed checksum integrity but failed the
    smoke generation gate (out-of-vocab tokens, empty output) — the
    bytes are the ones written, they just don't behave like a model.
    NOT retryable: the same artifact will fail the same way.  Mapped to
    409 by the ``:swap`` route with ``rolled_back: true``; the old
    version keeps serving."""


class NoModelsLoadedError(RuntimeError):
    """``load_all`` over the lifecycle cache left EVERY model in the
    terminal ``failed`` state — the pod has nothing to serve and should
    crash-loop loudly (a zoo with one bad adapter serves degraded
    instead and never raises this)."""


class ReplicaUnavailableError(RetryableError):
    """The fleet router could not place the request on any replica:
    every replica is ejected/draining/dead, or the chosen replica
    failed before producing a response and the retry budget (or the
    candidate set) is exhausted.  Same retryable-503 contract as queue
    backpressure — the request was fine, the *fleet* transiently was
    not; Knative-level retries (or the client's own backoff) land once
    a replica recovers.  ``retry_after_s`` optionally carries the
    router's next-probe estimate."""

    def __init__(self, message: str,
                 retry_after_s: "float | None" = None):
        super().__init__(message)
        self.retry_after_s = retry_after_s


class EngineRestartedError(RetryableError):
    """The supervisor restarted a hung/crashed engine out from under
    this in-flight request.  State (the KV slot) is gone; a retry hits
    the fresh engine."""


class EngineDrainingError(RetryableError):
    """A replacement worker cannot start because the previous engine /
    dispatcher is still draining (a timed-out ``stop()`` left its
    thread finishing in-flight work).  Transient by construction —
    retry once the drain completes (call ``stop()`` again first)."""


class StreamTimeoutError(RetryableError):
    """A token stream stalled: no token within the poll window, or the
    engine died mid-stream.  Raised by ``GenRequest.iter_tokens``
    instead of leaking a raw ``queue.Empty``."""


class DeadlineExceededError(RuntimeError):
    """The request's deadline expired (or admission math proved it
    will) before a result could be produced — HTTP 504."""
