"""Stable Diffusion txt2img predictor (KServe-V1-compatible).

Parity with the reference service (``online-inference/stable-diffusion/
service/service.py``): loads the serializer's encoder/vae/unet module
split (``load_tensorizer`` path, ``:57-132``), serves ``predict`` with the
request-``parameters`` override protocol (``:216-226`` — upper-cased keys
merged over env-var defaults), and returns PNG bytes (base64 in the JSON
data plane).  Denoising runs as a jitted DDIM loop with classifier-free
guidance.
"""

from __future__ import annotations

import base64
import dataclasses
import io
import os
import time
from typing import Any, Mapping, Optional

import jax
import jax.numpy as jnp
import numpy as np

from kubernetes_cloud_tpu.models.diffusion import (
    CLIPTextConfig,
    NoiseSchedule,
    UNetConfig,
    VAEConfig,
    clip_encode,
    ddim_step,
    make_schedule,
    unet_apply,
    vae_decode,
)
from kubernetes_cloud_tpu.serve.model import Model
from kubernetes_cloud_tpu.weights.tensorstream import load_pytree, read_index


def extract_prompt(payload: Mapping[str, Any]) -> str:
    """Request-protocol prompt extraction shared by all txt2img services."""
    return payload.get("prompt") or (
        payload.get("instances") or [{}])[0].get("prompt", "")


def png_predictions(imgs, inference_time: float) -> list[dict]:
    """Encode HWC uint8 images as the b64-PNG prediction records every
    txt2img service returns."""
    from PIL import Image

    preds = []
    for img in imgs:
        buf = io.BytesIO()
        Image.fromarray(img).save(buf, format="PNG")
        preds.append({
            "image_b64": base64.b64encode(buf.getvalue()).decode(),
            "format": "png",
            "inference_time": inference_time,
        })
    return preds


def _cfg_from_meta(cls, meta: dict, **drop):
    fields = {f.name for f in dataclasses.fields(cls)}
    raw = {k: v for k, v in dict(meta).items() if k in fields}
    for key in ("dtype", "param_dtype"):
        if isinstance(raw.get(key), str):
            raw[key] = jnp.bfloat16 if "bfloat16" in raw[key] else jnp.float32
    for k, v in raw.items():
        if isinstance(v, list):
            raw[k] = tuple(v)
    return cls(**raw)


class StableDiffusionService(Model):
    """txt2img over the encoder/vae/unet ``.tensors`` module split."""

    OPTIONS = {
        "HEIGHT": 512,
        "WIDTH": 512,
        "NUM_INFERENCE_STEPS": 30,
        "GUIDANCE_SCALE": 7.5,
        "SEED": -1,
    }

    def __init__(self, name: str, model_dir: str, tokenize=None):
        super().__init__(name)
        self.model_dir = model_dir
        self._tokenize = tokenize

    def load(self) -> None:
        t0 = time.time()
        unet_path = os.path.join(self.model_dir, "unet.tensors")
        meta = read_index(unet_path)["meta"]
        self.unet_cfg = _cfg_from_meta(UNetConfig, meta.get("config", {}))
        self.v_prediction = bool(meta.get("v_prediction", False))
        self.sched_cfg = _cfg_from_meta(NoiseSchedule,
                                        meta.get("schedule", {}))
        self.sched = make_schedule(self.sched_cfg)
        self.unet_params = load_pytree(unet_path)

        vae_path = os.path.join(self.model_dir, "vae.tensors")
        self.vae_cfg = _cfg_from_meta(
            VAEConfig, read_index(vae_path)["meta"].get("config", {}))
        self.vae_params = load_pytree(vae_path)

        enc_path = os.path.join(self.model_dir, "encoder.tensors")
        self.clip_cfg = _cfg_from_meta(
            CLIPTextConfig, read_index(enc_path)["meta"].get("config", {}))
        self.clip_params = load_pytree(enc_path)

        if self._tokenize is None:
            tok_dir = os.path.join(self.model_dir, "tokenizer")
            if os.path.exists(os.path.join(tok_dir, "vocab.json")):
                # imported checkpoints ship their CLIP BPE assets
                from kubernetes_cloud_tpu.serve.clip_bpe import CLIPBPECodec

                codec = CLIPBPECodec.from_dir(tok_dir)
                max_len = self.clip_cfg.max_length
                self._tokenize = (
                    lambda texts: codec.encode_batch(texts, max_len))
            else:  # self-trained models use the byte-level tokenizer
                from kubernetes_cloud_tpu.train.sd_trainer import (
                    _byte_clip_tokenize,
                )

                self._tokenize = _byte_clip_tokenize(self.clip_cfg)
        # Deserialization throughput log, as the reference's loader does
        # (``service.py:122-130``).
        nbytes = sum(os.path.getsize(os.path.join(self.model_dir, f))
                     for f in ("unet.tensors", "vae.tensors",
                               "encoder.tensors"))
        dt = max(time.time() - t0, 1e-9)
        print(f"sd load: {nbytes / 1e6:.1f} MB in {dt:.2f}s "
              f"({nbytes / dt / 1e6:.1f} MB/s)")
        self.ready = True

    def generate_batch(self, prompt: str, *, n_images: int, height: int,
                       width: int, steps: int, guidance_scale: float,
                       seed: Optional[int] = None,
                       mesh=None) -> np.ndarray:
        """Generate ``n_images`` candidates for one prompt in a single
        device program.  With ``mesh`` the latent batch is sharded over the
        ``data`` axis, so N local chips denoise N candidates concurrently —
        the modern-sharding form of the reference DALL-E service's
        ``replicate()`` + ``pmap`` + ``shard_prng_key`` generation
        (``online-inference/dalle-mini/model/service.py:93-109,130-137``)."""
        tokens = jnp.asarray(self._tokenize([prompt, ""]), jnp.int32)
        ctx2 = clip_encode(self.clip_cfg, self.clip_params, tokens)
        # [cond]*n then [uncond]*n for the CFG double-batch
        ctx = jnp.concatenate([
            jnp.repeat(ctx2[:1], n_images, axis=0),
            jnp.repeat(ctx2[1:], n_images, axis=0),
        ])
        factor = 2 ** (len(self.vae_cfg.block_out_channels) - 1)
        rng = jax.random.key(seed if seed not in (None, -1)
                             else int(time.time_ns() % (2 ** 31)))
        z = jax.random.normal(
            rng, (n_images, height // factor, width // factor,
                  self.vae_cfg.latent_channels), jnp.float32)
        if mesh is not None:
            from kubernetes_cloud_tpu.parallel.sharding import shard_batch

            z = shard_batch(z, mesh)
        n_train = self.sched["betas"].shape[0]
        ts = jnp.linspace(n_train - 1, 0, steps).astype(jnp.int32)
        g = guidance_scale
        pred_type = "v_prediction" if self.v_prediction else "epsilon"

        def body(i, z):
            t = ts[i]
            t_prev = jnp.where(i + 1 < steps, ts[jnp.minimum(i + 1,
                                                             steps - 1)], -1)
            zz = jnp.concatenate([z, z])
            out = unet_apply(self.unet_cfg, self.unet_params, zz,
                             jnp.full((2 * n_images,), t), ctx)
            cond, uncond = out[:n_images], out[n_images:]
            guided = uncond + g * (cond - uncond)
            return ddim_step(self.sched, guided, z,
                             jnp.full((n_images,), t),
                             jnp.full((n_images,), t_prev), pred_type)

        z = jax.lax.fori_loop(0, steps, body, z)
        img = vae_decode(self.vae_cfg, self.vae_params, z)
        arr = np.asarray(img, np.float32)
        return ((np.clip(arr, -1, 1) + 1) * 127.5).astype(np.uint8)

    def generate(self, prompt: str, *, height: int, width: int, steps: int,
                 guidance_scale: float,
                 seed: Optional[int] = None) -> np.ndarray:
        return self.generate_batch(
            prompt, n_images=1, height=height, width=width, steps=steps,
            guidance_scale=guidance_scale, seed=seed)[0]

    def predict(self, payload: Mapping[str, Any]) -> dict:
        opts = self.configure_request(payload)
        prompt = extract_prompt(payload)
        t0 = time.time()
        img = self.generate(
            prompt, height=int(opts["HEIGHT"]), width=int(opts["WIDTH"]),
            steps=int(opts["NUM_INFERENCE_STEPS"]),
            guidance_scale=float(opts["GUIDANCE_SCALE"]),
            seed=int(opts["SEED"]))
        return {"predictions": png_predictions([img], time.time() - t0)}


def main(argv: Optional[list] = None, service_cls=None) -> int:
    """Container entrypoint (``deploy/online-inference/stable-diffusion/
    03-inference-service.yaml``; also reused by dalle_service)."""
    import argparse
    import logging

    from kubernetes_cloud_tpu.serve import boot

    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--model", required=True,
                    help="dir with encoder/vae/unet .tensors module split")
    ap.add_argument("--vqgan", default=None,
                    help="accepted for layout parity; the module split "
                         "carries the image decoder (vae.tensors)")
    boot.add_common_args(ap)
    args = ap.parse_args(argv)
    logging.basicConfig(level=logging.INFO)
    boot.wait_for_artifact(args)
    cls = service_cls or StableDiffusionService
    svc = cls(args.model_name or "stable-diffusion", args.model)
    boot.serve([svc], args)
    return 0


if __name__ == "__main__":  # pragma: no cover - container entry
    import sys

    sys.exit(main())
