"""Resilient serving fleet: health-aware routing, retry budgets,
hedged dispatch, zero-drop rolling restarts.

The paper's serving layer never exposes a single pod: every workload is
a KServe ``InferenceService`` behind Knative autoscaling — a *fleet* of
replicas with an activator routing around unready pods.  Everything
below this module heals ONE engine inside ONE process
(:mod:`~kubernetes_cloud_tpu.serve.supervisor`); this module is the
layer above — what stands between a replica dying mid-stream, a hung
pod, or a rolling weight/config restart and the client's error budget.
The techniques are the "Tail at Scale" toolkit (Dean & Barroso, CACM
'13; PAPERS.md):

* **Health-aware routing** (:class:`ReplicaHealth`).  Active ``/readyz``
  probing — the body's ``heartbeat_age_s`` / ``queue_depth`` per model,
  which the PR-3 readiness split already carries, so a *hung* engine
  (alive thread, stale heartbeat) fails the probe even though its HTTP
  plane answers 200 — plus passive per-dispatch error/timeout EWMAs.
  Either signal feeds **outlier ejection**: an ejected replica takes no
  traffic until a probe succeeds (→ ``half_open``), then one trial
  request must succeed before full reinstatement (→ ``active``).
* **Weighted least-loaded dispatch**: score = (router-tracked in-flight
  + last-probed queue depth) / weight; ejected/draining replicas are
  skipped, and the skip is surfaced per response (``rerouted``) so load
  tests can report it honestly.
* **Retry budget** (:class:`RetryBudget`).  Failed dispatches retry on
  another replica ONLY while the token-bucket budget holds (each
  arriving request deposits ``retry_budget_ratio`` tokens, each retry
  spends one) — the bounded-retry discipline that keeps a brown-out
  from amplifying into a retry storm.  Only the typed RetryableError
  503 ladder (and transport failures/timeouts) retries; 504s carry a
  dead deadline and tenant-quota 503s (``error_kind`` in the body)
  would launder one tenant's quota through its neighbours' replicas.
  A request is retried only while ZERO tokens have been delivered to
  the client — with buffered JSON responses that is every failure, and
  greedy decoding makes the retried output token-identical by
  construction.
* **Hedged dispatch**.  A request still *queued-not-admitted* on its
  replica after the hedge delay (the engine's ``request_phase`` — a
  request that started decoding is never duplicated) is mirrored to a
  second replica; the first response wins and the loser is cancelled
  through the existing ``cancel()`` path (in-process directly, remote
  via ``POST /v1/models/<m>:cancel``).  The delay is adaptive (the
  full Tail-at-Scale recipe): ``hedge_ttft_factor`` × the rolling
  per-role TTFT quantile observed on winning responses, floored at
  the fixed ``hedge_after_s`` knob for backward compat — a fleet
  whose TTFT breathes with load hedges at "slower than peers right
  now", not at a constant tuned for yesterday's load.
* **Elastic membership + activator**.  ``add_replica`` /
  ``remove_replica`` change the pool copy-on-write under a lock while
  dispatch threads keep routing over their list snapshot, and an
  attached :class:`~kubernetes_cloud_tpu.serve.autoscaler.Activator`
  turns "no routable replica" from an instant 503 into Knative's
  hold-and-replay: the request parks (its park IS the scale-up
  signal), a spawned replica probes healthy, the request re-picks and
  dispatches exactly once — scale-from-zero with zero drops and zero
  duplicate prefills.  :class:`~kubernetes_cloud_tpu.serve.
  autoscaler.ElasticFleet` drives both through the control loop.
* **Zero-drop rolling restarts** (:meth:`FleetRouter.rolling_restart`).
  One replica at a time: stop routing to it, transplant its
  never-claimed queue through the router into its peers (the engines'
  existing ``requeue()`` machinery — waiters follow the request), let
  its in-flight slots drain (the PR-3 stop/drain path), rebuild, probe
  back to active, proceed.  Requests that race the drain window fail
  with a retryable 503 and are absorbed by the retry ladder, so the
  client-visible error count stays zero.
* **Fleet-wide fairness**.  One :class:`~kubernetes_cloud_tpu.serve.
  tenancy.FleetClock` is attached to every in-process replica's
  :class:`~kubernetes_cloud_tpu.serve.tenancy.TenantScheduler`, so the
  PR-9 WFQ virtual clocks (and the no-banked-credit floor) are a single
  fleet-wide ledger instead of per-replica opinions.

Replicas come in two shapes: :class:`LocalReplica` wraps an in-process
:class:`~kubernetes_cloud_tpu.serve.server.ModelServer` (tier-1 tests
and the availability bench stay CPU-runnable; calls go straight into
its routing, bypassing only the per-request HTTP metrics so
``kct_server_*`` counts each client request once, at the router's
door), and :class:`RemoteReplica` fronts a real pod by URL.  The router
itself IS a :class:`~kubernetes_cloud_tpu.serve.server.ModelServer`
subclass, so both front-ends (stdlib + native C++) serve it unchanged
and the V1 predict/completion/cancel surface, deadline headers, tenant
keys, ``/metrics`` and the debug plane all ride the shared
``handle()``.

Fault sites: ``fleet.dispatch`` (per dispatch attempt, on the
submitting HTTP thread — raise/hang contained to that request) and
``fleet.probe`` (on the prober thread — raise reads as a failed probe,
hang parks only the prober; dispatch keeps routing on last-known
health).  Chaos-locked by ``tests/test_fleet_chaos.py``.
"""

from __future__ import annotations

import dataclasses
import json
import logging
import queue
import threading
import time
import urllib.error
import urllib.request
from typing import Any, Mapping, Optional, Sequence

from kubernetes_cloud_tpu import faults, obs
from kubernetes_cloud_tpu.obs import dtrace
from kubernetes_cloud_tpu.obs.slo import SLOEvaluator
from kubernetes_cloud_tpu.serve.autoscaler import RollingDigest
from kubernetes_cloud_tpu.serve.errors import (
    ReplicaUnavailableError,
    RetryableError,
)
from kubernetes_cloud_tpu.serve.server import ModelServer
from kubernetes_cloud_tpu.serve.tenancy import FleetClock

log = logging.getLogger(__name__)

#: replica health states (the outlier-ejection state machine)
ACTIVE = "active"          # takes traffic
EJECTED = "ejected"        # outlier: probes only, no traffic
HALF_OPEN = "half_open"    # probe succeeded; one trial request decides
DRAINING = "draining"      # rolling restart in progress: no traffic
STATES = (ACTIVE, EJECTED, HALF_OPEN, DRAINING)

#: 503 ``error_kind``s the router must NOT retry on another replica:
#: a tenant-quota shed is the tenant's contract, and laundering it
#: through a neighbour replica's bucket would defeat admission control
_NO_RETRY_KINDS = frozenset({"TenantQuotaError"})

# Fleet metric families (labels: replica ids are configured, bounded)
_M_REPLICAS = obs.gauge(
    "kct_fleet_replicas",
    "Fleet replicas per health state (active | ejected | half_open | "
    "draining).", ("state",))
_M_DISPATCH = obs.counter(
    "kct_fleet_dispatches_total",
    "Dispatch attempts per replica by outcome (ok | error | timeout).",
    ("replica", "outcome"))
_M_RETRIES = obs.counter(
    "kct_fleet_retries_total",
    "Fleet-level retries by outcome (ok = the retry answered, failed "
    "= it did not, budget_exhausted = the retry token bucket refused "
    "one).", ("outcome",))
_M_HEDGES = obs.counter(
    "kct_fleet_hedges_total",
    "Hedged dispatches by outcome (win = the hedge answered first, "
    "loss = the primary did).", ("outcome",))
_M_EJECTIONS = obs.counter(
    "kct_fleet_ejections_total",
    "Replica ejections by cause (probe | errors | timeouts | trial).",
    ("replica", "cause"))
_M_RECOVERIES = obs.counter(
    "kct_fleet_recoveries_total",
    "Replicas reinstated to active after a half-open trial succeeded.",
    ("replica",))
_M_QUEUE = obs.gauge(
    "kct_fleet_queue_depth",
    "Last-probed aggregate admission queue depth per replica (what "
    "least-loaded dispatch weighs).", ("replica",))
_M_INFLIGHT = obs.gauge(
    "kct_fleet_inflight",
    "Router-tracked in-flight dispatches per replica.", ("replica",))
_M_TRANSPLANTED = obs.counter(
    "kct_fleet_transplanted_total",
    "Never-claimed queued requests moved off a draining replica "
    "during a rolling restart.", ("replica",))
_M_ROLLING = obs.counter(
    "kct_fleet_rolling_restarts_total",
    "Completed zero-drop rolling-restart sweeps over the fleet.")
_M_UNPLACEABLE = obs.counter(
    "kct_fleet_unplaceable_total",
    "Requests answered 503 because no active replica could take them "
    "(every replica ejected/draining/dead, or retries exhausted).")


@dataclasses.dataclass(frozen=True)
class FleetConfig:
    """Router knobs (deploy/README.md "Fleet & rolling restarts" maps
    them onto the Knative activator/containerConcurrency contract)."""

    #: active health probing cadence (GET /readyz per replica)
    probe_interval_s: float = 1.0
    probe_timeout_s: float = 5.0
    #: a readyz body whose worst model ``heartbeat_age_s`` exceeds this
    #: is unhealthy even at HTTP 200 — the hung-pod signal
    heartbeat_stale_s: float = 10.0
    #: consecutive failed probes before an ACTIVE replica is ejected
    probe_fail_threshold: int = 3
    #: passive outlier ejection: per-dispatch error EWMA weight and the
    #: level (after ``min_samples`` dispatches) that ejects
    error_ewma_alpha: float = 0.3
    error_ewma_eject: float = 0.6
    min_samples: int = 4
    #: consecutive dispatch timeouts that eject (a hung replica fails
    #: no requests — it just never answers)
    timeout_eject: int = 2
    #: bound on one dispatch attempt (generation included); a hung
    #: replica surfaces here, feeding the timeout ejector
    dispatch_timeout_s: float = 300.0
    #: retries per request (candidate replicas permitting)
    max_retries: int = 3
    #: retry budget: every arriving request deposits this many retry
    #: tokens (capped at ``retry_budget_burst``), every retry spends
    #: one — fleet-wide retries are bounded at ~ratio x request rate
    retry_budget_ratio: float = 0.2
    retry_budget_burst: float = 10.0
    #: hedge a request still queued-not-admitted after this long; None
    #: disables hedging.  With the live-TTFT digest active this is the
    #: FLOOR under the adaptive delay, not the delay itself
    hedge_after_s: Optional[float] = None
    #: rolling restart: bound on waiting a rebuilt replica healthy
    restart_probe_timeout_s: float = 60.0
    #: adaptive hedging: delay = max(hedge_after_s, this quantile of
    #: the rolling per-role TTFT digest x ``hedge_ttft_factor``); None
    #: falls back to the fixed hedge_after_s alone.  Only consulted
    #: while hedging is enabled (hedge_after_s set) and the digest has
    #: ``hedge_ttft_min_samples`` in its ``hedge_ttft_window_s``
    hedge_ttft_quantile: Optional[float] = 0.95
    hedge_ttft_factor: float = 2.0
    hedge_ttft_min_samples: int = 20
    hedge_ttft_window_s: float = 60.0

    def __post_init__(self):
        if self.probe_interval_s <= 0 or self.probe_timeout_s <= 0:
            raise ValueError("probe intervals must be > 0")
        if self.probe_fail_threshold < 1 or self.timeout_eject < 1:
            raise ValueError("ejection thresholds must be >= 1")
        if not 0 < self.error_ewma_alpha <= 1:
            raise ValueError("error_ewma_alpha must be in (0, 1]")
        if not 0 < self.error_ewma_eject <= 1:
            raise ValueError("error_ewma_eject must be in (0, 1]")
        if self.max_retries < 0 or self.retry_budget_ratio < 0:
            raise ValueError("retry knobs must be >= 0")
        if self.hedge_after_s is not None and self.hedge_after_s <= 0:
            raise ValueError("hedge_after_s must be > 0 (None disables)")
        if (self.hedge_ttft_quantile is not None
                and not 0 < self.hedge_ttft_quantile <= 1):
            raise ValueError("hedge_ttft_quantile must be in (0, 1] "
                             "(None disables the adaptive delay)")
        if self.hedge_ttft_factor <= 0 or self.hedge_ttft_window_s <= 0:
            raise ValueError("hedge_ttft factor/window must be > 0")
        if self.hedge_ttft_min_samples < 1:
            raise ValueError("hedge_ttft_min_samples must be >= 1")


class RetryBudget:
    """Token-bucket retry budget (Tail at Scale / Finagle style):
    deposits ride the request rate, so sustained retries are capped at
    ``ratio`` of traffic; the burst is the cold-start allowance.
    Thread-safe."""

    def __init__(self, ratio: float, burst: float):
        self.ratio = float(ratio)
        self.burst = max(float(burst), 1.0)
        self._level = self.burst
        self._lock = threading.Lock()

    def deposit(self) -> None:
        with self._lock:
            self._level = min(self.burst, self._level + self.ratio)

    def try_take(self) -> bool:
        with self._lock:
            if self._level >= 1.0:
                self._level -= 1.0
                return True
            return False

    @property
    def level(self) -> float:
        return self._level


class ReplicaHealth:
    """One replica's health state machine: active probes + passive
    dispatch outcomes in, ejection/recovery transitions out.  All
    transitions run under one small lock; nothing inside blocks."""

    def __init__(self, replica_id: str, cfg: FleetConfig):
        self.id = replica_id
        self.cfg = cfg
        self._lock = threading.Lock()
        self.state = ACTIVE
        self.ejected_cause: Optional[str] = None
        self.consec_probe_fails = 0
        self.consec_timeouts = 0
        self.ewma_error = 0.0
        self.samples = 0
        #: one trial request at a time while half-open
        self.trial_inflight = False
        #: last healthy-probe payload (queue depth feeds dispatch)
        self.queue_depth = 0
        self.heartbeat_age_s: Optional[float] = None
        self.last_probe_ok: Optional[bool] = None
        #: serving role learned from probe bodies (serve/continuous.py
        #: serving_metadata): decode-role replicas take requests only
        #: through their prefill partner's KV handoff, so the router
        #: never dispatches admission traffic to them
        self.role = "colocated"
        #: {model: weights_version} learned from probe bodies — during
        #: a rolling hot-swap this is how the router tells an already-
        #: swapped replica from one still serving the old artifact
        self.weights_versions: dict[str, str] = {}
        self.stats = {"probes": 0, "probe_fails": 0, "ejections": 0,
                      "recoveries": 0, "dispatch_ok": 0,
                      "dispatch_err": 0, "dispatch_timeout": 0}

    # -- transitions (return the event to record OUTSIDE the lock) ---------

    def _eject(self, cause: str) -> str:
        self.state = EJECTED
        self.ejected_cause = cause
        self.consec_probe_fails = 0
        self.consec_timeouts = 0
        self.ewma_error = 0.0
        self.samples = 0
        self.trial_inflight = False
        self.stats["ejections"] += 1
        return cause

    def note_probe(self, healthy: bool, queue_depth: int = 0,
                   heartbeat_age_s: Optional[float] = None,
                   role: Optional[str] = None, *,
                   weights_versions: Optional[dict[str, str]] = None
                   ) -> Optional[str]:
        """Record one active-probe verdict; returns an ejection cause
        or the string ``"half_open"`` on an EJECTED→HALF_OPEN
        transition (callers emit metrics/logs outside the lock)."""
        with self._lock:
            self.stats["probes"] += 1
            if healthy:
                self.consec_probe_fails = 0
                self.queue_depth = queue_depth
                self.heartbeat_age_s = heartbeat_age_s
                self.last_probe_ok = True
                if role is not None:
                    self.role = role
                if weights_versions:
                    self.weights_versions = dict(weights_versions)
                if self.state == EJECTED:
                    # recovery probe succeeded: one trial request will
                    # decide reinstatement
                    self.state = HALF_OPEN
                    self.trial_inflight = False
                    return "half_open"
                return None
            self.stats["probe_fails"] += 1
            self.last_probe_ok = False
            self.consec_probe_fails += 1
            if self.state == HALF_OPEN:
                return self._eject("probe")
            if (self.state == ACTIVE and self.consec_probe_fails
                    >= self.cfg.probe_fail_threshold):
                return self._eject("probe")
            return None

    def begin_dispatch(self) -> Optional[bool]:
        """Claim the replica for one dispatch: ``False`` for a normal
        dispatch, ``True`` for the half-open trial, ``None`` when the
        replica must not take traffic right now."""
        with self._lock:
            if self.state == ACTIVE:
                return False
            if self.state == HALF_OPEN and not self.trial_inflight:
                self.trial_inflight = True
                return True
            return None

    def note_result(self, ok: bool, *, timeout: bool = False,
                    trial: bool = False) -> Optional[str]:
        """Record one dispatch outcome; returns an ejection cause, the
        string ``"recovered"`` for a successful trial, or None."""
        with self._lock:
            if timeout:
                self.stats["dispatch_timeout"] += 1
            elif ok:
                self.stats["dispatch_ok"] += 1
            else:
                self.stats["dispatch_err"] += 1
            if trial:
                self.trial_inflight = False
                if self.state != HALF_OPEN:
                    return None  # a probe transitioned us meanwhile
                if ok:
                    self.state = ACTIVE
                    self.ejected_cause = None
                    self.stats["recoveries"] += 1
                    return "recovered"
                return self._eject("trial")
            self.consec_timeouts = (self.consec_timeouts + 1
                                    if timeout else 0)
            a = self.cfg.error_ewma_alpha
            self.ewma_error = (a * (0.0 if ok else 1.0)
                               + (1 - a) * self.ewma_error)
            self.samples += 1
            if self.state != ACTIVE:
                return None
            if self.consec_timeouts >= self.cfg.timeout_eject:
                return self._eject("timeouts")
            if (self.samples >= self.cfg.min_samples
                    and self.ewma_error >= self.cfg.error_ewma_eject):
                return self._eject("errors")
            return None

    def eject(self, cause: str) -> None:
        """Explicit ejection (rolling restart found a rebuilt replica
        that never came back healthy)."""
        with self._lock:
            self._eject(cause)

    def release_trial(self) -> None:
        """Un-claim a half-open trial that never reached the replica
        (an injected router-side dispatch fault) — charging it as a
        trial failure would eject a replica that saw nothing."""
        with self._lock:
            self.trial_inflight = False

    def begin_drain(self) -> None:
        with self._lock:
            self.state = DRAINING
            self.trial_inflight = False

    def force_active(self) -> None:
        """Rolling restart: the router just rebuilt and probed this
        replica itself — reinstate without a traffic trial."""
        with self._lock:
            self.state = ACTIVE
            self.ejected_cause = None
            self.consec_probe_fails = 0
            self.consec_timeouts = 0
            self.ewma_error = 0.0
            self.samples = 0
            self.trial_inflight = False

    def snapshot(self) -> dict:
        with self._lock:
            return {"state": self.state,
                    "role": self.role,
                    "weights_versions": dict(self.weights_versions),
                    "ejected_cause": self.ejected_cause,
                    "queue_depth": self.queue_depth,
                    "heartbeat_age_s": self.heartbeat_age_s,
                    "ewma_error": round(self.ewma_error, 4),
                    "consec_timeouts": self.consec_timeouts,
                    "consec_probe_fails": self.consec_probe_fails,
                    **self.stats}


class Replica:
    """One fleet member.  Subclasses supply transport; the router only
    ever talks through this surface."""

    #: local replicas can be drained/rebuilt in-process; remote pods
    #: restart via their own orchestrator (kubectl), not this router
    restartable = False

    def __init__(self, replica_id: str, cfg: FleetConfig, *,
                 weight: float = 1.0):
        if weight <= 0:
            raise ValueError("replica weight must be > 0")
        self.id = replica_id
        self.weight = float(weight)
        self.health = ReplicaHealth(replica_id, cfg)
        self.inflight = 0
        self._inflight_lock = threading.Lock()
        self._m_dispatch = {o: _M_DISPATCH.labels(replica=replica_id,
                                                  outcome=o)
                            for o in ("ok", "error", "timeout")}
        self._m_queue = _M_QUEUE.labels(replica=replica_id)
        self._m_inflight = _M_INFLIGHT.labels(replica=replica_id)

    # -- transport (subclasses) --------------------------------------------

    def call(self, method: str, path: str, body: bytes,
             headers: Optional[Mapping[str, str]] = None
             ) -> tuple[int, dict]:
        raise NotImplementedError

    def probe(self, timeout: float) -> tuple[int, dict]:
        """GET /readyz → (status, parsed body); raises on transport
        failure."""
        raise NotImplementedError

    def request_phase(self, request_id: Optional[str]) -> Optional[str]:
        """``"queued"`` / ``"active"`` / None (unknown).  Remote
        replicas return None — hedging then gates on time alone."""
        return None

    def cancel(self, request_id: Optional[str]) -> None:
        """Best-effort cancel-by-id (hedge loser / timeout orphan)."""

    def model_names(self) -> list[str]:
        return []

    # -- load accounting ---------------------------------------------------

    def inflight_inc(self) -> None:
        with self._inflight_lock:
            self.inflight += 1
        self._m_inflight.set(self.inflight)

    def inflight_dec(self) -> None:
        with self._inflight_lock:
            self.inflight -= 1
        self._m_inflight.set(self.inflight)

    def load_score(self) -> float:
        """Weighted least-loaded dispatch key: smaller = freer."""
        return (self.inflight + self.health.queue_depth) / self.weight

    def snapshot(self) -> dict:
        return {"id": self.id, "weight": self.weight,
                "inflight": self.inflight, **self.health.snapshot()}


class LocalReplica(Replica):
    """An in-process replica: a fully-formed ``ModelServer`` whose
    routing is invoked directly (no sockets).  This is what keeps
    tier-1 and the availability bench CPU-runnable; it is also an
    honest model of a sidecar-per-process deployment."""

    restartable = True

    def __init__(self, replica_id: str, server: ModelServer,
                 cfg: FleetConfig, *, weight: float = 1.0):
        super().__init__(replica_id, cfg, weight=weight)
        self.server = server

    def load(self) -> None:
        self.server.load_all()

    def call(self, method: str, path: str, body: bytes,
             headers: Optional[Mapping[str, str]] = None
             ) -> tuple[int, dict]:
        # _route, not handle(): the replica's routing (drain flag,
        # in-flight accounting, error mapping) without its per-request
        # HTTP metrics — kct_server_* must count each client request
        # once, at the router's own handle()
        return self.server._route(method, path, body, headers)

    def probe(self, timeout: float) -> tuple[int, dict]:
        status, obj = self.server._route("GET", "/readyz", b"", None)
        return status, obj if isinstance(obj, dict) else {}

    def engines(self) -> list:
        out = []
        for model in self.server.models.values():
            eng = getattr(model, "engine", None)
            if eng is not None:
                out.append(eng)
        return out

    def request_phase(self, request_id: Optional[str]) -> Optional[str]:
        best = None
        for model in self.server.models.values():
            fn = getattr(model, "request_phase", None)
            phase = fn(request_id) if fn is not None else None
            if phase == "active":
                return "active"
            best = best or phase
        return best

    def cancel(self, request_id: Optional[str]) -> None:
        for model in self.server.models.values():
            fn = getattr(model, "cancel_request", None)
            if fn is not None:
                try:
                    fn(request_id)
                except Exception:  # noqa: BLE001 - best-effort cleanup
                    log.exception("%s: cancel(%s) failed", self.id,
                                  request_id)

    def model_names(self) -> list[str]:
        return sorted(self.server.models)

    def attach_clock(self, clock: FleetClock) -> None:
        """(Re-)share the fleet virtual clock with every engine's
        tenant scheduler — idempotent, re-applied after each probe so
        supervisor/rolling restarts (fresh engines, fresh schedulers)
        rejoin the fleet ledger automatically."""
        for eng in self.engines():
            eng.tenants.attach_fleet_clock(clock)

    def extract_queued(self) -> list[tuple[str, list]]:
        """``(model_name, [GenRequest, ...])`` of never-claimed queued
        work, popped for transplant (rolling restart)."""
        out = []
        for name, model in self.server.models.items():
            eng = getattr(model, "engine", None)
            fn = getattr(eng, "extract_queued", None)
            if fn is not None:
                reqs = fn()
                if reqs:
                    out.append((name, reqs))
        return out

    def requeue(self, model_name: str, req) -> bool:
        model = self.server.models.get(model_name)
        eng = getattr(model, "engine", None)
        if eng is None or not eng.alive:
            return False
        eng.requeue(req)
        return True

    def restart(self) -> None:
        """Drain in-flight slots and rebuild every worker model (stop()
        → load(); weights and the jit cache survive, the engine and
        its pool are fresh) — the in-process rendering of a pod
        rollout."""
        for model in self.server.models.values():
            stop = getattr(model, "stop", None)
            if callable(stop):
                stop()
        self.server.load_all()


class RemoteReplica(Replica):
    """A real pod, by base URL (``http://host:port``)."""

    def __init__(self, replica_id: str, base_url: str, cfg: FleetConfig,
                 *, weight: float = 1.0):
        super().__init__(replica_id, cfg, weight=weight)
        self.base_url = base_url.rstrip("/")
        self.cfg = cfg
        self._models: list[str] = []

    def _request(self, method: str, path: str, body: bytes,
                 headers: Optional[Mapping[str, str]],
                 timeout: float) -> tuple[int, dict]:
        req = urllib.request.Request(
            self.base_url + path, data=body if method == "POST" else None,
            headers={"Content-Type": "application/json",
                     **(dict(headers) if headers else {})},
            method=method)
        try:
            with urllib.request.urlopen(req, timeout=timeout) as resp:
                return resp.status, json.loads(resp.read() or b"{}")
        except urllib.error.HTTPError as e:
            try:
                return e.code, json.loads(e.read() or b"{}")
            except ValueError:  # ingress HTML error page, not our JSON
                return e.code, {"error": f"HTTP {e.code}"}

    def call(self, method: str, path: str, body: bytes,
             headers: Optional[Mapping[str, str]] = None
             ) -> tuple[int, dict]:
        return self._request(method, path, body, headers,
                             self.cfg.dispatch_timeout_s)

    def probe(self, timeout: float) -> tuple[int, dict]:
        status, obj = self._request("GET", "/readyz", b"", None, timeout)
        models = obj.get("models")
        if isinstance(models, dict) and models:
            self._models = sorted(models)  # learned from the probe
        return status, obj

    def cancel(self, request_id: Optional[str]) -> None:
        if not request_id:
            return
        body = json.dumps({"request_id": request_id}).encode()
        for name in self._models or ["lm"]:
            try:
                self._request("POST", f"/v1/models/{name}:cancel", body,
                              None, self.cfg.probe_timeout_s)
            except Exception:  # noqa: BLE001 - best-effort cleanup
                log.debug("%s: remote cancel failed", self.id)

    def model_names(self) -> list[str]:
        return list(self._models)


def _probe_healthy(status: int, body: Mapping[str, Any], stale_s: float
                   ) -> tuple[bool, int, Optional[float], Optional[str],
                              dict[str, str]]:
    """Evaluate a /readyz answer: (healthy, queue_depth,
    worst_heartbeat_age, role, weights_versions).  HTTP 200 alone is
    not enough — a hung unsupervised engine still answers ready, but
    its per-model ``heartbeat_age_s`` gives it away.  ``role`` is the
    serving role the replica's models declare (serving_metadata): a
    "decode"-role replica serves only through its prefill partner's KV
    handoff, so the router learns to keep admission traffic off it.
    ``weights_versions`` maps model name → content-hash weight
    identity, so mid-hot-swap the router can tell which replicas have
    rolled onto the new artifact and which still serve the old one."""
    if status != 200:
        return False, 0, None, None, {}
    depth, worst_age, role = 0, None, None
    versions: dict[str, str] = {}
    for name, detail in (body.get("models") or {}).items():
        if not isinstance(detail, dict):
            continue
        if not detail.get("ok", True):
            return False, 0, None, None, {}
        depth += int(detail.get("queue_depth") or 0)
        got = detail.get("role")
        if got is not None:
            # one admission-taking model makes the replica routable
            role = got if role in (None, "decode") else role
        wv = detail.get("weights_version")
        if wv is not None:
            versions[str(name)] = str(wv)
        age = detail.get("heartbeat_age_s")
        if age is not None:
            age = float(age)
            worst_age = age if worst_age is None else max(worst_age, age)
    if worst_age is not None and worst_age > stale_s:
        return False, depth, worst_age, role, versions
    return True, depth, worst_age, role, versions


class FleetRouter(ModelServer):
    """N replicas behind the one V1 endpoint clients already speak.

    A ``ModelServer`` with no local models: every data-plane POST the
    shared ``handle()`` routes lands in the overridden ``_predict`` /
    ``_completion`` / ``_cancel`` and is dispatched to a replica;
    ``/readyz`` aggregates replica health; ``/metrics`` and the debug
    plane come from the base class unchanged."""

    def __init__(self, replicas: Sequence[Replica],
                 cfg: FleetConfig = FleetConfig(), *,
                 host: str = "0.0.0.0", port: int = 8080,
                 allow_empty: bool = False):
        if not replicas and not allow_empty:
            # an elastic fleet (autoscaler-owned membership, possibly
            # scaled to zero behind the activator) opts in explicitly
            raise ValueError("a fleet needs at least one replica")
        ids = [r.id for r in replicas]
        if len(set(ids)) != len(ids):
            raise ValueError(f"duplicate replica ids: {ids}")
        super().__init__([], host=host, port=port)
        self.replicas = list(replicas)
        self.cfg = cfg
        self.retry_budget = RetryBudget(cfg.retry_budget_ratio,
                                        cfg.retry_budget_burst)
        #: the fleet-wide WFQ ledger (serve/tenancy.FleetClock)
        self.clock = FleetClock()
        #: scale-from-zero hold-and-replay (attach_activator)
        self.activator = None
        #: rolling per-role TTFT digests feeding the adaptive hedge
        #: delay (observed from winning response bodies)
        self._ttft_digests: dict[str, RollingDigest] = {}
        self._probe_stop = threading.Event()
        self._probe_thread: Optional[threading.Thread] = None
        #: serializes rolling restarts (two sweeps would double-drain)
        self._restart_lock = threading.Lock()
        #: serializes membership writes; readers ride list snapshots
        #: (replacement, never mutation — the copy-on-write idiom)
        self._replica_lock = threading.Lock()
        self.stats = {"dispatches": 0, "retries": 0, "retried_ok": 0,
                      "retry_budget_exhausted": 0, "hedges": 0,
                      "hedge_wins": 0, "rerouted": 0, "unplaceable": 0,
                      "transplanted": 0, "rolling_restarts": 0,
                      "arrivals": 0, "activator_held": 0,
                      "activator_replayed": 0}
        #: stats increments come from concurrent HTTP dispatch
        #: threads; dict += is a read-modify-write that loses updates
        #: without this (the bench reports these numbers)
        self._stats_lock = threading.Lock()
        for r in self.replicas:
            attach = getattr(r, "attach_clock", None)
            if attach is not None:
                attach(self.clock)
        # the fleet view is where SLOs live: a default evaluator over
        # the declared promises, kept warm by the prober loop (poke()
        # never blocks it) and served at /debug/slo.  Its latency
        # thresholds double as the tail-sampler's breach targets.
        self.attach_slo(SLOEvaluator())
        store = dtrace.store()
        for spec in self.slo.specs:
            if spec.name == "ttft_p95" and store.ttft_target_s is None:
                store.ttft_target_s = spec.threshold_s
            if (spec.name == "inter_token_p95"
                    and store.inter_token_target_s is None):
                store.inter_token_target_s = spec.threshold_s

    def _bump(self, key: str, n: int = 1) -> None:
        with self._stats_lock:
            self.stats[key] += n

    # -- elastic membership ------------------------------------------------

    def add_replica(self, replica: Replica) -> None:
        """Register a (spawned) replica.  Membership changes replace
        ``self.replicas`` wholesale under the lock; in-flight dispatch
        threads keep iterating their own list snapshot, so routing
        never races a resize."""
        with self._replica_lock:
            if any(r.id == replica.id for r in self.replicas):
                raise ValueError(f"duplicate replica id: {replica.id}")
            self.replicas = [*self.replicas, replica]
        attach = getattr(replica, "attach_clock", None)
        if attach is not None:
            attach(self.clock)
        self._refresh_state_gauge()

    def remove_replica(self, replica_id: str) -> Optional["Replica"]:
        """Deregister a (drained) replica; returns it, or None if the
        id is not a member.  The caller owns stopping its workers."""
        with self._replica_lock:
            found = next((r for r in self.replicas
                          if r.id == replica_id), None)
            if found is not None:
                self.replicas = [r for r in self.replicas
                                 if r is not found]
        if found is not None:
            self._refresh_state_gauge()
        return found

    def attach_activator(self, activator) -> None:
        """Arm scale-from-zero hold-and-replay (serve/autoscaler.
        :class:`Activator`): a request that finds NO routable replica
        parks on the activator (the park itself pokes the control
        loop) instead of failing unplaceable, and re-picks when a
        spawn probes healthy — dispatched exactly once, after
        capacity exists."""
        self.activator = activator

    def role_signals(self) -> dict[str, dict]:
        """Per-role pool signals for the autoscaler: ready (routable)
        replica count and observed concurrency (router-tracked
        in-flight + last-probed admission queue depth)."""
        out: dict[str, dict] = {}
        for r in self.replicas:
            agg = out.setdefault(r.health.role,
                                 {"ready": 0, "concurrency": 0.0})
            if r.health.state in (ACTIVE, HALF_OPEN):
                agg["ready"] += 1
                agg["concurrency"] += (r.inflight
                                       + r.health.queue_depth)
        return out

    # -- lifecycle ---------------------------------------------------------

    def load_all(self) -> None:
        for r in self.replicas:
            load = getattr(r, "load", None)
            if callable(load):
                load()

    def start_probing(self) -> None:
        if self._probe_thread is not None and self._probe_thread.is_alive():
            return
        self._probe_stop.clear()
        self._probe_thread = threading.Thread(
            target=self._probe_loop, daemon=True, name="fleet-prober")
        self._probe_thread.start()

    def stop_probing(self) -> None:
        self._probe_stop.set()
        if self._probe_thread is not None:
            self._probe_thread.join(timeout=5.0)
            self._probe_thread = None

    def start(self) -> None:
        self.start_probing()
        super().start()

    def serve_forever(self) -> None:
        self.start_probing()
        super().serve_forever()

    def stop(self) -> None:
        self.stop_probing()
        super().stop()

    def shutdown(self) -> None:
        """Stop the router AND its in-process replicas' workers (tests
        and the bench; a production router never owns remote pods)."""
        self.stop()
        if self.slo is not None:
            self.slo.close()
        for r in self.replicas:
            server = getattr(r, "server", None)
            if server is None:
                continue
            for model in server.models.values():
                stop = getattr(model, "stop", None)
                if callable(stop):
                    try:
                        stop()
                    except Exception:  # noqa: BLE001 - teardown
                        log.exception("stopping %s/%s failed", r.id,
                                      model.name)

    # -- health probing ----------------------------------------------------

    def _probe_loop(self) -> None:
        while not self._probe_stop.wait(self.cfg.probe_interval_s):
            try:
                self.probe_now()
            except Exception:  # noqa: BLE001 - the prober never dies
                log.exception("fleet probe pass failed")

    def probe_now(self) -> None:
        """One probe pass over every replica (the thread calls this
        each interval; tests call it synchronously)."""
        for r in self.replicas:
            if r.health.state == DRAINING:
                continue  # deliberate; rolling_restart owns it
            try:
                faults.fire("fleet.probe")
                status, body = r.probe(self.cfg.probe_timeout_s)
                healthy, depth, age, role, versions = _probe_healthy(
                    status, body, self.cfg.heartbeat_stale_s)
            except Exception as e:  # noqa: BLE001 - a failed probe is
                # data, not an error: transport refusal, injected
                # fault, malformed body — all read "unhealthy"
                healthy, depth, age, role, versions = (False, 0, None,
                                                       None, {})
                log.debug("%s: probe failed: %s", r.id, e)
            event = r.health.note_probe(healthy, depth, age, role,
                                        weights_versions=versions)
            if healthy:
                r._m_queue.set(depth)
                attach = getattr(r, "attach_clock", None)
                if attach is not None:
                    # engines rebuilt by a supervisor restart carry
                    # fresh schedulers; re-attach is idempotent
                    attach(self.clock)
            if event == "half_open":
                log.info("%s: recovery probe succeeded; half-open", r.id)
            elif event is not None:
                log.warning("%s: ejected (cause=%s)", r.id, event)
                _M_EJECTIONS.labels(replica=r.id, cause=event).inc()
        self._refresh_state_gauge()
        if self.slo is not None:
            # fleet-wide burn-rate evaluation rides the prober cadence;
            # poke() only wakes the evaluator's own worker thread, so a
            # wedged evaluation (fault site slo.eval) can never stall
            # this loop
            self.slo.poke()

    def _refresh_state_gauge(self) -> None:
        counts = {s: 0 for s in STATES}
        for r in self.replicas:
            counts[r.health.state] += 1
        for state, n in counts.items():
            _M_REPLICAS.labels(state=state).set(n)

    # -- dispatch ----------------------------------------------------------

    def _pick(self, exclude: Sequence[Replica]
              ) -> tuple[Optional[Replica], Optional[bool], bool]:
        """Least-loaded active replica outside ``exclude``; returns
        (replica, is_trial, skipped_unhealthy).  ``skipped_unhealthy``
        is True when at least one replica was passed over for health —
        the honest ``rerouted`` signal load tests report.  Decode-role
        replicas (learned from probe bodies) are not admission targets
        at all — requests reach them through their prefill partner's
        KV handoff — so they are filtered up front, not counted as
        reroutes."""
        skipped = False
        for r in sorted((r for r in self.replicas if r not in exclude
                         and r.health.role != "decode"),
                        key=lambda r: r.load_score()):
            trial = r.health.begin_dispatch()
            if trial is None:
                skipped = True
                continue
            return r, trial, skipped
        return None, None, skipped

    def _call_replica(self, replica: Replica, path: str, body: bytes,
                      results: "queue.SimpleQueue", tag: str,
                      headers: Optional[Mapping[str, str]] = None
                      ) -> None:
        """One dispatch on its own thread (bounded waits + hedging need
        the caller free); the result is tagged onto the shared queue.
        The thread owns the replica's in-flight count."""
        replica.inflight_inc()
        t0 = time.monotonic()
        try:
            status, obj = replica.call("POST", path, body, headers)
        except RetryableError as e:
            status, obj = 503, {"error": str(e),
                                "error_kind": type(e).__name__}
        except Exception as e:  # noqa: BLE001 - transport failure is an
            # outcome to weigh, never an unwound HTTP thread
            status, obj = 0, {"error": str(e)}
        finally:
            replica.inflight_dec()
        results.put((tag, replica, status, obj, time.monotonic() - t0))

    @staticmethod
    def _retryable(status: int, obj: Mapping[str, Any]) -> bool:
        """The retry gate: transport failure (0), dispatch timeout
        (-1), or the typed RetryableError 503 ladder — minus the kinds
        that must not hop replicas (tenant quota).  504 carries a dead
        deadline; 4xx/500 are the request's or the pod's real fault."""
        if status in (0, -1):
            return True
        if status != 503:
            return False
        return obj.get("error_kind") not in _NO_RETRY_KINDS

    def _fleet_call(self, path: str, payload: dict) -> tuple[int, dict]:
        """Dispatch one data-plane request into the fleet: least-loaded
        pick, hedging, bounded retries, fleet accounting.  Returns the
        winning replica's (status, body) with a ``fleet`` annotation on
        success."""
        body = json.dumps(payload).encode()
        rid = payload.get("request_id")
        #: the door's trace context for this request — every dispatch
        #: leg becomes a "dispatch" child span of the router's server
        #: span, each leg carrying its own span id on the wire so the
        #: replica's tree parents into the right leg
        ctx = dtrace.context_for(rid)
        # the hedge leg re-ids the request with an "-h" suffix: the
        # engines' prefix matching (cancel/request_phase) still reaches
        # it, responses never echo request_id so clients can't tell,
        # and the leg's engine spans bind to the hedge door context
        # instead of colliding with the primary's
        hedge_body = body
        if rid:
            hedge_body = json.dumps(
                {**payload, "request_id": f"{rid}-h"}).encode()
        self.retry_budget.deposit()
        self._bump("arrivals")
        hold_deadline: Optional[float] = None
        retries = dispatches = 0
        hedged = hedge_win = rerouted = False
        tried: list[Replica] = []

        def annotate(obj: dict, replica_id: Optional[str]) -> dict:
            # success AND failure bodies both carry the fleet cost, so
            # load tests can report retry amplification honestly (a
            # request that burned 4 dispatches before its 503 must not
            # read as one)
            if ctx is not None:
                # tail-sampling keep reasons the router alone knows
                if hedged:
                    dtrace.note_keep(ctx.trace_id, "hedged")
                if retries:
                    dtrace.note_keep(ctx.trace_id, "retried")
            obj = dict(obj)
            obj["fleet"] = {
                "replica": replica_id, "retries": retries,
                "dispatches": dispatches, "retried_ok": False,
                "hedged": hedged, "hedge_win": hedge_win,
                "rerouted": rerouted,
            }
            return obj

        def fail(status: int, obj: dict, replica_id: str
                 ) -> tuple[int, dict]:
            # transport failures (0) and dispatch timeouts (-1) leave
            # the router as a retryable 503 — the client-facing
            # contract is the typed ladder, not internal sentinels
            if status in (0, -1):
                obj = dict(obj)
                obj.setdefault("error", "dispatch failed")
                obj["error_kind"] = "ReplicaUnavailableError"
                status = 503
            return status, annotate(obj, replica_id)

        last_failure: Optional[tuple[int, dict, str]] = None
        while True:
            replica, trial, skipped = self._pick(tried)
            rerouted = rerouted or skipped
            if replica is None:
                act = self.activator
                if act is not None and last_failure is None:
                    # scale-from-zero: no routable replica and nothing
                    # failed yet — park on the activator (whose park
                    # pokes the control loop) and re-pick on capacity.
                    # Total held time is bounded by the activator's
                    # max_hold_s however many wake/re-park rounds the
                    # race takes; past the deadline the request falls
                    # through to the retryable-unplaceable contract.
                    if hold_deadline is None:
                        hold_deadline = (time.monotonic()
                                         + act.max_hold_s)
                        self._bump("activator_held")
                    hold_wall, hold_t0 = time.time(), time.monotonic()
                    if (time.monotonic() < hold_deadline
                            and act.hold(deadline=hold_deadline)):
                        self._bump("activator_replayed")
                        if ctx is not None:
                            # the scale-from-zero hold window is a span
                            # of its own — cold-start wait must never
                            # masquerade as router queue time
                            dtrace.add_span(
                                ctx.trace_id, dtrace.new_span_id(),
                                ctx.span_id, "activator_hold",
                                ts=hold_wall,
                                dur_s=time.monotonic() - hold_t0,
                                replayed=True)
                            dtrace.note_keep(ctx.trace_id,
                                             "activator_held")
                        continue
                    if ctx is not None:
                        dtrace.add_span(
                            ctx.trace_id, dtrace.new_span_id(),
                            ctx.span_id, "activator_hold",
                            ts=hold_wall,
                            dur_s=time.monotonic() - hold_t0,
                            replayed=False)
                self._bump("unplaceable")
                _M_UNPLACEABLE.inc()
                if last_failure is not None:
                    # candidates ran out mid-retry: the annotated last
                    # failure keeps the dispatch cost reportable (a
                    # 503 that burned several attempts must not read
                    # as one)
                    return fail(*last_failure)
                raise ReplicaUnavailableError(
                    f"no active replica for {path} "
                    f"({len(self.replicas)} configured, "
                    f"{len(tried)} already tried); retry",
                    retry_after_s=self.cfg.probe_interval_s)
            self._bump("dispatches")
            dispatches += 1
            winner = replica.id
            try:
                faults.fire("fleet.dispatch")
                status, obj, was_hedged, won_by_hedge, winner = \
                    self._dispatch_one(replica, path, body, hedge_body,
                                       rid, trial, tried, ctx, retries)
            except faults.FaultError as e:
                # injected dispatch failure: contained to this request
                # and charged to nobody (the replica never saw it)
                if trial:
                    replica.health.release_trial()
                status, obj = 0, {"error": str(e)}
                was_hedged = won_by_hedge = False
            if was_hedged:
                dispatches += 1
            hedged = hedged or was_hedged
            hedge_win = hedge_win or won_by_hedge
            ok = status == 200
            if ok or (400 <= status < 500) or status == 504:
                # 4xx is the request's own problem and 504 a dead
                # deadline — neither improves on another replica
                if isinstance(obj, dict):
                    obj = annotate(obj, winner)
                    obj["fleet"]["retried_ok"] = ok and retries > 0
                if ok:
                    if retries:
                        self._bump("retried_ok")
                        _M_RETRIES.labels(outcome="ok").inc()
                    if rerouted:
                        self._bump("rerouted")
                return status, obj
            # a real failure (winner names the replica whose answer —
            # possibly the hedge's — this body came from)
            tried.append(replica)
            last_failure = (status, obj, winner)
            if not self._retryable(status, obj):
                return fail(*last_failure)
            if retries >= self.cfg.max_retries:
                _M_RETRIES.labels(outcome="failed").inc()
                return fail(*last_failure)
            if not self.retry_budget.try_take():
                self._bump("retry_budget_exhausted")
                _M_RETRIES.labels(outcome="budget_exhausted").inc()
                return fail(*last_failure)
            retries += 1
            self._bump("retries")

    def _dispatch_one(self, replica: Replica, path: str, body: bytes,
                      hedge_body: bytes, rid: Optional[str],
                      trial: bool, tried: list,
                      ctx: Optional[dtrace.TraceContext] = None,
                      attempt: int = 0
                      ) -> tuple[int, dict, bool, bool, str]:
        """One (possibly hedged) dispatch: primary on a worker thread,
        a mirror on the least-loaded OTHER replica if the request is
        still queued-not-admitted at ``hedge_after_s``; first success
        wins, the loser is cancelled through the ``cancel()`` path.
        With a trace context each leg is a sibling ``dispatch`` span
        (winner/loser/error/timeout tagged) whose span id rides the
        leg's Traceparent header, so the replica's tree parents into
        the exact leg that carried it.
        Returns (status, body, hedged, won_by_hedge, winner_id)."""
        results: "queue.SimpleQueue" = queue.SimpleQueue()
        #: tag -> (leg span id, wall start, monotonic start)
        leg_meta: dict[str, tuple[str, float, float]] = {}

        def start_leg(tag: str, rep: Replica, leg_body: bytes) -> None:
            headers = None
            if ctx is not None:
                sid = dtrace.new_span_id()
                leg_meta[tag] = (sid, time.time(), time.monotonic())
                headers = {dtrace.TRACEPARENT_HEADER:
                           ctx.child_wire(sid)}
            threading.Thread(
                target=self._call_replica,
                args=(rep, path, leg_body, results, tag, headers),
                daemon=True, name=f"dispatch-{rep.id}").start()

        def close_leg(tag: str, rep: Replica, outcome: str) -> None:
            meta = leg_meta.pop(tag, None)
            if ctx is None or meta is None:
                return
            sid, wall0, t0 = meta
            dtrace.add_span(ctx.trace_id, sid, ctx.span_id, "dispatch",
                            ts=wall0, dur_s=time.monotonic() - t0,
                            replica=rep.id, leg=tag, outcome=outcome,
                            retry=attempt)

        start_leg("primary", replica, body)
        pending = {"primary": replica}
        hedge_replica: Optional[Replica] = None
        hedge_trial = False
        deadline = time.monotonic() + self.cfg.dispatch_timeout_s
        hedge_delay = self._hedge_delay(replica.health.role)
        hedge_at = (time.monotonic() + hedge_delay
                    if hedge_delay is not None else None)
        first_failure: Optional[tuple[int, dict]] = None
        while pending:
            now = time.monotonic()
            wake = deadline if hedge_at is None else min(deadline,
                                                         hedge_at)
            try:
                tag, rep, status, obj, _dt = results.get(
                    timeout=max(wake - now, 0.001))
            except queue.Empty:
                if time.monotonic() >= deadline:
                    break  # overall dispatch timeout
                if hedge_at is not None and time.monotonic() >= hedge_at:
                    hedge_at = None  # fire at most one hedge
                    hedge_replica, hedge_trial = self._maybe_hedge(
                        replica, path, hedge_body, rid, tried, results,
                        start_leg)
                    if hedge_replica is not None:
                        pending["hedge"] = hedge_replica
                continue
            del pending[tag]
            is_trial = trial if tag == "primary" else hedge_trial
            ok = status == 200
            # 4xx and 504 are the *request's* problem — the replica
            # answered correctly, so its health is not dinged
            event = rep.health.note_result(
                ok or (400 <= status < 500) or status == 504,
                trial=is_trial)
            self._note_dispatch_metrics(rep, status, event)
            if ok:
                close_leg(tag, rep,
                          "win" if hedge_replica is not None else "ok")
                self._observe_ttft(rep, obj)
                # winner: cancel the losing leg through cancel(); a
                # loser holding a half-open trial claim gets it back —
                # its result will never be consumed, and a leaked
                # claim would park the replica in half_open forever
                for other_tag, other in pending.items():
                    other.cancel(rid)
                    close_leg(other_tag, other, "cancelled")
                    if (trial if other_tag == "primary"
                            else hedge_trial):
                        other.health.release_trial()
                if hedge_replica is not None and tag == "primary":
                    _M_HEDGES.labels(outcome="loss").inc()
                if tag == "hedge":
                    self._bump("hedge_wins")
                    _M_HEDGES.labels(outcome="win").inc()
                return (status, obj, hedge_replica is not None,
                        tag == "hedge", rep.id)
            close_leg(tag, rep, "error")
            if first_failure is None or status != 0:
                first_failure = (status, obj, rep.id)
            if rep is not replica:
                # a failed HEDGE replica is just as tried as a failed
                # primary: the retry ladder must not bounce straight
                # back onto it
                tried.append(rep)
            # a failed leg: keep waiting for the other, if any
        hedged = hedge_replica is not None
        if pending:
            # dispatch timeout: whoever is still pending gets the
            # timeout strike and a best-effort cancel (their worker
            # threads finish into the void; in-flight accounting
            # follows them down)
            for tag, rep in pending.items():
                is_trial = trial if tag == "primary" else hedge_trial
                event = rep.health.note_result(False, timeout=True,
                                               trial=is_trial)
                self._note_dispatch_metrics(rep, -1, event)
                rep.cancel(rid)
                close_leg(tag, rep, "timeout")
                if rep is not replica:
                    # a hedge replica pending at the deadline is as
                    # tried as the primary — the retry must not burn
                    # another full timeout on a replica that just hung
                    tried.append(rep)
            return -1, {"error": f"dispatch timed out after "
                                 f"{self.cfg.dispatch_timeout_s:.1f}s "
                                 f"on {replica.id}"}, hedged, False, \
                replica.id
        status, obj, failed_id = first_failure or (
            0, {"error": "dispatch produced no result"}, replica.id)
        return status, obj, hedged, False, failed_id

    def _hedge_delay(self, role: str) -> Optional[float]:
        """The Tail-at-Scale adaptive hedge trigger: ``hedge_ttft_
        factor`` × the rolling per-role TTFT quantile, floored at the
        fixed ``hedge_after_s`` knob.  ``hedge_after_s is None`` keeps
        hedging disabled (backward compat — the digest never *enables*
        hedging, it only tunes the delay); a cold or thin digest falls
        back to the floor."""
        base = self.cfg.hedge_after_s
        if base is None or self.cfg.hedge_ttft_quantile is None:
            return base
        digest = self._ttft_digests.get(role)
        if digest is None:
            return base
        q = digest.quantile(self.cfg.hedge_ttft_quantile,
                            min_samples=self.cfg.hedge_ttft_min_samples)
        if q is None:
            return base
        return max(base, q * self.cfg.hedge_ttft_factor)

    def _observe_ttft(self, replica: Replica, obj: Mapping[str, Any]
                      ) -> None:
        """Feed the winning response's per-prediction ``ttft_s`` into
        the replica's role digest (what ``_hedge_delay`` consults)."""
        preds = obj.get("predictions") if isinstance(obj, dict) else None
        if not isinstance(preds, list):
            return
        role = replica.health.role
        digest = self._ttft_digests.get(role)
        if digest is None:
            digest = self._ttft_digests.setdefault(
                role,
                RollingDigest(window_s=self.cfg.hedge_ttft_window_s))
        trace_id = obj.get("trace_id")
        for p in preds:
            ttft = p.get("ttft_s") if isinstance(p, dict) else None
            if ttft is not None:
                digest.observe(float(ttft))
                # exemplar ride-along for the fleet TTFT view: the
                # worst observed TTFTs keep their trace ids, served at
                # /debug/trace — "why was this request slow" is a curl
                dtrace.note_exemplar("ttft", float(ttft), trace_id)

    def _maybe_hedge(self, primary: Replica, path: str,
                     hedge_body: bytes, rid: Optional[str],
                     tried: Sequence[Replica],
                     results: "queue.SimpleQueue", start_leg
                     ) -> tuple[Optional[Replica], bool]:
        """Fire the hedge if the request is still queued-not-admitted
        on the primary (phase None = not even submitted yet counts;
        remote replicas report None and hedge on time alone) and a
        healthy second replica exists.  The hedge leg carries the
        ``-h``-suffixed request id and its own leg span (sibling of
        the primary's) via ``start_leg``."""
        if primary.request_phase(rid) == "active":
            return None, False  # decoding: its tokens are being paid for
        exclude = list(tried) + [primary]
        hedge, hedge_trial, _ = self._pick(exclude)
        if hedge is None:
            return None, False
        self._bump("hedges")
        self._bump("dispatches")
        start_leg("hedge", hedge, hedge_body)
        return hedge, bool(hedge_trial)

    def _note_dispatch_metrics(self, replica: Replica, status: int,
                               event: Optional[str]) -> None:
        if status == -1:
            outcome = "timeout"
        elif status == 200 or (400 <= status < 500) or status == 504:
            outcome = "ok"  # the replica answered; the answer may
            # still be the request's own 4xx/expired-deadline problem
        else:
            outcome = "error"
        replica._m_dispatch[outcome].inc()
        if event == "recovered":
            log.info("%s: half-open trial succeeded; active again",
                     replica.id)
            _M_RECOVERIES.labels(replica=replica.id).inc()
        elif event is not None:
            log.warning("%s: ejected (cause=%s)", replica.id, event)
            _M_EJECTIONS.labels(replica=replica.id, cause=event).inc()
        self._refresh_state_gauge()

    # -- data-plane overrides ----------------------------------------------

    def _map_fleet_error(self, e: Exception) -> tuple[int, dict]:
        body = {"error": str(e), "error_kind": type(e).__name__}
        retry_after = getattr(e, "retry_after_s", None)
        if retry_after is not None:
            body["retry_after_s"] = round(float(retry_after), 3)
        return 503, body

    def _predict(self, name: str, payload: dict) -> tuple[int, dict]:
        try:
            return self._fleet_call(f"/v1/models/{name}:predict", payload)
        except RetryableError as e:  # ReplicaUnavailableError et al.
            return self._map_fleet_error(e)

    def _completion(self, payload: dict) -> tuple[int, dict]:
        try:
            return self._fleet_call("/completion", payload)
        except RetryableError as e:
            return self._map_fleet_error(e)

    def _cancel(self, name: str, payload: dict) -> tuple[int, dict]:
        """Cancel fans out: the router does not track which replica
        holds the id (retries/hedges may have touched several)."""
        rid = payload.get("request_id")
        cancelled = False
        path = f"/v1/models/{name}:cancel"
        for r in self.replicas:
            try:
                status, obj = r.call("POST", path,
                                     json.dumps({"request_id": rid})
                                     .encode())
                cancelled = cancelled or bool(
                    isinstance(obj, dict) and obj.get("cancelled"))
            except Exception:  # noqa: BLE001 - best-effort fan-out
                log.debug("%s: cancel fan-out failed", r.id)
        return 200, {"cancelled": cancelled}

    # -- read-plane overrides ----------------------------------------------

    def _route(self, method: str, path: str, body: bytes,
               headers: Optional[Mapping[str, str]] = None
               ) -> tuple[int, dict]:
        if method == "GET":
            p = path.partition("?")[0]
            if p == "/v1/models":
                names = sorted({n for r in self.replicas
                                for n in r.model_names()})
                return 200, {"models": names}
            if (p.startswith("/v1/models/") and ":" not in p):
                name = p[len("/v1/models/"):]
                known = any(name in r.model_names()
                            for r in self.replicas)
                if not known:
                    return 404, {"error": f"model {name} not found"}
                ready = any(r.health.state in (ACTIVE, HALF_OPEN)
                            and name in r.model_names()
                            for r in self.replicas)
                return 200, {"name": name, "ready": ready}
        return super()._route(method, path, body, headers)

    def _readyz(self) -> tuple[int, dict]:
        """The fleet is ready while ANY replica can take traffic; the
        body carries every replica's health detail plus the shared
        clock, so ``curl /readyz`` alone tells a brown-out from a
        rolling restart from a dead fleet."""
        if self._draining:
            return 503, {"status": "draining"}
        detail = {r.id: r.snapshot() for r in self.replicas}
        ok = any(r.health.state in (ACTIVE, HALF_OPEN)
                 for r in self.replicas)
        return (200 if ok else 503), {
            "status": "ready" if ok else "unready",
            "fleet": True,
            "replicas": detail,
            "retry_budget": round(self.retry_budget.level, 2),
            "clock": self.clock.snapshot(),
        }

    # -- rolling restart ---------------------------------------------------

    def rolling_restart(self) -> dict:
        """Zero-drop rolling restart: drain → transplant → rebuild →
        probe → reinstate, one replica at a time (a weight/config
        rollout that never drops a queued request).  Requests racing
        the drain window fail retryable and are absorbed by the retry
        ladder.  Remote replicas are skipped — their restarts belong
        to the cluster orchestrator; this router just routes around
        them via health."""
        with self._restart_lock:
            report = []
            for r in self.replicas:
                if not r.restartable:
                    report.append({"replica": r.id, "skipped": "remote"})
                    continue
                t0 = time.monotonic()
                r.health.begin_drain()
                self._refresh_state_gauge()
                moved = self._transplant_from(r)
                r.restart()
                healthy = self._wait_healthy(r)
                if healthy:
                    r.health.force_active()
                self._refresh_state_gauge()
                took = time.monotonic() - t0
                report.append({"replica": r.id, "transplanted": moved,
                               "healthy": healthy,
                               "took_s": round(took, 3)})
                if not healthy:
                    # leave the replica ejected and STOP the sweep: a
                    # rollout that bricks replicas must not march on
                    r.health.eject("probe")
                    _M_EJECTIONS.labels(replica=r.id,
                                        cause="probe").inc()
                    self._refresh_state_gauge()
                    log.error("%s: did not come back healthy; rolling "
                              "restart halted", r.id)
                    break
            else:
                self._bump("rolling_restarts")
                _M_ROLLING.inc()
            return {"replicas": report,
                    "completed": all("skipped" in e or e.get("healthy")
                                     for e in report)}

    def _transplant_from(self, source: Replica) -> int:
        """Move the draining replica's never-claimed queue into its
        peers through the engines' requeue() path — the waiters'
        ``req.engine`` follows, so their in-flight HTTP threads
        complete against the new replica transparently."""
        extract = getattr(source, "extract_queued", None)
        if extract is None:
            return 0
        moved = 0
        for model_name, reqs in extract():
            for req in reqs:
                placed = False
                for target in sorted(
                        (t for t in self.replicas
                         if t is not source
                         and t.health.state in (ACTIVE, HALF_OPEN)),
                        key=lambda t: t.load_score()):
                    requeue = getattr(target, "requeue", None)
                    if requeue is not None and requeue(model_name, req):
                        placed = True
                        break
                if placed:
                    moved += 1
                    # a transplanted request's trace is tail-retained
                    # (the engine's requeue() span marks it too; this
                    # covers requests bound at the router door)
                    tctx = dtrace.context_for(req.request_id)
                    if tctx is not None:
                        dtrace.note_keep(tctx.trace_id, "transplanted")
                else:
                    # no in-process peer serves this model: fail it
                    # retryable so the waiter's own retry (or the
                    # client's) re-enters through the router.  The
                    # engines' failure idiom closes the token stream
                    # too — a streaming consumer must see the sentinel
                    # now, not a 60 s StreamTimeoutError later.
                    from kubernetes_cloud_tpu.serve.continuous import (
                        _STREAM_END,  # lazy: keeps fleet.py jax-free
                    )

                    req.error = ReplicaUnavailableError(
                        "replica draining for rolling restart; retry")
                    obs.tracing.trace(
                        req.request_id, "failed", model=model_name,
                        error=type(req.error).__name__)
                    req.stream.put(_STREAM_END)
                    req.event.set()
        if moved:
            self._bump("transplanted", moved)
            _M_TRANSPLANTED.labels(replica=source.id).inc(moved)
        return moved

    def _wait_healthy(self, r: Replica) -> bool:
        deadline = time.monotonic() + self.cfg.restart_probe_timeout_s
        while time.monotonic() < deadline:
            try:
                status, body = r.probe(self.cfg.probe_timeout_s)
                healthy, depth, _age, _role, _wv = _probe_healthy(
                    status, body, self.cfg.heartbeat_stale_s)
            except Exception:  # noqa: BLE001 - keep probing to deadline
                healthy, depth = False, 0
            if healthy:
                r._m_queue.set(depth)
                attach = getattr(r, "attach_clock", None)
                if attach is not None:
                    attach(self.clock)
                return True
            time.sleep(min(0.05, self.cfg.probe_interval_s))
        return False

    # -- distributed-trace assembly ----------------------------------------

    def _trace_sampling_authority(self, ctx) -> bool:
        """The router is ALWAYS the retention authority: a client-
        minted traceparent gives the router's context a parent, but
        the client has no span store to decide in — the buck stops
        here (replicas see a router-parented context and defer)."""
        return True

    def _trace_spans(self, trace_id: str) -> Optional[list]:
        """The assembler: the router's own spans plus a pull of
        ``GET /debug/trace/<id>`` from every replica (the ones that
        served the trace answer with their side of the tree; the rest
        404).  In-process replicas share this store — merge_spans
        dedups by span id.  A failing replica pull degrades to a
        partial tree, never an error."""
        spans = list(dtrace.store().spans_for(trace_id) or [])
        for r in self.replicas:
            try:
                status, obj = r.call("GET", f"/debug/trace/{trace_id}",
                                     b"")
                if status == 200 and isinstance(obj, dict):
                    spans.extend(obj.get("spans") or [])
            except Exception:  # noqa: BLE001 - partial tree over error
                log.debug("%s: trace pull failed", r.id)
        return spans or None

    # -- introspection -----------------------------------------------------

    def snapshot(self) -> dict:
        return {"replicas": [r.snapshot() for r in self.replicas],
                "stats": dict(self.stats),
                "retry_budget": round(self.retry_budget.level, 2),
                "clock": self.clock.snapshot()}


def jain_fairness(values: Sequence[float]) -> float:
    """Jain index over per-tenant fleet-wide weighted service (the
    acceptance metric the bench reports); 1.0 = perfectly fair."""
    vals = [float(v) for v in values if v is not None]
    if not vals or not any(vals):
        return 1.0
    return (sum(vals) ** 2) / (len(vals) * sum(v * v for v in vals))
