"""Region-scale traffic simulator for the elastic autoscaler.

The live fleet tests can exercise scale-from-zero and a flash crowd at
the scale of a laptop: a handful of replicas, seconds of traffic.  The
paper's regime is the other end — a region of Knative services riding
multi-hour diurnal load with occasional flash crowds, where the
question is not "does the control loop work" but "what does it COST":
cost-normalized goodput, SLO-violation minutes, how long a scale
reaction takes.  This module answers at that scale by simulation:

* **Workload** (:class:`WorkloadConfig`): an open-loop inhomogeneous
  Poisson arrival process — diurnal sinusoid × :class:`FlashCrowd`
  multipliers — thinning-sampled (:func:`~kubernetes_cloud_tpu.serve.
  trace.thinning_arrivals`), with every request drawn from a Zipf
  population of millions of users via O(1) inversion
  (:func:`~kubernetes_cloud_tpu.serve.trace.zipf_user`).  Everything
  derives from one seed; the same config reproduces the same run
  bit-for-bit.
* **Fleet model** (:class:`SimFleet` / :class:`_Pool`): per-role pools
  of :class:`SimReplica` s — slot-limited servers with configured
  prefill/decode token rates and measured-jitter cold starts — behind
  a pool FIFO (the router queue: freshly-ready replicas absorb the
  backlog, which is what the live router's transplant/least-loaded
  machinery does).  An empty pool holds arrivals activator-style and
  replays them when the first replica turns ready; a hold outliving
  ``max_hold_s`` is a **dropped** request (the acceptance criterion
  says the autoscaled arm must never produce one).  Optionally
  disaggregated: prefill pool → decode pool as a two-stage tandem
  queue, each sized by its own :class:`~kubernetes_cloud_tpu.serve.
  autoscaler.RolePolicy`.
* **The real controller**: :class:`SimFleet` implements
  :class:`~kubernetes_cloud_tpu.serve.autoscaler.ScalingTarget`, so
  the simulator steps the ACTUAL :class:`~kubernetes_cloud_tpu.serve.
  autoscaler.Autoscaler` — panic windows, pre-warming, hysteresis,
  measured cold-start feedback and all — under a virtual clock.  The
  BENCHMARKS.md numbers exercise the shipping control loop, not a
  model of it.
* **Report** (:func:`run_scenario` / :func:`compare_fleets`):
  per-request TTFT/TPOT against the SLO, **cost-normalized goodput**
  (SLO-meeting output tokens per replica-second paid),
  **SLO-violation minutes** (wall minutes whose completions miss the
  attainment bar), per-flash-crowd **reaction** (first scale-up after
  onset) and **recovery** (backlog back under the pool's target)
  times.  ``compare_fleets`` runs the same workload through the
  autoscaled fleet, a fixed minimal fleet, and a fixed peak-sized
  fleet — the A/B/C lane ``bench_serving --autoscale`` publishes.

The simulator is pure Python + ``random`` — no jax, no threads, no
wall clock — so the tier-1 smoke scenario finishes in well under a
second and the multi-hour region runs are just bigger loops
(``@pytest.mark.slow``).
"""

from __future__ import annotations

import dataclasses
import heapq
import math
import random
from collections import deque
from typing import Callable, Mapping, Optional, Sequence

from kubernetes_cloud_tpu.serve.autoscaler import (
    Autoscaler,
    AutoscalerConfig,
    PoolSignals,
    RolePolicy,
    ScalingTarget,
)
from kubernetes_cloud_tpu.serve.trace import thinning_arrivals, zipf_user


class VirtualClock:
    """The simulation's time source (monotonic, manually advanced).
    Injected as the :class:`Autoscaler`'s ``clock`` so the control
    loop's windows, cooldowns, and cold-start math run entirely in
    simulated time — a 4-hour region day replays in seconds."""

    def __init__(self, t0: float = 0.0):
        self._now = float(t0)

    def now(self) -> float:
        return self._now

    def advance_to(self, t: float) -> None:
        if t < self._now:
            raise ValueError("virtual clock cannot go backwards")
        self._now = t


@dataclasses.dataclass(frozen=True)
class FlashCrowd:
    """One flash-crowd event: the arrival rate multiplies by
    ``multiplier``, ramping linearly over ``ramp_s`` at each edge."""

    at_s: float
    duration_s: float
    multiplier: float = 6.0
    ramp_s: float = 10.0

    def __post_init__(self):
        if self.at_s < 0 or self.duration_s <= 0:
            raise ValueError("flash crowd timing must be >= 0 / > 0")
        if self.multiplier < 1.0:
            raise ValueError("multiplier must be >= 1")
        if self.ramp_s < 0 or 2 * self.ramp_s > self.duration_s:
            raise ValueError("ramps must fit inside the crowd")

    def multiplier_at(self, t: float) -> float:
        dt = t - self.at_s
        if dt < 0 or dt > self.duration_s:
            return 1.0
        if self.ramp_s > 0:
            edge = min(dt, self.duration_s - dt, self.ramp_s) \
                / self.ramp_s
        else:
            edge = 1.0
        return 1.0 + (self.multiplier - 1.0) * edge


@dataclasses.dataclass(frozen=True)
class WorkloadConfig:
    """The open-loop region workload: diurnal sinusoid × flash
    crowds, Zipf users, mixed request shapes."""

    duration_s: float = 600.0
    base_rps: float = 4.0
    diurnal_period_s: float = 600.0
    diurnal_amplitude: float = 0.6
    flash_crowds: tuple[FlashCrowd, ...] = ()
    n_users: int = 1_000_000
    zipf_s: float = 1.3
    #: uniform prompt / output token ranges (inclusive)
    prompt_tokens: tuple[int, int] = (16, 96)
    output_tokens: tuple[int, int] = (8, 48)
    seed: int = 0

    def __post_init__(self):
        if self.duration_s <= 0 or self.base_rps <= 0:
            raise ValueError("duration_s and base_rps must be > 0")
        if not 0 <= self.diurnal_amplitude < 1:
            raise ValueError("diurnal_amplitude must be in [0, 1)")
        if self.diurnal_period_s <= 0:
            raise ValueError("diurnal_period_s must be > 0")
        for lo, hi in (self.prompt_tokens, self.output_tokens):
            if lo < 1 or hi < lo:
                raise ValueError("token ranges must be 1 <= lo <= hi")
        for fc in self.flash_crowds:
            if fc.at_s + fc.duration_s > self.duration_s:
                raise ValueError("flash crowd exceeds the workload")

    def rate(self, t: float) -> float:
        lam = self.base_rps * (1.0 + self.diurnal_amplitude * math.sin(
            2 * math.pi * t / self.diurnal_period_s))
        for fc in self.flash_crowds:
            lam *= fc.multiplier_at(t)
        return max(lam, 0.0)

    def rate_max(self) -> float:
        peak = self.base_rps * (1.0 + self.diurnal_amplitude)
        for fc in self.flash_crowds:
            peak *= fc.multiplier
        return peak

    def sample(self, rng: random.Random) -> list["SimRequest"]:
        times = thinning_arrivals(rng, self.duration_s, self.rate,
                                  self.rate_max())
        plo, phi = self.prompt_tokens
        olo, ohi = self.output_tokens
        return [SimRequest(
            rid=i, t_arrive=t,
            user=zipf_user(rng, self.n_users, self.zipf_s),
            prompt_tokens=rng.randint(plo, phi),
            max_new_tokens=rng.randint(olo, ohi),
        ) for i, t in enumerate(times)]


@dataclasses.dataclass(frozen=True)
class ReplicaModel:
    """What one simulated replica can do (calibrate from the fused
    decode bench: tokens/s per slot, not per chip)."""

    slots: int = 4
    prefill_tps: float = 2000.0
    decode_tps: float = 40.0
    cold_start_s: float = 8.0
    #: uniform ±fraction jitter on each cold start (what the measured
    #: EWMA prior has to track)
    cold_start_jitter: float = 0.25

    def __post_init__(self):
        if self.slots < 1:
            raise ValueError("slots must be >= 1")
        if self.prefill_tps <= 0 or self.decode_tps <= 0:
            raise ValueError("token rates must be > 0")
        if self.cold_start_s <= 0:
            raise ValueError("cold_start_s must be > 0")
        if not 0 <= self.cold_start_jitter < 1:
            raise ValueError("cold_start_jitter must be in [0, 1)")


@dataclasses.dataclass(frozen=True)
class SloConfig:
    """Per-request SLOs and the per-minute attainment bar."""

    ttft_s: float = 2.5
    tpot_s: float = 0.1
    minute_attainment: float = 0.99

    def __post_init__(self):
        if self.ttft_s <= 0 or self.tpot_s <= 0:
            raise ValueError("SLOs must be > 0")
        if not 0 < self.minute_attainment <= 1:
            raise ValueError("minute_attainment must be in (0, 1]")


@dataclasses.dataclass(frozen=True)
class SimConfig:
    """Simulator mechanics (distinct from the workload and the
    controller under test)."""

    tick_s: float = 0.1
    #: activator bound: a request held this long with its pool still
    #: empty is dropped (the figure the acceptance criterion pins to
    #: zero for the autoscaled arm)
    max_hold_s: float = 30.0
    #: run past the last arrival to let in-flight work finish
    drain_grace_s: float = 120.0
    #: prefill pool → decode pool tandem instead of colocated
    disaggregated: bool = False
    replica: ReplicaModel = ReplicaModel()
    slo: SloConfig = SloConfig()

    def __post_init__(self):
        if self.tick_s <= 0:
            raise ValueError("tick_s must be > 0")
        if self.max_hold_s <= 0 or self.drain_grace_s < 0:
            raise ValueError("max_hold_s/drain_grace_s must be valid")


class SimRequest:
    """One request's lifecycle timestamps (filled in as it flows)."""

    __slots__ = ("rid", "t_arrive", "user", "prompt_tokens",
                 "max_new_tokens", "t_first", "t_done", "dropped")

    def __init__(self, rid: int, t_arrive: float, user: int,
                 prompt_tokens: int, max_new_tokens: int):
        self.rid = rid
        self.t_arrive = t_arrive
        self.user = user
        self.prompt_tokens = prompt_tokens
        self.max_new_tokens = max_new_tokens
        self.t_first: Optional[float] = None
        self.t_done: Optional[float] = None
        self.dropped = False


#: SimReplica lifecycle
_STARTING, _READY, _DRAINING, _GONE = "starting", "ready", "draining", \
    "gone"


class SimReplica:
    __slots__ = ("rid", "state", "ready_at", "active", "t_spawn")

    def __init__(self, rid: str, spawn_t: float, ready_at: float):
        self.rid = rid
        self.state = _STARTING
        self.t_spawn = spawn_t
        self.ready_at = ready_at
        self.active = 0  # in-service requests (slot occupancy)


class _Pool:
    """One role's replica pool: FIFO router queue in front of
    slot-limited replicas.  ``service(req)`` returns (ttft_offset_s,
    total_service_s) for this pool's stage."""

    def __init__(self, role: str, model: ReplicaModel,
                 service: Callable[[SimRequest], tuple[float, float]]):
        self.role = role
        self.model = model
        self.service = service
        self.replicas: list[SimReplica] = []
        self.queue: deque[tuple[SimRequest, float]] = deque()
        self.arrivals = 0
        self.next_stage: Optional["_Pool"] = None
        self.scale_log: list[tuple[float, int]] = []  # (t, +n/-n)
        self._seq = 0

    # -- membership --------------------------------------------------------

    def ready(self) -> list[SimReplica]:
        return [r for r in self.replicas if r.state == _READY]

    def counts(self) -> tuple[int, int, int]:
        s = sum(1 for r in self.replicas if r.state == _STARTING)
        rd = sum(1 for r in self.replicas if r.state == _READY)
        d = sum(1 for r in self.replicas if r.state == _DRAINING)
        return rd, s, d

    def alive(self) -> int:
        return sum(1 for r in self.replicas if r.state != _GONE)

    def spawn(self, now: float, rng: random.Random) -> SimReplica:
        self._seq += 1
        j = self.model.cold_start_jitter
        cold = self.model.cold_start_s * (
            1.0 + rng.uniform(-j, j) if j else 1.0)
        rep = SimReplica(f"{self.role}-{self._seq}", now, now + cold)
        self.replicas.append(rep)
        self.scale_log.append((now, 1))
        return rep

    def drain(self, now: float, n: int) -> int:
        victims = sorted(self.ready(), key=lambda r: r.active)[:n]
        for r in victims:
            r.state = _DRAINING
            if r.active == 0:
                r.state = _GONE
            self.scale_log.append((now, -1))
        return len(victims)

    def mark_ready(self, now: float,
                   on_cold_start: Optional[Callable[[str, float], None]]
                   ) -> int:
        """STARTING replicas whose cold start elapsed turn READY; the
        measured duration feeds the controller's prior."""
        turned = 0
        for r in self.replicas:
            if r.state == _STARTING and r.ready_at <= now:
                r.state = _READY
                turned += 1
                if on_cold_start is not None:
                    on_cold_start(self.role, r.ready_at - r.t_spawn)
        return turned

    # -- data path ---------------------------------------------------------

    def submit(self, req: SimRequest, t: float) -> None:
        self.arrivals += 1
        self.queue.append((req, t))

    def in_system(self) -> int:
        return sum(r.active for r in self.replicas
                   if r.state in (_READY, _DRAINING)) + len(self.queue)

    def dispatch(self, now: float, done_heap: list, seq: list,
                 max_hold_s: float, dropped: list) -> None:
        """Pull queued work into free slots (least-loaded first); age
        out holds that outlived ``max_hold_s`` with the pool still
        empty — the activator's bound."""
        if not self.queue:
            return
        ready = self.ready()
        if not ready:
            while self.queue and now - self.queue[0][1] >= max_hold_s:
                req, _t = self.queue.popleft()
                req.dropped = True
                dropped.append(req)
            return
        while self.queue:
            rep = min(ready, key=lambda r: r.active)
            if rep.active >= self.model.slots:
                return
            req, _enq = self.queue.popleft()
            rep.active += 1
            ttft_off, svc = self.service(req)
            if req.t_first is None and ttft_off is not None:
                req.t_first = now + ttft_off
            seq[0] += 1
            heapq.heappush(done_heap,
                           (now + svc, seq[0], self, rep, req))

    def complete(self, rep: SimReplica, req: SimRequest, t: float
                 ) -> None:
        rep.active -= 1
        if rep.state == _DRAINING and rep.active == 0:
            rep.state = _GONE
        if self.next_stage is not None:
            self.next_stage.submit(req, t)
        else:
            req.t_done = t


class SimFleet(ScalingTarget):
    """The simulated fleet: one pool per role, implementing
    :class:`ScalingTarget` so the REAL autoscaler sizes it."""

    def __init__(self, cfg: SimConfig, rng: random.Random):
        self.cfg = cfg
        self.rng = rng
        self.on_cold_start: Optional[Callable[[str, float], None]] = None
        m = cfg.replica
        if cfg.disaggregated:
            prefill = _Pool(
                "prefill", m,
                lambda r: (r.prompt_tokens / m.prefill_tps,
                           r.prompt_tokens / m.prefill_tps))
            decode = _Pool(
                "decode", m,
                lambda r: (None, r.max_new_tokens / m.decode_tps))
            prefill.next_stage = decode
            self.pools = {"prefill": prefill, "decode": decode}
            self.admit_pool = prefill
        else:
            pool = _Pool(
                "colocated", m,
                lambda r: (r.prompt_tokens / m.prefill_tps,
                           r.prompt_tokens / m.prefill_tps
                           + r.max_new_tokens / m.decode_tps))
            self.pools = {"colocated": pool}
            self.admit_pool = pool
        self._done_heap: list = []
        self._seq = [0]
        self.dropped: list[SimRequest] = []

    def provision(self, counts: Mapping[str, int]) -> None:
        """Pre-warm ``counts[role]`` replicas, ready at t=0 (initial
        pools for every arm; the fixed arms never change them)."""
        for role, n in counts.items():
            pool = self.pools[role]
            for _ in range(n):
                rep = pool.spawn(0.0, self.rng)
                rep.ready_at = 0.0
                rep.state = _READY
            del pool.scale_log[:]  # provisioning is not a scale event

    # -- ScalingTarget ------------------------------------------------------

    def roles(self) -> Sequence[str]:
        return tuple(self.pools)

    def signals(self, role: str) -> PoolSignals:
        pool = self.pools[role]
        ready, starting, draining = pool.counts()
        qlen = len(pool.queue)
        active = sum(r.active for r in pool.replicas
                     if r.state in (_READY, _DRAINING))
        held = qlen if ready == 0 else 0
        return PoolSignals(
            ready=ready, starting=starting, draining=draining,
            concurrency=active + (qlen - held),
            activator_depth=held, arrivals=pool.arrivals)

    def scale_up(self, role: str, n: int) -> int:
        now = self._now
        for _ in range(max(n, 0)):
            self.pools[role].spawn(now, self.rng)
        return max(n, 0)

    def scale_down(self, role: str, n: int) -> int:
        return self.pools[role].drain(self._now, max(n, 0))

    # -- tick mechanics ------------------------------------------------------

    _now = 0.0

    def advance(self, now: float) -> None:
        """One tick: readiness transitions, completions up to ``now``
        (stage hops included), then queue→slot dispatch."""
        self._now = now
        for pool in self.pools.values():
            pool.mark_ready(now, self.on_cold_start)
        while self._done_heap and self._done_heap[0][0] <= now:
            t, _s, pool, rep, req = heapq.heappop(self._done_heap)
            pool.complete(rep, req, t)
        for pool in self.pools.values():
            pool.dispatch(now, self._done_heap, self._seq,
                          self.cfg.max_hold_s, self.dropped)

    def in_system(self) -> int:
        return sum(p.in_system() for p in self.pools.values())

    def alive(self) -> int:
        return sum(p.alive() for p in self.pools.values())


def _percentile(xs: list[float], p: float) -> Optional[float]:
    if not xs:
        return None
    xs = sorted(xs)
    return round(xs[min(len(xs) - 1, int(p * len(xs)))], 4)


def run_scenario(workload: WorkloadConfig, sim: SimConfig, *,
                 mode: str = "autoscaled",
                 autoscaler_cfg: Optional[AutoscalerConfig] = None,
                 fixed_replicas: Optional[Mapping[str, int]] = None,
                 ) -> dict:
    """Replay ``workload`` against one fleet arm and report.

    ``mode="autoscaled"`` steps the real :class:`Autoscaler` (pools
    start at each role's ``min_replicas`` — possibly zero, arriving
    through the activator hold); ``mode="fixed"`` pins
    ``fixed_replicas`` for the whole run.  Deterministic for a given
    (workload, sim, controller) tuple."""
    if mode not in ("autoscaled", "fixed"):
        raise ValueError("mode must be autoscaled | fixed")
    rng = random.Random(workload.seed)
    requests = workload.sample(rng)
    clock = VirtualClock()
    fleet = SimFleet(sim, rng)
    scaler: Optional[Autoscaler] = None
    if mode == "autoscaled":
        cfg = autoscaler_cfg or AutoscalerConfig()
        scaler = Autoscaler(fleet, cfg, clock=clock.now)
        fleet.on_cold_start = scaler.note_cold_start
        fleet.provision({role: pol.min_replicas
                         for role, pol in cfg.roles.items()
                         if role in fleet.pools})
        ctrl_tick = cfg.tick_s
    else:
        if not fixed_replicas:
            raise ValueError("fixed mode needs fixed_replicas")
        fleet.provision(fixed_replicas)
        ctrl_tick = None

    horizon = workload.duration_s + sim.drain_grace_s
    tick = sim.tick_s
    i = 0
    t = 0.0
    next_ctrl = 0.0
    replica_seconds = 0.0
    while t < horizon:
        t = min(t + tick, horizon)
        clock.advance_to(t)
        fleet.advance(t)
        while i < len(requests) and requests[i].t_arrive <= t:
            fleet.admit_pool.submit(requests[i], requests[i].t_arrive)
            i += 1
        if scaler is not None and t >= next_ctrl:
            scaler.step(now=t)
            next_ctrl = t + ctrl_tick
        replica_seconds += fleet.alive() * tick
        if i >= len(requests) and fleet.in_system() == 0 \
                and not fleet._done_heap:
            break

    return _report(workload, sim, fleet, requests, scaler,
                   replica_seconds, mode)


def _report(workload: WorkloadConfig, sim: SimConfig, fleet: SimFleet,
            requests: list[SimRequest], scaler: Optional[Autoscaler],
            replica_seconds: float, mode: str) -> dict:
    slo = sim.slo
    done = [r for r in requests if r.t_done is not None]
    dropped = [r for r in requests if r.dropped]
    unfinished = len(requests) - len(done) - len(dropped)
    ttfts, tpots = [], []
    good_tokens = 0
    total_tokens = 0
    minute_total: dict[int, int] = {}
    minute_bad: dict[int, int] = {}
    for r in done:
        ttft = (r.t_first if r.t_first is not None else r.t_done) \
            - r.t_arrive
        tpot = (r.t_done - (r.t_first if r.t_first is not None
                            else r.t_arrive)) / max(r.max_new_tokens, 1)
        ttfts.append(ttft)
        tpots.append(tpot)
        ok = ttft <= slo.ttft_s and tpot <= slo.tpot_s
        total_tokens += r.max_new_tokens
        if ok:
            good_tokens += r.max_new_tokens
        minute = int(r.t_done // 60)
        minute_total[minute] = minute_total.get(minute, 0) + 1
        if not ok:
            minute_bad[minute] = minute_bad.get(minute, 0) + 1
    for r in dropped:  # a dropped request poisons its arrival minute
        minute = int(r.t_arrive // 60)
        minute_total[minute] = minute_total.get(minute, 0) + 1
        minute_bad[minute] = minute_bad.get(minute, 0) + 1
    violation_minutes = sum(
        1 for m, n in minute_total.items()
        if 1.0 - minute_bad.get(m, 0) / n < slo.minute_attainment)

    crowds = []
    for fc in workload.flash_crowds:
        reaction = recovery = None
        for pool in fleet.pools.values():
            for ts, delta in pool.scale_log:
                if delta > 0 and ts >= fc.at_s:
                    reaction = ts - fc.at_s if reaction is None \
                        else min(reaction, ts - fc.at_s)
                    break
        bad_after = [r.t_done for r in done
                     if r.t_done is not None and r.t_done >= fc.at_s
                     and ((r.t_first or r.t_done) - r.t_arrive
                          > slo.ttft_s)]
        if bad_after:
            recovery = max(bad_after) - fc.at_s
        elif done:
            recovery = 0.0
        crowds.append({
            "at_s": fc.at_s, "multiplier": fc.multiplier,
            "reaction_s": None if reaction is None
            else round(reaction, 3),
            "recovery_s": None if recovery is None
            else round(recovery, 3),
        })

    out = {
        "mode": mode,
        "requests": len(requests),
        "completed": len(done),
        "dropped": len(dropped),
        "unfinished": unfinished,
        "users": len({r.user for r in requests}),
        "slo_attainment": round(good_tokens / total_tokens, 4)
        if total_tokens else None,
        "total_tokens": total_tokens,
        "good_tokens": good_tokens,
        "replica_seconds": round(replica_seconds, 1),
        "cost_normalized_goodput": round(
            good_tokens / replica_seconds, 4) if replica_seconds
        else 0.0,
        "slo_violation_minutes": violation_minutes,
        "minutes_observed": len(minute_total),
        "ttft_p50_s": _percentile(ttfts, 0.50),
        "ttft_p95_s": _percentile(ttfts, 0.95),
        "tpot_p95_s": _percentile(tpots, 0.95),
        "scale_ups": sum(1 for p in fleet.pools.values()
                         for _, d in p.scale_log if d > 0),
        "scale_downs": sum(1 for p in fleet.pools.values()
                           for _, d in p.scale_log if d < 0),
        "flash_crowds": crowds,
        "pools": {role: {"final_alive": pool.alive(),
                         "arrivals": pool.arrivals}
                  for role, pool in fleet.pools.items()},
    }
    if scaler is not None:
        out["autoscaler"] = scaler.snapshot()
    return out


def flash_crowd_workload(*, duration_s: float = 1800.0,
                         base_rps: float = 3.0,
                         flash_at_s: float = 600.0,
                         flash_duration_s: float = 240.0,
                         flash_multiplier: float = 8.0,
                         seed: int = 0) -> WorkloadConfig:
    """The canonical acceptance workload: a diurnal half-hour with one
    hard flash crowd in the middle (bench + tests share it)."""
    return WorkloadConfig(
        duration_s=duration_s, base_rps=base_rps,
        diurnal_period_s=duration_s, diurnal_amplitude=0.5,
        flash_crowds=(FlashCrowd(at_s=flash_at_s,
                                 duration_s=flash_duration_s,
                                 multiplier=flash_multiplier,
                                 ramp_s=20.0),),
        seed=seed)


def default_autoscaler_cfg(*, max_replicas: int = 16,
                           min_replicas: int = 1,
                           target_concurrency: float = 3.0,
                           role: str = "colocated"
                           ) -> AutoscalerConfig:
    """A reasonable single-role controller for simulator runs."""
    return AutoscalerConfig(
        tick_s=1.0, stable_window_s=30.0, panic_window_s=6.0,
        panic_threshold=1.5, scale_down_delay_s=30.0, cooldown_s=5.0,
        scale_to_zero_grace_s=60.0,
        roles={role: RolePolicy(min_replicas=min_replicas,
                                max_replicas=max_replicas,
                                target_concurrency=target_concurrency)})


def peak_replicas(workload: WorkloadConfig, sim: SimConfig,
                  target_concurrency: float = 3.0) -> int:
    """Little's-law peak sizing: replicas a fixed fleet needs to hold
    the SLO at the workload's PEAK rate (what the over-provisioned
    comparison arm pays for all day)."""
    m = sim.replica
    mean_prompt = sum(workload.prompt_tokens) / 2
    mean_out = sum(workload.output_tokens) / 2
    service_s = mean_prompt / m.prefill_tps + mean_out / m.decode_tps
    concurrency = workload.rate_max() * service_s
    return max(1, math.ceil(concurrency / target_concurrency))


def compare_fleets(workload: WorkloadConfig, sim: SimConfig, *,
                   autoscaler_cfg: Optional[AutoscalerConfig] = None,
                   min_fleet: int = 1,
                   peak_fleet: Optional[int] = None) -> dict:
    """The three-arm A/B/C the acceptance criterion names: the SAME
    workload through (a) the autoscaled fleet, (b) a fixed minimal
    fleet (cheap, drowns in the flash crowd), (c) a fixed peak-sized
    fleet (meets SLO, pays peak all day).  The autoscaled arm must
    beat BOTH on cost-normalized goodput, with zero drops."""
    cfg = autoscaler_cfg or default_autoscaler_cfg()
    role = next(iter(cfg.roles))
    if role not in ("colocated",) and not sim.disaggregated:
        raise ValueError("role-split controller needs disaggregated sim")
    if peak_fleet is None:
        pol = cfg.roles[role]
        peak_fleet = min(
            peak_replicas(workload, sim, pol.target_concurrency),
            pol.max_replicas)
    auto = run_scenario(workload, sim, mode="autoscaled",
                        autoscaler_cfg=cfg)
    fixed_min = run_scenario(workload, sim, mode="fixed",
                             fixed_replicas={role: min_fleet})
    fixed_peak = run_scenario(workload, sim, mode="fixed",
                              fixed_replicas={role: peak_fleet})
    g = "cost_normalized_goodput"
    return {
        "autoscaled": auto,
        "fixed_min": fixed_min,
        "fixed_peak": fixed_peak,
        "min_fleet": min_fleet,
        "peak_fleet": peak_fleet,
        "autoscaled_beats_min": auto[g] > fixed_min[g],
        "autoscaled_beats_peak": auto[g] > fixed_peak[g],
        "autoscaled_zero_drops": auto["dropped"] == 0,
    }
