"""Dynamic request batching — the Triton scheduler capability.

The reference serves GPT-J/NeoX through Triton's C++ dynamic batcher +
the FasterTransformer backend, configured by ``config.pbtxt``
(``online-inference/fastertransformer/download-weights-job-gptj.yml``:
``max_batch_size``, ``dynamic_batching``, per-model instance groups).
The TPU equivalent: requests queue on the HTTP threads; a single
dispatcher thread drains the queue, coalesces up to ``max_batch_size``
instances (waiting at most ``max_queue_delay_us`` for stragglers — same
knob names as config.pbtxt), runs ONE batched device program, and
scatters results back to the waiting requests.

Why this shape on TPU: one XLA program at batch N is far cheaper than N
programs at batch 1 (the MXU is depth-loaded), and a single dispatcher
matches the one-program-at-a-time device semantics that
``containerConcurrency``-style locks otherwise enforce.

Config file parity: :func:`load_model_config` reads the same fields from
a JSON rendering of config.pbtxt (``model_config.json``).
"""

from __future__ import annotations

import dataclasses
import json
import logging
import os
import queue
import threading
import time
from typing import Any, Callable, Mapping, Optional, Sequence

from kubernetes_cloud_tpu import faults, obs
from kubernetes_cloud_tpu.obs.flight import FlightRecorder
from kubernetes_cloud_tpu.obs.tracing import trace
from kubernetes_cloud_tpu.serve.errors import (  # noqa: F401 - re-export
    DeadlineExceededError,
    EngineDrainingError,
    QueueFullError,
    RetryableError,
)
from kubernetes_cloud_tpu.serve.model import (
    Model,
    parse_instances,
    request_deadline,
)
from kubernetes_cloud_tpu.serve.supervisor import Heartbeat

log = logging.getLogger(__name__)

# Dynamic-batcher metric families (the Triton scheduler counters, as a
# Prometheus surface; the in-process stats dict below stays for tests)
_M_BATCHES = obs.counter(
    "kct_batcher_batches_total", "Batches dispatched to the device.",
    ("model",))
_M_REQUESTS = obs.counter(
    "kct_batcher_requests_total", "Requests coalesced into batches.",
    ("model",))
_M_BATCH_SIZE = obs.histogram(
    "kct_batcher_batch_size", "Instances per dispatched batch.",
    ("model",), buckets=(1, 2, 4, 8, 16, 32, 64, 128))
_M_DISPATCH_S = obs.histogram(
    "kct_batcher_dispatch_seconds",
    "Wall time of one batched device dispatch.", ("model",))
_M_SHED = obs.counter(
    "kct_batcher_shed_total",
    "Requests shed while queued (expired deadline).", ("model",))
_M_QUEUE = obs.gauge(
    "kct_batcher_queue_depth", "Pending-request queue depth.", ("model",))


@dataclasses.dataclass(frozen=True)
class BatcherConfig:
    """config.pbtxt-equivalent knobs (names kept)."""

    max_batch_size: int = 8
    max_queue_delay_us: int = 5000  # dynamic_batching.max_queue_delay_...
    max_queue_size: int = 256

    def __post_init__(self):
        if self.max_batch_size < 1:
            raise ValueError("max_batch_size must be >= 1")
        if self.max_queue_delay_us < 0:
            raise ValueError("max_queue_delay_us must be >= 0")
        if self.max_queue_size < 1:
            raise ValueError("max_queue_size must be >= 1")


def load_model_config(model_dir: str) -> BatcherConfig:
    """Read ``model_config.json`` (the config.pbtxt analogue) if present."""
    path = os.path.join(model_dir, "model_config.json")
    if not os.path.exists(path):
        return BatcherConfig()
    with open(path) as f:
        raw = json.load(f)
    dyn = raw.get("dynamic_batching") or {}
    return BatcherConfig(
        max_batch_size=int(raw.get("max_batch_size", 8)),
        max_queue_delay_us=int(dyn.get("max_queue_delay_microseconds",
                                       5000)),
        max_queue_size=int(dyn.get("max_queue_size", 256)),
    )


class _Pending:
    __slots__ = ("instances", "params", "event", "result", "error",
                 "claimed", "deadline", "request_id")

    def __init__(self, instances: Sequence[Any], params: Mapping[str, Any],
                 deadline: Optional[float] = None,
                 request_id: Optional[str] = None):
        self.instances = list(instances)
        self.params = dict(params)
        self.event = threading.Event()
        self.result: Optional[list] = None
        self.error: Optional[Exception] = None
        #: set by the dispatcher when dequeued — a claimed request's batch
        #: WILL complete (and set event), even across stop()
        self.claimed = False
        #: absolute monotonic deadline (None = wait forever); expired
        #: entries are shed by the dispatcher instead of batched
        self.deadline = deadline
        #: correlation id for lifecycle spans (None = untraced)
        self.request_id = request_id


class BatchingModel(Model):
    """Wrap a ``predict_batch(instances, params) -> list`` callable (or an
    inner Model) with dynamic batching.  Serve it with
    :class:`~kubernetes_cloud_tpu.serve.server.ModelServer` like any other
    model; the ``self_batching`` class attribute below makes the server
    skip its per-model request lock automatically (the dispatcher thread
    serializes device access itself — a lock would prevent requests from
    ever being concurrent enough to coalesce)."""

    #: ModelServer checks this attribute to skip its per-model lock.
    self_batching = True

    def __init__(self, name: str, inner: Model | Callable,
                 cfg: BatcherConfig = BatcherConfig()):
        super().__init__(name)
        self.cfg = cfg
        self.inner = inner
        self._queue: "queue.Queue[_Pending]" = queue.Queue(
            maxsize=cfg.max_queue_size)
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._held: Optional[_Pending] = None  # didn't fit/merge last batch
        #: beaten once per dispatch cycle; the supervisor's watchdog
        #: reads it (stale + live thread = wedged inner model call)
        self.heartbeat = Heartbeat()
        # Dispatcher generation: a supervisor restart bumps it so an
        # abandoned (wedged) dispatcher that eventually wakes exits
        # instead of racing the replacement for the queue.
        self._gen = 0
        #: the batch currently executing (supervisor fails it on restart)
        self._current_batch: list[_Pending] = []
        # batching telemetry (the Triton metrics a load test reads)
        self.stats = {"requests": 0, "batches": 0, "batched_instances": 0,
                      "deadline_shed": 0}
        #: coarse flight recorder: one record per dispatched batch
        #: (phases: "admit" = straggler coalescing wait, "decode" =
        #: the batched device dispatch) — the batch-level counterpart
        #: of the engine's per-iteration ring, served by the same
        #: GET /debug/timeline.  Survives dispatcher restarts (the
        #: model object owns it, like stats).
        self.flight = FlightRecorder(256, request_capacity=0)
        # scrape-facing mirror, label-bound once per model
        m = {"model": name}
        self._m_batches = _M_BATCHES.labels(**m)
        self._m_requests = _M_REQUESTS.labels(**m)
        self._m_batch_size = _M_BATCH_SIZE.labels(**m)
        self._m_dispatch_s = _M_DISPATCH_S.labels(**m)
        self._m_shed = _M_SHED.labels(**m)
        self._m_queue = _M_QUEUE.labels(**m)

    # -- lifecycle ---------------------------------------------------------

    def load(self) -> None:
        if self._thread is not None and self._thread.is_alive():
            if self._stop.is_set():
                # a previous stop() timed out mid-batch; two dispatchers
                # would race the queue and the device.  Typed retryable
                # (503): the old batch finishes on its own (KCT-ERR-004).
                raise EngineDrainingError(
                    "previous dispatcher still running; call stop() again")
            self.ready = True  # already loaded and dispatching
            return
        if isinstance(self.inner, Model) and not self.inner.ready:
            self.inner.load()
        self._stop.clear()  # support stop() -> load() restart
        # Requests enqueued in the stop/restart race window are stale:
        # their callers already received "batcher stopped".
        while True:
            try:
                stale = self._queue.get_nowait()
            except queue.Empty:
                break
            stale.error = RetryableError("batcher restarted")
            trace(stale.request_id, "failed", model=self.name,
                  error="RetryableError")
            stale.event.set()
        self._thread = threading.Thread(target=self._safe_dispatch_loop,
                                        args=(self._gen,), daemon=True,
                                        name=f"batcher-{self.name}")
        self._thread.start()
        self.ready = True

    def stop(self, timeout: float = 5.0) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=timeout)
            if self._thread.is_alive():
                # ready flips False over a live dispatcher; load() refuses
                # to start a second one until it actually exits — surface
                # that instead of letting load() discover it later.
                log.warning(
                    "batcher %s dispatcher did not stop within %.1f s "
                    "(batch still executing); call stop() again before "
                    "load()", self.name, timeout)
        self.ready = False

    # -- supervision -------------------------------------------------------

    def restart_dispatcher(self, err: Exception) -> int:
        """Supervisor restart path: abandon the current dispatcher (it
        may be wedged inside a batch — unjoinable), fail the work it had
        claimed with the retryable ``err``, and start a fresh dispatcher
        over the same queue.  Unclaimed queued requests survive and are
        served by the replacement; returns how many."""
        self._gen += 1  # wedged loop exits when (if) it wakes
        batch, self._current_batch = list(self._current_batch), []
        held, self._held = self._held, None
        for p in batch + ([held] if held is not None else []):
            p.error = err
            trace(p.request_id, "failed", model=self.name,
                  error=type(err).__name__)
            p.event.set()
        self._stop.clear()
        self._thread = threading.Thread(target=self._safe_dispatch_loop,
                                        args=(self._gen,), daemon=True,
                                        name=f"batcher-{self.name}")
        self._thread.start()
        self.ready = True
        return self._queue.qsize()

    def abandon_dispatcher(self, err: Exception) -> None:
        """Circuit-open path: no replacement — fail everything."""
        self._gen += 1
        # Set _stop BEFORE draining (mirrors the engine's abandon): a
        # predict() racing this shutdown either fails its entry check,
        # gets failed by its own post-enqueue recheck, or escapes via
        # the waiter loop's _stop condition — without this flag all
        # three guards stay dark and the straggler hangs forever.
        self._stop.set()
        batch, self._current_batch = list(self._current_batch), []
        held, self._held = self._held, None
        leftovers = batch + ([held] if held is not None else [])
        while True:
            try:
                leftovers.append(self._queue.get_nowait())
            except queue.Empty:
                break
        for p in leftovers:
            p.error = err
            trace(p.request_id, "failed", model=self.name,
                  error=type(err).__name__)
            p.event.set()
        self.ready = False

    def _local_health(self) -> dict:
        if not self.ready:
            return {"ok": False, "reason": "not loaded"}
        t = self._thread
        if t is None or not t.is_alive():
            return {"ok": False, "reason": "dispatcher dead"}
        return {"ok": True, "reason": "ok",
                "heartbeat_age_s": round(self.heartbeat.age, 3),
                "queue_depth": self._queue.qsize()}

    # -- request side ------------------------------------------------------

    def predict(self, payload: Mapping[str, Any]) -> dict:
        instances = parse_instances(payload)
        if len(instances) > self.cfg.max_batch_size:
            raise ValueError(
                f"request carries {len(instances)} instances > "
                f"max_batch_size {self.cfg.max_batch_size}")
        if self._stop.is_set() or not self.ready:
            raise RetryableError("batcher stopped")
        deadline = request_deadline(payload)
        if deadline is not None and time.monotonic() > deadline:
            raise DeadlineExceededError("deadline expired before admission")
        if faults.fire("queue") == "drop":
            raise QueueFullError("request queue full (injected)")
        pending = _Pending(instances, payload.get("parameters") or {},
                           deadline, request_id=payload.get("request_id"))
        # trace BEFORE the enqueue: once the pending is visible the
        # dispatcher may claim it immediately, and "dispatched" must
        # never outrun "queued" in the span stream
        trace(pending.request_id, "queued", model=self.name,
              instances=len(instances))
        try:
            self._queue.put_nowait(pending)
        except queue.Full:
            trace(pending.request_id, "shed", model=self.name,
                  reason="queue_full")
            raise QueueFullError("request queue full") from None
        if self._stop.is_set():
            # lost the race with stop()/abandon_dispatcher: the final
            # queue drain may already have run, so fail the stragglers
            # here (the queue hands each pending to exactly one drainer
            # — same shape as the engine's submit() recheck)
            while True:
                try:
                    stale = self._queue.get_nowait()
                except queue.Empty:
                    break
                stale.error = RetryableError("batcher stopped")
                trace(stale.request_id, "failed", model=self.name,
                      error="RetryableError")
                stale.event.set()
        # Bounded wait re-checking for shutdown: a request enqueued in the
        # race window after the dispatcher's final drain must not hang.
        # A CLAIMED request's batch is already executing and will finish
        # (its event always gets set), so only unclaimed waiters bail.
        while not pending.event.wait(timeout=0.5):
            if (self._stop.is_set() and not pending.claimed
                    and not pending.event.is_set()):
                raise RetryableError("batcher stopped")
        if pending.error is not None:
            raise pending.error
        return {"predictions": pending.result}

    # -- dispatcher --------------------------------------------------------

    def _run_inner(self, instances: list, params: Mapping[str, Any]) -> list:
        if isinstance(self.inner, Model):
            out = self.inner.predict(
                {"instances": instances, "parameters": dict(params)})
            return list(out["predictions"])
        return list(self.inner(instances, params))

    def _safe_dispatch_loop(self, gen: int) -> None:
        # The dispatcher must never die silently: a dead dispatcher with
        # ready=True hangs every request.  Unexpected loop errors fail the
        # in-flight work and the loop resumes.  The "dispatch" fault site
        # is different: it kills the THREAD (no drain, queue stranded) —
        # the segfault-class failure the supervisor's crash detection is
        # tested against.
        while not self._stop.is_set() and self._gen == gen:
            self.heartbeat.beat()
            try:
                if faults.fire("dispatch") is not None:
                    log.error("injected dispatcher death")
                    return
            except faults.FaultError:
                log.error("injected dispatcher death (raise)")
                return
            try:
                self._dispatch_once()
            except Exception:  # noqa: BLE001
                log.exception("batcher dispatch error; continuing")
        if self._gen != gen:
            return  # superseded by a supervisor restart; queue not ours
        self._drain_on_stop()

    def _shed_expired(self, p: _Pending) -> bool:
        """Fail (504) a pending whose deadline passed while it queued —
        a slot spent on it would produce an answer nobody reads."""
        if p.deadline is not None and time.monotonic() > p.deadline:
            self.stats["deadline_shed"] += 1
            self._m_shed.inc()
            trace(p.request_id, "shed", model=self.name,
                  reason="deadline_queued")
            p.error = DeadlineExceededError("deadline expired in queue")
            p.event.set()
            return True
        return False

    def _dispatch_once(self) -> None:
        self._m_queue.set(self._queue.qsize())
        delay_s = self.cfg.max_queue_delay_us / 1e6
        if self._held is not None:
            first, self._held = self._held, None
        else:
            try:
                first = self._queue.get(timeout=0.05)
            except queue.Empty:
                return
        if self._shed_expired(first):
            return
        t_coalesce = time.perf_counter()
        first.claimed = True
        batch = [first]
        total = len(first.instances)
        # coalesce: wait up to max_queue_delay for stragglers, while
        # respecting max_batch_size and only merging compatible
        # (same-parameters) requests — Triton's batching rule.
        deadline = delay_s
        while total < self.cfg.max_batch_size:
            try:
                nxt = self._queue.get(timeout=deadline)
            except queue.Empty:
                break
            if self._shed_expired(nxt):
                continue
            nxt.claimed = True
            if (nxt.params != first.params
                    or total + len(nxt.instances)
                    > self.cfg.max_batch_size):
                self._held = nxt  # seeds the next batch
                break
            batch.append(nxt)
            total += len(nxt.instances)
            deadline = 0  # drain whatever is already queued
        self._execute(batch,
                      coalesce_s=time.perf_counter() - t_coalesce)

    def _drain_on_stop(self) -> None:
        # fail pending requests rather than hang them
        leftovers = [self._held] if self._held is not None else []
        self._held = None
        while True:
            try:
                leftovers.append(self._queue.get_nowait())
            except queue.Empty:
                break
        for p in leftovers:
            p.error = RetryableError("batcher stopped")
            trace(p.request_id, "failed", model=self.name,
                  error="RetryableError")
            p.event.set()

    def _execute(self, batch: list[_Pending],
                 coalesce_s: float = 0.0) -> None:
        instances = [x for p in batch for x in p.instances]
        self.stats["requests"] += len(batch)
        self.stats["batches"] += 1
        self.stats["batched_instances"] += len(instances)
        self._m_requests.inc(len(batch))
        self._m_batches.inc()
        self._m_batch_size.observe(len(instances))
        for p in batch:
            trace(p.request_id, "dispatched", model=self.name,
                  batch_instances=len(instances))
        t0 = time.monotonic()
        self._current_batch = batch
        try:
            faults.fire("model_fn")
            results = self._run_inner(instances, batch[0].params)
            if len(results) != len(instances):
                # deliberate 500: a miscounting inner model is a server
                # fault, not something a client retry can fix
                # kct-lint: ignore[KCT-ERR-004] - deliberate 500
                raise RuntimeError(
                    f"inner model returned {len(results)} predictions "
                    f"for {len(instances)} instances")
            i = 0
            for p in batch:
                p.result = results[i:i + len(p.instances)]
                i += len(p.instances)
        except Exception as e:  # noqa: BLE001 - propagate per request
            # Wrap ValueError: by the time a batch executes, every payload
            # already passed request validation, so an inner ValueError is
            # a server-side fault (500), not a client error (400).
            if isinstance(e, ValueError):
                e = RuntimeError(f"batch execution failed: {e}")
            for p in batch:
                p.error = e
        finally:
            # Identity-guarded: an ABANDONED dispatcher waking from a
            # wedged inner call must not clobber the record of the
            # replacement dispatcher's in-flight batch — losing it would
            # strand that batch's waiters across the next restart.
            if self._current_batch is batch:
                self._current_batch = []
            dt = time.monotonic() - t0
            self._m_dispatch_s.observe(dt)
            rec = self.flight.begin()
            rec.phases = {"admit": coalesce_s, "decode": dt}
            rec.dur_s = coalesce_s + dt
            # ts is the interval START everywhere a record is consumed
            # (rates() windows, timeline correlation) — begin() ran
            # after the dispatch, so shift it back
            rec.ts -= rec.dur_s
            rec.active = len(batch)
            # a failed dispatch served nothing: goodput must read 0
            # during an outage, not len(instances)
            rec.decode_tokens = (0 if batch and batch[0].error is not None
                                 else len(instances))
            rec.queue_depth = self._queue.qsize()
            self.flight.commit(rec)
            for p in batch:
                trace(p.request_id,
                      "complete" if p.error is None else "failed",
                      model=self.name)
                p.event.set()
