"""Draft sources for speculative decoding (Leviathan et al., ICML '23;
see PAPERS.md).

Decode is memory-bound at small batch: every emitted token pays a full
weight sweep for ONE matmul row.  Speculative decoding buys k tokens
per sweep — a cheap *draft source* proposes k continuation tokens per
slot, and the engine verifies all of them in ONE batched target step
through the paged arena (:func:`~kubernetes_cloud_tpu.models.generate.
verify_step_pages`).  Greedy acceptance — keep the longest prefix
where the target's own argmax equals the draft — makes the output
bitwise the non-speculative decode, so correctness never depends on
the draft: a bad draft only costs speed.  That token-identity oracle
is what the tests assert across admission orders, prefix sharing,
preempt/resume, int8 arenas, and the sharded engine.

Three sources:

* :class:`ModelDraft` — a small causal LM (the pythia-70m-drafts-for-
  410m shape) running k sequential single-token steps over its own
  dense slot pool.  Rollback is host-side length truncation, catch-up
  after a fully-accepted round feeds the one not-yet-drafted token.
* :class:`NgramDraft` — prompt-lookup drafting: propose the k tokens
  that followed the most recent occurrence of the current trailing
  n-gram in the sequence itself.  Zero model cost; shines on
  extractive/repetitive workloads and is the engine's built-in
  ``spec_draft="ngram"`` mode.
* :class:`ScriptedDraft` — a deterministic callable for tests: a draft
  that disagrees at known positions makes the acceptance-ratio
  arithmetic assertable.

The engine owns scheduling; a draft source only answers "what comes
next for this slot?".  All methods run on the engine's scheduler
thread (single-owner, like the page allocator — no locks here).
"""

from __future__ import annotations

from typing import Callable, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from kubernetes_cloud_tpu.models.causal_lm import CausalLMConfig
from kubernetes_cloud_tpu.models.generate import init_cache


def _jit_draft_prefill():
    """The ENGINE's module-level prefill jit (lazy import breaks the
    cycle): every ModelDraft instance — and every engine restart —
    shares one compilation cache per (cfg, shape) instead of
    recompiling private copies; a draft whose config matches its
    target (tests' self-draft) reuses the target's programs outright."""
    from kubernetes_cloud_tpu.serve import continuous

    return continuous._jit_prefill()


def _jit_draft_decode():
    from kubernetes_cloud_tpu.serve import continuous

    return continuous._jit_decode()


class DraftSource:
    """Interface the engine drives once per speculative round."""

    #: surfaced in serving metadata / debug so a probe can tell which
    #: draft mode a replica runs
    kind = "none"
    #: draft-model device dispatches in the most recent propose() call
    #: (the engine prices their analytical FLOPs into the MFU gauge;
    #: zero-cost sources leave it 0)
    last_steps = 0
    #: a source with per-slot state is single-owner: every method runs
    #: on its engine's scheduler thread with no locks, and slot indices
    #: are meaningful only within one engine.  Stateless sources
    #: (ngram, scripted fns) flip this and may be handed to several
    #: engines (e.g. disaggregated decode slices).
    shareable = False
    #: True when slot_ready() JIT-compiles device programs — the engine
    #: widens its watchdog compile-grace window around such rounds
    compiles_on_slot_ready = False

    def slot_ready(self, slot: int, seq: Sequence[int]) -> None:
        """A slot became decode-ready holding context ``seq`` (prompt +
        emitted tokens) — build whatever per-slot state proposing
        needs."""

    def propose(self, want: dict[int, Sequence[int]], k: int
                ) -> dict[int, list[int]]:
        """Return up to ``k`` draft tokens per requesting slot.
        ``want`` maps slot → its full context (prompt + emitted);
        fewer than ``k`` proposals (or none) is always legal — the
        engine pads the verification window and unproposed columns are
        simply never accepted."""
        raise NotImplementedError

    def observe(self, slot: int, seq: Sequence[int]) -> None:
        """The round settled: ``seq`` is the slot's full accepted
        context.  Sources with per-slot state roll back here."""

    def free(self, slot: int) -> None:
        """The slot finished / was preempted — drop its state."""


class ModelDraft(DraftSource):
    """A small draft LM over its own dense slot pool.

    The pool mirrors the target engine's slot geometry (one row per
    target slot, ``max_len`` rows deep) but at the draft model's much
    smaller per-token KV cost.  Host-side ``lengths`` are the single
    source of truth; rollback after a partially-rejected round is a
    host array write — stale KV beyond the truncated length is never
    attended and is overwritten by the next real feed at its position
    (the same append-only argument the paged target arena makes)."""

    kind = "model"
    compiles_on_slot_ready = True

    def __init__(self, cfg: CausalLMConfig, params, *, slots: int,
                 max_len: int, pad_token_id: int = 0):
        self.cfg = cfg
        self.params = params
        self.slots = slots
        self.max_len = max_len
        self.pad = pad_token_id
        self.pool: Optional[dict] = None
        self._lengths = np.zeros((slots,), np.int64)
        self._prefill = _jit_draft_prefill()
        self._decode = _jit_draft_decode()
        self.stats = {"prefills": 0, "steps": 0, "catchup_steps": 0}
        self.last_steps = 0

    def _ensure_pool(self) -> None:
        if self.pool is None:
            self.pool = init_cache(self.cfg, self.slots, self.max_len)

    @staticmethod
    def _bucket(n: int) -> int:
        bucket = 32
        while bucket < n:
            bucket *= 2
        return bucket

    def slot_ready(self, slot: int, seq: Sequence[int]) -> None:
        """Prefill ``seq[:-1]`` into the slot's draft row (the final
        token is fed by the first proposal step, exactly like the
        target engine's last-token convention)."""
        self._ensure_pool()
        ctx = list(seq[:-1])
        if not ctx:  # a 1-token prompt: nothing resident yet
            self._lengths[slot] = 0
            return
        bucket = min(self._bucket(len(ctx)), self.max_len)
        ids = np.full((1, bucket), self.pad, np.int32)
        mask = np.zeros((1, bucket), np.int32)
        ids[0, :len(ctx)] = ctx
        mask[0, :len(ctx)] = 1
        _, self.pool = self._prefill(
            self.cfg, self.params, jnp.asarray(ids), jnp.asarray(mask),
            self.pool, jnp.asarray([slot], jnp.int32))
        self._lengths[slot] = len(ctx)
        self.stats["prefills"] += 1

    def _step(self, tokens: np.ndarray, active: np.ndarray) -> np.ndarray:
        """One batched draft decode step; returns argmax tokens [S]."""
        self.pool = dict(self.pool)
        self.pool["length"] = jnp.asarray(self._lengths, jnp.int32)
        logits, self.pool = self._decode(
            self.cfg, self.params, jnp.asarray(tokens, jnp.int32),
            self.pool, jnp.asarray(active))
        self._lengths[active] += 1
        self.stats["steps"] += 1
        return np.asarray(jnp.argmax(logits, axis=-1))

    def propose(self, want: dict[int, Sequence[int]], k: int
                ) -> dict[int, list[int]]:
        self._ensure_pool()
        self.last_steps = 0
        slots = sorted(want)
        if not slots or k < 1:
            return {}
        # catch-up: after a fully-accepted round the slot's last
        # accepted draft was never fed (its KV is missing) — feed every
        # known-but-undrafted token until only seq[-1] remains
        while True:
            lag = [s for s in slots
                   if self._lengths[s] < len(want[s]) - 1]
            if not lag:
                break
            tokens = np.full((self.slots,), self.pad, np.int32)
            active = np.zeros((self.slots,), bool)
            for s in lag:
                tokens[s] = want[s][self._lengths[s]]
                active[s] = True
            self._step(tokens, active)
            self.last_steps += 1
            self.stats["catchup_steps"] += 1
        # k proposal steps: feed seq[-1], then each fresh proposal
        out: dict[int, list[int]] = {s: [] for s in slots}
        active = np.zeros((self.slots,), bool)
        tokens = np.full((self.slots,), self.pad, np.int32)
        for s in slots:
            tokens[s] = want[s][-1]
            active[s] = True
        for _ in range(k):
            sampled = self._step(tokens, active)
            self.last_steps += 1
            tokens = np.full((self.slots,), self.pad, np.int32)
            for s in slots:
                out[s].append(int(sampled[s]))
                tokens[s] = sampled[s]
        return out

    def observe(self, slot: int, seq: Sequence[int]) -> None:
        # roll back to the accepted context: positions beyond
        # len(seq)-1 hold rejected-draft KV (seq[-1] itself is fed by
        # the next round's proposal step, mirroring the target)
        self._lengths[slot] = min(int(self._lengths[slot]), len(seq) - 1)

    def free(self, slot: int) -> None:
        self._lengths[slot] = 0


class NgramDraft(DraftSource):
    """Prompt-lookup drafting: no model, no state — propose the tokens
    that followed the most recent earlier occurrence of the current
    trailing n-gram.  Free to compute and surprisingly strong on
    summarization / extraction / code workloads where continuations
    repeat earlier spans; on mismatch the verify step rejects and the
    engine loses nothing but the (empty) draft cost."""

    kind = "ngram"
    shareable = True  # no per-slot state: propose() is a pure function

    def __init__(self, max_ngram: int = 3, window: int = 1024):
        if max_ngram < 1:
            raise ValueError("max_ngram must be >= 1")
        self.max_ngram = max_ngram
        self.window = window

    def propose(self, want: dict[int, Sequence[int]], k: int
                ) -> dict[int, list[int]]:
        out: dict[int, list[int]] = {}
        for slot, seq in want.items():
            seq = list(seq[-self.window:])
            if len(seq) < 2:
                continue
            # the scan runs on the scheduler thread every speculative
            # round: search int32 cells with bytes.rfind (C speed)
            # instead of a Python loop of per-position list slices —
            # an unaligned hit is a byte coincidence spanning cell
            # boundaries, not a token match, so keep looking left
            buf = np.asarray(seq, np.int32).tobytes()
            drafts: list[int] = []
            for n in range(min(self.max_ngram, len(seq) - 1), 0, -1):
                pat = buf[-4 * n:]
                # rightmost earlier occurrence wins (start <= the
                # final pattern's start - 1; overlap is fine): recent
                # context is the best predictor of what follows
                b = buf.rfind(pat, 0, 4 * (len(seq) - 1))
                while b >= 0 and b % 4:
                    b = buf.rfind(pat, 0, b + 4 * n - 1)
                if b >= 0:
                    i = b // 4
                    drafts = seq[i + n:i + n + k]
                    break
            if drafts:
                out[slot] = drafts
        return out


class ScriptedDraft(DraftSource):
    """Deterministic draft for tests: ``fn(slot, seq, k) -> drafts``.
    A script that disagrees with the target at known positions makes
    acceptance-ratio accounting exactly assertable."""

    kind = "scripted"
    shareable = True  # stateless wrapper (a stateful fn is the
    # caller's own concurrency problem)

    def __init__(self, fn: Callable[[int, Sequence[int], int],
                                    Sequence[int]]):
        self.fn = fn

    def propose(self, want: dict[int, Sequence[int]], k: int
                ) -> dict[int, list[int]]:
        return {slot: list(self.fn(slot, seq, k))[:k]
                for slot, seq in want.items()}
