"""KServe V1 data-plane HTTP server on the standard library.

Serves :class:`~kubernetes_cloud_tpu.serve.model.Model` instances behind
the exact REST surface the reference's InferenceServices expose
(``online-inference/tensorizer-isvc/README.md``; clients at
``image-classifier/service/predict_url.sh``):

* ``GET  /``                         liveness (Knative probe target)
* ``GET  /v1/models``                model list
* ``GET  /v1/models/<name>``         readiness
* ``POST /v1/models/<name>:predict`` prediction
* ``POST /completion``               FastAPI-compatible completion route
  (``finetuner-workflow/finetuner/inference.py:80-96``) when the model
  implements ``completion()``

Concurrency: one lock per model — the reference's GPU services run with
``containerConcurrency: 1`` (``stable-diffusion/03-inference-service.yaml:7``)
and a single TPU program likewise shouldn't interleave requests; Knative
provides scale-out.  Models that set ``self_batching = True`` (the
dynamic batcher, :mod:`kubernetes_cloud_tpu.serve.batcher`) bypass the
lock: they coalesce concurrent requests themselves.
"""

from __future__ import annotations

import json
import logging
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Iterable

from kubernetes_cloud_tpu.serve.batcher import QueueFullError
from kubernetes_cloud_tpu.serve.model import Model

log = logging.getLogger(__name__)


class ModelServer:
    def __init__(self, models: Iterable[Model], *, host: str = "0.0.0.0",
                 port: int = 8080):
        self.models = {m.name: m for m in models}
        self.locks = {name: threading.Lock() for name in self.models}
        self.host, self.port = host, port
        self._httpd: ThreadingHTTPServer | None = None

    def load_all(self) -> None:
        for model in self.models.values():
            if not model.ready:
                model.load()

    # -- request handling --------------------------------------------------

    def handle(self, method: str, path: str, body: bytes) -> tuple[int, dict]:
        if method == "GET":
            if path in ("/", "/healthz"):
                return 200, {"status": "alive"}
            if path == "/v1/models":
                return 200, {"models": sorted(self.models)}
            if path.startswith("/v1/models/"):
                name = path[len("/v1/models/"):]
                model = self.models.get(name)
                if model is None:
                    return 404, {"error": f"model {name} not found"}
                return 200, {"name": name, "ready": model.ready}
            return 404, {"error": "not found"}

        if method == "POST":
            try:
                payload = json.loads(body or b"{}")
            except json.JSONDecodeError as e:
                return 400, {"error": f"invalid JSON: {e}"}
            if path.endswith(":predict") and path.startswith("/v1/models/"):
                name = path[len("/v1/models/"):-len(":predict")]
                return self._predict(name, payload)
            if path == "/completion":
                return self._completion(payload)
            return 404, {"error": "not found"}

        return 405, {"error": "method not allowed"}

    def _dispatch(self, model: Model, fn, payload: dict,
                  what: str) -> tuple[int, dict]:
        """Shared model-call ladder: self-batching lock bypass (batchers
        coalesce concurrent requests themselves; the per-model lock
        would serialize them and defeat batching) + the error → status
        mapping, identical for every data-plane route."""
        try:
            if getattr(model, "self_batching", False):
                return 200, fn(payload)
            with self.locks[model.name]:
                return 200, fn(payload)
        except ValueError as e:  # request validation problems
            return 400, {"error": str(e)}
        except QueueFullError as e:  # backpressure: retriable overload
            return 503, {"error": str(e)}
        except Exception as e:  # surface as a 500, keep serving
            log.exception("%s failed", what)
            return 500, {"error": str(e)}

    def _predict(self, name: str, payload: dict) -> tuple[int, dict]:
        model = self.models.get(name)
        if model is None:
            return 404, {"error": f"model {name} not found"}
        if not model.ready:
            return 503, {"error": f"model {name} is not ready"}
        return self._dispatch(model, model.predict, payload, "predict")

    def _completion(self, payload: dict) -> tuple[int, dict]:
        capable = [(n, m) for n, m in self.models.items()
                   if getattr(m, "completion", None) is not None]
        if not capable:
            return 404, {"error": "no completion-capable model"}
        for name, model in capable:
            if not model.ready:
                continue
            return self._dispatch(model, model.completion, payload,
                                  "completion")
        return 503, {"error": "completion model is not ready"}

    # -- http plumbing -----------------------------------------------------

    def _make_handler(server):  # noqa: N805 - closure over the ModelServer
        class Handler(BaseHTTPRequestHandler):
            def _respond(self, method):
                length = int(self.headers.get("Content-Length") or 0)
                body = self.rfile.read(length) if length else b""
                status, obj = server.handle(method, self.path, body)
                data = json.dumps(obj).encode()
                self.send_response(status)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(data)))
                self.end_headers()
                self.wfile.write(data)

            def do_GET(self):
                self._respond("GET")

            def do_POST(self):
                self._respond("POST")

            def log_message(self, fmt, *args):
                log.debug("%s " + fmt, self.client_address[0], *args)

        return Handler

    def _bind(self) -> ThreadingHTTPServer:
        if self._httpd is not None:
            raise RuntimeError("server already started")
        self._httpd = ThreadingHTTPServer((self.host, self.port),
                                          self._make_handler())
        self.port = self._httpd.server_address[1]
        log.info("serving on %s:%d", self.host, self.port)
        return self._httpd

    def start(self) -> None:
        """Start serving in a background thread (returns immediately)."""
        httpd = self._bind()
        threading.Thread(target=httpd.serve_forever, daemon=True).start()

    def serve_forever(self) -> None:
        self.load_all()
        self._bind().serve_forever()

    def stop(self) -> None:
        if self._httpd is not None:
            self._httpd.shutdown()
            self._httpd = None
