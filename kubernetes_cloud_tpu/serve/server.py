"""KServe V1 data-plane HTTP server on the standard library.

Serves :class:`~kubernetes_cloud_tpu.serve.model.Model` instances behind
the exact REST surface the reference's InferenceServices expose
(``online-inference/tensorizer-isvc/README.md``; clients at
``image-classifier/service/predict_url.sh``):

* ``GET  /``, ``/healthz``           liveness: process alive — always
  200, even with a wedged engine (killing a pod that holds streamed
  weights is the supervisor's last resort, not the probe's first)
* ``GET  /readyz``                   readiness: models loaded ∧ engine
  heartbeat fresh ∧ circuit closed ∧ queue below shed threshold ∧ not
  draining (each model's ``health()``; Knative routes on this)
* ``GET  /v1/models``                model list
* ``GET  /v1/models/<name>``         per-model readiness
* ``POST /v1/models/<name>:predict`` prediction
* ``POST /completion``               FastAPI-compatible completion route
  (``finetuner-workflow/finetuner/inference.py:80-96``) when the model
  implements ``completion()``
* ``GET  /metrics``                  Prometheus text exposition of the
  process-global registry (:mod:`kubernetes_cloud_tpu.obs`) — engine,
  batcher, supervisor, server, and workflow families; the target of the
  ``prometheus.io/scrape`` pod annotations in ``deploy/``
* ``GET  /debug/timeline``           flight-recorder dump (per-iteration
  phase timings + batch composition; ``?last=N``, ``?model=name``)
* ``GET  /debug/slots``              per-slot engine occupancy
* ``GET  /debug/pages``              paged-KV arena occupancy +
  prefix-cache contents (block hashes, never prompt content)
* ``GET  /debug/profile?seconds=N``  arm one ``jax.profiler`` trace
  window (409 while one is already running)
* ``GET  /debug/trace[/<id>]``       distributed-trace span store:
  retained-trace index + worst-TTFT exemplars, or one assembled trace
  (spans, waterfall, critical-path attribution); the fleet router's
  copy also pulls the replicas that served the trace
* ``GET  /debug/slo``                last SLO burn-rate evaluation
  (error budgets per promise; the fleet router's prober keeps it warm)

Error mapping (:mod:`kubernetes_cloud_tpu.serve.errors`): ValueError →
400, RetryableError (queue full / engine restarted / stream stalled /
draining) → 503, DeadlineExceededError → 504, anything else → 500.
Requests may carry a deadline as an ``X-Request-Deadline-Ms`` header or
a ``deadline_ms`` payload field; expired work is shed, not computed.

SIGTERM (:func:`ModelServer.drain`, installed by ``serve.boot``)
follows the Knative pod-termination contract: readiness flips to 503
and admission stops immediately, in-flight requests run to completion,
self-batching workers drain their slots, then the listener closes.

Concurrency: one lock per model — the reference's GPU services run with
``containerConcurrency: 1`` (``stable-diffusion/03-inference-service.yaml:7``)
and a single TPU program likewise shouldn't interleave requests; Knative
provides scale-out.  Models that set ``self_batching = True`` (the
dynamic batcher, :mod:`kubernetes_cloud_tpu.serve.batcher`) bypass the
lock: they coalesce concurrent requests themselves.
"""

from __future__ import annotations

import dataclasses
import json
import logging
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Iterable, Mapping, Optional

from kubernetes_cloud_tpu import faults, obs
from kubernetes_cloud_tpu.obs import dtrace, tracing
from kubernetes_cloud_tpu.serve.errors import (
    DeadlineExceededError,
    NoModelsLoadedError,
    RetryableError,
)
from kubernetes_cloud_tpu.serve.model import Model
from kubernetes_cloud_tpu.serve.model_cache import ModelCache

log = logging.getLogger(__name__)

#: relative deadline budget header (KServe/Knative have no standard one;
#: gRPC's grpc-timeout plays this role on the other data plane)
DEADLINE_HEADER = "X-Request-Deadline-Ms"

#: tenant identity header (serve/tenancy.py).  Title-cased spelling so
#: ONE lookup works on both front-ends: the stdlib front-end's header
#: mapping is case-insensitive, the native front-end's raw header block
#: is parsed into Title-Cased names — "X-API-Key" arrives as this.
API_KEY_HEADER = "X-Api-Key"

# HTTP-layer metric families (labels bound per request; the label space
# is the fixed route vocabulary below — never the raw path, whose model
# names would otherwise make cardinality unbounded)
_M_REQUESTS = obs.counter(
    "kct_server_requests_total", "HTTP requests by route/method/status.",
    ("route", "method", "status"))
_M_LATENCY = obs.histogram(
    "kct_server_request_seconds", "HTTP request wall time by route.",
    ("route",))


def route_label(path: str) -> str:
    """Bounded route vocabulary for metric labels."""
    path = path.partition("?")[0]  # query strings are client-chosen
    if path in ("/", "/healthz"):
        return "healthz"
    if path == "/readyz":
        return "readyz"
    if path == "/metrics":
        return "metrics"
    if path == "/debug" or path.startswith("/debug/"):
        return "debug"
    if path == "/completion":
        return "completion"
    if path.endswith(":predict"):
        return "predict"
    if path.endswith(":cancel"):
        return "cancel"
    if path.endswith(":swap"):
        return "swap"
    if path.startswith("/v1/models"):
        return "models"
    return "other"


@dataclasses.dataclass
class TextResponse:
    """A non-JSON ``handle()`` body (the ``/metrics`` exposition); both
    front-ends serialize it verbatim with its content type."""

    body: str
    content_type: str = obs.CONTENT_TYPE


class _LockMap(dict):
    """Per-model dispatch locks, created lazily so models admitted into
    the cache after construction get one too."""

    def __missing__(self, name: str) -> threading.Lock:
        lock = self[name] = threading.Lock()
        return lock


class ModelServer:
    def __init__(self, models: "Iterable[Model] | ModelCache", *,
                 host: str = "0.0.0.0", port: int = 8080):
        #: lifecycle-managed registry ({name: Model} plus states/LRU/
        #: tenancy); accepts a pre-built cache for capacity/quota config
        self.models = models if isinstance(models, ModelCache) \
            else ModelCache(models)
        self.locks = _LockMap()
        self.host, self.port = host, port
        self._httpd: ThreadingHTTPServer | None = None
        self._draining = False
        self._inflight = 0
        self._inflight_lock = threading.Lock()
        #: per-window deep profiling armed via GET /debug/profile
        #: (serve.boot points trace_dir at --profile-dir)
        self.profiler = obs.ProfileWindow()
        #: SLO evaluator behind GET /debug/slo (attach_slo; the fleet
        #: router attaches one by default and pokes it from the prober)
        self.slo = None

    def attach_slo(self, evaluator) -> None:
        """Attach an :class:`~kubernetes_cloud_tpu.obs.slo.SLOEvaluator`
        for ``GET /debug/slo`` to serve snapshots of."""
        self.slo = evaluator

    def load_all(self) -> None:
        """Load every registered model, continuing past failures: a
        failed load lands that model in the cache's terminal ``failed``
        state (reported per-model by ``/readyz``) instead of leaving
        the registry half-populated.  Raises only when NOTHING loaded —
        a single-model pod still crash-loops loudly; a zoo with one
        bad adapter serves degraded."""
        failed = self.models.load_all()
        if failed and not any(m.ready for m in self.models.values()):
            raise NoModelsLoadedError(
                "no model loaded successfully: "
                + "; ".join(f"{n}: {e}" for n, e in failed.items()))

    # -- request handling --------------------------------------------------

    def handle(self, method: str, path: str, body: bytes,
               headers: Optional[Mapping[str, str]] = None
               ) -> tuple[int, dict | TextResponse]:
        t0 = time.monotonic()
        status, obj = self._route(method, path, body, headers)
        try:  # instrumentation must never turn a served answer into a 500
            route = route_label(path)
            # clamp the method like the route: the native front-end
            # forwards the client's raw token, and a label value per
            # invented method would grow the registry without bound
            meth = method if method in ("GET", "POST") else "other"
            _M_REQUESTS.labels(route=route, method=meth,
                               status=str(status)).inc()
            _M_LATENCY.labels(route=route).observe(time.monotonic() - t0)
        except Exception:  # noqa: BLE001 - pragma: no cover
            log.exception("request metrics recording failed")
        return status, obj

    def _route(self, method: str, path: str, body: bytes,
               headers: Optional[Mapping[str, str]] = None
               ) -> tuple[int, dict | TextResponse]:
        try:
            faults.fire("server.handle")
        except faults.FaultError as e:
            return 500, {"error": str(e)}
        if method == "GET":
            # split the query string off ONCE for every GET route:
            # /debug/* takes parameters; the fixed routes simply never
            # match a path that still carries one
            path, _, query = path.partition("?")
            if path in ("/", "/healthz"):
                # process liveness only — unconditionally alive; engine
                # trouble is /readyz's (and the supervisor's) business
                return 200, {"status": "alive"}
            if path == "/readyz":
                return self._readyz()
            if path == "/metrics":
                return self._metrics()
            if path == "/debug" or path.startswith("/debug/"):
                return self._debug(path, query)
            if path == "/v1/models":
                return 200, {"models": sorted(self.models)}
            if path.startswith("/v1/models/"):
                name = path[len("/v1/models/"):]
                model = self.models.get(name)
                if model is None:
                    return 404, {"error": f"model {name} not found"}
                out = {"name": name, "ready": model.ready}
                entry = self.models.entry(name)
                if entry is not None:
                    out.update(entry.snapshot())
                return 200, out
            return 404, {"error": "not found"}

        if method == "POST":
            # admission control: count in-flight BEFORE the drain check
            # so drain() observing _inflight == 0 proves no request can
            # still slip past the flag
            with self._inflight_lock:
                self._inflight += 1
            try:
                if self._draining:
                    return 503, {"error": "pod is draining; retry "
                                          "against another replica"}
                try:
                    payload = json.loads(body or b"{}")
                except json.JSONDecodeError as e:
                    return 400, {"error": f"invalid JSON: {e}"}
                if isinstance(payload, dict):
                    if headers is not None:
                        budget = headers.get(DEADLINE_HEADER)
                        if budget is not None:
                            payload.setdefault("deadline_ms", budget)
                        rid = headers.get(tracing.REQUEST_ID_HEADER)
                        if rid:
                            payload.setdefault("request_id", rid)
                        # tenant identity at the door: the API key
                        # rides the payload so every model sees the
                        # same classification regardless of front-end
                        # (serve/tenancy.py resolves key -> tenant; an
                        # explicit payload "tenant" field still wins)
                        key = headers.get(API_KEY_HEADER)
                        if key:
                            payload.setdefault("api_key", key)
                    # stamp every request exactly once at the door — the
                    # id ties HTTP, engine spans, and the client together
                    payload.setdefault("request_id",
                                       tracing.new_request_id())
                    # distributed-trace context at the same door: honor
                    # an inbound Traceparent (header, or payload field
                    # for headerless hops), mint when absent or garbage
                    # — never a 400 — and bind it so every engine span
                    # this request emits parents into this crossing
                    ctx = self._trace_door_enter(path, payload, headers)
                    if ctx is not None:
                        t0c, wall0 = time.monotonic(), time.time()
                        status, obj = self._route_post(path, payload)
                        return self._trace_door_exit(
                            path, payload, ctx, status, obj, wall0,
                            time.monotonic() - t0c)
                return self._route_post(path, payload)
            finally:
                with self._inflight_lock:
                    self._inflight -= 1

        return 405, {"error": "method not allowed"}

    def _route_post(self, path: str,
                    payload: dict) -> tuple[int, dict]:
        if path.endswith(":predict") and path.startswith("/v1/models/"):
            name = path[len("/v1/models/"):-len(":predict")]
            return self._predict(name, payload)
        if path.endswith(":cancel") and path.startswith("/v1/models/"):
            name = path[len("/v1/models/"):-len(":cancel")]
            return self._cancel(name, payload)
        if path.endswith(":swap") and path.startswith("/v1/models/"):
            name = path[len("/v1/models/"):-len(":swap")]
            return self._swap(name, payload)
        if path == "/completion":
            return self._completion(payload)
        return 404, {"error": "not found"}

    # -- distributed tracing at the door -----------------------------------

    def _trace_door_enter(self, path: str, payload: dict,
                          headers: Optional[Mapping[str, str]]
                          ) -> Optional[dtrace.TraceContext]:
        """Data-plane requests (predict/completion) get a trace
        context: parsed from the inbound ``Traceparent`` header (or a
        ``traceparent`` payload field), minted otherwise.  The payload
        field is rewritten to OUR span so a further door crossing
        parents into this one; the header a router sends per dispatch
        leg still wins at that door."""
        if not (path.endswith(":predict") or path == "/completion"):
            return None
        raw = headers.get(dtrace.TRACEPARENT_HEADER) if headers else None
        if not raw:
            raw = payload.get("traceparent")
        ctx = dtrace.parse(raw) or dtrace.mint()
        payload["traceparent"] = ctx.wire()
        dtrace.bind(payload.get("request_id"), ctx)
        return ctx

    def _trace_door_exit(self, path: str, payload: dict,
                         ctx: dtrace.TraceContext, status: int, obj,
                         wall0: float, dur_s: float) -> tuple[int, dict]:
        """Close the door crossing: record the ``server`` span, echo
        the trace id on served answers, mark 5xx traces keep-worthy,
        and — when this process is the sampling authority — make the
        tail-based retention decision."""
        rid = payload.get("request_id")
        # conditional: an in-process replica door rebinds the SAME id
        # in the shared store — only the door that bound it unbinds it
        dtrace.unbind(rid, ctx)
        trace_status = int(status)
        dtrace.add_span(ctx.trace_id, ctx.span_id, ctx.parent_id,
                        "server", ts=wall0, dur_s=dur_s,
                        status=trace_status, route=route_label(path),
                        request_id=rid)
        if isinstance(obj, dict) and 200 <= status < 300:
            obj.setdefault("trace_id", ctx.trace_id)
        if status >= 500:
            dtrace.note_keep(ctx.trace_id, "5xx")
        if self._trace_sampling_authority(ctx):
            dtrace.decide(ctx.trace_id)
        return status, obj

    def _trace_sampling_authority(self, ctx: dtrace.TraceContext) -> bool:
        """A standalone server decides retention for traces it roots
        AND for client-minted contexts (the client has no span store;
        somebody must decide or the store fills with undecided traces).
        Only a caller that claimed the decision on the wire — the
        fleet router's dispatch legs, which assemble the tree by
        pulling this store — suppresses the local decision (the router
        itself overrides this to always decide)."""
        return not ctx.caller_decides

    def _metrics(self) -> tuple[int, dict | TextResponse]:
        """Render the registry.  Failure is CONTAINED: a raising (or,
        with the thread-per-request front-ends, hanging) scrape answers
        this request only — the data plane and /readyz never route
        through here (chaos-locked by tests/test_obs.py)."""
        try:
            faults.fire("metrics.render")
            return 200, TextResponse(obs.render_text())
        except Exception as e:  # noqa: BLE001 - scrape must stay isolated
            log.exception("metrics render failed")
            return 500, {"error": f"metrics unavailable: {e}"}

    # -- debug plane (performance introspection) ---------------------------

    def _debug(self, path: str, query: str) -> tuple[int, dict]:
        """Route ``GET /debug/*``.  Failure is CONTAINED exactly like
        the metrics scrape: a raising (or hanging) introspection render
        answers this request only — the data plane and ``/readyz``
        never route through here (fault site ``debug.render``,
        chaos-locked by tests/test_debug_endpoints.py)."""
        import urllib.parse

        try:
            faults.fire("debug.render")
            params = urllib.parse.parse_qs(query)
            if path == "/debug/timeline":
                return self._debug_timeline(params)
            if path == "/debug/slots":
                return self._debug_slots(params)
            if path == "/debug/pages":
                return self._debug_pages(params)
            if path == "/debug/profile":
                return self._debug_profile(params)
            if path == "/debug/trace" or path.startswith("/debug/trace/"):
                trace_id = path[len("/debug/trace"):].strip("/") or None
                return self._debug_trace(trace_id, params)
            if path == "/debug/slo":
                return self._debug_slo(params)
            return 404, {"error": "unknown debug endpoint", "endpoints": [
                "/debug/timeline?last=N", "/debug/slots", "/debug/pages",
                "/debug/profile?seconds=N", "/debug/trace[/<trace_id>]",
                "/debug/slo"]}
        except ValueError as e:  # bad query parameters
            return 400, {"error": str(e)}
        except Exception as e:  # noqa: BLE001 - debug must stay isolated
            log.exception("debug render failed")
            return 500, {"error": f"debug unavailable: {e}"}

    def _debug_recorders(self):
        """``(name, kind, engine-or-None, recorder)`` per model that
        carries a flight recorder (continuous engine or batcher)."""
        out = []
        for name, model in self.models.items():
            engine = getattr(model, "engine", None)
            recorder = getattr(engine, "flight", None)
            if recorder is not None:
                out.append((name, "engine", engine, recorder))
                continue
            recorder = getattr(model, "flight", None)
            if recorder is not None:
                out.append((name, "batcher", None, recorder))
        return out

    def _debug_timeline(self, params) -> tuple[int, dict]:
        last = int(params.get("last", ["256"])[0])
        if last < 0:
            raise ValueError("last must be >= 0")
        only = params.get("model", [None])[0]
        models = {}
        for name, kind, engine, recorder in self._debug_recorders():
            if only and name != only:
                continue
            entry = {"kind": kind,
                     "iterations": recorder.tail(last),
                     "requests": recorder.request_tail(last)}
            if engine is not None:
                entry["meta"] = engine.debug_meta()
                entry["stats"] = dict(engine.stats)
            models[name] = entry
        return 200, {"models": models}

    def _debug_slots(self, params) -> tuple[int, dict]:
        models = {}
        for name, model in self.models.items():
            engine = getattr(model, "engine", None)
            slots = getattr(engine, "debug_slots", None)
            if slots is None:
                continue
            models[name] = {"slots": slots(),
                            "queue_depth": engine.queue_depth()}
            tenants = getattr(engine, "debug_tenants", None)
            if tenants is not None:
                models[name]["tenants"] = tenants()
        return 200, {"models": models}

    def _debug_pages(self, params) -> tuple[int, dict]:
        models = {}
        for name, model in self.models.items():
            engine = getattr(model, "engine", None)
            pages = getattr(engine, "debug_pages", None)
            if pages is None:
                continue
            models[name] = pages()  # None for the dense slot pool
        return 200, {"models": models}

    def _debug_trace(self, trace_id: Optional[str],
                     params) -> tuple[int, dict]:
        """``GET /debug/trace`` (retained-trace index + worst-TTFT
        exemplars) and ``GET /debug/trace/<id>`` (one assembled trace:
        spans, rendered waterfall, critical-path attribution).  Fault
        site ``trace.export`` — failure stays contained to this debug
        request, same contract as the metrics scrape."""
        faults.fire("trace.export")
        store = dtrace.store()
        if not trace_id:
            return 200, {"traces": store.index(),
                         "exemplars": store.exemplars(),
                         "store": store.snapshot()}
        spans = self._trace_spans(trace_id)
        if not spans:
            return 404, {"error": f"trace {trace_id} not found "
                                  "(dropped by sampling, evicted, or "
                                  "never seen)"}
        merged = dtrace.merge_spans(spans)
        return 200, {"trace_id": trace_id, "spans": merged,
                     "keep": sorted(store.keep_reasons(trace_id)),
                     "tree": dtrace.render_waterfall(merged),
                     "analysis": dtrace.analyze(merged)}

    def _trace_spans(self, trace_id: str) -> Optional[list]:
        """Local spans only; the fleet router overrides this with the
        assembler that also pulls the replicas that served the trace."""
        return dtrace.store().spans_for(trace_id)

    def _debug_slo(self, params) -> tuple[int, dict]:
        """``GET /debug/slo`` — the LAST burn-rate evaluation, verbatim
        (never evaluates inline: a hung evaluation parks the worker
        thread, not this debug request)."""
        if self.slo is None:
            return 404, {"error": "no SLO evaluator attached (the "
                                  "fleet router attaches one)"}
        snap = self.slo.snapshot()
        return 200, {"specs": [s.name for s in self.slo.specs],
                     "evaluated": snap.get("ts") is not None,
                     **snap}

    def _debug_profile(self, params) -> tuple[int, dict]:
        from kubernetes_cloud_tpu.obs.flight import ProfileActiveError

        seconds = float(params.get("seconds", ["5"])[0])
        try:
            return 200, self.profiler.arm(seconds)
        except ProfileActiveError as e:
            return 409, {"error": str(e)}

    def _readyz(self) -> tuple[int, dict]:
        if self._draining:
            return 503, {"status": "draining"}
        detail, ok = {}, True
        for name, model in self.models.items():
            h = model.health()
            entry = self.models.entry(name)
            if entry is not None:
                # lifecycle state + weights_version ride every probe
                # body so fleet routers can tell replicas apart
                # mid-rollout and report WHY a model is unready
                for key, value in entry.snapshot().items():
                    h.setdefault(key, value)
            detail[name] = h
            ok = ok and bool(h.get("ok"))
        return (200 if ok else 503), {
            "status": "ready" if ok else "unready", "models": detail}

    def _dispatch(self, model: Model, fn, payload: dict,
                  what: str) -> tuple[int, dict]:
        """Shared model-call ladder: self-batching lock bypass (batchers
        coalesce concurrent requests themselves; the per-model lock
        would serialize them and defeat batching) + the error → status
        mapping, identical for every data-plane route."""
        try:
            if getattr(model, "self_batching", False):
                return 200, fn(payload)
            with self.locks[model.name]:
                return 200, fn(payload)
        except ValueError as e:  # request validation problems
            return 400, {"error": str(e)}
        except DeadlineExceededError as e:  # shed: nobody is waiting
            return 504, {"error": str(e)}
        except RetryableError as e:  # transient overload/restart: retry
            # error_kind = the typed ladder's class name: the fleet
            # router retries most 503s on another replica but must NOT
            # launder a TenantQuotaError through a neighbour's bucket
            body = {"error": str(e), "error_kind": type(e).__name__}
            # tenant-quota sheds carry the bucket's refill estimate —
            # the Retry-After hint a well-behaved client backs off by
            retry_after = getattr(e, "retry_after_s", None)
            if retry_after is not None:
                body["retry_after_s"] = round(float(retry_after), 3)
            return 503, body
        except Exception as e:  # noqa: BLE001 - surface as 500, keep serving
            log.exception("%s failed", what)
            return 500, {"error": str(e)}

    def _predict(self, name: str, payload: dict) -> tuple[int, dict]:
        model = self.models.get(name)
        if model is None:
            return 404, {"error": f"model {name} not found"}
        if not model.ready:
            entry = self.models.entry(name)
            if entry is not None and entry.state == "failed":
                return 503, {"error": f"model {name} failed to load: "
                                      f"{entry.error}",
                             "error_kind": "ModelLoadFailed"}
            return 503, {"error": f"model {name} is not ready"}
        with self.models.using(name):
            return self._dispatch(model, model.predict, payload, "predict")

    def _cancel(self, name: str, payload: dict) -> tuple[int, dict]:
        """``POST /v1/models/<name>:cancel {"request_id": ...}`` —
        cancel an in-flight request by the id the door stamped.  The
        fleet router's hedge-loser / reroute cleanup path for REMOTE
        replicas (in-process replicas cancel directly); engines reap
        the marked request at their next scheduler pass via the
        existing ``cancel()`` machinery."""
        model = self.models.get(name)
        if model is None:
            return 404, {"error": f"model {name} not found"}
        fn = getattr(model, "cancel_request", None)
        if fn is None:
            return 404, {"error": f"model {name} does not support "
                                  "cancellation"}
        # the door stamps a fresh request_id on bodies without one, so
        # rid always exists; a minted one matches nothing → false
        rid = payload.get("request_id")
        return 200, {"cancelled": bool(fn(str(rid)))}

    def _swap(self, name: str, payload: dict) -> tuple[int, dict]:
        """``POST /v1/models/<name>:swap {"weights": path}`` — live
        weight hot-swap through the model's drain/transplant rollout
        (``swap_weights``).  The admin plane of a rollout: the old
        version keeps serving until the new one verifies; a failed or
        corrupt swap answers 409 with ``rolled_back: true`` and the
        still-serving version."""
        from kubernetes_cloud_tpu.weights.tensorstream import (
            WeightStreamError,
        )

        model = self.models.get(name)
        if model is None:
            return 404, {"error": f"model {name} not found"}
        fn = getattr(model, "swap_weights", None)
        if fn is None:
            return 404, {"error": f"model {name} does not support "
                                  "weight hot-swap"}
        weights = payload.get("weights")
        if not weights:
            return 400, {"error": 'payload needs {"weights": <path>}'}
        try:
            result = fn(str(weights))
        except RetryableError as e:  # swap already running
            return 503, {"error": str(e),
                         "error_kind": type(e).__name__}
        except (WeightStreamError, RuntimeError, ValueError) as e:
            log.exception("hot-swap of %s failed; old weights serving",
                          name)
            return 409, {
                "swapped": False, "rolled_back": True,
                "error": str(e), "error_kind": type(e).__name__,
                "weights_version": getattr(model, "weights_version",
                                           None)}
        return 200, {"swapped": True, **result}

    def _completion(self, payload: dict) -> tuple[int, dict]:
        capable = [(n, m) for n, m in self.models.items()
                   if getattr(m, "completion", None) is not None]
        if not capable:
            return 404, {"error": "no completion-capable model"}
        for name, model in capable:
            if not model.ready:
                continue
            return self._dispatch(model, model.completion, payload,
                                  "completion")
        return 503, {"error": "completion model is not ready"}

    # -- http plumbing -----------------------------------------------------

    def _make_handler(server):  # noqa: N805 - closure over the ModelServer
        class Handler(BaseHTTPRequestHandler):
            def _respond(self, method):
                length = int(self.headers.get("Content-Length") or 0)
                body = self.rfile.read(length) if length else b""
                status, obj = server.handle(method, self.path, body,
                                            self.headers)
                if isinstance(obj, TextResponse):
                    data, ctype = obj.body.encode(), obj.content_type
                else:
                    data, ctype = json.dumps(obj).encode(), \
                        "application/json"
                self.send_response(status)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(data)))
                self.end_headers()
                self.wfile.write(data)

            def do_GET(self):
                self._respond("GET")

            def do_POST(self):
                self._respond("POST")

            def log_message(self, fmt, *args):
                log.debug("%s " + fmt, self.client_address[0], *args)

        return Handler

    def _bind(self) -> ThreadingHTTPServer:
        if self._httpd is not None:
            raise RuntimeError("server already started")
        self._httpd = ThreadingHTTPServer((self.host, self.port),
                                          self._make_handler())
        self.port = self._httpd.server_address[1]
        log.info("serving on %s:%d", self.host, self.port)
        return self._httpd

    def start(self) -> None:
        """Start serving in a background thread (returns immediately)."""
        httpd = self._bind()
        threading.Thread(target=httpd.serve_forever, daemon=True).start()

    def serve_forever(self) -> None:
        self.load_all()
        self._bind().serve_forever()

    def stop(self) -> None:
        if self._httpd is not None:
            self._httpd.shutdown()
            self._httpd = None

    def drain(self, timeout: float = 30.0) -> dict:
        """Graceful SIGTERM sequence (the Knative/KServe pod-termination
        contract): ``/readyz`` → 503 and admission stops immediately;
        in-flight requests run to completion (bounded by ``timeout``);
        self-batching workers drain their slots; the listener closes.
        Idempotent; callable from any thread except an HTTP worker."""
        t0 = time.monotonic()
        self._draining = True  # readiness 503 + new POSTs rejected
        while time.monotonic() - t0 < timeout:
            with self._inflight_lock:
                if self._inflight == 0:
                    break
            time.sleep(0.02)
        for model in self.models.values():
            stop = getattr(model, "stop", None)
            if callable(stop):
                try:
                    stop()  # engine/batcher slot drain
                except Exception:  # noqa: BLE001 - drain best-effort
                    log.exception("stopping %s during drain failed",
                                  model.name)
        with self._inflight_lock:
            leftover = self._inflight
        self.stop()
        took = time.monotonic() - t0
        log.info("drain complete in %.2fs (%d request(s) abandoned)",
                 took, leftover)
        return {"drained": leftover == 0, "inflight": leftover,
                "took_s": round(took, 3)}
