"""Trace-replay harness: realistic multi-tenant arrival processes.

Closed-loop load generators (``load_test.py --mode async/ramp``) hold
concurrency constant, so the server's own backpressure throttles the
offered load — fine for throughput ceilings, wrong for SLO claims: a
production tenant mix arrives *open-loop* (users do not wait for each
other), bursty, and Zipf-skewed.  This module gives ``load_test.py
--trace`` that workload as data:

* **Trace schema** — JSONL, one request per line, deterministic and
  diffable::

      {"t": 1.204, "tenant": "tenant-0", "api_key": "key-0",
       "lane": "interactive", "prompt_tokens": 23,
       "max_new_tokens": 16, "id": "r-000017"}

  ``t`` is seconds from replay start (open-loop: the driver fires at
  ``t`` regardless of outstanding requests).  Either ``prompt`` (text)
  or ``prompt_tokens`` (a deterministic synthetic prompt of that many
  byte-tokenizer tokens is derived from ``id``) must be present.
  :func:`validate_trace` rejects anything else, with line numbers.

* **Generators** (:func:`generate_trace`) — arrival processes
  ``poisson`` (homogeneous), ``bursty`` (on/off modulated: quiet base
  rate punctuated by ``burst_factor``× storms), ``diurnal``
  (sinusoidal rate, thinning-sampled); tenant mix Zipf(``zipf_s``)
  over ``n_tenants``; mixed prompt/output lengths (short-interactive /
  long-batch mixture).  Everything derives from one ``seed``: the same
  flags reproduce the same trace byte-for-byte.

* **Replay + per-tenant report** (:func:`replay`) — fires the trace
  open-loop against a served model (tenant identity rides the
  ``X-API-Key`` header when the entry carries ``api_key``, the payload
  ``tenant`` field otherwise), then reports per-tenant p50/p95 TTFT,
  tokens/s, and latency percentiles plus a Jain fairness index over
  per-tenant decoded tokens — the figure BENCHMARKS.md "Multi-tenant
  fairness" tracks.
"""

from __future__ import annotations

import json
import math
import random
import threading
import time
from typing import Any, Mapping, Optional, Sequence

#: schema fields (anything else is a validation error — traces are
#: interchange artifacts, typos must not silently no-op)
REQUIRED_FIELDS = ("t",)
OPTIONAL_FIELDS = ("tenant", "api_key", "lane", "prompt", "prompt_tokens",
                   "max_new_tokens", "id")
_LANES = ("interactive", "batch")


def validate_trace(entries: Sequence[Mapping[str, Any]]) -> None:
    """Raise ``ValueError`` (with the offending line number) unless
    every entry conforms to the trace schema."""
    if not entries:
        raise ValueError("trace is empty")
    for i, e in enumerate(entries, 1):
        if not isinstance(e, Mapping):
            raise ValueError(f"trace line {i}: not an object")
        unknown = set(e) - set(REQUIRED_FIELDS) - set(OPTIONAL_FIELDS)
        if unknown:
            raise ValueError(
                f"trace line {i}: unknown fields {sorted(unknown)}")
        for f in REQUIRED_FIELDS:
            if f not in e:
                raise ValueError(f"trace line {i}: missing {f!r}")
        t = e["t"]
        if not isinstance(t, (int, float)) or isinstance(t, bool) \
                or not math.isfinite(t) or t < 0:
            raise ValueError(
                f"trace line {i}: t must be a finite number >= 0")
        if ("prompt" not in e) == ("prompt_tokens" not in e):
            raise ValueError(
                f"trace line {i}: exactly one of prompt | "
                f"prompt_tokens required")
        if "prompt" in e and (not isinstance(e["prompt"], str)
                              or not e["prompt"]):
            raise ValueError(
                f"trace line {i}: prompt must be a non-empty string")
        if "prompt_tokens" in e and (
                not isinstance(e["prompt_tokens"], int)
                or isinstance(e["prompt_tokens"], bool)
                or e["prompt_tokens"] < 1):
            raise ValueError(
                f"trace line {i}: prompt_tokens must be an int >= 1")
        if "max_new_tokens" in e and (
                not isinstance(e["max_new_tokens"], int)
                or isinstance(e["max_new_tokens"], bool)
                or e["max_new_tokens"] < 1):
            raise ValueError(
                f"trace line {i}: max_new_tokens must be an int >= 1")
        if "lane" in e and e["lane"] not in _LANES:
            raise ValueError(
                f"trace line {i}: lane must be one of {_LANES}")
        for f in ("tenant", "api_key", "id"):
            if f in e and (not isinstance(e[f], str) or not e[f]):
                raise ValueError(
                    f"trace line {i}: {f} must be a non-empty string")


def load_trace(path: str) -> list[dict]:
    """Read + validate a JSONL trace file (blank lines skipped)."""
    entries = []
    with open(path) as f:
        for i, line in enumerate(f, 1):
            line = line.strip()
            if not line:
                continue
            try:
                entries.append(json.loads(line))
            except ValueError as e:
                raise ValueError(f"trace line {i}: invalid JSON: {e}") \
                    from None
    validate_trace(entries)
    return entries


def save_trace(path: str, entries: Sequence[Mapping[str, Any]]) -> None:
    validate_trace(entries)
    with open(path, "w") as f:
        for e in entries:
            f.write(json.dumps(e, sort_keys=True) + "\n")


def zipf_weights(n: int, s: float) -> list[float]:
    """Zipf(s) popularity over ``n`` tenants, normalized (tenant 0 is
    the head of the skew — the "greedy" caller every fairness test
    worries about)."""
    w = [1.0 / (k ** s) for k in range(1, n + 1)]
    total = sum(w)
    return [x / total for x in w]


def zipf_user(rng: random.Random, n_users: int, s: float = 1.3) -> int:
    """Sample ONE user rank in ``[0, n_users)`` from a Zipf(``s``)
    population by inverse-CDF of the Pareto tail envelope
    (``P(rank >= k) ~ k^-(s-1)``) — O(1) per draw with no weight
    table, which is what lets the region-scale simulator
    (serve/simulate.py) model millions of users where
    :func:`zipf_weights` would materialize millions of floats per
    sample."""
    if n_users < 1:
        raise ValueError("n_users must be >= 1")
    if s <= 1.0:
        raise ValueError("zipf inversion needs s > 1")
    u = 1.0 - rng.random()  # (0, 1]: rank**-(s-1) inverted
    rank = int(u ** (-1.0 / (s - 1.0)))
    return min(max(rank - 1, 0), n_users - 1)


def thinning_arrivals(rng: random.Random, duration_s: float, rate_fn,
                      lam_max: float) -> list[float]:
    """Inhomogeneous-Poisson arrival times on ``[0, duration_s)`` by
    Lewis-Shedler thinning: candidates at the envelope rate
    ``lam_max``, each accepted with ``rate_fn(t) / lam_max``.  The
    generic sampler under the ``diurnal`` trace kind and the
    simulator's diurnal-plus-flash-crowd rate curves; ``rate_fn`` may
    dip to (or below) zero but must never exceed ``lam_max``."""
    if lam_max <= 0 or duration_s <= 0:
        raise ValueError("lam_max and duration_s must be > 0")
    out: list[float] = []
    t = 0.0
    while True:
        t += rng.expovariate(lam_max)
        if t >= duration_s:
            return out
        lam = float(rate_fn(t))
        if lam > lam_max * (1 + 1e-9):
            raise ValueError(
                f"rate_fn({t:.3f}) = {lam:.3f} exceeds the thinning "
                f"envelope lam_max = {lam_max:.3f}")
        # one draw per candidate unconditionally: the accept decision
        # AND the rng sequence match the historical diurnal sampler,
        # so seeded traces stay byte-identical
        if rng.random() * lam_max < lam:
            out.append(t)


def _arrival_times(rng: random.Random, kind: str, duration_s: float,
                   rate_rps: float, *, burst_factor: float,
                   period_s: float, amplitude: float) -> list[float]:
    """Sample one arrival process on [0, duration_s)."""
    if rate_rps <= 0 or duration_s <= 0:
        raise ValueError("rate_rps and duration_s must be > 0")
    if kind == "poisson":
        out, t = [], 0.0
        while True:
            t += rng.expovariate(rate_rps)
            if t >= duration_s:
                return out
            out.append(t)
    if kind == "bursty":
        # on/off modulated Poisson: half of each period quiet at the
        # base rate, half a burst_factor x storm — the queue-depth
        # shape admission control and preemption must absorb
        out, t = [], 0.0
        while t < duration_s:
            phase = (t % period_s) / period_s
            lam = rate_rps * (burst_factor if phase < 0.5 else 1.0)
            t += rng.expovariate(lam)
            if t < duration_s:
                out.append(t)
        return out
    if kind == "diurnal":
        # sinusoidal rate via thinning: candidates at the peak rate,
        # accepted with lambda(t)/lambda_max
        return thinning_arrivals(
            rng, duration_s,
            lambda t: rate_rps * (1.0 + amplitude * math.sin(
                2 * math.pi * t / period_s)),
            rate_rps * (1.0 + amplitude))
    raise ValueError(
        f"unknown arrival kind {kind!r} "
        f"(expected poisson | bursty | diurnal)")


def generate_trace(*, kind: str = "poisson", duration_s: float = 30.0,
                   rate_rps: float = 8.0, n_tenants: int = 4,
                   zipf_s: float = 1.1, seed: int = 0,
                   burst_factor: float = 4.0, period_s: float = 10.0,
                   amplitude: float = 0.8,
                   interactive_tenants: Optional[Sequence[str]] = None
                   ) -> list[dict]:
    """Deterministic synthetic trace: ``kind`` arrivals, Zipf tenant
    mix, mixed prompt/output lengths.  Tenants are named
    ``tenant-0..n-1`` with API keys ``key-0..n-1``; by default the
    Zipf head (``tenant-0``) runs the long-prompt/long-output batch
    lane and everyone else is interactive with short prompts — the
    worst-case mix for FIFO scheduling and exactly the one the
    fairness plane exists for.  The default mix reaches prompt 160 +
    max_new 64: replay against a pool with ``max_len >= 224`` or the
    longest batch entries 400 (and the outcome breakdown shows it)."""
    rng = random.Random(seed)
    times = _arrival_times(rng, kind, duration_s, rate_rps,
                           burst_factor=burst_factor, period_s=period_s,
                           amplitude=amplitude)
    weights = zipf_weights(n_tenants, zipf_s)
    names = [f"tenant-{k}" for k in range(n_tenants)]
    if interactive_tenants is None:
        interactive = set(names[1:])
    else:
        interactive = set(interactive_tenants)
    entries = []
    for i, t in enumerate(times):
        tenant = rng.choices(names, weights=weights)[0]
        if tenant in interactive:
            lane = "interactive"
            prompt_tokens = rng.randint(8, 32)
            max_new = rng.choice([4, 8, 16])
        else:
            lane = "batch"
            prompt_tokens = rng.randint(32, 160)
            max_new = rng.choice([16, 32, 64])
        entries.append({
            "t": round(t, 4),
            "tenant": tenant,
            "api_key": f"key-{names.index(tenant)}",
            "lane": lane,
            "prompt_tokens": prompt_tokens,
            "max_new_tokens": max_new,
            "id": f"r-{i:06d}",
        })
    validate_trace(entries)
    return entries


def synthetic_prompt(n_tokens: int, key: str = "") -> str:
    """Deterministic ``n_tokens``-char prompt (byte tokenizer: one char
    = one token), varied by ``key`` so distinct requests do not
    accidentally share a prefix-cache entry."""
    rng = random.Random(f"trace:{key}:{n_tokens}")
    return "".join(rng.choice("abcdefghij klmnop qrstuv wxyz")
                   for _ in range(n_tokens))


def entry_payload(e: Mapping[str, Any]) -> tuple[bytes, dict]:
    """One trace entry → (POST body, extra headers)."""
    prompt = e.get("prompt") or synthetic_prompt(
        int(e["prompt_tokens"]), e.get("id", ""))
    payload: dict[str, Any] = {
        "instances": [prompt],
        "parameters": {
            "max_new_tokens": int(e.get("max_new_tokens", 16)),
            "temperature": 0.0,
        },
    }
    headers: dict[str, str] = {}
    if e.get("api_key"):
        headers["X-API-Key"] = str(e["api_key"])
    elif e.get("tenant"):
        payload["tenant"] = str(e["tenant"])
    if e.get("lane"):
        payload["lane"] = str(e["lane"])
    if e.get("id"):
        headers["X-Request-Id"] = str(e["id"])
    return json.dumps(payload).encode(), headers


def jain_index(values: Sequence[float]) -> Optional[float]:
    """Jain's fairness index over per-tenant allocations: 1.0 =
    perfectly even, 1/n = one tenant took everything.  None when
    nothing was allocated."""
    xs = [float(v) for v in values]
    if not xs or not any(xs):
        return None
    sq = sum(x * x for x in xs)
    return round((sum(xs) ** 2) / (len(xs) * sq), 4)


def replay(url: str, entries: Sequence[Mapping[str, Any]], *,
           timeout: float = 300.0, speed: float = 1.0,
           headers: Optional[Mapping[str, str]] = None,
           max_workers: int = 128) -> dict:
    """Fire the trace open-loop and report per-tenant SLO stats.

    The dispatcher sleeps to each entry's ``t / speed`` and hands the
    request to a worker pool — arrivals never wait for completions.
    ``max_workers`` bounds true concurrency: a dispatch landing while
    every worker is busy queues inside the pool and fires late, which
    silently degrades the open-loop contract toward closed-loop — so
    every such dispatch (and any dispatcher oversleep) is counted in
    ``late_dispatches``; a nonzero count means raise ``max_workers``
    before trusting the latency figures."""
    from concurrent.futures import ThreadPoolExecutor

    from kubernetes_cloud_tpu.serve.load_test import _one_request

    ordered = sorted(entries, key=lambda e: e["t"])
    results: list[tuple[str, Any]] = []
    lock = threading.Lock()
    late = [0]
    inflight = [0]

    def fire(e):
        payload, extra = entry_payload(e)
        hdrs = {**(headers or {}), **extra}
        r = _one_request(url, payload, timeout, hdrs)
        with lock:
            inflight[0] -= 1
            results.append((str(e.get("tenant") or "default"), r))

    t0 = time.monotonic()
    with ThreadPoolExecutor(max_workers=max_workers) as pool:
        for e in ordered:
            due = t0 + float(e["t"]) / max(speed, 1e-9)
            delay = due - time.monotonic()
            if delay > 0:
                time.sleep(delay)
            with lock:
                # a saturated pool parks this submission behind an
                # in-flight request: the arrival will fire late
                if inflight[0] >= max_workers or delay < -0.05:
                    late[0] += 1
                inflight[0] += 1
            pool.submit(fire, e)
    total = time.monotonic() - t0
    return _report(results, total, late[0])


def _percentile(xs: list[float], p: float) -> Optional[float]:
    if not xs:
        return None
    xs = sorted(xs)
    return round(xs[min(len(xs) - 1, int(p * len(xs)))], 4)


def _report(results: list, total_time: float, late: int) -> dict:
    by_tenant: dict[str, list] = {}
    for tenant, r in results:
        by_tenant.setdefault(tenant, []).append(r)
    per_tenant = {}
    tokens_by_tenant = {}
    for tenant, rs in sorted(by_tenant.items()):
        ok = [r for r in rs if r.ok]
        lat = [r.latency for r in ok]
        ttfts = [r.ttft for r in ok if r.ttft is not None]
        toks = sum(r.tokens_out for r in ok)
        outcomes: dict[str, int] = {}
        for r in rs:
            outcomes[r.outcome] = outcomes.get(r.outcome, 0) + 1
        tokens_by_tenant[tenant] = toks
        per_tenant[tenant] = {
            "requests": len(rs),
            "successful": len(ok),
            "tokens_out_total": toks,
            "tokens_out_per_sec": round(toks / max(total_time, 1e-9), 4),
            "ttft_p50_s": _percentile(ttfts, 0.50),
            "ttft_p95_s": _percentile(ttfts, 0.95),
            "latency_p50_s": _percentile(lat, 0.50),
            "latency_p95_s": _percentile(lat, 0.95),
            "outcomes": outcomes,
        }
    return {
        "mode": "trace-replay",
        "requests": len(results),
        "total_time_s": round(total_time, 4),
        "late_dispatches": late,
        "tenants": per_tenant,
        # fairness over raw per-tenant decoded tokens: the
        # equal-weight figure; weighted setups divide by weight first
        # (scripts/bench_serving.py --fairness does)
        "jain_fairness_index": jain_index(
            list(tokens_by_tenant.values())),
    }
