"""Byte-level BPE codec (GPT-2 family), dependency-free.

The reference vendors a byte-pair encoder into its GPT-2 transformer
sidecar (``online-inference/gpt-2/transformer/encoder.py``) so the
pre/post-processing container needs no ML stack; this is the same
capability implemented from the published GPT-2 BPE algorithm: a byte→
unicode trampoline, greedy merge loop over ``merges.txt`` ranks, and a
regex pre-tokenizer.  Loads the standard ``vocab.json`` + ``merges.txt``
pair (what HF tokenizers write), so artifacts from the C++
``dataset_tokenizer`` (``csrc/dataset_tokenizer``) and HF checkpoints both
work.
"""

from __future__ import annotations

import json
import os
import re
from functools import lru_cache

# GPT-2's published pre-tokenization pattern, exactly, via the ``regex``
# module's \p{L}/\p{N} classes (so token boundaries match HF artifacts for
# all unicode letters/numerics, e.g. '²' is category-N, not a letter).
# Fallback for stdlib-only environments: letters = ``[^\W\d_]``,
# "punctuation" = everything neither whitespace nor letter nor digit —
# including '_', hence the explicit ``|_``.  Both round-trip
# byte-identically because byte-level BPE encodes whatever the splitter
# yields; only boundary placement (and thus merge behavior) differs.
try:
    import regex as _regex

    _PAT = _regex.compile(
        r"'s|'t|'re|'ve|'m|'ll|'d| ?\p{L}+| ?\p{N}+| ?[^\s\p{L}\p{N}]+"
        r"|\s+(?!\S)|\s+")
except ImportError:  # pragma: no cover - regex is in the baked image
    _PAT = re.compile(
        r"'s|'t|'re|'ve|'m|'ll|'d| ?[^\W\d_]+| ?\d+| ?(?:[^\s\w]|_)+"
        r"|\s+(?!\S)|\s+",
        re.UNICODE,
    )


@lru_cache(maxsize=1)
def bytes_to_unicode() -> dict[int, str]:
    """The reversible byte→printable-unicode map BPE operates over."""
    bs = (list(range(ord("!"), ord("~") + 1))
          + list(range(ord("\xa1"), ord("\xac") + 1))
          + list(range(ord("\xae"), ord("\xff") + 1)))
    cs = bs[:]
    n = 0
    for b in range(256):
        if b not in bs:
            bs.append(b)
            cs.append(256 + n)
            n += 1
    return dict(zip(bs, map(chr, cs)))


def _pairs(word: tuple[str, ...]) -> set[tuple[str, str]]:
    return set(zip(word, word[1:]))


class BPECodec:
    def __init__(self, vocab: dict[str, int],
                 merges: list[tuple[str, str]]):
        self.encoder = dict(vocab)
        self.decoder = {v: k for k, v in vocab.items()}
        self.ranks = {m: i for i, m in enumerate(merges)}
        self.byte_enc = bytes_to_unicode()
        self.byte_dec = {v: k for k, v in self.byte_enc.items()}
        self._cache: dict[str, tuple[str, ...]] = {}

    @classmethod
    def from_dir(cls, path: str) -> "BPECodec":
        with open(os.path.join(path, "vocab.json")) as f:
            vocab = json.load(f)
        merges = []
        with open(os.path.join(path, "merges.txt")) as f:
            for line in f:
                line = line.rstrip("\r\n")  # tolerate CRLF merges.txt
                # Only the '#version' header is a comment; real merge rules
                # can begin with '#' (e.g. "# #" building the '##' token).
                if not line or line.startswith("#version"):
                    continue
                a, _, b = line.partition(" ")
                merges.append((a, b))
        return cls(vocab, merges)

    # -- core merge loop ---------------------------------------------------

    def _bpe(self, token: str) -> tuple[str, ...]:
        cached = self._cache.get(token)
        if cached is not None:
            return cached
        word = tuple(token)
        while len(word) > 1:
            pairs = _pairs(word)
            best = min(pairs,
                       key=lambda p: self.ranks.get(p, float("inf")))
            if best not in self.ranks:
                break
            a, b = best
            merged: list[str] = []
            i = 0
            while i < len(word):
                if (i < len(word) - 1 and word[i] == a
                        and word[i + 1] == b):
                    merged.append(a + b)
                    i += 2
                else:
                    merged.append(word[i])
                    i += 1
            word = tuple(merged)
        self._cache[token] = word
        return word

    # -- public API --------------------------------------------------------

    def encode(self, text: str) -> list[int]:
        ids: list[int] = []
        for tok in _PAT.findall(text):
            mapped = "".join(self.byte_enc[b] for b in tok.encode("utf-8"))
            ids.extend(self.encoder[piece] for piece in self._bpe(mapped))
        return ids

    def decode(self, ids: list[int]) -> str:
        text = "".join(self.decoder[i] for i in ids)
        data = bytes(self.byte_dec[c] for c in text)
        return data.decode("utf-8", errors="replace")
