"""Native elastic autoscaler: role-aware replica pools, an activator
for scale-from-zero, panic-mode burst scaling, predictive pre-warming.

The paper's serving layer is KServe-on-Knative: every workload scales
on ``autoscaling.knative.dev/target`` concurrency with ``minScale: 0``
and an *activator* that holds requests while a pod cold-starts
(PAPERS.md KServe entry).  Everything below this module serves a
*fixed* replica set — PR 10's :class:`~kubernetes_cloud_tpu.serve.
fleet.FleetRouter` routes over whatever replicas it was handed.  This
module is the missing control plane, expressed natively over the
repo's own machinery instead of Knative CRDs:

* **Target tracking** (:class:`Autoscaler`).  A Knative-KPA-shaped
  control loop: per-role observed concurrency (in-flight + queued +
  activator-held) is averaged over a *stable* window to size the pool
  at ``ceil(concurrency / target_concurrency)``, while a short *panic*
  window watches for bursts — when the panic-window demand would need
  ``panic_threshold``× the ready pool, the loop enters **panic mode**:
  it scales straight to the panic-window desired count and refuses to
  scale down until the panic holds clear for ``panic_hold_s``.
* **Role-aware pools**.  Since PR 14 the fleet is role-split
  (DistServe): prefill replicas answer TTFT, decode slices answer
  TPOT.  Each role (``prefill`` / ``decode`` / ``colocated``) gets its
  own :class:`RolePolicy` (min/max, concurrency target) and its own
  independent control state — DistServe's placement optimizer
  expressed as a control loop instead of a one-shot solve.
* **Scale-to-zero + activator** (:class:`Activator`).  With
  ``min_replicas == 0`` an idle pool drains to nothing after
  ``scale_to_zero_grace_s``.  The first arrival then *holds* on the
  activator (the HTTP thread is the queue — exactly Knative's
  activator-in-the-data-path), pokes the control loop for immediate
  scale-up, and replays once a replica probes healthy.  Nothing is
  dropped and nothing is prefilled twice: the request is dispatched
  exactly once, after capacity exists.
* **Measured cold-start prior**.  Every spawn is timed spawn-begin →
  replica-probed-healthy and folded into an EWMA prior
  (``cold_start_prior_s`` until the first measurement) — the number
  predictive pre-warming plans around and the simulator calibrates
  against.
* **Predictive pre-warming**.  The recent arrival-rate trend (linear
  fit over ``trend_window_s``) projects demand one cold-start ahead;
  a rising trend provisions capacity *before* the queue builds, so a
  diurnal ramp is absorbed by replicas that were already warming.
* **Hysteresis & cooldown**.  Scale-up is immediate (queues hurt now);
  scale-down requires the surplus to persist for
  ``scale_down_delay_s`` AND a ``cooldown_s`` gap since the last scale
  event — flapping probes or a noisy minute cannot thrash the pool
  through spawn/drain cycles.

The loop talks to the world through :class:`ScalingTarget` — signals
in (:class:`PoolSignals`), spawn/drain verbs out.  Two bindings exist:
:class:`ElasticFleet` drives a real :class:`~kubernetes_cloud_tpu.
serve.fleet.FleetRouter` (spawn = build a :class:`LocalReplica` via a
factory and probe it healthy; drain = the fleet's zero-drop rolling
machinery: stop routing, transplant the queue into peers, wait out
in-flight, remove), and :class:`~kubernetes_cloud_tpu.serve.simulate.
SimFleet` drives the region-scale simulator — the SAME control loop
code is what the simulator measures, so the flash-crowd numbers in
BENCHMARKS.md "Elastic fleet" exercise this module, not a model of it.

deploy/README.md "Elastic autoscaling" maps every Knative annotation
(``autoscaling.knative.dev/target``, ``minScale`` / ``maxScale``,
panic windows) onto the :class:`AutoscalerConfig` fields below.
"""

from __future__ import annotations

import dataclasses
import logging
import math
import threading
import time
from typing import Callable, Mapping, Optional, Sequence

from kubernetes_cloud_tpu import obs

log = logging.getLogger(__name__)

#: serving roles a pool can scale (serve/continuous.py EngineConfig.role)
ROLES = ("colocated", "prefill", "decode")

# Autoscaler metric families (labels: the bounded role vocabulary)
_M_DESIRED = obs.gauge(
    "kct_autoscaler_desired_replicas",
    "Replicas the control loop wants per role (post-clamp).", ("role",))
_M_ACTUAL = obs.gauge(
    "kct_autoscaler_replicas",
    "Replicas per role by lifecycle state (ready | starting | "
    "draining).", ("role", "state"))
_M_PANIC = obs.gauge(
    "kct_autoscaler_panic",
    "1 while the role's pool is in panic-mode burst scaling.",
    ("role",))
_M_COLD_START = obs.histogram(
    "kct_autoscaler_cold_start_seconds",
    "Measured spawn-begin to replica-probed-healthy cold starts.",
    ("role",),
    buckets=(0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 20.0, 40.0,
             80.0, 160.0))
_M_ACT_DEPTH = obs.gauge(
    "kct_autoscaler_activator_queue_depth",
    "Requests held by the activator awaiting a cold-starting replica.")
_M_SCALE_EVENTS = obs.counter(
    "kct_autoscaler_scale_events_total",
    "Scale decisions applied per role by direction (up | down).",
    ("role", "direction"))


@dataclasses.dataclass(frozen=True)
class RolePolicy:
    """Per-role pool bounds + concurrency target.

    ``target_concurrency`` is Knative's
    ``autoscaling.knative.dev/target``: the in-flight + queued requests
    one replica should carry; the pool is sized at
    ``ceil(observed / target)``.  ``min_replicas`` / ``max_replicas``
    are ``minScale`` / ``maxScale`` (``min_replicas = 0`` enables
    scale-to-zero through the activator)."""

    min_replicas: int = 1
    max_replicas: int = 8
    target_concurrency: float = 4.0

    def __post_init__(self):
        if self.min_replicas < 0:
            raise ValueError("min_replicas must be >= 0")
        if self.max_replicas < max(self.min_replicas, 1):
            raise ValueError(
                "max_replicas must be >= max(min_replicas, 1)")
        if self.target_concurrency <= 0:
            raise ValueError("target_concurrency must be > 0")


def _default_roles() -> Mapping[str, RolePolicy]:
    return {"colocated": RolePolicy()}


@dataclasses.dataclass(frozen=True)
class AutoscalerConfig:
    """Control-loop knobs.  deploy/README.md "Elastic autoscaling"
    maps each onto its Knative annotation."""

    #: control-loop cadence (Knative: tick-interval, 2 s default there)
    tick_s: float = 1.0
    #: stable concurrency window (autoscaling.knative.dev/window)
    stable_window_s: float = 30.0
    #: panic window (panic-window-percentage x stable window)
    panic_window_s: float = 6.0
    #: panic entry: panic-window desired >= threshold x ready pool
    #: (panic-threshold-percentage / 100)
    panic_threshold: float = 2.0
    #: stay panicked (no scale-down) this long after the last trigger
    panic_hold_s: float = 30.0
    #: scale-down hysteresis: the surplus must persist this long
    #: (scale-down-delay)
    scale_down_delay_s: float = 15.0
    #: minimum gap between applied scale events per role
    cooldown_s: float = 5.0
    #: idle time before a min_replicas=0 pool drains to nothing
    #: (scale-to-zero-grace-period)
    scale_to_zero_grace_s: float = 30.0
    #: bound on replicas added by one decision (0 = unbounded;
    #: max-scale-up-rate)
    max_scale_up_step: int = 0
    #: predictive pre-warming from the arrival-rate trend
    prewarm: bool = True
    trend_window_s: float = 30.0
    #: cold-start prior until a spawn is measured; measurements fold
    #: in at this EWMA weight
    cold_start_prior_s: float = 10.0
    cold_start_ewma_alpha: float = 0.4
    #: bound on one activator hold (a cold start slower than this
    #: fails the held request retryable — the client's ladder retries)
    activator_max_hold_s: float = 60.0
    #: bound on waiting out a draining replica's in-flight work
    drain_timeout_s: float = 30.0
    #: per-role policies; roles absent here are not scaled
    roles: Mapping[str, RolePolicy] = dataclasses.field(
        default_factory=_default_roles)

    def __post_init__(self):
        if self.tick_s <= 0:
            raise ValueError("tick_s must be > 0")
        if self.stable_window_s <= 0 or self.panic_window_s <= 0:
            raise ValueError("windows must be > 0")
        if self.panic_window_s > self.stable_window_s:
            raise ValueError("panic_window_s must be <= stable_window_s")
        if self.panic_threshold < 1.0:
            raise ValueError("panic_threshold must be >= 1.0")
        if min(self.panic_hold_s, self.scale_down_delay_s,
               self.cooldown_s, self.scale_to_zero_grace_s) < 0:
            raise ValueError("delays must be >= 0")
        if self.max_scale_up_step < 0:
            raise ValueError("max_scale_up_step must be >= 0 (0 = off)")
        if self.cold_start_prior_s <= 0:
            raise ValueError("cold_start_prior_s must be > 0")
        if not 0 < self.cold_start_ewma_alpha <= 1:
            raise ValueError("cold_start_ewma_alpha must be in (0, 1]")
        if self.activator_max_hold_s <= 0 or self.drain_timeout_s <= 0:
            raise ValueError("hold/drain bounds must be > 0")
        for role, pol in self.roles.items():
            if role not in ROLES:
                raise ValueError(f"unknown role {role!r} "
                                 f"(expected one of {ROLES})")
            if not isinstance(pol, RolePolicy):
                raise ValueError(f"roles[{role!r}] must be a RolePolicy")

    def policy(self, role: str) -> Optional[RolePolicy]:
        return self.roles.get(role)


#: the Knative annotation each AutoscalerConfig / RolePolicy field
#: replaces (deploy/README.md renders this as the migration table and
#: tests lock it against the config surface)
KNATIVE_ANNOTATIONS = {
    "autoscaling.knative.dev/target": "RolePolicy.target_concurrency",
    "autoscaling.knative.dev/minScale": "RolePolicy.min_replicas",
    "autoscaling.knative.dev/maxScale": "RolePolicy.max_replicas",
    "autoscaling.knative.dev/window": "AutoscalerConfig.stable_window_s",
    "autoscaling.knative.dev/panic-window-percentage":
        "AutoscalerConfig.panic_window_s",
    "autoscaling.knative.dev/panic-threshold-percentage":
        "AutoscalerConfig.panic_threshold",
    "autoscaling.knative.dev/scale-down-delay":
        "AutoscalerConfig.scale_down_delay_s",
    "autoscaling.knative.dev/scale-to-zero-grace-period":
        "AutoscalerConfig.scale_to_zero_grace_s",
    "autoscaling.knative.dev/activation-scale": "Activator (hold+replay)",
}


@dataclasses.dataclass
class PoolSignals:
    """One role's observed state, sampled by the control loop each
    tick.  ``concurrency`` is in-flight + queued on ready replicas;
    ``activator_depth`` is requests held awaiting capacity;
    ``arrivals`` is a cumulative arrival count (the rate-trend input)."""

    ready: int = 0
    starting: int = 0
    draining: int = 0
    concurrency: float = 0.0
    activator_depth: int = 0
    arrivals: int = 0


class ScalingTarget:
    """What the control loop scales.  ``ElasticFleet`` binds a real
    router; ``simulate.SimFleet`` binds the region-scale simulator —
    both run the SAME :class:`Autoscaler`."""

    def roles(self) -> Sequence[str]:
        raise NotImplementedError

    def signals(self, role: str) -> PoolSignals:
        raise NotImplementedError

    def scale_up(self, role: str, n: int) -> int:
        """Begin ``n`` cold starts; returns how many actually began."""
        raise NotImplementedError

    def scale_down(self, role: str, n: int) -> int:
        """Begin draining ``n`` replicas; returns how many began."""
        raise NotImplementedError


class RollingDigest:
    """Bounded rolling sample window with quantiles: ``observe(v)``
    timestamped samples, ``quantile(q)`` over the trailing
    ``window_s``.  Feeds live-TTFT hedging (serve/fleet.py) and the
    control loop's concurrency/arrival-rate windows.  Thread-safe;
    nothing inside blocks."""

    def __init__(self, window_s: float = 60.0, max_samples: int = 4096):
        if window_s <= 0 or max_samples < 1:
            raise ValueError("window_s and max_samples must be > 0")
        self.window_s = float(window_s)
        self.max_samples = int(max_samples)
        self._samples: list[tuple[float, float]] = []
        self._lock = threading.Lock()

    def observe(self, value: float,
                now: Optional[float] = None) -> None:
        t = time.monotonic() if now is None else float(now)
        with self._lock:
            self._samples.append((t, float(value)))
            if len(self._samples) > self.max_samples:
                del self._samples[:len(self._samples)
                                  - self.max_samples]

    def _window(self, now: Optional[float]) -> list[tuple[float, float]]:
        t = time.monotonic() if now is None else float(now)
        cutoff = t - self.window_s
        with self._lock:
            # drop expired samples in place so the list stays small
            i = 0
            for i, (ts, _) in enumerate(self._samples):
                if ts >= cutoff:
                    break
            else:
                i = len(self._samples)
            if i:
                del self._samples[:i]
            return list(self._samples)

    def count(self, now: Optional[float] = None) -> int:
        return len(self._window(now))

    def quantile(self, q: float, now: Optional[float] = None,
                 min_samples: int = 1) -> Optional[float]:
        if not 0 <= q <= 1:
            raise ValueError("q must be in [0, 1]")
        vals = sorted(v for _, v in self._window(now))
        if len(vals) < max(min_samples, 1):
            return None
        return vals[min(len(vals) - 1, int(q * len(vals)))]

    def mean(self, now: Optional[float] = None) -> Optional[float]:
        vals = [v for _, v in self._window(now)]
        return sum(vals) / len(vals) if vals else None

    def trend(self, now: Optional[float] = None
              ) -> tuple[Optional[float], float]:
        """Least-squares ``(latest_fit, slope_per_s)`` over the window
        — the predictive pre-warm input.  ``(None, 0)`` below 2
        samples."""
        pts = self._window(now)
        if len(pts) < 2:
            return (pts[0][1] if pts else None), 0.0
        t0 = pts[0][0]
        xs = [t - t0 for t, _ in pts]
        ys = [v for _, v in pts]
        n = len(pts)
        mx, my = sum(xs) / n, sum(ys) / n
        var = sum((x - mx) ** 2 for x in xs)
        if var <= 1e-12:
            return ys[-1], 0.0
        slope = sum((x - mx) * (y - my)
                    for x, y in zip(xs, ys)) / var
        fit = my + slope * (xs[-1] - mx)
        return fit, slope


class Activator:
    """Hold-and-replay for scale-from-zero (Knative's activator in the
    data path).  An HTTP thread that finds no routable replica parks
    here; the park itself is the demand signal (``on_demand`` pokes
    the control loop immediately — no waiting out a tick), and
    ``notify_capacity()`` (a replica probed healthy) wakes every
    waiter to retry its pick.  The waiting thread IS the queue: the
    request body never moves, so a replayed request is dispatched —
    and prefilled — exactly once."""

    def __init__(self, max_hold_s: float = 60.0,
                 on_demand: Optional[Callable[[], None]] = None):
        if max_hold_s <= 0:
            raise ValueError("max_hold_s must be > 0")
        self.max_hold_s = float(max_hold_s)
        self.on_demand = on_demand
        self._cond = threading.Condition()
        self._depth = 0
        self._capacity_seq = 0
        self.stats = {"held": 0, "replayed": 0, "timeouts": 0}

    @property
    def depth(self) -> int:
        return self._depth

    def hold(self, deadline: Optional[float] = None) -> bool:
        """Park the calling thread until capacity is announced (True)
        or ``deadline`` (monotonic; default now + ``max_hold_s``)
        passes (False).  Callers loop: a wake only means "re-pick", not
        "your replica"."""
        if deadline is None:
            deadline = time.monotonic() + self.max_hold_s
        poke = self.on_demand
        with self._cond:
            self._depth += 1
            self.stats["held"] += 1
            _M_ACT_DEPTH.set(self._depth)
            seq = self._capacity_seq
        if poke is not None:
            try:  # the poke is advisory; a raising hook must not 500
                # the held request
                poke()
            except Exception:  # noqa: BLE001 - demand signal best-effort
                log.exception("activator on_demand hook failed")
        try:
            with self._cond:
                ok = self._cond.wait_for(
                    lambda: self._capacity_seq != seq,
                    timeout=max(deadline - time.monotonic(), 0.0))
                self.stats["replayed" if ok else "timeouts"] += 1
                return ok
        finally:
            with self._cond:
                self._depth -= 1
                _M_ACT_DEPTH.set(self._depth)

    def notify_capacity(self) -> None:
        with self._cond:
            self._capacity_seq += 1
            self._cond.notify_all()


class _RoleState:
    """Per-role controller memory (windows, panic, hysteresis)."""

    def __init__(self, cfg: AutoscalerConfig):
        self.conc = RollingDigest(window_s=cfg.stable_window_s)
        self.panic_conc = RollingDigest(window_s=cfg.panic_window_s)
        self.rate = RollingDigest(window_s=cfg.trend_window_s)
        self.last_arrivals: Optional[int] = None
        self.last_sample_t: Optional[float] = None
        self.panic_until = -math.inf
        self.below_since: Optional[float] = None
        self.idle_since: Optional[float] = None
        self.last_scale_at = -math.inf
        self.desired = 0


class Autoscaler:
    """The control loop.  Deterministic and clock-injectable: tests
    and the simulator call :meth:`step` with explicit virtual ``now``;
    live fleets run :meth:`start`'s thread on the wall clock."""

    def __init__(self, target: ScalingTarget,
                 cfg: AutoscalerConfig = AutoscalerConfig(), *,
                 clock: Callable[[], float] = time.monotonic):
        self.target = target
        self.cfg = cfg
        self.clock = clock
        self._states: dict[str, _RoleState] = {}
        self._cold_start_ewma: dict[str, float] = {}
        #: guards the cold-start prior fold: note_cold_start runs on
        #: every spawner thread (a scale-up of N spawns N at once) and
        #: the EWMA read-fold-store would drop measurements unguarded
        self._prior_lock = threading.Lock()
        self._kick = threading.Event()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self.stats = {"ticks": 0, "scale_ups": 0, "scale_downs": 0,
                      "panics": 0, "prewarm_ups": 0, "cold_starts": 0}
        self._m_desired = {r: _M_DESIRED.labels(role=r) for r in ROLES}
        self._m_panic = {r: _M_PANIC.labels(role=r) for r in ROLES}
        self._m_actual = {
            (r, s): _M_ACTUAL.labels(role=r, state=s)
            for r in ROLES for s in ("ready", "starting", "draining")}

    # -- cold-start prior ---------------------------------------------------

    def cold_start_s(self, role: str) -> float:
        """The planning prior: measured EWMA once spawns happened, the
        configured prior until then."""
        return self._cold_start_ewma.get(role,
                                         self.cfg.cold_start_prior_s)

    def note_cold_start(self, role: str, seconds: float) -> None:
        """Fold one measured spawn→healthy duration into the prior
        (targets call this; the histogram feeds dashboards)."""
        seconds = float(seconds)
        _M_COLD_START.labels(role=role).observe(seconds)
        with self._prior_lock:
            self.stats["cold_starts"] += 1
            prev = self._cold_start_ewma.get(role)
            a = self.cfg.cold_start_ewma_alpha
            self._cold_start_ewma[role] = (
                seconds if prev is None else a * seconds + (1 - a) * prev)

    def seed_cold_start(self, role: str, seconds: float) -> None:
        """Pre-populate the prior from an out-of-band measurement
        (the ``bench_serving --cold-start`` record) WITHOUT counting a
        spawn: a fresh autoscaler starts planning with the measured
        startup→first-token time instead of the configured guess.
        Live ``note_cold_start`` measurements fold over it normally."""
        seconds = float(seconds)
        if seconds <= 0:
            raise ValueError("cold-start seed must be > 0 seconds")
        with self._prior_lock:
            self._cold_start_ewma.setdefault(role, seconds)

    def seed_from_benchmark(self, record: Any) -> int:
        """Seed priors from a ``bench_serving.py --cold-start`` JSON
        record (a dict, a JSON string, or a path to a file of one
        record per line — the bench's output convention).  Reads
        ``{"cold_start_s": {role: seconds}}``; returns how many roles
        were seeded.  Unknown shapes seed nothing (0) rather than
        raise — the bench file is advisory input, not config."""
        import json
        import os

        records: list = []
        if isinstance(record, dict):
            records = [record]
        elif isinstance(record, str):
            if os.path.exists(record):
                with open(record) as f:
                    for line in f:
                        line = line.strip()
                        if not line:
                            continue
                        try:
                            records.append(json.loads(line))
                        except ValueError:
                            continue
            else:
                try:
                    records.append(json.loads(record))
                except ValueError:
                    return 0
        seeded = 0
        for rec in records:
            if not isinstance(rec, dict):
                continue
            per_role = rec.get("cold_start_s")
            if not isinstance(per_role, dict):
                continue
            for role, seconds in per_role.items():
                try:
                    self.seed_cold_start(str(role), float(seconds))
                    seeded += 1
                except (TypeError, ValueError):
                    continue
        return seeded

    # -- the control loop ---------------------------------------------------

    def kick(self) -> None:
        """Demand signal (the activator's ``on_demand``): run a tick
        now instead of waiting out the interval."""
        self._kick.set()

    def step(self, now: Optional[float] = None) -> dict:
        """One control tick over every configured role; returns the
        per-role decision detail (tests and the simulator assert on
        it)."""
        now = self.clock() if now is None else float(now)
        self.stats["ticks"] += 1
        out = {}
        for role in self.target.roles():
            pol = self.cfg.policy(role)
            if pol is None:
                continue
            out[role] = self._step_role(role, pol, now)
        return out

    def _step_role(self, role: str, pol: RolePolicy, now: float) -> dict:
        cfg = self.cfg
        st = self._states.get(role)
        if st is None:
            st = self._states[role] = _RoleState(cfg)
        sig = self.target.signals(role)
        conc = float(sig.concurrency) + float(sig.activator_depth)
        st.conc.observe(conc, now=now)
        st.panic_conc.observe(conc, now=now)

        # arrival rate sample from the cumulative counter
        if (st.last_arrivals is not None and st.last_sample_t is not None
                and now > st.last_sample_t):
            dt = now - st.last_sample_t
            st.rate.observe(
                max(sig.arrivals - st.last_arrivals, 0) / dt, now=now)
        st.last_arrivals = sig.arrivals
        st.last_sample_t = now

        stable = st.conc.mean(now=now) or 0.0
        panic = st.panic_conc.mean(now=now) or 0.0
        desired_stable = math.ceil(stable / pol.target_concurrency)
        desired_panic = math.ceil(panic / pol.target_concurrency)

        # panic entry: the short window would need threshold x the
        # ready pool — burst traffic must not wait out the stable
        # window's inertia
        ready = max(sig.ready, 1)
        if (desired_panic > sig.ready
                and desired_panic >= cfg.panic_threshold * ready):
            if now >= st.panic_until:
                self.stats["panics"] += 1
            st.panic_until = now + cfg.panic_hold_s
        in_panic = now < st.panic_until
        desired = max(desired_stable, desired_panic) if in_panic \
            else desired_stable

        # predictive pre-warming: project the arrival-rate trend one
        # cold start ahead; Little's law converts rate to concurrency
        # through the currently-observed concurrency-per-rps
        prewarmed = False
        if cfg.prewarm:
            rate_now, slope = st.rate.trend(now=now)
            if rate_now and rate_now > 0 and slope > 0:
                horizon = self.cold_start_s(role) + cfg.tick_s
                projected = stable * (rate_now + slope * horizon) \
                    / rate_now
                want = math.ceil(projected / pol.target_concurrency)
                if want > desired:
                    desired = want
                    prewarmed = True

        # a held arrival IS demand: never sit at zero with waiters
        if sig.activator_depth > 0:
            desired = max(desired, 1)

        # scale-to-zero: with min_replicas == 0 target tracking wants
        # 0 the moment the pool goes idle — the grace period holds the
        # LAST replica warm until the idleness has lasted; only then
        # does the pool actually drain to nothing
        if conc <= 0 and sig.activator_depth == 0:
            if st.idle_since is None:
                st.idle_since = now
        else:
            st.idle_since = None
        desired = min(max(desired, pol.min_replicas), pol.max_replicas)
        if (pol.min_replicas == 0 and desired == 0
                and (sig.ready + sig.starting) > 0
                and (st.idle_since is None or now - st.idle_since
                     < cfg.scale_to_zero_grace_s)):
            desired = 1

        current = sig.ready + sig.starting
        applied = 0
        if desired > current:
            n = desired - current
            if cfg.max_scale_up_step:
                n = min(n, cfg.max_scale_up_step)
            applied = self.target.scale_up(role, n)
            if applied:
                st.last_scale_at = now
                st.below_since = None
                self.stats["scale_ups"] += 1
                if prewarmed:
                    self.stats["prewarm_ups"] += 1
                _M_SCALE_EVENTS.labels(role=role, direction="up") \
                    .inc(applied)
        elif desired < current:
            if in_panic:
                st.below_since = None  # never scale down in panic
            else:
                if st.below_since is None:
                    st.below_since = now
                if (now - st.below_since >= cfg.scale_down_delay_s
                        and now - st.last_scale_at >= cfg.cooldown_s):
                    applied = -self.target.scale_down(
                        role, current - desired)
                    if applied:
                        st.last_scale_at = now
                        st.below_since = None
                        self.stats["scale_downs"] += 1
                        _M_SCALE_EVENTS.labels(
                            role=role, direction="down").inc(-applied)
        else:
            st.below_since = None

        st.desired = desired
        self._m_desired[role].set(desired)
        self._m_panic[role].set(1 if in_panic else 0)
        self._m_actual[(role, "ready")].set(sig.ready)
        self._m_actual[(role, "starting")].set(sig.starting)
        self._m_actual[(role, "draining")].set(sig.draining)
        return {"desired": desired, "ready": sig.ready,
                "starting": sig.starting, "draining": sig.draining,
                "concurrency": round(conc, 3),
                "stable": round(stable, 3), "panic": round(panic, 3),
                "in_panic": in_panic, "prewarmed": prewarmed,
                "applied": applied,
                "cold_start_s": round(self.cold_start_s(role), 3)}

    # -- live thread --------------------------------------------------------

    def start(self) -> None:
        if self._thread is not None and self._thread.is_alive():
            return
        self._stop.clear()
        self._thread = threading.Thread(
            target=self._run, daemon=True, name="autoscaler")
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        self._kick.set()
        if self._thread is not None:
            self._thread.join(timeout=5.0)
            self._thread = None

    def _run(self) -> None:
        while not self._stop.is_set():
            self._kick.wait(timeout=self.cfg.tick_s)
            self._kick.clear()
            if self._stop.is_set():
                return
            try:
                self.step()
            except Exception:  # noqa: BLE001 - the loop never dies; a
                # failed tick is retried next interval
                log.exception("autoscaler tick failed")

    def snapshot(self) -> dict:
        out = {"stats": dict(self.stats), "roles": {}}
        for role, st in self._states.items():
            out["roles"][role] = {
                "desired": st.desired,
                "in_panic": self.clock() < st.panic_until,
                "cold_start_s": round(self.cold_start_s(role), 3),
            }
        return out


class ElasticFleet(ScalingTarget):
    """Binds the control loop to a live :class:`~kubernetes_cloud_tpu.
    serve.fleet.FleetRouter`: spawn = factory-build a replica on a
    spawner thread, register it with the router, probe it healthy,
    feed the measured cold start back; drain = the fleet's zero-drop
    machinery (stop routing → transplant queued → wait out in-flight →
    stop → deregister).

    ``factory(role, replica_id)`` returns an **unloaded**
    :class:`~kubernetes_cloud_tpu.serve.fleet.LocalReplica`; the
    spawner thread pays ``load()`` (weights + warmup compile) so the
    measured cold start is honest.  Replicas the router already held
    at attach time join their role's pool (by probed role) and are
    drainable like spawned ones."""

    def __init__(self, router, factory, cfg: AutoscalerConfig, *,
                 clock: Callable[[], float] = time.monotonic):
        self.router = router
        self.factory = factory
        self.cfg = cfg
        self._lock = threading.Lock()
        self._starting: dict[str, int] = {}
        self._draining: dict[str, int] = {}
        self._spawn_seq = 0
        self._arrival_role = ("prefill" if "prefill" in cfg.roles
                              else "colocated")
        self.autoscaler = Autoscaler(self, cfg, clock=clock)
        self.activator = Activator(
            max_hold_s=cfg.activator_max_hold_s,
            on_demand=self.autoscaler.kick)
        router.attach_activator(self.activator)
        for replica in router.replicas:
            self._wire_supervisor(replica)

    def _wire_supervisor(self, replica) -> None:
        """A supervised replica's restarts/circuit-opens change ready
        capacity mid-tick — point the supervisor's capacity hook at
        the control loop so it re-evaluates immediately."""
        server = getattr(replica, "server", None)
        if server is None:
            return
        for model in getattr(server, "models", {}).values():
            sup = getattr(model, "supervisor", None)
            if sup is not None and hasattr(sup, "on_capacity_change"):
                sup.on_capacity_change = self.autoscaler.kick

    # -- lifecycle ----------------------------------------------------------

    def start(self) -> None:
        self.router.start_probing()
        self.autoscaler.start()

    def stop(self) -> None:
        self.autoscaler.stop()

    # -- ScalingTarget ------------------------------------------------------

    def roles(self) -> Sequence[str]:
        return tuple(self.cfg.roles)

    def signals(self, role: str) -> PoolSignals:
        agg = self.router.role_signals().get(role, {})
        with self._lock:
            starting = self._starting.get(role, 0)
            draining = self._draining.get(role, 0)
        arrivals = (self.router.stats.get("arrivals", 0)
                    if role == self._arrival_role else 0)
        act_depth = (self.activator.depth
                     if role == self._arrival_role else 0)
        return PoolSignals(
            ready=int(agg.get("ready", 0)), starting=starting,
            draining=draining,
            concurrency=float(agg.get("concurrency", 0.0)),
            activator_depth=act_depth, arrivals=arrivals)

    def scale_up(self, role: str, n: int) -> int:
        begun = 0
        for _ in range(max(n, 0)):
            with self._lock:
                self._spawn_seq += 1
                rid = f"as-{role}-{self._spawn_seq}"
                self._starting[role] = self._starting.get(role, 0) + 1
            threading.Thread(target=self._spawn, args=(role, rid),
                             daemon=True,
                             name=f"spawn-{rid}").start()
            begun += 1
        return begun

    def _spawn(self, role: str, rid: str) -> None:
        t0 = time.monotonic()
        try:
            replica = self.factory(role, rid)
            load = getattr(replica, "load", None)
            if callable(load):
                load()
            replica.health.role = role
            self._wire_supervisor(replica)
            self.router.add_replica(replica)
            healthy = self.router._wait_healthy(replica)
            if healthy:
                replica.health.force_active()
                self.autoscaler.note_cold_start(
                    role, time.monotonic() - t0)
                self.activator.notify_capacity()
                log.info("%s: spawned %s in %.2fs", role, rid,
                         time.monotonic() - t0)
            else:
                # a spawn that never probes healthy is removed, not
                # left haunting the pool as a permanently-ejected ghost
                replica.health.eject("probe")
                self.router.remove_replica(rid)
                log.error("%s: spawn %s never probed healthy", role,
                          rid)
        except Exception:  # noqa: BLE001 - a failed spawn must not
            # kill the spawner; the control loop will try again
            log.exception("%s: spawn %s failed", role, rid)
            try:
                self.router.remove_replica(rid)
            except Exception:  # noqa: BLE001 - best-effort cleanup
                log.debug("%s: cleanup of failed spawn %s", role, rid)
        finally:
            with self._lock:
                self._starting[role] = max(
                    self._starting.get(role, 1) - 1, 0)

    def scale_down(self, role: str, n: int) -> int:
        """Drain the least-loaded ``n`` active replicas of ``role``
        through the zero-drop path."""
        from kubernetes_cloud_tpu.serve.fleet import ACTIVE, HALF_OPEN

        victims = sorted(
            (r for r in self.router.replicas
             if r.health.role == role
             and r.health.state in (ACTIVE, HALF_OPEN)
             and getattr(r, "restartable", False)),
            key=lambda r: r.load_score())[:max(n, 0)]
        for r in victims:
            r.health.begin_drain()
            with self._lock:
                self._draining[role] = self._draining.get(role, 0) + 1
            threading.Thread(target=self._drain, args=(role, r),
                             daemon=True,
                             name=f"drain-{r.id}").start()
        return len(victims)

    def _drain(self, role: str, replica) -> None:
        try:
            self.router._transplant_from(replica)
            deadline = time.monotonic() + self.cfg.drain_timeout_s
            while replica.inflight > 0 and time.monotonic() < deadline:
                time.sleep(0.02)
            server = getattr(replica, "server", None)
            if server is not None:
                for model in server.models.values():
                    stop = getattr(model, "stop", None)
                    if callable(stop):
                        stop()
            self.router.remove_replica(replica.id)
            log.info("%s: drained and removed %s", role, replica.id)
        except Exception:  # noqa: BLE001 - a failed drain leaves the
            # replica draining (no traffic) rather than dropping work
            log.exception("%s: drain of %s failed", role, replica.id)
        finally:
            with self._lock:
                self._draining[role] = max(
                    self._draining.get(role, 1) - 1, 0)

    def snapshot(self) -> dict:
        return {"autoscaler": self.autoscaler.snapshot(),
                "activator": {"depth": self.activator.depth,
                              **self.activator.stats},
                "fleet": self.router.snapshot()}
