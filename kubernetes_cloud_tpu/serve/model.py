"""Predictor base class — the ``kserve.Model`` contract without kserve.

Mirrors the interface every reference predictor implements
(``online-inference/stable-diffusion/service/service.py:163-258``,
``online-inference/bloom-176b/model/bloom.py:40-90``,
``online-inference/tensorizer-isvc/kserve/kserve_api.py:19-74``): a named
model with ``load()`` flipping ``ready``, ``predict(payload)`` on the V1
data plane, and per-request parameter overrides merged over env-var
defaults (``service.py:216-226``: request keys are upper-cased and looked
up against the option dict).
"""

from __future__ import annotations

import os
import time
from typing import Any, Mapping, Optional


def request_deadline(payload: Mapping[str, Any]) -> Optional[float]:
    """Absolute monotonic deadline from the request's ``deadline_ms``
    budget (set by the client in the payload, or injected by the server
    from the ``X-Request-Deadline-Ms`` header).  None = no deadline.

    The budget is relative so it survives serialization — clients and
    pods don't share a clock; the serving pod anchors it at parse time.
    """
    ms = payload.get("deadline_ms")
    if ms is None:
        return None
    ms = float(ms)
    if not ms >= 0:  # rejects negatives AND NaN (which silently
        # disables every shed comparison downstream)
        raise ValueError("deadline_ms must be >= 0")
    return time.monotonic() + ms / 1000.0


def parse_instances(payload: Mapping[str, Any]) -> list:
    """V1 data-plane ``instances`` validation, shared by every batching
    predictor (one error message, one shape rule)."""
    instances = payload.get("instances")
    if not isinstance(instances, list) or not instances:
        raise ValueError('payload needs a non-empty {"instances": [...]}')
    return instances


def instance_text(inst: Any) -> str:
    """A V1 instance is either a bare string or ``{"text": ...}``."""
    return inst["text"] if isinstance(inst, Mapping) else str(inst)


class Model:
    #: content-hash identity of the loaded weights (``tensorstream.
    #: weights_version`` of the artifact) — None until a versioned
    #: artifact loads.  Surfaced in /readyz bodies, /debug/timeline
    #: meta, and per-prediction responses so fleet probes can tell
    #: replicas apart mid-rollout.
    weights_version: Optional[str] = None

    def __init__(self, name: str):
        self.name = name
        self.ready = False

    def load(self) -> None:
        self.ready = True

    def predict(self, payload: Mapping[str, Any]) -> dict:
        raise NotImplementedError

    # -- readiness ---------------------------------------------------------

    def health(self) -> dict:
        """The model's ``/readyz`` contribution: ``{"ok": bool,
        "reason": str, ...}``.  Supervised models defer to their
        :class:`~kubernetes_cloud_tpu.serve.supervisor.ServingSupervisor`
        (heartbeat freshness, circuit state, queue depth); everything
        else overrides :meth:`_local_health`."""
        sup = getattr(self, "supervisor", None)
        if sup is not None:
            return sup.health(self)
        return self._local_health()

    def _local_health(self) -> dict:
        out = {"ok": self.ready,
               "reason": "ok" if self.ready else "not loaded"}
        if self.weights_version is not None:
            out["weights_version"] = self.weights_version
        return out

    # -- option handling ---------------------------------------------------

    #: subclasses: {"OPTION_NAME": default}; values are parsed from env vars
    #: of the same name at construction (reference ``bloom.py:13-30``).
    OPTIONS: dict[str, Any] = {}

    def default_options(self) -> dict[str, Any]:
        opts = {}
        for key, default in self.OPTIONS.items():
            raw = os.environ.get(key)
            if raw is None:
                opts[key] = default
            elif isinstance(default, bool):
                opts[key] = raw.strip().lower() in ("1", "true", "yes", "on")
            elif isinstance(default, int):
                opts[key] = int(raw)
            elif isinstance(default, float):
                opts[key] = float(raw)
            else:
                opts[key] = raw
        return opts

    def configure_request(self, payload: Mapping[str, Any]) -> dict[str, Any]:
        """Merge request ``parameters`` over env defaults, upper-casing keys
        (byte-compatible with the reference's protocol)."""
        opts = self.default_options()
        for key, value in (payload.get("parameters") or {}).items():
            key = key.upper()
            if key in opts:
                opts[key] = value
        return opts
