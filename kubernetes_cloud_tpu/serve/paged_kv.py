"""Host-side page allocator + cross-request prefix cache for the paged
KV pool (vLLM/PagedAttention, SOSP '23 — see PAPERS.md).

The continuous-batching engine's original pool reserves ``max_len`` KV
rows per slot — pure internal fragmentation whenever completion lengths
vary.  The paged pool replaces that with a fixed arena of
``num_pages × page_size`` rows and a per-slot indirection table; this
module owns every *host-side* decision about that arena:

* **Allocation / refcounts.**  ``reserve()`` claims enough physical
  pages for ``prompt + max_new_tokens`` up front (admission-time
  reservation: a claimed slot can always run to completion, so the
  scheduler never needs mid-decode preemption), ``release()`` drops
  them when the slot evicts.  Pages are refcounted because prefix
  sharing aliases them across requests.
* **Prefix caching.**  Full prompt blocks are identified by *chained*
  block hashes (hash of the block's tokens + the previous block's
  hash, so a match certifies the entire preceding context, not just
  the block).  A new prompt walks its chain through the cache and
  reuses every matched page copy-free — the engine then prefills only
  the unmatched tail.
* **Copy-on-write.**  Matching never hands out a page the request
  would write into — with one deliberate exception: when the prompt is
  exactly page-aligned and *every* block matches, the last prompt
  token must still be recomputed (its logits seed sampling), and that
  token's K/V lands inside the last matched page.  ``reserve()`` then
  allocates a private copy and reports the (src, dst) pair; the engine
  issues the device-side page copy before the tail prefill.
* **LRU eviction.**  A released page whose content is a registered
  prompt block is not freed — it parks in an LRU of refcount-zero
  cached pages, serving future prefix hits, and is evicted only when a
  reservation needs the space.

Deliberately dependency-free (no jax, no numpy): the scheduler thread
calls into it under no lock (single-owner), and ``tests/test_paged_kv
.py`` drives it exhaustively without touching a device.
"""

from __future__ import annotations

import collections
import dataclasses
from typing import Optional, Sequence

from kubernetes_cloud_tpu.serve.errors import KVPagesExhaustedError

#: physical page 0 is the null page: free slots' page-table entries
#: point at it, and the decode program parks masked garbage writes
#: there.  Never allocated, never cached.
NULL_PAGE = 0

#: arena storage modes: "fp32" keeps K/V at the model's cache dtype
#: (the pre-quantization behavior), "int8" stores symmetric int8 with
#: per-page per-kv-head fp32 scales (models/generate.init_page_arena)
KV_DTYPES = ("fp32", "int8")


def kv_page_bytes(page_size: int, kv_heads: int, head_dim: int,
                  kv_dtype: str = "fp32", cache_bytes: int = 4) -> int:
    """Device bytes ONE physical page costs per layer: K + V rows plus,
    for int8, the two fp32 ``[Hkv]`` scale rows riding alongside.
    ``cache_bytes`` is the unquantized cache dtype's width (4 = fp32,
    2 = bf16).  The equal-arena-bytes sizing in
    ``serve.continuous.EngineConfig.arena_pages`` — and the capacity
    math in deploy/README.md "Quantized KV & fused kernels" — both
    read this, so the A/B benchmark and the docs can never disagree."""
    if kv_dtype == "int8":
        return 2 * (page_size * kv_heads * head_dim + 4 * kv_heads)
    if kv_dtype != "fp32":
        raise ValueError(f"kv_dtype must be one of {KV_DTYPES}, got "
                         f"{kv_dtype!r}")
    return 2 * page_size * kv_heads * head_dim * cache_bytes


def kv_bytes_per_token(page_size: int, kv_heads: int, head_dim: int,
                       num_layers: int, kv_dtype: str = "fp32",
                       cache_bytes: int = 4) -> float:
    """Whole-model KV bytes one resident token row costs (the
    ``kct_engine_kv_bytes_per_token`` gauge): per-layer page bytes
    amortized over the page's rows, times layers."""
    return num_layers * kv_page_bytes(page_size, kv_heads, head_dim,
                                      kv_dtype, cache_bytes) / page_size


def pages_needed(prompt_len: int, max_new_tokens: int,
                 page_size: int) -> int:
    """Pages one request reserves: its whole ``prompt + max_new``
    worst case, page-rounded.  Module-level (not a method) so
    admission-time validation can run before any allocator exists —
    one source of truth for the reservation accounting."""
    return -(-(prompt_len + max_new_tokens) // page_size)


def chain_hashes(prompt_ids: Sequence[int], page_size: int) -> list[int]:
    """Chained hashes of the prompt's *full* blocks (vLLM-style).

    ``h[i] = hash((h[i-1], block_i_tokens))`` — a match on block *i*
    therefore certifies token-exact equality of blocks ``0..i``, which
    is what makes cross-request page reuse sound: K/V values depend
    only on the tokens and their absolute positions, both pinned by
    the chain."""
    out: list[int] = []
    prev = 0
    for i in range(len(prompt_ids) // page_size):
        prev = hash((prev, tuple(prompt_ids[i * page_size:
                                            (i + 1) * page_size])))
        out.append(prev)
    return out


@dataclasses.dataclass
class PageReservation:
    """One admitted request's page claim, in slot-table order: entry
    ``i`` backs token positions ``[i*page_size, (i+1)*page_size)``."""

    pages: list[int]
    #: prompt tokens served from the prefix cache (the engine prefills
    #: only ``prompt_len - cached_tokens`` tail tokens)
    cached_tokens: int
    prompt_len: int
    #: (src, dst) when the last matched page needed a private copy
    #: (page-aligned full-prompt match); the engine must copy src→dst
    #: on device *before* the tail prefill writes into dst
    cow: Optional[tuple[int, int]]
    #: chain hashes of every full prompt block, for ``register()``
    hashes: list[int] = dataclasses.field(default_factory=list)


class PageAllocator:
    """Free-list + refcount + prefix-cache bookkeeping for one arena.

    Single-threaded by design: only the engine's scheduler thread
    allocates/releases (the same ownership discipline as the slot
    list), so no lock is taken here."""

    def __init__(self, num_pages: int, page_size: int,
                 kv_dtype: str = "fp32"):
        if num_pages < 2:
            raise ValueError("num_pages must be >= 2 (page 0 is the "
                             "null page)")
        if page_size < 1:
            raise ValueError("page_size must be >= 1")
        if kv_dtype not in KV_DTYPES:
            raise ValueError(f"kv_dtype must be one of {KV_DTYPES}, "
                             f"got {kv_dtype!r}")
        self.num_pages = num_pages
        self.page_size = page_size
        #: how the arena this allocator fronts stores K/V — carried so
        #: /debug/pages can tell a quantized replica from an fp32 one
        self.kv_dtype = kv_dtype
        self._free: list[int] = list(range(num_pages - 1, NULL_PAGE, -1))
        self._refcnt = [0] * num_pages
        #: chain hash -> physical page holding that block's K/V
        self._cached: dict[int, int] = {}
        #: physical page -> its chain hash (reverse map for eviction)
        self._page_hash: dict[int, int] = {}
        #: refcount-zero cached pages, oldest-released first
        self._lru: "collections.OrderedDict[int, None]" = \
            collections.OrderedDict()
        self.stats = {"hits": 0, "tokens_saved": 0, "cow_copies": 0,
                      "evicted_pages": 0, "allocated_pages": 0}

    # -- capacity ----------------------------------------------------------

    @property
    def capacity(self) -> int:
        """Pages a single reservation could ever claim (arena minus the
        null page)."""
        return self.num_pages - 1

    def free_pages(self) -> int:
        """Pages allocatable right now: the free list plus every
        refcount-zero cached page the LRU could evict."""
        return len(self._free) + len(self._lru)

    def used_pages(self) -> int:
        """Pages currently referenced by at least one live request."""
        return self.capacity - self.free_pages()

    def pages_needed(self, prompt_len: int, max_new_tokens: int) -> int:
        return pages_needed(prompt_len, max_new_tokens, self.page_size)

    def refcount(self, page: int) -> int:
        return self._refcnt[page]

    def is_cached(self, page: int) -> bool:
        return page in self._page_hash

    # -- allocation --------------------------------------------------------

    def _take_page(self) -> int:
        if self._free:
            page = self._free.pop()
        else:
            # evict the coldest refcount-zero cached page; its hash
            # entries die with it (a later identical prefix re-prefills)
            page, _ = self._lru.popitem(last=False)
            h = self._page_hash.pop(page)
            del self._cached[h]
            self.stats["evicted_pages"] += 1
        self._refcnt[page] = 1
        self.stats["allocated_pages"] += 1
        return page

    def _incref(self, page: int) -> None:
        if self._refcnt[page] == 0:
            self._lru.pop(page, None)  # back in live use, not evictable
        self._refcnt[page] += 1

    def reserve(self, prompt_ids: Sequence[int],
                max_new_tokens: int) -> PageReservation:
        """Claim pages for one request, reusing every cached prefix
        block the chained hashes certify.  Raises
        :class:`KVPagesExhaustedError` (a ``QueueFullError``) when the
        arena cannot currently (or can never) satisfy the claim —
        with *nothing* claimed, so the caller can retry the identical
        reservation next scheduler pass."""
        ps = self.page_size
        plen = len(prompt_ids)
        n_total = self.pages_needed(plen, max_new_tokens)
        if n_total > self.capacity:
            raise KVPagesExhaustedError(
                f"request needs {n_total} KV pages; the arena has "
                f"{self.capacity} (raise --num-pages or --page-size)")
        hashes = chain_hashes(prompt_ids, ps)
        matchable = 0
        for h in hashes:
            if h in self._cached:
                matchable += 1
            else:
                break
        # Feasibility per match depth: matched pages parked in the LRU
        # (refcount 0) are counted by free_pages() as evictable, but a
        # reservation pins them — they cannot also back its fresh
        # pages.  When a deep match is infeasible (its pins starve its
        # own fresh-page needs), degrade one block at a time down to an
        # unmatched reservation, which can always evict the cache it
        # would have reused: reuse is an optimization, never a reason
        # to refuse work the arena can hold.
        matched = matchable
        while True:
            # A fully page-aligned, fully matched prompt still
            # recomputes its last token (sampling needs those logits) —
            # the write lands inside the last matched page, so that
            # page goes private via copy-on-write instead of being
            # shared read-only.
            cow_needed = matched > 0 and matched * ps == plen
            fresh_needed = n_total - matched + (1 if cow_needed else 0)
            pinned = sum(1 for h in hashes[:matched]
                         if self._refcnt[self._cached[h]] == 0)
            if fresh_needed <= self.free_pages() - pinned:
                break
            if matched == 0:
                raise KVPagesExhaustedError(
                    f"KV pages exhausted: need {fresh_needed} free, "
                    f"have {self.free_pages()}")
            matched -= 1
        shared = [self._cached[h] for h in hashes[:matched]]
        for page in shared:
            self._incref(page)
        cow = None
        cow_src = None
        if cow_needed:
            cow_src = shared[-1]
            dst = self._take_page()
            shared[-1] = dst
            cow = (cow_src, dst)
            self.stats["cow_copies"] += 1
        pages = shared + [self._take_page()
                          for _ in range(n_total - len(shared))]
        if cow_src is not None:
            # dropped only after every fresh page is taken, so this
            # reservation can never evict-and-recycle its own copy
            # source; the engine still must order all device COW
            # copies before any prefill of the same scheduler pass
            self._decref(cow_src)
        cached_tokens = (matched * ps - 1) if cow_needed else matched * ps
        if cached_tokens:
            self.stats["hits"] += 1
            self.stats["tokens_saved"] += cached_tokens
        return PageReservation(pages=pages, cached_tokens=cached_tokens,
                               prompt_len=plen, cow=cow, hashes=hashes)

    def reserve_blank(self, n: int) -> list[int]:
        """Claim ``n`` fresh pages with no prefix-cache matching — the
        disaggregation adopt path (``serve/disagg.py``): page content
        arrives by device transfer from a prefill-role arena, not by
        prefill compute, so there is nothing to match yet.  Raises
        :class:`KVPagesExhaustedError` with nothing claimed (transient
        when the arena could drain into the claim; permanent when it
        can never hold it)."""
        if n > self.capacity:
            raise KVPagesExhaustedError(
                f"adoption needs {n} KV pages; the arena has "
                f"{self.capacity} (raise num_pages)")
        if n > self.free_pages():
            raise KVPagesExhaustedError(
                f"KV pages exhausted: adoption needs {n} free, have "
                f"{self.free_pages()}")
        return [self._take_page() for _ in range(n)]

    def register(self, res: PageReservation) -> None:
        """Publish the reservation's full prompt blocks into the prefix
        cache (call *after* the prefill wrote them).  Already-cached
        blocks — including a COW copy whose content duplicates the
        original — keep their existing entry."""
        self.register_blocks(res.hashes, res.pages)

    def register_blocks(self, hashes: Sequence[int],
                        pages: Sequence[int]) -> None:
        """Publish ``pages[i]`` as the cached copy of chain block
        ``hashes[i]`` — the shared tail of :meth:`register` and the
        adopt path (transferred prompt pages become prefix-cache
        entries on the receiving arena, so later requests sharing the
        prefix dedup against transferred content)."""
        for h, page in zip(hashes, pages):
            if h not in self._cached and page not in self._page_hash:
                self._cached[h] = page
                self._page_hash[page] = h

    def snapshot(self) -> dict:
        """Read-only occupancy + prefix-cache dump for the debug plane
        (``GET /debug/pages``).  Exposes block *hashes* (hex of the
        chained hash), refcounts, and LRU order — never token content:
        a hash certifies identity to someone who already holds the
        prompt, it reveals nothing to someone who doesn't."""
        lru = list(self._lru)
        lru_pos = {p: i for i, p in enumerate(lru)}
        cache = []
        for page, h in sorted(self._page_hash.items()):
            cache.append({
                "page": page,
                "hash": format(h & ((1 << 64) - 1), "016x"),
                "refcount": self._refcnt[page],
                "lru_position": lru_pos.get(page),  # None = in live use
            })
        return {
            "num_pages": self.num_pages,
            "page_size": self.page_size,
            "kv_dtype": self.kv_dtype,
            "capacity": self.capacity,
            "used_pages": self.used_pages(),
            "free_pages": self.free_pages(),
            "free_list_pages": len(self._free),
            "lru_evictable_pages": len(lru),
            "lru_order": lru,  # oldest (next evicted) first
            "prefix_cache": cache,
            "stats": dict(self.stats),
        }

    def _decref(self, page: int) -> None:
        if self._refcnt[page] <= 0:
            raise AssertionError(f"double free of page {page}")
        self._refcnt[page] -= 1
        if self._refcnt[page] == 0:
            if page in self._page_hash:
                # cached content: park evictable, newest last
                self._lru[page] = None
                self._lru.move_to_end(page)
            else:
                self._free.append(page)

    def release(self, pages: Sequence[int]) -> None:
        """Drop one request's claim.  Shared pages survive while any
        sibling still references them; cached pages at refcount zero
        park in the LRU instead of the free list."""
        for page in pages:
            self._decref(page)
