"""CLIP text tokenizer (byte-level BPE, ``</w>`` word-final variant).

SD conditions on CLIP-tokenized prompts; the reference gets this from
``transformers.CLIPTokenizer`` inside its service container
(``online-inference/stable-diffusion/service/service.py``).  This is the
same published algorithm, dependency-free, built on the repo's BPE
machinery (:mod:`kubernetes_cloud_tpu.serve.bpe`).  Differences from the
GPT-2 codec it reuses:

* text is whitespace-collapsed and lower-cased before splitting,
* the pre-tokenizer keeps contractions/words/digits but never leading
  spaces (CLIP drops them),
* every word's last symbol carries a ``</w>`` suffix, so merges and
  vocab entries distinguish word-final pieces,
* prompts are framed ``<|startoftext|> ... <|endoftext|>`` and padded to
  the conditioning length (SD-1.x pads with the end token, SD-2.x
  overrides the pad token in its tokenizer config).

Loads the standard ``vocab.json``/``merges.txt`` pair that ships inside
every diffusers snapshot's ``tokenizer/`` directory (what
``weights/sd_import.convert_checkpoint`` republishes for serving).
"""

from __future__ import annotations

import html
import json
import os
import re

from kubernetes_cloud_tpu.serve.bpe import BPECodec, bytes_to_unicode

try:
    import regex as _regex

    _PAT = _regex.compile(
        r"<\|startoftext\|>|<\|endoftext\|>|'s|'t|'re|'ve|'m|'ll|'d"
        r"|\p{L}+|\p{N}|[^\s\p{L}\p{N}]+",
        _regex.IGNORECASE)
except ImportError:  # pragma: no cover - regex is in the baked image
    _PAT = re.compile(
        r"<\|startoftext\|>|<\|endoftext\|>|'s|'t|'re|'ve|'m|'ll|'d"
        r"|[^\W\d_]+|\d|(?:[^\s\w]|_)+",
        re.IGNORECASE | re.UNICODE)

SOT = "<|startoftext|>"
EOT = "<|endoftext|>"


try:  # mirror transformers' basic_clean: ftfy first when available
    import ftfy as _ftfy
except ImportError:  # pragma: no cover - optional dependency
    _ftfy = None


def _clean(text: str) -> str:
    if _ftfy is not None:
        text = _ftfy.fix_text(text)
    text = html.unescape(html.unescape(text))
    return re.sub(r"\s+", " ", text).strip().lower()


class CLIPBPECodec(BPECodec):
    """CLIP variant of the byte-level BPE codec."""

    def __init__(self, vocab: dict[str, int],
                 merges: list[tuple[str, str]],
                 pad_token: str = EOT):
        super().__init__(vocab, merges)
        self.sot = self.encoder[SOT]
        self.eot = self.encoder[EOT]
        self.pad = self.encoder.get(pad_token, self.eot)

    @classmethod
    def from_dir(cls, path: str) -> "CLIPBPECodec":
        base = BPECodec.from_dir(path)
        merges = sorted(base.ranks, key=base.ranks.get)
        pad = EOT
        cfg_path = os.path.join(path, "tokenizer_config.json")
        if os.path.exists(cfg_path):
            with open(cfg_path) as f:
                raw = json.load(f).get("pad_token", EOT)
            # transformers serializes added tokens either bare or as
            # {"content": ...}
            pad = raw["content"] if isinstance(raw, dict) else raw
        return cls(base.encoder, merges, pad_token=pad)

    def _bpe(self, token: str) -> tuple[str, ...]:
        cached = self._cache.get(token)
        if cached is not None:
            return cached
        word = tuple(token[:-1]) + (token[-1] + "</w>",)
        while len(word) > 1:
            pairs = set(zip(word, word[1:]))
            best = min(pairs,
                       key=lambda p: self.ranks.get(p, float("inf")))
            if best not in self.ranks:
                break
            a, b = best
            merged: list[str] = []
            i = 0
            while i < len(word):
                if (i < len(word) - 1 and word[i] == a
                        and word[i + 1] == b):
                    merged.append(a + b)
                    i += 2
                else:
                    merged.append(word[i])
                    i += 1
            word = tuple(merged)
        self._cache[token] = word
        return word

    def encode(self, text: str) -> list[int]:
        """Prompt text → BPE ids (no special-token framing)."""
        ids: list[int] = []
        for tok in _PAT.findall(_clean(text)):
            if tok in (SOT, EOT):
                ids.append(self.encoder[tok])
                continue
            mapped = "".join(self.byte_enc[b] for b in tok.encode("utf-8"))
            ids.extend(self.encoder[piece] for piece in self._bpe(mapped))
        return ids

    def encode_batch(self, texts: list[str],
                     length: int = 77) -> list[list[int]]:
        """SD conditioning frames: ``[sot] ids[:length-2] [eot]`` padded
        to ``length`` — CLIPTokenizer's ``padding="max_length",
        truncation=True`` behavior."""
        out = []
        for t in texts:
            ids = self.encode(t)[: length - 2]
            row = [self.sot] + ids + [self.eot]
            row += [self.pad] * (length - len(row))
            out.append(row)
        return out

    def decode(self, ids: list[int]) -> str:
        # Strip only *trailing* pad tokens: SD-2.x tokenizers pad with
        # '!', a real vocab token that may legitimately appear mid-text.
        # (When pad == eot the eot filter below covers interior pads the
        # way CLIPTokenizer does.)
        end = len(ids)
        while end > 0 and ids[end - 1] == self.pad:
            end -= 1
        specials = {self.sot, self.eot}
        text = "".join(self.decoder[i] for i in ids[:end]
                       if i not in specials)
        data = bytes(self.byte_dec[c] for c in text)
        decoded = data.decode("utf-8", errors="replace")
        return decoded.replace("</w>", " ").strip()
