"""Causal-LM text-generation predictor.

The TPU-native replacement for the reference's LLM services: the GPT-J
tensorizer ISVC (``online-inference/tensorizer-isvc/kserve/kserve_api.py``),
the BLOOM services (``online-inference/bloom-176b*/``), and the finetuner's
completion server (``finetuner-workflow/finetuner/inference.py``).  The
model loads via tensorstream straight into (optionally tensor-parallel)
device memory; generation runs the prefill/decode programs from
:mod:`kubernetes_cloud_tpu.models.generate`.
"""

from __future__ import annotations

import dataclasses
import logging
import os
import time
from typing import Any, Mapping, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from kubernetes_cloud_tpu.models.causal_lm import CausalLMConfig
from kubernetes_cloud_tpu.models.generate import generate
from kubernetes_cloud_tpu.parallel.sharding import (
    logical_to_physical,
    param_specs,
)
from kubernetes_cloud_tpu.serve.errors import DeadlineExceededError
from kubernetes_cloud_tpu.serve.model import (
    Model,
    instance_text,
    parse_instances,
    request_deadline,
)
from kubernetes_cloud_tpu.weights.tensorstream import (
    load_pytree,
    read_index,
    weights_version,
)

log = logging.getLogger(__name__)


class ByteTokenizer:
    """Dependency-free byte-level tokenizer (ids 0-255 = bytes; 256 = eos,
    257 = pad).  Lets every service run end-to-end without vocab downloads;
    swap in any HF tokenizer object for real deployments."""

    eos_token_id = 256
    pad_token_id = 257
    vocab_size = 258

    def encode(self, text: str) -> list[int]:
        return list(text.encode("utf-8"))

    def decode(self, ids: Sequence[int]) -> str:
        data = bytes(i for i in ids if 0 <= i < 256)
        return data.decode("utf-8", errors="replace")


class CausalLMService(Model):
    """Text-generation predictor on the KServe V1 protocol.

    Request: ``{"instances": ["prompt", ...], "parameters": {...}}``;
    response ``{"predictions": [{"generated_text": ...}, ...]}``.
    Parameter names follow the reference's env-default + per-request
    override protocol (``bloom.py:13-30,57-77``).
    """

    OPTIONS = {
        "MAX_NEW_TOKENS": 64,
        "TEMPERATURE": 0.7,
        "TOP_K": 0,
        "TOP_P": 1.0,
        "SEED": 0,
        "ECHO_PROMPT": False,
    }

    def __init__(
        self,
        name: str,
        cfg: CausalLMConfig,
        *,
        tokenizer=None,
        params: Any = None,
        weights_path: Optional[str] = None,
        weights_index: Optional[dict] = None,
        mesh=None,
        dtype=jnp.bfloat16,
    ):
        super().__init__(name)
        self.cfg = dataclasses.replace(cfg, param_dtype=dtype)
        self.tokenizer = tokenizer or ByteTokenizer()
        self.params = params
        self.weights_path = weights_path
        # pre-read header (saves a remote round-trip on cold start)
        self.weights_index = weights_index
        self.mesh = mesh
        self.dtype = dtype
        # jit per (shape-bucket, sampling-config); cached by jax across
        # requests — the point of _encode_batch's bucketing.
        self._generate = jax.jit(
            generate, static_argnums=(0,),
            static_argnames=("max_new_tokens", "temperature", "top_k",
                             "top_p", "eos_token_id", "pad_token_id"))

    def _shardings(self, params_like: Any = None):
        if self.mesh is None:
            return None
        if params_like is None:
            from kubernetes_cloud_tpu.models.causal_lm import init_params
            params_like = jax.eval_shape(
                lambda: init_params(self.cfg, jax.random.key(0)))
        return logical_to_physical(param_specs(params_like), self.mesh)

    def load_params(self, weights_path: Optional[str] = None,
                    index: Optional[dict] = None) -> tuple[Any, str]:
        """Chunk-verified streamed load of an artifact into (sharded)
        device params — the cold-start path, and how a live hot-swap
        prepares its new version off to the side.  Returns
        ``(params, weights_version)``; corruption/truncation raise the
        typed ``tensorstream`` errors instead of returning params."""
        path = weights_path or self.weights_path
        if path is None:
            raise ValueError("need params or weights_path")
        if index is None:
            index = read_index(path)
        params = load_pytree(path, self._shardings(), dtype=self.dtype,
                             index=index)
        return params, weights_version(index)

    def load(self) -> None:
        t0 = time.perf_counter()
        if self.params is None:
            self.params, self.weights_version = self.load_params(
                self.weights_path, self.weights_index)
        elif self.mesh is not None:
            shardings = logical_to_physical(param_specs(self.params),
                                            self.mesh)
            self.params = jax.device_put(self.params, shardings)
        nbytes = sum(x.nbytes for x in jax.tree.leaves(self.params))
        dt = time.perf_counter() - t0
        # deserialization-rate log, same shape as the reference's
        # (load_model.py:62-75)
        log.info("loaded %s: %.1f MiB in %.2fs (%.1f MiB/s)", self.name,
                 nbytes / 2**20, dt, nbytes / 2**20 / max(dt, 1e-9))
        self.ready = True

    # -- inference ---------------------------------------------------------

    def _encode_batch(self, prompts: Sequence[str]) -> tuple[jax.Array, jax.Array]:
        """Tokenize and right-pad to a power-of-two bucket.

        Bucketing keeps the number of distinct compiled program shapes
        logarithmic in prompt length — without it every new prompt length
        costs a fresh XLA compile (~20 s on v5e), which would dwarf the
        cold-start budget the reference's Tensorizer work targets."""
        if not prompts:
            raise ValueError("instances must be a non-empty list")
        enc = [self.tokenizer.encode(p) for p in prompts]
        longest = max(len(e) for e in enc)
        bucket = 32
        while bucket < longest:
            bucket *= 2
        pad = getattr(self.tokenizer, "pad_token_id", 0) or 0
        ids = np.full((len(enc), bucket), pad, np.int32)
        mask = np.zeros((len(enc), bucket), np.int32)
        for i, e in enumerate(enc):
            ids[i, : len(e)] = e
            mask[i, : len(e)] = 1
        return jnp.asarray(ids), jnp.asarray(mask)

    def generate_outputs(self, prompts: Sequence[str],
                         opts: Mapping[str, Any]) -> list[dict]:
        """Generate; returns ``{"generated_text", "tokens_out"}`` per
        prompt (``tokens_out`` = completion tokens excluding pad/eos, the
        figure the load test aggregates into end-to-end tokens/s)."""
        ids, mask = self._encode_batch(prompts)
        t0 = time.perf_counter()
        out = self._generate(
            self.cfg, self.params, ids, mask,
            max_new_tokens=max(1, min(int(opts["MAX_NEW_TOKENS"]), 2048)),
            temperature=float(opts["TEMPERATURE"]),
            top_k=int(opts["TOP_K"]),
            top_p=float(opts["TOP_P"]),
            eos_token_id=getattr(self.tokenizer, "eos_token_id", None),
            pad_token_id=getattr(self.tokenizer, "pad_token_id", 0) or 0,
            rng=jax.random.key(int(opts["SEED"])),
        )
        out = np.asarray(jax.block_until_ready(out))
        log.info("INFERENCE TIME: %.2fs", time.perf_counter() - t0)
        outputs = []
        prompt_lens = np.asarray(mask.sum(-1))
        pad = getattr(self.tokenizer, "pad_token_id", None)
        eos = getattr(self.tokenizer, "eos_token_id", None)
        for i, row in enumerate(out):
            plen = int(prompt_lens[i])
            completion = [t for t in row[plen:].tolist()
                          if t != pad and t != eos]
            toks = completion
            if opts.get("ECHO_PROMPT"):
                toks = [t for t in row[:plen].tolist()
                        if t != pad and t != eos] + completion
            entry = {"generated_text": self.tokenizer.decode(toks),
                     "tokens_out": len(completion)}
            if self.weights_version is not None:
                entry["weights_version"] = self.weights_version
            outputs.append(entry)
        return outputs

    def generate_texts(self, prompts: Sequence[str],
                       opts: Mapping[str, Any]) -> list[str]:
        return [o["generated_text"]
                for o in self.generate_outputs(prompts, opts)]

    def predict(self, payload: Mapping[str, Any]) -> dict:
        deadline = request_deadline(payload)
        if deadline is not None and time.monotonic() > deadline:
            # shed before compiling/generating — the one-shot path has
            # no queue to age in, so only an already-dead budget sheds
            raise DeadlineExceededError("deadline expired before start")
        prompts = [instance_text(i) for i in parse_instances(payload)]
        opts = self.configure_request(payload)
        return {"predictions": self.generate_outputs(prompts, opts)}

    #: FastAPI-completion body keys → OPTIONS keys; shared by every
    #: completion route (one-shot here, continuous-batching wrapper)
    COMPLETION_ALIASES = {"max_new_tokens": "MAX_NEW_TOKENS",
                          "temperature": "TEMPERATURE", "top_k": "TOP_K",
                          "top_p": "TOP_P", "seed": "SEED"}

    def completion_options(self, payload: Mapping[str, Any]) -> dict:
        opts = self.default_options()
        for key, target in self.COMPLETION_ALIASES.items():
            if key in payload:
                opts[target] = payload[key]
        return opts

    def completion(self, payload: Mapping[str, Any]) -> dict:
        """FastAPI-completion-compatible route (reference
        ``inference.py:43-56``: prompt + max_new_tokens/temperature/...)."""
        prompt = payload.get("prompt", "")
        opts = self.completion_options(payload)
        text = self.generate_texts([prompt], opts)[0]
        return {"completion": text}


# --------------------------------------------------------------------------
# container entrypoint (deploy/online-inference/*/; deploy/finetuner-workflow
# model-inference-service template)


def _resolve_weights(model_arg: str) -> str:
    """``--model`` accepts a ``.tensors`` file/object, a local directory
    holding ``model.tensors`` (the trainer's ``final/`` layout), or a
    remote prefix (``gs://bucket/model`` → ``.../model.tensors``) —
    remote objects stream by byte range, no local copy."""
    from kubernetes_cloud_tpu.weights.tensorstream import resolve_artifact

    return resolve_artifact(model_arg)


def _config_from_index(index: dict, path: str,
                       preset: Optional[str]) -> CausalLMConfig:
    if preset:
        from kubernetes_cloud_tpu.models.causal_lm import PRESETS

        return PRESETS[preset]
    meta = index["meta"].get("model_config")
    if not meta:
        raise ValueError(
            f"{path} carries no model_config metadata; pass --preset")
    meta = {k: v for k, v in meta.items()
            if k not in ("dtype", "param_dtype")}
    return CausalLMConfig(**meta)


def _config_from_artifact(path: str, preset: Optional[str]) -> CausalLMConfig:
    from kubernetes_cloud_tpu.weights.tensorstream import read_index

    return _config_from_index(read_index(path) if not preset else {},
                              path, preset)


def _tokenizer_for(model_dir: str):
    try:  # HF tokenizer files beside the weights, if any
        from transformers import AutoTokenizer

        return AutoTokenizer.from_pretrained(model_dir)
    except Exception:  # noqa: BLE001 - offline/no files => byte-level
        return ByteTokenizer()


def main(argv: Optional[list] = None) -> int:
    import argparse

    from kubernetes_cloud_tpu.serve import boot

    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--model", required=True,
                    help=".tensors file or dir containing model.tensors")
    ap.add_argument("--preset", default=None,
                    help="architecture preset overriding artifact metadata")
    ap.add_argument("--tp", type=int, default=0,
                    help="tensor-parallel ways (model mesh axis)")
    ap.add_argument("--max-batch-size", type=int, default=0,
                    help=">0 wraps the service in the dynamic batcher")
    ap.add_argument("--continuous-batching", action="store_true",
                    help="serve through the slot-based continuous-"
                         "batching engine instead of the request-level "
                         "dynamic batcher (serve/continuous.py)")
    ap.add_argument("--slots", type=int, default=0,
                    help="continuous batching: persistent decode batch "
                         "width (default from model_config.json)")
    ap.add_argument("--pool-max-len", type=int, default=0,
                    help="continuous batching: KV rows per slot "
                         "(prompt + completion)")
    ap.add_argument("--paged", action="store_true",
                    help="continuous batching: block-granular paged KV "
                         "pool + cross-request prefix caching instead "
                         "of the dense per-slot pool (vLLM-style; see "
                         "deploy/README.md 'Paged KV & prefix caching')")
    ap.add_argument("--page-size", type=int, default=0,
                    help="paged mode: KV rows per page (the prefix-"
                         "sharing unit; default from model_config.json)")
    ap.add_argument("--num-pages", type=int, default=0,
                    help="paged mode: arena pages incl. the null page "
                         "(0 = equal bytes with the slot pool)")
    ap.add_argument("--kv-dtype", choices=("fp32", "int8"), default=None,
                    help="paged mode: KV storage — int8 quantizes the "
                         "arena (per-page per-head scales) for ~2-4x "
                         "resident pages at equal bytes under a "
                         "measured logit-error budget (deploy/README "
                         "'Quantized KV & fused kernels')")
    ap.add_argument("--attn-impl",
                    choices=("gather", "pallas", "fused"), default=None,
                    help="paged mode: decode attention kernel — "
                         "'fused' folds gather+attention+output "
                         "projection into one Mosaic kernel")
    ap.add_argument("--role",
                    choices=("colocated", "prefill", "decode"),
                    default=None,
                    help="paged mode: prefill/decode disaggregation "
                         "(DistServe-style) — 'prefill' admits and "
                         "prefills, handing KV page-granularly to "
                         "in-process decode engines (--decode-slices); "
                         "'decode' marks a dedicated decode replica "
                         "the fleet router keeps admission traffic "
                         "off; default 'colocated'")
    ap.add_argument("--decode-slices", type=int, default=0,
                    help="role=prefill: how many decode engines the "
                         "prefill engine feeds (each owns its own "
                         "arena / slice group)")
    ap.add_argument("--prefill-chunk", type=int, default=-1,
                    help="continuous batching: Sarathi-style chunked "
                         "prefill token budget per scheduler pass — "
                         "long prompts prefill in bounded chunks "
                         "co-scheduled with decode steps so they "
                         "cannot stall active streams (0 disables, "
                         "-1 keeps the model_config.json value; see "
                         "deploy/README.md 'Latency: chunked prefill "
                         "& speculative decoding')")
    ap.add_argument("--spec-draft", default=None,
                    help="paged continuous batching: speculative-"
                         "decoding draft source — 'ngram' for "
                         "prompt-lookup drafting or a draft model dir "
                         "(e.g. pythia-70m drafting for pythia-410m; "
                         "must share the target's tokenizer).  Greedy "
                         "outputs stay bitwise-identical to "
                         "non-speculative decode")
    ap.add_argument("--spec-k", type=int, default=0,
                    help="draft tokens proposed (and verified in one "
                         "batched target step) per speculative round "
                         "(0 keeps the default)")
    ap.add_argument("--ragged", choices=("on", "off"), default=None,
                    help="paged continuous batching: ragged token-"
                         "level dispatch — the scheduler pass runs ONE "
                         "flat-batch program covering prefill chunks, "
                         "admission tails, decode steps, spec "
                         "verification, and COW copies as segments "
                         "(default on; 'off' keeps the padded multi-"
                         "program iteration for one release — see "
                         "deploy/README.md 'Ragged dispatch')")
    ap.add_argument("--flight-records", type=int, default=-1,
                    help="continuous batching: flight-recorder ring "
                         "capacity (per-iteration phase records for "
                         "GET /debug/timeline; 0 disables, -1 keeps "
                         "the default/model_config.json value)")
    ap.add_argument("--tenancy", default=None, metavar="FILE",
                    help="continuous batching: JSON tenant table for "
                         "the multi-tenant traffic plane (per-tenant "
                         "token-bucket admission, weighted fair "
                         "queueing, QoS lanes); overrides the "
                         "model_config.json 'tenancy' key — see "
                         "deploy/README.md 'Multi-tenancy & QoS'")
    ap.add_argument("--max-seq-len", type=int, default=0)
    ap.add_argument("--config", default=None,
                    help="model_config.json for batcher knobs")
    ap.add_argument("--smoke", default=None, metavar="PROMPT",
                    help="load, run one generation for PROMPT, print the "
                         "KServe V1 response, and exit (workflow "
                         "serve-smoke step; no HTTP server)")
    ap.add_argument("--smoke-tokens", type=int, default=16,
                    help="max new tokens for --smoke")
    boot.add_common_args(ap)
    args = ap.parse_args(argv)
    logging.basicConfig(level=logging.INFO)
    boot.wait_for_artifact(args)

    from kubernetes_cloud_tpu.weights.tensorstream import read_index

    weights = _resolve_weights(args.model)
    index = read_index(weights)  # one header fetch serves config + load
    cfg = _config_from_index(index, weights, args.preset)
    if args.max_seq_len:
        cfg = dataclasses.replace(cfg, max_seq_len=args.max_seq_len)
    mesh = None
    if args.tp > 1:
        from kubernetes_cloud_tpu.core.distributed import (
            maybe_initialize_distributed,
        )
        from kubernetes_cloud_tpu.core.mesh import MeshSpec, build_mesh

        maybe_initialize_distributed()
        mesh = build_mesh(MeshSpec(model=args.tp, fsdp=-1))

    model_dir = (args.model if os.path.isdir(args.model)
                 else os.path.dirname(args.model))
    svc: Any = CausalLMService(
        args.model_name or "model", cfg,
        tokenizer=_tokenizer_for(model_dir), weights_path=weights,
        weights_index=index, mesh=mesh)
    if args.smoke is not None:
        # one-shot readiness probe: the workflow's serve step must prove
        # the finetuned artifact loads and generates, then release the
        # (simulated) accelerator — no listener left behind
        import json

        svc.load()
        out = svc.predict({
            "instances": [args.smoke],
            "parameters": {"max_new_tokens": max(1, args.smoke_tokens)},
        })
        if not (out.get("predictions") and all(
                "generated_text" in p for p in out["predictions"])):
            print(f"smoke test got malformed response: {out}")
            return 1
        print(json.dumps(out))
        return 0
    if args.continuous_batching:
        from kubernetes_cloud_tpu.serve.continuous import (
            ContinuousBatchingModel,
            load_engine_config,
        )

        ecfg = load_engine_config(os.path.dirname(args.config)
                                  if args.config else model_dir)
        # ONE replace: __post_init__ validates the paged geometry
        # (max_len % page_size), so flags must land together — applying
        # --paged before --page-size would validate a half-built config
        overrides: dict = {}
        if args.slots > 0:
            overrides["slots"] = args.slots
        if args.pool_max_len > 0:
            overrides["max_len"] = args.pool_max_len
        if args.paged:
            overrides["paged"] = True
        if args.page_size > 0:
            overrides["page_size"] = args.page_size
        if args.num_pages > 0:
            overrides["num_pages"] = args.num_pages
        if args.kv_dtype:
            overrides["kv_dtype"] = args.kv_dtype
        if args.attn_impl:
            overrides["attn_impl"] = args.attn_impl
        if args.role:
            overrides["role"] = args.role
        if args.decode_slices > 0:
            overrides["decode_slices"] = args.decode_slices
        if args.flight_records >= 0:
            overrides["flight_records"] = args.flight_records
        if args.prefill_chunk >= 0:
            overrides["prefill_chunk_tokens"] = args.prefill_chunk
        if args.spec_draft:
            overrides["spec_draft"] = args.spec_draft
        if args.spec_k > 0:
            overrides["spec_k"] = args.spec_k
        if args.ragged is not None:
            overrides["ragged"] = args.ragged == "on"
        if args.tenancy:
            import json

            from kubernetes_cloud_tpu.serve.tenancy import parse_tenancy

            with open(args.tenancy) as f:
                raw = json.load(f)
            # accept a bare tenant table or a {"tenancy": {...}}
            # wrapper (the model_config.json shape)
            overrides["tenancy"] = parse_tenancy(raw.get("tenancy", raw))
        if overrides:
            ecfg = dataclasses.replace(ecfg, **overrides)
        svc = ContinuousBatchingModel(svc.name, svc, ecfg)
    elif args.max_batch_size > 0 or args.config:
        from kubernetes_cloud_tpu.serve.batcher import (
            BatchingModel,
            load_model_config,
        )

        bcfg = load_model_config(os.path.dirname(args.config)
                                 if args.config else model_dir)
        if args.max_batch_size > 0:
            bcfg = dataclasses.replace(bcfg,
                                       max_batch_size=args.max_batch_size)
        svc = BatchingModel(svc.name, svc, bcfg)
    boot.serve([svc], args)
    return 0


if __name__ == "__main__":  # pragma: no cover - container entry
    import sys

    sys.exit(main())
