"""Continuous-batching decode engine — iteration-level scheduling.

The request-level batcher (:mod:`kubernetes_cloud_tpu.serve.batcher`)
coalesces queued requests into ONE batch and runs it to completion:
throughput is gated by the longest completion in each wave, and the MXU
idles between waves.  This module replaces run-to-completion generation
with Orca-style iteration-level scheduling (OSDI '22; the technique
behind vLLM, see PAPERS.md): a persistent slot-based KV pool
(``[L, SLOTS, max_len, Hkv, Dh]``, slots shard over the mesh like the
one-shot cache) plus a host-side scheduler that every iteration

1. admits queued requests into free slots (one compiled
   ``prefill_into_slots`` per prompt-length bucket),
2. steps the whole active batch one token (``decode_step_slots`` — ONE
   compiled program, reused forever),
3. emits each slot's token to its waiting request (token streaming), and
4. evicts slots on EOS / max-tokens / cancel, so the next queued request
   starts immediately instead of waiting for the batch.

Decode therefore always runs near-full regardless of how request
lengths mix.  Sampling runs host-side per slot (each request carries
its own temperature/top-k/top-p/seed — requests never need
parameter-compatible merging like the Triton-style batcher requires).

**Paged mode** (``EngineConfig.paged``; vLLM/PagedAttention, SOSP '23)
replaces the dense per-slot pool with a block-granular page arena
(``[L, NUM_PAGES, page_size, Hkv, Dh]``) plus per-slot indirection
tables: each request reserves only the pages its ``prompt +
max_new_tokens`` actually needs, so HBM capacity stops being gated by
the worst-case ``max_len`` and concurrent sequences scale with *real*
context lengths.  Full prompt pages are identified by chained block
hashes and reused copy-on-write across requests
(:mod:`kubernetes_cloud_tpu.serve.paged_kv`), so a shared system
prompt's prefill runs once, not per request — the engine admits a
prefix hit by prefilling only the uncached tail.  Both modes are locked
token-identical to greedy ``generate`` and to each other
(``tests/test_paged_kv.py``).

Contract parity with :class:`~kubernetes_cloud_tpu.serve.batcher.
BatchingModel`: ``self_batching = True`` (ModelServer skips its
per-model lock), bounded queue with
:class:`~kubernetes_cloud_tpu.serve.batcher.QueueFullError`
backpressure (HTTP 503), and ``stop()`` drains in-flight slots before
returning.  Correctness is locked by
``tests/test_continuous_batching.py``: greedy outputs are
token-identical to :func:`~kubernetes_cloud_tpu.models.generate.
generate` for any admission order.
"""

from __future__ import annotations

import dataclasses
import logging
import queue
import threading
import time
from typing import Any, Iterator, Mapping, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from kubernetes_cloud_tpu import faults, obs
from kubernetes_cloud_tpu.obs import flops as obs_flops
from kubernetes_cloud_tpu.obs.flight import PHASES, FlightRecorder
from kubernetes_cloud_tpu.obs.tracing import trace
from kubernetes_cloud_tpu.models.causal_lm import CausalLMConfig
from kubernetes_cloud_tpu.models.generate import (
    copy_pages,
    decode_step_pages,
    decode_step_slots,
    extract_pages,
    init_cache,
    init_page_arena,
    install_pages,
    prefill_chunk_into_slots,
    prefill_into_pages,
    prefill_into_slots,
    ragged_step_pages,
    verify_step_pages,
)
from kubernetes_cloud_tpu.serve.errors import (
    DeadlineExceededError,
    EngineDrainingError,
    EngineRestartedError,
    KVPagesExhaustedError,
    QueueFullError,
    RetryableError,
    StreamTimeoutError,
    SwapInProgressError,
    SwapVerificationError,
)
from kubernetes_cloud_tpu.serve import paged_kv
from kubernetes_cloud_tpu.serve.paged_kv import PageAllocator
from kubernetes_cloud_tpu.serve.spec_decode import (
    DraftSource,
    ModelDraft,
    NgramDraft,
)
from kubernetes_cloud_tpu.serve.tenancy import (
    LANES,
    TenancyConfig,
    TenantScheduler,
    parse_tenancy,
)
from kubernetes_cloud_tpu.serve.model import (
    Model,
    instance_text,
    parse_instances,
    request_deadline,
)
from kubernetes_cloud_tpu.serve.supervisor import Heartbeat

log = logging.getLogger(__name__)

_STREAM_END = object()  # sentinel closing a request's token stream

# Engine metric families (labels bound per engine via its model name).
# The stats dict below stays — it is the zero-dependency in-process
# telemetry the bench reads; these are the scrape-facing mirror with
# latency distributions the dict can't carry.
_M_ITERS = obs.counter(
    "kct_engine_iterations_total", "Decode scheduler iterations.",
    ("model",))
_M_ITER_S = obs.histogram(
    "kct_engine_iteration_seconds",
    "Wall time of one scheduler pass, split by kind: phase=\"prefill\" "
    "passes admitted at least one request (prefill stalls live here), "
    "phase=\"chunked_prefill\" passes carried budget-bounded prefill "
    "chunks co-scheduled with decode (Sarathi mode — these should "
    "track the decode distribution, not the prefill one), "
    "phase=\"decode\" ran the decode step only (= per-token latency "
    "for every active request).  The role label names which side of a "
    "disaggregated deployment the pass ran on (colocated | prefill | "
    "decode).", ("model", "phase", "role"))
_M_PHASE_S = obs.counter(
    "kct_engine_phase_seconds_total",
    "Seconds accumulated in each named scheduler phase (admit | "
    "cow_copy | prefill | decode | sample | stream | host_sync); "
    "rate() over two phases gives the live phase share.  Recorded "
    "only while the flight recorder is enabled (its default).",
    ("model", "phase"))
_M_MFU = obs.gauge(
    "kct_engine_mfu",
    "Model-FLOPs utilization over the trailing flight-recorder "
    "window: analytical FLOPs/s for tokens actually served over the "
    "chip's dense peak (0 while the peak is unknown - set "
    "KCT_PEAK_FLOPS).", ("model",))
_M_GOODPUT = obs.gauge(
    "kct_engine_goodput_tokens_per_s",
    "Tokens served per second (decode + computed prefill) over the "
    "trailing flight-recorder window.", ("model",))
_M_ADMITTED = obs.counter(
    "kct_engine_admitted_total", "Requests admitted into slots.",
    ("model",))
_M_EVICTED = obs.counter(
    "kct_engine_evicted_total",
    "Slots freed (EOS / max-tokens / cancel / failure).", ("model",))
_M_SHED = obs.counter(
    "kct_engine_shed_total",
    "Requests shed without decoding, by reason "
    "(deadline_admission | deadline_queued | queue_full).",
    ("model", "reason"))
_M_CANCELLED = obs.counter(
    "kct_engine_cancelled_total", "Requests cancelled by the client.",
    ("model",))
_M_TOKENS = obs.counter(
    "kct_engine_tokens_total", "Completion tokens emitted.", ("model",))
_M_TTFT = obs.histogram(
    "kct_engine_ttft_seconds",
    "Time from submit to the request's first emitted token.", ("model",))
_M_SWAPS = obs.counter(
    "kct_weights_swaps_total",
    "Live weight hot-swap attempts by outcome (ok | rolled_back).",
    ("model", "outcome"))
_M_SWAP_S = obs.histogram(
    "kct_weights_swap_seconds",
    "Wall time of a successful hot-swap: streamed load + smoke "
    "verification + engine build + cutover + queue transplant.",
    ("model",))
_M_ACTIVE = obs.gauge(
    "kct_engine_active_slots", "Slots currently decoding.", ("model",))
_M_SLOTS = obs.gauge(
    "kct_engine_slots", "Configured slot-pool width.", ("model",))
_M_QUEUE = obs.gauge(
    "kct_engine_queue_depth", "Admission queue depth.", ("model",))
_M_KV_UTIL = obs.gauge(
    "kct_engine_kv_utilization",
    "Fraction of the KV pool's token rows holding live context.",
    ("model",))
_M_KV_PAGES = obs.gauge(
    "kct_engine_kv_pages",
    "Allocatable pages in the paged KV arena (excludes the null page).",
    ("model",))
_M_KV_PAGES_FREE = obs.gauge(
    "kct_engine_kv_pages_free",
    "Pages allocatable right now (free list + LRU-evictable cached).",
    ("model",))
_M_PREFIX_HITS = obs.counter(
    "kct_engine_prefix_cache_hits_total",
    "Admissions that reused at least one cached prefix page.", ("model",))
_M_PREFIX_TOKENS = obs.counter(
    "kct_engine_prefix_cache_tokens_saved_total",
    "Prompt tokens served from the prefix cache instead of prefill "
    "compute.", ("model",))
_M_COW = obs.counter(
    "kct_engine_kv_cow_total",
    "Shared prefix pages copied on write before a private tail "
    "prefill.", ("model",))
_M_KV_BYTES = obs.gauge(
    "kct_engine_kv_bytes_per_token",
    "Device KV-cache bytes one resident token row costs across every "
    "layer (int8 arenas include their per-page scale rows) — the "
    "capacity-planning constant behind pages-per-HBM-byte math.",
    ("model",))
_M_QUANT_ERR = obs.gauge(
    "kct_engine_quant_logit_err",
    "Max absolute logit error measured by the most recent "
    "quantization-quality probe against an fp32 arena (0 until a "
    "probe ran; 0 forever on fp32 replicas).", ("model",))
_M_MESH_SHARDS = obs.gauge(
    "kct_engine_mesh_shards",
    "Model-axis mesh shards the decode program runs across (1 = "
    "single-chip; >1 = the shard_map TP program or GSPMD placement "
    "splits every KV head group over that many devices).", ("model",))
_M_KV_TRANSFER_S = obs.histogram(
    "kct_engine_kv_transfer_seconds",
    "Prefill→decode KV handover latency, extract-start to "
    "install-complete, observed on the decode side (disaggregated "
    "serving only).", ("model",))
_M_KV_TRANSFER_PAGES = obs.counter(
    "kct_engine_kv_transfer_pages_total",
    "KV pages moved between disaggregated arenas, by direction "
    "(out = handed off by a prefill-role engine, in = installed by a "
    "decode-role engine).", ("model", "direction"))
_M_SPEC_ACCEPT = obs.gauge(
    "kct_engine_spec_accept_ratio",
    "Lifetime fraction of speculative draft tokens the target's "
    "greedy verification accepted (0 until the first speculative "
    "round; the headline draft-quality signal — decode speedup is "
    "roughly 1 + ratio * spec_k per target dispatch).", ("model",))
_M_SPEC_TOKENS = obs.counter(
    "kct_engine_spec_tokens_total",
    "Speculative draft tokens by verification result (accepted = "
    "emitted without their own target dispatch, rejected = rolled "
    "back by host-side length truncation).", ("model", "result"))
_M_PREFILL_CHUNKS = obs.counter(
    "kct_engine_prefill_chunks_total",
    "Chunked-prefill slices dispatched (Sarathi co-scheduling): a "
    "long prompt admits as several bounded chunks interleaved with "
    "decode steps instead of one stall-length prefill.", ("model",))
_M_DISPATCHES = obs.counter(
    "kct_engine_dispatches_total",
    "Device programs the scheduler launched, by kind.  The padded "
    "multi-program iteration issues up to one each of prefill | "
    "chunk_prefill | decode | verify | cow_copy per pass; the ragged "
    "engine issues exactly one kind=\"ragged\" flat-batch program — "
    "rate(kind=\"ragged\") vs the sum of the padded kinds is the "
    "dispatch-count delta the ragged A/B lane reports.",
    ("model", "kind"))
_M_PADDED_TOKENS = obs.counter(
    "kct_engine_padded_tokens_total",
    "Token rows computed but carrying no real work: bucket padding in "
    "prefill/chunk dispatches, frozen slots in decode steps, masked "
    "draft lanes in verification, and ladder padding in the ragged "
    "flat batch.  The waste the ragged dispatch deletes — compare "
    "against kct_engine_tokens_total for the padding overhead ratio.",
    ("model",))


class RequestCancelled(RuntimeError):
    """The client cancelled (or disappeared from) an in-flight request."""


@dataclasses.dataclass(frozen=True)
class EngineConfig:
    """Knobs for the continuous-batching engine (deploy/README.md maps
    them onto the KServe ``containerConcurrency`` contract)."""

    slots: int = 8            # persistent decode batch width
    max_len: int = 512        # KV rows per slot (prompt + completion)
    max_queue_size: int = 256  # admission queue bound (503 beyond)
    max_admit_per_step: int = 4  # prefills per iteration (admission policy)
    idle_wait_s: float = 0.05  # poll interval when no slot is active
    drain_timeout_s: float = 30.0  # stop(): max wait for in-flight slots
    #: hang-detection grace around each FIRST prefill of a new
    #: (bucket, batch) shape: a cold-cache XLA compile blocks the
    #: scheduler for 20-40s on real hardware, which is indistinguishable
    #: from a wedge by heartbeat alone.  Must exceed the worst-case
    #: single compile; applies only while the cold call is in flight.
    compile_grace_s: float = 120.0
    #: block-granular paged KV pool + cross-request prefix caching
    #: (vLLM/PagedAttention) instead of the dense per-slot pool.
    #: ``max_len`` stays the per-request cap (it sizes the page table);
    #: HBM is bounded by ``num_pages`` instead of ``slots * max_len``.
    paged: bool = False
    #: KV rows per page; full prompt pages are the prefix-cache sharing
    #: unit, so smaller pages share more but gather/hash more
    page_size: int = 16
    #: arena pages INCLUDING the reserved null page; 0 = equal bytes
    #: with the slot pool it replaces (slots * max_len rows) + null
    num_pages: int = 0
    #: paged decode attention: "gather" (pure jnp, runs anywhere),
    #: "pallas" (Mosaic paged-attention kernel), or "fused" (ONE
    #: Mosaic kernel folding page gather + attention + output
    #: projection — ops/fused_decode.py; kernels run interpreted
    #: off-TPU so every impl stays CPU-testable)
    attn_impl: str = "gather"
    #: paged KV storage: "fp32" keeps the model's cache dtype (token-
    #: identical to the slot pool), "int8" stores quantized K/V with
    #: per-page per-head scales — ~4x (fp32) / ~2x (bf16) the resident
    #: pages at equal arena bytes, under a measured logit-error budget
    #: instead of bitwise identity (deploy/README.md "Quantized KV &
    #: fused kernels")
    kv_dtype: str = "fp32"
    #: flight-recorder ring capacity: per-iteration phase records kept
    #: in bounded memory for ``GET /debug/timeline``.  Always on by
    #: default (the recorder is memory-only); 0 disables it — the A/B
    #: knob the overhead benchmark flips (BENCHMARKS.md "Flight
    #: recorder overhead").
    flight_records: int = 1024
    #: multi-tenant traffic plane (serve/tenancy.py): per-tenant
    #: token-bucket admission, weighted fair queueing in decoded+
    #: prefilled tokens, QoS lanes with interactive-over-batch
    #: preemption.  None = one unlimited default tenant, which is
    #: byte-for-byte the pre-tenancy FIFO behavior.
    tenancy: Optional[TenancyConfig] = None
    #: prefill/decode disaggregation (DistServe, OSDI '24 — see
    #: PAPERS.md).  "colocated" is the classic engine.  "prefill"
    #: admits + prefills only: after a request's first token it hands
    #: its prompt KV over page-granularly (serve/disagg.py) instead of
    #: decoding, so prefill bursts never occupy a decode iteration.
    #: "decode" runs the iteration loop over adopted requests whose KV
    #: arrived by page transfer (zero re-prefill on the happy path).
    role: str = "colocated"
    #: role="prefill" model-level wiring: how many in-process decode
    #: engines the prefill engine feeds (each owns its own arena —
    #: on hardware, its own slice group; see deploy/README.md
    #: "Sharded & disaggregated serving")
    decode_slices: int = 1
    #: Sarathi-style chunked prefill (deploy/README.md "Latency:
    #: chunked prefill & speculative decoding"): per-scheduler-pass
    #: prefill token budget.  0 = unchunked — every admission prefills
    #: its whole uncached tail in one dispatch (the legacy behavior).
    #: >0: prefill work is sliced into chunks of at most this many
    #: tokens co-scheduled with decode steps, so one long prompt can
    #: no longer stall every active decode slot for its whole prefill;
    #: a partially-prefilled request keeps its slot (and, paged, its
    #: pages) and resumes at its absolute position next pass,
    #: attending to its own prior chunks through the same gathered
    #: view prefix-cache tail prefill uses.  Also chunks the
    #: preemption-resume re-prefill, softening that cost.
    prefill_chunk_tokens: int = 0
    #: speculative decoding draft source (serve/spec_decode.py):
    #: None = off; "ngram" = built-in prompt-lookup drafting (no draft
    #: model); any other string = a model dir the serving layer loads
    #: as the draft LM (engines built directly pass the draft via
    #: their ``draft=`` kwarg instead).  Paged engines only; greedy
    #: (temperature 0) requests only — stochastic slots in the same
    #: batch keep decoding one token per step through the same
    #: verification dispatch.
    spec_draft: Optional[str] = None
    #: draft tokens proposed (and verified in ONE batched target
    #: step) per speculative round
    spec_k: int = 4
    #: ragged token-level dispatch (Orca selective batching / Sarathi
    #: single hybrid batch): every scheduler pass runs ONE flat
    #: ``[total_tokens]`` program — prefill chunks, decode steps,
    #: spec-decode verification and COW copies are just segment shapes
    #: inside it, with attention routed per-segment through the paged
    #: indirection.  Token counts bucket to a small power-of-two
    #: ladder so the executable cache stays bounded (deploy/README.md
    #: "Ragged dispatch").  Paged engines only; the padded
    #: multi-program iteration remains as the ``ragged=False``
    #: fallback for one release.
    ragged: bool = True

    def __post_init__(self):
        if self.slots < 1:
            raise ValueError("slots must be >= 1")
        if self.flight_records < 0:
            raise ValueError("flight_records must be >= 0")
        if self.max_len < 2:
            raise ValueError("max_len must be >= 2")
        if self.max_queue_size < 1:
            raise ValueError("max_queue_size must be >= 1")
        if self.max_admit_per_step < 1:
            raise ValueError("max_admit_per_step must be >= 1")
        if self.role not in ("colocated", "prefill", "decode"):
            raise ValueError(
                "role must be 'colocated', 'prefill' or 'decode'")
        if self.role != "colocated" and not self.paged:
            raise ValueError(
                "prefill/decode roles require paged=True (the KV "
                "hand-over between roles is page-granular)")
        if self.decode_slices < 1:
            raise ValueError("decode_slices must be >= 1")
        if self.prefill_chunk_tokens < 0:
            raise ValueError("prefill_chunk_tokens must be >= 0 "
                             "(0 disables chunking)")
        if not 1 <= self.spec_k <= 64:
            raise ValueError("spec_k must be in [1, 64]")
        if self.spec_draft is not None and not self.paged:
            raise ValueError(
                "speculative decoding requires paged=True (draft "
                "verification runs through the paged arena; rollback "
                "is host-side length truncation over append-only "
                "pages)")
        if self.paged:
            if self.page_size < 1:
                raise ValueError("page_size must be >= 1")
            if self.max_len % self.page_size:
                raise ValueError(
                    f"max_len ({self.max_len}) must be a multiple of "
                    f"page_size ({self.page_size})")
            if self.attn_impl not in ("gather", "pallas", "fused"):
                raise ValueError("attn_impl must be 'gather', 'pallas' "
                                 "or 'fused'")
            if self.kv_dtype not in paged_kv.KV_DTYPES:
                raise ValueError(
                    f"kv_dtype must be one of {paged_kv.KV_DTYPES}")
            if self.num_pages and self.num_pages < 2:
                raise ValueError("num_pages must be >= 2 (page 0 is "
                                 "the null page)")

    @property
    def pages_per_slot(self) -> int:
        """Page-table width: blocks covering one request at max_len."""
        return self.max_len // self.page_size

    @property
    def effective_num_pages(self) -> int:
        """Arena size at fp32 storage; default matches the slot pool's
        row count so paged-vs-slot comparisons are equal-HBM by
        construction.  :meth:`arena_pages` is the kv_dtype-aware form
        the engine actually allocates."""
        if self.num_pages:
            return self.num_pages
        return self.slots * self.pages_per_slot + 1

    def arena_pages(self, model_cfg) -> int:
        """Arena size INCLUDING the null page, at equal BYTES.

        An explicit ``num_pages`` wins.  Otherwise the budget is the
        slot pool this config would have allocated (``slots × max_len``
        rows at the model's cache dtype), converted into pages at the
        configured ``kv_dtype`` — so flipping int8 on turns the same
        HBM bill into ~4x (fp32 cache) / ~2x (bf16) the resident
        pages instead of shrinking the footprint.  One source of
        truth: ``bench_serving --kv-dtype`` A/Bs and the deploy/README
        capacity math both reduce to this arithmetic."""
        if self.num_pages:
            return self.num_pages
        if self.kv_dtype == "fp32":
            return self.slots * self.pages_per_slot + 1
        cache_bytes = jnp.dtype(model_cfg.dtype).itemsize
        budget = self.slots * self.pages_per_slot * paged_kv.kv_page_bytes(
            self.page_size, model_cfg.kv_heads, model_cfg.head_dim,
            "fp32", cache_bytes)
        page_b = paged_kv.kv_page_bytes(
            self.page_size, model_cfg.kv_heads, model_cfg.head_dim,
            self.kv_dtype)
        return max(2, budget // page_b + 1)


@dataclasses.dataclass
class KVHandoff:
    """Page-granular KV payload a prefill-role engine hands to the
    decode plane (host-staged here; on hardware the same page indices
    would address an ICI/DMA transfer).  ``data`` holds the prompt's
    resident pages as host arrays (``extract_pages``), ``prompt_len``
    the positions they cover (``0..prompt_len-1``), ``hashes`` the
    chain hashes of every FULL block so the receiving arena can
    publish transferred pages into its prefix cache."""

    data: dict
    prompt_len: int
    hashes: list
    #: monotonic extract start — the decode side observes
    #: ``kct_engine_kv_transfer_seconds`` against it at install
    started_at: float


class GenRequest:
    """One in-flight generation: prompt ids in, a token stream out."""

    __slots__ = ("prompt_ids", "max_new_tokens", "temperature", "top_k",
                 "top_p", "rng", "tokens", "stream", "event", "error",
                 "claimed", "cancelled", "submitted_at", "admitted_at",
                 "first_token_at", "done_at", "deadline", "engine",
                 "request_id", "cached_tokens", "tenant", "lane",
                 "pinned_pages", "preemptions", "resume_len",
                 "prefill_pos")

    def __init__(self, prompt_ids: Sequence[int], *, max_new_tokens: int,
                 temperature: float, top_k: int, top_p: float, seed: int,
                 deadline: Optional[float] = None,
                 request_id: Optional[str] = None,
                 tenant: str = "default", lane: str = "interactive"):
        self.prompt_ids = list(prompt_ids)
        self.max_new_tokens = int(max_new_tokens)
        self.temperature = float(temperature)
        self.top_k = int(top_k)
        self.top_p = float(top_p)
        self.rng = np.random.default_rng(int(seed))
        self.tokens: list[int] = []  # emitted completion tokens
        self.stream: "queue.SimpleQueue[Any]" = queue.SimpleQueue()
        self.event = threading.Event()
        self.error: Optional[Exception] = None
        #: set by the scheduler at admission — a claimed request occupies
        #: a slot and WILL finish (stop() drains it)
        self.claimed = False
        self.cancelled = False
        self.submitted_at = time.monotonic()
        #: when the scheduler claimed the request (TTFT decomposes as
        #: queue-wait = admitted_at - submitted_at, prefill-compute =
        #: first_token_at - admitted_at)
        self.admitted_at: Optional[float] = None
        self.first_token_at: Optional[float] = None
        self.done_at: Optional[float] = None
        #: absolute monotonic deadline (None = wait forever); expired
        #: queued requests are shed at admission instead of decoded
        self.deadline = deadline
        #: the engine currently responsible for this request — updated
        #: by ``requeue()`` when a supervisor transplants the queue to a
        #: replacement engine, so liveness re-checks follow the request
        self.engine: Optional["ContinuousBatchingEngine"] = None
        #: correlation id for lifecycle spans (None = untraced)
        self.request_id = request_id
        #: prompt tokens served from the prefix cache at admission
        #: (paged engine; 0 otherwise) — surfaced per prediction so
        #: load tests can account prefill compute actually spent
        self.cached_tokens = 0
        #: traffic-plane identity (serve/tenancy.py): resolved tenant
        #: name + QoS lane, carried through spans and /debug/slots
        self.tenant = tenant
        self.lane = lane
        #: paged mode keeps a preempted request's KV pages reserved so
        #: resume is prefill-free; cleared on resume/transplant/close
        self.pinned_pages: Optional[list] = None
        #: times this request was preempted mid-decode (surfaced per
        #: prediction — the fairness bench asserts preemption actually
        #: exercised)
        self.preemptions = 0
        #: tokens already emitted at the last (re)admission — the
        #: preemption progress guard reads the delta (a batch slot is
        #: only preemptable after min_batch_progress fresh tokens)
        self.resume_len = 0
        #: chunked prefill: absolute context positions already resident
        #: in this request's KV claim (cached prefix included).  A
        #: request preempted MID-CHUNK keeps it alongside its pinned
        #: pages, so resume continues prefilling from here instead of
        #: recomputing delivered chunks; 0 whenever the claim is gone.
        self.prefill_pos = 0

    def cancel(self) -> None:
        """Mark the request dead (client gone).  The scheduler purges it
        at its next iteration — out of the bounded queue (so it can't
        hold capacity against live clients) or out of its slot."""
        self.cancelled = True

    def iter_tokens(self, timeout: float = 60.0) -> Iterator[int]:
        """Stream tokens as the scheduler emits them (SSE-style).

        A stalled stream raises the typed, retryable
        :class:`~kubernetes_cloud_tpu.serve.errors.StreamTimeoutError`
        instead of leaking a raw ``queue.Empty``; each short poll
        re-checks engine liveness first, so a dead engine surfaces in
        ≤0.5 s rather than after the full ``timeout``."""
        while True:
            deadline = time.monotonic() + timeout
            while True:
                try:
                    item = self.stream.get(timeout=min(0.5, timeout))
                    break
                except queue.Empty:
                    eng = self.engine
                    if (eng is not None and not eng.alive
                            and self.stream.empty()):
                        # the client gets its 503 now — mark the request
                        # dead so a supervisor transplant doesn't decode
                        # it into a void on the replacement engine
                        self.cancel()
                        raise StreamTimeoutError(
                            "token stream stalled: engine is dead; "
                            "retry") from None
                    if time.monotonic() >= deadline:
                        state = ("alive" if eng is not None and eng.alive
                                 else "dead")
                        raise StreamTimeoutError(
                            f"no token within {timeout:.1f}s "
                            f"(engine {state}); retry") from None
            if item is _STREAM_END:
                if self.error is not None:
                    raise self.error
                return
            yield item

    def wait(self, engine: Optional["ContinuousBatchingEngine"] = None
             ) -> list[int]:
        """Block until finished; returns emitted tokens or raises."""
        # Bounded wait re-checking engine liveness: a request enqueued in
        # a crash/stop race window must not hang (same shape as
        # BatchingModel.predict's wait loop).  self.engine (kept current
        # across supervisor transplants) takes precedence over the
        # caller's possibly-stale reference.
        while not self.event.wait(timeout=0.5):
            eng = self.engine or engine
            if (eng is not None and not eng.alive
                    and not self.event.is_set()):
                # raising IS the client's answer (503): mark the request
                # dead so a supervisor transplanting the crashed
                # engine's queue doesn't burn slots decoding it
                self.cancel()
                raise RetryableError("engine stopped")
        if self.error is not None:
            raise self.error
        return list(self.tokens)


def _filtered_probs(logits: np.ndarray, *, temperature: float,
                    top_k: int, top_p: float) -> np.ndarray:
    """The stochastic sampling distribution for one [V] logits row:
    temperature → top-k → top-p filtering, then softmax — the exact op
    order ``_sample_host`` has always used (refactored out so
    speculative rejection sampling can score draft tokens against the
    same distribution the non-speculative path samples from)."""
    logits = logits.astype(np.float64) / temperature
    if 0 < top_k < logits.shape[-1]:
        kth = np.sort(logits)[-top_k]
        logits = np.where(logits < kth, -np.inf, logits)
    if top_p < 1.0:
        sorted_logits = np.sort(logits)[::-1]
        probs = _softmax(sorted_logits)
        cum = np.cumsum(probs)
        cutoff = sorted_logits[min(int((cum < top_p).sum()),
                                   len(sorted_logits) - 1)]
        logits = np.where(logits < cutoff, -np.inf, logits)
    return _softmax(logits)


def _sample_host(logits: np.ndarray, rng: np.random.Generator, *,
                 temperature: float, top_k: int, top_p: float) -> int:
    """Host-side mirror of :func:`models.generate.sample_token` for one
    slot's [V] logits row.  Greedy (temperature 0) is exactly argmax, so
    greedy decode is token-identical to the device sampler; stochastic
    sampling matches its distribution (numpy RNG, not jax's)."""
    if temperature == 0.0:
        return int(logits.argmax())
    probs = _filtered_probs(logits, temperature=temperature,
                            top_k=top_k, top_p=top_p)
    return int(rng.choice(probs.shape[-1], p=probs))


def _softmax(x: np.ndarray) -> np.ndarray:
    e = np.exp(x - x[np.isfinite(x)].max())
    e = np.where(np.isfinite(x), e, 0.0)
    return e / e.sum()


_JITTED: dict[str, Any] = {}


def _jit_prefill():
    # Module-level singletons so every engine instance (and every test)
    # shares one compilation cache.  Pool buffers are donated: every
    # iteration replaces the engine's pool reference, so the device
    # updates K/V in place instead of copying the whole pool.
    if "prefill" not in _JITTED:
        _JITTED["prefill"] = jax.jit(prefill_into_slots, static_argnums=0,
                                     donate_argnums=4)
    return _JITTED["prefill"]


def _jit_decode():
    if "decode" not in _JITTED:
        _JITTED["decode"] = jax.jit(decode_step_slots, static_argnums=0,
                                    donate_argnums=3)
    return _JITTED["decode"]


def _jit_prefill_pages():
    if "prefill_pages" not in _JITTED:
        _JITTED["prefill_pages"] = jax.jit(
            prefill_into_pages, static_argnums=0, donate_argnums=4)
    return _JITTED["prefill_pages"]


def _jit_decode_pages():
    if "decode_pages" not in _JITTED:
        _JITTED["decode_pages"] = jax.jit(
            decode_step_pages, static_argnums=0,
            static_argnames=("impl",), donate_argnums=3)
    return _JITTED["decode_pages"]


def _jit_copy_pages():
    if "copy_pages" not in _JITTED:
        _JITTED["copy_pages"] = jax.jit(copy_pages, donate_argnums=0)
    return _JITTED["copy_pages"]


def _jit_chunk_slots():
    if "chunk_slots" not in _JITTED:
        _JITTED["chunk_slots"] = jax.jit(
            prefill_chunk_into_slots, static_argnums=0, donate_argnums=4)
    return _JITTED["chunk_slots"]


def _jit_verify_pages():
    if "verify_pages" not in _JITTED:
        _JITTED["verify_pages"] = jax.jit(
            verify_step_pages, static_argnums=0, donate_argnums=4)
    return _JITTED["verify_pages"]


def _jit_ragged_pages():
    if "ragged_pages" not in _JITTED:
        _JITTED["ragged_pages"] = jax.jit(
            ragged_step_pages, static_argnums=0,
            static_argnames=("impl",), donate_argnums=6)
    return _JITTED["ragged_pages"]


def _pow2_bucket(n: int, floor: int) -> int:
    """Smallest power-of-two ≥ max(n, floor) — the ragged geometry
    ladder (log-many compiled shapes per dimension)."""
    b = floor
    while b < n:
        b *= 2
    return b


class _RaggedPass:
    """One scheduler pass's flat hybrid batch, accumulated host-side.

    The scheduler's builders (chunk continuation, admission, decode,
    spec verify) append *segments* — runs of real tokens for one slot
    at absolute context positions — plus copy-on-write page pairs and
    deferred continuations; ``_flush_ragged`` then pads to the
    geometry ladder, runs ONE device program, and replays the
    continuations (emit / finish-chunking / handoff) against the
    gathered logits in build order."""

    __slots__ = ("tokens", "seg_slot", "positions", "out_rows",
                 "copy_src", "copy_dst", "override_rows",
                 "continuations", "kinds", "step_slots", "_base_slots")

    def __init__(self, slots: int):
        self.tokens: list[int] = []
        self.seg_slot: list[int] = []
        self.positions: list[int] = []
        #: flat-batch row indices whose logits the host reads
        self.out_rows: list[int] = []
        self.copy_src: list[int] = []
        self.copy_dst: list[int] = []
        #: page lists dispatched as table rows ``slots + i`` — a
        #: mid-chunk slot's global table row is deliberately null, and
        #: a slot preempted+refilled within one pass needs two
        #: different rows, so chunk segments always route through a
        #: private virtual row instead of the slot's own
        self.override_rows: list[list] = []
        self.continuations: list = []
        self.kinds: set[str] = set()
        #: decode/verify slots stepped this pass (active_slot_steps)
        self.step_slots = 0
        self._base_slots = slots

    def override(self, pages: list) -> int:
        """Reserve a private table row; returns its virtual slot id."""
        self.override_rows.append(list(pages))
        return self._base_slots + len(self.override_rows) - 1

    def add_segment(self, vslot: int, token_ids, start: int, *,
                    kind: str, out: str) -> list[int]:
        """Append one segment; ``out`` is which rows the host will
        read ("all" | "last" | "none").  Returns indices into the
        flush's gathered logits for those rows."""
        base = len(self.tokens)
        n = len(token_ids)
        self.tokens.extend(int(t) for t in token_ids)
        self.seg_slot.extend([int(vslot)] * n)
        self.positions.extend(range(int(start), int(start) + n))
        self.kinds.add(kind)
        if out == "all":
            rows = range(base, base + n)
        elif out == "last" and n:
            rows = [base + n - 1]
        else:
            rows = []
        idxs = []
        for r in rows:
            idxs.append(len(self.out_rows))
            self.out_rows.append(r)
        return idxs


class ContinuousBatchingEngine:
    """Owns the slot pool and the scheduler thread.

    Works on token ids only — tokenization/option plumbing lives in
    :class:`ContinuousBatchingModel`.  Thread-safe: ``submit`` may be
    called from any number of HTTP threads; one scheduler thread owns
    the device.
    """

    def __init__(self, cfg: CausalLMConfig, params: Any,
                 engine_cfg: EngineConfig = EngineConfig(), *,
                 eos_token_id: Optional[int] = None, pad_token_id: int = 0,
                 mesh=None, name: str = "engine", draft: Any = None,
                 weights_version: Optional[str] = None):
        self.cfg = cfg
        self.params = params
        #: content-hash identity of the params this engine decodes with
        #: (a hot-swap builds a NEW engine for the new version, so the
        #: version is engine-scoped by construction — a request served
        #: mid-rollout reports the weights that actually produced it)
        self.weights_version = weights_version
        self.ecfg = engine_cfg
        self.eos = eos_token_id
        self.pad = pad_token_id
        self.mesh = mesh
        #: metric/trace label (the serving model's name); restarts reuse
        #: it, so a replacement engine continues the same time series
        self.name = name
        self.pool: Optional[dict] = None
        self._slots: list[Optional[GenRequest]] = [None] * engine_cfg.slots
        #: arena size INCLUDING the null page, kv_dtype-aware (equal
        #: bytes with the slot pool unless num_pages pins it); 0 for
        #: the dense pool
        self._num_pages = (engine_cfg.arena_pages(cfg)
                           if engine_cfg.paged else 0)
        # Per-tenant queues + WFQ drain order instead of one global
        # deque (serve/tenancy.py); _qlock still guards every queue
        # mutation AND the virtual-time/occupancy accounting, so the
        # old single-queue invariants (purgeable middles, trace-inside-
        # lock ordering) carry over.  The no-config default is one
        # unlimited FIFO tenant — the legacy behavior exactly.
        self.tenants = TenantScheduler(
            engine_cfg.tenancy, slots=engine_cfg.slots,
            page_capacity=(self._num_pages - 1
                           if engine_cfg.paged else 0),
            model=name)
        self._qlock = threading.Lock()
        self._stop = threading.Event()
        self._work = threading.Event()  # submit()/stop() wake the loop
        self._thread: Optional[threading.Thread] = None
        self._prefill = _jit_prefill()
        self._decode = _jit_decode()
        #: paged mode: host-owned page allocator + indirection state
        #: (the scheduler thread is the single owner, like _slots)
        self.paged = engine_cfg.paged
        self.allocator: Optional[PageAllocator] = None
        self._prefill_pages = _jit_prefill_pages()
        self._decode_pages = _jit_decode_pages()
        self._copy_pages = _jit_copy_pages()
        self._chunk_slots = _jit_chunk_slots()
        self._verify_pages = _jit_verify_pages()
        self._ragged_pages = _jit_ragged_pages()
        #: ragged token-level dispatch: the whole pass is ONE flat-
        #: batch program; paged engines only (the segment routing IS
        #: the paged indirection)
        self._ragged = engine_cfg.paged and engine_cfg.ragged
        #: the pass under construction (scheduler thread only); None
        #: between passes and always None on the padded path
        self._pass: Optional[_RaggedPass] = None
        #: chunked prefill (Sarathi co-scheduling): slots mid-prefill,
        #: slot -> {"req", "vprompt", "resumed", "res"}; the request's
        #: ``prefill_pos`` tracks delivered positions.  Chunking slots
        #: hold their slot + pages but are excluded from the decode
        #: batch until their final chunk lands.
        self._chunking: dict[int, dict] = {}
        self._budget_left: Optional[int] = None  # per-pass chunk budget
        #: mesh-sharded decode (ROADMAP item 1): with a model axis > 1
        #: and a dividing config, the paged programs are replaced by
        #: ONE shard_map TP program per iteration
        #: (models/tp_decode.py) — params split q/k/v and sharded by
        #: heads, the arena (and its int8 scales) sharded over the
        #: kv-head axis, scheduler state replicated on the host.
        #: Otherwise a mesh still shards pool + params via GSPMD
        #: placement (the pre-TP behavior).
        self.mesh_shards = 1
        self._tp_active = False
        if mesh is not None:
            from kubernetes_cloud_tpu.core.mesh import AXIS_MODEL

            self.mesh_shards = int(mesh.shape.get(AXIS_MODEL, 1))
        if engine_cfg.paged and self.mesh_shards > 1:
            from kubernetes_cloud_tpu.models import tp_decode

            reason = tp_decode.tp_unsupported_reason(cfg, mesh)
            if reason is None:
                self.params = tp_decode.place_tp_params(cfg, params, mesh)
                if self._ragged:
                    # ragged engines build ONE shard_map program — the
                    # flat hybrid batch is the only iteration shape, so
                    # the legacy prefill/decode/verify trio never
                    # compiles
                    _tp_rg = tp_decode.build_tp_ragged_program(
                        cfg, mesh, self.params,
                        kv_dtype=engine_cfg.kv_dtype,
                        attn_impl=engine_cfg.attn_impl)
                    self._ragged_pages = (
                        lambda _c, p, tok, ss, pos, msk, pool, tbl,
                        orows, csrc, cdst, impl=None:
                        _tp_rg(p, tok, ss, pos, msk, pool, tbl,
                               orows, csrc, cdst))
                else:
                    _tp_pf, _tp_dec, _tp_vf = tp_decode.build_tp_programs(
                        cfg, mesh, self.params,
                        kv_dtype=engine_cfg.kv_dtype,
                        attn_impl=engine_cfg.attn_impl)
                    # same call signature as the single-chip jits (cfg
                    # is baked into the shard_map closure; impl
                    # likewise)
                    self._prefill_pages = (
                        lambda _c, p, ids, msk, pool, tbl, st:
                        _tp_pf(p, ids, msk, pool, tbl, st))
                    self._decode_pages = (
                        lambda _c, p, tok, pool, tbl, ln, impl=None:
                        _tp_dec(p, tok, pool, tbl, ln))
                    self._verify_pages = (
                        lambda _c, p, tok, msk, pool, tbl, ln:
                        _tp_vf(p, tok, msk, pool, tbl, ln))
                self._tp_active = True
            else:
                log.warning(
                    "engine %s: shard_map TP decode unavailable (%s); "
                    "falling back to GSPMD placement", name, reason)
        #: speculative decoding (serve/spec_decode.py): a draft source
        #: proposes spec_k tokens per greedy slot, verified in ONE
        #: batched target step.  ``draft`` may be a DraftSource, a
        #: (cfg, params) pair for the small draft LM, or None (then
        #: spec_draft == "ngram" still activates prompt-lookup
        #: drafting).  Prefill-role engines never decode, so they
        #: never speculate.
        self.draft: Optional[DraftSource] = None
        self._draft_flops = (0.0, 0.0)
        if engine_cfg.paged and engine_cfg.role != "prefill":
            src = None
            if isinstance(draft, DraftSource):
                src = draft
            elif draft is not None:
                dcfg, dparams = draft
                src = ModelDraft(dcfg, dparams, slots=engine_cfg.slots,
                                 max_len=engine_cfg.max_len,
                                 pad_token_id=pad_token_id)
            elif engine_cfg.spec_draft == "ngram":
                src = NgramDraft()
            if src is not None:
                self.draft = src
                dc = getattr(src, "cfg", None)
                if dc is not None:
                    self._draft_flops = obs_flops.decode_flops_coeffs(dc)
                if engine_cfg.attn_impl in ("pallas", "fused"):
                    log.warning(
                        "%s: speculative verification always runs the "
                        "XLA attention path while decode runs "
                        "attn_impl=%r; greedy identity then rests on "
                        "cross-kernel argmax agreement — which "
                        "kernel_parity.py only gates against the "
                        "gather/xla pair — and a stochastic slot "
                        "co-batched with a greedy one samples from "
                        "the verification logits, so its seeded "
                        "output can depend on co-batched traffic "
                        "near softmax ties.  Validate with "
                        "bench_serving --spec-decode on this hardware "
                        "before trusting bitwise identity.",
                        name, engine_cfg.attn_impl)
        #: slots the draft source currently holds context for — filled
        #: lazily at the first speculative round a slot joins (covers
        #: fresh admission, every resume flavor, and adoption with one
        #: hook), dropped on finish/preempt
        self._spec_ready: set[int] = set()
        #: False until the (spec_k+1)-wide verify program has compiled:
        #: the first speculative round raises grace_until around its
        #: dispatch (plus the draft LM's own first compiles) exactly
        #: like _prefill_cold_guard, so a 20-40s cold-cache XLA compile
        #: on the scheduler thread doesn't read as a wedge to the
        #: supervisor watchdog
        self._spec_warm = False
        #: prefill/decode disaggregation (serve/disagg.py): a prefill-
        #: role engine hands requests over after their first token;
        #: a decode-role engine adopts transferred KV at pass start
        self.role = engine_cfg.role
        self._handoff_cb = None
        self._adopt_lock = threading.Lock()
        self._adopt: list[tuple[GenRequest, KVHandoff]] = []
        self._install_pages = jax.jit(install_pages, donate_argnums=0)
        self._page_table = np.zeros(
            (engine_cfg.slots, engine_cfg.pages_per_slot), np.int32)
        self._lengths = np.zeros((engine_cfg.slots,), np.int32)
        self._slot_pages: list[Optional[list]] = [None] * engine_cfg.slots
        #: device mirror of _page_table, refreshed only when admission/
        #: eviction dirties it — the table is constant across the
        #: (hot) decode iterations in between, unlike lengths
        self._page_table_dev: Optional[jax.Array] = None
        self._page_table_dirty = True
        #: armed by reset_peak_active(); applied on the scheduler
        #: thread so the reset can't lose a race with its
        #: read-modify-write peak update
        self._peak_reset = threading.Event()
        #: beaten once per scheduler pass (idle polls included), so a
        #: fresh heartbeat always means "the loop is turning" — the
        #: supervisor's watchdog reads it
        self.heartbeat = Heartbeat()
        #: set by a supervisor giving up on this engine; the scheduler
        #: exits at the next opportunity without touching the queue
        self._abandoned = False
        #: requests popped+claimed by _admit but not yet slotted — a
        #: wedge/crash inside prefill leaves them in neither the queue
        #: nor _slots, so failure paths must fail them explicitly or
        #: their waiters would hang on a live-but-wedged engine
        self._admitting: list[GenRequest] = []
        #: prefill shapes already compiled; a first-time shape raises
        #: grace_until around its dispatch so the watchdog doesn't read
        #: the cold compile as a hang (cleared the moment it returns)
        self._warm_shapes: set[tuple[int, int]] = set()
        self.grace_until = 0.0  # monotonic; heartbeat staleness before
        # this instant is a compile, not a wedge
        #: the exception that killed the scheduler, if it crashed
        self.last_error: Optional[Exception] = None
        #: EWMA of decode-iteration wall time — admission control uses
        #: it to estimate queued-work delay for deadline shedding
        self.iter_s: Optional[float] = None
        # iteration-level telemetry (the serving bench reads these);
        # prefill_tokens counts tokens actually run through prefill
        # (prefix-cache hits subtract), prompt_tokens the total asked
        # for — their gap is the compute the cache eliminated
        self.stats = {"iterations": 0, "admitted": 0, "emitted_tokens": 0,
                      "evictions": 0, "cancelled": 0, "active_slot_steps": 0,
                      "deadline_shed": 0, "prefill_tokens": 0,
                      "prompt_tokens": 0, "prefix_hits": 0,
                      "prefix_tokens_saved": 0, "cow_copies": 0,
                      "peak_active": 0, "preemptions": 0, "resumed": 0,
                      # disaggregation accounting: handoffs a prefill-
                      # role engine exported, requests a decode-role
                      # engine adopted, pages moved either way, and
                      # prompt tokens RE-prefilled for resumes whose
                      # KV was lost (the happy-path handover keeps
                      # this at 0 — the acceptance bar)
                      "handoffs": 0, "adopted": 0,
                      "kv_transfer_pages": 0, "reprefill_tokens": 0,
                      # latency offensive: chunked-prefill slices
                      # dispatched, and the speculative-decoding
                      # ledger (drafted vs accepted is the accept
                      # ratio; rounds = verification dispatches)
                      "prefill_chunks": 0, "spec_rounds": 0,
                      "spec_drafted": 0, "spec_accepted": 0,
                      # ragged-dispatch A/B accounting: device programs
                      # launched (every kind) and token rows computed
                      # as padding — the bench's dispatch-count and
                      # padding-waste deltas read straight from here
                      "dispatches": 0, "padded_tokens": 0}
        #: always-on flight recorder: bounded ring of per-iteration
        #: phase timings + batch composition (GET /debug/timeline);
        #: flight_records=0 disables it for overhead A/Bs.  A restart
        #: builds a fresh engine and therefore a fresh ring, like stats.
        self.flight: Optional[FlightRecorder] = (
            FlightRecorder(engine_cfg.flight_records)
            if engine_cfg.flight_records else None)
        #: the record of the scheduler pass currently in flight (owned
        #: by the scheduler thread; helpers like _emit/_finish_slot
        #: accumulate into it)
        self._rec = None
        # analytical FLOPs coefficients: one token at context c costs
        # base + per_ctx * c (obs/flops.py); precomputed so the hot
        # loop pays two multiply-adds per iteration
        self._flops_base, self._flops_per_ctx = \
            obs_flops.decode_flops_coeffs(cfg)
        self._peak_flops = obs_flops.peak_flops_per_s()
        # the same coefficients price the WFQ service clock per token
        # KIND (VTC's deferred weighted-cost item): a prefill token at
        # context c costs (base + per_ctx*c)/base decode-equivalents
        self.tenants.set_cost_model(self._flops_base, self._flops_per_ctx)
        #: which phase label the decode step bills to — "fused_decode"
        #: makes a fused-kernel rollout visible in the phase-share rate
        self._decode_phase = ("fused_decode"
                              if self.paged
                              and engine_cfg.attn_impl == "fused"
                              else "decode")
        #: last kv_quant_probe result attached via note_quant_probe
        #: (bench / operator tooling); surfaces in /debug/pages
        self.last_quant_probe: Optional[dict] = None
        self._rates_at = 0.0  # last MFU/goodput gauge refresh (gated)
        # scrape-facing mirror: label-bound children resolved once so the
        # per-iteration cost is attribute access, not dict lookups
        m = {"model": self.name}
        self._m_iters = _M_ITERS.labels(**m)
        self._m_iter_prefill = _M_ITER_S.labels(model=self.name,
                                                phase="prefill",
                                                role=engine_cfg.role)
        self._m_iter_decode = _M_ITER_S.labels(model=self.name,
                                               phase="decode",
                                               role=engine_cfg.role)
        self._m_iter_chunked = _M_ITER_S.labels(model=self.name,
                                                phase="chunked_prefill",
                                                role=engine_cfg.role)
        self._m_phase = {p: _M_PHASE_S.labels(model=self.name, phase=p)
                         for p in PHASES}
        self._m_mfu = _M_MFU.labels(**m)
        self._m_goodput = _M_GOODPUT.labels(**m)
        self._m_admitted = _M_ADMITTED.labels(**m)
        self._m_evicted = _M_EVICTED.labels(**m)
        self._m_cancelled = _M_CANCELLED.labels(**m)
        self._m_tokens = _M_TOKENS.labels(**m)
        self._m_ttft = _M_TTFT.labels(**m)
        self._m_active = _M_ACTIVE.labels(**m)
        self._m_queue = _M_QUEUE.labels(**m)
        self._m_kv_util = _M_KV_UTIL.labels(**m)
        self._m_kv_pages = _M_KV_PAGES.labels(**m)
        self._m_kv_pages_free = _M_KV_PAGES_FREE.labels(**m)
        self._m_prefix_hits = _M_PREFIX_HITS.labels(**m)
        self._m_prefix_tokens = _M_PREFIX_TOKENS.labels(**m)
        self._m_cow = _M_COW.labels(**m)
        self._m_quant_err = _M_QUANT_ERR.labels(**m)
        self._m_quant_err.set(0.0)
        self._m_spec_accept = _M_SPEC_ACCEPT.labels(**m)
        self._m_spec_accepted = _M_SPEC_TOKENS.labels(
            model=self.name, result="accepted")
        self._m_spec_rejected = _M_SPEC_TOKENS.labels(
            model=self.name, result="rejected")
        self._m_prefill_chunks = _M_PREFILL_CHUNKS.labels(**m)
        self._m_dispatch = {
            kind: _M_DISPATCHES.labels(model=self.name, kind=kind)
            for kind in ("prefill", "chunk_prefill", "decode", "verify",
                         "cow_copy", "ragged")}
        self._m_padded = _M_PADDED_TOKENS.labels(**m)
        if self.draft is not None:
            self._m_spec_accept.set(0.0)
        self._m_kv_transfer_s = _M_KV_TRANSFER_S.labels(**m)
        self._m_kv_transfer_out = _M_KV_TRANSFER_PAGES.labels(
            model=self.name, direction="out")
        self._m_kv_transfer_in = _M_KV_TRANSFER_PAGES.labels(
            model=self.name, direction="in")
        _M_MESH_SHARDS.labels(**m).set(self.mesh_shards)
        cache_bytes = jnp.dtype(cfg.dtype).itemsize
        if self.paged:
            bpt = paged_kv.kv_bytes_per_token(
                engine_cfg.page_size, cfg.kv_heads, cfg.head_dim,
                cfg.num_layers, engine_cfg.kv_dtype, cache_bytes)
        else:
            bpt = (cfg.num_layers * 2 * cfg.kv_heads * cfg.head_dim
                   * cache_bytes)
        self.kv_bytes_per_token = float(bpt)
        _M_KV_BYTES.labels(**m).set(self.kv_bytes_per_token)
        _M_SLOTS.labels(**m).set(engine_cfg.slots)

    # -- lifecycle ---------------------------------------------------------

    @property
    def alive(self) -> bool:
        return self._thread is not None and self._thread.is_alive()

    @property
    def draining(self) -> bool:
        """A timed-out stop() left the scheduler still running."""
        return self.alive and self._stop.is_set()

    def start(self) -> None:
        if self.alive:
            if self._stop.is_set():
                # a previous stop() timed out mid-drain; two schedulers
                # would race the queue and the pool.  Typed retryable
                # (503): the drain finishes on its own (KCT-ERR-004).
                raise EngineDrainingError(
                    "previous scheduler still draining; call stop() again")
            return
        self._stop.clear()
        self.pool = self._init_pool()
        # Warm the steady-state decode program BEFORE the scheduler (and
        # readiness) exists: the loop's first real iteration must not
        # sit in a 20-40s XLA compile looking exactly like a wedged
        # device to the supervisor's heartbeat watchdog.  An all-frozen
        # step is a semantic no-op on a fresh pool (every slot writes at
        # length 0), and the persistent compile cache (serve/boot.py)
        # makes this instant on warm boots.  Prefill compiles stay
        # per-bucket on demand, protected by the compile_grace_s window
        # (_admit raises grace_until around each first-time shape).
        if self._ragged:
            # the steady-state ragged decode shape: the smallest
            # ladder rung (8 tokens, 8 out rows, no COW).  All-masked
            # rows write into the null page, so this is a semantic
            # no-op exactly like the frozen decode warm-up below.
            z8 = jnp.zeros((8,), jnp.int32)
            tbl = jnp.zeros((2 * self.ecfg.slots,
                             self.ecfg.pages_per_slot), jnp.int32)
            c0 = jnp.zeros((0,), jnp.int32)
            _, self.pool = self._ragged_pages(
                self.cfg, self.params, z8, z8, z8, z8, self.pool,
                tbl, z8, c0, c0, impl=self.ecfg.attn_impl)
            self._warm_shapes.add(("ragged", 8, 8, 0))
        elif self.paged:
            _, self.pool = self._decode_pages(
                self.cfg, self.params,
                jnp.zeros((self.ecfg.slots,), jnp.int32), self.pool,
                self._device_page_table(),
                jnp.asarray(self._lengths), impl=self.ecfg.attn_impl)
        else:
            _, self.pool = self._decode(
                self.cfg, self.params,
                jnp.zeros((self.ecfg.slots,), jnp.int32), self.pool,
                jnp.zeros((self.ecfg.slots,), bool))
        self.heartbeat.beat()
        self._thread = threading.Thread(target=self._loop, daemon=True,
                                        name="cb-engine")
        self._thread.start()

    def stop(self) -> None:
        """Stop admitting, fail queued requests, drain in-flight slots
        to completion, then stop the scheduler."""
        self._stop.set()
        self._work.set()
        if self._thread is not None:
            self._thread.join(timeout=self.ecfg.drain_timeout_s)
            if self._thread.is_alive():
                log.warning(
                    "continuous-batching engine did not drain within "
                    "%.0f s; scheduler thread still running",
                    self.ecfg.drain_timeout_s)

    def _init_pool(self) -> dict:
        if self.paged:
            return self._init_arena()
        pool = init_cache(self.cfg, self.ecfg.slots, self.ecfg.max_len)
        if self.mesh is not None:
            from jax.sharding import PartitionSpec as P

            from kubernetes_cloud_tpu.core.mesh import AXIS_MODEL, BATCH_AXES
            from kubernetes_cloud_tpu.parallel.sharding import (
                logical_to_physical,
            )

            batch_ways = 1
            for ax in BATCH_AXES:
                batch_ways *= self.mesh.shape.get(ax, 1)
            if self.ecfg.slots % max(batch_ways, 1):
                raise ValueError(
                    f"slots ({self.ecfg.slots}) must be divisible by the "
                    f"mesh batch ways ({batch_ways})")
            heads = (AXIS_MODEL if self.cfg.kv_heads
                     % max(self.mesh.shape.get(AXIS_MODEL, 1), 1) == 0
                     else None)
            kv = P(None, BATCH_AXES, None, heads, None)
            pool = jax.device_put(pool, logical_to_physical(
                {"k": kv, "v": kv, "length": P(BATCH_AXES)}, self.mesh))
        return pool

    def _init_arena(self) -> dict:
        """Paged mode: fixed page arena + fresh allocator and cleared
        host-side indirection (restart = cold prefix cache)."""
        self.allocator = PageAllocator(self._num_pages,
                                       self.ecfg.page_size,
                                       kv_dtype=self.ecfg.kv_dtype)
        self._page_table[:] = 0
        self._page_table_dirty = True
        self._lengths[:] = 0
        self._slot_pages = [None] * self.ecfg.slots
        arena = init_page_arena(self.cfg, self._num_pages,
                                self.ecfg.page_size,
                                kv_dtype=self.ecfg.kv_dtype)
        if self.mesh is not None:
            # pages replicate (the indirection gather is position-
            # blind); only KV heads shard — the one rule table
            # (parallel/sharding.kv_arena_specs) also defines the TP
            # program's shard_map specs, so placement and program can
            # never disagree.  An int8 arena's [L, NP, Hkv] scale
            # buffers follow their pages' head axis.
            from jax.sharding import PartitionSpec as P

            from kubernetes_cloud_tpu.core.mesh import AXIS_MODEL
            from kubernetes_cloud_tpu.parallel.sharding import (
                kv_arena_specs,
                logical_to_physical,
            )

            if self.cfg.kv_heads % max(
                    self.mesh.shape.get(AXIS_MODEL, 1), 1) == 0:
                spec = kv_arena_specs("k_scale" in arena)
            else:  # heads don't divide: replicate (GSPMD fallback)
                kv = P(None, None, None, None, None)
                spec = {"k": kv, "v": kv}
                if "k_scale" in arena:
                    sc = P(None, None, None)
                    spec.update(k_scale=sc, v_scale=sc)
            arena = jax.device_put(arena,
                                   logical_to_physical(spec, self.mesh))
        return arena

    # -- request side ------------------------------------------------------

    def reset_peak_active(self) -> None:
        """Restart the ``peak_active`` stat's window from the next
        scheduler pass (benchmarks bracket their measured window with
        this).  Applied scheduler-side: a direct cross-thread write
        could land inside the scheduler's read-modify-write of the
        same key and be overwritten."""
        self._peak_reset.set()

    def note_quant_probe(self, probe: Mapping[str, Any]) -> None:
        """Attach a :func:`~kubernetes_cloud_tpu.models.generate.
        kv_quant_probe` result to this engine: feeds the
        ``kct_engine_quant_logit_err`` gauge and ``/debug/pages`` so a
        scrape can see the replica's measured error budget, not just
        its dtype."""
        self.last_quant_probe = dict(probe)
        self._m_quant_err.set(float(probe.get("max_logit_err", 0.0)))

    def set_handoff(self, cb) -> None:
        """Wire the prefill→decode coupling (serve/disagg.py): on a
        prefill-role engine, ``cb(req, KVHandoff)`` fires on the
        scheduler thread right after a request's first token, instead
        of the request keeping its slot for decode."""
        self._handoff_cb = cb

    def adopt(self, req: GenRequest, payload: KVHandoff) -> None:
        """Decode-role intake: take over a request whose prompt KV
        arrives by page transfer instead of prefill compute.
        Thread-safe; the scheduler installs the pages at its next pass
        (it is the arena's single owner — installing from this thread
        would race the decode program's donated buffer)."""
        if not self.paged:
            raise ValueError("adopt() requires the paged engine")
        if self._stop.is_set() or not self.alive:
            raise RetryableError("engine stopped")
        req.engine = self
        req.claimed = False
        with self._adopt_lock:
            self._adopt.append((req, payload))
        self._work.set()
        if self._stop.is_set():
            # lost the race with stop(): the scheduler may already
            # have run its final drain (same shape as submit())
            self._fail_adoptions(RetryableError("engine stopped"))

    def _fail_adoptions(self, err: Exception) -> None:
        with self._adopt_lock:
            pending, self._adopt = self._adopt, []
        for req, _payload in pending:
            if req.event.is_set():
                continue
            req.error = err
            trace(req.request_id, "failed", model=self.name,
                  error=type(err).__name__)
            req.stream.put(_STREAM_END)
            req.event.set()

    def _process_adoptions(self) -> None:
        """Install transferred KV into freshly reserved pages and
        queue the adopted requests (scheduler thread — single owner of
        arena + allocator).  The request resumes through the existing
        pinned-pages path: its indirection re-installs at
        ``prompt + tokens - 1`` with ZERO re-prefill tokens.  Page
        exhaustion keeps the remainder pending — pages free as
        decoding slots evict, exactly like waiting admission."""
        with self._adopt_lock:
            pending, self._adopt = self._adopt, []
        if not pending:
            return
        for i, (req, payload) in enumerate(pending):
            if req.cancelled:
                self.stats["cancelled"] += 1
                self._m_cancelled.inc()
                trace(req.request_id, "cancelled", model=self.name)
                req.error = RequestCancelled("request cancelled")
                req.stream.put(_STREAM_END)
                req.event.set()
                continue
            plen = payload.prompt_len
            vnew = req.max_new_tokens - len(req.tokens) + 1
            n_total = paged_kv.pages_needed(plen, vnew, self.ecfg.page_size)
            try:
                pages = self.allocator.reserve_blank(n_total)
            except KVPagesExhaustedError:
                # Backpressure, NOT the pinned-reclaim valve: every
                # pinned queue entry here is itself adoption/preempt
                # state, and stripping one to page another in converts
                # transferred KV into future re-prefill one for one
                # (pure churn, measured in the disagg bench).  Pinned
                # requests resume without reserving, so waiting for a
                # slot eviction always makes progress.
                with self._adopt_lock:  # retry next pass, order kept
                    self._adopt = list(pending[i:]) + self._adopt
                break
            t0 = time.perf_counter()
            n_payload = payload.data["k"].shape[1]
            # Bucket the install shape (power-of-two page count) so
            # varied prompt lengths reuse one compiled program per
            # bucket instead of paying a blocking XLA compile on the
            # decode scheduler thread per distinct page count — the
            # same rationale as _bucket() for prefill shapes.  Pad
            # rows write into the null page (garbage by design).
            bucket = 1
            while bucket < n_payload:
                bucket *= 2
            if bucket > n_payload:
                pad = bucket - n_payload
                data = {k: np.concatenate(
                    [v, np.zeros((v.shape[0], pad) + v.shape[2:],
                                 v.dtype)], axis=1)
                    for k, v in payload.data.items()}
                dst = pages[:n_payload] + [paged_kv.NULL_PAGE] * pad
            else:
                data, dst = payload.data, pages[:n_payload]
            self.pool = self._install_pages(
                self.pool, jnp.asarray(dst, jnp.int32), data)
            dt = time.perf_counter() - t0
            # full prompt blocks become prefix-cache entries on this
            # arena too, so later requests sharing the prefix dedup
            # against transferred content.  Never the partial last
            # page: the next decode write lands at position plen,
            # i.e. page plen // page_size, which is only part of the
            # published set when plen is page-aligned — and then the
            # write goes to the FOLLOWING (blank) page.
            n_pub = plen // self.ecfg.page_size
            self.allocator.register_blocks(payload.hashes[:n_pub],
                                           pages[:n_pub])
            req.pinned_pages = pages
            # the transferred pages hold every position through
            # prompt_len: a chunking engine's pinned-resume check must
            # see the claim as fully delivered (zero re-prefill)
            req.prefill_pos = payload.prompt_len
            req.resume_len = len(req.tokens)
            with self._qlock:
                self.tenants.note_pages(req.tenant, len(pages))
                # bypasses the queue bound like requeue(): the request
                # already won admission on the prefill side
                self.tenants.append(req)
            self.stats["adopted"] += 1
            self.stats["kv_transfer_pages"] += n_payload
            self._m_kv_transfer_in.inc(n_payload)
            self._m_kv_transfer_s.observe(
                time.monotonic() - payload.started_at)
            trace(req.request_id, "kv_install", model=self.name,
                  dur_s=dt, pages=n_payload)
            rec = self._rec
            if rec is not None:
                rec.phases["kv_transfer"] = \
                    rec.phases.get("kv_transfer", 0.0) + dt

    def _device_page_table(self) -> jax.Array:
        """Host→device upload of the indirection table, paid only when
        admission/eviction changed it (decode iterations between
        scheduler events reuse the resident copy)."""
        if self._page_table_dirty or self._page_table_dev is None:
            self._page_table_dev = jnp.asarray(self._page_table)
            self._page_table_dirty = False
        return self._page_table_dev

    def queue_depth(self) -> int:
        """Aggregate admission-queue depth ACROSS every per-tenant
        queue — what deadline admission, the supervisor's ``/readyz``
        shed threshold, and the queue-depth gauge all read, so the
        traffic plane cannot hide queued work from any of them."""
        with self._qlock:
            depth = self.tenants.depth()
        with self._adopt_lock:
            # pending adoptions are queued work too: they hold a KV
            # payload and a waiting client, they just haven't paged in
            return depth + len(self._adopt)

    def estimated_queue_delay(self, tenant: Optional[str] = None
                              ) -> float:
        """Admission-control estimate: how long freshly queued work
        will wait, from queue depth and the measured iteration time.
        0.0 until the first decode iteration lands (optimism at cold
        start beats shedding the warmup request).

        With a ``tenant``, the estimate is WFQ-aware: the tenant waits
        behind its OWN queue at its share of the admission bandwidth
        (worst case ~1/n_busy of ``max_admit_per_step`` per pass) —
        NOT behind the aggregate FIFO backlog.  Without this, a batch
        tenant's deep queue would shed another tenant's deadline-
        bearing interactive request at the door, defeating exactly the
        isolation the traffic plane provides.  For the no-config
        single-tenant engine both forms are identical.  The aggregate
        form (no tenant) still feeds the supervisor's readiness
        threshold."""
        if self.iter_s is None:
            return 0.0
        if tenant is None:
            return (self.queue_depth() / self.ecfg.max_admit_per_step
                    ) * self.iter_s
        with self._qlock:
            own = self.tenants.state(tenant).queued()
            busy = self.tenants.busy_count()
        return (own * max(busy, 1)
                / self.ecfg.max_admit_per_step) * self.iter_s

    def submit(self, prompt_ids: Sequence[int], *, max_new_tokens: int = 64,
               temperature: float = 0.0, top_k: int = 0, top_p: float = 1.0,
               seed: int = 0, deadline: Optional[float] = None,
               request_id: Optional[str] = None,
               tenant: Optional[str] = None, api_key: Optional[str] = None,
               lane: Optional[str] = None) -> GenRequest:
        if not prompt_ids:
            raise ValueError("prompt must be non-empty")
        if max_new_tokens < 1:
            raise ValueError("max_new_tokens must be >= 1")
        if len(prompt_ids) + max_new_tokens > self.ecfg.max_len:
            raise ValueError(
                f"prompt ({len(prompt_ids)}) + max_new_tokens "
                f"({max_new_tokens}) exceeds the pool max_len "
                f"({self.ecfg.max_len})")
        if self.paged:
            needed = paged_kv.pages_needed(len(prompt_ids), max_new_tokens,
                                           self.ecfg.page_size)
            cap = self._num_pages - 1
            if needed > cap:
                # can never be satisfied, even by a drained arena: a
                # config error, not transient backpressure
                raise ValueError(
                    f"prompt + max_new_tokens needs {needed} KV pages; "
                    f"the arena has {cap} (raise num_pages)")
        if (self.cfg.pos_emb == "learned"
                and len(prompt_ids) + max_new_tokens > self.cfg.max_seq_len):
            # same guard as generate(): wpe gathers clamp silently beyond
            # the table, so reject instead of degrading completions
            raise ValueError(
                f"prompt ({len(prompt_ids)}) + max_new_tokens "
                f"({max_new_tokens}) exceeds max_seq_len "
                f"({self.cfg.max_seq_len}) for learned positions")
        if self._stop.is_set() or not self.alive:
            raise RetryableError("engine stopped")
        # Traffic-plane admission, BEFORE the shared queue: identity,
        # then the tenant's own token buckets.  The fault site runs on
        # THIS (HTTP) thread only — the scheduler pass never routes
        # through it, so an injected raise/hang is contained to the
        # submitting request (chaos-locked by tests/test_tenancy_chaos)
        spec = self.tenants.resolve(tenant, api_key)
        if lane is not None and lane not in LANES:
            raise ValueError(f"lane must be one of {LANES}")
        if lane == "interactive" and spec.lane != "interactive":
            # the interactive lane is preemption PRIORITY and
            # batch-lane work is what gets preempted: a self-declared
            # upgrade would both jump the QoS queue and make the
            # caller's long generations unevictable — lane upgrades
            # are a config decision, not a payload field
            raise ValueError(
                f"tenant {spec.name!r} may not upgrade to the "
                f"interactive lane per-request (configure its lane)")
        req_lane = lane or spec.lane
        faults.fire("tenancy.admit")
        self.tenants.admit_check(spec, len(prompt_ids))
        # from here on a shed bought the tenant nothing: refund the
        # bucket charge so backpressure cannot double-penalize a
        # tenant below its contracted rate
        if deadline is not None:
            now = time.monotonic()
            if deadline <= now:
                self.tenants.refund(spec, len(prompt_ids))
                self._shed(request_id, "deadline_admission", spec.name)
                raise DeadlineExceededError(
                    "deadline expired before admission")
            est = self.estimated_queue_delay(spec.name)
            if now + est > deadline:
                # shedding at the door beats burning a slot on an
                # answer nobody is waiting for
                self.tenants.refund(spec, len(prompt_ids))
                self._shed(request_id, "deadline_admission", spec.name)
                raise DeadlineExceededError(
                    f"queue delay ~{est:.3f}s implies a deadline miss")
        if faults.fire("queue") == "drop":
            self.tenants.refund(spec, len(prompt_ids))
            self._shed(request_id, "queue_full", spec.name)
            raise QueueFullError("request queue full (injected)")
        req = GenRequest(prompt_ids, max_new_tokens=max_new_tokens,
                         temperature=temperature, top_k=top_k, top_p=top_p,
                         seed=seed, deadline=deadline,
                         request_id=request_id, tenant=spec.name,
                         lane=req_lane)
        req.engine = self
        with self._qlock:
            # the bounded queue is enforced PER TENANT (weight share
            # of max_queue_size) with the aggregate bound as the
            # memory backstop: one tenant's flood fills only its own
            # slice, never its neighbours' admission
            full = (self.tenants.state(spec.name).queued()
                    >= self.tenants.queue_share(
                        spec, self.ecfg.max_queue_size)
                    or self.tenants.depth() >= self.ecfg.max_queue_size)
            if not full:
                self.tenants.append(req)
                # trace INSIDE the lock: the scheduler pops under the
                # same lock, so "admitted" can never outrun this
                # record (span order queued → admitted is a
                # documented invariant)
                trace(request_id, "queued", model=self.name,
                      prompt_tokens=len(req.prompt_ids),
                      tenant=spec.name, lane=req_lane)
        if full:
            # refund outside the queue lock (the bucket has its own)
            self.tenants.refund(spec, len(prompt_ids))
            self._shed(request_id, "queue_full", spec.name)
            raise QueueFullError("request queue full")
        if self._stop.is_set():
            # lost the race with stop(): the scheduler may already have
            # run its final queue drain, so fail the stragglers here —
            # every request must get its error + stream close exactly
            # once (the queue hands each to one drainer)
            self._fail_queued(RetryableError("engine stopped"))
        self._work.set()
        return req

    def requeue(self, req: GenRequest) -> None:
        """Re-admit a request a previous engine was abandoned with
        (supervisor transplant).  Bypasses the queue bound — the request
        already won admission once."""
        req.engine = self
        req.claimed = False
        req.admitted_at = None  # queue-wait restarts on the new engine
        # pinned pages (a preempted request's prefill-free resume
        # claim) belonged to the ABANDONED engine's arena — the
        # replacement re-prefills its context instead
        req.pinned_pages = None
        req.prefill_pos = 0
        trace(req.request_id, "requeued", model=self.name)
        with self._qlock:
            self.tenants.append(req)
        self._work.set()

    @staticmethod
    def _rid_matches(req: GenRequest, request_id: str) -> bool:
        """True when ``req`` belongs to the HTTP-level ``request_id``:
        exact match, or the per-prompt suffixed form a multi-instance
        predict submits (``rid-0``, ``rid-1``, …)."""
        rid = req.request_id
        return rid is not None and (
            rid == request_id or rid.startswith(request_id + "-"))

    def request_phase(self, request_id: Optional[str]) -> Optional[str]:
        """Where an HTTP-level request currently is on THIS engine:
        ``"active"`` (at least one of its prompts holds a slot),
        ``"queued"`` (known, but no slot yet), or ``None`` (unknown —
        finished, never submitted, or already transplanted).  The
        fleet router's hedging gate: a request still queued-not-
        admitted may be duplicated on another replica; one that
        started decoding may not (its tokens are already being paid
        for)."""
        if not request_id:
            return None
        for req in list(self._slots):
            if req is not None and self._rid_matches(req, request_id):
                return "active"
        for req in self._admitting:
            if self._rid_matches(req, request_id):
                return "active"
        with self._qlock:
            for req in self.tenants.iter_queued():
                if self._rid_matches(req, request_id):
                    return "queued"
        with self._adopt_lock:
            for req, _ in self._adopt:
                if self._rid_matches(req, request_id):
                    return "queued"
        return None

    def cancel_request(self, request_id: Optional[str]) -> bool:
        """Cancel every in-flight prompt of an HTTP-level request by id
        (the fleet router's hedge-loser path; also served as ``POST
        /v1/models/<name>:cancel``).  Rides the existing ``cancel()``
        machinery — the scheduler reaps marked requests at its next
        pass, out of the queue or out of their slots.  Returns True if
        anything matched."""
        if not request_id:
            return False
        hit = False
        for req in list(self._slots):
            if req is not None and self._rid_matches(req, request_id):
                req.cancel()
                hit = True
        for req in self._admitting:
            # mid-admission (queue popped, slot not yet assigned — the
            # whole prefill window): request_phase already calls this
            # "active", so cancel must reach it too or a hedge loser
            # caught here decodes its full generation into the void
            if self._rid_matches(req, request_id):
                req.cancel()
                hit = True
        with self._qlock:
            for req in self.tenants.iter_queued():
                if self._rid_matches(req, request_id):
                    req.cancel()
                    hit = True
        with self._adopt_lock:
            for req, _ in self._adopt:
                if self._rid_matches(req, request_id):
                    req.cancel()
                    hit = True
        if hit:
            self._work.set()
        return hit

    def extract_queued(self) -> list[GenRequest]:
        """Pop every never-claimed queued request, WITHOUT failing it —
        the zero-drop rolling-restart transplant (the router re-admits
        each into another replica via ``requeue()`` before this engine
        drains).  Pinned-page claims die with this engine's arena, so
        they are dropped here exactly like a supervisor transplant;
        the receiving engine resumes via re-prefill, token-identity
        intact."""
        with self._qlock:
            queued = [r for r in self.tenants.drain() if not r.cancelled]
        with self._adopt_lock:
            adopts, self._adopt = self._adopt, []
        queued.extend(r for r, _ in adopts if not r.cancelled)
        for req in queued:
            req.pinned_pages = None  # old arena; see requeue()
            req.prefill_pos = 0
            req.claimed = False
        return queued

    def abandon(self, err: Exception) -> list[GenRequest]:
        """Supervisor restart path: give up on this engine NOW, without
        joining its (possibly wedged) scheduler thread.  Active requests
        fail with the retryable ``err``; queued, never-claimed requests
        are returned for re-admission into the replacement.  If the old
        thread ever wakes it sees ``_abandoned`` and exits without
        touching the queue again."""
        self._abandoned = True
        self._stop.set()
        self._work.set()
        with self._qlock:
            queued = [r for r in self.tenants.drain() if not r.cancelled]
        with self._adopt_lock:
            adopts, self._adopt = self._adopt, []
        queued.extend(r for r, _ in adopts if not r.cancelled)
        for req in queued:
            # pinned claims (and pending adoption payloads) belonged
            # to THIS engine's arena; the replacement re-prefills
            req.pinned_pages = None
            req.prefill_pos = 0
        self._fail_active(err)
        return queued

    # -- debug plane (GET /debug/*) ----------------------------------------
    # Read-only snapshots taken from HTTP threads while the scheduler
    # runs.  Everything here reads Python-atomic references (or retries
    # the rare mid-mutation dict copy); the scheduler is never paused —
    # the debug plane observes the data plane, it must not wedge it.

    def debug_meta(self) -> dict:
        """Config + analytical constants the timeline analyzer needs."""
        meta = {"slots": self.ecfg.slots, "max_len": self.ecfg.max_len,
                "paged": self.paged, "alive": self.alive,
                "role": self.role, "mesh_shards": self.mesh_shards,
                "flops_base": self._flops_base,
                "flops_per_ctx": self._flops_per_ctx,
                "peak_flops_per_s": self._peak_flops,
                "iter_s_ewma": self.iter_s,
                "kv_bytes_per_token": self.kv_bytes_per_token,
                "flight_records": self.ecfg.flight_records}
        if self.weights_version is not None:
            meta["weights_version"] = self.weights_version
        if self.paged:
            meta["page_size"] = self.ecfg.page_size
            meta["num_pages"] = self._num_pages
            meta["attn_impl"] = self.ecfg.attn_impl
            meta["kv_dtype"] = self.ecfg.kv_dtype
            meta["ragged"] = self._ragged
        if self.ecfg.prefill_chunk_tokens:
            meta["prefill_chunk_tokens"] = self.ecfg.prefill_chunk_tokens
        if self.draft is not None:
            meta["spec_draft"] = self.draft.kind
            meta["spec_k"] = self.ecfg.spec_k
        return meta

    def debug_slots(self) -> list[dict]:
        """Per-slot occupancy: who is decoding, how far along."""
        now = time.monotonic()
        out = []
        for i, req in enumerate(list(self._slots)):
            if req is None:
                out.append({"slot": i, "state": "free"})
                continue
            entry = {"slot": i,
                     "state": ("prefilling" if i in self._chunking
                               else "decoding"),
                     "request_id": req.request_id,
                     "tenant": req.tenant,
                     "lane": req.lane,
                     "prompt_tokens": len(req.prompt_ids),
                     "tokens_out": len(req.tokens),
                     "max_new_tokens": req.max_new_tokens,
                     "cached_tokens": req.cached_tokens,
                     "preemptions": req.preemptions,
                     "age_s": round(now - req.submitted_at, 3)}
            if req.deadline is not None:
                entry["deadline_in_s"] = round(req.deadline - now, 3)
            if i in self._chunking:
                # chunked prefill in flight: how much of the virtual
                # prompt's KV is already resident
                entry["prefill_pos"] = req.prefill_pos
            if self.paged:
                pages = self._slot_pages[i]
                entry["pages"] = len(pages) if pages else 0
                entry["context_len"] = int(self._lengths[i])
            out.append(entry)
        return out

    def debug_tenants(self) -> dict:
        """Per-tenant traffic-plane state (queue depths by lane,
        occupancy vs quota, virtual clocks, lifetime counters) — the
        ``/debug/slots`` companion the fairness bench reads."""
        with self._qlock:
            return self.tenants.snapshot()

    def debug_pages(self) -> Optional[dict]:
        """Page-arena occupancy + prefix-cache contents (hashes with
        refcounts and LRU order — block HASHES, never prompt content);
        ``None`` for the dense slot pool."""
        if not self.paged or self.allocator is None:
            return None
        snap = None
        for _ in range(3):  # dict copies can race a mid-pass mutation
            try:
                snap = self.allocator.snapshot()
                break
            except RuntimeError:
                continue
        if snap is None:
            return {"error": "allocator busy; retry"}
        # fleet probes tell a quantized replica from an fp32 one here
        # (and in /readyz model detail) during rolling restarts
        snap["attn_impl"] = self.ecfg.attn_impl
        snap["kv_bytes_per_token"] = self.kv_bytes_per_token
        if self.last_quant_probe is not None:
            snap["quant_probe"] = dict(self.last_quant_probe)
        live_rows = int(sum(int(n) for n in self._lengths))
        reserved_rows = snap["used_pages"] * self.ecfg.page_size
        snap["live_rows"] = live_rows
        snap["reserved_rows"] = reserved_rows
        # what kct_engine_kv_utilization now reports in paged mode
        snap["utilization"] = round(
            snap["used_pages"] / max(snap["capacity"], 1), 6)
        # internal fragmentation: reserved (worst-case) rows not yet
        # holding live context — the admission-time-reservation cost
        # preemption-based growth (ROADMAP item 2/4 follow-up) removes
        snap["fragmentation"] = (
            round(1.0 - live_rows / reserved_rows, 4)
            if reserved_rows else 0.0)
        return snap

    # -- scheduler ---------------------------------------------------------

    def _loop(self) -> None:
        # A scheduler fault is a CRASH, not something to paper over:
        # fail the in-flight work loudly (retryable 503s) and exit —
        # restart policy (fresh pool, queue transplant, crash-loop
        # circuit breaker) belongs to serve/supervisor.py, not to a loop
        # reusing state that just proved corrupt.  Waiters never hang: a
        # dead engine fails wait()/iter_tokens() within one poll.
        while True:
            if self._abandoned:
                return
            self.heartbeat.beat()
            self._update_gauges()
            stopping = self._stop.is_set()
            if stopping:
                self._fail_queued(RetryableError("engine stopped"),
                                  release_pinned=True)
                self._fail_adoptions(RetryableError("engine stopped"))
            if stopping and not any(s is not None for s in self._slots):
                return
            try:
                self._step(stopping)
            except Exception as e:  # noqa: BLE001
                if self._abandoned or self._stop.is_set():
                    return  # already failed over / shutting down
                log.exception("continuous-batching scheduler crashed")
                self.last_error = e
                self._fail_active(
                    EngineRestartedError(f"engine crashed: {e}; retry"))
                # queued (unclaimed) requests stay queued: a supervisor
                # transplants them to the replacement engine; without
                # one, their waiters see the dead engine within a poll.
                return

    def _update_gauges(self) -> None:
        """Scrape-facing levels, refreshed once per scheduler pass (idle
        polls included, so a drained pool reads 0, not its last value)."""
        used = active = 0
        for req in self._slots:
            if req is not None:
                active += 1
                used += min(len(req.prompt_ids) + len(req.tokens),
                            self.ecfg.max_len)
        self._m_active.set(active)
        self._m_queue.set(self.queue_depth())
        self.tenants.refresh_gauges()
        if self._peak_reset.is_set():
            self._peak_reset.clear()
            self.stats["peak_active"] = active
        else:
            self.stats["peak_active"] = max(self.stats["peak_active"],
                                            active)
        if self.paged and self.allocator is not None:
            alloc = self.allocator
            # TRUE page-arena utilization: pages reserved by live
            # requests (or pinned by the cache at refcount > 0) over
            # allocatable pages (null page excluded) — what
            # /debug/pages shows and what capacity planning needs.
            # The old live-token-rows ratio understated pressure: a
            # full arena of worst-case reservations read as nearly
            # empty right after admission.
            self._m_kv_util.set(alloc.used_pages()
                                / max(alloc.capacity, 1))
            self._m_kv_pages.set(alloc.capacity)
            self._m_kv_pages_free.set(alloc.free_pages())
        else:
            self._m_kv_util.set(
                used / (self.ecfg.slots * self.ecfg.max_len))
        if self.flight is not None:
            now = time.monotonic()
            if now - self._rates_at >= 0.5:  # gate: rates() scans the
                self._rates_at = now         # ring, not per-pass work
                rates = self.flight.rates()
                self._m_goodput.set(rates["tokens_per_s"])
                self._m_mfu.set(obs_flops.mfu(rates["flops_per_s"],
                                              self._peak_flops))

    def _shed(self, request_id: Optional[str], reason: str,
              tenant: Optional[str] = None) -> None:
        _M_SHED.labels(model=self.name, reason=reason).inc()
        if tenant is not None:
            self.tenants.count_shed(
                tenant, "queue_full" if reason == "queue_full"
                else "deadline")
        trace(request_id, "shed", model=self.name, reason=reason)

    def _step(self, stopping: bool) -> None:
        faults.fire("iteration")
        fr = self.flight
        rec = self._rec = fr.begin() if fr is not None else None
        t_pass = time.perf_counter()
        if rec is not None:
            rec.queue_depth = self.queue_depth()
        self._reap_cancelled()
        ch = self.ecfg.prefill_chunk_tokens
        self._budget_left = ch if ch else None
        # ragged mode: every builder below appends segments to this
        # pass instead of dispatching its own padded program; ONE
        # flush at the end of the pass runs the whole hybrid batch
        self._pass = (_RaggedPass(self.ecfg.slots)
                      if self._ragged else None)
        admitted = 0
        # mid-prefill slots advance EVERY pass, drain included: their
        # pending chunks are in-flight work exactly like active slots
        chunked = self._continue_chunks()
        if not stopping:
            if self.paged:
                # disaggregation intake first: adopted requests join
                # the queue with their KV already installed, so this
                # pass's admission can place them (zero re-prefill)
                self._process_adoptions()
            t_admit = time.perf_counter()
            pre = {p: (rec.phases.get(p, 0.0) if rec is not None
                       else 0.0)
                   for p in ("prefill", "cow_copy", "sample", "stream")}
            admitted = self._admit()
            if rec is not None:
                # pure scheduler bookkeeping: the admit wall minus the
                # device/emit phases _admit_* accounted INSIDE this
                # window (chunk continuation already billed its own)
                overhead = (time.perf_counter() - t_admit
                            - sum(rec.phases.get(p, 0.0) - pre[p]
                                  for p in pre))
                if overhead > 0:
                    rec.phases["admit"] = overhead
        if rec is not None:
            rec.prefilling = len(self._chunking)
        partial = bool(self._chunking)
        # a slot admitted THIS pass under ragged dispatch has no
        # emitted token yet (its first sample waits on the flush), so
        # it cannot feed a decode segment — it joins next pass, same
        # (context, feed) sequence one pass later.  Padded admission
        # emits eagerly, so the guard never bites there.
        active = [i for i, s in enumerate(self._slots)
                  if s is not None and i not in self._chunking
                  and (s.tokens or self._pass is None)]
        if not active:
            # prefill/chunk-only pass: the built segments (if any)
            # still need their one dispatch before the continuations
            # can emit first tokens / finish chunking
            self._flush_ragged()
            if admitted or chunked:
                (self._m_iter_chunked if partial or chunked
                 else self._m_iter_prefill
                 ).observe(time.perf_counter() - t_pass)
            self._commit_rec(t_pass)
            if not stopping:
                self._work.clear()
                if not self.tenants.depth() and not self._chunking:
                    self._work.wait(self.ecfg.idle_wait_s)
            return
        if self.draft is not None:
            # every slot speculates: greedy slots verify by exact
            # match, stochastic slots by rejection sampling against
            # the verification distribution (distribution-exact)
            self._spec_round(active)
        else:
            self._decode_round(active)
        self._flush_ragged()
        (((self._m_iter_chunked if partial or chunked
           else self._m_iter_prefill) if (admitted or chunked)
          else self._m_iter_decode)
         ).observe(time.perf_counter() - t_pass)
        self._commit_rec(t_pass)

    def _count_dispatch(self, kind: str, padded: int) -> None:
        """Dispatch/padding accounting: one device program launched,
        ``padded`` of whose token rows carried no real work (bucket
        padding, frozen slots, masked draft lanes, ladder rounding).
        The ragged A/B bench lane reads both deltas from here."""
        self._m_dispatch[kind].inc()
        self.stats["dispatches"] += 1
        if padded > 0:
            self._m_padded.inc(padded)
            self.stats["padded_tokens"] += padded

    def _flush_ragged(self) -> None:
        """THE engine iteration under ragged dispatch: run the pass's
        flat hybrid batch — every chunk-prefill, admission-prefill,
        decode, and spec-verify segment the builders appended, plus
        the COW page copies — as ONE device program, then replay the
        deferred host continuations in build order (exactly the padded
        engine's emission order).

        The flat length rides a pow-2 geometry ladder (floor 8) so the
        executable cache stays bounded: a pass with 37 real tokens and
        5 read rows runs the (64, 8) bucket, not a fresh compile per
        shape.  Padding rows are masked (``valid=False`` routes their
        KV writes to the null page) and read row 0 harmlessly.  The
        page table ships as ``[2*slots, P]``: rows < slots mirror
        ``_page_table``, rows >= slots are the pass's private override
        rows (mid-chunk prefill writes into reservation pages the
        slot's global row deliberately doesn't hold yet)."""
        ps, self._pass = self._pass, None
        if ps is None or not ps.tokens:
            return
        rec = self._rec
        n_real = len(ps.tokens)
        m_real = len(ps.out_rows)
        c_real = len(ps.copy_src)
        n_b = _pow2_bucket(n_real, 8)
        m_b = _pow2_bucket(max(m_real, 1), 8)
        # COW pairs round to 8; zero stays zero (the common no-COW
        # pass must not drag a copy prologue into its executable)
        c_b = (-(-c_real // 8) * 8) if c_real else 0
        tokens = np.full((n_b,), self.pad, np.int32)
        tokens[:n_real] = ps.tokens
        seg = np.zeros((n_b,), np.int32)
        seg[:n_real] = ps.seg_slot
        pos = np.zeros((n_b,), np.int32)
        pos[:n_real] = ps.positions
        mask = np.zeros((n_b,), np.int32)
        mask[:n_real] = 1
        out_rows = np.zeros((m_b,), np.int32)
        out_rows[:m_real] = ps.out_rows
        # padded copy pairs are (0, 0): a null-page self-copy
        csrc = np.zeros((c_b,), np.int32)
        cdst = np.zeros((c_b,), np.int32)
        csrc[:c_real] = ps.copy_src
        cdst[:c_real] = ps.copy_dst
        slots = self.ecfg.slots
        table = np.zeros((2 * slots, self.ecfg.pages_per_slot),
                         np.int32)
        table[:slots] = self._page_table
        for i, pages in enumerate(ps.override_rows):
            table[slots + i, :len(pages)] = pages
        shape_key = ("ragged", n_b, m_b, c_b)
        cold = self._prefill_cold_guard(shape_key)
        if "verify" in ps.kinds:
            faults.fire("spec.verify")
        if "decode" in ps.kinds or "verify" in ps.kinds:
            faults.fire("decode_step")
        faults.fire("model_fn")
        t0 = time.perf_counter()
        logits, self.pool = self._ragged_pages(
            self.cfg, self.params, jnp.asarray(tokens),
            jnp.asarray(seg), jnp.asarray(pos), jnp.asarray(mask),
            self.pool, jnp.asarray(table), jnp.asarray(out_rows),
            jnp.asarray(csrc), jnp.asarray(cdst),
            impl=self.ecfg.attn_impl)
        logits.block_until_ready()
        if cold:
            self._warm_shapes.add(shape_key)
        t1 = time.perf_counter()
        logits = np.asarray(logits)
        t2 = time.perf_counter()
        self._count_dispatch("ragged", n_b - n_real)
        if c_real:
            self.stats["cow_copies"] += c_real
            self._m_cow.inc(c_real)
        if "decode" in ps.kinds or "verify" in ps.kinds:
            dt = t2 - t0
            self.iter_s = dt if self.iter_s is None else (
                0.9 * self.iter_s + 0.1 * dt)
            self.stats["iterations"] += 1
            self.stats["active_slot_steps"] += ps.step_slots
            self._m_iters.inc()
            if "verify" in ps.kinds:
                self.stats["spec_rounds"] += 1
        if rec is not None:
            rec.phases["ragged"] = rec.phases.get("ragged", 0.0) \
                + (t1 - t0)
            rec.phases["host_sync"] = rec.phases.get("host_sync", 0.0) \
                + (t2 - t1)
        for fin in ps.continuations:
            fin(logits)

    def _decode_round(self, active: list[int]) -> None:
        """The classic per-token step: ONE decode dispatch for every
        decode-ready slot.  Ragged mode builds one-token segments into
        the pass instead (zero padding: the flat batch holds exactly
        ``len(active)`` rows before the ladder rounds up)."""
        rec = self._rec
        tokens = np.full((self.ecfg.slots,), self.pad, np.int32)
        mask = np.zeros((self.ecfg.slots,), bool)
        ctx_sum = 0  # analytical-FLOPs accounting (each new token
        # attends its whole context, itself included)
        for i in active:
            req = self._slots[i]
            tokens[i] = req.tokens[-1]
            mask[i] = True
            ctx_sum += min(len(req.prompt_ids) + len(req.tokens) + 1,
                           self.ecfg.max_len)
        if self._pass is not None:
            rows = {}
            for i in active:
                idx = self._pass.add_segment(
                    i, [int(tokens[i])], int(self._lengths[i]),
                    kind="decode", out="all")
                rows[i] = idx[0]
                self._lengths[i] += 1
            self._pass.step_slots += len(active)
            if rec is not None:
                rec.active = len(active)
                rec.decode_tokens = len(active)
                rec.flops += (len(active) * self._flops_base
                              + self._flops_per_ctx * ctx_sum)

            def _fin(logits, order=list(active), rows=rows):
                for i in order:
                    if self._slots[i] is not None:
                        self._emit(i, logits[rows[i]])

            self._pass.continuations.append(_fin)
            return
        faults.fire("decode_step")
        faults.fire("model_fn")
        t0 = time.perf_counter()
        if self.paged:
            logits, self.pool = self._decode_pages(
                self.cfg, self.params, jnp.asarray(tokens), self.pool,
                self._device_page_table(), jnp.asarray(self._lengths),
                impl=self.ecfg.attn_impl)
            # each active slot's token just landed at position
            # lengths[i]; the next iteration (and its page lookup)
            # sees the advanced context
            for i in active:
                self._lengths[i] += 1
        else:
            logits, self.pool = self._decode(self.cfg, self.params,
                                             jnp.asarray(tokens), self.pool,
                                             jnp.asarray(mask))
        self._count_dispatch("decode", self.ecfg.slots - len(active))
        # decode = dispatch + device compute; host_sync = the
        # device→host logits copy (the split the flight recorder
        # reports; the explicit block costs nothing — asarray would
        # have blocked on the same computation)
        logits.block_until_ready()
        t1 = time.perf_counter()
        logits = np.asarray(logits)
        t2 = time.perf_counter()
        dt = t2 - t0
        self.iter_s = dt if self.iter_s is None else (
            0.9 * self.iter_s + 0.1 * dt)
        self.stats["iterations"] += 1
        self.stats["active_slot_steps"] += len(active)
        self._m_iters.inc()
        if rec is not None:
            ph = self._decode_phase  # "fused_decode" under the fused kernel
            rec.phases[ph] = rec.phases.get(ph, 0.0) + (t1 - t0)
            rec.phases["host_sync"] = rec.phases.get("host_sync", 0.0) \
                + (t2 - t1)
            rec.active = len(active)
            rec.decode_tokens = len(active)
            rec.flops += (len(active) * self._flops_base
                          + self._flops_per_ctx * ctx_sum)
        for i in active:
            self._emit(i, logits[i])

    def _spec_round(self, active: list[int]) -> None:
        """One speculative pass (serve/spec_decode.py): the draft
        source proposes up to ``spec_k`` tokens per active slot, and
        ONE batched target dispatch scores every slot's pending token
        plus its drafts at their true positions through the paged
        arena.  Greedy (temperature 0) slots emit the longest prefix
        where the target's own argmax equals the draft (plus the one
        bonus token the target computed anyway) — bitwise the sequence
        non-speculative decode would emit.  Stochastic slots emit via
        rejection sampling against the verification rows' filtered
        distributions (``_emit_rejection``) — distribution-exact, so
        temperature > 0 requests finally speculate too.  Either way
        rejected-draft KV rolls back by simply not advancing host-side
        lengths past the accepted context: pages are append-only per
        slot, so the next real write at each position overwrites the
        dead rows.  Ragged mode builds the verification as per-slot
        segments of the pass's flat batch instead of a padded
        ``[slots, k+1]`` dispatch."""
        rec = self._rec
        k = self.ecfg.spec_k
        # cold-compile window: the first round compiles the verify
        # program (and a ModelDraft's prefill/decode — a new slot can
        # also hit a fresh draft-prefill bucket later), none of which
        # start() warms; without the grace the watchdog reads the
        # compile as a wedged device and restarts a healthy engine
        cold = not self._spec_warm or (
            getattr(self.draft, "compiles_on_slot_ready", False)
            and any(i not in self._spec_ready for i in active))
        if cold:
            self.grace_until = max(
                self.grace_until,
                time.monotonic() + self.ecfg.compile_grace_s)
        t0 = time.perf_counter()
        for i in active:
            if i not in self._spec_ready:
                req = self._slots[i]
                self.draft.slot_ready(i, req.prompt_ids + req.tokens)
                self._spec_ready.add(i)
        want = {i: self._slots[i].prompt_ids + self._slots[i].tokens
                for i in active}
        props = self.draft.propose(want, k)
        t1 = time.perf_counter()
        dsteps = getattr(self.draft, "last_steps", 0)
        if not any(props.values()):
            # nothing drafted this round: the (k+1)-wide verify
            # dispatch would price each slot's one guaranteed token at
            # multi-query cost — take the plain decode step (the
            # configured kernel) instead.  observe() keeps per-slot
            # draft state rolled to the settled context exactly as a
            # verified round would.
            if rec is not None and t1 - t0 > 0:
                rec.phases["draft"] = rec.phases.get("draft", 0.0) \
                    + (t1 - t0)
            if cold:
                self.grace_until = 0.0  # no verify compile happened
            self._decode_round(active)
            if self._pass is not None:
                # the context roll must see the token the deferred
                # decode continuation emits — observe after the flush
                def _observe(_logits, order=list(active)):
                    for i in order:
                        if (i in self._spec_ready
                                and self._slots[i] is not None):
                            req = self._slots[i]
                            self.draft.observe(
                                i, req.prompt_ids + req.tokens)

                self._pass.continuations.append(_observe)
                return
            for i in active:
                if i in self._spec_ready and self._slots[i] is not None:
                    req = self._slots[i]
                    self.draft.observe(i, req.prompt_ids + req.tokens)
            return
        l0 = self._lengths.copy()
        drafts = {i: list((props.get(i) or [])[:k]) for i in active}
        ctx_flops = 0.0
        for i in active:
            ctx_flops += obs_flops.span_flops(
                self._flops_base, self._flops_per_ctx, int(l0[i]),
                1 + len(drafts[i]))
        if self._pass is not None:
            rows = {}
            for i in active:
                req = self._slots[i]
                rows[i] = self._pass.add_segment(
                    i, [req.tokens[-1]] + drafts[i], int(l0[i]),
                    kind="verify", out="all")
            self._pass.step_slots += len(active)
            if rec is not None:
                if t1 - t0 > 0:
                    rec.phases["draft"] = rec.phases.get("draft", 0.0) \
                        + (t1 - t0)
                rec.active = len(active)
                rec.flops += ctx_flops
                db, dp = self._draft_flops
                if dsteps and db:
                    avg_ctx = (sum(int(l0[i]) for i in active)
                               / len(active))
                    rec.flops += dsteps * len(active) * (db
                                                         + dp * avg_ctx)

            def _fin(logits, order=list(active), rows=rows,
                     drafts=drafts, l0=l0):
                self._spec_emit(order, l0, drafts,
                                lambda i, j: logits[rows[i][j]])

            self._pass.continuations.append(_fin)
            if cold:
                # the flat-batch program's compile is the flush's
                # ladder guard's to cover; the draft's own compiles
                # (propose above) already returned
                self._spec_warm = True
            return
        width = k + 1
        tokens = np.full((self.ecfg.slots, width), self.pad, np.int32)
        mask = np.zeros((self.ecfg.slots, width), np.int32)
        for i in active:
            req = self._slots[i]
            tokens[i, 0] = req.tokens[-1]
            mask[i, 0] = 1
            d = drafts[i]
            if d:
                tokens[i, 1:1 + len(d)] = d
                mask[i, 1:1 + len(d)] = 1
        faults.fire("spec.verify")
        faults.fire("decode_step")
        faults.fire("model_fn")
        t2 = time.perf_counter()
        logits, self.pool = self._verify_pages(
            self.cfg, self.params, jnp.asarray(tokens),
            jnp.asarray(mask), self.pool, self._device_page_table(),
            jnp.asarray(self._lengths))
        logits.block_until_ready()
        self._count_dispatch(
            "verify", self.ecfg.slots * width - int(mask.sum()))
        if cold:
            self._spec_warm = True
            self.grace_until = 0.0  # compiled; wedges detect normally
        t3 = time.perf_counter()
        logits = np.asarray(logits)
        t4 = time.perf_counter()
        dt = t4 - t2
        self.iter_s = dt if self.iter_s is None else (
            0.9 * self.iter_s + 0.1 * dt)
        self.stats["iterations"] += 1
        self.stats["spec_rounds"] += 1
        self.stats["active_slot_steps"] += len(active)
        self._m_iters.inc()
        self._spec_emit(active, l0, drafts, lambda i, j: logits[i, j])
        if rec is not None:
            ph = rec.phases
            if t1 - t0 > 0:
                ph["draft"] = ph.get("draft", 0.0) + (t1 - t0)
            ph["verify"] = ph.get("verify", 0.0) + (t3 - t2)
            ph["host_sync"] = ph.get("host_sync", 0.0) + (t4 - t3)
            rec.active = len(active)
            rec.flops += ctx_flops
            db, dp = self._draft_flops
            if dsteps and db and active:
                # draft dispatches run at roughly the round's contexts
                avg_ctx = sum(int(l0[i]) for i in active) / len(active)
                rec.flops += dsteps * len(active) * (db + dp * avg_ctx)

    def _spec_emit(self, order: list[int], l0: np.ndarray,
                   drafts: dict, get_row) -> None:
        """Shared verification emit (padded and ragged feed it their
        own ``get_row``): walk each slot's verification rows, emit the
        accepted prefix plus one extra token — greedy by exact match,
        stochastic by rejection sampling — then roll host-side lengths
        to the accepted context."""
        rec = self._rec
        emitted_total = 0
        drafted_total = accepted_total = 0
        for i in order:
            req = self._slots[i]
            if req is None:
                continue
            d = drafts.get(i) or []
            drafted = len(d)
            if req.temperature == 0.0:
                m = 0
                for j in range(drafted + 1):
                    self._emit(i, get_row(i, j))
                    m += 1
                    if self._slots[i] is None:
                        break  # EOS / max-tokens: _finish_slot reset
                    if j >= drafted:
                        break  # no more drafts to confirm
                    if req.tokens[-1] != int(d[j]):
                        break  # target disagreed: later drafts are dead
            else:
                m = self._emit_rejection(i, d, get_row)
            emitted_total += m
            if self._slots[i] is not None:
                # the rollback IS this assignment: positions beyond
                # the accepted context hold rejected-draft KV that the
                # next real write at each position overwrites
                self._lengths[i] = int(l0[i]) + m
                if i in self._spec_ready:
                    self.draft.observe(i, req.prompt_ids + req.tokens)
            if drafted:
                drafted_total += drafted
                accepted_total += m - 1
        self.stats["spec_drafted"] += drafted_total
        self.stats["spec_accepted"] += accepted_total
        if drafted_total:
            self._m_spec_accepted.inc(accepted_total)
            self._m_spec_rejected.inc(drafted_total - accepted_total)
        if self.stats["spec_drafted"]:
            self._m_spec_accept.set(self.stats["spec_accepted"]
                                    / self.stats["spec_drafted"])
        if rec is not None:
            rec.decode_tokens = emitted_total
            rec.spec_drafted = drafted_total
            rec.spec_accepted = accepted_total

    def _emit_rejection(self, i: int, d: list[int], get_row) -> int:
        """Stochastic speculative emit for one slot: delta-proposal
        rejection sampling (Leviathan et al., PAPERS.md).  The draft
        proposes point masses, so the generic accept probability
        min(1, p/q) reduces to p(draft) under the verification row's
        filtered distribution; a rejection samples the residual — p
        with the draft token zeroed, renormalized — and the emitted
        marginal is exactly p, the distribution the non-speculative
        path samples from.  Returns tokens emitted."""
        req = self._slots[i]
        m = 0
        for j in range(len(d) + 1):
            row = get_row(i, j)
            if j < len(d):
                p = _filtered_probs(row, temperature=req.temperature,
                                    top_k=req.top_k, top_p=req.top_p)
                t = int(d[j])
                if float(req.rng.random()) < float(p[t]):
                    self._emit(i, row, token=t)
                    m += 1
                    if self._slots[i] is None:
                        break
                    continue
                residual = p.copy()
                residual[t] = 0.0
                s = float(residual.sum())
                # s == 0 means p was (numerically) a point mass on the
                # draft token itself — acceptance was then certain, so
                # this is pure paranoia against float underflow
                tok = (int(req.rng.choice(residual.shape[-1],
                                          p=residual / s))
                       if s > 0 else t)
                self._emit(i, row, token=tok)
                m += 1
                break
            # every draft accepted: the bonus token samples the last
            # row's distribution through the ordinary path
            self._emit(i, row)
            m += 1
            break
        return m

    def _commit_rec(self, t_pass: float) -> None:
        """Publish the pass's flight record (if it did any work) and
        feed the per-phase counters; idle polls stay off the ring."""
        rec, self._rec = self._rec, None
        if rec is None:
            return
        if not (rec.active or rec.admitted or rec.evicted
                or rec.decode_tokens or rec.phases.get("kv_transfer")):
            return
        rec.dur_s = time.perf_counter() - t_pass
        for phase, secs in rec.phases.items():
            self._m_phase[phase].inc(secs)
        self.flight.commit(rec)

    def _reap_cancelled(self) -> None:
        for i, req in enumerate(self._slots):
            if req is not None and req.cancelled:
                self.stats["cancelled"] += 1
                self._m_cancelled.inc()
                self._finish_slot(i, error=RequestCancelled(
                    "request cancelled"))
        # Purge cancelled requests from anywhere in ANY tenant queue,
        # even with zero free slots — a dead request must not hold
        # bounded queue capacity (503ing live clients) while long
        # generations run.
        with self._qlock:
            dead = self.tenants.purge(lambda r: r.cancelled)
        for req in dead:
            self._release_pinned(req)
            self.stats["cancelled"] += 1
            self._m_cancelled.inc()
            trace(req.request_id, "cancelled", model=self.name)
            req.error = RequestCancelled("request cancelled")
            req.stream.put(_STREAM_END)
            req.event.set()

    def _reclaim_pinned(self) -> bool:
        """Release ONE queued preempted request's pinned page claim
        (it re-prefills at resume) so an admission blocked on a full
        arena can proceed; False when nothing is pinned.  Scheduler-
        thread only."""
        with self._qlock:
            req = self.tenants.find_pinned()
            if req is None:
                return False
            pages, req.pinned_pages = req.pinned_pages, None
            req.prefill_pos = 0
            self.tenants.note_pages(req.tenant, -len(pages))
        self.allocator.release(pages)
        return True

    def _release_pinned(self, req: GenRequest) -> None:
        """Free a preempted request's pinned KV pages when it leaves
        the queue for good (cancel / deadline shed / stop).  Scheduler-
        thread only — the allocator is single-owner, like _slots."""
        pages, req.pinned_pages = req.pinned_pages, None
        req.prefill_pos = 0
        if pages and self.allocator is not None:
            self.allocator.release(pages)
            with self._qlock:
                self.tenants.note_pages(req.tenant, -len(pages))

    def _close_out_unadmittable(self, req: GenRequest) -> bool:
        """Close a popped request that must not decode (cancelled or
        deadline-expired while queued); True when it was closed.  The
        WFQ pop charged a provisional slot — give it back."""
        if req.cancelled:  # cancel landed after this step's purge
            with self._qlock:
                self.tenants.note_dequeued(req)
            self._release_pinned(req)
            self.stats["cancelled"] += 1
            self._m_cancelled.inc()
            trace(req.request_id, "cancelled", model=self.name)
            req.error = RequestCancelled("request cancelled")
            req.stream.put(_STREAM_END)
            req.event.set()
            return True
        if (req.deadline is not None
                and time.monotonic() > req.deadline):
            # expired while queued: shed instead of spending prefill
            # + decode on an answer nobody is waiting for — and
            # refund the admission-bucket charge like every other
            # shed (the tenant got no service; cancellation, by
            # contrast, keeps its charge: the client walked away)
            with self._qlock:
                self.tenants.note_dequeued(req)
            self._release_pinned(req)
            self.tenants.refund(self.tenants.state(req.tenant).spec,
                                len(req.prompt_ids))
            self.stats["deadline_shed"] += 1
            self._shed(req.request_id, "deadline_queued", req.tenant)
            req.error = DeadlineExceededError(
                "deadline expired in queue")
            req.stream.put(_STREAM_END)
            req.event.set()
            return True
        return False

    def _unpop_leftover(self, forced: list) -> None:
        """A forced preemptor the admit pass could not place (budget
        exhausted, or paged admission broke on page exhaustion) MUST
        go back to its lane head with its provisional slot charge
        reversed — dropping it would hang its client forever and leak
        the tenant's occupancy accounting."""
        while forced:
            with self._qlock:
                self.tenants.unpop(forced.pop())

    def _next_admittable(self, forced: list) -> Optional[GenRequest]:
        """Next decodable request: preemption-forced pops first, then
        the weighted-fair-queueing drain; cancelled and deadline-
        expired requests are closed out on the way.  None when every
        queue is drained."""
        while True:
            if forced:
                req = forced.pop(0)
            else:
                with self._qlock:
                    req = self.tenants.pop_next()
                if req is None:
                    return None
            if self._close_out_unadmittable(req):
                continue
            return req

    def _prefill_cold_guard(self, shape_key) -> bool:
        cold = shape_key not in self._warm_shapes
        if cold:
            # first compile of this shape: 20-40s of legitimate
            # silence on cold-cache hardware — tell the watchdog
            self.grace_until = (time.monotonic()
                                + self.ecfg.compile_grace_s)
        return cold

    def _spec_free(self, slot: int) -> None:
        """Drop the draft source's state for a slot leaving the decode
        batch (finish / preemption) — the lazy ``_spec_ready`` hook
        rebuilds it if the request ever decodes here again."""
        if slot in self._spec_ready:
            self._spec_ready.discard(slot)
            if self.draft is not None:
                self.draft.free(slot)

    def _continue_chunks(self) -> int:
        """Advance every mid-prefill slot by up to the pass's chunk
        budget, oldest chunk first; returns prompt tokens prefilled.
        Runs before admission so in-flight prefills never starve
        behind fresh arrivals."""
        if not self._chunking:
            return 0
        total = 0
        for slot in list(self._chunking):
            if self._budget_left is not None and self._budget_left <= 0:
                break
            st = self._chunking.get(slot)
            if st is None or st["req"].cancelled:
                continue  # _reap_cancelled owns the eviction
            total += self._advance_chunk(slot, st)
        return total

    def _advance_chunk(self, slot: int, st: dict) -> int:
        """Dispatch the next prefill chunk(s) for a mid-prefill slot,
        within the pass's remaining token budget; completes the slot
        (first token / decode-ready / handoff) when the final chunk
        lands.  Returns prompt tokens prefilled."""
        req = st["req"]
        vprompt = st["vprompt"]
        total = 0
        while True:
            pos = req.prefill_pos
            take = len(vprompt) - pos
            if take <= 0:
                break
            if self._budget_left is not None:
                if self._budget_left <= 0:
                    return total
                take = min(take, self._budget_left)
            chunk = vprompt[pos:pos + take]
            if self._pass is not None:
                final = pos + take >= len(vprompt)
                # a mid-chunk slot's GLOBAL table row is deliberately
                # null (the publication contract: no prefix hits until
                # the whole prompt landed), so the chunk writes route
                # through a private override row of the flush table —
                # which also keeps a preempt-then-readmit slot's two
                # lives on two different rows within one pass
                vrow = self._pass.override(self._slot_pages[slot])
                idx = self._pass.add_segment(
                    vrow, chunk, pos, kind="chunk",
                    out=("last" if final and not st["resumed"]
                         else "none"))
                req.prefill_pos = pos + take
                if self._budget_left is not None:
                    self._budget_left -= take
                total += take
                self.stats["prefill_tokens"] += take
                self.stats["prefill_chunks"] += 1
                self._m_prefill_chunks.inc()
                if st["resumed"]:
                    self.stats["reprefill_tokens"] += take
                rec = self._rec
                if rec is not None:
                    rec.prefill_tokens += take
                    rec.flops += obs_flops.span_flops(
                        self._flops_base, self._flops_per_ctx, pos,
                        take)
                if final:
                    row = idx[0] if idx else None

                    def _fin(logits, slot=slot, st=st, row=row):
                        # guard: a mid-pass preemption already popped
                        # this chunking state (the executed chunk
                        # landed in the request's pinned pages with
                        # prefill_pos advanced — resume continues
                        # past it, nothing to finish here)
                        if self._chunking.get(slot) is st:
                            self._finish_chunking(
                                slot, st,
                                None if row is None
                                else logits[row][None])

                    self._pass.continuations.append(_fin)
                    break
                continue
            # chunk shapes bucket tighter than prompts (floor 4, not
            # 32): at budget 8 a 32-wide bucket would spend 4x the
            # chunk's compute on padding — the budget bounds the
            # compiled-shape set anyway (pow2s up to the budget)
            bucket = 4
            while bucket < take:
                bucket *= 2
            bucket = min(bucket, self.ecfg.max_len)
            ids = np.full((1, bucket), self.pad, np.int32)
            mask = np.zeros((1, bucket), np.int32)
            ids[0, :take] = chunk
            mask[0, :take] = 1
            final = pos + take >= len(vprompt)
            if self.paged:
                pages = self._slot_pages[slot]
                tables = np.zeros((1, self.ecfg.pages_per_slot),
                                  np.int32)
                tables[0, :len(pages)] = pages
                shape_key = ("paged", bucket, 1)
                cold = self._prefill_cold_guard(shape_key)
                faults.fire("model_fn")
                t0 = time.perf_counter()
                logits, self.pool = self._prefill_pages(
                    self.cfg, self.params, jnp.asarray(ids),
                    jnp.asarray(mask), self.pool, jnp.asarray(tables),
                    jnp.asarray([pos], jnp.int32))
            else:
                shape_key = ("chunk", bucket, 1)
                cold = self._prefill_cold_guard(shape_key)
                faults.fire("model_fn")
                t0 = time.perf_counter()
                logits, self.pool = self._chunk_slots(
                    self.cfg, self.params, jnp.asarray(ids),
                    jnp.asarray(mask), self.pool,
                    jnp.asarray([slot], jnp.int32),
                    jnp.asarray([pos], jnp.int32))
            # only the FINAL chunk's logits are ever read (they seed
            # the first sampled token); intermediate chunks skip the
            # device→host sync so the pass pipelines into its decode
            logits = np.asarray(logits) if final else None
            if cold:
                self._warm_shapes.add(shape_key)
                self.grace_until = 0.0
            self._count_dispatch("chunk_prefill", bucket - take)
            req.prefill_pos = pos + take
            if self._budget_left is not None:
                self._budget_left -= take
            total += take
            self.stats["prefill_tokens"] += take
            self.stats["prefill_chunks"] += 1
            self._m_prefill_chunks.inc()
            if st["resumed"]:
                self.stats["reprefill_tokens"] += take
            rec = self._rec
            if rec is not None:
                rec.phases["prefill"] = rec.phases.get("prefill", 0.0) \
                    + (time.perf_counter() - t0)
                rec.prefill_tokens += take
                rec.flops += obs_flops.span_flops(
                    self._flops_base, self._flops_per_ctx, pos, take)
            if req.prefill_pos >= len(vprompt):
                self._finish_chunking(slot, st, logits)
                break
        return total

    def _finish_chunking(self, slot: int, st: dict,
                         logits: np.ndarray) -> None:
        """The final chunk landed.  Fresh requests emit their first
        token from the chunk's last-token logits (then hand off on a
        prefill-role engine); resumes discard the logits — the last
        emitted token was already streamed — and just rejoin the
        decode batch, token-identity intact."""
        req = st["req"]
        vprompt = st["vprompt"]
        del self._chunking[slot]
        if self.paged:
            pages = self._slot_pages[slot]
            self._page_table[slot, :] = 0
            self._page_table[slot, :len(pages)] = pages
            self._page_table_dirty = True
            self._lengths[slot] = len(vprompt)
            if st.get("res") is not None:
                # publish full prompt blocks only now that their whole
                # prefill landed (the cache-publication contract: a
                # mid-chunk claim must never serve prefix hits)
                self.allocator.register(st["res"])
            else:
                # a mid-chunk preemption dropped the reservation (the
                # pages travelled pinned on the request instead):
                # publish the prompt's full blocks now that every
                # prompt position landed, or a preempted prompt would
                # silently never serve prefix hits — pages[i] backs
                # positions [i*ps, (i+1)*ps) in both layouts, and
                # emitted-token KV starts on the page AFTER the last
                # full prompt block
                hashes = paged_kv.chain_hashes(req.prompt_ids,
                                               self.ecfg.page_size)
                if hashes:
                    self.allocator.register_blocks(
                        hashes, pages[:len(hashes)])
        # dense mode: the chunk program advanced pool["length"] itself
        if st["resumed"]:
            req.resume_len = len(req.tokens)
            self.stats["resumed"] += 1
            trace(req.request_id, "prefill", model=self.name, slot=slot,
                  resumed=True, chunked=True)
            if self.role == "prefill":
                self._handoff_slot(slot)
                return
            trace(req.request_id, "decode", model=self.name, slot=slot)
            return
        self.stats["admitted"] += 1
        self.stats["prompt_tokens"] += len(vprompt)
        if req.cached_tokens:
            self.stats["prefix_hits"] += 1
            self.stats["prefix_tokens_saved"] += req.cached_tokens
            self._m_prefix_hits.inc()
            self._m_prefix_tokens.inc(req.cached_tokens)
        self._m_admitted.inc()
        rec = self._rec
        if rec is not None:
            rec.admitted += 1
            rec.cached_tokens += req.cached_tokens
            if req.cached_tokens:
                rec.prefix_hits += 1
        trace(req.request_id, "prefill", model=self.name, slot=slot,
              cached_tokens=req.cached_tokens, chunked=True)
        trace(req.request_id, "decode", model=self.name, slot=slot)
        self._emit(slot, logits[0])
        if self.role == "prefill" and self._slots[slot] is not None:
            self._handoff_slot(slot)

    def _admit(self) -> int:
        """Admit queued requests into free slots; returns how many (a
        prefill-bearing pass is what the phase-labeled iteration
        histogram and the stall analysis key on).  With every slot
        busy, QoS-lane preemption may first evict batch slots for
        waiting interactive requests (``_preempt_for_interactive``)."""
        free = [i for i, s in enumerate(self._slots) if s is None]
        forced = self._preempt_for_interactive(free)
        # the admit budget must cover every forced preemptor — they
        # are already popped and charged, and the slots they evicted
        # are in `free`; a budget below len(forced) (reachable with
        # max_admit_per_step < max_preempt_per_step) would strand them
        budget = min(len(free), max(self.ecfg.max_admit_per_step,
                                    len(forced)))
        if self.paged:
            return self._admit_paged(free, budget, forced)
        return self._admit_slots(free, budget, forced)

    def _preempt_for_interactive(self, free: list[int]) -> list[GenRequest]:
        """Lane semantics: while NO slot is free and an interactive
        request waits for a tenant still under its slot quota, evict a
        batch-lane slot mid-decode (victim: the batch request whose
        tenant has consumed the most weighted service, newest first on
        ties).  The evicted request re-queues at its lane head with
        its state intact — paged mode keeps its pages pinned so resume
        is prefill-free; slot mode re-prefills its context — and its
        emitted tokens / RNG are never recomputed, so outputs stay
        token-identical across the round trip.  Returns the popped
        interactive requests, which the admit pass MUST place (they
        are already charged and out of the queue)."""
        forced: list[GenRequest] = []
        cap = self.tenants.cfg.max_preempt_per_step
        # keep preempting while every free slot is already earmarked
        # by a forced pop (a burst of interactive arrivals may evict
        # several batch slots in ONE pass, up to the per-pass cap) —
        # but never when a genuinely spare slot could serve the
        # arrival without an eviction
        while len(forced) < cap and len(free) <= len(forced):
            with self._qlock:
                req = self.tenants.pop_interactive_preemptor()
                if req is None:
                    break
                victim = self.tenants.pick_victim(
                    [(i, r) for i, r in enumerate(self._slots)
                     if r is not None],
                    tokenless_eligible=self.paged)
                if victim is None:  # no batch-lane slot to evict
                    self.tenants.unpop(req)
                    break
            self._preempt_slot(victim)
            free.append(victim)
            forced.append(req)
        return forced

    def _preempt_slot(self, slot: int) -> None:
        req = self._slots[slot]
        self._slots[slot] = None
        chunking = self._chunking.pop(slot, None)
        self._spec_free(slot)
        if self.paged:
            # keep the pages reserved (pinned on the request): the KV
            # for every consumed position survives, so resume is just
            # re-installing the indirection — prefill-free.  A slot
            # caught MID-CHUNK keeps its prefill_pos alongside the
            # pins, so resume continues chunking from there instead of
            # recomputing delivered chunks.
            if chunking is None:
                req.prefill_pos = int(self._lengths[slot])
            req.pinned_pages, self._slot_pages[slot] = \
                self._slot_pages[slot], None
            self._page_table[slot, :] = 0
            self._page_table_dirty = True
            self._lengths[slot] = 0
        else:
            # the slot's KV rows are recycled; resume re-prefills
            # prompt + emitted tokens (deterministic, so re-derived KV
            # continues the sequence bitwise-identically)
            req.prefill_pos = 0
            self.pool = dict(self.pool)
            self.pool["length"] = self.pool["length"].at[slot].set(0)
        req.claimed = False  # back in the queue, not slot-bound
        req.preemptions += 1
        self.stats["preemptions"] += 1
        trace(req.request_id, "preempted", model=self.name, slot=slot,
              tenant=req.tenant, tokens=len(req.tokens))
        with self._qlock:
            self.tenants.note_preempted(req)
            self.tenants.append_head(req)

    def _admit_slots(self, free: list[int], budget: int,
                     forced: Optional[list] = None) -> int:
        batch: list[GenRequest] = []
        resumes: list[GenRequest] = []
        forced = forced or []
        while len(batch) + len(resumes) < budget:
            req = self._next_admittable(forced)
            if req is None:
                break
            req.claimed = True
            resumed = bool(req.tokens)  # preempted mid-decode earlier
            req.admitted_at = time.monotonic()
            trace(req.request_id, "admitted", model=self.name,
                  queue_s=round(req.admitted_at - req.submitted_at, 6),
                  tenant=req.tenant, lane=req.lane, resumed=resumed)
            (resumes if resumed else batch).append(req)
        self._unpop_leftover(forced)
        # Claimed but not yet slotted: visible to the failure paths
        # until every group lands in _slots (cleared at the end; a
        # crash in between is _fail_active's to clean up).
        self._admitting = batch + resumes
        if self.ecfg.prefill_chunk_tokens:
            # Sarathi co-scheduling: each admission enters chunking
            # state and prefills only what the pass's token budget
            # allows (a short prompt completes immediately; a long one
            # interleaves with decode passes).  Resumes chunk their
            # re-prefill the same way — the preemption cost this
            # softens.
            for req in batch:
                slot = free.pop(0)
                self._slots[slot] = req
                req.prefill_pos = 0
                with self._qlock:
                    self.tenants.charge_prefill(req,
                                                len(req.prompt_ids))
                self._chunking[slot] = {
                    "req": req, "vprompt": list(req.prompt_ids),
                    "resumed": False, "res": None}
                self._advance_chunk(slot, self._chunking[slot])
            for req in resumes:
                slot = free.pop(0)
                self._slots[slot] = req
                req.prefill_pos = 0
                self._chunking[slot] = {
                    "req": req,
                    "vprompt": req.prompt_ids + req.tokens[:-1],
                    "resumed": True, "res": None}
                self._advance_chunk(slot, self._chunking[slot])
            self._admitting = []
            return len(batch) + len(resumes)
        # One prefill dispatch per prompt-length bucket, not per request:
        # a same-bucket burst scatters into its slots with a single
        # program call (compile count stays bounded at
        # #buckets x max_admit_per_step shapes).
        by_bucket: dict[int, list[GenRequest]] = {}
        for req in batch:
            by_bucket.setdefault(self._bucket(len(req.prompt_ids)),
                                 []).append(req)
        for bucket, group in by_bucket.items():
            slots = [free.pop(0) for _ in group]
            ids = np.full((len(group), bucket), self.pad, np.int32)
            mask = np.zeros((len(group), bucket), np.int32)
            for r, req in enumerate(group):
                ids[r, :len(req.prompt_ids)] = req.prompt_ids
                mask[r, :len(req.prompt_ids)] = 1
            shape_key = (bucket, len(group))
            cold = self._prefill_cold_guard(shape_key)
            faults.fire("model_fn")
            t0 = time.perf_counter()
            logits, self.pool = self._prefill(
                self.cfg, self.params, jnp.asarray(ids), jnp.asarray(mask),
                self.pool, jnp.asarray(slots, jnp.int32))
            logits = np.asarray(logits)
            self._count_dispatch(
                "prefill", int(len(group) * bucket - mask.sum()))
            rec = self._rec
            if rec is not None:
                rec.phases["prefill"] = rec.phases.get("prefill", 0.0) \
                    + (time.perf_counter() - t0)
            if cold:
                self._warm_shapes.add(shape_key)
                self.grace_until = 0.0  # compiled; wedges detect normally
            for r, (slot, req) in enumerate(zip(slots, group)):
                self._slots[slot] = req
                self.stats["admitted"] += 1
                self.stats["prefill_tokens"] += len(req.prompt_ids)
                self.stats["prompt_tokens"] += len(req.prompt_ids)
                self._m_admitted.inc()
                with self._qlock:  # WFQ service clock: prompt tokens
                    self.tenants.charge_prefill(req, len(req.prompt_ids))
                if rec is not None:
                    rec.admitted += 1
                    rec.prefill_tokens += len(req.prompt_ids)
                    rec.flops += obs_flops.span_flops(
                        self._flops_base, self._flops_per_ctx, 0,
                        len(req.prompt_ids))
                trace(req.request_id, "prefill", model=self.name,
                      slot=slot, bucket=bucket)
                # the slot now joins the persistent decode batch; emit
                # BEFORE the first token so span order reads
                # prefill → decode → first_token
                trace(req.request_id, "decode", model=self.name, slot=slot)
                self._emit(slot, logits[r])
        for req in resumes:
            self._resume_into_slot(free.pop(0), req)
        self._admitting = []
        return len(batch) + len(resumes)

    def _resume_into_slot(self, slot: int, req: GenRequest) -> None:
        """Slot-mode resume after preemption: re-derive the slot's KV
        by prefilling prompt + every emitted token but the last (the
        exact context a continuing decode would hold — the last token's
        KV is written by its own next decode step), then re-activate.
        The prefill logits are DISCARDED: the last emitted token was
        already streamed, and re-sampling it would double-emit.  The
        request's RNG and token list are untouched, so the continuation
        is token-identical to never having been preempted."""
        ids_list = req.prompt_ids + req.tokens[:-1]
        bucket = self._bucket(len(ids_list))
        ids = np.full((1, bucket), self.pad, np.int32)
        mask = np.zeros((1, bucket), np.int32)
        ids[0, :len(ids_list)] = ids_list
        mask[0, :len(ids_list)] = 1
        shape_key = (bucket, 1)
        cold = self._prefill_cold_guard(shape_key)
        faults.fire("model_fn")
        t0 = time.perf_counter()
        logits, self.pool = self._prefill(
            self.cfg, self.params, jnp.asarray(ids), jnp.asarray(mask),
            self.pool, jnp.asarray([slot], jnp.int32))
        logits.block_until_ready()  # discard: see docstring
        self._count_dispatch("prefill", int(bucket - mask.sum()))
        rec = self._rec
        if rec is not None:
            rec.phases["prefill"] = rec.phases.get("prefill", 0.0) \
                + (time.perf_counter() - t0)
            rec.admitted += 1
            rec.prefill_tokens += len(ids_list)
            rec.flops += obs_flops.span_flops(
                self._flops_base, self._flops_per_ctx, 0, len(ids_list))
        if cold:
            self._warm_shapes.add(shape_key)
            self.grace_until = 0.0
        self._slots[slot] = req
        req.resume_len = len(req.tokens)
        self.stats["resumed"] += 1
        # engine-level prefill_tokens counts the recompute (it is real
        # compute the stall analysis must see); the tenant's virtual
        # clock does NOT advance — the victim already paid for these
        # tokens once, and preemption overhead is the preemptor's
        # fault, not the victim's service
        self.stats["prefill_tokens"] += len(ids_list)
        self.stats["reprefill_tokens"] += len(ids_list)
        trace(req.request_id, "prefill", model=self.name, slot=slot,
              resumed=True)
        trace(req.request_id, "decode", model=self.name, slot=slot)

    def _handoff_slot(self, slot: int) -> None:
        """Prefill role: the request's first token is out — extract
        its prompt KV page-granularly and hand the request to the
        decode plane instead of keeping the slot for decode.
        Scheduler thread only: reading the arena between program
        dispatches is what makes the extract safe against buffer
        donation.  The slot's claim is fully released here (shared
        prefix pages survive in this arena's cache; the decode side
        holds its own claim)."""
        req = self._slots[slot]
        pages = self._slot_pages[slot]
        plen = int(self._lengths[slot])
        ps = self.ecfg.page_size
        n_prompt = -(-plen // ps)
        t0 = time.perf_counter()
        started = time.monotonic()
        data = extract_pages(self.pool, pages[:n_prompt])
        dt = time.perf_counter() - t0
        vprompt = req.prompt_ids + req.tokens[:-1]
        payload = KVHandoff(data=data, prompt_len=plen,
                            hashes=paged_kv.chain_hashes(vprompt, ps),
                            started_at=started)
        self._slots[slot] = None
        self._slot_pages[slot] = None
        self.allocator.release(pages)
        self._page_table[slot, :] = 0
        self._page_table_dirty = True
        self._lengths[slot] = 0
        with self._qlock:
            self.tenants.note_finished(req, len(pages))
        req.claimed = False
        self.stats["handoffs"] += 1
        self.stats["kv_transfer_pages"] += n_prompt
        self._m_kv_transfer_out.inc(n_prompt)
        trace(req.request_id, "kv_extract", model=self.name,
              dur_s=dt, pages=n_prompt)
        rec = self._rec
        if rec is not None:
            rec.phases["kv_transfer"] = \
                rec.phases.get("kv_transfer", 0.0) + dt
        cb = self._handoff_cb
        if cb is None:
            # a prefill-role engine with no decode plane attached must
            # not strand the stream mid-request (the first token is
            # already out; the retry recomputes it elsewhere)
            req.error = RetryableError("no decode replica attached; "
                                       "retry")
            req.stream.put(_STREAM_END)
            req.event.set()
            return
        cb(req, payload)

    def _admit_paged(self, free: list[int], budget: int,
                     forced: Optional[list] = None) -> int:
        """Paged admission: reserve pages (reusing cached prefix blocks)
        per request, then prefill only the uncached tails, grouped by
        tail-length bucket.  A reservation that cannot be satisfied
        right now puts the request back at the queue head — pages free
        as decoding slots evict, exactly like waiting for a free slot.

        Resumes ride the same machinery: a preempted request with its
        pages still pinned just re-installs its indirection (prefill-
        free); one whose pages are gone (supervisor transplant) runs as
        a virtual prompt of ``prompt + tokens[:-1]`` whose prefill
        logits are discarded — either way the emitted-token list and
        RNG are untouched, so the continuation is token-identical."""
        rec = self._rec
        forced = forced or []
        #: (req, reservation, virtual prompt, is_resume)
        batch: list[tuple[GenRequest, Any, list, bool]] = []
        pinned: list[GenRequest] = []
        while len(batch) + len(pinned) < budget:
            req = self._next_admittable(forced)
            if req is None:
                break
            resumed = bool(req.tokens)
            if req.pinned_pages:
                # a pinned claim still holds every delivered position's
                # KV — covers decode-ready resumes AND a request
                # preempted mid-chunked-prefill (tokens may be empty;
                # prefill_pos says how far its chunks got)
                req.claimed = True
                req.admitted_at = time.monotonic()
                trace(req.request_id, "admitted", model=self.name,
                      queue_s=round(req.admitted_at - req.submitted_at,
                                    6),
                      tenant=req.tenant, lane=req.lane, resumed=resumed)
                pinned.append(req)
                continue
            # a resume without pages re-derives KV from its virtual
            # prompt; its reservation covers exactly the positions the
            # original claim did (context so far + what remains)
            vprompt = (req.prompt_ids if not resumed
                       else req.prompt_ids + req.tokens[:-1])
            vnew = (req.max_new_tokens if not resumed
                    else req.max_new_tokens - len(req.tokens) + 1)
            if self.role == "prefill":
                # a prefill-role engine never decodes: reserve only
                # the prompt's own pages (the decode plane holds the
                # full prompt+completion claim after the handoff)
                vnew = 0
            res = None
            while res is None:
                try:
                    res = self.allocator.reserve(vprompt, vnew)
                except KVPagesExhaustedError:
                    # pressure valve first: queued preempted requests
                    # still pin their old pages for a prefill-free
                    # resume, and on a full arena those pins would
                    # turn the very preemption that freed this slot
                    # into a no-op — reclaim one claim (its owner
                    # re-prefills at resume, like a transplant) and
                    # retry before giving up
                    if not self._reclaim_pinned():
                        break
            if res is None:
                # genuinely transient (submit() rejects permanently-
                # impossible claims): requeue at the head and stop
                # admitting — later arrivals must not starve this one
                with self._qlock:
                    self.tenants.unpop(req)
                break
            req.claimed = True
            req.admitted_at = time.monotonic()
            if not resumed:
                req.cached_tokens = res.cached_tokens
            trace(req.request_id, "admitted", model=self.name,
                  queue_s=round(req.admitted_at - req.submitted_at, 6),
                  tenant=req.tenant, lane=req.lane, resumed=resumed)
            batch.append((req, res, vprompt, resumed))
        self._unpop_leftover(forced)
        self._admitting = [req for req, _, _, _ in batch] + pinned
        # Every copy-on-write page copy is dispatched BEFORE any prefill
        # of this pass: the allocator may have recycled a COW source's
        # physical page for a later reservation in the same batch, and
        # the copy must read it before that reservation's prefill
        # overwrites it.
        t_cow = time.perf_counter()
        any_cow = False
        for req, res, _, _ in batch:
            if res.cow is not None:
                src, dst = res.cow
                any_cow = True
                if self._pass is not None:
                    # the flush program's copy prologue runs before its
                    # layer scan — i.e. before every write of the pass,
                    # the same ordering this loop's eager dispatches
                    # give the padded engine (flush counts the stats)
                    self._pass.copy_src.append(src)
                    self._pass.copy_dst.append(dst)
                    continue
                self.stats["cow_copies"] += 1
                self._m_cow.inc()
                self.pool = self._copy_pages(
                    self.pool, jnp.asarray([src], jnp.int32),
                    jnp.asarray([dst], jnp.int32))
                self._count_dispatch("cow_copy", 0)
        if rec is not None and any_cow and self._pass is None:
            rec.phases["cow_copy"] = rec.phases.get("cow_copy", 0.0) \
                + (time.perf_counter() - t_cow)
        if self.ecfg.prefill_chunk_tokens:
            n = self._admit_paged_chunked(free, batch, pinned)
            self._admitting = []
            return n
        if self._pass is not None:
            # ragged admission: every uncached tail is a segment of
            # the pass's flat batch at its true positions — no
            # tail-length bucketing (the flush ladder bounds shapes),
            # no per-bucket dispatch.  Slot state installs NOW (the
            # segment's global table row must resolve at flush);
            # first-token emission and prefill-role handoff defer to
            # continuations, after the program ran.
            for req, res, vprompt, resumed in batch:
                slot = free.pop(0)
                self._slots[slot] = req
                self._slot_pages[slot] = res.pages
                self._page_table[slot, :] = 0
                self._page_table[slot, :len(res.pages)] = res.pages
                self._page_table_dirty = True
                self._lengths[slot] = len(vprompt)
                self.allocator.register(res)
                plen = len(vprompt)
                computed = plen - res.cached_tokens
                idx = self._pass.add_segment(
                    slot, vprompt[res.cached_tokens:],
                    res.cached_tokens, kind="prefill",
                    out=("none" if resumed else "last"))
                self.stats["prefill_tokens"] += computed
                with self._qlock:
                    self.tenants.note_pages(req.tenant, len(res.pages))
                    if not resumed:
                        self.tenants.charge_prefill(
                            req, computed, start=res.cached_tokens)
                if rec is not None:
                    rec.admitted += 1
                    rec.prefill_tokens += computed
                    rec.pages_reserved += len(res.pages)
                    rec.flops += obs_flops.span_flops(
                        self._flops_base, self._flops_per_ctx,
                        res.cached_tokens, computed)
                if resumed:
                    req.resume_len = len(req.tokens)
                    self.stats["resumed"] += 1
                    self.stats["reprefill_tokens"] += computed
                    trace(req.request_id, "prefill", model=self.name,
                          slot=slot, resumed=True)
                    if self.role == "prefill":
                        # the re-derived KV must land in the arena
                        # before the extract reads it
                        def _fin(logits, slot=slot, req=req):
                            if self._slots[slot] is req:
                                self._handoff_slot(slot)

                        self._pass.continuations.append(_fin)
                        continue
                    trace(req.request_id, "decode", model=self.name,
                          slot=slot)
                    continue
                self.stats["admitted"] += 1
                self.stats["prompt_tokens"] += plen
                if res.cached_tokens:
                    self.stats["prefix_hits"] += 1
                    self.stats["prefix_tokens_saved"] += \
                        res.cached_tokens
                    self._m_prefix_hits.inc()
                    self._m_prefix_tokens.inc(res.cached_tokens)
                self._m_admitted.inc()
                if rec is not None:
                    rec.cached_tokens += res.cached_tokens
                    if res.cached_tokens:
                        rec.prefix_hits += 1
                trace(req.request_id, "prefill", model=self.name,
                      slot=slot, cached_tokens=res.cached_tokens)
                trace(req.request_id, "decode", model=self.name,
                      slot=slot)

                def _fin(logits, slot=slot, req=req, row=idx[0]):
                    # guard: an interactive burst next pass can't have
                    # preempted us yet (continuations run inside this
                    # pass), but a cancel reap can — emit only if the
                    # slot still holds this request
                    if self._slots[slot] is not req:
                        return
                    self._emit(slot, logits[row])
                    if (self.role == "prefill"
                            and self._slots[slot] is not None):
                        self._handoff_slot(slot)

                self._pass.continuations.append(_fin)
            for req in pinned:
                slot = free.pop(0)
                pages, req.pinned_pages = req.pinned_pages, None
                self._slots[slot] = req
                self._slot_pages[slot] = pages
                self._page_table[slot, :] = 0
                self._page_table[slot, :len(pages)] = pages
                self._page_table_dirty = True
                self._lengths[slot] = (len(req.prompt_ids)
                                       + len(req.tokens) - 1)
                req.resume_len = len(req.tokens)
                self.stats["resumed"] += 1
                trace(req.request_id, "decode", model=self.name,
                      slot=slot, resumed=True)
            self._admitting = []
            return len(batch) + len(pinned)
        by_bucket: dict[int, list[tuple[GenRequest, Any, list, bool]]] = {}
        for entry in batch:
            _, res, vprompt, _ = entry
            tail = len(vprompt) - res.cached_tokens
            by_bucket.setdefault(self._bucket(tail), []).append(entry)
        n_pages = self.ecfg.pages_per_slot
        for bucket, group in by_bucket.items():
            slots = [free.pop(0) for _ in group]
            ids = np.full((len(group), bucket), self.pad, np.int32)
            mask = np.zeros((len(group), bucket), np.int32)
            tables = np.zeros((len(group), n_pages), np.int32)
            start = np.zeros((len(group),), np.int32)
            for r, (req, res, vprompt, _) in enumerate(group):
                tail = vprompt[res.cached_tokens:]
                ids[r, :len(tail)] = tail
                mask[r, :len(tail)] = 1
                tables[r, :len(res.pages)] = res.pages
                start[r] = res.cached_tokens
            shape_key = ("paged", bucket, len(group))
            cold = self._prefill_cold_guard(shape_key)
            faults.fire("model_fn")
            t0 = time.perf_counter()
            logits, self.pool = self._prefill_pages(
                self.cfg, self.params, jnp.asarray(ids), jnp.asarray(mask),
                self.pool, jnp.asarray(tables), jnp.asarray(start))
            logits = np.asarray(logits)
            self._count_dispatch(
                "prefill", int(len(group) * bucket - mask.sum()))
            if rec is not None:
                rec.phases["prefill"] = rec.phases.get("prefill", 0.0) \
                    + (time.perf_counter() - t0)
            if cold:
                self._warm_shapes.add(shape_key)
                self.grace_until = 0.0
            for r, (slot, (req, res, vprompt, resumed)) in enumerate(
                    zip(slots, group)):
                self._slots[slot] = req
                self._slot_pages[slot] = res.pages
                self._page_table[slot, :] = 0
                self._page_table[slot, :len(res.pages)] = res.pages
                self._page_table_dirty = True
                self._lengths[slot] = len(vprompt)
                # the pages now hold this prompt's blocks: publish them
                # for the next request sharing the prefix
                self.allocator.register(res)
                plen = len(vprompt)
                computed = plen - res.cached_tokens
                self.stats["prefill_tokens"] += computed
                with self._qlock:
                    self.tenants.note_pages(req.tenant, len(res.pages))
                    if not resumed:
                        # cache hits charge the computed tail only, at
                        # its true deep-context FLOP price
                        self.tenants.charge_prefill(
                            req, computed, start=res.cached_tokens)
                if rec is not None:
                    rec.admitted += 1
                    rec.prefill_tokens += computed
                    rec.pages_reserved += len(res.pages)
                    rec.flops += obs_flops.span_flops(
                        self._flops_base, self._flops_per_ctx,
                        res.cached_tokens, computed)
                if resumed:
                    # transplant resume: the virtual prompt re-derived
                    # the context; nothing new to emit or account —
                    # the original admission already counted the
                    # request, and the victim's service clock does not
                    # pay for preemption overhead
                    req.resume_len = len(req.tokens)
                    self.stats["resumed"] += 1
                    self.stats["reprefill_tokens"] += computed
                    trace(req.request_id, "prefill", model=self.name,
                          slot=slot, resumed=True)
                    if self.role == "prefill":
                        # a requeued mid-decode request (decode-
                        # replica death) re-prefilled here; hand its
                        # re-derived KV to a surviving decode slice
                        self._handoff_slot(slot)
                        continue
                    trace(req.request_id, "decode", model=self.name,
                          slot=slot)
                    continue
                self.stats["admitted"] += 1
                self.stats["prompt_tokens"] += plen
                if res.cached_tokens:
                    self.stats["prefix_hits"] += 1
                    self.stats["prefix_tokens_saved"] += res.cached_tokens
                    self._m_prefix_hits.inc()
                    self._m_prefix_tokens.inc(res.cached_tokens)
                self._m_admitted.inc()
                if rec is not None:
                    rec.cached_tokens += res.cached_tokens
                    if res.cached_tokens:
                        rec.prefix_hits += 1
                trace(req.request_id, "prefill", model=self.name,
                      slot=slot, bucket=bucket,
                      cached_tokens=res.cached_tokens)
                trace(req.request_id, "decode", model=self.name, slot=slot)
                self._emit(slot, logits[r])
                if self.role == "prefill" and self._slots[slot] is not None:
                    # first token emitted and more are wanted: the
                    # decode plane takes it from here, KV and all
                    # (an EOS / max-1 request already finished above)
                    self._handoff_slot(slot)
        for req in pinned:
            # prefill-free resume: the pinned pages still hold KV for
            # every consumed position; re-installing the indirection
            # at context length prompt + tokens - 1 (the last emitted
            # token's KV is written by its own next decode step) puts
            # the request exactly where preemption found it
            slot = free.pop(0)
            pages, req.pinned_pages = req.pinned_pages, None
            self._slots[slot] = req
            self._slot_pages[slot] = pages
            self._page_table[slot, :] = 0
            self._page_table[slot, :len(pages)] = pages
            self._page_table_dirty = True
            self._lengths[slot] = len(req.prompt_ids) + len(req.tokens) - 1
            req.resume_len = len(req.tokens)
            self.stats["resumed"] += 1
            trace(req.request_id, "decode", model=self.name, slot=slot,
                  resumed=True)
        self._admitting = []
        return len(batch) + len(pinned)

    def _admit_paged_chunked(self, free: list[int], batch: list,
                             pinned: list) -> int:
        """Chunked-prefill placement for paged admissions: every
        request takes its slot and reservation now, but prefill runs
        in budget-bounded chunks — the slot's page table and length
        stay null until the final chunk lands, so the decode program
        keeps routing its masked garbage write into the null page
        meanwhile."""
        rec = self._rec
        for req, res, vprompt, resumed in batch:
            slot = free.pop(0)
            self._slots[slot] = req
            self._slot_pages[slot] = res.pages
            self._page_table[slot, :] = 0
            self._page_table_dirty = True
            self._lengths[slot] = 0
            req.prefill_pos = res.cached_tokens
            with self._qlock:
                self.tenants.note_pages(req.tenant, len(res.pages))
                if not resumed:
                    self.tenants.charge_prefill(
                        req, len(vprompt) - res.cached_tokens,
                        start=res.cached_tokens)
            if rec is not None:
                rec.pages_reserved += len(res.pages)
            self._chunking[slot] = {"req": req, "vprompt": vprompt,
                                    "resumed": resumed, "res": res}
            self._advance_chunk(slot, self._chunking[slot])
        for req in pinned:
            slot = free.pop(0)
            pages, req.pinned_pages = req.pinned_pages, None
            self._slots[slot] = req
            self._slot_pages[slot] = pages
            vprompt = (req.prompt_ids + req.tokens[:-1]
                       if req.tokens else list(req.prompt_ids))
            if req.tokens and req.prefill_pos >= len(vprompt):
                # fully-delivered claim: the classic prefill-free
                # resume — reinstall the indirection and decode
                self._page_table[slot, :] = 0
                self._page_table[slot, :len(pages)] = pages
                self._page_table_dirty = True
                self._lengths[slot] = len(vprompt)
                req.resume_len = len(req.tokens)
                self.stats["resumed"] += 1
                trace(req.request_id, "decode", model=self.name,
                      slot=slot, resumed=True)
                continue
            # preempted mid-chunk: the pinned pages hold positions
            # 0..prefill_pos-1 — keep chunking from right there (the
            # chunks already delivered are never recomputed)
            self._page_table[slot, :] = 0
            self._page_table_dirty = True
            self._lengths[slot] = 0
            self._chunking[slot] = {"req": req, "vprompt": vprompt,
                                    "resumed": bool(req.tokens),
                                    "res": None}
            self._advance_chunk(slot, self._chunking[slot])
        return len(batch) + len(pinned)

    def _bucket(self, n: int) -> int:
        """Power-of-two prompt bucket (same rationale as
        ``CausalLMService._encode_batch``: log-many compiled prefill
        shapes), clamped to the pool's max_len."""
        bucket = 32
        while bucket < n:
            bucket *= 2
        return min(bucket, self.ecfg.max_len)

    def _emit(self, slot: int, logits_row: np.ndarray,
              token: Optional[int] = None) -> None:
        """Sample the slot's next token, stream it out, and evict the
        slot if the request just finished — ordering identical to
        :func:`models.generate.generate`'s sample→emit→check-eos loop.
        ``token`` bypasses sampling for a caller that already drew it
        (stochastic speculative accept/reject — ``_emit_rejection``
        consumed the slot RNG itself)."""
        req = self._slots[slot]
        t0 = time.perf_counter()
        tok = (int(token) if token is not None
               else _sample_host(logits_row, req.rng,
                                 temperature=req.temperature,
                                 top_k=req.top_k, top_p=req.top_p))
        t1 = time.perf_counter()
        if req.first_token_at is None:
            req.first_token_at = time.monotonic()
            self._m_ttft.observe(req.first_token_at - req.submitted_at)
            self.tenants.observe_ttft(
                req, req.first_token_at - req.submitted_at)
            trace(req.request_id, "first_token", model=self.name,
                  ttft_s=round(req.first_token_at - req.submitted_at, 6),
                  prefill_s=round(req.first_token_at
                                  - (req.admitted_at or req.submitted_at),
                                  6))
        req.tokens.append(tok)
        # WFQ service clock: one decoded token.  Deliberately LOCK-FREE
        # on the hot path: only the scheduler thread charges clocks,
        # and the one other vt writer — append()'s idle-tenant lift,
        # under _qlock on HTTP threads — cannot run concurrently for
        # this tenant (a tenant with an active slot is in_system, so
        # the lift is skipped); GIL-atomic float reads make the
        # cross-thread vt *reads* in pop ordering safe.
        self.tenants.charge_decode(
            req, ctx=min(len(req.prompt_ids) + len(req.tokens),
                         self.ecfg.max_len))
        if faults.fire("stream") != "drop":  # "drop" loses the delivery
            req.stream.put(tok)
        rec = self._rec
        if rec is not None:
            ph = rec.phases
            ph["sample"] = ph.get("sample", 0.0) + (t1 - t0)
            ph["stream"] = ph.get("stream", 0.0) \
                + (time.perf_counter() - t1)
        self.stats["emitted_tokens"] += 1
        self._m_tokens.inc()
        if ((self.eos is not None and tok == self.eos)
                or len(req.tokens) >= req.max_new_tokens):
            self._finish_slot(slot)

    def _finish_slot(self, slot: int,
                     error: Optional[Exception] = None) -> None:
        req = self._slots[slot]
        self._slots[slot] = None
        self._chunking.pop(slot, None)
        self._spec_free(slot)
        req.prefill_pos = 0
        self.stats["evictions"] += 1
        self._m_evicted.inc()
        released = (len(self._slot_pages[slot])
                    if self.paged and self._slot_pages[slot] else 0)
        with self._qlock:
            self.tenants.note_finished(req, released)
        rec = self._rec
        if rec is not None:
            rec.evicted += 1
        if self.paged:
            # Drop the page claim (shared prefix pages survive while
            # siblings reference them; cached ones park in the LRU) and
            # null the indirection so the frozen slot's garbage write
            # lands in the null page until the next admission.
            pages, self._slot_pages[slot] = self._slot_pages[slot], None
            if pages:
                self.allocator.release(pages)
                if rec is not None:
                    rec.pages_freed += len(pages)
            self._page_table[slot, :] = 0
            self._page_table_dirty = True
            self._lengths[slot] = 0
        else:
            # Reset the freed row's length so the frozen-slot K/V write
            # in decode_step_slots stays at position 0 until the next
            # admission.
            self.pool = dict(self.pool)
            self.pool["length"] = self.pool["length"].at[slot].set(0)
        req.error = error
        req.done_at = time.monotonic()
        trace(req.request_id, _terminal_span(error), model=self.name,
              tokens=len(req.tokens),
              duration_s=round(req.done_at - req.submitted_at, 6))
        if self.flight is not None:
            summary = {"request_id": req.request_id, "ts": time.time(),
                       "outcome": _terminal_span(error),
                       "tokens": len(req.tokens),
                       "prompt_tokens": len(req.prompt_ids),
                       "cached_tokens": req.cached_tokens,
                       "duration_s": round(req.done_at - req.submitted_at,
                                           6)}
            if req.first_token_at is not None:
                summary["ttft_s"] = round(
                    req.first_token_at - req.submitted_at, 6)
                if req.admitted_at is not None:
                    summary["queue_s"] = round(
                        req.admitted_at - req.submitted_at, 6)
                    summary["prefill_s"] = round(
                        req.first_token_at - req.admitted_at, 6)
            self.flight.record_request(summary)
        req.stream.put(_STREAM_END)
        req.event.set()

    def _fail_queued(self, err: Exception,
                     release_pinned: bool = False) -> None:
        with self._qlock:
            drained = self.tenants.drain()
        for req in drained:
            if release_pinned:
                # scheduler-thread drains free a preempted request's
                # pinned pages; the submit()-race caller (an HTTP
                # thread) must not touch the single-owner allocator —
                # that engine is stopping and its arena dies with it
                self._release_pinned(req)
            req.error = err
            trace(req.request_id, "failed", model=self.name,
                  error=type(err).__name__)
            req.stream.put(_STREAM_END)
            req.event.set()

    def _fail_active(self, err: Exception) -> None:
        for i, req in enumerate(self._slots):
            if req is not None:
                self._slots[i] = None
                req.error = err
                req.done_at = time.monotonic()
                trace(req.request_id, "failed", model=self.name,
                      error=type(err).__name__)
                req.stream.put(_STREAM_END)
                req.event.set()
        # Requests claimed by a mid-flight _admit (popped from the
        # queue, not yet slotted — e.g. wedged inside prefill): without
        # this they would be orphaned with no error, no stream close,
        # and a live-looking engine to wait on forever.
        admitting, self._admitting = self._admitting, []
        for req in admitting:
            if not req.event.is_set():
                req.error = err
                req.done_at = time.monotonic()
                trace(req.request_id, "failed", model=self.name,
                      error=type(err).__name__)
                req.stream.put(_STREAM_END)
                req.event.set()


def _terminal_span(error: Optional[Exception]) -> str:
    """Map a slot's final state onto the trace span vocabulary."""
    if error is None:
        return "complete"
    if isinstance(error, RequestCancelled):
        return "cancelled"
    if isinstance(error, DeadlineExceededError):
        return "shed"
    return "failed"


class ContinuousBatchingModel(Model):
    """Serve a :class:`~kubernetes_cloud_tpu.serve.lm_service.
    CausalLMService` through the continuous-batching engine.

    Drop-in alternative to wrapping the service in ``BatchingModel``:
    same V1 predict / completion surface, same ``self_batching``
    contract (ModelServer skips its lock), same ``QueueFullError``
    backpressure.  Requests are tokenized on the HTTP thread, submitted
    per-prompt (no parameter-compatibility merging needed), and decoded
    as their slots finish.
    """

    self_batching = True

    def __init__(self, name: str, service, cfg: EngineConfig = EngineConfig(),
                 draft_service=None):
        super().__init__(name)
        self.service = service
        self.cfg = cfg
        self.engine: Optional[ContinuousBatchingEngine] = None
        #: speculative decoding's draft LM (``cfg.spec_draft`` names a
        #: model dir): loaded once and kept across engine restarts —
        #: the supervisor's rebuild path reuses still-loaded weights
        #: for the draft exactly like the target
        self.draft_service = draft_service
        #: guards the engine/params pointer cutover — held by load()
        #: and the supervisor's restart path so a hot-swap can never
        #: interleave with an engine rebuild (only pointer mutation
        #: happens under it; weight I/O stays outside)
        self._swap_lock = threading.RLock()
        #: non-blocking serializer for swap_weights: a second swap
        #: while one is in flight is SwapInProgressError (503), not a
        #: queue of multi-second weight loads
        self._swapping = threading.Lock()

    def _build_engine(self, params,
                      weights_version: Optional[str] = None):
        """Construct (but don't start) an engine over ``params`` —
        shared by cold ``load()`` and ``swap_weights``'s prepare-aside
        path, so both rollout shapes run the exact same build."""
        tok = self.service.tokenizer
        draft = None
        sd = self.cfg.spec_draft
        if sd and sd != "ngram":
            if self.draft_service is None:
                self.draft_service = _draft_service_for(sd)
            if not self.draft_service.ready:
                self.draft_service.load()
            draft = (self.draft_service.cfg,
                     self.draft_service.params)
        kw = dict(eos_token_id=getattr(tok, "eos_token_id", None),
                  pad_token_id=getattr(tok, "pad_token_id", 0) or 0,
                  mesh=self.service.mesh, name=self.name,
                  draft=draft, weights_version=weights_version)
        if self.cfg.role == "prefill":
            # disaggregated pod: one prefill engine feeding
            # cfg.decode_slices decode engines through page-
            # granular KV handoff (serve/disagg.py)
            from kubernetes_cloud_tpu.serve.disagg import (
                build_disaggregated_engine,
            )

            return build_disaggregated_engine(
                self.service.cfg, params, self.cfg, **kw)
        return ContinuousBatchingEngine(
            self.service.cfg, params, self.cfg, **kw)

    def load(self) -> None:
        if self.engine is not None and self.engine.draining:
            # flipping ready=True over a stopped-but-draining engine
            # would make every predict 500 until someone load()s again.
            # Typed retryable (503), not a bare 500 (KCT-ERR-004).
            raise EngineDrainingError(
                "previous engine still draining; call stop() again")
        if not self.service.ready:
            self.service.load()
        self.weights_version = getattr(self.service, "weights_version",
                                       None)
        if self.engine is None or not self.engine.alive:
            engine = self._build_engine(self.service.params,
                                        self.weights_version)
            with self._swap_lock:
                self.engine = engine
            self.engine.start()
        self.ready = True

    def stop(self) -> None:
        if self.engine is not None:
            self.engine.stop()
        self.ready = False

    # -- live weight hot-swap ----------------------------------------------

    def _smoke_check(self, params, smoke_tokens: int) -> None:
        """kv_quant_probe-style gate: the candidate weights must drive
        a real end-to-end generation (in-vocab tokens out) BEFORE they
        may take traffic — checksum integrity says the bytes are the
        ones written; this says they behave like a model."""
        if smoke_tokens <= 0:
            return
        svc = self.service
        ids, mask = svc._encode_batch(["weights hot-swap probe"])
        out = svc._generate(
            svc.cfg, params, ids, mask,
            max_new_tokens=int(smoke_tokens), temperature=1.0,
            top_k=1, top_p=1.0, eos_token_id=None,
            pad_token_id=getattr(svc.tokenizer, "pad_token_id", 0) or 0,
            rng=jax.random.key(0))
        arr = np.asarray(jax.block_until_ready(out))
        fresh = arr[:, ids.shape[1]:]
        if fresh.shape[-1] < 1 or not bool(
                np.all((fresh >= 0) & (fresh < svc.cfg.vocab_size))):
            raise SwapVerificationError(
                "smoke generation over candidate weights produced "
                "invalid tokens — refusing to swap")

    def swap_weights(self, weights_path: str, *,
                     smoke_tokens: int = 4) -> dict:
        """Roll new weights into the RUNNING model: prepare the new
        version entirely off to the side (chunk-verified streamed
        load, smoke generation, fresh engine build — no lock held, the
        old engine keeps serving throughout), then an atomic pointer
        cutover and a queued-work transplant through the same
        extract/requeue path a supervisor restart uses.  Any failure
        before the cutover rolls back by discarding the prepared side:
        the old version is never released until the new one has passed
        verification, and no accepted request is dropped either way —
        queued work moves to the new engine, in-flight slots finish on
        the weights that prefilled them."""
        from kubernetes_cloud_tpu.weights.tensorstream import (
            read_index,
            resolve_artifact,
        )

        if self.engine is None or not self.ready:
            raise RetryableError(
                "model not serving; load() it before swapping weights")
        if not self._swapping.acquire(blocking=False):
            raise SwapInProgressError(
                f"a weight swap is already in flight on {self.name}")
        t0 = time.perf_counter()
        try:
            try:
                # -- prepare off to the side (old engine untouched) ---
                path = resolve_artifact(weights_path)
                index = read_index(path)
                new_params, new_version = self.service.load_params(
                    path, index)
                self._smoke_check(new_params, smoke_tokens)
                new_engine = self._build_engine(new_params, new_version)
                new_engine.start()
                try:
                    # chaos hook: the window after the new version is
                    # fully prepared, before it takes any traffic
                    faults.fire("weights.swap")
                    with self._swap_lock:
                        old_engine, self.engine = self.engine, new_engine
                        svc = self.service
                        svc.params = new_params
                        svc.weights_path = path
                        svc.weights_index = index
                        svc.weights_version = new_version
                        self.weights_version = new_version
                except Exception:  # noqa: BLE001 - rollback then re-raise
                    # rollback: discard the prepared side whole — the
                    # old engine never stopped serving
                    new_engine.stop()
                    raise
            except Exception:  # noqa: BLE001 - metric then re-raise
                _M_SWAPS.labels(model=self.name,
                                outcome="rolled_back").inc()
                raise
            # -- committed: transplant queued work, drain the old -----
            transplanted = 0
            empty_rounds = 0
            while empty_rounds < 3:
                # settle loop: a _submit_all racing the cutover may
                # still land requests on the old engine; keep pulling
                # until it stays empty
                moved = old_engine.extract_queued()
                if moved:
                    empty_rounds = 0
                    for r in moved:
                        new_engine.requeue(r)
                    transplanted += len(moved)
                else:
                    empty_rounds += 1
                    time.sleep(0.005)
            try:
                # blocks until active slots finish on the old weights
                old_engine.stop()
            except Exception:  # noqa: BLE001 - swap already committed
                log.exception("%s: draining the old engine after a "
                              "committed swap failed", self.name)
            dt = time.perf_counter() - t0
            _M_SWAPS.labels(model=self.name, outcome="ok").inc()
            _M_SWAP_S.labels(model=self.name).observe(dt)
            log.info("%s: hot-swapped to weights %s in %.2fs "
                     "(%d queued request(s) transplanted)", self.name,
                     new_version, dt, transplanted)
            return {"weights_version": new_version,
                    "transplanted": transplanted,
                    "swap_seconds": round(dt, 3)}
        finally:
            self._swapping.release()

    def request_phase(self, request_id: Optional[str]) -> Optional[str]:
        """Fleet-router hedging gate: where the request is on this
        replica's engine (``"queued"`` / ``"active"`` / ``None``)."""
        eng = self.engine
        return eng.request_phase(request_id) if eng is not None else None

    def cancel_request(self, request_id: Optional[str]) -> bool:
        """Cancel by HTTP-level request id (``:cancel`` route / fleet
        hedge-loser path)."""
        eng = self.engine
        return (eng.cancel_request(request_id)
                if eng is not None else False)

    def _local_health(self) -> dict:
        """Unsupervised readiness (a ServingSupervisor, when watching
        this model, answers instead — with heartbeat/circuit/queue
        detail)."""
        if not self.ready:
            return {"ok": False, "reason": "not loaded"}
        eng = self.engine
        if eng is None or not eng.alive:
            return {"ok": False, "reason": "engine dead"}
        return {"ok": True, "reason": "ok",
                "heartbeat_age_s": round(eng.heartbeat.age, 3),
                "queue_depth": eng.queue_depth(),
                **self.serving_metadata()}

    def serving_metadata(self) -> dict:
        """Rollout metadata carried in every ``/readyz`` verdict (the
        supervisor merges it into its own detail): a fleet probe can
        tell a quantized replica — and which decode kernel it runs —
        from an fp32 one during a rolling restart, instead of
        discovering the mismatch in its logit budget."""
        eng = self.engine
        if eng is None:
            return {}
        meta = {}
        if getattr(eng, "weights_version", None) is not None:
            # content-hash identity of the weights THIS engine serves
            # (engine-scoped: mid-swap the old engine keeps reporting
            # the version that prefilled its slots)
            meta["weights_version"] = eng.weights_version
        return {**meta,
                "kv_dtype": (eng.ecfg.kv_dtype if eng.paged else "fp32"),
                "attn_impl": (eng.ecfg.attn_impl if eng.paged
                              else "dense"),
                # the fleet router learns roles from probe bodies:
                # decode-role replicas take no admission traffic
                # (serve/fleet.py), and a probe can tell a sharded
                # replica from a single-chip one mid-rolling-restart
                "role": eng.ecfg.role,
                "mesh_shards": getattr(eng, "mesh_shards", 1),
                # the latency-offensive knobs, so a probe can tell a
                # chunking/speculating replica mid-rolling-restart
                "prefill_chunk_tokens": eng.ecfg.prefill_chunk_tokens,
                "spec_draft": (eng.draft.kind
                               if getattr(eng, "draft", None) is not None
                               else "none"),
                # flat-batch vs padded multi-program iteration — a
                # probe can tell which replica shape it is hitting
                # mid-rollout of the ragged flag flip
                "ragged": bool(getattr(eng, "_ragged", False))}

    # -- request side ------------------------------------------------------

    def _submit_all(self, prompts: Sequence[str], opts: Mapping[str, Any],
                    deadline: Optional[float] = None,
                    request_id: Optional[str] = None,
                    tenant: Optional[str] = None,
                    api_key: Optional[str] = None,
                    lane: Optional[str] = None) -> list[GenRequest]:
        # Snapshot the engine once: a supervisor restart thread swaps
        # self.engine (briefly to None) concurrently, and a re-read
        # mid-loop would turn that transient into an AttributeError 500
        # instead of a retryable 503.
        engine = self.engine
        if engine is None or not self.ready:
            raise RetryableError("engine stopped")
        tok = self.service.tokenizer
        reqs: list[GenRequest] = []
        try:
            for i, p in enumerate(prompts):
                # one span stream per prompt: the HTTP-level id for a
                # single-instance request, suffixed for multi-instance
                rid = (request_id if request_id and len(prompts) == 1
                       else f"{request_id}-{i}" if request_id else None)
                reqs.append(engine.submit(
                    tok.encode(p),
                    max_new_tokens=max(1, min(int(opts["MAX_NEW_TOKENS"]),
                                              2048)),
                    temperature=float(opts["TEMPERATURE"]),
                    top_k=int(opts["TOP_K"]),
                    top_p=float(opts["TOP_P"]),
                    seed=int(opts["SEED"]) + i,
                    deadline=deadline, request_id=rid,
                    tenant=tenant, api_key=api_key, lane=lane))
        except Exception:  # noqa: BLE001 - cleanup only; re-raised as-is
            for r in reqs:  # don't orphan already-queued siblings
                r.cancel()
            raise
        return reqs

    def _finish(self, req: GenRequest, opts: Mapping[str, Any]) -> dict:
        toks = req.wait(self.engine)
        tok = self.service.tokenizer
        pad = getattr(tok, "pad_token_id", None)
        eos = getattr(tok, "eos_token_id", None)
        kept = [t for t in toks if t != pad and t != eos]
        out_ids = kept
        if opts.get("ECHO_PROMPT"):
            # token-level echo, one decode call — byte-compatible with
            # CausalLMService.generate_outputs for any tokenizer
            out_ids = [t for t in req.prompt_ids
                       if t != pad and t != eos] + kept
        out = {"generated_text": tok.decode(out_ids),
               "tokens_out": len(kept),
               # prefill accounting: what the prompt cost vs what the
               # prefix cache saved (0 unless the paged engine hit) —
               # load_test.py sums these into its outcomes summary
               "prompt_tokens": len(req.prompt_ids),
               "cached_tokens": req.cached_tokens,
               # traffic-plane accounting: how the request was
               # classified and whether QoS preemption touched it —
               # the trace-replay harness groups its per-tenant stats
               # on these
               "tenant": req.tenant,
               "lane": req.lane,
               "preemptions": req.preemptions,
               # how this prediction's KV was stored: "int8" means the
               # tokens came from the quantized arena under its
               # measured logit-error budget, not bitwise fp identity
               "kv_dtype": (self.cfg.kv_dtype if self.cfg.paged
                            else "fp32")}
        # which weights produced these tokens: the request's OWN
        # engine (requeue() re-points it at transplant), so a request
        # finishing on the draining pre-swap engine reports the old
        # version while post-cutover traffic reports the new one
        wv = getattr(req.engine or self.engine, "weights_version", None)
        if wv is not None:
            out["weights_version"] = wv
        if req.first_token_at is not None:
            # client-visible TTFT (load_test reports its distribution
            # and checks it against the server-side histogram),
            # decomposed into queue-wait vs prefill-compute so slow
            # first tokens are attributable (capacity vs chunking)
            out["ttft_s"] = round(req.first_token_at - req.submitted_at, 6)
            if req.admitted_at is not None:
                out["ttft_queue_s"] = round(
                    req.admitted_at - req.submitted_at, 6)
                out["ttft_prefill_s"] = round(
                    req.first_token_at - req.admitted_at, 6)
        return out

    @staticmethod
    def _identity(payload: Mapping[str, Any]) -> dict:
        """Tenant identity off the payload: an explicit ``tenant``
        field, the ``X-API-Key`` value the server stamped as
        ``api_key``, and an optional per-request ``lane`` override —
        resolution itself (key → tenant → lane default) lives in the
        engine's :class:`~kubernetes_cloud_tpu.serve.tenancy.
        TenantScheduler`."""
        return {"tenant": payload.get("tenant"),
                "api_key": payload.get("api_key"),
                "lane": payload.get("lane")}

    def predict(self, payload: Mapping[str, Any]) -> dict:
        prompts = [instance_text(i) for i in parse_instances(payload)]
        opts = self.service.configure_request(payload)
        reqs = self._submit_all(prompts, opts,
                                deadline=request_deadline(payload),
                                request_id=payload.get("request_id"),
                                **self._identity(payload))
        return {"predictions": [self._finish(r, opts) for r in reqs]}

    def completion(self, payload: Mapping[str, Any]) -> dict:
        prompt = payload.get("prompt", "")
        opts = self.service.completion_options(payload)
        req = self._submit_all([prompt], opts,
                               deadline=request_deadline(payload),
                               request_id=payload.get("request_id"),
                               **self._identity(payload))[0]
        return {"completion": self._finish(req, opts)["generated_text"]}


def _draft_service_for(model_dir: str):
    """Build a ``CausalLMService`` over the draft checkpoint dir named
    by ``EngineConfig.spec_draft`` (lazy import — the weights stack is
    only paid when a draft model is actually configured).  The draft
    MUST share the target's tokenizer/vocab: proposals are token ids
    verified by the target, so a vocab mismatch would only ever reject
    (correct, but pure waste)."""
    import os

    from kubernetes_cloud_tpu.serve import lm_service as lms
    from kubernetes_cloud_tpu.weights.tensorstream import read_index

    weights = lms._resolve_weights(model_dir)
    index = read_index(weights)
    cfg = lms._config_from_index(index, weights, None)
    mdir = (model_dir if os.path.isdir(model_dir)
            else os.path.dirname(model_dir))
    return lms.CausalLMService("draft", cfg,
                               tokenizer=lms._tokenizer_for(mdir),
                               weights_path=weights,
                               weights_index=index)


def load_engine_config(model_dir: str) -> EngineConfig:
    """Read continuous-batching knobs from ``model_config.json`` (the
    same file the dynamic batcher reads), ``continuous_batching`` key;
    the traffic plane's tenant table comes from the top-level
    ``tenancy`` key (schema: deploy/README.md "Multi-tenancy & QoS")."""
    import json
    import os

    path = os.path.join(model_dir, "model_config.json")
    if not os.path.exists(path):
        return EngineConfig()
    with open(path) as f:
        raw = json.load(f)
    cb = raw.get("continuous_batching") or {}
    base = EngineConfig()
    return EngineConfig(
        slots=int(cb.get("slots", base.slots)),
        max_len=int(cb.get("max_len", base.max_len)),
        max_queue_size=int(cb.get("max_queue_size", base.max_queue_size)),
        max_admit_per_step=int(cb.get("max_admit_per_step",
                                      base.max_admit_per_step)),
        paged=bool(cb.get("paged", base.paged)),
        page_size=int(cb.get("page_size", base.page_size)),
        num_pages=int(cb.get("num_pages", base.num_pages)),
        attn_impl=str(cb.get("attn_impl", base.attn_impl)),
        kv_dtype=str(cb.get("kv_dtype", base.kv_dtype)),
        flight_records=int(cb.get("flight_records", base.flight_records)),
        role=str(cb.get("role", base.role)),
        decode_slices=int(cb.get("decode_slices", base.decode_slices)),
        prefill_chunk_tokens=int(cb.get("prefill_chunk_tokens",
                                        base.prefill_chunk_tokens)),
        spec_draft=cb.get("spec_draft", base.spec_draft),
        spec_k=int(cb.get("spec_k", base.spec_k)),
        ragged=bool(cb.get("ragged", base.ragged)),
        tenancy=parse_tenancy(raw.get("tenancy")),
    )
