"""ImageNet download Job (``deploy/jobset/imagenet-download-job.yaml``).

The reference fetches ImageNet from Kaggle with the kaggle CLI inside a
Job (``kubeflow/training-operator/resnet50/k8s``); this entrypoint does
the same when the kaggle CLI + ``KAGGLE_USERNAME``/``KAGGLE_KEY`` secret
env are present, and otherwise falls back to a plain URL-list fetch
(``--urls``) through the framework downloader — either way ending with
the ``.ready.txt`` sentinel the trainer Job gates on.
"""

from __future__ import annotations

import argparse
import logging
import os
import shutil
import subprocess
from typing import Optional

from kubernetes_cloud_tpu.data.downloader_cli import (
    download_dataset,
    is_ready,
    mark_ready,
)

log = logging.getLogger(__name__)

KAGGLE_DATASET = "imagenet-object-localization-challenge"


def main(argv: Optional[list] = None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--output", required=True)
    ap.add_argument("--urls", default=None,
                    help="URL-list fallback when kaggle is unavailable")
    ap.add_argument("--competition", default=KAGGLE_DATASET)
    args = ap.parse_args(argv)
    logging.basicConfig(level=logging.INFO)

    if is_ready(args.output):
        log.info("%s already ready", args.output)
        return 0
    os.makedirs(args.output, exist_ok=True)

    kaggle = shutil.which("kaggle")
    if kaggle and os.environ.get("KAGGLE_USERNAME") \
            and os.environ.get("KAGGLE_KEY"):
        log.info("downloading %s via kaggle CLI", args.competition)
        subprocess.run(
            [kaggle, "competitions", "download", "-c", args.competition,
             "-p", args.output], check=True)
        # extract before marking ready: the trainer expects the
        # ImageFolder layout, not archives
        for entry in sorted(os.listdir(args.output)):
            if entry.endswith(".zip"):
                path = os.path.join(args.output, entry)
                log.info("extracting %s", entry)
                shutil.unpack_archive(path, args.output)
                os.remove(path)
        mark_ready(args.output)
        return 0

    if args.urls:
        with open(args.urls) as f:
            urls = [ln.strip() for ln in f if ln.strip()]
        download_dataset(urls, args.output)
        return 0

    raise SystemExit(
        "no kaggle CLI/credentials and no --urls fallback given")


if __name__ == "__main__":  # pragma: no cover - container entry
    import sys

    sys.exit(main())
