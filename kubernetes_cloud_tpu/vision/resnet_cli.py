"""ResNet ImageNet trainer container entrypoint
(``deploy/jobset/resnet50-imagenet-jobset.yaml``).

The reference trains resnet50 two ways — torchrun DDP under PyTorchJob
and Horovod under MPIJob (``kubeflow/training-operator/resnet50/``);
here both collapse into one SPMD program launched identically on every
JobSet worker: batch axis sharded over the mesh, gradient allreduce
emitted by XLA, sync-BN for free.  Flag names follow the manifest.
"""

from __future__ import annotations

import argparse
import logging
import os
from typing import Optional

log = logging.getLogger(__name__)


def _bool(v: str) -> bool:
    return str(v).strip().lower() in ("1", "true", "yes", "on")


def build_parser() -> argparse.ArgumentParser:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--data-dir", required=True,
                    help="ImageNet-folder layout: <root>/{train,val}/<cls>/")
    ap.add_argument("--epochs", type=int, default=90)
    ap.add_argument("--batch-size", type=int, default=256,
                    help="global batch, split over the data axis")
    ap.add_argument("--base-lr", type=float, default=0.1)
    ap.add_argument("--momentum", type=float, default=0.9)
    ap.add_argument("--weight-decay", type=float, default=1e-4)
    ap.add_argument("--label-smoothing", type=float, default=0.0,
                    help="accepted for manifest parity (smoothing off "
                         "matches the reference recipe)")
    ap.add_argument("--bf16", type=_bool, default=True)
    ap.add_argument("--eval-every", type=int, default=1)
    ap.add_argument("--checkpoint-dir", default="./checkpoints")
    ap.add_argument("--depth", type=int, default=50)
    ap.add_argument("--image-size", type=int, default=224)
    ap.add_argument("--num-classes", type=int, default=0,
                    help="0 = infer from the train folder")
    ap.add_argument("--steps-per-epoch", type=int, default=0,
                    help="0 = full epoch; >0 truncates (smoke runs)")
    return ap


def main(argv: Optional[list] = None) -> int:
    import dataclasses

    args = build_parser().parse_args(argv)
    logging.basicConfig(level=logging.INFO)

    from kubernetes_cloud_tpu.core.distributed import (
        is_primary,
        maybe_initialize_distributed,
    )

    maybe_initialize_distributed()

    import itertools

    import jax
    import jax.numpy as jnp

    from kubernetes_cloud_tpu.core.mesh import MeshSpec, build_mesh
    from kubernetes_cloud_tpu.data.images import ImageFolderDataset
    from kubernetes_cloud_tpu.models.vision.resnet import ResNetConfig
    from kubernetes_cloud_tpu.train.vision_trainer import (
        VisionTrainConfig,
        evaluate,
        init_vision_state,
        make_eval_step,
        make_vision_train_step,
        save_classifier,
        train_epoch,
    )

    mesh = build_mesh(MeshSpec(data=-1))
    world = jax.process_count()
    # n_data counts GLOBAL batch shards (build_mesh spans all processes'
    # devices), so it is also the lr linear-scaling factor — do not
    # multiply by world again.
    n_data = mesh.shape["data"] * mesh.shape["fsdp"]
    if args.batch_size % n_data or args.batch_size % world:
        raise SystemExit(
            f"--batch-size {args.batch_size} must divide both the "
            f"{n_data} batch shards and {world} hosts")
    local_bs = args.batch_size // world

    train_ds = ImageFolderDataset(os.path.join(args.data_dir, "train"),
                                  image_size=args.image_size, train=True)
    val_dir = os.path.join(args.data_dir, "val")
    val_ds = (ImageFolderDataset(val_dir, image_size=args.image_size,
                                 train=False)
              if os.path.isdir(val_dir) else None)
    n_classes = args.num_classes or len(train_ds.class_to_idx)

    model_cfg = ResNetConfig(
        depth=args.depth, num_classes=n_classes,
        dtype=jnp.bfloat16 if args.bf16 else jnp.float32)
    steps_per_epoch = (args.steps_per_epoch
                       or max(1, (len(train_ds) // world) // local_bs))
    # lr x data-parallel size: the reference's linear scaling rule
    # (resnet50_pytorch.py:103-106) — expressed via world_scale
    tcfg = VisionTrainConfig(
        learning_rate=args.base_lr, momentum=args.momentum,
        weight_decay=args.weight_decay, epochs=args.epochs,
        steps_per_epoch=steps_per_epoch, world_scale=n_data)
    state = init_vision_state(model_cfg, tcfg, jax.random.key(0), mesh)
    step = jax.jit(make_vision_train_step(model_cfg, tcfg),
                   donate_argnums=0)
    eval_step = jax.jit(make_eval_step(model_cfg))

    for epoch in range(args.epochs):
        batches = train_ds.batches(
            local_bs, epoch=epoch, process_index=jax.process_index(),
            process_count=world)
        if args.steps_per_epoch:
            batches = itertools.islice(batches, args.steps_per_epoch)
        state, summary = train_epoch(step, state, batches, mesh=mesh)
        if is_primary():
            log.info("epoch %d: loss=%.4f %.1f samples/s", epoch,
                     summary["loss"], summary["samples_per_second"])
        if val_ds is not None and (epoch + 1) % max(args.eval_every,
                                                   1) == 0:
            metrics = evaluate(
                eval_step, state,
                val_ds.batches(local_bs, epoch=0,
                               process_index=jax.process_index(),
                               process_count=world,
                               drop_remainder=False),
                mesh=mesh)
            if is_primary():
                log.info("epoch %d eval: top1=%.4f top5=%.4f", epoch,
                         metrics.get("top1", 0), metrics.get("top5", 0))

    if is_primary():
        final = save_classifier(
            os.path.join(args.checkpoint_dir, "final"), model_cfg, state)
        log.info("saved %s", final)
    return 0


if __name__ == "__main__":  # pragma: no cover - container entry
    import sys

    sys.exit(main())
