"""Executable Argo-style workflow orchestration.

The reference repo's identity is "five primitives composed by a workflow
DAG" (SURVEY §1); this package is the engine that actually executes that
composition, locally (subprocess over the in-tree CLIs) or in-cluster
(``batch/v1`` Jobs via the stdlib k8s client):

* :mod:`.spec` — typed ``WorkflowSpec``/``Step`` with Argo's
  ``retryStrategy``, ``when``, and parameter templating;
* :mod:`.engine` — concurrent topological scheduling, retry with
  backoff+jitter, persisted-state + ``.ready.txt``-sentinel resume;
* :mod:`.executors` — local subprocess and Kubernetes Job executors;
* :mod:`.events` — JSONL step-event log (start/finish/retry/duration);
* :mod:`.argo_import` — loads the shipped ``deploy/`` Argo manifests
  into executable specs;
* :mod:`.pipelines` — canned ``finetune-and-serve`` end-to-end DAG;
* :mod:`.cli` — ``python -m kubernetes_cloud_tpu.workflow``.
"""

from kubernetes_cloud_tpu.workflow.engine import WorkflowRun, load_state
from kubernetes_cloud_tpu.workflow.spec import (
    RetryStrategy,
    SpecError,
    Step,
    TemplateError,
    WorkflowSpec,
    artifact_complete,
    evaluate_when,
    render,
)

__all__ = [
    "RetryStrategy",
    "SpecError",
    "Step",
    "TemplateError",
    "WorkflowRun",
    "WorkflowSpec",
    "artifact_complete",
    "evaluate_when",
    "load_state",
    "render",
]
