"""Workflow runner CLI — the local ``argo submit``.

::

    python -m kubernetes_cloud_tpu.workflow run finetune-and-serve
    python -m kubernetes_cloud_tpu.workflow run spec.json -p run_name=r1
    python -m kubernetes_cloud_tpu.workflow run \
        deploy/finetuner-workflow/finetune-workflow.yaml -p run_name=r1
    python -m kubernetes_cloud_tpu.workflow import \
        deploy/finetuner-workflow/finetune-workflow.yaml -o spec.json
    python -m kubernetes_cloud_tpu.workflow status --workdir runs/...
    python -m kubernetes_cloud_tpu.workflow list

``run`` targets a canned pipeline name, a spec JSON file, or an Argo
Workflow YAML (imported on the fly).  ``-p key=value`` mirrors ``argo
submit -p``; reruns over the same ``--workdir`` resume, skipping steps
whose state or artifacts are already complete.
"""

from __future__ import annotations

import argparse
import json
import os
from typing import Optional, Sequence

from kubernetes_cloud_tpu.workflow import pipelines
from kubernetes_cloud_tpu.workflow.engine import STATE_FILE, WorkflowRun, load_state
from kubernetes_cloud_tpu.workflow.events import EVENT_LOG, read_events, summarize
from kubernetes_cloud_tpu.workflow.spec import SpecError, WorkflowSpec


def _parse_overrides(pairs: Sequence[str]) -> dict:
    out = {}
    for pair in pairs or []:
        if "=" not in pair:
            raise SpecError(f"-p expects key=value, got {pair!r}")
        key, value = pair.split("=", 1)
        out[key.strip()] = value
    return out


def _load_target(target: str, overrides=None) -> WorkflowSpec:
    if target in pipelines.CANNED:
        return pipelines.canned(target)
    if target.endswith((".yaml", ".yml")):
        from kubernetes_cloud_tpu.workflow.argo_import import (
            load_argo_workflow,
        )

        # -p overrides shape withParam fan-outs, fixed at import time
        return load_argo_workflow(target, overrides)
    if target.endswith(".json"):
        with open(target) as fh:
            return WorkflowSpec.from_dict(json.load(fh))
    raise SpecError(
        f"unknown target {target!r}: expected a canned pipeline "
        f"({sorted(pipelines.CANNED)}), a .json spec, or an Argo .yaml")


def _print_summary(result: dict) -> None:
    width = max((len(n) for n in result["steps"]), default=4)
    print(f"workflow: {result['status']}  ({result['workdir']})")
    for name, status in result["steps"].items():
        print(f"  {name:<{width}}  {status}")


def cmd_run(args) -> int:
    overrides = _parse_overrides(args.param)
    spec = _load_target(args.target, overrides)
    workdir = args.workdir or os.path.join(
        "workflow-runs", spec.name)
    os.makedirs(workdir, exist_ok=True)
    if "workdir" in spec.parameters and "workdir" not in overrides:
        # canned pipelines root their artifacts in the run directory
        overrides["workdir"] = os.path.abspath(workdir)
    executors = None
    if args.executor == "k8s":
        from kubernetes_cloud_tpu.deploy.k8s_client import K8sClient
        from kubernetes_cloud_tpu.workflow.executors import K8sJobExecutor

        client = K8sClient(retries=3)
        executors = {"local": K8sJobExecutor(client,
                                             namespace=args.namespace),
                     "k8s": K8sJobExecutor(client,
                                           namespace=args.namespace)}
    run = WorkflowRun(spec, workdir, params=overrides,
                      executors=executors, max_workers=args.max_workers)
    result = run.run(resume=not args.no_resume)
    _print_summary(result)
    return 0 if result["status"] == "succeeded" else 1


def cmd_import(args) -> int:
    from kubernetes_cloud_tpu.workflow.argo_import import load_argo_workflow

    spec = load_argo_workflow(args.path)
    order = spec.validate()
    if args.output:
        with open(args.output, "w") as fh:
            json.dump(spec.to_dict(), fh, indent=1)
        print(f"wrote {args.output}")
    print(f"workflow {spec.name}: {len(spec.steps)} steps, "
          f"{len(spec.parameters)} parameters")
    for name in order:
        step = spec.step(name)
        deps = f" <- {','.join(step.deps)}" if step.deps else ""
        cond = f"  when: {step.when}" if step.when else ""
        print(f"  {name}{deps}{cond}")
    return 0


def cmd_status(args) -> int:
    state = load_state(args.workdir)
    if not state:
        print(f"no {STATE_FILE} under {args.workdir}")
        return 1
    rollup = summarize(read_events(os.path.join(args.workdir, EVENT_LOG)))
    print(f"workflow: {state.get('workflow')}")
    width = max((len(n) for n in state.get("steps", {})), default=4)
    for name, info in state.get("steps", {}).items():
        extra = rollup.get(name, {})
        attempts = info.get("attempts", 0)
        dur = extra.get("duration", 0.0)
        print(f"  {name:<{width}}  {info.get('status', '?'):<16} "
              f"attempts={attempts} duration={dur:.1f}s")
    return 0


def cmd_list(_args) -> int:
    for name in sorted(pipelines.CANNED):
        print(name)
    return 0


def build_parser() -> argparse.ArgumentParser:
    ap = argparse.ArgumentParser(
        prog="python -m kubernetes_cloud_tpu.workflow",
        description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter)
    sub = ap.add_subparsers(dest="cmd", required=True)

    run = sub.add_parser("run", help="execute a pipeline / spec / manifest")
    run.add_argument("target",
                     help="canned pipeline name, spec.json, or Argo .yaml")
    run.add_argument("-p", "--param", action="append", default=[],
                     metavar="KEY=VALUE", help="parameter override")
    run.add_argument("--workdir", default=None,
                     help="state/artifact dir (default workflow-runs/<name>)")
    run.add_argument("--max-workers", type=int, default=4)
    run.add_argument("--no-resume", action="store_true",
                     help="ignore prior state and artifacts")
    run.add_argument("--executor", choices=("local", "k8s"),
                     default="local")
    run.add_argument("--namespace", default="default")
    run.set_defaults(fn=cmd_run)

    imp = sub.add_parser("import", help="Argo YAML -> executable spec")
    imp.add_argument("path")
    imp.add_argument("-o", "--output", default=None,
                     help="write the spec as JSON")
    imp.set_defaults(fn=cmd_import)

    status = sub.add_parser("status", help="inspect a run directory")
    status.add_argument("--workdir", required=True)
    status.set_defaults(fn=cmd_status)

    lst = sub.add_parser("list", help="canned pipelines")
    lst.set_defaults(fn=cmd_list)
    return ap


def main(argv: Optional[Sequence[str]] = None) -> int:
    args = build_parser().parse_args(argv)
    try:
        return args.fn(args)
    except (SpecError, FileNotFoundError) as e:
        print(f"error: {e}")
        return 2


if __name__ == "__main__":
    import sys

    sys.exit(main())
