"""DAG engine: topological scheduling, concurrent independent steps,
retry with backoff, and preemption-safe resume.

What Argo's workflow-controller does for the reference's manifests, as a
local library: steps whose dependencies are satisfied run concurrently in
a thread pool (each step is a subprocess or k8s Job — threads only wait);
failures retry per the step's :class:`~.spec.RetryStrategy` with
exponential backoff + jitter; a failure fail-fasts scheduling (running
branches drain, nothing new starts).

Resume is stricter than the reference's restart hack
(``gpt-neox/04-finetune-workflow.yaml:420-425``): every state transition
is persisted to ``state.json`` (atomic rename), and on rerun a step is
skipped when its prior state is terminal-successful **or** its declared
artifacts are already sentinel-complete (``.ready.txt`` contract) — so a
SIGKILL'd run re-executes only the interrupted tail.  Every attempt is
recorded in the JSONL step-event log (:mod:`.events`).
"""

from __future__ import annotations

import concurrent.futures as cf
import json
import os
import random
import time
from typing import Any, Mapping, Optional

from kubernetes_cloud_tpu import obs
from kubernetes_cloud_tpu.workflow.events import EVENT_LOG, WorkflowEventLog
from kubernetes_cloud_tpu.workflow.executors import LocalExecutor, StepResult
from kubernetes_cloud_tpu.workflow.spec import (
    Step,
    WorkflowSpec,
    artifact_complete,
    evaluate_when,
    render,
)

STATE_FILE = "state.json"

PENDING = "pending"
RUNNING = "running"
SUCCEEDED = "succeeded"
FAILED = "failed"
SKIPPED = "skipped"
UPSTREAM_FAILED = "upstream_failed"

_DONE_OK = (SUCCEEDED, SKIPPED)
_TERMINAL_BAD = (FAILED, UPSTREAM_FAILED)

# Orchestrator metric families — the same signals the JSONL event log
# records, as a scrapeable surface (Argo's workflow-controller exposes
# the equivalent ones).  Step names are a bounded label space: they
# come from authored WorkflowSpecs, not request traffic.
_M_STEP_S = obs.histogram(
    "kct_workflow_step_seconds", "Step execution wall time.",
    ("workflow", "step"),
    buckets=(0.1, 0.5, 1, 5, 15, 60, 300, 1800, 7200))
_M_RETRIES = obs.counter(
    "kct_workflow_step_retries_total", "Step retry attempts.",
    ("workflow", "step"))
_M_TRANSITIONS = obs.counter(
    "kct_workflow_transitions_total",
    "Step state transitions by resulting state.", ("workflow", "state"))


def load_state(workdir: str) -> dict:
    path = os.path.join(workdir, STATE_FILE)
    if not os.path.exists(path):
        return {}
    try:
        with open(path) as fh:
            return json.load(fh)
    except (json.JSONDecodeError, OSError):
        # torn write can't happen (atomic rename), but a hand-edited or
        # foreign file shouldn't wedge the engine
        return {}


class WorkflowRun:
    """One execution (or resumption) of a :class:`WorkflowSpec`."""

    def __init__(self, spec: WorkflowSpec, workdir: str, *,
                 params: Optional[Mapping[str, str]] = None,
                 executors: Optional[Mapping[str, Any]] = None,
                 max_workers: int = 4,
                 sleep=time.sleep,
                 rng: Optional[random.Random] = None):
        self.spec = spec
        self.topo = spec.validate()
        self.workdir = workdir
        os.makedirs(workdir, exist_ok=True)
        self.params = spec.resolve_parameters(params)
        self.executors = {"local": LocalExecutor()}
        self.executors.update(executors or {})
        self.max_workers = max(1, max_workers)
        self._sleep = sleep
        self._rng = rng or random.Random()
        self.events = WorkflowEventLog(os.path.join(workdir, EVENT_LOG))
        self.run_id = ""  # assigned (or restored) by run()
        self._status: dict = {}
        self._outputs: dict = {}
        self._attempts: dict = {}

    # -- state persistence -------------------------------------------------

    def _save_state(self) -> None:
        state = {
            "workflow": self.spec.name,
            "run_id": self.run_id,
            "params": self.params,
            "steps": {
                name: {"status": status,
                       "attempts": self._attempts.get(name, 0),
                       "output": self._outputs.get(name, "")}
                for name, status in self._status.items()},
        }
        path = os.path.join(self.workdir, STATE_FILE)
        tmp = path + ".tmp"
        with open(tmp, "w") as fh:
            json.dump(state, fh, indent=1)
        os.replace(tmp, path)

    # -- rendering ---------------------------------------------------------

    def _rendered(self, step: Step) -> Step:
        """Template-expand a step against parameters + upstream outputs at
        submission time (outputs of deps exist by then)."""
        import dataclasses

        env = {k: render(str(v), self.params, self._outputs)
               for k, v in step.env.items()}
        # per-run identity for executors that name external resources
        # (K8sJobExecutor Job names must not collide across runs)
        env.setdefault("WORKFLOW_RUN_ID", self.run_id)
        return dataclasses.replace(
            step,
            command=[render(str(a), self.params, self._outputs)
                     for a in step.command],
            env=env,
            artifacts=self._artifacts(step),
            manifest=(render(step.manifest, self.params, self._outputs)
                      if step.manifest else ""),
        )

    def _artifacts(self, step: Step) -> list:
        return [render(str(a), self.params, self._outputs)
                for a in step.artifacts]

    # -- execution ---------------------------------------------------------

    def _run_step(self, step: Step) -> StepResult:
        executor = self.executors.get(step.executor)
        if executor is None:
            # e.g. a resource-template step from an imported manifest under
            # --executor local: fail the step with a pointer, not the engine
            msg = (f"no {step.executor!r} executor registered "
                   f"(have: {sorted(self.executors)}); "
                   f"run with --executor k8s for resource steps")
            self._attempts[step.name] = 1
            self.events.emit("step_finish", step.name, status=FAILED,
                             rc=-1, stderr=msg)
            return StepResult(rc=-1, stderr=msg)
        try:
            rendered = self._rendered(step)
        except Exception as e:  # noqa: BLE001 - template/spec fault
            self._attempts[step.name] = 1
            self.events.emit("step_finish", step.name, status=FAILED,
                             rc=-1, stderr=f"{type(e).__name__}: {e}")
            return StepResult(rc=-1, stderr=f"{type(e).__name__}: {e}")
        attempt = 0
        while True:
            self._attempts[step.name] = attempt + 1
            self.events.emit("step_start", step.name, attempt=attempt,
                             command=rendered.command[:8])
            try:
                result = executor.execute(rendered, timeout=step.timeout,
                                          attempt=attempt)
            except Exception as e:  # noqa: BLE001 - executor/infra fault
                # must not escape the worker: an uncaught exception would
                # abort run() with half-written state and no finish event
                result = StepResult(rc=-1, stderr=f"{type(e).__name__}: {e}")
            if result.ok:
                self.events.emit("step_finish", step.name, status=SUCCEEDED,
                                 attempt=attempt, rc=result.rc,
                                 duration=round(result.duration, 4))
                self._observe_step(step.name, result.duration)
                return result
            if attempt >= step.retry.limit:
                self.events.emit("step_finish", step.name, status=FAILED,
                                 attempt=attempt, rc=result.rc,
                                 duration=round(result.duration, 4),
                                 stderr=result.stderr[-2000:])
                self._observe_step(step.name, result.duration)
                return result
            delay = step.retry.delay(attempt, self._rng)
            self.events.emit("step_retry", step.name, attempt=attempt,
                             rc=result.rc, delay=round(delay, 4))
            _M_RETRIES.labels(workflow=self.spec.name,
                              step=step.name).inc()
            self._sleep(delay)
            attempt += 1

    def _observe_step(self, step_name: str, duration: float) -> None:
        _M_STEP_S.labels(workflow=self.spec.name,
                         step=step_name).observe(duration)

    def _transition(self, name: str, state: str) -> None:
        self._status[name] = state
        _M_TRANSITIONS.labels(workflow=self.spec.name, state=state).inc()

    def _skip(self, name: str, reason: str) -> None:
        self._transition(name, SKIPPED)
        # a skipped step has no captured stdout; downstream
        # {{steps.<name>.outputs.result}} references resolve to ""
        self._outputs.setdefault(name, "")
        self.events.emit("step_skipped", name, reason=reason)

    def _deps_state(self, step: Step) -> str:
        states = [self._status[d] for d in step.deps]
        if any(s in _TERMINAL_BAD for s in states):
            return "failed"
        if all(s in _DONE_OK for s in states):
            return "ready"
        return "waiting"

    def run(self, resume: bool = True) -> dict:
        import uuid

        prior = (load_state(self.workdir) or {}) if resume else {}
        # prior state only resumes the *same* run: same workflow AND same
        # resolved parameters — a rerun with different -p overrides must
        # re-execute (its artifacts land elsewhere), relying only on
        # sentinel-complete artifact gates for skipping
        same_run = (prior.get("workflow") == self.spec.name
                    and prior.get("params") == self.params)
        prior_steps = prior.get("steps", {}) if same_run else {}
        self.run_id = ((same_run and prior.get("run_id"))
                       or uuid.uuid4().hex[:8])

        self._status = {s.name: PENDING for s in self.spec.steps}
        for s in self.spec.steps:
            carried = prior_steps.get(s.name, {})
            if carried.get("status") in _DONE_OK:
                self._status[s.name] = carried["status"]
                self._outputs[s.name] = carried.get("output", "")
                self._attempts[s.name] = carried.get("attempts", 0)
                self.events.emit("step_skipped", s.name, reason="prior-state")

        self.events.emit("workflow_start", workflow=self.spec.name,
                         resumed=bool(prior_steps))
        self._save_state()

        failed_fast = False
        futures: dict = {}
        with cf.ThreadPoolExecutor(max_workers=self.max_workers) as pool:
            while True:
                progressed = True
                while progressed and not failed_fast:
                    progressed = False
                    for name in self.topo:
                        if self._status[name] != PENDING or name in futures:
                            continue
                        step = self.spec.step(name)
                        deps = self._deps_state(step)
                        if deps == "failed":
                            self._transition(name, UPSTREAM_FAILED)
                            self.events.emit("step_finish", name,
                                             status=UPSTREAM_FAILED)
                            progressed = True
                        elif deps == "ready":
                            try:
                                gated = not evaluate_when(
                                    step.when, self.params, self._outputs)
                                complete = not gated and step.artifacts \
                                    and all(artifact_complete(a)
                                            for a in self._artifacts(step))
                            except Exception as e:  # noqa: BLE001
                                # bad when/artifact template: fail the step,
                                # not the engine
                                self._transition(name, FAILED)
                                self.events.emit(
                                    "step_finish", name, status=FAILED,
                                    rc=-1,
                                    stderr=f"{type(e).__name__}: {e}")
                                failed_fast = True
                                progressed = True
                                break
                            if gated:
                                self._skip(name, "when-false")
                                progressed = True
                            elif complete:
                                # preemption-safe resume: outputs already on
                                # disk from a killed prior run
                                self._skip(name, "sentinel-complete")
                                progressed = True
                            else:
                                self._transition(name, RUNNING)
                                futures[name] = pool.submit(
                                    self._run_step, step)
                    if progressed:
                        self._save_state()

                if not futures:
                    break
                done, _ = cf.wait(futures.values(),
                                  return_when=cf.FIRST_COMPLETED)
                for name in [n for n, f in futures.items() if f in done]:
                    result = futures.pop(name).result()
                    if result.ok:
                        self._transition(name, SUCCEEDED)
                        self._outputs[name] = result.output
                    else:
                        self._transition(name, FAILED)
                        failed_fast = True
                self._save_state()

        # fail-fast stopped scheduling; steps downstream of a failure are
        # terminally unreachable (mark them), while pending steps whose
        # deps all succeeded stay pending — a rerun resumes exactly there
        changed = True
        while changed:
            changed = False
            for name in self.topo:
                if self._status[name] != PENDING:
                    continue
                if self._deps_state(self.spec.step(name)) == "failed":
                    self._transition(name, UPSTREAM_FAILED)
                    self.events.emit("step_finish", name,
                                     status=UPSTREAM_FAILED)
                    changed = True

        ok = all(s in _DONE_OK for s in self._status.values())
        status = SUCCEEDED if ok else FAILED
        self.events.emit("workflow_finish", status=status,
                         steps=dict(self._status))
        self._save_state()
        self.events.close()
        return {"status": status, "steps": dict(self._status),
                "outputs": dict(self._outputs), "workdir": self.workdir}
