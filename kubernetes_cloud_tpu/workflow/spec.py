"""Typed workflow model — the Argo Workflow surface as plain dataclasses.

The reference composes its five primitives (PVC → downloader Job →
tokenizer → trainer → InferenceService) with an Argo Workflow
(``deploy/finetuner-workflow/finetune-workflow.yaml``): step dependencies,
``retryStrategy``, 56 ``{{workflow.parameters.x}}`` parameters, ``when``
conditions, and sprig expressions.  This module is the executable spec
those manifests import into (:mod:`.argo_import`) and the engine
(:mod:`.engine`) schedules:

* :class:`RetryStrategy` — Argo's ``limit`` plus exponential backoff with
  jitter (the reference relies on bare ``limit: 1``; preemptible TPU
  slices need real backoff);
* :class:`Step` — argv + deps + retry + timeout + artifact gates on the
  existing ``.ready.txt`` sentinel contract (``weights/checkpoint.py``);
* :class:`WorkflowSpec` — parameters + DAG with cycle/unknown-dep
  validation and a topological order;
* :func:`render` / :func:`evaluate_when` — Argo-compatible
  ``{{workflow.parameters.x}}`` / ``{{steps.x.outputs.result}}``
  templating, a safe subset of ``{{=sprig...}}`` expressions, and the
  ``when`` condition grammar (``==``/``!=``/``&&``/``||``).
"""

from __future__ import annotations

import ast
import dataclasses
import random
import re
from types import SimpleNamespace
from typing import Any, Mapping, Optional

#: completion sentinel written next to finished artifacts; must stay in
#: sync with ``weights.checkpoint.READY_SENTINEL`` (kept literal here so
#: importing the spec never drags in orbax — tests assert the equality).
READY_SENTINEL = ".ready.txt"


class SpecError(ValueError):
    """Structural problem in a workflow spec (cycle, unknown dep, ...)."""


class TemplateError(ValueError):
    """Unresolvable ``{{...}}`` reference."""


# ---------------------------------------------------------------------------
# templating


_TEMPLATE_RE = re.compile(r"\{\{(.+?)\}\}")
_STEP_OUT_RE = re.compile(r"^steps\.([\w.-]+)\.outputs\.result$")
_TERNARY_RE = re.compile(r"^(?P<cond>[^?]+)\?(?P<then>[^:]+):(?P<else>.+)$")


class _Sprig:
    """The sprig functions the shipped manifests actually use."""

    @staticmethod
    def replace(old: str, new: str, s: str) -> str:
        return s.replace(old, new)

    @staticmethod
    def default(default: Any, value: Any = "") -> Any:
        return value if value not in ("", None) else default

    @staticmethod
    def trim(s: str) -> str:
        return s.strip()

    @staticmethod
    def lower(s: str) -> str:
        return s.lower()

    @staticmethod
    def upper(s: str) -> str:
        return s.upper()


_ALLOWED_NODES = (
    ast.Expression, ast.Name, ast.Attribute, ast.Constant, ast.Load,
    ast.BinOp, ast.Add, ast.Compare, ast.Eq, ast.NotEq, ast.IfExp,
    ast.Call, ast.BoolOp, ast.And, ast.Or, ast.UnaryOp, ast.Not,
)


def _eval_expression(expr: str, params: Mapping[str, str]) -> str:
    """Evaluate an Argo ``{{=...}}`` expression over the parameter dict.

    Supports the subset the shipped manifests use: ``sprig.replace``,
    ``sprig.default``, string ``+`` concatenation, ``==``/``!=``, and the
    ``cond ? a : b`` ternary — validated against an AST whitelist, never
    raw ``eval`` of arbitrary code."""
    m = _TERNARY_RE.match(expr)
    if m:
        expr = (f"({m.group('then').strip()}) if ({m.group('cond').strip()})"
                f" else ({m.group('else').strip()})")
    try:
        tree = ast.parse(expr, mode="eval")
    except SyntaxError as e:
        raise TemplateError(f"bad expression {expr!r}: {e}") from e
    for node in ast.walk(tree):
        if not isinstance(node, _ALLOWED_NODES):
            raise TemplateError(
                f"disallowed construct {type(node).__name__} in {expr!r}")
        if isinstance(node, ast.Call):
            fn = node.func
            if not (isinstance(fn, ast.Attribute)
                    and isinstance(fn.value, ast.Name)
                    and fn.value.id == "sprig"):
                raise TemplateError(f"only sprig.* calls allowed: {expr!r}")
    ns = {
        "sprig": _Sprig,
        "workflow": SimpleNamespace(
            parameters=SimpleNamespace(**dict(params))),
    }
    try:
        out = eval(compile(tree, "<workflow-template>", "eval"),  # noqa: S307
                   {"__builtins__": {}}, ns)
    except AttributeError as e:
        raise TemplateError(f"unknown reference in {expr!r}: {e}") from e
    return str(out)


def render(text: str, params: Mapping[str, str],
           step_outputs: Optional[Mapping[str, str]] = None,
           strict: bool = True) -> str:
    """Expand ``{{workflow.parameters.x}}``, ``{{steps.s.outputs.result}}``
    and ``{{=expr}}`` templates in ``text`` (Argo semantics: parameters are
    strings)."""

    def _sub(m: re.Match) -> str:
        inner = m.group(1).strip()
        if inner.startswith("="):
            return _eval_expression(inner[1:].strip(), params)
        if inner.startswith("workflow.parameters."):
            key = inner[len("workflow.parameters."):]
            if key in params:
                value = params[key]
                if value is None:
                    raise TemplateError(f"parameter {key!r} has no value")
                return str(value)
            if strict:
                raise TemplateError(f"unknown workflow parameter {key!r}")
            return m.group(0)
        out = _STEP_OUT_RE.match(inner)
        if out:
            name = out.group(1)
            if step_outputs is not None and name in step_outputs:
                return str(step_outputs[name])
            if strict:
                raise TemplateError(f"no recorded output for step {name!r}")
            return m.group(0)
        if strict:
            raise TemplateError(f"unsupported template {m.group(0)!r}")
        return m.group(0)

    return _TEMPLATE_RE.sub(_sub, text)


_TRUTHY = {"true", "t", "yes", "y", "on", "1"}


def _atom(token: str) -> str:
    token = token.strip()
    if len(token) >= 2 and token[0] == token[-1] and token[0] in "'\"":
        return token[1:-1]
    return token


def evaluate_when(cond: str, params: Mapping[str, str],
                  step_outputs: Optional[Mapping[str, str]] = None) -> bool:
    """Argo ``when`` grammar over rendered text: ``==``/``!=`` comparisons
    of (possibly quoted) atoms combined with ``&&`` and ``||`` (``&&``
    binds tighter, as in Argo's govaluate)."""
    if not cond or not cond.strip():
        return True
    rendered = render(cond, params, step_outputs)

    def _compare(term: str) -> bool:
        if "!=" in term:
            lhs, rhs = term.split("!=", 1)
            return _atom(lhs) != _atom(rhs)
        if "==" in term:
            lhs, rhs = term.split("==", 1)
            return _atom(lhs) == _atom(rhs)
        return _atom(term).lower() in _TRUTHY

    return any(
        all(_compare(term) for term in clause.split("&&"))
        for clause in rendered.split("||"))


# ---------------------------------------------------------------------------
# model


@dataclasses.dataclass
class RetryStrategy:
    """Argo ``retryStrategy`` with the backoff the reference leaves out.

    ``limit`` is the number of *retries* (Argo semantics: total attempts =
    limit + 1).  Delay before retry ``n`` (0-based) is
    ``min(backoff * factor**n, max_backoff) * (1 + jitter * U[0,1))``."""

    limit: int = 0
    backoff: float = 1.0
    factor: float = 2.0
    max_backoff: float = 60.0
    jitter: float = 0.25

    def delay(self, attempt: int,
              rng: Optional[random.Random] = None) -> float:
        base = min(self.backoff * self.factor ** attempt, self.max_backoff)
        return base * (1.0 + self.jitter * (rng or random).random())


@dataclasses.dataclass
class Step:
    """One node of the DAG.

    ``command`` is the templated argv the executor runs (the package's own
    CLIs for the local executor; the container command for the k8s Job
    executor).  ``artifacts`` are paths gating resume: a directory is
    complete when it holds the ``.ready.txt`` sentinel, a file when it
    exists — a step whose artifacts are all complete is skipped on rerun
    (preemption-safe resume, SURVEY §5.3)."""

    name: str
    command: list = dataclasses.field(default_factory=list)
    deps: list = dataclasses.field(default_factory=list)
    retry: RetryStrategy = dataclasses.field(default_factory=RetryStrategy)
    timeout: Optional[float] = None
    artifacts: list = dataclasses.field(default_factory=list)
    env: dict = dataclasses.field(default_factory=dict)
    when: str = ""
    executor: str = "local"
    image: str = ""
    manifest: str = ""  # raw resource-template manifest (k8s apply steps)

    def validate(self) -> None:
        if not self.name:
            raise SpecError("step with empty name")
        if not self.command and not self.manifest:
            raise SpecError(f"step {self.name!r} has no command or manifest")


@dataclasses.dataclass
class WorkflowSpec:
    """Parameters + step DAG.  ``parameters`` maps name → default value;
    ``None`` marks a required parameter (Argo parameters without
    ``value:``)."""

    name: str
    steps: list = dataclasses.field(default_factory=list)
    parameters: dict = dataclasses.field(default_factory=dict)

    def step(self, name: str) -> Step:
        for s in self.steps:
            if s.name == name:
                return s
        raise KeyError(name)

    def validate(self) -> list:
        """Cycle / duplicate / unknown-dep checks; returns a topological
        order of step names (Kahn)."""
        names = [s.name for s in self.steps]
        if len(set(names)) != len(names):
            dupes = sorted({n for n in names if names.count(n) > 1})
            raise SpecError(f"duplicate step names: {dupes}")
        known = set(names)
        for s in self.steps:
            s.validate()
            for d in s.deps:
                if d not in known:
                    raise SpecError(
                        f"step {s.name!r} depends on unknown step {d!r}")
        indeg = {s.name: len(set(s.deps)) for s in self.steps}
        children: dict = {n: [] for n in names}
        for s in self.steps:
            for d in set(s.deps):
                children[d].append(s.name)
        order = [n for n in names if indeg[n] == 0]
        seen = list(order)
        while order:
            n = order.pop(0)
            for c in children[n]:
                indeg[c] -= 1
                if indeg[c] == 0:
                    order.append(c)
                    seen.append(c)
        if len(seen) != len(names):
            stuck = sorted(set(names) - set(seen))
            raise SpecError(f"dependency cycle involving: {stuck}")
        return seen

    def resolve_parameters(self,
                           overrides: Optional[Mapping[str, str]] = None
                           ) -> dict:
        """Defaults + overrides; rejects unknown overrides and missing
        required parameters (mirrors ``argo submit -p`` behavior)."""
        params = dict(self.parameters)
        for key, value in (overrides or {}).items():
            if key not in params:
                raise SpecError(f"unknown parameter {key!r} "
                                f"(spec has: {sorted(params)})")
            params[key] = value
        missing = sorted(k for k, v in params.items() if v is None)
        if missing:
            raise SpecError(f"missing required parameters: {missing}")
        return {k: str(v) for k, v in params.items()}

    # -- (de)serialization for spec files ----------------------------------

    def to_dict(self) -> dict:
        return dataclasses.asdict(self)

    @classmethod
    def from_dict(cls, data: Mapping[str, Any]) -> "WorkflowSpec":
        steps = []
        for raw in data.get("steps", []):
            raw = dict(raw)
            retry = raw.pop("retry", None) or {}
            steps.append(Step(retry=RetryStrategy(**retry), **raw))
        return cls(name=data.get("name", "workflow"), steps=steps,
                   parameters=dict(data.get("parameters", {})))


def artifact_complete(path: str) -> bool:
    """Sentinel gate: directories require the ``.ready.txt`` contract the
    downloader/trainer already write; plain files just need to exist."""
    import os

    if os.path.isdir(path):
        return os.path.exists(os.path.join(path, READY_SENTINEL))
    return os.path.exists(path)
