"""Argo Workflow YAML → :class:`WorkflowSpec` importer.

Makes the shipped manifests (``deploy/finetuner-workflow/
finetune-workflow.yaml`` and friends) locally executable: parameters,
step groups (sequential groups; members of a group run concurrently),
``retryStrategy``, ``when`` conditions, ``withParam`` fan-out, container
templates (argv), and ``resource`` templates (raw manifest, executed by
the k8s executor) all carry over.

``{{inputs.parameters.x}}`` references are substituted with the calling
step's argument expressions at import time (which may themselves contain
``{{workflow.parameters.*}}`` templating — resolved later at run time by
the engine, exactly like Argo's two-phase expansion).  Container commands
for binaries that only exist inside the reference images are remapped to
this package's CLIs so the DAG runs on a dev box.
"""

from __future__ import annotations

import json
import re
from typing import Any, Mapping, Optional

from kubernetes_cloud_tpu.workflow.spec import (
    RetryStrategy,
    SpecError,
    Step,
    WorkflowSpec,
    render,
)

_INPUT_RE = re.compile(r"\{\{\s*inputs\.parameters\.([\w.-]+)\s*\}\}")
_ITEM_RE = re.compile(r"\{\{\s*item\s*\}\}")


def _params_list(raw: Any) -> dict:
    return {p["name"]: p.get("value") for p in (raw or [])}


def _sub_inputs(text: str, inputs: Mapping[str, str]) -> str:
    def _sub(m: re.Match) -> str:
        key = m.group(1)
        if key not in inputs:
            raise SpecError(f"step argument {key!r} not supplied")
        return str(inputs[key])

    return _INPUT_RE.sub(_sub, text)


def _template_argv(template: Mapping[str, Any],
                   inputs: Mapping[str, str]) -> tuple:
    # The container command carries over verbatim: the k8s executor ships
    # it unmodified into the template's image, while the local executor
    # remaps image-only binaries to in-tree CLIs at execution time
    # (LocalExecutor.REMAP).
    container = template["container"]
    argv = [str(a) for a in (list(container.get("command", []))
                             + list(container.get("args", [])))]
    argv = [_sub_inputs(a, inputs) for a in argv]
    image = container.get("image", "")
    return argv, image


def _make_steps(name: str, call: Mapping[str, Any],
                template: Mapping[str, Any], deps: list,
                workflow_params: Mapping[str, str]) -> list:
    """One workflow step (or a withParam fan-out of them) from a template
    invocation."""
    inputs = _params_list(call.get("arguments", {}).get("parameters"))
    declared = _params_list(template.get("inputs", {}).get("parameters"))
    for key, default in declared.items():
        if default is not None:  # defaultless inputs must be supplied —
            inputs.setdefault(key, default)  # _sub_inputs errors otherwise
    retry_raw = template.get("retryStrategy") or {}
    retry = RetryStrategy(limit=int(retry_raw.get("limit", 0)))
    when = call.get("when", "")

    def _one(step_name: str, item: Optional[str]) -> Step:
        sub = dict(inputs)
        if item is not None:
            sub = {k: _ITEM_RE.sub(item, str(v)) for k, v in sub.items()}
        if "container" in template:
            argv, image = _template_argv(template, sub)
            return Step(name=step_name, command=argv, deps=list(deps),
                        retry=retry, when=when, image=image)
        if "resource" in template:
            manifest = _sub_inputs(template["resource"]["manifest"], sub)
            return Step(name=step_name, command=[], deps=list(deps),
                        retry=retry, when=when, executor="k8s",
                        manifest=manifest)
        raise SpecError(
            f"template {template.get('name')!r} is neither container "
            f"nor resource")

    with_param = call.get("withParam")
    if not with_param:
        return [_one(name, None)]
    items = json.loads(render(str(with_param), workflow_params))
    return [_one(f"{name}-{i}", str(item))
            for i, item in enumerate(items)]


def load_argo_workflow(path: str,
                       overrides: Optional[Mapping[str, str]] = None
                       ) -> WorkflowSpec:
    """``overrides`` (the ``-p`` values) matter at import time only for
    ``withParam`` fan-outs, whose cardinality is fixed while building the
    DAG; all other templating stays deferred to the engine."""
    import yaml

    with open(path) as fh:
        doc = yaml.safe_load(fh)
    spec = doc.get("spec", {})
    params = _params_list(spec.get("arguments", {}).get("parameters"))
    fanout_params = dict(params)
    for key, value in (overrides or {}).items():
        if key in fanout_params:
            fanout_params[key] = value
    templates = {t["name"]: t for t in spec.get("templates", [])}
    entry_name = spec.get("entrypoint")
    if entry_name not in templates:
        raise SpecError(f"entrypoint {entry_name!r} not among templates")
    entry = templates[entry_name]

    meta = doc.get("metadata", {})
    name = (meta.get("name")
            or meta.get("generateName", "workflow").rstrip("-"))

    steps: list = []
    if "steps" in entry:
        prev_group: list = []
        for group in entry["steps"]:
            current: list = []
            for call in group:
                template = templates.get(call["template"])
                if template is None:
                    raise SpecError(
                        f"step {call['name']!r} references unknown "
                        f"template {call['template']!r}")
                for s in _make_steps(call["name"], call, template,
                                     prev_group, fanout_params):
                    steps.append(s)
                    current.append(s.name)
            prev_group = current
    elif "dag" in entry:
        for task in entry["dag"].get("tasks", []):
            template = templates.get(task["template"])
            if template is None:
                raise SpecError(
                    f"task {task['name']!r} references unknown "
                    f"template {task['template']!r}")
            deps = list(task.get("dependencies", []))
            steps.extend(_make_steps(task["name"], task, template, deps,
                                     fanout_params))
    else:
        raise SpecError(f"entrypoint {entry_name!r} has no steps or dag")

    spec_obj = WorkflowSpec(name=name, steps=steps, parameters=params)
    spec_obj.validate()
    return spec_obj
