"""Canned pipelines — the reference's flagship composition as a built-in.

``finetune-and-serve`` is the flagship pipeline (corpus →
dataset-downloader → tokenizer → finetuner → tensors-verify →
serve smoke-test) sized to complete on the CPU-simulated mesh in one
command::

    python -m kubernetes_cloud_tpu.workflow run finetune-and-serve

Every step is one of the package's real CLIs driven through the local
subprocess executor, every artifact hand-off uses the ``.ready.txt``
sentinel contract, and the whole DAG is preemption-safe: kill it at any
point and a rerun resumes from the completed steps.
"""

from __future__ import annotations

import sys

from kubernetes_cloud_tpu.workflow.spec import RetryStrategy, Step, WorkflowSpec

#: deterministic corpus generator (the demo-dataset step's local stand-in);
#: argv: corpus_dir urls_file n_docs
_SEED_SRC = """\
import os, random, sys, urllib.request
corpus, urls_file, n = sys.argv[1], sys.argv[2], int(sys.argv[3])
os.makedirs(corpus, exist_ok=True)
rng = random.Random(0)
words = ("tpu pod slice mesh shard batch token train serve scale "
         "cloud workload tensor stream fast jax xla graph").split()
paths = []
for i in range(n):
    text = "\\n".join(
        " ".join(rng.choice(words) for _ in range(rng.randint(6, 14)))
        for _ in range(rng.randint(20, 40)))
    path = os.path.join(corpus, f"doc{i:03d}.txt")
    with open(path, "w") as fh:
        fh.write(text + "\\n")
    paths.append(path)
tmp = urls_file + ".tmp"
with open(tmp, "w") as fh:
    for p in paths:
        fh.write("file://" + urllib.request.pathname2url(os.path.abspath(p))
                 + "\\n")
os.replace(tmp, urls_file)
print(urls_file)
"""

_CPU_ENV = {
    "JAX_PLATFORMS": "cpu",
    "XLA_FLAGS": "--xla_force_host_platform_device_count=8",
}


def build_finetune_and_serve() -> WorkflowSpec:
    """The flagship DAG with the reference's step names
    (``finetune-workflow.yaml:200-321``), CPU-sim sized."""
    py = sys.executable
    wd = "{{workflow.parameters.workdir}}"
    run = "{{workflow.parameters.run_name}}"
    tokens = f"{wd}/dataset.tokens"
    steps = [
        Step(
            name="seed-corpus",
            command=[py, "-c", _SEED_SRC, f"{wd}/corpus", f"{wd}/urls.txt",
                     "{{workflow.parameters.docs}}"],
            artifacts=[f"{wd}/urls.txt"],
        ),
        Step(
            name="dataset-downloader",
            command=[py, "-m", "kubernetes_cloud_tpu.data.dataset_downloader",
                     "--urls", f"{wd}/urls.txt",
                     "--output", f"{wd}/dataset", "--retries", "3"],
            deps=["seed-corpus"],
            retry=RetryStrategy(limit=2, backoff=0.5),
            artifacts=[f"{wd}/dataset"],
        ),
        Step(
            name="tokenizer",
            command=[py, "-m", "kubernetes_cloud_tpu.data.tokenizer_cli",
                     "--input", f"{wd}/dataset", "--output", tokens,
                     "--tokenizer", "byte",
                     "--context-size", "{{workflow.parameters.context}}",
                     "--eot-token", "0", "--pad-token", "1"],
            deps=["dataset-downloader"],
            retry=RetryStrategy(limit=1, backoff=0.5),
            artifacts=[tokens, tokens + ".json"],
        ),
        Step(
            name="finetuner",
            command=[py, "-m", "kubernetes_cloud_tpu.train.finetuner_cli",
                     "--run-name", run,
                     "--model", "{{workflow.parameters.model}}",
                     "--dataset", tokens,
                     "--context-size", "{{workflow.parameters.context}}",
                     "--mesh", "{{workflow.parameters.mesh}}",
                     "--bs", "{{workflow.parameters.bs}}",
                     "--gradients", "1",
                     "--epochs", "{{workflow.parameters.epochs}}",
                     "--save-steps", "2",
                     "--output-path", wd,
                     "--logs", f"{wd}/logs"],
            deps=["tokenizer"],
            retry=RetryStrategy(limit=1, backoff=2.0),
            timeout=1800.0,
            env=dict(_CPU_ENV),
            artifacts=[f"{wd}/results-{run}"],
        ),
        Step(
            # post-serialize integrity gate: chunk-checksum the fresh
            # artifact BEFORE a pod pays a cold start on it — a corrupt
            # or truncated save fails the workflow here (exit 3/4,
            # weights/verify_cli.py) instead of a serving rollout
            name="tensors-verify",
            command=[py, "-m", "kubernetes_cloud_tpu.weights.verify_cli",
                     f"{wd}/results-{run}/final"],
            deps=["finetuner"],
            timeout=600.0,
            env=dict(_CPU_ENV),
        ),
        Step(
            name="serve-smoke",
            command=[py, "-m", "kubernetes_cloud_tpu.serve.lm_service",
                     "--model", f"{wd}/results-{run}/final",
                     "--ready-file", f"{wd}/results-{run}/.ready.txt",
                     "--smoke", "{{workflow.parameters.prompt}}",
                     "--smoke-tokens",
                     "{{workflow.parameters.max_new_tokens}}"],
            deps=["tensors-verify"],
            retry=RetryStrategy(limit=1, backoff=2.0),
            timeout=900.0,
            env=dict(_CPU_ENV),
        ),
    ]
    return WorkflowSpec(
        name="finetune-and-serve",
        steps=steps,
        parameters={
            # workdir is injected by the CLI (the run directory)
            "workdir": None,
            "run_name": "finetune-local",
            "docs": "6",
            "context": "32",
            "model": "test-tiny",
            "mesh": "data=8",
            "bs": "8",
            "epochs": "1",
            "prompt": "Hello TPU",
            "max_new_tokens": "8",
        },
    )


CANNED = {
    "finetune-and-serve": build_finetune_and_serve,
}


def canned(name: str) -> WorkflowSpec:
    from kubernetes_cloud_tpu.workflow.spec import SpecError

    if name not in CANNED:
        raise SpecError(
            f"unknown pipeline {name!r}; available: {sorted(CANNED)}")
    return CANNED[name]()
