"""Step executors: local subprocess and Kubernetes Job.

The local executor drives the package's own CLIs (``python -m
kubernetes_cloud_tpu...``) — the CPU-simulated-mesh path that makes the
shipped Argo manifests runnable without a cluster.  The k8s executor is
the in-cluster path: it materializes a step as a ``batch/v1`` Job through
the stdlib :class:`~kubernetes_cloud_tpu.deploy.k8s_client.K8sClient`
(whose request layer now retries transient apiserver failures, shared
with every other client caller) and polls Job status; ``resource``
templates (the InferenceService apply step) POST their manifest to the
derived CRD path.
"""

from __future__ import annotations

import dataclasses
import os
import subprocess
import sys
import time
from typing import Any, Mapping, Optional

from kubernetes_cloud_tpu.workflow.spec import Step


@dataclasses.dataclass
class StepResult:
    rc: int
    stdout: str = ""
    stderr: str = ""
    #: Argo ``outputs.result`` analogue: last non-empty stdout line,
    #: referenceable downstream as ``{{steps.<name>.outputs.result}}``.
    output: str = ""
    duration: float = 0.0

    @property
    def ok(self) -> bool:
        return self.rc == 0


def _result_from_stdout(rc: int, stdout: str, stderr: str,
                        duration: float) -> StepResult:
    lines = [ln for ln in stdout.strip().splitlines() if ln.strip()]
    return StepResult(rc=rc, stdout=stdout, stderr=stderr,
                      output=lines[-1].strip() if lines else "",
                      duration=duration)


class LocalExecutor:
    """Run a step's argv as a subprocess.

    Container-image argv heads are remapped to their local equivalents
    here — an executor-local concern, so the same imported spec still
    submits the *unmodified* command when run through the k8s executor:
    ``python``/``python3`` become the running interpreter, and binaries
    that exist only inside the reference images (the Go/C++ tokenizer)
    become the in-tree CLI.  stdout is captured for ``outputs.result``
    templating."""

    #: argv-head -> replacement prefix (None => [sys.executable])
    REMAP = {
        "python": None,
        "python3": None,
        "/usr/local/bin/dataset_tokenizer":
            [None, "-m", "kubernetes_cloud_tpu.data.tokenizer_cli"],
        "/ko-app/dataset_tokenizer":
            [None, "-m", "kubernetes_cloud_tpu.data.tokenizer_cli"],
    }

    def __init__(self, base_env: Optional[Mapping[str, str]] = None,
                 cwd: Optional[str] = None):
        self.base_env = dict(base_env or {})
        self.cwd = cwd

    def _argv(self, step: Step) -> list:
        argv = list(step.command)
        if argv and argv[0] in self.REMAP:
            prefix = self.REMAP[argv[0]] or [None]
            argv = [sys.executable if p is None else p
                    for p in prefix] + argv[1:]
        return argv

    def execute(self, step: Step, *, timeout: Optional[float] = None,
                attempt: int = 0) -> StepResult:
        env = dict(os.environ)
        env.update(self.base_env)
        env.update(step.env)
        t0 = time.monotonic()
        try:
            proc = subprocess.run(
                self._argv(step), env=env, cwd=self.cwd,
                capture_output=True, text=True, timeout=timeout)
        except subprocess.TimeoutExpired as e:
            out = e.stdout or b""
            return _result_from_stdout(
                124,
                out.decode(errors="replace") if isinstance(out, bytes)
                else out,
                f"step {step.name!r} timed out after {timeout}s",
                time.monotonic() - t0)
        except FileNotFoundError as e:
            return _result_from_stdout(127, "", str(e),
                                       time.monotonic() - t0)
        return _result_from_stdout(proc.returncode, proc.stdout, proc.stderr,
                                   time.monotonic() - t0)


# ---------------------------------------------------------------------------
# kubernetes


def _crd_path_for(manifest: Mapping[str, Any], namespace: str) -> str:
    api_version = manifest["apiVersion"]
    kind = manifest["kind"]
    plural = kind.lower() + "s"
    if "/" in api_version:
        group, version = api_version.split("/", 1)
        return f"/apis/{group}/{version}/namespaces/{namespace}/{plural}"
    return f"/api/{api_version}/namespaces/{namespace}/{plural}"


class K8sJobExecutor:
    """Run a step as a ``batch/v1`` Job and wait for completion.

    Retries of the *step* stay with the engine (``backoffLimit: 0`` on the
    Job), so the JSONL event log sees every attempt; transient apiserver
    errors are absorbed by the client's own request retries."""

    def __init__(self, client, namespace: str = "default", *,
                 poll: float = 2.0, sleep=time.sleep):
        self.client = client
        self.namespace = namespace
        self.poll = poll
        self._sleep = sleep

    def job_manifest(self, step: Step, run_id: str,
                     attempt: int = 0) -> dict:
        # attempt-suffixed: Jobs are immutable and attempt N-1's failed Job
        # (backoffLimit 0, not deleted) would 409 an identically-named
        # retry.  The suffix survives the 63-char truncation — otherwise a
        # retry could silently poll the previous attempt's Job.
        suffix = f"-a{attempt}"
        base = f"{run_id}-{step.name}".replace("_", "-").lower()
        name = base[:63 - len(suffix)] + suffix
        container = {
            "name": "main",
            "image": step.image or "python:3.11-slim",
            "command": [str(a) for a in step.command],
        }
        if step.env:
            container["env"] = [{"name": k, "value": str(v)}
                                for k, v in sorted(step.env.items())]
        return {
            "apiVersion": "batch/v1",
            "kind": "Job",
            "metadata": {"name": name,
                         "labels": {"workflow-run": run_id,
                                    "workflow-step": step.name}},
            "spec": {
                "backoffLimit": 0,
                "template": {
                    "metadata": {"labels": {"workflow-step": step.name}},
                    "spec": {"restartPolicy": "Never",
                             "containers": [container]},
                },
            },
        }

    def _apply_resource(self, step: Step,
                        timeout: Optional[float]) -> StepResult:
        # Apply-and-forget: the manifest is POSTed (or merge-patched on
        # 409) and the step succeeds on acceptance.  Argo's
        # successCondition wait is not implemented — gate downstream steps
        # on the artifact/readiness contract instead (the canned pipeline
        # uses `lm_service --ready-file`).
        import yaml

        from kubernetes_cloud_tpu.deploy.k8s_client import ApiError

        t0 = time.monotonic()
        manifest = yaml.safe_load(step.manifest)
        path = _crd_path_for(manifest, self.namespace)
        try:
            self.client.create(path, manifest)
        except ApiError as e:
            if e.status != 409:  # already exists => apply semantics
                return StepResult(rc=1, stderr=str(e),
                                  duration=time.monotonic() - t0)
            name = manifest["metadata"]["name"]
            self.client.patch(f"{path}/{name}", manifest)
        return StepResult(rc=0, output=manifest["metadata"].get("name", ""),
                          duration=time.monotonic() - t0)

    def execute(self, step: Step, *, timeout: Optional[float] = None,
                attempt: int = 0) -> StepResult:
        if step.manifest:
            return self._apply_resource(step, timeout)
        from kubernetes_cloud_tpu.deploy.k8s_client import ApiError

        t0 = time.monotonic()
        run_id = step.env.get("WORKFLOW_RUN_ID", "wf")
        manifest = self.job_manifest(step, run_id, attempt)
        path = f"/apis/batch/v1/namespaces/{self.namespace}/jobs"
        name = manifest["metadata"]["name"]
        try:
            self.client.create(path, manifest)
        except ApiError as e:
            # 409: the Job already exists — a lost create response was
            # retried, or a prior orchestrator died after creating it.
            # Either way the Job is there; fall through to polling it.
            if e.status != 409:
                raise
        deadline = (time.monotonic() + timeout) if timeout else None
        while True:
            status = (self.client.get(f"{path}/{name}") or {}).get(
                "status", {})
            if status.get("succeeded"):
                return StepResult(rc=0, output=name,
                                  duration=time.monotonic() - t0)
            if status.get("failed"):
                return StepResult(rc=1, stderr=f"job {name} failed",
                                  duration=time.monotonic() - t0)
            if deadline and time.monotonic() > deadline:
                return StepResult(rc=124,
                                  stderr=f"job {name} timed out",
                                  duration=time.monotonic() - t0)
            self._sleep(self.poll)
