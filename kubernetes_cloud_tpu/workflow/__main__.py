"""``python -m kubernetes_cloud_tpu.workflow`` entry point."""

import sys

from kubernetes_cloud_tpu.workflow.cli import main

if __name__ == "__main__":
    sys.exit(main())
