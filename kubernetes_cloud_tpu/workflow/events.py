"""JSONL step-event log — the orchestrator's operational record.

Argo keeps per-node phase/retry history in the Workflow CRD status; the
local engine writes the same information as an append-only JSONL stream
(``events.jsonl`` in the run directory) through the exact writer the
training metrics use (:class:`kubernetes_cloud_tpu.train.metrics
.JsonlWriter`), so the one reader chain consumes both streams.

Events: ``workflow_start`` / ``workflow_finish``, ``step_start`` /
``step_finish`` (with duration + rc), ``step_retry`` (with the backoff
delay), ``step_skipped`` (sentinel-complete resume or ``when`` false).
"""

from __future__ import annotations

import time
from typing import Any, Optional

from kubernetes_cloud_tpu.train.metrics import JsonlWriter, read_jsonl

EVENT_LOG = "events.jsonl"


class WorkflowEventLog:
    """Append-only event emitter; safe to leave open across a SIGKILL
    (line-buffered writes, torn tails tolerated by :func:`read_events`)
    and across threads — concurrent steps emit from the pool's workers;
    whole-line atomicity comes from the writer's internal lock, so this
    layer holds no lock of its own across the file I/O (kct-lint
    KCT-LOCK-001)."""

    def __init__(self, path: str):
        self._writer = JsonlWriter(path)
        self.path = path

    def emit(self, event: str, step: Optional[str] = None,
             **fields: Any) -> None:
        rec = {"ts": time.time(), "event": event}
        if step is not None:
            rec["step"] = step
        rec.update(fields)
        self._writer.write(rec)

    def close(self) -> None:
        self._writer.close()


def read_events(path: str) -> list:
    return read_jsonl(path)


def summarize(events: list) -> dict:
    """Per-step rollup: attempts, last status, total wall time."""
    steps: dict = {}
    for rec in events:
        name = rec.get("step")
        if not name:
            continue
        info = steps.setdefault(
            name, {"attempts": 0, "status": "pending", "duration": 0.0})
        event = rec.get("event")
        if event == "step_start":
            info["attempts"] += 1
            info["status"] = "running"
        elif event == "step_retry":
            info["status"] = "retrying"
        elif event == "step_finish":
            info["status"] = rec.get("status", "unknown")
            info["duration"] += float(rec.get("duration", 0.0))
        elif event == "step_skipped":
            info["status"] = "skipped"
            info["reason"] = rec.get("reason", "")
    return steps
