"""Tensor-parallel paged decode — ONE ``shard_map``ped program per
engine iteration (ROADMAP item 1; the BLOOM-176B serving pattern).

The serving engine's flagship reference workload is a model that cannot
fit one chip, yet ``serve/continuous.py``'s device programs were
single-chip: a mesh only sharded them implicitly through GSPMD.  This
module makes the parallelism *explicit* Megatron-style intra-layer TP
(PAPERS.md, Megatron-LM): every prefill and decode iteration is one
``shard_map`` over the ``model`` axis in which each shard owns

* a contiguous slice of the attention heads — ``wq``/``wk``/``wv``
  sharded on the head dim (the fused ``wqkv`` is split at load so the
  ``[H + 2·Hkv]`` dim chunks cleanly; rules live in the
  :mod:`kubernetes_cloud_tpu.parallel.sharding` table), the paged KV
  arena sharded on its kv-head axis
  (:func:`~kubernetes_cloud_tpu.parallel.sharding.kv_arena_specs`),
  and an int8 arena's per-page scale buffers following their pages'
  head axis;
* a row slice of ``W_o`` and a column slice of ``W_in`` — the two
  ``psum`` points per block (attention output, MLP output), exactly
  Megatron's ``g``/``f`` operators;
* a vocab slice of the (tied or untied) embedding: the token lookup is
  a masked-gather + ``psum`` (one shard contributes per token, so the
  sum is exact) and the LM head emits a logits slice that one
  ``all_gather`` reassembles.

Everything the scheduler owns — page tables, lengths, sampling —
stays replicated host state; per-shard attention math is bitwise the
single-chip math per head (contractions over heads/ffn are the only
reassociated sums), so greedy decode is token-identical to the
unsharded engine (``tests/test_sharded_engine.py`` locks it for fp32
AND int8 arenas, 2- and 4-way).  The jnp fallbacks (and interpreted
Pallas kernels) keep every impl CPU-testable on a host-platform mesh
of virtual devices, so tier-1 exercises real ≥2-way sharding.

Scope: pure-TP serving meshes (every axis but ``model`` must be 1 —
batch/fsdp sharding of a decode batch belongs to the fleet layer, not
the kernel).  MoE experts run replicated inside the program (the
routing all-to-all of true expert parallelism is deferred; the config
still serves correctly).  :func:`tp_unsupported_reason` names the
constraint violated so the engine can fall back to GSPMD loudly.
"""

from __future__ import annotations

import functools
from typing import Any, Optional

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from kubernetes_cloud_tpu.core.mesh import AXIS_MODEL
from kubernetes_cloud_tpu.models.causal_lm import CausalLMConfig, _norm
from kubernetes_cloud_tpu.models.generate import (
    _page_scatter_indices,
    _quant_decode_write,
    _quant_prefill_write,
    copy_pages,
)
from kubernetes_cloud_tpu.ops.attention import attention
from kubernetes_cloud_tpu.ops.layers import (
    alibi_slopes,
    apply_rotary,
    rope_cache,
)
from kubernetes_cloud_tpu.parallel.sharding import (
    kv_arena_specs,
    logical_to_physical,
    param_specs,
)
from kubernetes_cloud_tpu.utils.compat import shard_map

Params = dict[str, Any]


def tp_shards(mesh) -> int:
    """How many ways the ``model`` axis shards the decode program."""
    if mesh is None:
        return 1
    return int(mesh.shape.get(AXIS_MODEL, 1))


def tp_unsupported_reason(cfg: CausalLMConfig, mesh) -> Optional[str]:
    """None when the shard_map TP decode program can serve this
    (config, mesh) pair; otherwise the constraint violated — the
    engine logs it and falls back to GSPMD placement."""
    m = tp_shards(mesh)
    if m < 2:
        return "model axis is 1 (nothing to shard)"
    for ax, size in mesh.shape.items():
        if ax != AXIS_MODEL and size > 1:
            return (f"mesh axis {ax!r} has size {size}; the TP decode "
                    f"program shards only 'model'")
    if cfg.num_heads % m:
        return f"num_heads ({cfg.num_heads}) not divisible by {m} shards"
    if cfg.kv_heads % m:
        return f"kv_heads ({cfg.kv_heads}) not divisible by {m} shards"
    if cfg.vocab_size % m:
        return f"vocab_size ({cfg.vocab_size}) not divisible by {m} shards"
    if not cfg.moe_experts and cfg.ffn_size % m:
        return f"ffn_size ({cfg.ffn_size}) not divisible by {m} shards"
    return None


# ---------------------------------------------------------------------------
# parameter layout: fused wqkv split so heads chunk cleanly over `model`
# ---------------------------------------------------------------------------


def split_qkv_params(cfg: CausalLMConfig, params: Params) -> Params:
    """Serving decode layout: ``attn.wqkv`` → ``wq``/``wk``/``wv``
    (and ``bqkv`` → ``bq``/``bk``/``bv``).  The fused ``[H + 2·Hkv]``
    projection dim cannot be chunked evenly over shards without mixing
    q heads into a k/v shard, so the split happens once at engine
    init; everything else is shared by reference."""
    h, hkv = cfg.num_heads, cfg.kv_heads
    attn = dict(params["blocks"]["attn"])
    wqkv = attn.pop("wqkv")
    attn["wq"] = wqkv[:, :, :h]
    attn["wk"] = wqkv[:, :, h:h + hkv]
    attn["wv"] = wqkv[:, :, h + hkv:]
    if "bqkv" in attn:
        b = attn.pop("bqkv")
        attn["bq"] = b[:, :h]
        attn["bk"] = b[:, h:h + hkv]
        attn["bv"] = b[:, h + hkv:]
    blocks = dict(params["blocks"])
    blocks["attn"] = attn
    out = dict(params)
    out["blocks"] = blocks
    return out


def tp_param_specs(params_split: Params) -> Any:
    """PartitionSpec tree for the split layout, straight from the
    :mod:`parallel.sharding` rule table — with one serving override:
    MoE expert weights stay replicated inside the shard_map program
    (true expert parallelism's dispatch all-to-all is deferred; a
    replicated-expert block computes a replicated output, so no psum
    is needed and correctness is untouched)."""
    specs = param_specs(params_split)

    def fix(path, spec):
        for part in path:
            if getattr(part, "key", getattr(part, "name", None)) == "moe":
                return P()
        return spec

    return jax.tree_util.tree_map_with_path(
        fix, specs, is_leaf=lambda x: isinstance(x, P))


def place_tp_params(cfg: CausalLMConfig, params: Params, mesh) -> Params:
    """Split + place the parameter pytree for the TP decode program."""
    split = split_qkv_params(cfg, params)
    return jax.device_put(split,
                          logical_to_physical(tp_param_specs(split), mesh))


def place_arena(arena: dict, mesh) -> dict:
    """Place a page arena per :func:`kv_arena_specs` (kv heads over
    ``model``; int8 scales follow their pages' head axis)."""
    return jax.device_put(
        arena, logical_to_physical(kv_arena_specs("k_scale" in arena),
                                   mesh))


# ---------------------------------------------------------------------------
# per-shard block math (mirrors models/generate.py; psum where the rule
# table splits a contraction)
# ---------------------------------------------------------------------------


def _tp_embed(cfg: CausalLMConfig, params: Params, input_ids: jax.Array,
              positions: jax.Array, idx: jax.Array, m: int) -> jax.Array:
    """Vocab-sharded embedding lookup: each shard holds ``V/m`` rows;
    exactly one shard contributes per token, so the psum is exact."""
    v_loc = cfg.vocab_size // m
    wte = params["embed"]["wte"]
    loc = input_ids - idx * v_loc
    valid = (loc >= 0) & (loc < v_loc)
    rows = wte[jnp.clip(loc, 0, v_loc - 1)]
    x = jax.lax.psum(jnp.where(valid[..., None], rows,
                               jnp.zeros_like(rows)), AXIS_MODEL)
    x = x.astype(cfg.dtype)
    if cfg.pos_emb == "learned":
        x = x + params["embed"]["wpe"][positions].astype(cfg.dtype)
    if cfg.embed_layernorm:
        x = _norm(cfg, params["embed"]["ln"], x)
    return x


def _tp_qkv(cfg: CausalLMConfig, p: Params, x: jax.Array, *,
            rope, q_positions):
    """Head-sliced mirror of ``causal_lm._project_qkv``: this shard's
    q/k/v heads only (contraction over hidden is intact, so per-head
    values are bitwise the single-chip ones)."""
    attn_in = _norm(cfg, p["ln1"], x)
    q = jnp.einsum("bsd,dnk->bsnk", attn_in,
                   p["attn"]["wq"].astype(cfg.dtype))
    k = jnp.einsum("bsd,dnk->bsnk", attn_in,
                   p["attn"]["wk"].astype(cfg.dtype))
    v = jnp.einsum("bsd,dnk->bsnk", attn_in,
                   p["attn"]["wv"].astype(cfg.dtype))
    if cfg.use_bias:
        q = q + p["attn"]["bq"].astype(cfg.dtype)
        k = k + p["attn"]["bk"].astype(cfg.dtype)
        v = v + p["attn"]["bv"].astype(cfg.dtype)
    if rope is not None:
        cos, sin = rope
        q = apply_rotary(q, cos, sin, positions=q_positions,
                         interleaved=cfg.rope_interleaved)
        k = apply_rotary(k, cos, sin, positions=q_positions,
                         interleaved=cfg.rope_interleaved)
    return q, k, v


def _tp_wo(cfg: CausalLMConfig, p: Params, attn_vec: jax.Array
           ) -> jax.Array:
    """Row-parallel output projection: partial per-shard contraction
    over this shard's heads, psummed; bias added once post-psum."""
    part = jnp.einsum("bsnk,nkd->bsd", attn_vec,
                      p["attn"]["wo"].astype(cfg.dtype))
    out = jax.lax.psum(part, AXIS_MODEL)
    if cfg.use_bias:
        out = out + p["attn"]["bo"].astype(cfg.dtype)
    return out


def _tp_finish(cfg: CausalLMConfig, p: Params, x: jax.Array,
               attn_out: jax.Array, token_mask, moe_no_drop: bool
               ) -> jax.Array:
    """Mirror of ``causal_lm._finish_block``'s residual wiring with a
    column/row-parallel MLP (psum on the down projection); ``attn_out``
    arrives already psummed + biased.  MoE blocks run replicated (see
    :func:`tp_param_specs`)."""
    if cfg.parallel_residual:
        mlp_in = _norm(cfg, p["ln2"], x)
    else:
        x = x + attn_out
        mlp_in = _norm(cfg, p["ln2"], x)
    if "moe" in p:
        from kubernetes_cloud_tpu.ops.moe import moe_ffn

        if token_mask is not None and token_mask.ndim != 2:
            token_mask = None
        mlp_out, _aux = moe_ffn(
            mlp_in, p["moe"]["router"], p["moe"]["wi"], p["moe"]["wo"],
            top_k=cfg.moe_top_k, capacity_factor=cfg.moe_capacity_factor,
            act=cfg.act, dtype=cfg.dtype, token_mask=token_mask,
            group_size=cfg.moe_group_size, no_drop=moe_no_drop)
    else:
        hmid = jnp.einsum("bsd,df->bsf", mlp_in,
                          p["mlp"]["wi"].astype(cfg.dtype))
        if cfg.use_bias:
            hmid = hmid + p["mlp"]["bi"].astype(cfg.dtype)
        hmid = jax.nn.gelu(hmid, approximate=cfg.act == "gelu_tanh")
        mlp_out = jax.lax.psum(
            jnp.einsum("bsf,fd->bsd", hmid,
                       p["mlp"]["wo"].astype(cfg.dtype)), AXIS_MODEL)
        if cfg.use_bias:
            mlp_out = mlp_out + p["mlp"]["bo"].astype(cfg.dtype)
    if cfg.parallel_residual:
        return x + attn_out + mlp_out
    return x + mlp_out


def _tp_unembed(cfg: CausalLMConfig, params: Params, x: jax.Array,
                idx: jax.Array, m: int) -> jax.Array:
    """final_ln + vocab-sliced LM head; one all_gather reassembles the
    full fp32 logits in shard order (= the unsharded vocab order)."""
    x = _norm(cfg, params["final_ln"], x)
    if cfg.tie_embeddings:
        logits = jnp.einsum("bsd,vd->bsv", x,
                            params["embed"]["wte"].astype(cfg.dtype))
    else:
        logits = jnp.einsum("bsd,dv->bsv", x,
                            params["lm_head"].astype(cfg.dtype))
    if "lm_head_bias" in params:  # GPT-J imports; kept replicated
        v_loc = cfg.vocab_size // m
        logits = logits + jax.lax.dynamic_slice_in_dim(
            params["lm_head_bias"], idx * v_loc, v_loc).astype(cfg.dtype)
    logits = logits.astype(jnp.float32)
    return jax.lax.all_gather(logits, AXIS_MODEL, axis=logits.ndim - 1,
                              tiled=True)


# ---------------------------------------------------------------------------
# the two shard-mapped programs
# ---------------------------------------------------------------------------


def _decode_shard_fn(cfg: CausalLMConfig, m: int, impl: str,
                     interpret: bool, params: Params, tokens: jax.Array,
                     arena: dict, page_table: jax.Array,
                     lengths: jax.Array) -> tuple[jax.Array, dict]:
    """Per-shard body of one decode iteration (mirrors
    ``generate.decode_step_pages`` with head-local KV writes and the
    two Megatron psum points per block)."""
    idx = jax.lax.axis_index(AXIS_MODEL)
    h_loc = cfg.num_heads // m
    s = tokens.shape[0]
    ps = arena["k"].shape[2]
    max_len = page_table.shape[1] * ps
    pos = lengths
    positions = pos[:, None]
    quant = "k_scale" in arena

    rope = (rope_cache(max_len, cfg.rotary_dim, cfg.rope_theta)
            if cfg.pos_emb == "rope" else None)
    kpos_all = jnp.broadcast_to(jnp.arange(max_len), (s, max_len))
    slopes_loc = bias = None
    if cfg.pos_emb == "alibi":
        slopes_loc = jax.lax.dynamic_slice_in_dim(
            alibi_slopes(cfg.num_heads), idx * h_loc, h_loc)
        bias = (slopes_loc[None, :, None, None]
                * kpos_all.astype(jnp.float32)[:, None, None, :])
    key_mask = (kpos_all <= pos[:, None]).astype(jnp.int32)

    phys = jnp.take_along_axis(page_table, (pos // ps)[:, None],
                               axis=1)[:, 0]
    rows = pos % ps

    x = _tp_embed(cfg, params, tokens[:, None], positions, idx, m)

    def body(carry, layer):
        x = carry
        if quant:
            p, ck, cv, sk, sv = layer
        else:
            p, ck, cv = layer
            sk = sv = None
        q, k_new, v_new = _tp_qkv(cfg, p, x, rope=rope,
                                  q_positions=positions)
        if quant:
            ck, sk = _quant_decode_write(ck, sk, phys, rows, k_new[:, 0])
            cv, sv = _quant_decode_write(cv, sv, phys, rows, v_new[:, 0])
        else:
            ck = ck.at[phys, rows].set(k_new[:, 0].astype(ck.dtype))
            cv = cv.at[phys, rows].set(v_new[:, 0].astype(cv.dtype))
        if impl == "fused":
            from kubernetes_cloud_tpu.ops.fused_decode import (
                fused_paged_decode,
            )

            part = fused_paged_decode(
                q[:, 0],
                ck if quant else ck.astype(cfg.dtype),
                cv if quant else cv.astype(cfg.dtype),
                page_table, pos + 1,
                p["attn"]["wo"].astype(cfg.dtype),
                k_scale=sk, v_scale=sv, slopes=slopes_loc,
                impl="pallas", interpret=interpret)
            attn_out = jax.lax.psum(part, AXIS_MODEL)
            if cfg.use_bias:
                attn_out = attn_out + p["attn"]["bo"].astype(cfg.dtype)
            attn_out = attn_out[:, None, :]
        else:
            if impl == "pallas":
                from kubernetes_cloud_tpu.ops.paged_attention import (
                    paged_decode_attention,
                )

                attn_vec = paged_decode_attention(
                    q[:, 0],
                    ck if quant else ck.astype(cfg.dtype),
                    cv if quant else cv.astype(cfg.dtype),
                    page_table, pos + 1, k_scale=sk, v_scale=sv,
                    slopes=slopes_loc, impl="pallas",
                    interpret=interpret)[:, None]
            else:
                from kubernetes_cloud_tpu.ops.paged_attention import (
                    gather_pages,
                )

                dense_k = gather_pages(ck, page_table, sk)
                dense_v = gather_pages(cv, page_table, sv)
                attn_vec = attention(q, dense_k.astype(cfg.dtype),
                                     dense_v.astype(cfg.dtype),
                                     causal=False, bias=bias,
                                     mask=key_mask, impl="xla")
            attn_out = _tp_wo(cfg, p, attn_vec)
        x = _tp_finish(cfg, p, x, attn_out, None, True)
        return x, ((ck, cv, sk, sv) if quant else (ck, cv))

    if quant:
        xs = (params["blocks"], arena["k"], arena["v"],
              arena["k_scale"], arena["v_scale"])
        x, (ks, vs, ssk, ssv) = jax.lax.scan(body, x, xs)
        new_arena = {"k": ks, "v": vs, "k_scale": ssk, "v_scale": ssv}
    else:
        x, (ks, vs) = jax.lax.scan(
            body, x, (params["blocks"], arena["k"], arena["v"]))
        new_arena = {"k": ks, "v": vs}
    logits = _tp_unembed(cfg, params, x, idx, m)[:, 0]
    return logits, new_arena


def _prefill_shard_fn(cfg: CausalLMConfig, m: int, interpret: bool,
                      params: Params, input_ids: jax.Array,
                      attention_mask: jax.Array, arena: dict,
                      page_tables: jax.Array, start: jax.Array
                      ) -> tuple[jax.Array, dict]:
    """Per-shard body of one prefill pass (mirrors
    ``generate.prefill_into_pages``: tail-only prefill at absolute
    positions, attending to the cached prefix through each shard's
    gathered head-slice view)."""
    idx = jax.lax.axis_index(AXIS_MODEL)
    h_loc = cfg.num_heads // m
    b, t = input_ids.shape
    ps = arena["k"].shape[2]
    max_len = page_tables.shape[1] * ps
    tail_lens = attention_mask.sum(-1).astype(jnp.int32)
    positions = start[:, None] + jnp.clip(
        jnp.cumsum(attention_mask, 1) - 1, 0)
    quant = "k_scale" in arena

    rope = (rope_cache(max_len, cfg.rotary_dim, cfg.rope_theta)
            if cfg.pos_emb == "rope" else None)
    kpos_all = jnp.broadcast_to(jnp.arange(max_len), (b, max_len))
    bias = None
    if cfg.pos_emb == "alibi":
        slopes_loc = jax.lax.dynamic_slice_in_dim(
            alibi_slopes(cfg.num_heads), idx * h_loc, h_loc)
        bias = (slopes_loc[None, :, None, None]
                * kpos_all.astype(jnp.float32)[:, None, None, :])
    key_mask = (kpos_all[:, None, None, :]
                <= positions[:, None, :, None]).astype(jnp.int32)

    phys, rows = _page_scatter_indices(page_tables, positions,
                                       attention_mask != 0, ps)
    phys_f = phys.reshape(b * t)
    rows_f = rows.reshape(b * t)
    valid_f = (attention_mask != 0).reshape(b * t)
    hkv_loc = cfg.kv_heads // m

    x = _tp_embed(cfg, params, input_ids, positions, idx, m)

    def body(carry, layer):
        x = carry
        if quant:
            p, ck, cv, sk, sv = layer
        else:
            p, ck, cv = layer
            sk = sv = None
        q, k_new, v_new = _tp_qkv(cfg, p, x, rope=rope,
                                  q_positions=positions)
        k_flat = k_new.reshape(b * t, hkv_loc, cfg.head_dim)
        v_flat = v_new.reshape(b * t, hkv_loc, cfg.head_dim)
        if quant:
            ck, sk = _quant_prefill_write(ck, sk, page_tables, phys_f,
                                          rows_f, k_flat, valid_f)
            cv, sv = _quant_prefill_write(cv, sv, page_tables, phys_f,
                                          rows_f, v_flat, valid_f)
            from kubernetes_cloud_tpu.ops.paged_attention import (
                gather_pages,
            )

            dense_k = gather_pages(ck, page_tables, sk)
            dense_v = gather_pages(cv, page_tables, sv)
        else:
            ck = ck.at[phys_f, rows_f].set(k_flat.astype(ck.dtype))
            cv = cv.at[phys_f, rows_f].set(v_flat.astype(cv.dtype))
            dense_k = ck[page_tables].reshape(b, max_len, hkv_loc,
                                              cfg.head_dim)
            dense_v = cv[page_tables].reshape(b, max_len, hkv_loc,
                                              cfg.head_dim)
        attn_vec = attention(q, dense_k.astype(cfg.dtype),
                             dense_v.astype(cfg.dtype), causal=False,
                             bias=bias, mask=key_mask, impl="xla")
        attn_out = _tp_wo(cfg, p, attn_vec)
        x = _tp_finish(cfg, p, x, attn_out, attention_mask, True)
        return x, ((ck, cv, sk, sv) if quant else (ck, cv))

    if quant:
        xs = (params["blocks"], arena["k"], arena["v"],
              arena["k_scale"], arena["v_scale"])
        x, (ks, vs, ssk, ssv) = jax.lax.scan(body, x, xs)
        new_arena = {"k": ks, "v": vs, "k_scale": ssk, "v_scale": ssv}
    else:
        x, (ks, vs) = jax.lax.scan(
            body, x, (params["blocks"], arena["k"], arena["v"]))
        new_arena = {"k": ks, "v": vs}
    logits = _tp_unembed(cfg, params, x, idx, m)
    last = jnp.take_along_axis(
        logits, (tail_lens - 1)[:, None, None].clip(0), axis=1)[:, 0]
    return last, new_arena


def _verify_shard_fn(cfg: CausalLMConfig, m: int, params: Params,
                     tokens: jax.Array, mask: jax.Array, arena: dict,
                     page_table: jax.Array, lengths: jax.Array
                     ) -> tuple[jax.Array, dict]:
    """Per-shard body of one speculative verification step (mirrors
    ``generate.verify_step_pages``: every slot's pending token + its
    draft proposals score in ONE multi-query pass at their true
    absolute positions, K/V written through the page indirection so
    the gathered view is bitwise the sequential-decode one)."""
    idx = jax.lax.axis_index(AXIS_MODEL)
    h_loc = cfg.num_heads // m
    s, t = tokens.shape
    ps = arena["k"].shape[2]
    max_len = page_table.shape[1] * ps
    positions = jnp.minimum(lengths[:, None] + jnp.arange(t)[None, :],
                            max_len - 1)
    valid = (mask != 0) & (lengths[:, None] + jnp.arange(t)[None, :]
                           < max_len)
    quant = "k_scale" in arena

    rope = (rope_cache(max_len, cfg.rotary_dim, cfg.rope_theta)
            if cfg.pos_emb == "rope" else None)
    kpos_all = jnp.broadcast_to(jnp.arange(max_len), (s, max_len))
    bias = None
    if cfg.pos_emb == "alibi":
        slopes_loc = jax.lax.dynamic_slice_in_dim(
            alibi_slopes(cfg.num_heads), idx * h_loc, h_loc)
        bias = (slopes_loc[None, :, None, None]
                * kpos_all.astype(jnp.float32)[:, None, None, :])
    key_mask = (kpos_all[:, None, None, :]
                <= positions[:, None, :, None]).astype(jnp.int32)

    phys, rows = _page_scatter_indices(page_table, positions, valid, ps)
    phys_f = phys.reshape(s * t)
    rows_f = rows.reshape(s * t)
    valid_f = valid.reshape(s * t)
    hkv_loc = cfg.kv_heads // m

    x = _tp_embed(cfg, params, tokens, positions, idx, m)

    def body(carry, layer):
        x = carry
        if quant:
            p, ck, cv, sk, sv = layer
        else:
            p, ck, cv = layer
            sk = sv = None
        q, k_new, v_new = _tp_qkv(cfg, p, x, rope=rope,
                                  q_positions=positions)
        k_flat = k_new.reshape(s * t, hkv_loc, cfg.head_dim)
        v_flat = v_new.reshape(s * t, hkv_loc, cfg.head_dim)
        if quant:
            ck, sk = _quant_prefill_write(ck, sk, page_table, phys_f,
                                          rows_f, k_flat, valid_f)
            cv, sv = _quant_prefill_write(cv, sv, page_table, phys_f,
                                          rows_f, v_flat, valid_f)
            from kubernetes_cloud_tpu.ops.paged_attention import (
                gather_pages,
            )

            dense_k = gather_pages(ck, page_table, sk)
            dense_v = gather_pages(cv, page_table, sv)
        else:
            ck = ck.at[phys_f, rows_f].set(k_flat.astype(ck.dtype))
            cv = cv.at[phys_f, rows_f].set(v_flat.astype(cv.dtype))
            dense_k = ck[page_table].reshape(s, max_len, hkv_loc,
                                             cfg.head_dim)
            dense_v = cv[page_table].reshape(s, max_len, hkv_loc,
                                             cfg.head_dim)
        attn_vec = attention(q, dense_k.astype(cfg.dtype),
                             dense_v.astype(cfg.dtype), causal=False,
                             bias=bias, mask=key_mask, impl="xla")
        attn_out = _tp_wo(cfg, p, attn_vec)
        x = _tp_finish(cfg, p, x, attn_out, mask, True)
        return x, ((ck, cv, sk, sv) if quant else (ck, cv))

    if quant:
        xs = (params["blocks"], arena["k"], arena["v"],
              arena["k_scale"], arena["v_scale"])
        x, (ks, vs, ssk, ssv) = jax.lax.scan(body, x, xs)
        new_arena = {"k": ks, "v": vs, "k_scale": ssk, "v_scale": ssv}
    else:
        x, (ks, vs) = jax.lax.scan(
            body, x, (params["blocks"], arena["k"], arena["v"]))
        new_arena = {"k": ks, "v": vs}
    return _tp_unembed(cfg, params, x, idx, m), new_arena


def _ragged_shard_fn(cfg: CausalLMConfig, m: int, impl: str,
                     interpret: bool, params: Params, tokens: jax.Array,
                     seg_slot: jax.Array, positions: jax.Array,
                     mask: jax.Array, arena: dict, page_table: jax.Array,
                     out_rows: jax.Array, copy_src: jax.Array,
                     copy_dst: jax.Array) -> tuple[jax.Array, dict]:
    """Per-shard body of ONE ragged hybrid iteration (mirrors
    ``generate.ragged_step_pages``): the flat ``[N]`` token batch —
    prefill chunks, decode steps, spec-verify windows — runs dense
    through the head-sliced block math, attention routes per-segment
    through the page indirection, and the pass's COW page pairs copy
    head-locally up front (pages and their scale rows shard on the
    kv-head axis, so a per-shard copy IS the whole copy)."""
    idx = jax.lax.axis_index(AXIS_MODEL)
    h_loc = cfg.num_heads // m
    n = tokens.shape[0]
    ps = arena["k"].shape[2]
    max_len = page_table.shape[1] * ps
    quant = "k_scale" in arena

    if copy_src.shape[0]:
        arena = copy_pages(arena, copy_src, copy_dst)

    valid = (mask != 0) & (positions < max_len)
    positions = jnp.minimum(positions, max_len - 1)[:, None]  # [N, 1]
    mask2 = valid.astype(jnp.int32)[:, None]
    pt_tok = page_table[seg_slot]                             # [N, P]
    ctx_lens = positions[:, 0] + 1

    rope = (rope_cache(max_len, cfg.rotary_dim, cfg.rope_theta)
            if cfg.pos_emb == "rope" else None)
    kpos_all = jnp.broadcast_to(jnp.arange(max_len), (n, max_len))
    slopes_loc = bias = None
    if cfg.pos_emb == "alibi":
        slopes_loc = jax.lax.dynamic_slice_in_dim(
            alibi_slopes(cfg.num_heads), idx * h_loc, h_loc)
        bias = (slopes_loc[None, :, None, None]
                * kpos_all.astype(jnp.float32)[:, None, None, :])
    key_mask = (kpos_all[:, None, None, :]
                <= positions[:, None, :, None]).astype(jnp.int32)

    phys, rows = _page_scatter_indices(pt_tok, positions,
                                       valid[:, None], ps)
    phys_f = phys.reshape(n)
    rows_f = rows.reshape(n)
    valid_f = valid
    hkv_loc = cfg.kv_heads // m

    x = _tp_embed(cfg, params, tokens[:, None], positions, idx, m)

    def body(carry, layer):
        x = carry
        if quant:
            p, ck, cv, sk, sv = layer
        else:
            p, ck, cv = layer
            sk = sv = None
        q, k_new, v_new = _tp_qkv(cfg, p, x, rope=rope,
                                  q_positions=positions)
        k_flat = k_new.reshape(n, hkv_loc, cfg.head_dim)
        v_flat = v_new.reshape(n, hkv_loc, cfg.head_dim)
        if quant:
            ck, sk = _quant_prefill_write(ck, sk, pt_tok, phys_f,
                                          rows_f, k_flat, valid_f)
            cv, sv = _quant_prefill_write(cv, sv, pt_tok, phys_f,
                                          rows_f, v_flat, valid_f)
        else:
            ck = ck.at[phys_f, rows_f].set(k_flat.astype(ck.dtype))
            cv = cv.at[phys_f, rows_f].set(v_flat.astype(cv.dtype))
        if impl == "fused":
            from kubernetes_cloud_tpu.ops.fused_decode import (
                fused_paged_segment,
            )

            part = fused_paged_segment(
                q[:, 0],
                ck if quant else ck.astype(cfg.dtype),
                cv if quant else cv.astype(cfg.dtype),
                page_table, seg_slot, ctx_lens,
                p["attn"]["wo"].astype(cfg.dtype),
                k_scale=sk, v_scale=sv, slopes=slopes_loc,
                impl="pallas", interpret=interpret)
            attn_out = jax.lax.psum(part, AXIS_MODEL)
            if cfg.use_bias:
                attn_out = attn_out + p["attn"]["bo"].astype(cfg.dtype)
            attn_out = attn_out[:, None, :]
        else:
            if impl == "pallas":
                from kubernetes_cloud_tpu.ops.paged_attention import (
                    paged_segment_attention,
                )

                attn_vec = paged_segment_attention(
                    q[:, 0],
                    ck if quant else ck.astype(cfg.dtype),
                    cv if quant else cv.astype(cfg.dtype),
                    page_table, seg_slot, ctx_lens, k_scale=sk,
                    v_scale=sv, slopes=slopes_loc, impl="pallas",
                    interpret=interpret)[:, None]
            else:
                from kubernetes_cloud_tpu.ops.paged_attention import (
                    gather_pages,
                )

                dense_k = gather_pages(ck, pt_tok, sk)
                dense_v = gather_pages(cv, pt_tok, sv)
                attn_vec = attention(q, dense_k.astype(cfg.dtype),
                                     dense_v.astype(cfg.dtype),
                                     causal=False, bias=bias,
                                     mask=key_mask, impl="xla")
            attn_out = _tp_wo(cfg, p, attn_vec)
        x = _tp_finish(cfg, p, x, attn_out, mask2, True)
        return x, ((ck, cv, sk, sv) if quant else (ck, cv))

    if quant:
        xs = (params["blocks"], arena["k"], arena["v"],
              arena["k_scale"], arena["v_scale"])
        x, (ks, vs, ssk, ssv) = jax.lax.scan(body, x, xs)
        new_arena = {"k": ks, "v": vs, "k_scale": ssk, "v_scale": ssv}
    else:
        x, (ks, vs) = jax.lax.scan(
            body, x, (params["blocks"], arena["k"], arena["v"]))
        new_arena = {"k": ks, "v": vs}
    logits = _tp_unembed(cfg, params, x[out_rows], idx, m)[:, 0]
    return logits, new_arena


#: (cfg, mesh, kv_dtype, attn_impl) → (prefill_jit, decode_jit,
#: verify_jit); one compilation cache shared by every engine
#: incarnation (a supervisor restart builds a new engine but reuses
#: the programs).  Ragged engines key with a trailing "ragged" marker
#: and cache the single hybrid program instead of the trio.
_PROGRAMS: dict = {}


def build_tp_programs(cfg: CausalLMConfig, mesh, params_split: Params, *,
                      kv_dtype: str = "fp32", attn_impl: str = "gather"):
    """The two jitted shard_map programs for one (config, mesh) pair.

    ``params_split`` supplies the tree STRUCTURE the in_specs must
    match (use_bias / moe / tied-embeddings variants); the cache
    assumes one structure per config, which ``split_qkv_params``
    guarantees for framework-initialized parameters.  Signatures match
    the single-chip programs minus the static config:

    * ``prefill(params, ids, mask, arena, tables, start)``
    * ``decode(params, tokens, arena, table, lengths)``
    * ``verify(params, tokens, mask, arena, table, lengths)`` —
      the speculative-decoding multi-query step

    The arena argument is donated, like the single-chip jits."""
    key = (cfg, mesh, kv_dtype, attn_impl)
    if key in _PROGRAMS:
        return _PROGRAMS[key]
    reason = tp_unsupported_reason(cfg, mesh)
    if reason is not None:
        raise ValueError(f"TP decode program unsupported: {reason}")
    m = tp_shards(mesh)
    interpret = jax.default_backend() != "tpu"
    quant = kv_dtype == "int8"
    pspecs = tp_param_specs(params_split)
    arena_spec = kv_arena_specs(quant)
    rep = P()

    decode = shard_map(
        functools.partial(_decode_shard_fn, cfg, m, attn_impl, interpret),
        mesh=mesh,
        in_specs=(pspecs, rep, arena_spec, rep, rep),
        out_specs=(rep, arena_spec),
        check_rep=False)
    prefill = shard_map(
        functools.partial(_prefill_shard_fn, cfg, m, interpret),
        mesh=mesh,
        in_specs=(pspecs, rep, rep, arena_spec, rep, rep),
        out_specs=(rep, arena_spec),
        check_rep=False)
    verify = shard_map(
        functools.partial(_verify_shard_fn, cfg, m),
        mesh=mesh,
        in_specs=(pspecs, rep, rep, arena_spec, rep, rep),
        out_specs=(rep, arena_spec),
        check_rep=False)
    programs = (jax.jit(prefill, donate_argnums=(3,)),
                jax.jit(decode, donate_argnums=(2,)),
                jax.jit(verify, donate_argnums=(3,)))
    _PROGRAMS[key] = programs
    return programs


def build_tp_ragged_program(cfg: CausalLMConfig, mesh,
                            params_split: Params, *,
                            kv_dtype: str = "fp32",
                            attn_impl: str = "gather"):
    """ONE jitted shard_map program for the ragged hybrid iteration —
    the whole sharded surface of a ragged engine (``EngineConfig.
    ragged``): prefill chunks, decode steps, spec-verify windows, and
    COW copies are all segment shapes inside this single program, so a
    TP engine pays one shard_map launch per scheduler pass instead of
    up to four.

    Signature (static config bound):

    * ``ragged(params, tokens, seg_slot, positions, mask, arena,
      table, out_rows, copy_src, copy_dst)`` → ``(logits [M, V],
      arena)``

    The arena argument is donated, like the trio's."""
    key = (cfg, mesh, kv_dtype, attn_impl, "ragged")
    if key in _PROGRAMS:
        return _PROGRAMS[key]
    reason = tp_unsupported_reason(cfg, mesh)
    if reason is not None:
        raise ValueError(f"TP ragged program unsupported: {reason}")
    m = tp_shards(mesh)
    interpret = jax.default_backend() != "tpu"
    quant = kv_dtype == "int8"
    pspecs = tp_param_specs(params_split)
    arena_spec = kv_arena_specs(quant)
    rep = P()

    ragged = shard_map(
        functools.partial(_ragged_shard_fn, cfg, m, attn_impl, interpret),
        mesh=mesh,
        in_specs=(pspecs, rep, rep, rep, rep, arena_spec, rep, rep, rep,
                  rep),
        out_specs=(rep, arena_spec),
        check_rep=False)
    program = jax.jit(ragged, donate_argnums=(5,))
    _PROGRAMS[key] = program
    return program
