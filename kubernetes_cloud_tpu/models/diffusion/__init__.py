"""Stable-Diffusion-class latent diffusion models, TPU-first.

Pure-pytree re-implementations of the three modules the reference
finetunes and serves (CLIP text encoder / VAE / UNet —
``sd-finetuner-workflow/sd-finetuner/finetuner.py:648-659``,
``online-inference/stable-diffusion/``), plus the DDPM/DDIM noise
schedule.  NHWC layout throughout (TPU conv-native), GroupNorm statistics
in fp32, bulk compute in bfloat16.
"""

from kubernetes_cloud_tpu.models.diffusion.schedule import (  # noqa: F401
    NoiseSchedule,
    add_noise,
    ddim_step,
    make_schedule,
    timestep_embedding,
    velocity_target,
)
from kubernetes_cloud_tpu.models.diffusion.clip_text import (  # noqa: F401
    CLIPTextConfig,
    clip_encode,
    clip_init,
)
from kubernetes_cloud_tpu.models.diffusion.vae import (  # noqa: F401
    VAEConfig,
    vae_decode,
    vae_encode,
    vae_init,
)
from kubernetes_cloud_tpu.models.diffusion.unet import (  # noqa: F401
    UNetConfig,
    unet_apply,
    unet_init,
)
