"""AutoencoderKL (the SD VAE), pure-pytree, NHWC.

The reference freezes the VAE and uses only ``encode`` during finetuning
(``sd-finetuner/finetuner.py:484-500`` latents = vae.encode(x).sample() *
0.18215) and ``decode`` during serving (``online-inference/
stable-diffusion/service/service.py`` pipeline).  Standard SD-1.x
topology: conv_in → N down blocks (2 resnets each, stride-2 conv between)
→ mid (resnet, self-attn, resnet) → moments; decoder mirrors with
nearest-neighbor upsampling.
"""

from __future__ import annotations

import dataclasses
from typing import Any

import jax
import jax.numpy as jnp

from kubernetes_cloud_tpu.models.diffusion.nn2d import (
    conv2d,
    conv_init,
    downsample,
    downsample_init,
    group_norm,
    group_norm_init,
    resnet_block,
    resnet_block_init,
    self_attention_2d,
    self_attention_2d_init,
    upsample,
    upsample_init,
)

Params = dict[str, Any]


@dataclasses.dataclass(frozen=True)
class VAEConfig:
    in_channels: int = 3
    latent_channels: int = 4
    block_out_channels: tuple = (128, 256, 512, 512)
    layers_per_block: int = 2
    norm_groups: int = 32
    scaling_factor: float = 0.18215


def vae_init(cfg: VAEConfig, rng: jax.Array) -> Params:
    n_blocks = len(cfg.block_out_channels)
    keys = iter(jax.random.split(rng, 64))
    ch0 = cfg.block_out_channels[0]
    chN = cfg.block_out_channels[-1]

    enc: Params = {"conv_in": conv_init(next(keys), 3, 3, cfg.in_channels,
                                        ch0)}
    cin = ch0
    down = []
    for i, cout in enumerate(cfg.block_out_channels):
        blk: Params = {"resnets": []}
        for _ in range(cfg.layers_per_block):
            blk["resnets"].append(resnet_block_init(next(keys), cin, cout))
            cin = cout
        if i < n_blocks - 1:
            blk["down"] = downsample_init(next(keys), cout)
        down.append(blk)
    enc["down"] = down
    enc["mid"] = {
        "res1": resnet_block_init(next(keys), chN, chN),
        "attn": self_attention_2d_init(next(keys), chN),
        "res2": resnet_block_init(next(keys), chN, chN),
    }
    enc["norm_out"] = group_norm_init(chN)
    enc["conv_out"] = conv_init(next(keys), 3, 3, chN,
                                2 * cfg.latent_channels)

    dec: Params = {"conv_in": conv_init(next(keys), 3, 3,
                                        cfg.latent_channels, chN)}
    dec["mid"] = {
        "res1": resnet_block_init(next(keys), chN, chN),
        "attn": self_attention_2d_init(next(keys), chN),
        "res2": resnet_block_init(next(keys), chN, chN),
    }
    cin = chN
    up = []
    for i, cout in enumerate(reversed(cfg.block_out_channels)):
        blk = {"resnets": []}
        for _ in range(cfg.layers_per_block + 1):
            blk["resnets"].append(resnet_block_init(next(keys), cin, cout))
            cin = cout
        if i < n_blocks - 1:
            blk["up"] = upsample_init(next(keys), cout)
        up.append(blk)
    dec["up"] = up
    dec["norm_out"] = group_norm_init(ch0)
    dec["conv_out"] = conv_init(next(keys), 3, 3, ch0, cfg.in_channels)
    return {"encoder": enc, "decoder": dec}


def _encode_moments(cfg: VAEConfig, params: Params, x: jax.Array) -> jax.Array:
    g = cfg.norm_groups
    p = params["encoder"]
    h = conv2d(p["conv_in"], x)
    for blk in p["down"]:
        for r in blk["resnets"]:
            h = resnet_block(r, h, groups=g)
        if "down" in blk:
            h = downsample(blk["down"], h)
    h = resnet_block(p["mid"]["res1"], h, groups=g)
    h = self_attention_2d(p["mid"]["attn"], h, groups=g)
    h = resnet_block(p["mid"]["res2"], h, groups=g)
    h = jax.nn.silu(group_norm(p["norm_out"], h, g))
    return conv2d(p["conv_out"], h)  # [B, h, w, 2*latent]


def vae_encode(cfg: VAEConfig, params: Params, x: jax.Array,
               rng: jax.Array) -> jax.Array:
    """Image [B, H, W, 3] (in [-1, 1]) → scaled latent sample
    [B, H/8, W/8, latent] — the reference's ``vae.encode(...).sample() *
    scaling_factor``."""
    moments = _encode_moments(cfg, params, x)
    if "quant_conv" in params:
        # Diffusers AutoencoderKL applies a 1x1 conv between the encoder
        # and the latent distribution; present only on imported weights.
        moments = conv2d(params["quant_conv"], moments)
    mean, logvar = jnp.split(moments, 2, axis=-1)
    logvar = jnp.clip(logvar.astype(jnp.float32), -30.0, 20.0)
    std = jnp.exp(0.5 * logvar)
    z = mean.astype(jnp.float32) + std * jax.random.normal(
        rng, mean.shape, jnp.float32)
    return (z * cfg.scaling_factor).astype(x.dtype)


def vae_decode(cfg: VAEConfig, params: Params, z: jax.Array) -> jax.Array:
    """Scaled latent → image [B, H, W, 3] in [-1, 1]."""
    g = cfg.norm_groups
    p = params["decoder"]
    z = z / cfg.scaling_factor
    if "post_quant_conv" in params:
        z = conv2d(params["post_quant_conv"], z)
    h = conv2d(p["conv_in"], z)
    h = resnet_block(p["mid"]["res1"], h, groups=g)
    h = self_attention_2d(p["mid"]["attn"], h, groups=g)
    h = resnet_block(p["mid"]["res2"], h, groups=g)
    for blk in p["up"]:
        for r in blk["resnets"]:
            h = resnet_block(r, h, groups=g)
        if "up" in blk:
            h = upsample(blk["up"], h)
    h = jax.nn.silu(group_norm(p["norm_out"], h, g))
    return conv2d(p["conv_out"], h)
