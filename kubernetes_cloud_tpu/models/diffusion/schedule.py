"""DDPM/DDIM noise schedules for latent diffusion.

Replaces the diffusers ``DDPMScheduler``/``DDIMScheduler`` objects the
reference trains and serves with (``sd-finetuner/finetuner.py:467-530``
``noise_scheduler.add_noise`` + v-prediction at ``:502-511``;
``online-inference/stable-diffusion/service/service.py`` sampling loop)
as plain arrays + pure functions, jit/scan-friendly.
"""

from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp


@dataclasses.dataclass(frozen=True)
class NoiseSchedule:
    num_train_timesteps: int = 1000
    beta_start: float = 0.00085
    beta_end: float = 0.012
    schedule: str = "scaled_linear"  # SD's default; or "linear"

    def __post_init__(self):
        if self.schedule not in ("scaled_linear", "linear"):
            raise ValueError(f"unknown beta schedule: {self.schedule!r}")


def make_schedule(cfg: NoiseSchedule = NoiseSchedule()) -> dict[str, jax.Array]:
    """Precompute betas / cumulative alphas (fp32)."""
    if cfg.schedule == "scaled_linear":
        betas = jnp.linspace(cfg.beta_start ** 0.5, cfg.beta_end ** 0.5,
                             cfg.num_train_timesteps,
                             dtype=jnp.float32) ** 2
    else:
        betas = jnp.linspace(cfg.beta_start, cfg.beta_end,
                             cfg.num_train_timesteps, dtype=jnp.float32)
    alphas_cumprod = jnp.cumprod(1.0 - betas)
    return {"betas": betas, "alphas_cumprod": alphas_cumprod}


def _gather(acp: jax.Array, t: jax.Array, ndim: int) -> tuple[jax.Array,
                                                              jax.Array]:
    """sqrt(acp[t]), sqrt(1-acp[t]) broadcast to rank ``ndim``."""
    a = acp[t]
    shape = (-1,) + (1,) * (ndim - 1)
    return (jnp.sqrt(a).reshape(shape), jnp.sqrt(1.0 - a).reshape(shape))


def add_noise(sched: dict, x0: jax.Array, noise: jax.Array,
              t: jax.Array) -> jax.Array:
    """Forward process q(x_t | x_0)."""
    sa, sna = _gather(sched["alphas_cumprod"], t, x0.ndim)
    return (sa * x0.astype(jnp.float32)
            + sna * noise.astype(jnp.float32)).astype(x0.dtype)


def velocity_target(sched: dict, x0: jax.Array, noise: jax.Array,
                    t: jax.Array) -> jax.Array:
    """v-prediction target (``get_velocity``; reference v-pred support at
    ``sd-finetuner/finetuner.py:502-511``)."""
    sa, sna = _gather(sched["alphas_cumprod"], t, x0.ndim)
    return (sa * noise.astype(jnp.float32)
            - sna * x0.astype(jnp.float32)).astype(x0.dtype)


def pred_x0(sched: dict, model_out: jax.Array, sample: jax.Array,
            t: jax.Array, prediction_type: str = "epsilon") -> jax.Array:
    """Recover x0 from the model output under either parameterization."""
    sa, sna = _gather(sched["alphas_cumprod"], t, sample.ndim)
    sample = sample.astype(jnp.float32)
    model_out = model_out.astype(jnp.float32)
    if prediction_type == "epsilon":
        return (sample - sna * model_out) / sa
    if prediction_type == "v_prediction":
        return sa * sample - sna * model_out
    raise ValueError(f"unknown prediction_type: {prediction_type!r}")


def ddim_step(sched: dict, model_out: jax.Array, sample: jax.Array,
              t: jax.Array, t_prev: jax.Array,
              prediction_type: str = "epsilon") -> jax.Array:
    """One deterministic DDIM update x_t → x_{t_prev} (eta = 0).

    ``t_prev < 0`` means "final step" (alpha_prev = 1).
    """
    x0 = pred_x0(sched, model_out, sample, t, prediction_type)
    acp = sched["alphas_cumprod"]
    a_prev = jnp.where(t_prev >= 0, acp[jnp.maximum(t_prev, 0)], 1.0)
    shape = (-1,) + (1,) * (sample.ndim - 1)
    sa_prev = jnp.sqrt(a_prev).reshape(shape)
    sna_prev = jnp.sqrt(1.0 - a_prev).reshape(shape)
    sa, sna = _gather(acp, t, sample.ndim)
    eps = (sample.astype(jnp.float32) - sa * x0) / sna
    return (sa_prev * x0 + sna_prev * eps).astype(sample.dtype)


def timestep_embedding(t: jax.Array, dim: int,
                       max_period: float = 10000.0) -> jax.Array:
    """Sinusoidal timestep embedding [B] → [B, dim] (fp32)."""
    half = dim // 2
    freqs = jnp.exp(-jnp.log(max_period)
                    * jnp.arange(half, dtype=jnp.float32) / half)
    args = t.astype(jnp.float32)[:, None] * freqs[None, :]
    emb = jnp.concatenate([jnp.cos(args), jnp.sin(args)], axis=-1)
    if dim % 2:
        emb = jnp.pad(emb, ((0, 0), (0, 1)))
    return emb
