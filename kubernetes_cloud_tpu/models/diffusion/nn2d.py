"""2D building blocks shared by the VAE and UNet, NHWC / TPU-native.

NHWC is the layout XLA's TPU conv emitter prefers (channels on the minor,
lane-mapped dimension); GroupNorm statistics run in fp32.
"""

from __future__ import annotations

from typing import Any, Optional

import jax
import jax.numpy as jnp

Params = dict[str, Any]


def conv_init(rng: jax.Array, kh: int, kw: int, cin: int, cout: int,
              param_dtype=jnp.float32) -> Params:
    fan_in = kh * kw * cin
    scale = (1.0 / fan_in) ** 0.5
    w = jax.random.uniform(rng, (kh, kw, cin, cout), jnp.float32,
                           -scale, scale)
    return {"kernel": w.astype(param_dtype),
            "bias": jnp.zeros((cout,), param_dtype)}


def conv2d(p: Params, x: jax.Array, *, stride: int = 1,
           padding="SAME", dtype=None) -> jax.Array:
    dtype = dtype or x.dtype
    y = jax.lax.conv_general_dilated(
        x.astype(dtype), p["kernel"].astype(dtype),
        window_strides=(stride, stride), padding=padding,
        dimension_numbers=("NHWC", "HWIO", "NHWC"))
    return y + p["bias"].astype(dtype)


def group_norm_init(ch: int, param_dtype=jnp.float32) -> Params:
    return {"scale": jnp.ones((ch,), param_dtype),
            "bias": jnp.zeros((ch,), param_dtype)}


def group_norm(p: Params, x: jax.Array, groups: int = 32,
               eps: float = 1e-6) -> jax.Array:
    b, h, w, c = x.shape
    g = min(groups, c)
    x32 = x.astype(jnp.float32).reshape(b, h, w, g, c // g)
    mean = x32.mean(axis=(1, 2, 4), keepdims=True)
    var = jnp.square(x32 - mean).mean(axis=(1, 2, 4), keepdims=True)
    y = ((x32 - mean) * jax.lax.rsqrt(var + eps)).reshape(b, h, w, c)
    return (y * p["scale"] + p["bias"]).astype(x.dtype)


def linear_init(rng: jax.Array, din: int, dout: int,
                param_dtype=jnp.float32, scale: Optional[float] = None,
                bias: bool = True) -> Params:
    if scale is None:
        scale = (1.0 / din) ** 0.5
    w = jax.random.uniform(rng, (din, dout), jnp.float32, -scale, scale)
    p = {"w": w.astype(param_dtype)}
    if bias:
        p["b"] = jnp.zeros((dout,), param_dtype)
    return p


def linear(p: Params, x: jax.Array, dtype=None) -> jax.Array:
    dtype = dtype or x.dtype
    y = x.astype(dtype) @ p["w"].astype(dtype)
    if "b" in p:
        y = y + p["b"].astype(dtype)
    return y


def resnet_block_init(rng: jax.Array, cin: int, cout: int,
                      temb_dim: Optional[int] = None,
                      param_dtype=jnp.float32) -> Params:
    k = jax.random.split(rng, 4)
    p: Params = {
        "norm1": group_norm_init(cin, param_dtype),
        "conv1": conv_init(k[0], 3, 3, cin, cout, param_dtype),
        "norm2": group_norm_init(cout, param_dtype),
        "conv2": conv_init(k[1], 3, 3, cout, cout, param_dtype),
    }
    if temb_dim is not None:
        p["temb"] = linear_init(k[2], temb_dim, cout, param_dtype)
    if cin != cout:
        p["shortcut"] = conv_init(k[3], 1, 1, cin, cout, param_dtype)
    return p


def resnet_block(p: Params, x: jax.Array,
                 temb: Optional[jax.Array] = None,
                 groups: int = 32) -> jax.Array:
    h = jax.nn.silu(group_norm(p["norm1"], x, groups))
    h = conv2d(p["conv1"], h)
    if temb is not None and "temb" in p:
        h = h + linear(p["temb"], jax.nn.silu(temb),
                       dtype=h.dtype)[:, None, None, :]
    h = jax.nn.silu(group_norm(p["norm2"], h, groups))
    h = conv2d(p["conv2"], h)
    if "shortcut" in p:
        x = conv2d(p["shortcut"], x)
    return x + h


def self_attention_2d_init(rng: jax.Array, ch: int,
                           param_dtype=jnp.float32) -> Params:
    k = jax.random.split(rng, 5)
    return {
        "norm": group_norm_init(ch, param_dtype),
        "q": linear_init(k[0], ch, ch, param_dtype),
        "k": linear_init(k[1], ch, ch, param_dtype),
        "v": linear_init(k[2], ch, ch, param_dtype),
        "out": linear_init(k[3], ch, ch, param_dtype),
    }


def self_attention_2d(p: Params, x: jax.Array,
                      groups: int = 32) -> jax.Array:
    """Single-head self-attention over spatial positions (VAE mid block)."""
    b, h, w, c = x.shape
    y = group_norm(p["norm"], x, groups).reshape(b, h * w, c)
    q, k, v = linear(p["q"], y), linear(p["k"], y), linear(p["v"], y)
    logits = jnp.einsum("bqc,bkc->bqk", q, k).astype(jnp.float32)
    probs = jax.nn.softmax(logits * (c ** -0.5), axis=-1).astype(y.dtype)
    o = jnp.einsum("bqk,bkc->bqc", probs, v)
    return x + linear(p["out"], o).reshape(b, h, w, c)


def downsample_init(rng: jax.Array, ch: int, param_dtype=jnp.float32):
    return {"conv": conv_init(rng, 3, 3, ch, ch, param_dtype)}


def downsample(p: Params, x: jax.Array, pad: str = "asym") -> jax.Array:
    # SD's VAE encoder uses asymmetric (0,1) padding for its stride-2
    # downsampling convs; the UNet's downsamplers pad symmetrically (1,1).
    # The distinction matters for weight-import parity.
    lohi = (0, 1) if pad == "asym" else (1, 1)
    x = jnp.pad(x, ((0, 0), lohi, lohi, (0, 0)))
    return conv2d(p["conv"], x, stride=2, padding="VALID")


def upsample_init(rng: jax.Array, ch: int, param_dtype=jnp.float32):
    return {"conv": conv_init(rng, 3, 3, ch, ch, param_dtype)}


def upsample(p: Params, x: jax.Array) -> jax.Array:
    b, h, w, c = x.shape
    x = jax.image.resize(x, (b, 2 * h, 2 * w, c), method="nearest")
    return conv2d(p["conv"], x)
