"""UNet2DCondition (the SD denoiser), pure-pytree, NHWC.

The module the reference actually trains (VAE and CLIP are frozen,
``sd-finetuner/finetuner.py:661-663``): a conditional UNet with timestep
embeddings, cross-attention to the CLIP text states in every spatial
transformer, skip connections between down and up paths.  SD-1.x
topology: block channels (320, 640, 1280, 1280), 2 resnets per block,
one transformer layer per attention block, 8 heads, cross-attn dim 768.

Config-driven so tests run a tiny instance; attention uses the shared
:mod:`ops.attention` (pallas-eligible on TPU for fused shapes).
"""

from __future__ import annotations

import dataclasses
from typing import Any, Optional

import jax
import jax.numpy as jnp

from kubernetes_cloud_tpu.models.diffusion.nn2d import (
    conv2d,
    conv_init,
    downsample,
    downsample_init,
    group_norm,
    group_norm_init,
    linear,
    linear_init,
    resnet_block,
    resnet_block_init,
    upsample,
    upsample_init,
)
from kubernetes_cloud_tpu.models.diffusion.schedule import timestep_embedding
from kubernetes_cloud_tpu.ops.attention import attention

Params = dict[str, Any]


@dataclasses.dataclass(frozen=True)
class UNetConfig:
    in_channels: int = 4
    out_channels: int = 4
    block_out_channels: tuple = (320, 640, 1280, 1280)
    layers_per_block: int = 2
    cross_attn_dim: int = 768
    # int (SD-1.x: 8 everywhere) or per-down-block tuple (SD-2.x configs
    # list heads per block, e.g. (5, 10, 20, 20)); up blocks mirror.
    num_heads: Any = 8
    norm_groups: int = 32
    # blocks with a spatial transformer (SD: all but the last down block /
    # first up block)
    attn_blocks: Optional[tuple] = None  # None => all but innermost
    dtype: Any = jnp.bfloat16

    @property
    def temb_dim(self) -> int:
        return 4 * self.block_out_channels[0]

    def has_attn(self, i: int) -> bool:
        if self.attn_blocks is not None:
            return i in self.attn_blocks
        return i < len(self.block_out_channels) - 1

    def heads_at(self, i: int) -> int:
        """Attention heads for down-block ``i`` (up blocks mirror)."""
        if isinstance(self.num_heads, (tuple, list)):
            return self.num_heads[i]
        return self.num_heads


def _xattn_init(rng: jax.Array, ch: int, ctx: int, heads: int) -> Params:
    """One BasicTransformerBlock: self-attn, cross-attn, geglu FF."""
    k = iter(jax.random.split(rng, 16))
    inner = ch

    def attn(kdim):
        return {
            "q": linear_init(next(k), ch, inner, bias=False),
            "k": linear_init(next(k), kdim, inner, bias=False),
            "v": linear_init(next(k), kdim, inner, bias=False),
            "out": linear_init(next(k), inner, ch),
        }

    def ln():
        return {"scale": jnp.ones((ch,), jnp.float32),
                "bias": jnp.zeros((ch,), jnp.float32)}

    return {
        "norm1": ln(), "attn1": attn(ch),
        "norm2": ln(), "attn2": attn(ctx),
        "norm3": ln(),
        "ff1": linear_init(next(k), ch, 8 * ch),   # geglu: 2 * 4ch
        "ff2": linear_init(next(k), 4 * ch, ch),
    }


def _spatial_transformer_init(rng: jax.Array, ch: int, ctx: int,
                              heads: int) -> Params:
    k = iter(jax.random.split(rng, 4))
    return {
        "norm": group_norm_init(ch),
        "proj_in": linear_init(next(k), ch, ch),
        "block": _xattn_init(next(k), ch, ctx, heads),
        "proj_out": linear_init(next(k), ch, ch),
    }


def _mh_attn(p: Params, x: jax.Array, ctx: jax.Array,
             heads: int) -> jax.Array:
    b, s, c = x.shape
    dh = c // heads
    q = linear(p["q"], x).reshape(b, s, heads, dh)
    k = linear(p["k"], ctx).reshape(b, ctx.shape[1], heads, dh)
    v = linear(p["v"], ctx).reshape(b, ctx.shape[1], heads, dh)
    o = attention(q, k, v, causal=False, impl="xla")
    return linear(p["out"], o.reshape(b, s, c))


def _layer_norm(p: Params, x: jax.Array) -> jax.Array:
    x32 = x.astype(jnp.float32)
    mean = x32.mean(-1, keepdims=True)
    var = jnp.square(x32 - mean).mean(-1, keepdims=True)
    y = (x32 - mean) * jax.lax.rsqrt(var + 1e-5)
    return (y * p["scale"] + p["bias"]).astype(x.dtype)


def _spatial_transformer(p: Params, x: jax.Array, ctx: jax.Array,
                         heads: int, groups: int) -> jax.Array:
    b, h, w, c = x.shape
    y = group_norm(p["norm"], x, groups).reshape(b, h * w, c)
    y = linear(p["proj_in"], y)
    blk = p["block"]
    y1 = _layer_norm(blk["norm1"], y)
    y = y + _mh_attn(blk["attn1"], y1, y1, heads)
    y = y + _mh_attn(blk["attn2"], _layer_norm(blk["norm2"], y), ctx,
                     heads)
    z = linear(blk["ff1"], _layer_norm(blk["norm3"], y))
    z1, z2 = jnp.split(z, 2, axis=-1)
    # geglu's gate uses exact (erf) gelu, matching the weights' provenance
    y = y + linear(blk["ff2"], z1 * jax.nn.gelu(z2, approximate=False))
    y = linear(p["proj_out"], y)
    return x + y.reshape(b, h, w, c)


def unet_init(cfg: UNetConfig, rng: jax.Array) -> Params:
    keys = iter(jax.random.split(rng, 256))
    chans = cfg.block_out_channels
    ch0 = chans[0]
    temb = cfg.temb_dim

    p: Params = {
        "time_mlp1": linear_init(next(keys), ch0, temb),
        "time_mlp2": linear_init(next(keys), temb, temb),
        "conv_in": conv_init(next(keys), 3, 3, cfg.in_channels, ch0),
    }

    down = []
    cin = ch0
    for i, cout in enumerate(chans):
        blk: Params = {"resnets": [], "attns": []}
        for _ in range(cfg.layers_per_block):
            blk["resnets"].append(
                resnet_block_init(next(keys), cin, cout, temb))
            cin = cout
            if cfg.has_attn(i):
                blk["attns"].append(_spatial_transformer_init(
                    next(keys), cout, cfg.cross_attn_dim, cfg.num_heads))
        if i < len(chans) - 1:
            blk["down"] = downsample_init(next(keys), cout)
        down.append(blk)
    p["down"] = down

    chN = chans[-1]
    p["mid"] = {
        "res1": resnet_block_init(next(keys), chN, chN, temb),
        "attn": _spatial_transformer_init(next(keys), chN,
                                          cfg.cross_attn_dim,
                                          cfg.num_heads),
        "res2": resnet_block_init(next(keys), chN, chN, temb),
    }

    # Up path: skip channels come off the down-path stack in reverse.
    skip_chans = [ch0]
    cin_d = ch0
    for i, cout in enumerate(chans):
        for _ in range(cfg.layers_per_block):
            skip_chans.append(cout)
            cin_d = cout
        if i < len(chans) - 1:
            skip_chans.append(cout)

    up = []
    cin = chN
    rev = list(reversed(chans))
    for i, cout in enumerate(rev):
        blk = {"resnets": [], "attns": []}
        attn_i = len(chans) - 1 - i
        for _ in range(cfg.layers_per_block + 1):
            skip = skip_chans.pop()
            blk["resnets"].append(
                resnet_block_init(next(keys), cin + skip, cout, temb))
            cin = cout
            if cfg.has_attn(attn_i):
                blk["attns"].append(_spatial_transformer_init(
                    next(keys), cout, cfg.cross_attn_dim, cfg.num_heads))
        if i < len(chans) - 1:
            blk["up"] = upsample_init(next(keys), cout)
        up.append(blk)
    p["up"] = up

    p["norm_out"] = group_norm_init(ch0)
    p["conv_out"] = conv_init(next(keys), 3, 3, ch0, cfg.out_channels)
    return p


def unet_apply(cfg: UNetConfig, params: Params, x: jax.Array,
               t: jax.Array, ctx: jax.Array) -> jax.Array:
    """(latents [B,h,w,C], timesteps [B], text states [B,S,ctx_dim]) →
    predicted noise/velocity [B,h,w,C]."""
    g = cfg.norm_groups
    x = x.astype(cfg.dtype)
    ctx = ctx.astype(cfg.dtype)

    temb = timestep_embedding(t, cfg.block_out_channels[0])
    temb = linear(params["time_mlp2"],
                  jax.nn.silu(linear(params["time_mlp1"],
                                     temb.astype(cfg.dtype))))

    n = len(cfg.block_out_channels)
    h = conv2d(params["conv_in"], x)
    skips = [h]
    for i, blk in enumerate(params["down"]):
        attns = blk.get("attns") or []  # empty lists vanish in serialization
        for j, r in enumerate(blk["resnets"]):
            h = resnet_block(r, h, temb, groups=g)
            if attns:
                h = _spatial_transformer(attns[j], h, ctx,
                                         cfg.heads_at(i), g)
            skips.append(h)
        if "down" in blk:
            h = downsample(blk["down"], h, pad="same")
            skips.append(h)

    h = resnet_block(params["mid"]["res1"], h, temb, groups=g)
    h = _spatial_transformer(params["mid"]["attn"], h, ctx,
                             cfg.heads_at(n - 1), g)
    h = resnet_block(params["mid"]["res2"], h, temb, groups=g)

    for i, blk in enumerate(params["up"]):
        attns = blk.get("attns") or []
        for j, r in enumerate(blk["resnets"]):
            h = jnp.concatenate([h, skips.pop()], axis=-1)
            h = resnet_block(r, h, temb, groups=g)
            if attns:
                h = _spatial_transformer(attns[j], h, ctx,
                                         cfg.heads_at(n - 1 - i), g)
        if "up" in blk:
            h = upsample(blk["up"], h)

    h = jax.nn.silu(group_norm(params["norm_out"], h, g))
    return conv2d(params["conv_out"], h).astype(jnp.float32)
