"""CLIP text encoder (the SD conditioning tower), pure-pytree.

The reference loads ``CLIPTextModel`` from transformers and freezes it
(``sd-finetuner-workflow/sd-finetuner/finetuner.py:648-663``); serving
deserializes it as the ``encoder`` module (``online-inference/
stable-diffusion/serializer/serialize.py:13-50``).  Architecture: causal
transformer encoder with quick-GELU, learned positions, final LayerNorm;
SD-1.x uses the ViT-L/14 text tower (hidden 768, 12 layers).
"""

from __future__ import annotations

import dataclasses
from typing import Any

import jax
import jax.numpy as jnp

from kubernetes_cloud_tpu.ops.attention import attention
from kubernetes_cloud_tpu.ops.layers import layer_norm

Params = dict[str, Any]


@dataclasses.dataclass(frozen=True)
class CLIPTextConfig:
    vocab_size: int = 49408
    hidden_size: int = 768
    num_layers: int = 12
    num_heads: int = 12
    max_length: int = 77
    # SD-1.x's ViT-L tower uses quick-gelu; SD-2.x's OpenCLIP-derived
    # tower uses exact gelu (hidden_act in the HF config).
    act: str = "quick_gelu"
    dtype: Any = jnp.bfloat16
    param_dtype: Any = jnp.float32

    @property
    def head_dim(self) -> int:
        return self.hidden_size // self.num_heads

    @property
    def ffn_size(self) -> int:
        return 4 * self.hidden_size


def clip_init(cfg: CLIPTextConfig, rng: jax.Array) -> Params:
    keys = jax.random.split(rng, 6)
    d, l, f = cfg.hidden_size, cfg.num_layers, cfg.ffn_size

    def normal(key, shape, s=0.02):
        return (jax.random.normal(key, shape, jnp.float32) * s).astype(
            cfg.param_dtype)

    def ln(prefix=()):
        return {"scale": jnp.ones((*prefix, d), cfg.param_dtype),
                "bias": jnp.zeros((*prefix, d), cfg.param_dtype)}

    return {
        "wte": normal(keys[0], (cfg.vocab_size, d)),
        "wpe": normal(keys[1], (cfg.max_length, d)),
        "blocks": {
            "ln1": ln((l,)),
            "ln2": ln((l,)),
            "wqkv": normal(keys[2], (l, d, 3 * d)),
            "bqkv": jnp.zeros((l, 3 * d), cfg.param_dtype),
            "wo": normal(keys[3], (l, d, d)),
            "bo": jnp.zeros((l, d), cfg.param_dtype),
            "wi": normal(keys[4], (l, d, f)),
            "bi": jnp.zeros((l, f), cfg.param_dtype),
            "wout": normal(keys[5], (l, f, d)),
            "bout": jnp.zeros((l, d), cfg.param_dtype),
        },
        "final_ln": ln(),
    }


def _quick_gelu(x: jax.Array) -> jax.Array:
    return x * jax.nn.sigmoid(1.702 * x)


def clip_encode(cfg: CLIPTextConfig, params: Params,
                input_ids: jax.Array) -> jax.Array:
    """Token ids [B, S] → last hidden states [B, S, D] (post final LN) —
    the conditioning tensor SD's UNet cross-attends to."""
    b, s = input_ids.shape
    x = (params["wte"][input_ids]
         + params["wpe"][:s][None]).astype(cfg.dtype)
    h, dh = cfg.num_heads, cfg.head_dim
    act = (_quick_gelu if cfg.act == "quick_gelu"
           else lambda y: jax.nn.gelu(y, approximate=False))

    def body(carry, p):
        x = carry
        y = layer_norm(x, p["ln1"]["scale"], p["ln1"]["bias"])
        qkv = jnp.einsum("bsd,de->bse", y, p["wqkv"].astype(cfg.dtype))
        qkv = qkv + p["bqkv"].astype(cfg.dtype)
        q, k, v = jnp.split(qkv.reshape(b, s, 3 * h, dh), 3, axis=2)
        a = attention(q, k, v, causal=True, impl="xla")
        a = a.reshape(b, s, -1)
        a = jnp.einsum("bsd,de->bse", a, p["wo"].astype(cfg.dtype))
        x = x + a + p["bo"].astype(cfg.dtype)
        y = layer_norm(x, p["ln2"]["scale"], p["ln2"]["bias"])
        y = jnp.einsum("bsd,df->bsf", y, p["wi"].astype(cfg.dtype))
        y = act(y + p["bi"].astype(cfg.dtype))
        y = jnp.einsum("bsf,fd->bsd", y, p["wout"].astype(cfg.dtype))
        return x + y + p["bout"].astype(cfg.dtype), None

    x, _ = jax.lax.scan(body, x, params["blocks"])
    return layer_norm(x, params["final_ln"]["scale"],
                      params["final_ln"]["bias"])
