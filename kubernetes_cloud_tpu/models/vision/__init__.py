from kubernetes_cloud_tpu.models.vision.resnet import (  # noqa: F401
    PRESETS,
    ResNetConfig,
    forward,
    init_params,
    loss_fn,
    topk_accuracy,
)
