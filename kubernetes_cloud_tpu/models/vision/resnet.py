"""ResNet image classifiers, TPU-first.

Covers the reference's ResNet50 ImageNet trainers
(``kubeflow/training-operator/resnet50/resnet50_pytorch.py``,
``resnet50_horovod.py`` — the same torchvision model trained two ways) and
the TF-2 Inception-class serving path (``online-inference/image-classifier``)
as one configurable residual family (depths 18/34/50/101/152).

Design (deliberately not a torch translation):

* **NHWC layout.** TPUs tile convolutions onto the MXU in NHWC; torch's
  NCHW would force layout transposes at every op.  Conv kernels are HWIO.
* **Pure pytrees + functions**, like :mod:`..causal_lm`: ``init_params``
  returns nested dicts, ``forward`` is pure.  BatchNorm running statistics
  live in a separate ``batch_stats`` pytree threaded through ``forward``
  (functional state, not module attributes).
* **Global BatchNorm for free.** Under ``jit`` with the batch sharded over
  the ``data`` axis, ``jnp.mean`` over the batch dim is the *global* mean —
  XLA inserts the cross-replica reduction.  The reference's per-GPU-stats
  DDP BatchNorm is strictly weaker; sync-BN is the default here.
* **bf16 compute, fp32 statistics.** Convs/matmuls run in bfloat16 on the
  MXU; BN statistics, softmax and loss run in float32 (the mixed-precision
  discipline ``util.py:20-67`` gets from torch.cuda.amp).
"""

from __future__ import annotations

import dataclasses
from typing import Any, Optional

import jax
import jax.numpy as jnp

Params = dict[str, Any]

# Per-depth (block type, blocks per stage).  Bottleneck blocks expand
# channels 4x (torchvision's resnet.py layout, reproduced from the
# architecture, not the code).
_DEPTHS = {
    18: ("basic", (2, 2, 2, 2)),
    34: ("basic", (3, 4, 6, 3)),
    50: ("bottleneck", (3, 4, 6, 3)),
    101: ("bottleneck", (3, 4, 23, 3)),
    152: ("bottleneck", (3, 8, 36, 3)),
}


@dataclasses.dataclass(frozen=True)
class ResNetConfig:
    depth: int = 50
    num_classes: int = 1000
    width: int = 64  # stem channels; stages run width * (1, 2, 4, 8)
    bn_momentum: float = 0.9  # running-stat EMA decay (torch's 1 - 0.1)
    bn_eps: float = 1e-5
    dtype: Any = jnp.bfloat16
    param_dtype: Any = jnp.float32

    def __post_init__(self):
        if self.depth not in _DEPTHS:
            raise ValueError(
                f"depth must be one of {sorted(_DEPTHS)}, got {self.depth}")

    @property
    def block(self) -> str:
        return _DEPTHS[self.depth][0]

    @property
    def stage_sizes(self) -> tuple[int, ...]:
        return _DEPTHS[self.depth][1]

    @property
    def expansion(self) -> int:
        return 4 if self.block == "bottleneck" else 1


PRESETS = {
    "resnet18": ResNetConfig(depth=18),
    "resnet34": ResNetConfig(depth=34),
    "resnet50": ResNetConfig(depth=50),
    "resnet101": ResNetConfig(depth=101),
    "resnet152": ResNetConfig(depth=152),
    # CIFAR-scale config for tests and the CPU smoke path.
    "resnet-tiny": ResNetConfig(depth=18, num_classes=10, width=8),
}


# --------------------------------------------------------------------------
# init


def _conv_init(rng, kh, kw, cin, cout, dtype):
    # He/Kaiming normal (fan_out, relu), the standard ResNet init.
    fan_out = kh * kw * cout
    std = jnp.sqrt(2.0 / fan_out)
    return (jax.random.normal(rng, (kh, kw, cin, cout)) * std).astype(dtype)


def _bn_init(c, dtype):
    return {"scale": jnp.ones((c,), dtype), "bias": jnp.zeros((c,), dtype)}


def _bn_stats_init(c):
    # Running stats are always fp32.
    return {"mean": jnp.zeros((c,), jnp.float32),
            "var": jnp.ones((c,), jnp.float32)}


def init_params(cfg: ResNetConfig, rng: jax.Array) -> tuple[Params, Params]:
    """Returns ``(params, batch_stats)``."""
    pd = cfg.param_dtype
    n_convs = 2 + sum(cfg.stage_sizes) * (3 if cfg.block == "bottleneck"
                                          else 2) + 4
    rngs = iter(jax.random.split(rng, n_convs + 1))

    params: Params = {}
    stats: Params = {}
    params["stem"] = {
        "kernel": _conv_init(next(rngs), 7, 7, 3, cfg.width, pd),
        "bn": _bn_init(cfg.width, pd),
    }
    stats["stem"] = {"bn": _bn_stats_init(cfg.width)}

    cin = cfg.width
    for s, n_blocks in enumerate(cfg.stage_sizes):
        planes = cfg.width * (2 ** s)
        cout = planes * cfg.expansion
        stage_p, stage_s = [], []
        for b in range(n_blocks):
            stride = 2 if (s > 0 and b == 0) else 1
            bp: Params = {}
            bs: Params = {}
            if cfg.block == "bottleneck":
                shapes = [(1, 1, cin, planes), (3, 3, planes, planes),
                          (1, 1, planes, cout)]
            else:
                shapes = [(3, 3, cin, planes), (3, 3, planes, cout)]
            for i, (kh, kw, ci, co) in enumerate(shapes):
                bp[f"conv{i}"] = {
                    "kernel": _conv_init(next(rngs), kh, kw, ci, co, pd),
                    "bn": _bn_init(co, pd),
                }
                bs[f"conv{i}"] = {"bn": _bn_stats_init(co)}
            if stride != 1 or cin != cout:
                bp["proj"] = {
                    "kernel": _conv_init(next(rngs), 1, 1, cin, cout, pd),
                    "bn": _bn_init(cout, pd),
                }
                bs["proj"] = {"bn": _bn_stats_init(cout)}
            stage_p.append(bp)
            stage_s.append(bs)
            cin = cout
        params[f"stage{s}"] = stage_p
        stats[f"stage{s}"] = stage_s

    head_std = 1.0 / jnp.sqrt(cin)
    params["head"] = {
        "w": (jax.random.uniform(next(rngs), (cin, cfg.num_classes),
                                 minval=-1, maxval=1) * head_std).astype(pd),
        "bias": jnp.zeros((cfg.num_classes,), pd),
    }
    return params, stats


# --------------------------------------------------------------------------
# forward


def _conv(x, kernel, *, stride=1, dtype=jnp.bfloat16):
    return jax.lax.conv_general_dilated(
        x.astype(dtype), kernel.astype(dtype),
        window_strides=(stride, stride),
        padding="SAME" if kernel.shape[0] > 1 else "VALID",
        dimension_numbers=("NHWC", "HWIO", "NHWC"),
    )


def _batch_norm(x, p, s, *, train, momentum, eps):
    """Functional BatchNorm.  Returns ``(y, new_stats)``; statistics in
    fp32.  Under pjit with a data-sharded batch the reductions are global
    (sync-BN)."""
    xf = x.astype(jnp.float32)
    if train:
        mean = jnp.mean(xf, axis=(0, 1, 2))
        var = jnp.var(xf, axis=(0, 1, 2))
        # Running stats fold in the *unbiased* variance (n/(n-1)), like
        # torch BatchNorm; normalization itself uses the biased estimate.
        n = xf.shape[0] * xf.shape[1] * xf.shape[2]
        unbiased = var * (n / max(n - 1, 1))
        new_stats = {
            "mean": momentum * s["mean"] + (1 - momentum) * mean,
            "var": momentum * s["var"] + (1 - momentum) * unbiased,
        }
    else:
        mean, var = s["mean"], s["var"]
        new_stats = s
    y = (xf - mean) * jax.lax.rsqrt(var + eps)
    y = y * p["scale"].astype(jnp.float32) + p["bias"].astype(jnp.float32)
    return y.astype(x.dtype), new_stats


def _conv_bn(x, p, s, *, stride, relu, train, cfg):
    y = _conv(x, p["kernel"], stride=stride, dtype=cfg.dtype)
    y, ns = _batch_norm(y, p["bn"], s["bn"], train=train,
                        momentum=cfg.bn_momentum, eps=cfg.bn_eps)
    if relu:
        y = jax.nn.relu(y)
    return y, {"bn": ns}


def _block(x, bp, bs, *, stride, cfg, train):
    ns: Params = {}
    if cfg.block == "bottleneck":
        y, ns["conv0"] = _conv_bn(x, bp["conv0"], bs["conv0"], stride=1,
                                  relu=True, train=train, cfg=cfg)
        y, ns["conv1"] = _conv_bn(y, bp["conv1"], bs["conv1"], stride=stride,
                                  relu=True, train=train, cfg=cfg)
        y, ns["conv2"] = _conv_bn(y, bp["conv2"], bs["conv2"], stride=1,
                                  relu=False, train=train, cfg=cfg)
    else:
        y, ns["conv0"] = _conv_bn(x, bp["conv0"], bs["conv0"], stride=stride,
                                  relu=True, train=train, cfg=cfg)
        y, ns["conv1"] = _conv_bn(y, bp["conv1"], bs["conv1"], stride=1,
                                  relu=False, train=train, cfg=cfg)
    if "proj" in bp:
        shortcut, ns["proj"] = _conv_bn(x, bp["proj"], bs["proj"],
                                        stride=stride, relu=False,
                                        train=train, cfg=cfg)
    else:
        shortcut = x
    return jax.nn.relu(y + shortcut), ns


def forward(
    cfg: ResNetConfig,
    params: Params,
    images: jax.Array,  # [B, H, W, 3], float
    batch_stats: Params,
    *,
    train: bool = False,
) -> tuple[jax.Array, Params]:
    """Returns ``(logits[B, num_classes] fp32, new_batch_stats)``."""
    new_stats: Params = {}
    x = _conv(images, params["stem"]["kernel"], stride=2, dtype=cfg.dtype)
    x, sbn = _batch_norm(x, params["stem"]["bn"], batch_stats["stem"]["bn"],
                         train=train, momentum=cfg.bn_momentum,
                         eps=cfg.bn_eps)
    new_stats["stem"] = {"bn": sbn}
    x = jax.nn.relu(x)
    x = jax.lax.reduce_window(
        x, -jnp.inf, jax.lax.max, (1, 3, 3, 1), (1, 2, 2, 1),
        "SAME")

    for s in range(len(cfg.stage_sizes)):
        stage_ns = []
        for b, (bp, bs) in enumerate(zip(params[f"stage{s}"],
                                         batch_stats[f"stage{s}"])):
            stride = 2 if (s > 0 and b == 0) else 1
            x, ns = _block(x, bp, bs, stride=stride, cfg=cfg, train=train)
            stage_ns.append(ns)
        new_stats[f"stage{s}"] = stage_ns

    x = jnp.mean(x.astype(jnp.float32), axis=(1, 2))  # global average pool
    logits = x @ params["head"]["w"].astype(jnp.float32) + \
        params["head"]["bias"].astype(jnp.float32)
    return logits, new_stats


# --------------------------------------------------------------------------
# loss / metrics


def loss_fn(cfg: ResNetConfig, params: Params, batch: dict,
            batch_stats: Params) -> tuple[jax.Array, dict]:
    """Cross-entropy with label smoothing off (reference parity:
    ``util.py:70-108`` uses plain ``F.cross_entropy``)."""
    logits, new_stats = forward(cfg, params, batch["image"], batch_stats,
                                train=True)
    labels = batch["label"]
    logp = jax.nn.log_softmax(logits, axis=-1)
    nll = -jnp.take_along_axis(logp, labels[:, None], axis=-1)[:, 0]
    loss = jnp.mean(nll)
    acc = jnp.mean((jnp.argmax(logits, -1) == labels).astype(jnp.float32))
    return loss, {"loss": loss, "accuracy": acc, "batch_stats": new_stats}


def topk_correct(logits: jax.Array, labels: jax.Array,
                 ks: tuple[int, ...] = (1, 5)) -> dict:
    """Per-example top-k hit indicators (float 0/1, shape [B]) per k.
    Each k is clamped to the class count (top-5 on a 2-class head is
    top-2), keeping the metric defined for small-class configs."""
    n_classes = logits.shape[-1]
    maxk = min(max(ks), n_classes)
    _, pred = jax.lax.top_k(logits, maxk)  # [B, maxk]
    correct = pred == labels[:, None]
    return {f"top{k}": jnp.any(correct[:, :min(k, n_classes)],
                               axis=1).astype(jnp.float32) for k in ks}


def topk_accuracy(logits: jax.Array, labels: jax.Array,
                  ks: tuple[int, ...] = (1, 5)) -> dict:
    """Top-k accuracies (reference ``util.py:150-166`` ``accuracy()``)."""
    return {k: jnp.mean(v)
            for k, v in topk_correct(logits, labels, ks).items()}
