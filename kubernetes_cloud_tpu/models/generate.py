"""Autoregressive generation with a KV cache.

This is the TPU replacement for the reference's serving decoders — HF
``pipeline("text-generation")`` (``finetuner-workflow/finetuner/
inference.py:80-96``), FasterTransformer's fused CUDA decoder
(``online-inference/fastertransformer/``), and DeepSpeed-Inference kernel
injection (``online-inference/bloom-176b-deepspeed/``).  Design:

* **Prefill + decode split.**  Prefill runs the full-sequence forward once
  and records per-layer K/V (one MXU-heavy program); decode is a second
  compiled program with sequence length 1 that appends to the cache.
* **Static shapes.**  The cache is ``[L, B, max_len, Hkv, Dh]``; decode
  steps run under ``lax.while_loop`` with an all-rows-done early exit, so
  one compilation serves any prompt/completion length ≤ max_len.
* **Sharding.**  The cache shards like activations (batch over
  ``data``/``fsdp``, heads over ``model``), so tensor-parallel serving
  needs no code beyond the usual mesh placement.

The decode block mirrors :func:`causal_lm.forward` exactly;
``tests/test_generate.py`` locks the two paths together
(prefill+decode logits == full-forward logits).
"""

from __future__ import annotations

from typing import Any, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from kubernetes_cloud_tpu.models.causal_lm import (
    CausalLMConfig,
    _embed,
    _finish_block,
    _project_qkv,
    _unembed,
)
from kubernetes_cloud_tpu.ops.attention import attention
from kubernetes_cloud_tpu.ops.layers import alibi_slopes, rope_cache

Params = dict[str, Any]


def init_cache(cfg: CausalLMConfig, batch: int, max_len: int,
               dtype=None) -> dict[str, jax.Array]:
    dtype = dtype or cfg.dtype
    shape = (cfg.num_layers, batch, max_len, cfg.kv_heads, cfg.head_dim)
    return {
        "k": jnp.zeros(shape, dtype),
        "v": jnp.zeros(shape, dtype),
        # number of valid tokens per row
        "length": jnp.zeros((batch,), jnp.int32),
    }


def _alibi_bias(cfg: CausalLMConfig, kpos: jax.Array) -> jax.Array:
    slopes = alibi_slopes(cfg.num_heads)
    return slopes[None, :, None, None] * kpos.astype(jnp.float32)[:, None,
                                                                  None, :]


def prefill(cfg: CausalLMConfig, params: Params, input_ids: jax.Array,
            attention_mask: jax.Array, cache: dict) -> tuple[jax.Array, dict]:
    """Run the prompt through the model, filling cache positions
    ``0..S-1``.  Prompts are right-padded; ``attention_mask`` marks real
    tokens.  Returns (last-real-token logits [B, V], cache).

    Attention dispatches ``impl="auto"``: on TPU with flash-eligible
    shapes (rope positions, 2-D padding mask) the prefill — the
    MXU-heavy half of every prefill-bearing engine iteration the
    flight recorder flags — runs the fused flash kernel; everywhere
    else (CPU tier-1, ALiBi bias, odd shapes) it falls back to the XLA
    path unchanged."""
    b, s = input_ids.shape
    max_len = cache["k"].shape[2]
    lengths = attention_mask.sum(-1).astype(jnp.int32)
    positions = jnp.clip(jnp.cumsum(attention_mask, 1) - 1, 0)

    rope = (rope_cache(max_len, cfg.rotary_dim, cfg.rope_theta)
            if cfg.pos_emb == "rope" else None)
    bias = None
    if cfg.pos_emb == "alibi":
        kpos = positions.astype(jnp.float32)
        bias = _alibi_bias(cfg, kpos)

    x = _embed(cfg, params, input_ids, positions)

    def body(carry, p):
        x = carry
        q, k_new, v_new, attn_in = _project_qkv(
            cfg, p, x, rope=rope, q_positions=positions)
        attn_vec = attention(q, k_new, v_new, causal=True, bias=bias,
                             mask=attention_mask, impl="auto")
        x, _aux = _finish_block(cfg, p, x, attn_vec, attn_in,
                                token_mask=attention_mask, moe_no_drop=True)
        return x, (k_new, v_new)

    x, (ks, vs) = jax.lax.scan(body, x, params["blocks"])

    # Write prompt K/V into the cache (positions 0..S-1).
    cache = dict(cache)
    cache["k"] = jax.lax.dynamic_update_slice(
        cache["k"], ks.astype(cache["k"].dtype), (0, 0, 0, 0, 0))
    cache["v"] = jax.lax.dynamic_update_slice(
        cache["v"], vs.astype(cache["v"].dtype), (0, 0, 0, 0, 0))
    cache["length"] = lengths

    logits = _unembed(cfg, params, x)
    last = jnp.take_along_axis(
        logits, (lengths - 1)[:, None, None].clip(0), axis=1)[:, 0]
    return last, cache


def decode_step(cfg: CausalLMConfig, params: Params, token: jax.Array,
                cache: dict) -> tuple[jax.Array, dict]:
    """One decode step: ``token`` [B] → logits [B, V]; appends to cache."""
    b = token.shape[0]
    max_len = cache["k"].shape[2]
    pos = cache["length"]  # [B] position this token will occupy
    positions = pos[:, None]

    rope = (rope_cache(max_len, cfg.rotary_dim, cfg.rope_theta)
            if cfg.pos_emb == "rope" else None)

    kpos_all = jnp.broadcast_to(jnp.arange(max_len), (b, max_len))
    bias = _alibi_bias(cfg, kpos_all) if cfg.pos_emb == "alibi" else None
    key_mask = kpos_all <= pos[:, None]  # causal: keys up to current pos

    x = _embed(cfg, params, token[:, None], positions)
    rows = jnp.arange(b)

    def body(carry, layer):
        x = carry
        p, ck, cv = layer
        q, k_new, v_new, attn_in = _project_qkv(
            cfg, p, x, rope=rope, q_positions=positions)
        ck = ck.at[rows, pos].set(k_new[:, 0].astype(ck.dtype))
        cv = cv.at[rows, pos].set(v_new[:, 0].astype(cv.dtype))
        attn_vec = attention(q, ck.astype(cfg.dtype), cv.astype(cfg.dtype),
                             causal=False, bias=bias, mask=key_mask,
                             impl="xla")
        x, _aux = _finish_block(cfg, p, x, attn_vec, attn_in,
                                moe_no_drop=True)
        return x, (ck, cv)

    x, (ks, vs) = jax.lax.scan(body, x,
                               (params["blocks"], cache["k"], cache["v"]))
    cache = {"k": ks, "v": vs, "length": cache["length"] + 1}
    return _unembed(cfg, params, x)[:, 0], cache


def prefill_into_slots(cfg: CausalLMConfig, params: Params,
                       input_ids: jax.Array, attention_mask: jax.Array,
                       pool: dict, slot_ids: jax.Array
                       ) -> tuple[jax.Array, dict]:
    """Prefill a new request batch and scatter its K/V into pool rows.

    ``pool`` is a persistent slot-based cache (``init_cache`` with
    batch = SLOTS); ``slot_ids`` [B] names the rows the scheduler
    assigned.  Runs the ordinary :func:`prefill` into a scratch cache of
    the pool's ``max_len`` so the block math (and therefore numerics)
    cannot diverge from one-shot generation, then writes the rows in.
    Returns (last-real-token logits [B, V], pool).
    """
    b = input_ids.shape[0]
    max_len = pool["k"].shape[2]
    scratch = init_cache(cfg, b, max_len, pool["k"].dtype)
    logits, scratch = prefill(cfg, params, input_ids, attention_mask,
                              scratch)
    pool = dict(pool)
    pool["k"] = pool["k"].at[:, slot_ids].set(scratch["k"])
    pool["v"] = pool["v"].at[:, slot_ids].set(scratch["v"])
    pool["length"] = pool["length"].at[slot_ids].set(scratch["length"])
    return logits, pool


def decode_step_slots(cfg: CausalLMConfig, params: Params, tokens: jax.Array,
                      pool: dict, active: jax.Array
                      ) -> tuple[jax.Array, dict]:
    """One decode iteration for every slot in the pool.

    ``tokens`` [SLOTS] is each slot's previously sampled token (pad for
    free slots); ``active`` [SLOTS] bool masks slots holding a request.
    Reuses :func:`decode_step`'s block math unchanged — attention is
    row-independent, so free slots cost FLOPs but cannot perturb active
    rows.  Free slots stay frozen: their length does not advance, and
    their (garbage) K/V write lands at their reset position 0, which the
    next admission's prefill overwrites.  Returns (logits [SLOTS, V],
    pool).
    """
    logits, new = decode_step(cfg, params, tokens, pool)
    new["length"] = jnp.where(active, new["length"], pool["length"])
    return logits, new


# ---------------------------------------------------------------------------
# paged KV pool (vLLM/PagedAttention; serve/continuous.py paged mode)
# ---------------------------------------------------------------------------


#: int8 quantization range (symmetric; -128 unused so the scale maps
#: the per-(page, head) absmax exactly onto the grid edge)
INT8_MAX = 127.0
#: scale floor so an all-zero page can never divide by zero
_SCALE_EPS = 1e-8


def init_page_arena(cfg: CausalLMConfig, num_pages: int, page_size: int,
                    dtype=None, kv_dtype: str = "fp32"
                    ) -> dict[str, jax.Array]:
    """Block-granular KV arena: ``[L, NUM_PAGES, page_size, Hkv, Dh]``.

    Physical page 0 is the *null page* (``serve.paged_kv.NULL_PAGE``):
    free slots' page-table entries point at it, so the all-slots decode
    program has somewhere harmless to park masked garbage writes.  No
    per-row ``length`` lives on device — the paged scheduler owns
    lengths host-side and passes them as program arguments.

    ``kv_dtype="int8"`` stores K/V quantized (symmetric int8) with
    per-page, per-kv-head fp32 scales in parallel ``k_scale``/
    ``v_scale`` buffers ``[L, NUM_PAGES, Hkv]`` — roughly quartering
    (vs fp32; halving vs bf16) the HBM each resident token costs, at a
    measured logit-error budget instead of bitwise token identity
    (:func:`kv_quant_probe`)."""
    shape = (cfg.num_layers, num_pages, page_size, cfg.kv_heads,
             cfg.head_dim)
    if kv_dtype == "int8":
        sshape = (cfg.num_layers, num_pages, cfg.kv_heads)
        return {"k": jnp.zeros(shape, jnp.int8),
                "v": jnp.zeros(shape, jnp.int8),
                "k_scale": jnp.zeros(sshape, jnp.float32),
                "v_scale": jnp.zeros(sshape, jnp.float32)}
    if kv_dtype != "fp32":
        raise ValueError(f"kv_dtype must be 'fp32' or 'int8', got "
                         f"{kv_dtype!r}")
    return {"k": jnp.zeros(shape, dtype or cfg.dtype),
            "v": jnp.zeros(shape, dtype or cfg.dtype)}


def copy_pages(arena: dict, src: jax.Array, dst: jax.Array) -> dict:
    """Copy physical pages ``src[i] -> dst[i]`` across every layer —
    the device half of the allocator's copy-on-write: a shared prefix
    page goes private before the tail prefill writes into it.  A
    quantized arena's scale rows travel with their pages."""
    out = {"k": arena["k"].at[:, dst].set(arena["k"][:, src]),
           "v": arena["v"].at[:, dst].set(arena["v"][:, src])}
    if "k_scale" in arena:
        out["k_scale"] = arena["k_scale"].at[:, dst].set(
            arena["k_scale"][:, src])
        out["v_scale"] = arena["v_scale"].at[:, dst].set(
            arena["v_scale"][:, src])
    return out


def extract_pages(arena: dict, pages: Sequence[int]) -> dict:
    """Pull physical pages out of an arena as host arrays — the
    extract half of the prefill→decode KV handover
    (``serve/disagg.py``): ``[L, n, ps, Hkv, Dh]`` per K/V (plus the
    ``[L, n, Hkv]`` scale rows of an int8 arena).  Must run on the
    arena owner's scheduler thread, between program dispatches —
    the decode/prefill jits donate the arena buffer, so a concurrent
    reader would hold a deleted array."""
    idx = jnp.asarray(list(pages), jnp.int32)
    out = {"k": np.asarray(arena["k"][:, idx]),
           "v": np.asarray(arena["v"][:, idx])}
    if "k_scale" in arena:
        out["k_scale"] = np.asarray(arena["k_scale"][:, idx])
        out["v_scale"] = np.asarray(arena["v_scale"][:, idx])
    return out


def install_pages(arena: dict, dst: jax.Array, payload: dict) -> dict:
    """Write transferred page content into ``dst`` physical pages —
    the install half of the KV handover.  Jit-friendly (the engine
    wraps it with a donated arena); on a mesh-sharded arena the head
    axis re-shards under GSPMD on the way in."""
    out = {"k": arena["k"].at[:, dst].set(
               payload["k"].astype(arena["k"].dtype)),
           "v": arena["v"].at[:, dst].set(
               payload["v"].astype(arena["v"].dtype))}
    if "k_scale" in arena:
        out["k_scale"] = arena["k_scale"].at[:, dst].set(payload["k_scale"])
        out["v_scale"] = arena["v_scale"].at[:, dst].set(payload["v_scale"])
    return out


def _quant_decode_write(pages: jax.Array, scale: jax.Array,
                        phys: jax.Array, rows: jax.Array,
                        new: jax.Array) -> tuple[jax.Array, jax.Array]:
    """Write one fp row per slot into an int8 arena (one layer).

    The per-(page, head) scale is monotone: when a new row's absmax
    exceeds the page's current scale, the page's resident int8 values
    are re-quantized to the grown scale first (losing at most half a
    quantization step — the drift the logit-error budget prices in);
    an unchanged scale makes the rescale ``round(q * 1.0)`` — exact.
    ``pages`` [NP, ps, Hkv, D] int8, ``scale`` [NP, Hkv] fp32,
    ``phys``/``rows`` [S], ``new`` [S, Hkv, D] fp."""
    new = new.astype(jnp.float32)
    absmax = jnp.max(jnp.abs(new), axis=-1)                  # [S, Hkv]
    old = scale[phys]                                        # [S, Hkv]
    ns = jnp.maximum(old, jnp.maximum(absmax / INT8_MAX, _SCALE_EPS))
    ratio = jnp.where(ns > 0, old / ns, 1.0)[:, None, :, None]
    blk = jnp.clip(jnp.round(pages[phys].astype(jnp.float32) * ratio),
                   -INT8_MAX, INT8_MAX)                      # [S, ps, Hkv, D]
    blk = blk.at[jnp.arange(phys.shape[0]), rows].set(
        jnp.clip(jnp.round(new / ns[..., None]), -INT8_MAX, INT8_MAX))
    return (pages.at[phys].set(blk.astype(jnp.int8)),
            scale.at[phys].set(ns))


def _quant_prefill_write(pages: jax.Array, scale: jax.Array,
                         page_tables: jax.Array, phys_f: jax.Array,
                         rows_f: jax.Array, new_f: jax.Array,
                         valid_f: jax.Array
                         ) -> tuple[jax.Array, jax.Array]:
    """Scatter a prefill tail's rows into an int8 arena (one layer).

    Scales grow by scatter-max over every written row, then each
    touched request's resident pages re-quantize to the grown scales
    (untouched pages see ratio 1.0 — an exact no-op; shared prefix
    pages are never written so their scales never change).  ``phys_f``/
    ``rows_f``/``valid_f`` [B*T], ``new_f`` [B*T, Hkv, D] fp,
    ``page_tables`` [B, P]."""
    new_f = new_f.astype(jnp.float32)
    absmax = jnp.max(jnp.abs(new_f), axis=-1) / INT8_MAX     # [B*T, Hkv]
    absmax = jnp.where(valid_f[:, None], absmax, 0.0)
    ns = jnp.maximum(scale.at[phys_f].max(absmax), _SCALE_EPS)
    ratio = jnp.where(ns > 0, scale / ns, 1.0)               # [NP, Hkv]
    blk = jnp.clip(
        jnp.round(pages[page_tables].astype(jnp.float32)
                  * ratio[page_tables][:, :, None, :, None]),
        -INT8_MAX, INT8_MAX)                                 # [B, P, ...]
    pages = pages.at[page_tables].set(blk.astype(jnp.int8))
    q = jnp.clip(jnp.round(new_f / ns[phys_f][..., None]),
                 -INT8_MAX, INT8_MAX)
    return pages.at[phys_f, rows_f].set(q.astype(jnp.int8)), ns


def _page_scatter_indices(page_tables: jax.Array, positions: jax.Array,
                          valid: jax.Array, page_size: int
                          ) -> tuple[jax.Array, jax.Array]:
    """Map absolute token positions to (physical page, row) pairs via
    each request's page table; invalid (padding) writes route to the
    null page so they can never collide with a real row."""
    phys = jnp.take_along_axis(page_tables, positions // page_size,
                               axis=1)
    rows = positions % page_size
    phys = jnp.where(valid, phys, 0)
    rows = jnp.where(valid, rows, 0)
    return phys, rows


def prefill_into_pages(cfg: CausalLMConfig, params: Params,
                       input_ids: jax.Array, attention_mask: jax.Array,
                       arena: dict, page_tables: jax.Array,
                       start: jax.Array) -> tuple[jax.Array, dict]:
    """Prefill a batch of prompt *tails* into their reserved pages.

    ``input_ids`` [B, T] holds each request's uncached tail tokens
    (right-padded); ``start`` [B] is the absolute position of each
    tail's first token (0 for a prefix-cache miss, the cached length on
    a hit); ``page_tables`` [B, P] names the physical pages backing the
    request, null-padded past its reservation.  Tail queries attend to
    the cached prefix *and* causally to the tail itself through the
    same gathered view decode uses, so a prefix-cache hit is
    numerically identical to recomputing the whole prompt.  Returns
    (last-real-token logits [B, V], arena)."""
    b, t = input_ids.shape
    ps = arena["k"].shape[2]
    max_len = page_tables.shape[1] * ps
    tail_lens = attention_mask.sum(-1).astype(jnp.int32)
    positions = start[:, None] + jnp.clip(
        jnp.cumsum(attention_mask, 1) - 1, 0)  # [B, T] absolute

    rope = (rope_cache(max_len, cfg.rotary_dim, cfg.rope_theta)
            if cfg.pos_emb == "rope" else None)
    kpos_all = jnp.broadcast_to(jnp.arange(max_len), (b, max_len))
    bias = (_alibi_bias(cfg, kpos_all.astype(jnp.float32))
            if cfg.pos_emb == "alibi" else None)
    # key j visible to tail query i iff j <= its absolute position:
    # covers the cached prefix and the causal triangle within the tail,
    # and excludes every not-yet-written (garbage) row
    key_mask = (kpos_all[:, None, None, :]
                <= positions[:, None, :, None]).astype(jnp.int32)

    phys, rows = _page_scatter_indices(page_tables, positions,
                                       attention_mask != 0, ps)
    phys_f = phys.reshape(b * t)
    rows_f = rows.reshape(b * t)
    valid_f = (attention_mask != 0).reshape(b * t)
    quant = "k_scale" in arena

    x = _embed(cfg, params, input_ids, positions)

    def body(carry, layer):
        x = carry
        if quant:
            p, ck, cv, sk, sv = layer
        else:
            p, ck, cv = layer
            sk = sv = None
        q, k_new, v_new, attn_in = _project_qkv(
            cfg, p, x, rope=rope, q_positions=positions)
        k_flat = k_new.reshape(b * t, cfg.kv_heads, cfg.head_dim)
        v_flat = v_new.reshape(b * t, cfg.kv_heads, cfg.head_dim)
        if quant:
            ck, sk = _quant_prefill_write(ck, sk, page_tables, phys_f,
                                          rows_f, k_flat, valid_f)
            cv, sv = _quant_prefill_write(cv, sv, page_tables, phys_f,
                                          rows_f, v_flat, valid_f)
            from kubernetes_cloud_tpu.ops.paged_attention import (
                gather_pages,
            )

            dense_k = gather_pages(ck, page_tables, sk)
            dense_v = gather_pages(cv, page_tables, sv)
        else:
            ck = ck.at[phys_f, rows_f].set(k_flat.astype(ck.dtype))
            cv = cv.at[phys_f, rows_f].set(v_flat.astype(cv.dtype))
            dense_k = ck[page_tables].reshape(b, max_len, cfg.kv_heads,
                                              cfg.head_dim)
            dense_v = cv[page_tables].reshape(b, max_len, cfg.kv_heads,
                                              cfg.head_dim)
        attn_vec = attention(q, dense_k.astype(cfg.dtype),
                             dense_v.astype(cfg.dtype), causal=False,
                             bias=bias, mask=key_mask, impl="xla")
        x, _aux = _finish_block(cfg, p, x, attn_vec, attn_in,
                                token_mask=attention_mask,
                                moe_no_drop=True)
        return x, ((ck, cv, sk, sv) if quant else (ck, cv))

    if quant:
        xs = (params["blocks"], arena["k"], arena["v"],
              arena["k_scale"], arena["v_scale"])
        x, (ks, vs, ssk, ssv) = jax.lax.scan(body, x, xs)
        new_arena = {"k": ks, "v": vs, "k_scale": ssk, "v_scale": ssv}
    else:
        x, (ks, vs) = jax.lax.scan(
            body, x, (params["blocks"], arena["k"], arena["v"]))
        new_arena = {"k": ks, "v": vs}
    logits = _unembed(cfg, params, x)
    last = jnp.take_along_axis(
        logits, (tail_lens - 1)[:, None, None].clip(0), axis=1)[:, 0]
    return last, new_arena


def prefill_chunk_into_slots(cfg: CausalLMConfig, params: Params,
                             input_ids: jax.Array,
                             attention_mask: jax.Array, pool: dict,
                             slot_ids: jax.Array, start: jax.Array
                             ) -> tuple[jax.Array, dict]:
    """Prefill a *chunk* of prompt tokens at absolute positions into
    slot rows — the dense-pool half of Sarathi-style chunked prefill
    (``EngineConfig.prefill_chunk_tokens``).

    ``input_ids`` [B, T] holds each request's next chunk (right-
    padded); ``start`` [B] is the absolute position of each chunk's
    first token (0 for the first chunk, the resident context length
    after).  Chunk queries attend to the slot's already-prefilled
    positions *and* causally within the chunk through the same pool
    view decode uses, so splitting a prompt into chunks is numerically
    the one-shot prefill — the same mechanism ``prefill_into_pages``
    proves for prefix-cache tail prefill, on the dense pool.  Pad
    columns write at their own (beyond-context) positions, which are
    never attended and are overwritten by their eventual real write.
    Returns (last-real-token logits [B, V], pool); the pool's
    ``length`` rows advance to ``start + chunk_len``."""
    b, t = input_ids.shape
    max_len = pool["k"].shape[2]
    chunk_lens = attention_mask.sum(-1).astype(jnp.int32)
    positions = jnp.minimum(start[:, None] + jnp.arange(t)[None, :],
                            max_len - 1)

    rope = (rope_cache(max_len, cfg.rotary_dim, cfg.rope_theta)
            if cfg.pos_emb == "rope" else None)
    kpos_all = jnp.broadcast_to(jnp.arange(max_len), (b, max_len))
    bias = (_alibi_bias(cfg, kpos_all.astype(jnp.float32))
            if cfg.pos_emb == "alibi" else None)
    # key j visible to chunk query i iff j <= its absolute position:
    # covers the resident prefix and the causal triangle in the chunk
    key_mask = (kpos_all[:, None, None, :]
                <= positions[:, None, :, None]).astype(jnp.int32)

    x = _embed(cfg, params, input_ids, positions)

    def body(carry, layer):
        x = carry
        p, ck, cv = layer
        q, k_new, v_new, attn_in = _project_qkv(
            cfg, p, x, rope=rope, q_positions=positions)
        rows = ck[slot_ids]                       # [B, max_len, Hkv, D]
        rows = rows.at[jnp.arange(b)[:, None], positions].set(
            k_new.astype(ck.dtype))
        ck = ck.at[slot_ids].set(rows)
        rowsv = cv[slot_ids]
        rowsv = rowsv.at[jnp.arange(b)[:, None], positions].set(
            v_new.astype(cv.dtype))
        cv = cv.at[slot_ids].set(rowsv)
        attn_vec = attention(q, ck[slot_ids].astype(cfg.dtype),
                             cv[slot_ids].astype(cfg.dtype),
                             causal=False, bias=bias, mask=key_mask,
                             impl="xla")
        x, _aux = _finish_block(cfg, p, x, attn_vec, attn_in,
                                token_mask=attention_mask,
                                moe_no_drop=True)
        return x, (ck, cv)

    x, (ks, vs) = jax.lax.scan(body, x,
                               (params["blocks"], pool["k"], pool["v"]))
    pool = {"k": ks, "v": vs,
            "length": pool["length"].at[slot_ids].set(start + chunk_lens)}
    logits = _unembed(cfg, params, x)
    last = jnp.take_along_axis(
        logits, (chunk_lens - 1)[:, None, None].clip(0), axis=1)[:, 0]
    return last, pool


def verify_step_pages(cfg: CausalLMConfig, params: Params,
                      tokens: jax.Array, mask: jax.Array, arena: dict,
                      page_table: jax.Array, lengths: jax.Array
                      ) -> tuple[jax.Array, dict]:
    """ONE batched target step verifying speculative drafts through the
    paged arena (Leviathan et al.; see PAPERS.md).

    ``tokens`` [S, T] carries, per slot, its previously sampled token
    in column 0 and draft proposals in columns 1..T-1; ``mask`` [S, T]
    marks fed columns (all-zero for inactive slots).  Every fed token's
    K/V is written at absolute positions ``lengths .. lengths+T-1``
    through the per-slot page indirection — EXACTLY where sequential
    decode steps would write them, so the gathered attention view (and
    therefore every logits row) is the one sequential decode computes.
    The host accepts the longest prefix where the target's greedy
    argmax agrees with the drafts and rolls back by truncating its
    host-side lengths: pages are append-only per slot, so rejected-
    token KV is simply dead rows the next real write overwrites (null-
    page routed when beyond the slot's reservation).  Returns (logits
    [S, T, V] — one row per fed position — and the arena)."""
    s, t = tokens.shape
    ps = arena["k"].shape[2]
    max_len = page_table.shape[1] * ps
    positions = jnp.minimum(lengths[:, None] + jnp.arange(t)[None, :],
                            max_len - 1)
    valid = (mask != 0) & (lengths[:, None] + jnp.arange(t)[None, :]
                           < max_len)
    quant = "k_scale" in arena

    rope = (rope_cache(max_len, cfg.rotary_dim, cfg.rope_theta)
            if cfg.pos_emb == "rope" else None)
    kpos_all = jnp.broadcast_to(jnp.arange(max_len), (s, max_len))
    bias = (_alibi_bias(cfg, kpos_all.astype(jnp.float32))
            if cfg.pos_emb == "alibi" else None)
    key_mask = (kpos_all[:, None, None, :]
                <= positions[:, None, :, None]).astype(jnp.int32)

    phys, rows = _page_scatter_indices(page_table, positions, valid, ps)
    phys_f = phys.reshape(s * t)
    rows_f = rows.reshape(s * t)
    valid_f = valid.reshape(s * t)

    x = _embed(cfg, params, tokens, positions)

    def body(carry, layer):
        x = carry
        if quant:
            p, ck, cv, sk, sv = layer
        else:
            p, ck, cv = layer
            sk = sv = None
        q, k_new, v_new, attn_in = _project_qkv(
            cfg, p, x, rope=rope, q_positions=positions)
        k_flat = k_new.reshape(s * t, cfg.kv_heads, cfg.head_dim)
        v_flat = v_new.reshape(s * t, cfg.kv_heads, cfg.head_dim)
        if quant:
            ck, sk = _quant_prefill_write(ck, sk, page_table, phys_f,
                                          rows_f, k_flat, valid_f)
            cv, sv = _quant_prefill_write(cv, sv, page_table, phys_f,
                                          rows_f, v_flat, valid_f)
            from kubernetes_cloud_tpu.ops.paged_attention import (
                gather_pages,
            )

            dense_k = gather_pages(ck, page_table, sk)
            dense_v = gather_pages(cv, page_table, sv)
        else:
            ck = ck.at[phys_f, rows_f].set(k_flat.astype(ck.dtype))
            cv = cv.at[phys_f, rows_f].set(v_flat.astype(cv.dtype))
            dense_k = ck[page_table].reshape(s, max_len, cfg.kv_heads,
                                             cfg.head_dim)
            dense_v = cv[page_table].reshape(s, max_len, cfg.kv_heads,
                                             cfg.head_dim)
        attn_vec = attention(q, dense_k.astype(cfg.dtype),
                             dense_v.astype(cfg.dtype), causal=False,
                             bias=bias, mask=key_mask, impl="xla")
        x, _aux = _finish_block(cfg, p, x, attn_vec, attn_in,
                                token_mask=mask, moe_no_drop=True)
        return x, ((ck, cv, sk, sv) if quant else (ck, cv))

    if quant:
        xs = (params["blocks"], arena["k"], arena["v"],
              arena["k_scale"], arena["v_scale"])
        x, (ks, vs, ssk, ssv) = jax.lax.scan(body, x, xs)
        new_arena = {"k": ks, "v": vs, "k_scale": ssk, "v_scale": ssv}
    else:
        x, (ks, vs) = jax.lax.scan(
            body, x, (params["blocks"], arena["k"], arena["v"]))
        new_arena = {"k": ks, "v": vs}
    return _unembed(cfg, params, x), new_arena


def decode_step_pages(cfg: CausalLMConfig, params: Params,
                      tokens: jax.Array, arena: dict,
                      page_table: jax.Array, lengths: jax.Array,
                      impl: str = "gather") -> tuple[jax.Array, dict]:
    """One decode iteration for every slot over the paged arena.

    ``tokens`` [S] is each slot's previously sampled token, ``lengths``
    [S] the host-tracked context length (= the position this token
    occupies), ``page_table`` [S, P] the per-slot indirection.  Free
    slots carry an all-null table and length 0, so their (garbage) K/V
    write lands in the null page and their logits row is never read.
    ``impl`` selects the attention path: ``"gather"`` (pure jnp,
    bit-identical to :func:`decode_step` over the equivalent dense
    pool), ``"pallas"`` (the Mosaic paged-attention kernel in
    :mod:`kubernetes_cloud_tpu.ops.paged_attention`), or ``"fused"``
    (:mod:`kubernetes_cloud_tpu.ops.fused_decode`: gather + attention
    + output projection in ONE kernel).  Off-TPU the kernels run in
    interpreter mode so the whole surface stays CPU-testable.  A
    quantized arena (``k_scale`` present) dequantizes in whichever
    path is selected.  Returns (logits [S, V], arena)."""
    s = tokens.shape[0]
    ps = arena["k"].shape[2]
    max_len = page_table.shape[1] * ps
    pos = lengths
    positions = pos[:, None]
    quant = "k_scale" in arena
    interpret = jax.default_backend() != "tpu"

    rope = (rope_cache(max_len, cfg.rotary_dim, cfg.rope_theta)
            if cfg.pos_emb == "rope" else None)
    kpos_all = jnp.broadcast_to(jnp.arange(max_len), (s, max_len))
    bias = (_alibi_bias(cfg, kpos_all.astype(jnp.float32))
            if cfg.pos_emb == "alibi" else None)
    slopes = (alibi_slopes(cfg.num_heads) if cfg.pos_emb == "alibi"
              else None)
    key_mask = (kpos_all <= pos[:, None]).astype(jnp.int32)

    phys = jnp.take_along_axis(page_table, (pos // ps)[:, None],
                               axis=1)[:, 0]
    rows = pos % ps

    x = _embed(cfg, params, tokens[:, None], positions)

    def body(carry, layer):
        x = carry
        if quant:
            p, ck, cv, sk, sv = layer
        else:
            p, ck, cv = layer
            sk = sv = None
        q, k_new, v_new, attn_in = _project_qkv(
            cfg, p, x, rope=rope, q_positions=positions)
        if quant:
            ck, sk = _quant_decode_write(ck, sk, phys, rows, k_new[:, 0])
            cv, sv = _quant_decode_write(cv, sv, phys, rows, v_new[:, 0])
        else:
            ck = ck.at[phys, rows].set(k_new[:, 0].astype(ck.dtype))
            cv = cv.at[phys, rows].set(v_new[:, 0].astype(cv.dtype))
        if impl == "fused":
            from kubernetes_cloud_tpu.ops.fused_decode import (
                fused_paged_decode,
            )

            attn_out = fused_paged_decode(
                q[:, 0],
                ck if quant else ck.astype(cfg.dtype),
                cv if quant else cv.astype(cfg.dtype),
                page_table, pos + 1,
                p["attn"]["wo"].astype(cfg.dtype),
                k_scale=sk, v_scale=sv, slopes=slopes, impl="pallas",
                interpret=interpret)
            if cfg.use_bias:
                attn_out = attn_out + p["attn"]["bo"].astype(cfg.dtype)
            x, _aux = _finish_block(cfg, p, x, None, attn_in,
                                    moe_no_drop=True,
                                    attn_out=attn_out[:, None, :])
            return x, ((ck, cv, sk, sv) if quant else (ck, cv))
        if impl == "pallas":
            from kubernetes_cloud_tpu.ops.paged_attention import (
                paged_decode_attention,
            )

            attn_vec = paged_decode_attention(
                q[:, 0],
                ck if quant else ck.astype(cfg.dtype),
                cv if quant else cv.astype(cfg.dtype),
                page_table, pos + 1, k_scale=sk, v_scale=sv,
                slopes=slopes, impl="pallas", interpret=interpret,
            )[:, None]
        elif quant:
            from kubernetes_cloud_tpu.ops.paged_attention import (
                gather_pages,
            )

            dense_k = gather_pages(ck, page_table, sk)
            dense_v = gather_pages(cv, page_table, sv)
            attn_vec = attention(q, dense_k.astype(cfg.dtype),
                                 dense_v.astype(cfg.dtype), causal=False,
                                 bias=bias, mask=key_mask, impl="xla")
        else:
            dense_k = ck[page_table].reshape(s, max_len, cfg.kv_heads,
                                             cfg.head_dim)
            dense_v = cv[page_table].reshape(s, max_len, cfg.kv_heads,
                                             cfg.head_dim)
            attn_vec = attention(q, dense_k.astype(cfg.dtype),
                                 dense_v.astype(cfg.dtype), causal=False,
                                 bias=bias, mask=key_mask, impl="xla")
        x, _aux = _finish_block(cfg, p, x, attn_vec, attn_in,
                                moe_no_drop=True)
        return x, ((ck, cv, sk, sv) if quant else (ck, cv))

    if quant:
        xs = (params["blocks"], arena["k"], arena["v"],
              arena["k_scale"], arena["v_scale"])
        x, (ks, vs, ssk, ssv) = jax.lax.scan(body, x, xs)
        new_arena = {"k": ks, "v": vs, "k_scale": ssk, "v_scale": ssv}
    else:
        x, (ks, vs) = jax.lax.scan(
            body, x, (params["blocks"], arena["k"], arena["v"]))
        new_arena = {"k": ks, "v": vs}
    return _unembed(cfg, params, x)[:, 0], new_arena


def ragged_step_pages(cfg: CausalLMConfig, params: Params,
                      tokens: jax.Array, seg_slot: jax.Array,
                      positions: jax.Array, mask: jax.Array, arena: dict,
                      page_table: jax.Array, out_rows: jax.Array,
                      copy_src: jax.Array, copy_dst: jax.Array,
                      impl: str = "gather") -> tuple[jax.Array, dict]:
    """ONE ragged hybrid step: a flat ``[N]`` batch of real tokens from
    every segment kind a scheduler pass produces (Orca selective
    batching, OSDI '22; Sarathi's single hybrid batch).

    ``tokens`` [N] is the flat fed-token batch — prefill-chunk tokens,
    decode tokens, and spec-verify windows concatenated, padded to a
    bucketed N; ``seg_slot`` [N] names each token's owning slot (= its
    row in ``page_table``), ``positions`` [N] its absolute position,
    ``mask`` [N] the real-token flags (pad rows route to the null
    page).  Embeddings, the MLP stack, and the LM head run dense over
    the flat batch — token-level ops are row-independent, so a token
    computes bit-for-bit what it computes in the padded per-kind
    programs; attention routes per-segment through the paged
    indirection (``ops.paged_attention.paged_segment_attention``).
    Within one pass every token's K/V scatters BEFORE attention in each
    layer (the :func:`verify_step_pages` discipline), and the per-token
    causal frontier ``kpos <= position`` gives chunk tokens the
    within-chunk triangle and decode/verify tokens their full context —
    so segment kinds cannot see across each other except through pages
    they legitimately share (prefix sharing).

    ``out_rows`` [M] selects the flat rows whose logits the host will
    read (chunk-final, decode, and verify rows); the LM head runs on
    those M rows only.  ``copy_src``/``copy_dst`` [C] are this pass's
    copy-on-write page pairs, applied before any write so a shared
    source page can never be read after its private copy diverges —
    COW stops being its own dispatch.  Returns (logits [M, V], arena).
    """
    n = tokens.shape[0]
    ps = arena["k"].shape[2]
    max_len = page_table.shape[1] * ps
    quant = "k_scale" in arena
    interpret = jax.default_backend() != "tpu"

    if copy_src.shape[0]:
        arena = copy_pages(arena, copy_src, copy_dst)

    valid = (mask != 0) & (positions < max_len)
    positions = jnp.minimum(positions, max_len - 1)[:, None]  # [N, 1]
    mask2 = valid.astype(jnp.int32)[:, None]
    pt_tok = page_table[seg_slot]                             # [N, P]
    ctx_lens = positions[:, 0] + 1

    rope = (rope_cache(max_len, cfg.rotary_dim, cfg.rope_theta)
            if cfg.pos_emb == "rope" else None)
    kpos_all = jnp.broadcast_to(jnp.arange(max_len), (n, max_len))
    bias = (_alibi_bias(cfg, kpos_all.astype(jnp.float32))
            if cfg.pos_emb == "alibi" else None)
    slopes = (alibi_slopes(cfg.num_heads) if cfg.pos_emb == "alibi"
              else None)
    key_mask = (kpos_all[:, None, None, :]
                <= positions[:, None, :, None]).astype(jnp.int32)

    phys, rows = _page_scatter_indices(pt_tok, positions,
                                       valid[:, None], ps)
    phys_f = phys.reshape(n)
    rows_f = rows.reshape(n)
    valid_f = valid

    x = _embed(cfg, params, tokens[:, None], positions)

    def body(carry, layer):
        x = carry
        if quant:
            p, ck, cv, sk, sv = layer
        else:
            p, ck, cv = layer
            sk = sv = None
        q, k_new, v_new, attn_in = _project_qkv(
            cfg, p, x, rope=rope, q_positions=positions)
        k_flat = k_new.reshape(n, cfg.kv_heads, cfg.head_dim)
        v_flat = v_new.reshape(n, cfg.kv_heads, cfg.head_dim)
        if quant:
            ck, sk = _quant_prefill_write(ck, sk, pt_tok, phys_f,
                                          rows_f, k_flat, valid_f)
            cv, sv = _quant_prefill_write(cv, sv, pt_tok, phys_f,
                                          rows_f, v_flat, valid_f)
        else:
            ck = ck.at[phys_f, rows_f].set(k_flat.astype(ck.dtype))
            cv = cv.at[phys_f, rows_f].set(v_flat.astype(cv.dtype))
        if impl == "fused":
            from kubernetes_cloud_tpu.ops.fused_decode import (
                fused_paged_segment,
            )

            attn_out = fused_paged_segment(
                q[:, 0],
                ck if quant else ck.astype(cfg.dtype),
                cv if quant else cv.astype(cfg.dtype),
                page_table, seg_slot, ctx_lens,
                p["attn"]["wo"].astype(cfg.dtype),
                k_scale=sk, v_scale=sv, slopes=slopes, impl="pallas",
                interpret=interpret)
            if cfg.use_bias:
                attn_out = attn_out + p["attn"]["bo"].astype(cfg.dtype)
            x, _aux = _finish_block(cfg, p, x, None, attn_in,
                                    token_mask=mask2, moe_no_drop=True,
                                    attn_out=attn_out[:, None, :])
            return x, ((ck, cv, sk, sv) if quant else (ck, cv))
        if impl == "pallas":
            from kubernetes_cloud_tpu.ops.paged_attention import (
                paged_segment_attention,
            )

            attn_vec = paged_segment_attention(
                q[:, 0],
                ck if quant else ck.astype(cfg.dtype),
                cv if quant else cv.astype(cfg.dtype),
                page_table, seg_slot, ctx_lens, k_scale=sk, v_scale=sv,
                slopes=slopes, impl="pallas", interpret=interpret,
            )[:, None]
        elif quant:
            from kubernetes_cloud_tpu.ops.paged_attention import (
                gather_pages,
            )

            dense_k = gather_pages(ck, pt_tok, sk)
            dense_v = gather_pages(cv, pt_tok, sv)
            attn_vec = attention(q, dense_k.astype(cfg.dtype),
                                 dense_v.astype(cfg.dtype), causal=False,
                                 bias=bias, mask=key_mask, impl="xla")
        else:
            dense_k = ck[pt_tok].reshape(n, max_len, cfg.kv_heads,
                                         cfg.head_dim)
            dense_v = cv[pt_tok].reshape(n, max_len, cfg.kv_heads,
                                         cfg.head_dim)
            attn_vec = attention(q, dense_k.astype(cfg.dtype),
                                 dense_v.astype(cfg.dtype), causal=False,
                                 bias=bias, mask=key_mask, impl="xla")
        x, _aux = _finish_block(cfg, p, x, attn_vec, attn_in,
                                token_mask=mask2, moe_no_drop=True)
        return x, ((ck, cv, sk, sv) if quant else (ck, cv))

    if quant:
        xs = (params["blocks"], arena["k"], arena["v"],
              arena["k_scale"], arena["v_scale"])
        x, (ks, vs, ssk, ssv) = jax.lax.scan(body, x, xs)
        new_arena = {"k": ks, "v": vs, "k_scale": ssk, "v_scale": ssv}
    else:
        x, (ks, vs) = jax.lax.scan(
            body, x, (params["blocks"], arena["k"], arena["v"]))
        new_arena = {"k": ks, "v": vs}
    # LM head over the M read rows only: the flat batch's other rows'
    # logits are never consumed, and M bounds the host transfer.
    return _unembed(cfg, params, x[out_rows])[:, 0], new_arena


def kv_quant_probe(cfg: CausalLMConfig, params: Params,
                   prompts: Sequence[Sequence[int]], *,
                   max_new_tokens: int = 16, page_size: int = 16,
                   impl: str = "gather",
                   kv_dtype: str = "int8", mesh=None) -> dict:
    """Measured logit-error budget for a quantized arena.

    Runs every prompt through an fp32 paged arena and a ``kv_dtype``
    arena side by side, teacher-forced on the fp32 path's greedy
    tokens, and reports per-position greedy top-1 agreement plus the
    max/mean absolute logit error — the numbers the int8 acceptance
    bar (top-1 agreement ≥ 99% on the fixed eval set) is asserted
    against in tests and recorded by ``scripts/bench_serving.py
    --kv-dtype int8``.  Teacher-forcing makes the comparison
    per-position exact: both paths always score the SAME context, so a
    single early disagreement cannot cascade into meaningless
    downstream comparisons.

    With ``mesh`` (model axis > 1), both arenas shard over the kv-head
    axis and the probe drives the ``shard_map`` TP programs
    (:mod:`kubernetes_cloud_tpu.models.tp_decode`) instead — the
    sharded acceptance bar for a quantized mesh replica."""
    # jit the single-host paths so the 2 * len(prompts) * max_new_tokens
    # model calls hit 4 cached executables (prefill/decode x fp32/quant)
    # instead of paying eager dispatch of the full forward every step.
    _jit_prefill = jax.jit(lambda p_, a, i_, m_, t_, s_: prefill_into_pages(
        cfg, p_, i_, m_, a, t_, s_))
    _jit_decode = jax.jit(lambda p_, a, tok, t_, ln: decode_step_pages(
        cfg, p_, tok, a, t_, ln, impl=impl))
    run_prefill = (lambda kd, a, i_, m_, t_, s_: _jit_prefill(
        params, a, i_, m_, t_, s_))
    run_decode = (lambda kd, a, tok, t_, ln: _jit_decode(
        params, a, tok, t_, ln))
    place = lambda a: a  # noqa: E731 - trivial identity default
    if mesh is not None:
        from kubernetes_cloud_tpu.models import tp_decode

        if tp_decode.tp_shards(mesh) > 1:
            reason = tp_decode.tp_unsupported_reason(cfg, mesh)
            if reason is not None:
                raise ValueError(f"sharded quant probe: {reason}")
            params_tp = tp_decode.place_tp_params(cfg, params, mesh)
            progs = {kd: tp_decode.build_tp_programs(
                cfg, mesh, params_tp, kv_dtype=kd, attn_impl=impl)
                for kd in ("fp32", kv_dtype)}
            run_prefill = (lambda kd, a, i_, m_, t_, s_:
                           progs[kd][0](params_tp, i_, m_, a, t_, s_))
            run_decode = (lambda kd, a, tok, t_, ln:
                          progs[kd][1](params_tp, tok, a, t_, ln))
            place = lambda a: tp_decode.place_arena(a, mesh)  # noqa: E731
    agree = total = 0
    max_err = 0.0
    err_sum = 0.0
    # ONE geometry for the whole eval set: every prompt right-pads to
    # the longest and reserves the same page count, so each arena
    # compiles one prefill and one decode program instead of a fresh
    # pair per distinct prompt length.  Padded positions are masked
    # out of attention and their writes route to the null page, so
    # the reported numbers are unchanged.
    t_max = max(len(p) for p in prompts)
    n_pages = -(-(t_max + max_new_tokens) // page_size)
    tables = jnp.asarray([list(range(1, n_pages + 1))], jnp.int32)
    for prompt in prompts:
        plen = len(prompt)
        arenas, logits = {}, {}
        pad = t_max - plen
        ids = jnp.asarray([list(prompt) + [0] * pad], jnp.int32)
        mask = jnp.asarray([[1] * plen + [0] * pad], jnp.int32)
        start = jnp.zeros((1,), jnp.int32)
        for kd in ("fp32", kv_dtype):
            arena = place(init_page_arena(cfg, n_pages + 1, page_size,
                                          kv_dtype=kd))
            lg, arena = run_prefill(kd, arena, ids, mask, tables, start)
            arenas[kd], logits[kd] = arena, lg
        for step in range(max_new_tokens):
            ref = np.asarray(logits["fp32"])[0]
            got = np.asarray(logits[kv_dtype])[0]
            err = float(np.abs(ref - got).max())
            max_err = max(max_err, err)
            err_sum += float(np.abs(ref - got).mean())
            agree += int(ref.argmax() == got.argmax())
            total += 1
            if step == max_new_tokens - 1:
                break
            tok = jnp.asarray([int(ref.argmax())], jnp.int32)
            ln = jnp.asarray([plen + step], jnp.int32)
            for kd in ("fp32", kv_dtype):
                logits[kd], arenas[kd] = run_decode(
                    kd, arenas[kd], tok, tables, ln)
    return {"kv_dtype": kv_dtype, "positions": total,
            "top1_agreement": round(agree / max(total, 1), 6),
            "max_logit_err": round(max_err, 6),
            "mean_logit_err": round(err_sum / max(total, 1), 8)}


def sample_token(logits: jax.Array, rng: jax.Array, *, temperature: float,
                 top_k: int, top_p: float) -> jax.Array:
    """Temperature / top-k / top-p sampling; temperature 0 = greedy."""
    if temperature == 0.0:
        return logits.argmax(-1).astype(jnp.int32)
    logits = logits / temperature
    if top_k > 0 and top_k < logits.shape[-1]:
        kth = jax.lax.top_k(logits, top_k)[0][..., -1:]
        logits = jnp.where(logits < kth, -jnp.inf, logits)
    if top_p < 1.0:
        sorted_logits = jnp.sort(logits, axis=-1)[..., ::-1]
        probs = jax.nn.softmax(sorted_logits, axis=-1)
        cum = jnp.cumsum(probs, axis=-1)
        # smallest set with cumulative prob >= top_p
        cutoff_idx = (cum < top_p).sum(-1, keepdims=True)
        cutoff = jnp.take_along_axis(sorted_logits, cutoff_idx, axis=-1)
        logits = jnp.where(logits < cutoff, -jnp.inf, logits)
    return jax.random.categorical(rng, logits).astype(jnp.int32)


def generate(
    cfg: CausalLMConfig,
    params: Params,
    input_ids: jax.Array,
    attention_mask: Optional[jax.Array] = None,
    *,
    max_new_tokens: int = 64,
    temperature: float = 0.7,
    top_k: int = 0,
    top_p: float = 1.0,
    eos_token_id: Optional[int] = None,
    pad_token_id: int = 0,
    rng: Optional[jax.Array] = None,
) -> jax.Array:
    """Generate completions.  Returns [B, S + max_new_tokens] token ids
    (prompt included; finished rows padded with ``pad_token_id``).

    Mirrors the sampling surface the reference exposes per-request
    (``online-inference/*/service.py`` ``parameters`` dicts and the
    ``/completion`` body, ``finetuner-workflow/finetuner/inference.py:43-56``).
    """
    b, s = input_ids.shape
    if attention_mask is None:
        attention_mask = jnp.ones_like(input_ids)
    if rng is None:
        rng = jax.random.key(0)
    if max_new_tokens < 1:
        raise ValueError("max_new_tokens must be >= 1")
    max_len = s + max_new_tokens
    if cfg.pos_emb == "learned" and max_len > cfg.max_seq_len:
        # wpe gathers clamp silently beyond the table, so reject instead of
        # producing degraded completions.
        raise ValueError(
            f"prompt ({s}) + max_new_tokens ({max_new_tokens}) exceeds "
            f"max_seq_len ({cfg.max_seq_len}) for learned positions")
    eos = -1 if eos_token_id is None else eos_token_id

    cache = init_cache(cfg, b, max_len)
    logits, cache = prefill(cfg, params, input_ids, attention_mask, cache)

    out = jnp.full((b, max_len), pad_token_id, jnp.int32)
    out = jax.lax.dynamic_update_slice(out, input_ids.astype(jnp.int32),
                                       (0, 0))

    def cond(state):
        i, _, _, _, done, _ = state
        return (i < max_new_tokens) & ~done.all()

    def step(state):
        i, logits, cache, out, done, rng = state
        rng, sub = jax.random.split(rng)
        token = sample_token(logits, sub, temperature=temperature,
                             top_k=top_k, top_p=top_p)
        token = jnp.where(done, pad_token_id, token)
        # write at each row's current length position
        out = out.at[jnp.arange(b), cache["length"]].set(
            jnp.where(done, out[jnp.arange(b), cache["length"]], token))
        done = done | (token == eos)
        logits, cache = decode_step(cfg, params, token, cache)
        return i + 1, logits, cache, out, done, rng

    state = (jnp.int32(0), logits, cache, out,
             jnp.zeros((b,), bool), rng)
    _, _, _, out, _, _ = jax.lax.while_loop(cond, step, state)
    return out
