"""Decoder-only causal language models, TPU-first.

One configurable architecture covers the model families the reference
finetunes and serves — GPT-NeoX/Pythia (parallel residual + partial rotary,
reference ``finetuner-workflow/`` + ``kubeflow/training-operator/gpt-neox/``),
GPT-J (parallel residual, full rotary,
``online-inference/fastertransformer/``), BLOOM (ALiBi + serial residual,
``online-inference/bloom-176b*/``), and GPT-2 (learned positions,
``online-inference/gpt-2/``).

Design (deliberately not a torch translation):

* **Pure pytrees + functions.** ``init_params`` returns a nested dict of
  arrays; ``forward``/``loss_fn`` are pure and jit-compiled with the config
  static.  Sharding is applied by pairing the pytree with a matching
  ``PartitionSpec`` pytree (:mod:`kubernetes_cloud_tpu.parallel.sharding`) —
  no module system, no parameter registry.
* **Stacked layers + ``lax.scan``.** All transformer blocks live in one
  pytree node with a leading layer dimension, scanned at trace time: one
  block is traced/compiled regardless of depth, and rematerialization is a
  single ``jax.checkpoint`` policy over the scanned body.
* **bf16 compute, fp32 where it matters.** Matmuls run in bfloat16 on the
  MXU; norm statistics, softmax and the final loss run in float32.
"""

from __future__ import annotations

import dataclasses
import math
from typing import Any, Optional

import jax
import jax.numpy as jnp

from kubernetes_cloud_tpu.ops.attention import attention
from kubernetes_cloud_tpu.ops.layers import (
    alibi_slopes,
    apply_rotary,
    layer_norm,
    rms_norm,
    rope_cache,
)

Params = dict[str, Any]


@dataclasses.dataclass(frozen=True)
class CausalLMConfig:
    vocab_size: int = 50304
    hidden_size: int = 512
    num_layers: int = 4
    num_heads: int = 8
    num_kv_heads: Optional[int] = None  # GQA; None => MHA
    intermediate_size: Optional[int] = None  # None => 4 * hidden
    max_seq_len: int = 2048
    # position scheme: "rope" (neox/gptj), "alibi" (bloom), "learned" (gpt2)
    pos_emb: str = "rope"
    rope_theta: float = 10000.0
    rotary_pct: float = 1.0  # GPT-NeoX uses 0.25
    parallel_residual: bool = True  # neox/gptj True, bloom/gpt2 False
    norm: str = "layernorm"  # or "rmsnorm"
    # "gelu_tanh" (GPT-2/GPT-J/BLOOM) or "gelu_exact" (erf; GPT-NeoX/Pythia)
    act: str = "gelu_tanh"
    use_bias: bool = True
    tie_embeddings: bool = False
    embed_layernorm: bool = False  # BLOOM's post-embedding LayerNorm
    layernorm_eps: float = 1e-5
    dtype: Any = jnp.bfloat16  # compute dtype
    param_dtype: Any = jnp.float32
    remat: bool = False  # rematerialize each block in the backward pass
    # Remat policy: "nothing" = full recompute (min memory); "attn_out" =
    # save each block's attention output so the backward pass never
    # re-runs attention — the right pairing for the flash kernel, whose
    # custom-vjp backward already does its own internal recompute.
    # "attn_island" / "attn_island_mlp": attention sits *outside* the
    # rematerialized regions — the checkpointed front half (ln1+qkv+rope)
    # and back half (wo+mlp) surround an un-rematted attention call, so
    # its residuals (q/k/v/out/lse on the flash path) are saved and the
    # backward never re-runs the attention forward at all.  Pair with the
    # flash kernel: the XLA path would save [B,H,S,S] probabilities.
    # "_mlp" additionally saves each block's MLP hidden activation.
    remat_policy: str = "nothing"
    # Cross-entropy chunking: 0 computes the full [B, S, V] fp32 logits
    # tensor at once (6 GiB at B=32, S=1024, V=50k — the largest single
    # allocation in training); >0 scans the loss over sequence chunks of
    # this many positions, rematerializing each chunk's logits in the
    # backward pass.  Must divide the sequence length.
    loss_chunk_size: int = 0
    # GPT-J uses interleaved (rotate_every_two) rotary channel pairing;
    # NeoX/LLaMA use the half-split convention.
    rope_interleaved: bool = False
    # Attention backend: "auto"/"xla"/"pallas" (single-device per shard) or
    # "ring" — sequence-parallel ring attention over the ``seq`` mesh axis
    # (requires passing ``mesh`` to forward/loss_fn; SURVEY.md §5.7).
    attn_impl: str = "auto"
    # Mixture-of-experts FFN (0 = dense).  Experts shard over the
    # ``expert`` mesh axis; the reference has no EP (SURVEY.md §2.3).
    moe_experts: int = 0
    moe_top_k: int = 2
    moe_capacity_factor: float = 1.25
    moe_aux_weight: float = 0.01
    moe_group_size: int = 1024
    # Bulk-cast each block's weights to the compute dtype once before the
    # layer scan (instead of per-use .astype inside the block), so remat's
    # backward recompute reuses the bf16 copies.  Norm scales/biases
    # (ln1/ln2) and the MoE router stay in fp32 — their numerics are
    # load-bearing (ops/moe.py runs routing in fp32 on purpose).
    cast_once: bool = False

    def __post_init__(self):
        if self.attn_impl not in ("auto", "xla", "pallas", "ring"):
            raise ValueError(f"unknown attn_impl: {self.attn_impl!r}")
        if self.remat_policy not in ("nothing", "attn_out", "attn_mlp",
                                     "attn_island", "attn_island_mlp"):
            raise ValueError(f"unknown remat_policy: {self.remat_policy!r}")
        if self.loss_chunk_size < 0:
            raise ValueError(
                f"loss_chunk_size must be >= 0, got {self.loss_chunk_size}")
        if self.moe_experts:
            if (self.moe_experts < 0 or self.moe_top_k < 1
                    or self.moe_top_k > self.moe_experts):
                raise ValueError(
                    f"moe_top_k={self.moe_top_k} must be in "
                    f"[1, moe_experts={self.moe_experts}]")
            if self.moe_capacity_factor <= 0:
                raise ValueError("moe_capacity_factor must be positive")
        if self.attn_impl == "ring" and self.pos_emb == "alibi":
            raise ValueError("ring attention does not support alibi bias yet")
        if self.pos_emb not in ("rope", "alibi", "learned"):
            raise ValueError(f"unknown pos_emb: {self.pos_emb!r}")
        if self.norm not in ("layernorm", "rmsnorm"):
            raise ValueError(f"unknown norm: {self.norm!r}")
        if self.act not in ("gelu_tanh", "gelu_exact"):
            raise ValueError(f"unknown act: {self.act!r}")
        if self.hidden_size % self.num_heads:
            raise ValueError("hidden_size must divide evenly into heads")
        if self.num_kv_heads and self.num_heads % self.num_kv_heads:
            raise ValueError("num_heads must be a multiple of num_kv_heads")

    @property
    def head_dim(self) -> int:
        return self.hidden_size // self.num_heads

    @property
    def kv_heads(self) -> int:
        return self.num_kv_heads or self.num_heads

    @property
    def ffn_size(self) -> int:
        return self.intermediate_size or 4 * self.hidden_size

    @property
    def rotary_dim(self) -> int:
        rot = int(self.head_dim * self.rotary_pct)
        return rot - rot % 2


#: Architecture presets for the model families the reference targets.
#: Sizes follow the public configs of each family (vocab/hidden/layers/heads);
#: a "-test" preset keeps CI fast.
PRESETS: dict[str, CausalLMConfig] = {
    "test-tiny": CausalLMConfig(
        vocab_size=512, hidden_size=64, num_layers=2, num_heads=4,
        max_seq_len=128, rotary_pct=0.25),
    "pythia-70m": CausalLMConfig(
        act="gelu_exact",
        vocab_size=50304, hidden_size=512, num_layers=6, num_heads=8,
        rotary_pct=0.25),
    "pythia-410m": CausalLMConfig(
        act="gelu_exact",
        vocab_size=50304, hidden_size=1024, num_layers=24, num_heads=16,
        rotary_pct=0.25),
    "pythia-1.4b": CausalLMConfig(
        act="gelu_exact",
        vocab_size=50304, hidden_size=2048, num_layers=24, num_heads=16,
        rotary_pct=0.25),
    "gpt-j-6b": CausalLMConfig(
        vocab_size=50400, hidden_size=4096, num_layers=28, num_heads=16,
        rope_theta=10000.0, rotary_pct=64 / 256, tie_embeddings=False,
        rope_interleaved=True),
    "gpt-neox-20b": CausalLMConfig(
        act="gelu_exact",
        vocab_size=50432, hidden_size=6144, num_layers=44, num_heads=64,
        rotary_pct=0.25),
    "bloom-560m": CausalLMConfig(
        vocab_size=250880, hidden_size=1024, num_layers=24, num_heads=16,
        pos_emb="alibi", parallel_residual=False, embed_layernorm=True,
        tie_embeddings=True),
    "bloom-176b": CausalLMConfig(
        vocab_size=250880, hidden_size=14336, num_layers=70, num_heads=112,
        pos_emb="alibi", parallel_residual=False, embed_layernorm=True,
        tie_embeddings=True),
    "gpt2-xl": CausalLMConfig(
        vocab_size=50257, hidden_size=1600, num_layers=48, num_heads=25,
        pos_emb="learned", parallel_residual=False, tie_embeddings=True,
        max_seq_len=1024),
}


def _norm_params(cfg: CausalLMConfig, shape_prefix=()) -> Params:
    shape = (*shape_prefix, cfg.hidden_size)
    p: Params = {"scale": jnp.ones(shape, cfg.param_dtype)}
    if cfg.norm == "layernorm":
        p["bias"] = jnp.zeros(shape, cfg.param_dtype)
    return p


def init_params(cfg: CausalLMConfig, rng: jax.Array) -> Params:
    """Initialize the parameter pytree.

    Layout (leading ``L`` = num_layers on every block leaf):

    ``embed.wte [V, D]``, optional ``embed.wpe [S, D]``, optional
    ``embed.ln``; ``blocks.ln1/ln2 [L, D]``, ``blocks.attn.wqkv
    [L, D, H + 2*Hkv, Dh]``, ``blocks.attn.wo [L, H, Dh, D]``,
    ``blocks.mlp.wi [L, D, F]``, ``blocks.mlp.wo [L, F, D]``;
    ``final_ln``; ``lm_head [D, V]`` unless tied.
    """
    keys = jax.random.split(rng, 8)
    d, l, h, hkv, dh, f = (cfg.hidden_size, cfg.num_layers, cfg.num_heads,
                           cfg.kv_heads, cfg.head_dim, cfg.ffn_size)
    std = 0.02
    wo_std = std / math.sqrt(2 * l)  # GPT-2-style scaled residual init

    def normal(key, shape, s=std):
        return (jax.random.normal(key, shape, jnp.float32) * s).astype(
            cfg.param_dtype)

    embed: Params = {"wte": normal(keys[0], (cfg.vocab_size, d))}
    if cfg.pos_emb == "learned":
        embed["wpe"] = normal(keys[1], (cfg.max_seq_len, d))
    if cfg.embed_layernorm:
        embed["ln"] = _norm_params(cfg)

    blocks: Params = {
        "ln1": _norm_params(cfg, (l,)),
        "attn": {
            "wqkv": normal(keys[2], (l, d, h + 2 * hkv, dh)),
            "wo": normal(keys[3], (l, h, dh, d), wo_std),
        },
    }
    if cfg.moe_experts:
        ne = cfg.moe_experts
        blocks["moe"] = {
            "router": normal(keys[7], (l, d, ne)),
            "wi": normal(keys[4], (l, ne, d, f)),
            "wo": normal(keys[5], (l, ne, f, d), wo_std),
        }
    else:
        blocks["mlp"] = {
            "wi": normal(keys[4], (l, d, f)),
            "wo": normal(keys[5], (l, f, d), wo_std),
        }
    blocks["ln2"] = _norm_params(cfg, (l,))
    if cfg.use_bias:
        blocks["attn"]["bqkv"] = jnp.zeros((l, h + 2 * hkv, dh),
                                           cfg.param_dtype)
        blocks["attn"]["bo"] = jnp.zeros((l, d), cfg.param_dtype)
        if not cfg.moe_experts:
            blocks["mlp"]["bi"] = jnp.zeros((l, f), cfg.param_dtype)
            blocks["mlp"]["bo"] = jnp.zeros((l, d), cfg.param_dtype)

    params: Params = {"embed": embed, "blocks": blocks,
                      "final_ln": _norm_params(cfg)}
    if not cfg.tie_embeddings:
        params["lm_head"] = normal(keys[6], (d, cfg.vocab_size))
    return params


def _norm(cfg: CausalLMConfig, p: Params, x: jax.Array) -> jax.Array:
    if cfg.norm == "rmsnorm":
        return rms_norm(x, p["scale"], cfg.layernorm_eps)
    return layer_norm(x, p["scale"], p["bias"], cfg.layernorm_eps)


def _project_qkv(cfg: CausalLMConfig, p: Params, x: jax.Array, *,
                 rope: Optional[tuple[jax.Array, jax.Array]],
                 q_positions: Optional[jax.Array] = None):
    """Block front half: pre-norm + fused QKV projection + rotary.

    Shared between the training ``forward`` and the KV-cached decode path
    (:mod:`kubernetes_cloud_tpu.models.generate`) so the two can never
    diverge architecturally.  Returns (q, k, v, attn_in)."""
    h, hkv = cfg.num_heads, cfg.kv_heads
    attn_in = _norm(cfg, p["ln1"], x)
    qkv = jnp.einsum("bsd,dnk->bsnk", attn_in,
                     p["attn"]["wqkv"].astype(cfg.dtype))
    if cfg.use_bias:
        qkv = qkv + p["attn"]["bqkv"].astype(cfg.dtype)
    q, k, v = jnp.split(qkv, [h, h + hkv], axis=2)
    if rope is not None:
        cos, sin = rope
        q = apply_rotary(q, cos, sin, positions=q_positions,
                         interleaved=cfg.rope_interleaved)
        k = apply_rotary(k, cos, sin, positions=q_positions,
                         interleaved=cfg.rope_interleaved)
    return q, k, v, attn_in


def _finish_block(cfg: CausalLMConfig, p: Params, x: jax.Array,
                  attn_vec: jax.Array, attn_in: jax.Array,
                  token_mask: Optional[jax.Array] = None,
                  moe_no_drop: bool = False,
                  attn_out: Optional[jax.Array] = None
                  ) -> tuple[jax.Array, jax.Array]:
    """Block back half: output projection + residual wiring + MLP/MoE.

    Returns ``(out, aux)`` where ``aux`` is the MoE load-balancing loss
    (0.0 for dense blocks).  ``token_mask`` [B, S] keeps padding from
    routing/claiming MoE capacity; ``moe_no_drop`` (decode path) raises
    capacity so co-batched requests can't perturb each other's logits.
    A caller that already projected the attention output (the fused
    paged-decode kernel folds ``W_o`` into the attention sweep; the
    caller must also have added ``bo`` when ``use_bias``) passes it as
    ``attn_out`` [B, S, D] — projection AND bias here are skipped;
    ``attn_vec`` may then be None."""
    if attn_out is None:
        attn_out = jnp.einsum("bsnk,nkd->bsd", attn_vec,
                              p["attn"]["wo"].astype(cfg.dtype))
        if cfg.use_bias:
            attn_out = attn_out + p["attn"]["bo"].astype(cfg.dtype)

    if cfg.parallel_residual:
        # GPT-NeoX/GPT-J: x + attn(ln1(x)) + mlp(ln2(x))
        mlp_in = _norm(cfg, p["ln2"], x)
    else:
        x = x + attn_out
        mlp_in = _norm(cfg, p["ln2"], x)

    aux = jnp.zeros((), jnp.float32)
    if "moe" in p:
        from kubernetes_cloud_tpu.ops.moe import moe_ffn

        if token_mask is not None and token_mask.ndim != 2:
            # Full [B, 1, Sq, Sk] attention masks carry no per-token
            # validity; only key-padding masks gate MoE routing.
            token_mask = None

        mlp_out, aux = moe_ffn(
            mlp_in, p["moe"]["router"], p["moe"]["wi"], p["moe"]["wo"],
            top_k=cfg.moe_top_k, capacity_factor=cfg.moe_capacity_factor,
            act=cfg.act, dtype=cfg.dtype, token_mask=token_mask,
            group_size=cfg.moe_group_size, no_drop=moe_no_drop)
    else:
        hmid = jnp.einsum("bsd,df->bsf", mlp_in,
                          p["mlp"]["wi"].astype(cfg.dtype))
        if cfg.use_bias:
            hmid = hmid + p["mlp"]["bi"].astype(cfg.dtype)
        hmid = jax.nn.gelu(hmid, approximate=cfg.act == "gelu_tanh")
        from jax.ad_checkpoint import checkpoint_name

        # saveable under remat_policy="attn_mlp": skips re-running the
        # [D,4D] matmul in the backward recompute at 4D*S*B bf16 memory
        hmid = checkpoint_name(hmid, "mlp_mid")
        mlp_out = jnp.einsum("bsf,fd->bsd", hmid,
                             p["mlp"]["wo"].astype(cfg.dtype))
        if cfg.use_bias:
            mlp_out = mlp_out + p["mlp"]["bo"].astype(cfg.dtype)

    if cfg.parallel_residual:
        return x + attn_out + mlp_out, aux
    return x + mlp_out, aux


def _qkv_half(cfg: CausalLMConfig, p: Params, x: jax.Array,
              rope: Optional[tuple[jax.Array, jax.Array]]):
    """Checkpointed front half for the ``attn_island`` remat policies."""
    q, k, v, _ = _project_qkv(cfg, p, x, rope=rope)
    return q, k, v


def _mlp_half(cfg: CausalLMConfig, p: Params, x: jax.Array,
              attn_vec: jax.Array, mask: Optional[jax.Array]):
    """Checkpointed back half for the ``attn_island`` remat policies."""
    return _finish_block(cfg, p, x, attn_vec, None, token_mask=mask)


def _attn_call(cfg: CausalLMConfig, q, k, v, bias, mask, mesh):
    """The attention dispatch shared by both block layouts."""
    if cfg.attn_impl == "ring" and mesh is not None:
        from kubernetes_cloud_tpu.ops.ring_attention import ring_attention

        return ring_attention(q, k, v, mesh, causal=True, kv_mask=mask)
    # ``bias`` rank disambiguates: [H] = ALiBi slopes (computed
    # in-kernel on the pallas path), higher rank = materialized bias.
    slopes = bias if bias is not None and bias.ndim == 1 else None
    return attention(q, k, v, causal=True,
                     bias=None if slopes is not None else bias,
                     alibi_slopes=slopes, mask=mask,
                     impl="auto" if cfg.attn_impl == "ring"
                     else cfg.attn_impl)


def _block(cfg: CausalLMConfig, p: Params, x: jax.Array,
           rope: Optional[tuple[jax.Array, jax.Array]],
           bias: Optional[jax.Array], mask: Optional[jax.Array],
           mesh=None) -> tuple[jax.Array, jax.Array]:
    q, k, v, attn_in = _project_qkv(cfg, p, x, rope=rope)
    attn_vec = _attn_call(cfg, q, k, v, bias, mask, mesh)
    from jax.ad_checkpoint import checkpoint_name

    attn_vec = checkpoint_name(attn_vec, "attn_out")
    return _finish_block(cfg, p, x, attn_vec, attn_in, token_mask=mask)


def _embed(cfg: CausalLMConfig, params: Params, input_ids: jax.Array,
           positions: Optional[jax.Array] = None) -> jax.Array:
    x = params["embed"]["wte"][input_ids].astype(cfg.dtype)
    if cfg.pos_emb == "learned":
        if positions is None:
            x = x + params["embed"]["wpe"][: input_ids.shape[1]].astype(
                cfg.dtype)
        else:
            x = x + params["embed"]["wpe"][positions].astype(cfg.dtype)
    if cfg.embed_layernorm:
        x = _norm(cfg, params["embed"]["ln"], x)
    return x


def _unembed_raw(cfg: CausalLMConfig, params: Params,
                 x: jax.Array) -> jax.Array:
    """final_ln + LM head, in the compute dtype (no fp32 materialization)."""
    x = _norm(cfg, params["final_ln"], x)
    if cfg.tie_embeddings:
        logits = jnp.einsum("bsd,vd->bsv", x,
                            params["embed"]["wte"].astype(cfg.dtype))
    else:
        logits = jnp.einsum("bsd,dv->bsv", x,
                            params["lm_head"].astype(cfg.dtype))
    if "lm_head_bias" in params:  # GPT-J's biased output projection
        logits = logits + params["lm_head_bias"].astype(cfg.dtype)
    return logits


def _unembed(cfg: CausalLMConfig, params: Params, x: jax.Array) -> jax.Array:
    return _unembed_raw(cfg, params, x).astype(jnp.float32)


def forward(cfg: CausalLMConfig, params: Params, input_ids: jax.Array,
            attention_mask: Optional[jax.Array] = None,
            mesh=None, with_aux: bool = False,
            return_hidden: bool = False) -> jax.Array:
    """Token ids [B, S] → logits [B, S, V] (float32).

    ``mesh`` is only needed for ``attn_impl="ring"`` (sequence parallelism):
    activations are constrained seq-sharded and attention runs as a
    blockwise ring over the ``seq`` axis.  ``with_aux=True`` also returns
    the mean MoE load-balancing loss across layers.  ``return_hidden=True``
    returns the pre-final-norm hidden states (and the aux loss) instead of
    logits — the chunked-loss path unembeds per chunk itself.
    """
    b, s = input_ids.shape
    if cfg.attn_impl == "ring" and mesh is None:
        raise ValueError(
            "attn_impl='ring' (sequence parallelism) requires mesh=; "
            "without it attention would silently fall back to the dense "
            "path and materialize full SxS logits")
    if cfg.cast_once:
        def _cast(path, leaf):
            keys = {getattr(p, "key", None) for p in path}
            if keys & {"ln1", "ln2", "router"}:
                return leaf
            return leaf.astype(cfg.dtype)

        params = dict(params)
        params["blocks"] = jax.tree_util.tree_map_with_path(
            _cast, params["blocks"])

    x = _embed(cfg, params, input_ids)
    seq_parallel = cfg.attn_impl == "ring" and mesh is not None
    if seq_parallel:
        from jax.sharding import NamedSharding, PartitionSpec as P

        from kubernetes_cloud_tpu.core.mesh import AXIS_SEQ, BATCH_AXES

        x = jax.lax.with_sharding_constraint(
            x, NamedSharding(mesh, P(BATCH_AXES, AXIS_SEQ, None)))

    rope = None
    bias = None
    if cfg.pos_emb == "rope":
        rope = rope_cache(s, cfg.rotary_dim, cfg.rope_theta)
    elif cfg.pos_emb == "alibi":
        # Per-head slopes only; the per-key bias ``slope * k_pos`` (ALiBi's
        # -slope*(i-j) under the causal mask, by softmax shift-invariance)
        # is materialized by the XLA path or computed in-kernel by pallas.
        bias = alibi_slopes(cfg.num_heads)

    if cfg.remat and cfg.remat_policy.startswith("attn_island"):
        # Attention runs *outside* the two checkpointed halves: its
        # forward is computed exactly once and its residuals (q/k/v/out
        # + the flash kernel's logsumexp) are saved for the backward.
        front = jax.checkpoint(_qkv_half, static_argnums=(0,))
        mlp_policy = (
            jax.checkpoint_policies.save_only_these_names("mlp_mid")
            if cfg.remat_policy == "attn_island_mlp"
            else jax.checkpoint_policies.nothing_saveable)
        back = jax.checkpoint(_mlp_half, static_argnums=(0,),
                              policy=mlp_policy)

        def body(carry, layer_params):
            q, k, v = front(cfg, layer_params, carry, rope)
            attn_vec = _attn_call(cfg, q, k, v, bias, attention_mask, mesh)
            return back(cfg, layer_params, carry, attn_vec, attention_mask)

    else:
        block = _block
        if cfg.remat:
            saved = {"nothing": (), "attn_out": ("attn_out",),
                     "attn_mlp": ("attn_out", "mlp_mid")}[cfg.remat_policy]
            policy = (jax.checkpoint_policies.save_only_these_names(*saved)
                      if saved else jax.checkpoint_policies.nothing_saveable)
            # cfg (0) and mesh (6) are static: hashable non-array metadata.
            block = jax.checkpoint(
                _block, static_argnums=(0, 6), policy=policy)

        def body(carry, layer_params):
            out, aux = block(cfg, layer_params, carry, rope, bias,
                             attention_mask, mesh)
            return out, aux

    x, auxs = jax.lax.scan(body, x, params["blocks"])
    if return_hidden:
        return x, auxs.mean()
    logits = _unembed(cfg, params, x)
    if with_aux:
        return logits, auxs.mean()
    return logits


def loss_fn(cfg: CausalLMConfig, params: Params, batch: dict[str, jax.Array],
            mesh=None) -> tuple[jax.Array, dict[str, jax.Array]]:
    """Next-token cross-entropy with attention-mask label masking.

    Matches the reference trainer's semantics (labels are the inputs,
    positions with ``attention_mask == 0`` excluded from the loss —
    ``finetuner-workflow/finetuner/finetuner.py:469-493``).
    """
    input_ids = batch["input_ids"]
    # attention_mask=None stays None through forward (keeps the unpadded
    # fast path / pallas dispatch eligible); the ones-mask is only for
    # label accounting.
    attn_mask = batch.get("attention_mask")
    hidden, aux = forward(cfg, params, input_ids,
                          attention_mask=attn_mask, mesh=mesh,
                          return_hidden=True)
    if cfg.loss_chunk_size:
        loss, metrics = chunked_next_token_xent(
            cfg, params, hidden, input_ids, attn_mask,
            cfg.loss_chunk_size)
    else:
        loss, metrics = fused_next_token_xent(
            cfg, params, hidden, input_ids, attn_mask)
    if cfg.moe_experts:
        loss = loss + cfg.moe_aux_weight * aux
        metrics = dict(metrics, loss=loss, aux_loss=aux)
    return loss, metrics


def shift_targets(
    input_ids: jax.Array, attn_mask: Optional[jax.Array],
) -> tuple[jax.Array, jax.Array]:
    """Next-token label accounting, shared by every loss path (reference
    semantics ``finetuner.py:469-493``): ``targets[i] = input_ids[i+1]``,
    a position contributes iff it AND its target are unmasked, and the
    final position (no target) is masked.  Returned padded to the full
    sequence length so chunked/pipelined shapes stay uniform."""
    b = input_ids.shape[0]
    mask = (jnp.ones_like(input_ids) if attn_mask is None else attn_mask)
    targets = jnp.concatenate(
        [input_ids[:, 1:], jnp.zeros((b, 1), input_ids.dtype)], axis=1)
    tgt_mask = jnp.concatenate(
        [(mask[:, 1:] != 0) & (mask[:, :-1] != 0),
         jnp.zeros((b, 1), bool)], axis=1)
    return targets, tgt_mask


def fused_next_token_xent(
    cfg: CausalLMConfig, params: Params, hidden: jax.Array,
    input_ids: jax.Array, attn_mask: Optional[jax.Array],
) -> tuple[jax.Array, dict[str, jax.Array]]:
    """Next-token CE straight from hidden states, without materializing
    fp32 logits or a log-softmax tensor.

    ``nll = lse - logits[target]`` is exactly ``-log_softmax[target]``,
    but the [B, S, V] logits stay in the compute dtype (the MXU already
    rounded them) and only the per-position lse/target-logit reductions
    run in fp32 — the fp32 logits + logp pair the naive path writes is
    ~6.6 GiB at bs16/seq1024/vocab50k, the single largest HBM cost of
    the training step after attention (round-4 trace).
    """
    targets, tgt_mask = shift_targets(input_ids, attn_mask)
    nll = _nll_from_hidden(cfg, params, hidden, targets)
    denom = jnp.maximum(tgt_mask.sum(), 1)
    loss = jnp.where(tgt_mask, nll, 0.0).sum() / denom
    return loss, {"loss": loss, "tokens": tgt_mask.sum()}


def _nll_from_hidden(cfg: CausalLMConfig, params: Params, hidden: jax.Array,
                     targets: jax.Array) -> jax.Array:
    """[B, S, D] pre-final-norm hidden + [B, S] targets → fp32 [B, S] nll,
    via the lse formulation above.  Shared by the dense and chunked paths
    so their numerics can only differ by summation order."""
    logits = _unembed_raw(cfg, params, hidden)
    m = jax.lax.stop_gradient(jnp.max(logits, axis=-1, keepdims=True))
    lse = (jnp.log(jnp.sum(jnp.exp((logits - m).astype(jnp.float32)),
                           axis=-1))
           + m[..., 0].astype(jnp.float32))
    tgt = jnp.take_along_axis(logits, targets[..., None], axis=-1)[..., 0]
    return lse - tgt.astype(jnp.float32)


def chunked_next_token_xent(
    cfg: CausalLMConfig, params: Params, hidden: jax.Array,
    input_ids: jax.Array, attn_mask: Optional[jax.Array],
    chunk: int,
) -> tuple[jax.Array, dict[str, jax.Array]]:
    """Next-token CE without ever materializing [B, S, V] logits.

    The sequence is scanned in chunks of ``chunk`` positions; each chunk
    unembeds (final norm + lm_head) and reduces to masked nll sums, with
    ``jax.checkpoint`` so the backward pass recomputes each chunk's
    logits instead of storing them.  Peak loss memory drops from
    O(B*S*V) to O(B*chunk*V).  Numerics identical to the dense path
    (same fp32 log_softmax per position).
    """
    b, s = input_ids.shape
    if s % chunk:
        raise ValueError(f"loss_chunk_size {chunk} must divide seq {s}")
    targets, tgt_mask = shift_targets(input_ids, attn_mask)

    n_chunks = s // chunk
    h = hidden.reshape(b, n_chunks, chunk, -1).swapaxes(0, 1)
    t = targets.reshape(b, n_chunks, chunk).swapaxes(0, 1)
    m = tgt_mask.reshape(b, n_chunks, chunk).swapaxes(0, 1)

    @jax.checkpoint
    def chunk_nll(hc, tc, mc):
        nll = _nll_from_hidden(cfg, params, hc, tc)
        return jnp.where(mc, nll, 0.0).sum()

    def body(acc, xs):
        hc, tc, mc = xs
        return acc + chunk_nll(hc, tc, mc), None

    total, _ = jax.lax.scan(body, jnp.zeros((), jnp.float32), (h, t, m))
    denom = jnp.maximum(tgt_mask.sum(), 1)
    loss = total / denom
    return loss, {"loss": loss, "tokens": tgt_mask.sum()}


def next_token_xent(
    logits: jax.Array, input_ids: jax.Array,
    attn_mask: Optional[jax.Array] = None,
) -> tuple[jax.Array, dict[str, jax.Array]]:
    """Shared next-token cross-entropy tail (dense and pipelined paths)."""
    targets, tgt_mask = shift_targets(input_ids, attn_mask)
    # the final position is masked by shift_targets; drop it before the
    # softmax so the dense path does no wasted vocab work on it
    logp = jax.nn.log_softmax(logits[:, :-1], axis=-1)
    nll = -jnp.take_along_axis(
        logp, targets[:, :-1, None], axis=-1)[..., 0]
    denom = jnp.maximum(tgt_mask.sum(), 1)
    loss = jnp.where(tgt_mask[:, :-1], nll, 0.0).sum() / denom
    return loss, {"loss": loss, "tokens": tgt_mask.sum()}


def param_count(params: Params) -> int:
    return sum(x.size for x in jax.tree_util.tree_leaves(params))
