from kubernetes_cloud_tpu.models.causal_lm import (  # noqa: F401
    CausalLMConfig,
    PRESETS,
    forward,
    init_params,
    loss_fn,
)
