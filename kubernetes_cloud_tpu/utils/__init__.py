from kubernetes_cloud_tpu.utils.cli import (  # noqa: F401
    DashParser,
    FuzzyBoolAction,
    validators,
)
