"""CLI flag system: dash/underscore-tolerant flags, fuzzy booleans, typed
validators.

Behavioral parity with the reference's flag framework
(``finetuner-workflow/finetuner/utils.py:149-356``): every workflow YAML in
the reference templates flags in ``--dash-case`` while the Python uses
``underscore_case``; ``DashParser`` accepts both spellings for every option
so the ported Argo parameter lists (``finetune-workflow.yaml:8-199``) work
verbatim.  ``FuzzyBoolAction`` accepts the boolean spellings the workflows
pass (``true/false/yes/no/on/off/1/0``, bare flag = true).
"""

from __future__ import annotations

import argparse
import os
from types import SimpleNamespace

_TRUE = {"true", "t", "yes", "y", "on", "1"}
_FALSE = {"false", "f", "no", "n", "off", "0"}


def parse_bool(value: str) -> bool:
    v = value.strip().lower()
    if v in _TRUE:
        return True
    if v in _FALSE:
        return False
    raise argparse.ArgumentTypeError(f"not a boolean: {value!r}")


class FuzzyBoolAction(argparse.Action):
    """``--flag``, ``--flag true``, ``--flag=no`` all work.

    Matches the reference's inversion contract (``utils.py:229-292``):
    "``True`` means the same as the flag being present" — a bare flag or a
    truthy value sets ``not default``, a falsy value sets ``default``.
    With ``default=True`` this gives ``store_false`` behavior, so
    ``--no-resume`` (dest=resume, default=True) turns resume off and
    ``--no-resume false`` keeps it on."""

    def __init__(self, option_strings, dest, nargs="?", default=False, **kwargs):
        kwargs.pop("type", None)
        super().__init__(option_strings, dest, nargs=nargs, default=default,
                         **kwargs)

    def __call__(self, parser, namespace, values, option_string=None):
        if values is None:
            truthy = True
        elif isinstance(values, bool):
            truthy = values
        else:
            truthy = parse_bool(values)
        setattr(namespace, self.dest,
                (not self.default) if truthy else self.default)


class DashParser(argparse.ArgumentParser):
    """ArgumentParser where every long option gets a dash and an underscore
    alias, parsing to the underscore destination
    (reference ``utils.py:149-226``)."""

    def add_argument(self, *names, **kwargs):
        long_names = [n for n in names if n.startswith("--")]
        other = [n for n in names if not n.startswith("--")]
        aliases: list[str] = []
        seen = set()
        for name in long_names:
            body = name[2:]
            for variant in {body, body.replace("-", "_"), body.replace("_", "-")}:
                flag = "--" + variant
                if flag not in seen:
                    seen.add(flag)
                    aliases.append(flag)
        if long_names and "dest" not in kwargs:
            kwargs["dest"] = long_names[0][2:].replace("-", "_")
        return super().add_argument(*other, *aliases, **kwargs)

    def add_bool_argument(self, *names, default=False, help=None):
        return self.add_argument(*names, action=FuzzyBoolAction,
                                 default=default, help=help)


def _positive(type_, special_val=None):
    """> 0, with an optional escape value (e.g. -1 = autosize; reference
    ``utils.py`` ``val.positive(int, special_val=-1)``)."""
    def check(value):
        v = type_(value)
        if special_val is not None and v == special_val:
            return v
        if v <= 0:
            raise argparse.ArgumentTypeError(f"must be > 0, got {v}")
        return v
    return check


def _non_negative(type_, special_val=None):
    def check(value):
        v = type_(value)
        if special_val is not None and v == special_val:
            return v
        if v < 0:
            raise argparse.ArgumentTypeError(f"must be >= 0, got {v}")
        return v
    return check


def _at_most_1(type_):
    def check(value):
        v = type_(value)
        if not (0 <= v <= 1):
            raise argparse.ArgumentTypeError(f"must be in [0, 1], got {v}")
        return v
    return check


def _at_most_32_bit(type_):
    def check(value):
        v = type_(value)
        if not (0 <= v < 2 ** 32):
            raise argparse.ArgumentTypeError(f"must fit in 32 bits, got {v}")
        return v
    return check


def _extant_file(value: str) -> str:
    if not os.path.isfile(value):
        raise argparse.ArgumentTypeError(f"no such file: {value}")
    return value


#: Typed argument validators (reference ``utils.py:295-356``).
validators = SimpleNamespace(
    positive=_positive,
    non_negative=_non_negative,
    at_most_1=_at_most_1,
    at_most_32_bit=_at_most_32_bit,
    extant_file=_extant_file,
    parse_bool=parse_bool,
)

#: Short alias matching the reference's ``import utils.validators as val``.
val = validators
