"""Version-compat shims for the jax API surface the framework uses."""

from __future__ import annotations

import inspect

try:
    from jax import shard_map as _shard_map
except ImportError:  # older jax
    from jax.experimental.shard_map import shard_map as _shard_map

# jax>=0.8 renamed check_rep -> check_vma; jax 0.9 dropped check_rep.
_PARAMS = inspect.signature(_shard_map).parameters
_CHECK_KW = "check_vma" if "check_vma" in _PARAMS else "check_rep"
_HAS_AXIS_NAMES = "axis_names" in _PARAMS


def tpu_compiler_params(**kwargs):
    """``pltpu.CompilerParams`` across the rename (jax < 0.5 spells it
    ``TPUCompilerParams``)."""
    from jax.experimental.pallas import tpu as pltpu

    cls = getattr(pltpu, "CompilerParams", None)
    if cls is None:
        cls = pltpu.TPUCompilerParams
    return cls(**kwargs)


def shard_map(*args, **kwargs):
    """jax.shard_map accepting either check_rep= or check_vma=."""
    for alias in ("check_rep", "check_vma"):
        if alias in kwargs and alias != _CHECK_KW:
            kwargs[_CHECK_KW] = kwargs.pop(alias)
    if "axis_names" in kwargs and not _HAS_AXIS_NAMES:
        raise NotImplementedError(
            "this jax version's shard_map lacks axis_names= (partial-manual "
            "mode); jax >= 0.8 is required for the pipeline-parallel path")
    return _shard_map(*args, **kwargs)
