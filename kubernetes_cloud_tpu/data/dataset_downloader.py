"""Dataset-downloader container entrypoint (workflow step
``deploy/finetuner-workflow/finetune-workflow.yaml`` dataset-downloader;
the reference's demo-corpus fetcher, ``finetune-workflow.yaml:192-195``).

``--urls`` takes a URL-list file or single URL; ``--output`` is the PVC
destination (implementation in
:mod:`kubernetes_cloud_tpu.data.downloader_cli`)."""

from __future__ import annotations

import argparse
import os
from typing import Optional

from kubernetes_cloud_tpu.data.downloader_cli import download_dataset


def main(argv: Optional[list] = None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--output", required=True, help="destination dir")
    ap.add_argument("--urls", default=None,
                    help="URL-list file or single URL; default: the "
                         "DATASET_URLS env")
    ap.add_argument("--retries", type=int, default=3)
    args = ap.parse_args(argv)
    source = args.urls or os.environ.get("DATASET_URLS")
    if not source:
        raise SystemExit("need --urls or DATASET_URLS")
    if os.path.exists(source):
        with open(source) as f:
            urls = [ln.strip() for ln in f if ln.strip()]
    else:
        urls = [source]
    download_dataset(urls, args.output, retries=args.retries)
    return 0


if __name__ == "__main__":  # pragma: no cover - container entry
    import sys

    sys.exit(main())
