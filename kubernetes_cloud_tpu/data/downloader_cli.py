"""Artifact downloader Jobs — model / dataset fetch onto the shared PVC.

The reference runs Go binaries as workflow steps: ``model_downloader``
(HF/diffusers snapshot → PVC, ``finetune-workflow.yaml:184-187,347-351``;
``--type diffusers`` variant at ``sd-finetune-workflow-template.yaml:229-233``)
and dataset fetchers (``smashwords-downloader``,
``finetune-workflow.yaml:192-195``; plain wget steps in
``gpt-neox/04-finetune-workflow.yaml:306-340``).  These are I/O-bound
container steps, so Python is the right tool (SURVEY.md §2.2); the
contract they must honor:

* idempotent — rerunning over a populated dir is a no-op;
* completion sentinel — ``.ready.txt`` written last, which downstream
  steps / serving pods poll before touching the artifact
  (``finetuner.py:1062``, ``bloom.py:79-90``);
* destination layout — a flat directory consumable by ``from_pretrained``
  -style loaders or the tokenizer step.

Usage::

    python -m kubernetes_cloud_tpu.data.downloader_cli model \
        --model EleutherAI/pythia-410m --dest /mnt/pvc/model [--type hf]
    python -m kubernetes_cloud_tpu.data.downloader_cli dataset \
        --urls urls.txt --dest /mnt/pvc/dataset
"""

from __future__ import annotations

import argparse
import hashlib
import os
import shutil
import sys
import time
import urllib.parse
import urllib.request

# One sentinel contract, one implementation — shared with the checkpoint
# layer that serves/trainers already poll.
from kubernetes_cloud_tpu.weights.checkpoint import (  # noqa: F401
    READY_SENTINEL,
    is_ready,
    mark_ready,
    wait_ready,
)


def download_model(model: str, dest: str, *, model_type: str = "hf",
                   revision: str | None = None,
                   allow_patterns: list[str] | None = None,
                   retries: int = 1) -> str:
    """HF snapshot → flat dir on the PVC.  ``model_type='diffusers'``
    keeps the pipeline subfolder layout (vae/ unet/ text_encoder/);
    ``'hf'`` flattens a transformers checkpoint.  ``retries`` bounds
    re-attempts of a failed fetch (the reference's Argo retryStrategy
    uses download=1; snapshot_download resumes partial files, so a retry
    only refetches what is missing)."""
    if is_ready(dest):
        print(f"{dest} already ready, skipping")
        return dest
    os.makedirs(dest, exist_ok=True)

    def _fetch():
        if os.path.isdir(model):
            # Local path (pre-mounted snapshot): copy is the download.
            for entry in os.listdir(model):
                src = os.path.join(model, entry)
                dst = os.path.join(dest, entry)
                if os.path.isdir(src):
                    shutil.copytree(src, dst, dirs_exist_ok=True)
                else:
                    shutil.copy2(src, dst)
            return
        from huggingface_hub import snapshot_download

        patterns = allow_patterns
        if patterns is None and model_type == "hf":
            # skip alternate-format weights; JAX import reads safetensors
            # or torch .bin, never both
            patterns = ["*.json", "*.txt", "*.model", "*.safetensors",
                        "tokenizer*", "*.bin"]
        snapshot_download(model, revision=revision, local_dir=dest,
                          allow_patterns=patterns)

    last_err: Exception | None = None
    for attempt in range(max(1, retries + 1)):
        try:
            _fetch()
            last_err = None
            break
        except Exception as e:  # noqa: BLE001 - retry any fetch error
            last_err = e
            if attempt < retries:
                time.sleep(2.0 * (attempt + 1))
    if last_err is not None:
        raise RuntimeError(f"failed to fetch {model}: {last_err}")
    mark_ready(dest)
    return dest


def download_dataset(urls: list[str], dest: str, *,
                     retries: int = 3) -> str:
    """Fetch a URL list into ``dest`` (the wget-step / demo-corpus
    equivalent).  Retries per file mirror the workflow's retryStrategy
    (``04-finetune-workflow.yaml:315-316``)."""
    if is_ready(dest):
        print(f"{dest} already ready, skipping")
        return dest
    os.makedirs(dest, exist_ok=True)
    seen: dict[str, str] = {}
    for url in urls:
        name = os.path.basename(urllib.parse.urlparse(url).path) or "file"
        if seen.setdefault(name, url) != url:
            # Same basename from a different URL: disambiguate rather than
            # silently skipping (which would mark a truncated corpus ready).
            digest = hashlib.sha256(url.encode()).hexdigest()[:8]
            stem, dot, ext = name.partition(".")
            name = f"{stem}-{digest}{dot}{ext}"
        out = os.path.join(dest, name)
        if os.path.exists(out):
            continue
        last_err: Exception | None = None
        for attempt in range(retries):
            try:
                tmp = out + ".tmp"
                with urllib.request.urlopen(url) as r, open(tmp, "wb") as f:
                    shutil.copyfileobj(r, f)
                os.replace(tmp, out)
                last_err = None
                break
            except Exception as e:  # noqa: BLE001 - retry any fetch error
                last_err = e
                time.sleep(2.0 * (attempt + 1))
        if last_err is not None:
            raise RuntimeError(f"failed to fetch {url}: {last_err}")
    mark_ready(dest)
    return dest


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    sub = ap.add_subparsers(dest="cmd", required=True)

    m = sub.add_parser("model")
    m.add_argument("--model", required=True,
                   help="HF repo id or local snapshot path")
    m.add_argument("--dest", required=True)
    m.add_argument("--type", dest="model_type", default="hf",
                   choices=("hf", "diffusers"))
    m.add_argument("--revision", default=None)
    m.add_argument("--retries", type=int, default=1,
                   help="re-attempts on fetch failure (reference Argo "
                        "retryStrategy: download=1)")

    d = sub.add_parser("dataset")
    d.add_argument("--urls", required=True,
                   help="file with one URL per line, or a single URL")
    d.add_argument("--dest", required=True)
    d.add_argument("--retries", type=int, default=3)

    w = sub.add_parser("wait")
    w.add_argument("--dest", required=True)
    w.add_argument("--timeout", type=float, default=3600.0)

    args = ap.parse_args(argv)
    if args.cmd == "model":
        download_model(args.model, args.dest, model_type=args.model_type,
                       revision=args.revision, retries=args.retries)
    elif args.cmd == "dataset":
        if os.path.exists(args.urls):
            with open(args.urls) as f:
                urls = [ln.strip() for ln in f if ln.strip()]
        else:
            urls = [args.urls]
        download_dataset(urls, args.dest, retries=args.retries)
    else:
        if not wait_ready(args.dest, args.timeout):
            print(f"timed out waiting for {args.dest}", file=sys.stderr)
            return 1
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
