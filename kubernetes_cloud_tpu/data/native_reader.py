"""ctypes bindings for the native batch reader (``csrc/batch_reader``).

The training input hot path — shuffled row gather + uint16→int32 widen +
trailing-pad mask — runs GIL-free in C++ threads, with madvise-based
prefetch of the next batch's pages.  The reference does the equivalent
per row in Python over numpy's mmap (``finetuner.py:633-695``); the
Python fallback in :class:`~kubernetes_cloud_tpu.data.tokenized
.TokenizedDataset` keeps working wherever a C++ toolchain is absent.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
from typing import Optional

import numpy as np

_CSRC = os.path.join(os.path.dirname(os.path.dirname(
    os.path.dirname(os.path.abspath(__file__)))), "csrc", "batch_reader")

_lib: Optional[ctypes.CDLL] = None
_lib_failed = False


def build_library(out_dir: Optional[str] = None, *,
                  force: bool = False) -> str:
    """Compile the shared library (cached); returns its path."""
    src = os.path.join(_CSRC, "batch_reader.cpp")
    if out_dir is None:
        out_dir = os.path.join(_CSRC, "build")
    os.makedirs(out_dir, exist_ok=True)
    lib = os.path.join(out_dir, "libbatch_reader.so")
    if not force and os.path.exists(lib) and (
            os.path.getmtime(lib) >= os.path.getmtime(src)):
        return lib
    # Compile to a private temp path and rename: concurrent processes
    # (pytest-xdist, several data workers) must never dlopen a
    # half-written .so or interleave compiler output at one path.
    tmp = f"{lib}.tmp.{os.getpid()}"
    subprocess.run(
        ["g++", "-O3", "-std=c++17", "-shared", "-fPIC", "-pthread",
         src, "-o", tmp],
        check=True, capture_output=True, text=True)
    os.replace(tmp, lib)
    return lib


def _load() -> Optional[ctypes.CDLL]:
    global _lib, _lib_failed
    if _lib is not None or _lib_failed:
        return _lib
    try:
        lib = ctypes.CDLL(build_library())
    except Exception:  # noqa: BLE001 - no toolchain => python fallback
        _lib_failed = True
        return None
    lib.br_open.restype = ctypes.c_void_p
    lib.br_open.argtypes = [ctypes.c_char_p, ctypes.c_int64]
    lib.br_num_rows.restype = ctypes.c_int64
    lib.br_num_rows.argtypes = [ctypes.c_void_p]
    lib.br_gather.restype = ctypes.c_int
    lib.br_gather.argtypes = [
        ctypes.c_void_p, ctypes.POINTER(ctypes.c_int64), ctypes.c_int64,
        ctypes.POINTER(ctypes.c_int32), ctypes.POINTER(ctypes.c_int32),
        ctypes.c_int32, ctypes.c_int]
    lib.br_prefetch.restype = None
    lib.br_prefetch.argtypes = [
        ctypes.c_void_p, ctypes.POINTER(ctypes.c_int64), ctypes.c_int64]
    lib.br_close.restype = None
    lib.br_close.argtypes = [ctypes.c_void_p]
    _lib = lib
    return _lib


def available() -> bool:
    return _load() is not None


class NativeTokenReader:
    """Native gather over a flat uint16 context-row file."""

    def __init__(self, path: str, context_size: int,
                 pad_token: Optional[int] = None, *, n_threads: int = 4):
        lib = _load()
        if lib is None:
            raise RuntimeError("native batch reader unavailable")
        self._lib = lib
        self._handle = lib.br_open(path.encode(), context_size)
        if not self._handle:
            raise OSError(f"br_open failed for {path}")
        self.context_size = context_size
        self.pad_token = pad_token
        self.n_threads = n_threads
        self.num_rows = int(lib.br_num_rows(self._handle))

    def __len__(self) -> int:
        return self.num_rows

    def gather(self, rows) -> dict[str, np.ndarray]:
        """rows [N] -> {"input_ids" [N, C] int32, "attention_mask" ...}"""
        rows = np.ascontiguousarray(rows, np.int64)
        n = rows.shape[0]
        ids = np.empty((n, self.context_size), np.int32)
        mask = np.empty((n, self.context_size), np.int32)
        rc = self._lib.br_gather(
            self._handle,
            rows.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)), n,
            ids.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
            mask.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
            -1 if self.pad_token is None else int(self.pad_token),
            self.n_threads)
        if rc != 0:
            raise IndexError(
                f"row index out of range (num_rows={self.num_rows})")
        return {"input_ids": ids, "attention_mask": mask}

    def prefetch(self, rows) -> None:
        """Advise the kernel to page in the next batch's rows."""
        rows = np.ascontiguousarray(rows, np.int64)
        self._lib.br_prefetch(
            self._handle,
            rows.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
            rows.shape[0])

    def close(self) -> None:
        if getattr(self, "_handle", None):
            self._lib.br_close(self._handle)
            self._handle = None

    def __del__(self):  # pragma: no cover - GC timing
        try:
            self.close()
        except Exception:  # noqa: BLE001
            pass
