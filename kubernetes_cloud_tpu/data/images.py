"""Image-classification input pipeline (ImageNet-folder layout).

Replaces the reference's torchvision ``ImageFolder`` + ``DistributedSampler``
+ transform stack (``kubeflow/training-operator/resnet50/util.py:169-199``):

* class-per-directory layout discovered the same way (sorted dir names →
  label ids);
* per-host sharding replaces ``DistributedSampler`` — each host reads only
  ``files[process_index::process_count]`` and builds its slice of the
  globally-sharded batch (under pjit the global batch is the concatenation);
* transforms: resize-crop to ``image_size``, fp32 scale to [0,1], ImageNet
  mean/std normalization, random horizontal flip in training.

NumPy/PIL only — the decode happens on host CPU, the normalized batch is
device_put as NHWC (TPU layout).
"""

from __future__ import annotations

import dataclasses
import os
from typing import Iterator, Optional

import numpy as np

IMAGENET_MEAN = np.array([0.485, 0.456, 0.406], np.float32)
IMAGENET_STD = np.array([0.229, 0.224, 0.225], np.float32)

_EXTS = (".jpg", ".jpeg", ".png", ".bmp", ".webp")


@dataclasses.dataclass
class ImageFolderDataset:
    """<root>/<class_name>/<image> layout, labels by sorted class name."""

    root: str
    image_size: int = 224
    train: bool = True
    seed: int = 0

    def __post_init__(self):
        classes = sorted(
            d for d in os.listdir(self.root)
            if os.path.isdir(os.path.join(self.root, d)))
        if not classes:
            raise FileNotFoundError(f"no class directories under {self.root}")
        self.class_to_idx = {c: i for i, c in enumerate(classes)}
        self.samples: list[tuple[str, int]] = []
        for c in classes:
            cdir = os.path.join(self.root, c)
            for fn in sorted(os.listdir(cdir)):
                if fn.lower().endswith(_EXTS):
                    self.samples.append(
                        (os.path.join(cdir, fn), self.class_to_idx[c]))

    def __len__(self) -> int:
        return len(self.samples)

    def _load(self, path: str, rng: Optional[np.random.Generator]):
        from PIL import Image

        img = Image.open(path).convert("RGB")
        s = self.image_size
        if self.train and rng is not None:
            # Random resized crop, cheap variant: resize short side to
            # [s, 1.15*s], random crop, random hflip.
            short = int(s * (1 + 0.15 * rng.random()))
            img = _resize_short(img, short)
            x0 = rng.integers(0, img.width - s + 1)
            y0 = rng.integers(0, img.height - s + 1)
            img = img.crop((x0, y0, x0 + s, y0 + s))
            if rng.random() < 0.5:
                img = img.transpose(Image.FLIP_LEFT_RIGHT)
        else:
            return eval_transform(img, s)
        arr = np.asarray(img, np.float32) / 255.0
        return (arr - IMAGENET_MEAN) / IMAGENET_STD

    def batches(
        self,
        batch_size: int,
        *,
        epoch: int = 0,
        process_index: int = 0,
        process_count: int = 1,
        drop_remainder: bool = True,
    ) -> Iterator[dict]:
        """Per-host shard of globally-shuffled batches.  ``batch_size`` is
        the per-host size; shuffling is seeded by (seed, epoch) identically
        on every host so the global permutation agrees (the
        ``DistributedSampler.set_epoch`` contract)."""
        order = np.arange(len(self.samples))
        if self.train:
            np.random.default_rng((self.seed, epoch)).shuffle(order)
        # Strided shards differ in length by up to one sample; truncate to
        # the common minimum so every host yields the SAME number of
        # batches — unequal counts deadlock the SPMD program at the first
        # collective.  (DistributedSampler pads with duplicates instead;
        # truncation drops <process_count samples and stays exact.)
        common = len(order) // process_count
        local = order[process_index::process_count][:common]
        rng = np.random.default_rng((self.seed, epoch, process_index))
        n_full = len(local) // batch_size
        end = n_full * batch_size if drop_remainder else len(local)
        for i in range(0, end, batch_size):
            idx = local[i:i + batch_size]
            imgs = np.stack([
                self._load(self.samples[j][0], rng if self.train else None)
                for j in idx])
            labels = np.array([self.samples[j][1] for j in idx], np.int32)
            yield {"image": imgs, "label": labels}


def _resize_short(img, short: int):
    from PIL import Image

    w, h = img.size
    if w < h:
        return img.resize((short, int(h * short / w)), Image.BILINEAR)
    return img.resize((int(w * short / h), short), Image.BILINEAR)


def eval_transform(img, size: int) -> np.ndarray:
    """Standard ImageNet eval preprocessing: resize short side by 256/224
    (exactly 256 for the 224 crop), center-crop, scale to [0,1], normalize.
    Shared by the eval data path and the serving-side ImageTransformer so
    train-time and serve-time preprocessing cannot drift."""
    img = _resize_short(img, int(round(size * 256 / 224)))
    x0 = (img.width - size) // 2
    y0 = (img.height - size) // 2
    img = img.crop((x0, y0, x0 + size, y0 + size))
    arr = np.asarray(img, np.float32) / 255.0
    return (arr - IMAGENET_MEAN) / IMAGENET_STD


def synthetic_batches(batch_size: int, *, image_size: int = 224,
                      num_classes: int = 1000, steps: int = 10,
                      seed: int = 0) -> Iterator[dict]:
    """Deterministic synthetic data for smoke tests and benchmarks: each
    class has a distinct mean pixel value, so a working model can actually
    learn the mapping (unlike pure noise)."""
    rng = np.random.default_rng(seed)
    for _ in range(steps):
        labels = rng.integers(0, num_classes, size=batch_size).astype(
            np.int32)
        base = (labels[:, None, None, None] / num_classes).astype(np.float32)
        noise = rng.normal(0, 0.1, (batch_size, image_size, image_size, 3))
        yield {"image": (base + noise).astype(np.float32), "label": labels}
