from kubernetes_cloud_tpu.data.tokenized import (  # noqa: F401
    TokenizedDataset,
    sharded_batches,
)
from kubernetes_cloud_tpu.data.tokenizer_cli import (  # noqa: F401
    build_tokenizer,
    run_tokenizer,
)
