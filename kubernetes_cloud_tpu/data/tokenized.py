"""Memory-mapped pre-tokenized dataset + per-host sharded batching.

Consumer side of the ``dataset_tokenizer`` output, with the reference
trainer's semantics (``finetuner-workflow/finetuner/finetuner.py:633-695``):
a flat little-endian uint16 file of fixed-size context rows, mmap'd
zero-copy, with the attention mask derived from trailing pad tokens
(pad runs at the end of a row are masked out; pad ids appearing mid-row —
e.g. when pad == eot — stay visible).

Distribution replaces ``torch.utils.data.DistributedSampler``
(``kubeflow/training-operator/resnet50/util.py:169-199``): each host reads
only its row stripe and global arrays are assembled with
``jax.make_array_from_process_local_data`` over the mesh's batch axes.
"""

from __future__ import annotations

import json
import os
from typing import Iterator, Optional

import jax
import numpy as np

from kubernetes_cloud_tpu.parallel.sharding import batch_spec, logical_to_physical


class TokenizedDataset:
    def __init__(self, path: str, context_size: Optional[int] = None,
                 *, pad_token: Optional[int] = None):
        if context_size is None or pad_token is None:
            sidecar = path + ".json"
            if os.path.exists(sidecar):
                with open(sidecar) as f:
                    meta = json.load(f)
                context_size = context_size or meta["context_size"]
                pad_token = pad_token if pad_token is not None else (
                    meta.get("pad_token"))
        if context_size is None:
            raise ValueError("context_size not given and no sidecar found")
        nbytes = os.path.getsize(path)
        row_bytes = context_size * 2
        if nbytes % row_bytes:
            raise ValueError(
                f"{path}: {nbytes} bytes is not a whole number of "
                f"{context_size}-token rows")
        self.path = path
        self.context_size = context_size
        self.pad_token = pad_token
        self.tokens = np.memmap(path, dtype=np.uint16, mode="r",
                                shape=(nbytes // row_bytes, context_size))
        # Native threaded gather+mask (csrc/batch_reader) when the
        # toolchain is present; the numpy mmap stays as the fallback and
        # the per-row __getitem__ path.
        self._native = None
        from kubernetes_cloud_tpu.data import native_reader

        if native_reader.available():
            try:
                self._native = native_reader.NativeTokenReader(
                    path, context_size, pad_token)
            except Exception:  # noqa: BLE001 - any native failure =>
                self._native = None  # python fallback, never a crash

    def __len__(self) -> int:
        return self.tokens.shape[0]

    def __getitem__(self, idx) -> dict[str, np.ndarray]:
        ids = np.asarray(self.tokens[idx], dtype=np.int32)
        return {"input_ids": ids, "attention_mask": self.mask_for(ids)}

    def mask_for(self, ids: np.ndarray) -> np.ndarray:
        """1 for real tokens; trailing pad-token runs are 0."""
        if self.pad_token is None:
            return np.ones_like(ids, dtype=np.int32)
        is_pad = ids == self.pad_token
        # a position is masked iff it and everything after it is pad
        trailing_pad = np.flip(
            np.logical_and.accumulate(np.flip(is_pad, -1), axis=-1), -1)
        return (~trailing_pad).astype(np.int32)

    def gather(self, rows: np.ndarray) -> dict[str, np.ndarray]:
        """Batch gather: native (threaded, GIL-free) when available."""
        if self._native is not None:
            return self._native.gather(rows)
        ids = np.asarray(self.tokens[np.asarray(rows)], dtype=np.int32)
        return {"input_ids": ids, "attention_mask": self.mask_for(ids)}

    def prefetch(self, rows: np.ndarray) -> None:
        if self._native is not None:
            self._native.prefetch(rows)

    def split(self, train_ratio: float) -> tuple["Slice", "Slice"]:
        """Deterministic train/val split by leading fraction (reference
        ``--train_ratio`` flag semantics)."""
        n_train = int(len(self) * train_ratio)
        return Slice(self, 0, n_train), Slice(self, n_train, len(self))


class Slice:
    def __init__(self, ds: TokenizedDataset, start: int, stop: int):
        self.ds, self.start, self.stop = ds, start, stop

    def __len__(self) -> int:
        return self.stop - self.start

    def __getitem__(self, idx):
        if isinstance(idx, (int, np.integer)):
            if idx < 0 or idx >= len(self):
                raise IndexError(idx)
            return self.ds[self.start + int(idx)]
        return self.ds[np.asarray(idx) + self.start]

    def gather(self, rows: np.ndarray) -> dict[str, np.ndarray]:
        rows = np.asarray(rows)
        if ((rows < 0) | (rows >= len(self))).any():
            raise IndexError("slice row index out of range")
        return self.ds.gather(rows + self.start)

    def prefetch(self, rows: np.ndarray) -> None:
        self.ds.prefetch(np.asarray(rows) + self.start)


def sharded_batches(
    dataset,
    global_batch_size: int,
    mesh,
    *,
    shuffle: bool = True,
    seed: int = 0,
    epochs: Optional[int] = None,
    drop_last: bool = True,
    skip_batches: int = 0,
) -> Iterator[dict[str, jax.Array]]:
    """Yield globally-sharded batches from a per-host dataset stripe.

    Each process loads rows ``i`` with ``i % process_count == process_index``
    within the shuffled order, then the local arrays are joined into global
    arrays sharded over the mesh batch axes.
    """
    n_proc = jax.process_count()
    proc = jax.process_index()
    if global_batch_size % n_proc:
        raise ValueError("global batch must divide evenly across hosts")
    local_bs = global_batch_size // n_proc
    sharding = logical_to_physical(batch_spec(2), mesh)

    rng = np.random.RandomState(seed)
    epoch = 0
    while epochs is None or epoch < epochs:
        order = np.arange(len(dataset))
        if shuffle:
            rng.shuffle(order)
        order = order[proc::n_proc]
        # Every host MUST emit the same batch count or the SPMD program
        # deadlocks at the first collective; strided shards differ in length
        # by one, so compute the count from the guaranteed-common minimum.
        n_full = (len(dataset) // n_proc) // local_bs
        if skip_batches >= n_full:
            # Resume fast-forward: advance the (deterministic) shuffle
            # stream without materializing device batches.
            skip_batches -= n_full
            epoch += 1
            continue
        start = skip_batches
        skip_batches = 0
        gather = getattr(dataset, "gather", None)
        prefetch = getattr(dataset, "prefetch", None)
        for b in range(start, n_full):
            idx = order[b * local_bs:(b + 1) * local_bs]
            if gather is not None:
                local = gather(idx)
                if prefetch is not None and b + 1 < n_full:
                    # overlap the next batch's page-ins with device compute
                    prefetch(order[(b + 1) * local_bs:(b + 2) * local_bs])
            else:
                rows = [dataset[int(i)] for i in idx]
                local = {
                    k: np.stack([r[k] for r in rows]) for k in rows[0]
                }
            yield {
                k: jax.make_array_from_process_local_data(
                    sharding if v.ndim == 2 else
                    logical_to_physical(batch_spec(v.ndim), mesh), v)
                for k, v in local.items()
            }
        if not drop_last and len(order) % local_bs:
            pass  # partial batches are dropped; parity with DistributedSampler
        epoch += 1
