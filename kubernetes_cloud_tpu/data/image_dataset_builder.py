"""Bulk image-dataset builder — the Spark/img2dataset pipeline.

The reference downloads web-scale image-caption datasets with img2dataset
under a pyspark distributor and writes webdataset shards to the PVC
(``spark/docker/download_imgdataset.py:19-32``, submitted via
``spark/example-spark-submit.sh``).  Same capability, framework-native:

* input: CSV/TSV of ``url<sep>caption`` rows (cc12m-style);
* fetch + decode + resize (center-crop to ``image_size``) in a worker
  pool — ``distributor="threads"`` (I/O-bound default) or
  ``"processes"`` (the Spark-executor analogue for CPU-bound decode);
* output: **webdataset-layout tar shards** (``{key}.jpg`` + ``{key}.txt``
  + ``{key}.json`` members) consumable by
  :class:`kubernetes_cloud_tpu.data.diffusion.LocalBase`-style loaders
  after extraction, or streamed as tars;
* per-shard stats JSON (success/failure counts) like img2dataset's.

The k8s-scale-out story is unchanged from the reference: N builder pods
each take a slice (``--slice i/N``) of the URL list — the embarrassingly
parallel axis Spark was providing.
"""

from __future__ import annotations

import argparse
import csv
import io
import json
import os
import tarfile
import urllib.request
from concurrent.futures import (
    ProcessPoolExecutor,
    ThreadPoolExecutor,
)
from dataclasses import dataclass


@dataclass(frozen=True)
class BuilderConfig:
    image_size: int = 256
    shard_size: int = 1000  # samples per tar
    workers: int = 16
    distributor: str = "threads"  # or "processes"
    timeout: float = 10.0
    jpeg_quality: int = 95


def _fetch_and_process(job: tuple[int, str, str, BuilderConfig]):
    """Runs in the worker pool: fetch → decode → resize-crop → re-encode.
    Returns (key, jpeg_bytes, caption, meta) or (key, None, caption, meta
    with error)."""
    idx, url, caption, cfg = job
    key = f"{idx:09d}"
    meta = {"url": url, "caption": caption, "key": key}
    try:
        if os.path.exists(url):  # local path rows (pre-fetched corpora)
            with open(url, "rb") as f:
                raw = f.read()
        else:
            with urllib.request.urlopen(url, timeout=cfg.timeout) as r:
                raw = r.read()
        from PIL import Image

        img = Image.open(io.BytesIO(raw)).convert("RGB")
        s = cfg.image_size
        w, h = img.size
        scale = s / min(w, h)
        img = img.resize((max(s, int(w * scale)), max(s, int(h * scale))),
                         Image.BILINEAR)
        x0 = (img.width - s) // 2
        y0 = (img.height - s) // 2
        img = img.crop((x0, y0, x0 + s, y0 + s))
        buf = io.BytesIO()
        img.save(buf, "JPEG", quality=cfg.jpeg_quality)
        meta.update(width=s, height=s, status="success")
        return key, buf.getvalue(), caption, meta
    except Exception as e:  # noqa: BLE001 - per-sample failure is data
        meta.update(status="failed", error=str(e))
        return key, None, caption, meta


def read_url_list(path: str, *, url_col: str = "url",
                  caption_col: str = "caption") -> list[tuple[str, str]]:
    """CSV/TSV with header; falls back to 2 positional columns."""
    delim = "\t" if path.endswith((".tsv", ".txt")) else ","
    rows: list[tuple[str, str]] = []
    with open(path, newline="") as f:
        sniff = csv.reader(f, delimiter=delim)
        header = next((row for row in sniff if row), None)  # skip blanks
        if header is None:
            return rows
        if url_col in header:
            ui, ci = header.index(url_col), (
                header.index(caption_col) if caption_col in header else None)
            for row in sniff:
                if len(row) > ui:
                    rows.append((row[ui],
                                 row[ci] if ci is not None
                                 and len(row) > ci else ""))
        else:  # headerless
            rows.append((header[0], header[1] if len(header) > 1 else ""))
            for row in sniff:
                if row:
                    rows.append((row[0], row[1] if len(row) > 1 else ""))
    return rows


def build(
    url_list: str,
    output_dir: str,
    cfg: BuilderConfig = BuilderConfig(),
    *,
    slice_index: int = 0,
    slice_count: int = 1,
) -> dict:
    """Build webdataset tar shards; returns aggregate stats."""
    os.makedirs(output_dir, exist_ok=True)
    rows = read_url_list(url_list)[slice_index::slice_count]
    jobs = [(slice_index + i * slice_count, url, cap, cfg)
            for i, (url, cap) in enumerate(rows)]

    pool_cls = (ProcessPoolExecutor if cfg.distributor == "processes"
                else ThreadPoolExecutor)
    stats = {"total": len(jobs), "success": 0, "failed": 0, "shards": 0}
    shard_idx = 0
    tar: tarfile.TarFile | None = None
    in_shard = 0

    def open_shard(i: int) -> tarfile.TarFile:
        path = os.path.join(output_dir,
                            f"{slice_index:03d}-{i:05d}.tar")
        return tarfile.open(path, "w")

    def add_member(tf: tarfile.TarFile, name: str, data: bytes) -> None:
        info = tarfile.TarInfo(name)
        info.size = len(data)
        tf.addfile(info, io.BytesIO(data))

    with pool_cls(max_workers=cfg.workers) as pool:
        for key, jpeg, caption, meta in pool.map(_fetch_and_process, jobs):
            if jpeg is None:
                stats["failed"] += 1
                continue
            if tar is None or in_shard >= cfg.shard_size:
                if tar is not None:
                    tar.close()
                tar = open_shard(shard_idx)
                shard_idx += 1
                stats["shards"] += 1
                in_shard = 0
            add_member(tar, f"{key}.jpg", jpeg)
            add_member(tar, f"{key}.txt", caption.encode())
            add_member(tar, f"{key}.json",
                       json.dumps(meta).encode())
            in_shard += 1
            stats["success"] += 1
    if tar is not None:
        tar.close()

    with open(os.path.join(output_dir,
                           f"stats-{slice_index:03d}.json"), "w") as f:
        json.dump(stats, f)
    return stats


def main(argv=None) -> dict:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--url-list", required=True)
    ap.add_argument("--output-dir", required=True)
    ap.add_argument("--image-size", type=int, default=256)
    ap.add_argument("--shard-size", type=int, default=1000)
    ap.add_argument("--workers", type=int, default=16)
    ap.add_argument("--distributor", default="threads",
                    choices=("threads", "processes"))
    ap.add_argument("--slice", default="0/1",
                    help="i/N: this pod's slice of the url list")
    args = ap.parse_args(argv)
    i, n = (int(x) for x in args.slice.split("/"))
    cfg = BuilderConfig(image_size=args.image_size,
                        shard_size=args.shard_size, workers=args.workers,
                        distributor=args.distributor)
    stats = build(args.url_list, args.output_dir, cfg,
                  slice_index=i, slice_count=n)
    print(json.dumps(stats))
    return stats


if __name__ == "__main__":
    main()
