"""Image+caption datasets for diffusion finetuning.

Behavioral parity with the reference's dataset module
(``sd-finetuner-workflow/sd-finetuner/datasets.py``):

* :class:`LocalBase` — pairs ``img.png``/``img.jpg`` with ``img.txt`` by
  file stem (``datasets.py:145-233``), center-crop-resizes to the training
  resolution, normalizes to [-1, 1], and applies unconditional-guidance
  caption dropout with probability ``ucg`` (``datasets.py:181-183``).
* :class:`DreamBoothDataset` — instance/class directory pairs for
  prior-preservation training (``datasets.py:51-142``); generating missing
  class images is the trainer's job (``:94-101``), the dataset only
  reports ``missing_class_images``.
* :class:`PromptDataset` — prompts for class-image generation
  (``datasets.py:236-250``).

Arrays are NHWC float32 — the TPU conv layout — not torchvision CHW.
"""

from __future__ import annotations

import os
import random
from typing import Optional

import numpy as np

_IMG_EXTS = (".png", ".jpg", ".jpeg", ".webp", ".bmp")


def load_image(path: str, size: int) -> np.ndarray:
    """Load → center-crop → resize → [-1, 1] float32 NHWC (single image)."""
    from PIL import Image

    with Image.open(path) as im:
        im = im.convert("RGB")
        w, h = im.size
        crop = min(w, h)
        left, top = (w - crop) // 2, (h - crop) // 2
        im = im.crop((left, top, left + crop, top + crop))
        im = im.resize((size, size), Image.BICUBIC)
        arr = np.asarray(im, dtype=np.float32)
    return arr / 127.5 - 1.0


class LocalBase:
    """File-stem-paired image/caption dataset with ucg dropout."""

    def __init__(self, data_root: str, size: int = 512, ucg: float = 0.1,
                 seed: Optional[int] = None):
        self.size = size
        self.ucg = ucg
        self._rng = random.Random(seed)
        self.examples: list[tuple[str, str]] = []
        for name in sorted(os.listdir(data_root)):
            stem, ext = os.path.splitext(name)
            if ext.lower() not in _IMG_EXTS:
                continue
            txt = os.path.join(data_root, stem + ".txt")
            caption = ""
            if os.path.exists(txt):
                with open(txt) as fh:
                    caption = fh.read().strip()
            self.examples.append((os.path.join(data_root, name), caption))
        if not self.examples:
            raise ValueError(f"no images under {data_root}")

    def __len__(self) -> int:
        return len(self.examples)

    def __getitem__(self, idx: int) -> dict:
        path, caption = self.examples[idx]
        if self.ucg and self._rng.random() < self.ucg:
            caption = ""  # unconditional-guidance dropout
        return {"image": load_image(path, self.size), "caption": caption}


class DreamBoothDataset:
    """Instance/class pairs for prior-preservation finetuning.

    ``__getitem__`` returns both an instance and a (cycled) class example;
    the collate function stacks them [instance..., class...] so the trainer
    can chunk the loss (``sd-finetuner/finetuner.py:513-525``).
    """

    def __init__(self, instance_data_root: str, instance_prompt: str,
                 class_data_root: Optional[str] = None,
                 class_prompt: Optional[str] = None, size: int = 512,
                 num_class_images: int = 0):
        self.size = size
        self.instance_prompt = instance_prompt
        self.class_prompt = class_prompt
        self.instance_images = [
            os.path.join(instance_data_root, n)
            for n in sorted(os.listdir(instance_data_root))
            if os.path.splitext(n)[1].lower() in _IMG_EXTS]
        if not self.instance_images:
            raise ValueError(f"no images under {instance_data_root}")
        self.class_images: list[str] = []
        self.num_class_images = num_class_images
        if class_data_root:
            os.makedirs(class_data_root, exist_ok=True)
            self.class_data_root = class_data_root
            self.class_images = [
                os.path.join(class_data_root, n)
                for n in sorted(os.listdir(class_data_root))
                if os.path.splitext(n)[1].lower() in _IMG_EXTS]
        else:
            self.class_data_root = None

    @property
    def missing_class_images(self) -> int:
        """How many class images the trainer must generate first
        (reference auto-generates them, ``datasets.py:94-101``)."""
        if self.class_data_root is None:
            return 0
        return max(0, self.num_class_images - len(self.class_images))

    @property
    def with_prior(self) -> bool:
        return bool(self.class_data_root and self.class_images)

    def __len__(self) -> int:
        return max(len(self.instance_images),
                   len(self.class_images) or 1)

    def __getitem__(self, idx: int) -> dict:
        out = {
            "instance_image": load_image(
                self.instance_images[idx % len(self.instance_images)],
                self.size),
            "instance_caption": self.instance_prompt,
        }
        if self.with_prior:
            out["class_image"] = load_image(
                self.class_images[idx % len(self.class_images)], self.size)
            out["class_caption"] = self.class_prompt or ""
        return out


class PromptDataset:
    """N copies of one prompt (for class-image generation jobs)."""

    def __init__(self, prompt: str, num_samples: int):
        self.prompt = prompt
        self.num_samples = num_samples

    def __len__(self) -> int:
        return self.num_samples

    def __getitem__(self, idx: int) -> dict:
        return {"prompt": self.prompt, "index": idx}


def collate_images(rows: list[dict]) -> dict:
    """LocalBase batch → {"images" [B,H,W,3], "captions" list[str]}."""
    return {"images": np.stack([r["image"] for r in rows]),
            "captions": [r["caption"] for r in rows]}


def collate_dreambooth(rows: list[dict]) -> dict:
    """[instance..., class...] stacking so the prior-loss chunk split is a
    fixed midpoint (reference collate + chunked loss)."""
    images = [r["instance_image"] for r in rows]
    captions = [r["instance_caption"] for r in rows]
    if "class_image" in rows[0]:
        images += [r["class_image"] for r in rows]
        captions += [r["class_caption"] for r in rows]
    return {"images": np.stack(images), "captions": captions}
