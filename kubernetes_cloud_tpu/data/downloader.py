"""Model-downloader container entrypoint (workflow step
``deploy/finetuner-workflow/finetune-workflow.yaml`` model-downloader;
``deploy/online-inference/stable-diffusion/02-model-download-job.yaml``).

Flag surface mirrors the reference's Go ``model_downloader``
(``finetune-workflow.yaml:184-187,347-351``); implementation in
:mod:`kubernetes_cloud_tpu.data.downloader_cli`.
"""

from __future__ import annotations

import argparse
from typing import Optional

from kubernetes_cloud_tpu.data.downloader_cli import download_model


def main(argv: Optional[list] = None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--model", required=True,
                    help="HF repo id or local snapshot path")
    ap.add_argument("--dest", required=True)
    ap.add_argument("--type", dest="model_type", default="hf",
                    choices=("hf", "diffusers"))
    ap.add_argument("--revision", default=None)
    ap.add_argument("--tokenizer-only", default="false",
                    help="fetch only tokenizer/config files")
    ap.add_argument("--retries", type=int, default=1,
                    help="re-attempts on fetch failure (reference Argo "
                         "retryStrategy: download=1)")
    args = ap.parse_args(argv)
    tokenizer_only = str(args.tokenizer_only).strip().lower() in (
        "1", "true", "yes", "on")
    patterns = (["*.json", "*.txt", "*.model", "tokenizer*", "vocab*",
                 "merges*"] if tokenizer_only else None)
    download_model(args.model, args.dest, model_type=args.model_type,
                   revision=args.revision, allow_patterns=patterns,
                   retries=args.retries)
    return 0


if __name__ == "__main__":  # pragma: no cover - container entry
    import sys

    sys.exit(main())
