"""Build + invoke the native ``dataset_tokenizer`` CLI.

The reference runs its Go tokenizer as a container step
(``finetuner-workflow/finetune-workflow.yaml:423-479``); here the C++
source ships in-tree (``csrc/dataset_tokenizer``) and is compiled on
demand (image builds run ``make`` instead).
"""

from __future__ import annotations

import os
import subprocess
from typing import Optional, Sequence

_CSRC = os.path.join(os.path.dirname(os.path.dirname(
    os.path.dirname(os.path.abspath(__file__)))), "csrc", "dataset_tokenizer")


def build_tokenizer(out_dir: Optional[str] = None, *,
                    force: bool = False) -> str:
    """Compile the CLI (cached); returns the binary path."""
    src = os.path.join(_CSRC, "dataset_tokenizer.cpp")
    if out_dir is None:
        out_dir = os.path.join(_CSRC, "build")
    os.makedirs(out_dir, exist_ok=True)
    binary = os.path.join(out_dir, "dataset_tokenizer")
    if not force and os.path.exists(binary) and (
            os.path.getmtime(binary) >= os.path.getmtime(src)):
        return binary
    subprocess.run(
        ["g++", "-O2", "-std=c++17", "-o", binary, src],
        check=True, capture_output=True, text=True)
    return binary


def run_tokenizer(args: Sequence[str], *, binary: Optional[str] = None,
                  check: bool = True) -> subprocess.CompletedProcess:
    if binary is None:
        binary = build_tokenizer()
    return subprocess.run([binary, *args], check=check,
                          capture_output=True, text=True)


def main(argv: Optional[Sequence[str]] = None) -> int:
    """Module entry point for workflow steps: build the native binary if
    needed, then exec it with the given flags (same surface as the
    container's ``/usr/local/bin/dataset_tokenizer``)."""
    import sys

    if argv is None:
        argv = sys.argv[1:]
    try:
        binary = build_tokenizer()
    except subprocess.CalledProcessError as e:
        print(e.stderr or str(e), file=sys.stderr)
        return 1
    except OSError as e:  # g++ itself missing
        print(f"cannot build dataset_tokenizer: {e}", file=sys.stderr)
        return 1
    return subprocess.run([binary, *argv]).returncode


if __name__ == "__main__":  # pragma: no cover - container entry
    import sys

    sys.exit(main())
