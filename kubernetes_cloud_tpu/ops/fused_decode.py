"""Fused paged-decode kernel: gather + attention + output projection.

The paged decode step pays three dispatches per layer on its hottest
path: the page gather (or the paged-attention kernel), the attention
itself, and the ``[S, H·Dh] @ [H·Dh, hidden]`` output projection.  This
module folds all three into ONE Mosaic kernel, using the
:mod:`kubernetes_cloud_tpu.ops.paged_attention` kernel as the template:

* grid ``(slot, kv_head, page)`` with the page table as a scalar-
  prefetch operand — each step streams exactly one resident KV page
  per (slot, kv-head), never the whole arena;
* flash-style online softmax across the page sweep (identical
  accumulator discipline to the unfused kernel);
* when a (slot, kv-head)'s sweep finishes, its normalized ``[G, Dh]``
  attention block is immediately contracted against that head group's
  ``[G·Dh, hidden]`` slice of ``W_o`` and accumulated into a per-slot
  fp32 ``[1, hidden]`` scratch — the ``[S, H, Dh]`` attention tensor is
  never materialized in HBM, and the projection matmul rides the same
  kernel invocation;
* int8 arenas dequantize in-kernel exactly like the unfused path
  (score scale folds the K page scale; the V scale applies post-matmul).

``impl="ref"`` is the jnp fallback — the unfused gather attention
followed by an einsum — which defines the semantics and keeps tier-1
CPU-runnable; ``scripts/kernel_parity.py`` locks kernel vs ref vs a
dense reference on hardware, ``tests/test_quantized_kv.py`` in
interpreter mode.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from kubernetes_cloud_tpu.ops.paged_attention import (
    NEG_INF,
    paged_decode_attention,
)


def _ref_impl(q, k_pages, v_pages, page_table, ctx_lens, wo, slopes,
              scale, k_scale, v_scale):
    attn = paged_decode_attention(
        q, k_pages, v_pages, page_table, ctx_lens, k_scale=k_scale,
        v_scale=v_scale, slopes=slopes, scale=scale, impl="gather")
    return jnp.einsum("shd,hdo->so", attn, wo.astype(attn.dtype))


def _kernel(pt_ref, len_ref, slopes_ref, q_ref, k_ref, v_ref, *rest,
            group: int, page_size: int, n_pages: int, n_kv: int,
            scale: float, have_slopes: bool, have_scales: bool):
    if have_scales:
        ks_ref, vs_ref, wo_ref, o_ref, acc_ref, m_ref, l_ref, oacc_ref \
            = rest
    else:
        wo_ref, o_ref, acc_ref, m_ref, l_ref, oacc_ref = rest
        ks_ref = vs_ref = None
    s, kh, p = pl.program_id(0), pl.program_id(1), pl.program_id(2)

    @pl.when((kh == 0) & (p == 0))
    def _():
        oacc_ref[...] = jnp.zeros_like(oacc_ref)

    @pl.when(p == 0)
    def _():
        acc_ref[...] = jnp.zeros_like(acc_ref)
        m_ref[...] = jnp.full_like(m_ref, NEG_INF)
        l_ref[...] = jnp.zeros_like(l_ref)

    ctx = len_ref[s]
    q = q_ref[0, 0].astype(jnp.float32)          # [G, D]
    kblk = k_ref[0, :, 0, :]                     # [ps, D]
    vblk = v_ref[0, :, 0, :]
    k_scale = ks_ref[0, 0] * scale if have_scales else scale
    scores = jax.lax.dot_general(
        q, kblk.astype(jnp.float32), (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32) * k_scale  # [G, ps]
    kpos = (p * page_size
            + jax.lax.broadcasted_iota(jnp.int32, (group, page_size), 1))
    if have_slopes:
        slope = slopes_ref[pl.ds(kh * group, group)]  # [G]
        scores = scores + slope[:, None] * kpos.astype(jnp.float32)
    scores = jnp.where(kpos < ctx, scores, NEG_INF)

    m_prev = m_ref[:, :1]
    l_prev = l_ref[:, :1]
    m_new = jnp.maximum(m_prev, jnp.max(scores, axis=1, keepdims=True))
    alpha = jnp.exp(m_prev - m_new)
    probs = jnp.where(scores > NEG_INF * 0.5, jnp.exp(scores - m_new), 0.0)
    l_new = l_prev * alpha + jnp.sum(probs, axis=1, keepdims=True)
    pv = jax.lax.dot_general(
        probs, vblk.astype(jnp.float32), (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32)
    if have_scales:
        pv = pv * vs_ref[0, 0]
    acc_ref[...] = acc_ref[...] * alpha + pv
    m_ref[...] = jnp.broadcast_to(m_new, m_ref.shape)
    l_ref[...] = jnp.broadcast_to(l_new, l_ref.shape)

    @pl.when(p == n_pages - 1)
    def _():
        # this head group's sweep is done: normalize and fold its
        # projection slice into the per-slot output accumulator (the
        # attention vector never leaves VMEM)
        attn = acc_ref[...] / jnp.maximum(l_ref[:, :1], 1e-30)  # [G, D]
        d = attn.shape[1]
        part = jnp.zeros_like(oacc_ref)                # [1, hidden]
        for g in range(group):  # static unroll; slices are static
            part = part + jax.lax.dot_general(
                attn[g:g + 1, :],
                wo_ref[0, g * d:(g + 1) * d, :].astype(jnp.float32),
                (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32)
        oacc_ref[...] = oacc_ref[...] + part

    @pl.when((kh == n_kv - 1) & (p == n_pages - 1))
    def _():
        o_ref[...] = oacc_ref[...].astype(o_ref.dtype)


def _pallas_impl(q, k_pages, v_pages, page_table, ctx_lens, wo, slopes,
                 scale, k_scale, v_scale, interpret):
    s, h, d = q.shape
    _, ps, hkv, _ = k_pages.shape
    p_per = page_table.shape[1]
    g = h // hkv
    hidden = wo.shape[-1]
    have_slopes = slopes is not None
    have_scales = k_scale is not None
    qg = q.reshape(s, hkv, g, d)
    # [H, Dh, hidden] → per-kv-head-group projection slices
    wo3 = wo.reshape(hkv, g * d, hidden)

    kernel = functools.partial(
        _kernel, group=g, page_size=ps, n_pages=p_per, n_kv=hkv,
        scale=scale, have_slopes=have_slopes, have_scales=have_scales)
    in_specs = [
        pl.BlockSpec((1, 1, g, d),
                     lambda s_, kh, p_, pt, ln, sl: (s_, kh, 0, 0)),
        pl.BlockSpec((1, ps, 1, d),
                     lambda s_, kh, p_, pt, ln, sl: (pt[s_, p_], 0,
                                                     kh, 0)),
        pl.BlockSpec((1, ps, 1, d),
                     lambda s_, kh, p_, pt, ln, sl: (pt[s_, p_], 0,
                                                     kh, 0)),
    ]
    if have_scales:
        in_specs += [
            pl.BlockSpec((1, 1),
                         lambda s_, kh, p_, pt, ln, sl: (pt[s_, p_], kh)),
            pl.BlockSpec((1, 1),
                         lambda s_, kh, p_, pt, ln, sl: (pt[s_, p_], kh)),
        ]
    in_specs.append(
        pl.BlockSpec((1, g * d, hidden),
                     lambda s_, kh, p_, pt, ln, sl: (kh, 0, 0)))
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=3,
        grid=(s, hkv, p_per),
        in_specs=in_specs,
        out_specs=pl.BlockSpec(
            (1, hidden), lambda s_, kh, p_, pt, ln, sl: (s_, 0)),
        scratch_shapes=[
            pltpu.VMEM((g, d), jnp.float32),
            pltpu.VMEM((g, 128), jnp.float32),
            pltpu.VMEM((g, 128), jnp.float32),
            pltpu.VMEM((1, hidden), jnp.float32),
        ],
    )
    slopes_arg = (slopes.astype(jnp.float32) if have_slopes
                  else jnp.zeros((h,), jnp.float32))
    args = [qg, k_pages, v_pages]
    if have_scales:
        args += [k_scale.astype(jnp.float32), v_scale.astype(jnp.float32)]
    args.append(wo3)
    return pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((s, hidden), q.dtype),
        interpret=interpret,
    )(page_table.astype(jnp.int32), ctx_lens.astype(jnp.int32),
      slopes_arg, *args)


def fused_paged_decode(
    q: jax.Array,            # [S, H, D] one query token per slot
    k_pages: jax.Array,      # [NP, ps, Hkv, D] arena (one layer)
    v_pages: jax.Array,
    page_table: jax.Array,   # [S, P] physical page per slot block
    ctx_lens: jax.Array,     # [S] valid keys per slot (incl. current)
    wo: jax.Array,           # [H, Dh, hidden] output projection
    *,
    k_scale: Optional[jax.Array] = None,  # [NP, Hkv] int8 dequant
    v_scale: Optional[jax.Array] = None,
    slopes: Optional[jax.Array] = None,   # [H] ALiBi slopes
    scale: Optional[float] = None,
    impl: str = "ref",
    interpret: bool = False,
) -> jax.Array:
    """One decode token per slot → projected attention output
    ``[S, hidden]`` (``W_o`` applied; the caller adds its bias).  Free
    slots (``ctx_lens == 0``) return unspecified values, like the
    unfused kernel."""
    if scale is None:
        scale = q.shape[-1] ** -0.5
    if impl == "pallas":
        return _pallas_impl(q, k_pages, v_pages, page_table, ctx_lens,
                            wo, slopes, float(scale), k_scale, v_scale,
                            interpret)
    return _ref_impl(q, k_pages, v_pages, page_table, ctx_lens, wo,
                     slopes, float(scale), k_scale, v_scale)


def fused_paged_segment(
    q: jax.Array,            # [N, H, D] one query per flat token
    k_pages: jax.Array,      # [NP, ps, Hkv, D] arena (one layer)
    v_pages: jax.Array,
    page_table: jax.Array,   # [S, P] physical page per slot block
    seg_slot: jax.Array,     # [N] owning slot per flat token
    ctx_lens: jax.Array,     # [N] keys visible to each token (incl. self)
    wo: jax.Array,           # [H, Dh, hidden] output projection
    *,
    k_scale: Optional[jax.Array] = None,
    v_scale: Optional[jax.Array] = None,
    slopes: Optional[jax.Array] = None,
    scale: Optional[float] = None,
    impl: str = "ref",
    interpret: bool = False,
) -> jax.Array:
    """Segment-aware fused decode for a flat ragged token batch: the
    per-token expansion of the slot page table
    (:func:`kubernetes_cloud_tpu.ops.paged_attention.
    paged_segment_attention`) feeding the fused gather + attention +
    projection kernel.  The kernel grid is per-row in N, so multi-token
    segments (prefill chunks, spec-verify windows) ride the decode
    kernel unchanged — within-segment causality is entirely in
    ``ctx_lens``.  Returns ``[N, hidden]`` (``W_o`` applied)."""
    return fused_paged_decode(
        q, k_pages, v_pages, page_table[seg_slot], ctx_lens, wo,
        k_scale=k_scale, v_scale=v_scale, slopes=slopes, scale=scale,
        impl=impl, interpret=interpret)
