"""Multi-head attention with selectable backend.

``impl="xla"`` is the reference implementation (einsum + fp32 softmax) that
runs anywhere, including the CPU-simulated test mesh.  ``impl="pallas"``
dispatches to the fused flash-attention TPU kernel in
:mod:`kubernetes_cloud_tpu.ops.flash_attention`.  ``impl="auto"`` picks
pallas on TPU backends when shapes are tile-aligned, xla otherwise.

This replaces the reference's stack of attention engines — torch SDPA in the
finetuner, FasterTransformer fused CUDA decoders
(``online-inference/fastertransformer/build/Dockerfile:16-70``), and
DeepSpeed-Inference kernel injection
(``online-inference/bloom-176b-deepspeed/Dockerfile:1-15``) — with one
mesh-sharded op: head dimension sharded over the ``model`` axis, batch over
``data``/``fsdp``, sequence over ``seq`` (ring attention).
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp

NEG_INF = -1e15


def _mha_xla(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    *,
    causal: bool,
    bias: Optional[jax.Array],
    mask: Optional[jax.Array],
    scale: float,
) -> jax.Array:
    # q: [B, Sq, H, Dh], k/v: [B, Sk, Hkv, Dh] (GQA when Hkv < H)
    b, sq, h, dh = q.shape
    hkv = k.shape[2]
    if hkv != h:
        group = h // hkv
        q = q.reshape(b, sq, hkv, group, dh)
        logits = jnp.einsum("bqkgd,bskd->bkgqs", q, k) * scale
        logits = logits.reshape(b, h, sq, k.shape[1])
    else:
        logits = jnp.einsum("bqhd,bshd->bhqs", q, k) * scale
    logits = logits.astype(jnp.float32)
    if bias is not None:
        logits = logits + bias.astype(jnp.float32)
    sk = k.shape[1]
    if causal:
        q_pos = jax.lax.broadcasted_iota(jnp.int32, (sq, sk), 0) + (sk - sq)
        k_pos = jax.lax.broadcasted_iota(jnp.int32, (sq, sk), 1)
        logits = jnp.where(q_pos >= k_pos, logits, NEG_INF)
    if mask is not None:
        # mask: [B, Sk] (1 = attend) or [B, 1, Sq, Sk]
        if mask.ndim == 2:
            mask = mask[:, None, None, :]
        logits = jnp.where(mask != 0, logits, NEG_INF)
    if q.dtype == jnp.bfloat16:
        # Softmax arithmetic stays fp32 (max/sub/exp/sum run in registers
        # inside one fusion) but the [B, H, Sq, Sk] exp tensor is *stored*
        # bf16: the logits were already bf16-rounded by the MXU matmul, so
        # this costs <0.4% on probs while halving the dominant HBM traffic
        # of the training step (1 GiB → 512 MiB per layer at bs16/seq1024;
        # the fp32 materialization was ~40% of step time, round-4 trace).
        m = jnp.max(logits, axis=-1, keepdims=True)
        e = jnp.exp(logits - m).astype(q.dtype)
        s = jnp.sum(e, axis=-1, keepdims=True, dtype=jnp.float32)
        probs = e * (1.0 / s).astype(q.dtype)
    else:
        probs = jax.nn.softmax(logits, axis=-1).astype(q.dtype)
    if hkv != h:
        group = h // hkv
        probs_g = probs.reshape(b, hkv, group, sq, sk)
        out = jnp.einsum("bkgqs,bskd->bqkgd", probs_g, v)
        return out.reshape(b, sq, h, dh)
    return jnp.einsum("bhqs,bshd->bqhd", probs, v)


def attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    *,
    causal: bool = True,
    bias: Optional[jax.Array] = None,
    mask: Optional[jax.Array] = None,
    scale: Optional[float] = None,
    impl: str = "auto",
    alibi_slopes: Optional[jax.Array] = None,
) -> jax.Array:
    """Scaled dot-product attention over [B, S, H, Dh] tensors.

    ``bias``: additive [B or 1, H, Sq, Sk] bias tensor (XLA path only).
    ``alibi_slopes``: per-head [H] ALiBi slopes — the structured form of
    the per-key bias ``slope_h * k_pos``; the pallas path computes it
    in-kernel, the XLA path materializes it here.
    ``mask``: [B, Sk] key padding mask or full [B, 1, Sq, Sk] mask, nonzero
    = attend (the reference trains with exactly this padding-mask semantics,
    ``finetuner-workflow/finetuner/finetuner.py:475-493``).
    """
    if scale is None:
        scale = q.shape[-1] ** -0.5
    explicit = impl == "pallas"
    if impl == "auto":
        impl = _pick_impl(q, k, bias, mask, alibi_slopes)
    if impl == "pallas":
        from kubernetes_cloud_tpu.ops import flash_attention

        return flash_attention.flash_attention(
            q, k, v, causal=causal, bias=bias, mask=mask, scale=scale,
            alibi_slopes=alibi_slopes, explicit=explicit,
        )
    if alibi_slopes is not None:
        kpos = jnp.arange(k.shape[1], dtype=jnp.float32)
        alibi = alibi_slopes[None, :, None, None] * kpos[None, None, None, :]
        bias = alibi if bias is None else bias + alibi
    return _mha_xla(q, k, v, causal=causal, bias=bias, mask=mask, scale=scale)


def _pick_impl(q, k, bias, mask, alibi_slopes=None) -> str:
    from kubernetes_cloud_tpu.ops import flash_attention

    if not flash_attention.available():
        return "xla"
    if mask is not None and mask.ndim != 2:
        return "xla"  # full [B,1,Sq,Sk] masks stay on the einsum path
    if not flash_attention.supports(q, k, bias, alibi_slopes, mask=mask):
        return "xla"
    return "pallas"
