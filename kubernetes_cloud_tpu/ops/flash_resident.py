"""Batch-folded flash attention for short sequences — a Pallas TPU kernel.

The general flash kernels (:mod:`~kubernetes_cloud_tpu.ops.flash_kernel`
and the stock Pallas op) grid over ``(batch, head, q_block, ...)``; at
bench-class shapes (B16 H16 S1024 D64) that is ~1000 grid steps of
~0.1 GFLOP each, and the fixed per-step cost (DMA latency, grid
bookkeeping — measured ~4.4 µs/step on v5e) dominates: 4-7 ms per
attention call, slower than XLA's materialized softmax.

This kernel targets exactly those shapes.  It grids over
``(batch_chunk, kv_head, group, q_block)`` where each step holds a
*chunk of batches* of the full K/V sequence resident in VMEM and loops
the chunk inside the kernel, so per-step work is
``BB × 2·bq·S·D`` FLOPs and the fixed cost amortizes away.  The
softmax is one-shot over the full key range (the [bq, S] score block
lives in VMEM — no online renormalization).  A small planner picks the
largest (batch_chunk, q_block) that fits the VMEM budget.  Forward
saves only the logsumexp; backward recomputes probabilities from it
(FlashAttention-2 style) in two kernels (dq, then dk/dv).

Matmul operands stay in the input dtype (bf16 on the MXU's native
path) with fp32 accumulation — an fp32×fp32 dot would run at a
fraction of MXU rate.

GQA maps every query head of a group onto the same resident KV block
(like flash_kernel); ALiBi comes in as per-head slopes computed
in-kernel.  No segment/padding masks: shapes with masks route to the
general kernels — the packed-dataset training path and batched decode
prefill both run maskless.

Replaces the reference's fused CUDA attention at training/serving
shapes (FasterTransformer decoders,
``online-inference/fastertransformer/build/Dockerfile:16-70``).
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

NEG_INF = -1e30
_ROWPAD = 8  # lane padding for [.., S]-shaped row vectors (see flash_kernel)

#: Scoped-VMEM ceiling requested from Mosaic.  v5e has 128 MiB of
#: physical VMEM; the default 16 MiB scoped limit is what makes other
#: kernels shrink their blocks (and pay per-grid-step fixed costs ~1000
#: times).  This kernel asks for most of it and folds the whole batch
#: into each grid step instead.
_VMEM_LIMIT = 100 * 1024 * 1024
#: plan budget for the *estimated* working set; the Mosaic stack
#: allocator roughly double-counts a naive estimate (double buffering +
#: transient temporaries), so plan to about a third of the limit.
_VMEM_BUDGET = 32 * 1024 * 1024
#: measured on v5e at B16 H16 S1024 D64: bq256 fwd 3.5 ms vs bq512 4.9 ms
_MAX_BLOCK_Q = 256


def _vmem_estimate(bb: int, bq: int, sk: int, d: int,
                   dtype_bytes: int) -> int:
    """Rough per-grid-step VMEM bytes for the fwd/bwd kernels (double
    buffering on block inputs/outputs, fp32 score scratch + bf16 probs)."""
    io = 2 * (bb * bq * d          # q
              + 2 * bb * sk * d    # k + v
              + bb * bq * d)       # out / dq
    io += 2 * bb * max(bq, _ROWPAD) * _ROWPAD * 2  # lse/delta rows (f32)
    scratch = bq * sk * 4 + bq * sk * dtype_bytes + bq * sk * 4
    return io * dtype_bytes + scratch


def _plan(b: int, sq: int, sk: int, d: int,
          dtype_bytes: int) -> Optional[tuple[int, int]]:
    """Largest (batch_chunk, q_block) whose working set fits the budget."""
    bq = min(_MAX_BLOCK_Q, sq)
    while bq >= 128:
        bb = b
        while bb >= 1:
            if (b % bb == 0 and sq % bq == 0
                    and _vmem_estimate(bb, bq, sk, d, dtype_bytes)
                    <= _VMEM_BUDGET):
                return bb, bq
            bb //= 2
        bq //= 2
    return None


def _alibi(slope, bq, sk):
    kpos = jax.lax.broadcasted_iota(jnp.int32, (bq, sk), 1).astype(
        jnp.float32)
    return slope * kpos


def _score_addend(slope, qi0, bq, sk, causal: bool, have_slopes: bool):
    """ALiBi + causal additive term for a [bq, sk] score block, hoisted
    out of the kernels' batch loops (identical for every batch).  Masked
    entries carry NEG_INF: exp() underflows them to exactly 0, so no
    select is needed on the probability side (causal rows always have a
    live diagonal)."""
    addend = None
    if have_slopes:
        addend = _alibi(slope, bq, sk)
    if causal:
        qpos = jax.lax.broadcasted_iota(jnp.int32, (bq, sk), 0) + qi0
        kpos = jax.lax.broadcasted_iota(jnp.int32, (bq, sk), 1)
        neg = jnp.where(qpos >= kpos, 0.0, NEG_INF)
        addend = neg if addend is None else addend + neg
    return addend


# ---------------------------------------------------------------------------
# forward
# ---------------------------------------------------------------------------


def _fwd_kernel(*refs, bb: int, group: int, bq: int, causal: bool,
                scale: float, have_slopes: bool):
    idx = 0
    q_ref = refs[idx]; idx += 1
    k_ref = refs[idx]; idx += 1
    v_ref = refs[idx]; idx += 1
    slopes_ref = None
    if have_slopes:
        slopes_ref = refs[idx]; idx += 1
    o_ref, lse_ref = refs[idx], refs[idx + 1]

    i = pl.program_id(3)
    qi0 = i * bq
    sk = k_ref.shape[2]
    head = pl.program_id(1) * group + pl.program_id(2)
    slope = slopes_ref[head, 0] if have_slopes else None

    addend = _score_addend(slope, qi0, bq, sk, causal, have_slopes)

    def body(b, _):
        # scale folded onto the small [bq, D] operand, not the scores
        qs = (q_ref[b, 0].astype(jnp.float32) * scale).astype(q_ref.dtype)
        s = jax.lax.dot_general(
            qs, k_ref[b, 0], (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)  # [bq, sk]
        if addend is not None:
            s = s + addend
        m = jnp.max(s, axis=1, keepdims=True)
        p = jnp.exp(s - m)
        l = jnp.sum(p, axis=1, keepdims=True)
        l_safe = jnp.maximum(l, 1e-30)
        pv = jax.lax.dot_general(
            p.astype(v_ref.dtype), v_ref[b, 0], (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        o_ref[b, 0] = (pv / l_safe).astype(o_ref.dtype)
        lse_ref[b, 0] = jnp.broadcast_to(m + jnp.log(l_safe),
                                         (bq, _ROWPAD))
        return _

    jax.lax.fori_loop(0, bb, body, 0)


def _plan_or_raise(b, sq, sk, d, h, hkv, dtype_bytes):
    if not supported(b, sq, sk, d, h, hkv, dtype_bytes):
        raise ValueError(
            f"shape B{b} H{h}/{hkv} S{sq}/{sk} D{d} is not resident-kernel "
            "eligible (see flash_resident.supported); route via "
            "ops.attention / ops.flash_attention instead of calling "
            "flash_mha_resident directly")
    return _plan(b, sq, sk, d, dtype_bytes)


def _fwd(q, k, v, slopes, causal, scale, interpret):
    b, h, sq, d = q.shape
    hkv, sk = k.shape[1], k.shape[2]
    g = h // hkv
    bb, bq = _plan_or_raise(b, sq, sk, d, h, hkv, q.dtype.itemsize)
    nb, nq = b // bb, sq // bq
    have_slopes = slopes is not None

    grid = (nb, hkv, g, nq)
    in_specs = [
        pl.BlockSpec((bb, 1, bq, d),
                     lambda b_, kh, g_, i: (b_, kh * g + g_, i, 0)),
        pl.BlockSpec((bb, 1, sk, d), lambda b_, kh, g_, i: (b_, kh, 0, 0)),
        pl.BlockSpec((bb, 1, sk, d), lambda b_, kh, g_, i: (b_, kh, 0, 0)),
    ]
    args = [q, k, v]
    if have_slopes:
        in_specs.append(pl.BlockSpec((h, 1), lambda b_, kh, g_, i: (0, 0),
                                     memory_space=pltpu.SMEM))
        args.append(slopes.reshape(h, 1).astype(jnp.float32))

    out, lse = pl.pallas_call(
        functools.partial(
            _fwd_kernel, bb=bb, group=g, bq=bq, causal=causal,
            scale=scale, have_slopes=have_slopes),
        grid=grid,
        in_specs=in_specs,
        out_specs=[
            pl.BlockSpec((bb, 1, bq, d),
                         lambda b_, kh, g_, i: (b_, kh * g + g_, i, 0)),
            pl.BlockSpec((bb, 1, bq, _ROWPAD),
                         lambda b_, kh, g_, i: (b_, kh * g + g_, i, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((b, h, sq, d), q.dtype),
            jax.ShapeDtypeStruct((b, h, sq, _ROWPAD), jnp.float32),
        ],
        interpret=interpret,
        compiler_params=pltpu.CompilerParams(
            vmem_limit_bytes=_VMEM_LIMIT),
    )(*args)
    return out, lse


# ---------------------------------------------------------------------------
# backward
# ---------------------------------------------------------------------------


def _dq_kernel(*refs, bb: int, group: int, bq: int, causal: bool,
               scale: float, have_slopes: bool):
    idx = 0
    q_ref = refs[idx]; idx += 1
    k_ref = refs[idx]; idx += 1
    v_ref = refs[idx]; idx += 1
    do_ref = refs[idx]; idx += 1
    lse_ref = refs[idx]; idx += 1
    delta_ref = refs[idx]; idx += 1
    slopes_ref = None
    if have_slopes:
        slopes_ref = refs[idx]; idx += 1
    dq_ref = refs[idx]

    i = pl.program_id(3)
    qi0 = i * bq
    sk = k_ref.shape[2]
    head = pl.program_id(1) * group + pl.program_id(2)
    slope = slopes_ref[head, 0] if have_slopes else None

    addend = _score_addend(slope, qi0, bq, sk, causal, have_slopes)

    def body(b, _):
        qs = (q_ref[b, 0].astype(jnp.float32) * scale).astype(q_ref.dtype)
        s = jax.lax.dot_general(
            qs, k_ref[b, 0], (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)
        if addend is not None:
            s = s + addend
        lse = lse_ref[b, 0][:, :1]
        p = jnp.exp(s - lse)
        dp = jax.lax.dot_general(
            do_ref[b, 0], v_ref[b, 0], (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)
        delta = delta_ref[b, 0][:, :1]
        ds = (p * (dp - delta) * scale).astype(k_ref.dtype)
        dq_ref[b, 0] = jax.lax.dot_general(
            ds, k_ref[b, 0], (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32).astype(dq_ref.dtype)
        return _

    jax.lax.fori_loop(0, bb, body, 0)


def _dkv_kernel(*refs, bb: int, group: int, bk: int, causal: bool,
                scale: float, have_slopes: bool):
    idx = 0
    q_ref = refs[idx]; idx += 1
    k_ref = refs[idx]; idx += 1
    v_ref = refs[idx]; idx += 1
    do_ref = refs[idx]; idx += 1
    lse_ref = refs[idx]; idx += 1   # [bb, 1, _ROWPAD, Sq] pre-transposed
    delta_ref = refs[idx]; idx += 1
    slopes_ref = None
    if have_slopes:
        slopes_ref = refs[idx]; idx += 1
    dk_ref, dv_ref = refs[idx], refs[idx + 1]

    j = pl.program_id(3)
    kj0 = j * bk
    sq = q_ref.shape[2]
    head = pl.program_id(1) * group + pl.program_id(2)
    slope = slopes_ref[head, 0] if have_slopes else None

    addend = None
    if have_slopes:
        kpos = (jax.lax.broadcasted_iota(jnp.int32, (bk, sq), 0) + kj0
                ).astype(jnp.float32)
        addend = slope * kpos
    if causal:
        kpos = jax.lax.broadcasted_iota(jnp.int32, (bk, sq), 0) + kj0
        qpos = jax.lax.broadcasted_iota(jnp.int32, (bk, sq), 1)
        neg = jnp.where(qpos >= kpos, 0.0, NEG_INF)
        addend = neg if addend is None else addend + neg

    def body(b, _):
        # s^T layout: [bk, sq] so the dv/dk contractions are row-major
        ks = (k_ref[b, 0].astype(jnp.float32) * scale).astype(k_ref.dtype)
        st = jax.lax.dot_general(
            ks, q_ref[b, 0], (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)
        if addend is not None:
            st = st + addend
        lse_row = lse_ref[b, 0][:1, :]             # [1, sq]
        pt = jnp.exp(st - lse_row)                 # [bk, sq]
        ptb = pt.astype(v_ref.dtype)
        dv_ref[b, 0] = jax.lax.dot_general(
            ptb, do_ref[b, 0], (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32).astype(dv_ref.dtype)
        dpt = jax.lax.dot_general(
            v_ref[b, 0], do_ref[b, 0], (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)    # [bk, sq]
        delta_row = delta_ref[b, 0][:1, :]
        dst = (pt * (dpt - delta_row) * scale).astype(q_ref.dtype)
        dk_ref[b, 0] = jax.lax.dot_general(
            dst, q_ref[b, 0], (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32).astype(dk_ref.dtype)
        return _

    jax.lax.fori_loop(0, bb, body, 0)


def _bwd(causal, scale, interpret, res, dout):
    q, k, v, slopes, out, lse = res
    b, h, sq, d = q.shape
    hkv, sk = k.shape[1], k.shape[2]
    g = h // hkv
    bb, bq = _plan_or_raise(b, sq, sk, d, h, hkv, q.dtype.itemsize)
    bk = bq
    nb, nq, nk = b // bb, sq // bq, sk // bk
    have_slopes = slopes is not None

    delta = jnp.sum(out.astype(jnp.float32) * dout.astype(jnp.float32),
                    axis=-1)
    delta_pad = jax.lax.broadcast_in_dim(delta, (b, h, sq, _ROWPAD),
                                         (0, 1, 2))
    slope_arg = (slopes.reshape(h, 1).astype(jnp.float32)
                 if have_slopes else None)

    qspec = pl.BlockSpec((bb, 1, bq, d),
                         lambda b_, kh, g_, i: (b_, kh * g + g_, i, 0))
    kvspec = pl.BlockSpec((bb, 1, sk, d),
                          lambda b_, kh, g_, i: (b_, kh, 0, 0))
    rowspec = pl.BlockSpec((bb, 1, bq, _ROWPAD),
                           lambda b_, kh, g_, i: (b_, kh * g + g_, i, 0))
    in_specs = [qspec, kvspec, kvspec, qspec, rowspec, rowspec]
    args = [q, k, v, dout, lse, delta_pad]
    if have_slopes:
        in_specs.append(pl.BlockSpec((h, 1), lambda b_, kh, g_, i: (0, 0),
                                     memory_space=pltpu.SMEM))
        args.append(slope_arg)
    dq = pl.pallas_call(
        functools.partial(
            _dq_kernel, bb=bb, group=g, bq=bq, causal=causal,
            scale=scale, have_slopes=have_slopes),
        grid=(nb, hkv, g, nq),
        in_specs=in_specs,
        out_specs=qspec,
        out_shape=jax.ShapeDtypeStruct((b, h, sq, d), q.dtype),
        interpret=interpret,
        compiler_params=pltpu.CompilerParams(
            vmem_limit_bytes=_VMEM_LIMIT),
    )(*args)

    # dk/dv kernel wants lse/delta as [1, Sq] row vectors (q along lanes);
    # build the transposed copies host-side instead of transposing in-kernel.
    lse_t = jax.lax.broadcast_in_dim(
        lse[..., 0], (b, h, _ROWPAD, sq), (0, 1, 3))
    delta_t = jax.lax.broadcast_in_dim(
        delta, (b, h, _ROWPAD, sq), (0, 1, 3))
    qfull = pl.BlockSpec((bb, 1, sq, d),
                         lambda b_, kh, g_, j: (b_, kh * g + g_, 0, 0))
    kblk = pl.BlockSpec((bb, 1, bk, d),
                        lambda b_, kh, g_, j: (b_, kh, j, 0))
    rowfull = pl.BlockSpec((bb, 1, _ROWPAD, sq),
                           lambda b_, kh, g_, j: (b_, kh * g + g_, 0, 0))
    in_specs = [qfull, kblk, kblk, qfull, rowfull, rowfull]
    args = [q, k, v, dout, lse_t, delta_t]
    if have_slopes:
        in_specs.append(pl.BlockSpec((h, 1), lambda b_, kh, g_, j: (0, 0),
                                     memory_space=pltpu.SMEM))
        args.append(slope_arg)
    # GQA: the kernel writes per-query-head dk/dv partials (unreduced over
    # the group); for g == 1 that is already the answer, for g > 1 the
    # group reduction happens outside in one cheap XLA sum.
    out_h = h
    per_head = pl.BlockSpec((bb, 1, bk, d),
                            lambda b_, kh, g_, j: (b_, kh * g + g_, j, 0))
    dk, dv = pl.pallas_call(
        functools.partial(
            _dkv_kernel, bb=bb, group=g, bk=bk, causal=causal,
            scale=scale, have_slopes=have_slopes),
        grid=(nb, hkv, g, nk),
        in_specs=in_specs,
        out_specs=[per_head, per_head],
        out_shape=[
            jax.ShapeDtypeStruct((b, out_h, sk, d), jnp.float32),
            jax.ShapeDtypeStruct((b, out_h, sk, d), jnp.float32),
        ],
        interpret=interpret,
        compiler_params=pltpu.CompilerParams(
            vmem_limit_bytes=_VMEM_LIMIT),
    )(*args)
    if g > 1:
        dk = dk.reshape(b, hkv, g, sk, d).sum(axis=2)
        dv = dv.reshape(b, hkv, g, sk, d).sum(axis=2)

    return (dq.astype(q.dtype), dk.astype(k.dtype), dv.astype(v.dtype),
            None)


# ---------------------------------------------------------------------------
# public op
# ---------------------------------------------------------------------------


@functools.partial(jax.custom_vjp, nondiff_argnums=(4, 5, 6))
def _flash(q, k, v, slopes, causal, scale, interpret):
    out, _ = _fwd(q, k, v, slopes, causal, scale, interpret)
    return out


def _flash_fwd(q, k, v, slopes, causal, scale, interpret):
    out, lse = _fwd(q, k, v, slopes, causal, scale, interpret)
    return out, (q, k, v, slopes, out, lse)


_flash.defvjp(_flash_fwd, _bwd)


def supported(b: int, sq: int, sk: int, d: int, h: int, hkv: int,
              dtype_bytes: int = 2) -> bool:
    """Eligibility: aligned self-attention shapes whose K/V chunk plan
    fits the VMEM budget."""
    if h % hkv:
        return False
    if sq != sk or sq % 128 or d % 64 or d % 128 and d != 64:
        return False
    return _plan(b, sq, sk, d, dtype_bytes) is not None


def flash_mha_resident(
    q: jax.Array,  # [B, H, Sq, D]
    k: jax.Array,  # [B, Hkv, Sk, D]
    v: jax.Array,
    *,
    slopes: Optional[jax.Array] = None,
    causal: bool = True,
    scale: Optional[float] = None,
    interpret: bool = False,
) -> jax.Array:
    """Batch-folded resident flash attention; returns [B, H, Sq, D]."""
    if scale is None:
        scale = q.shape[-1] ** -0.5
    return _flash(q, k, v, slopes, causal, float(scale), interpret)
